"""ctypes binding for the native canonical scanner (native/src/das_native.cc).

The C++ library parses canonical knowledge-base files on std::thread
workers (GIL-free) and computes all md5 handles inline; this module decodes
its record stream into `AtomSpaceData`, producing records identical to the
pure-Python loader (das_tpu/ingest/canonical.py) — differentially tested in
tests/test_native.py.

The library is auto-built on first use (``make -C native``, a few seconds)
and cached; set ``DAS_TPU_NO_NATIVE=1`` to force the Python path, or
``DAS_TPU_NATIVE_LIB`` to point at a prebuilt .so.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
from typing import List, Optional

from das_tpu.storage.atom_table import AtomSpaceData
from das_tpu.utils.logger import logger

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_NATIVE_DIR = os.path.join(_REPO_ROOT, "native")
_DEFAULT_LIB = os.path.join(_NATIVE_DIR, "build", "libdas_native.so")

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


from das_tpu.ingest.canonical import CanonicalParseError


class NativeParseError(CanonicalParseError):
    pass


def _build_library() -> bool:
    if not os.path.isdir(_NATIVE_DIR):
        return False
    try:
        proc = subprocess.run(
            ["make", "-C", _NATIVE_DIR],
            capture_output=True,
            text=True,
            timeout=300,
        )
    except (OSError, subprocess.TimeoutExpired) as exc:
        logger().info(f"native build unavailable: {exc}")
        return False
    if proc.returncode != 0:
        logger().info(f"native build failed:\n{proc.stderr}")
        return False
    return os.path.exists(_DEFAULT_LIB)


def get_lib() -> Optional[ctypes.CDLL]:
    """Load (building if needed) the native library; None when unavailable."""
    global _lib, _load_attempted
    if _lib is not None or _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("DAS_TPU_NO_NATIVE"):
        return None
    path = os.environ.get("DAS_TPU_NATIVE_LIB", _DEFAULT_LIB)
    if path == _DEFAULT_LIB and os.path.isdir(_NATIVE_DIR):
        # always run make: a no-op when fresh, and it catches stale .so
        # after native/src edits (make's dep check, not mtime guessing here)
        if not _build_library() and not os.path.exists(path):
            return None
    elif not os.path.exists(path):
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError as exc:
        logger().info(f"native library load failed: {exc}")
        return None
    lib.das_parse_files.restype = ctypes.c_void_p
    lib.das_parse_files.argtypes = [
        ctypes.POINTER(ctypes.c_char_p),
        ctypes.c_int,
        ctypes.c_int,
    ]
    lib.das_parse_text.restype = ctypes.c_void_p
    lib.das_parse_text.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
    lib.das_buffer_count.restype = ctypes.c_int
    lib.das_buffer_count.argtypes = [ctypes.c_void_p]
    lib.das_buffer.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.das_buffer.argtypes = [
        ctypes.c_void_p,
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_uint64),
    ]
    lib.das_error.restype = ctypes.c_char_p
    lib.das_error.argtypes = [ctypes.c_void_p]
    lib.das_free.argtypes = [ctypes.c_void_p]
    lib.das_buffer_release.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.das_md5_hex.argtypes = [
        ctypes.c_char_p,
        ctypes.c_uint64,
        ctypes.c_char_p,
    ]
    # columnar API (das_columnar.cc) — a prebuilt .so from before the
    # columnar scanner may lack these symbols; only the columnar path is
    # disabled then, the record-stream path keeps working
    try:
        lib.das_parse_files_columnar.restype = ctypes.c_void_p
        lib.das_parse_files_columnar.argtypes = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.das_col_error.restype = ctypes.c_char_p
        lib.das_col_error.argtypes = [ctypes.c_void_p]
        lib.das_col_get.restype = ctypes.c_int
        lib.das_col_get.argtypes = [
            ctypes.c_void_p,
            ctypes.c_int,
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint8)),
            ctypes.POINTER(ctypes.c_uint64),
        ]
        lib.das_col_free.argtypes = [ctypes.c_void_p]
        lib.das_tpu_has_columnar = True
    except AttributeError:
        lib.das_tpu_has_columnar = False
    _lib = lib
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def columnar_available() -> bool:
    lib = get_lib()
    return lib is not None and getattr(lib, "das_tpu_has_columnar", False)


def native_md5_hex(data: bytes) -> str:
    lib = get_lib()
    assert lib is not None
    out = ctypes.create_string_buffer(32)
    lib.das_md5_hex(data, len(data), out)
    return out.raw.decode("ascii")


# ---------------------------------------------------------------------------
# record-stream decoding
# ---------------------------------------------------------------------------


def _decode_into(buf: bytes, data: AtomSpaceData) -> None:
    """Replay one record stream into the store.

    Produces records identical to the Python loader's (mirrors the
    construction in das_tpu/ingest/canonical.py) but builds
    NodeRec/LinkRec/TypedefRec directly with inline dedup — the
    per-record `Expression` hop and `add_*` dispatch are pure overhead at
    millions of records — and decodes each record's contiguous hex block
    with a single bytes.decode.
    """
    from das_tpu.storage.atom_table import LinkRec, NodeRec, TypedefRec

    table = data.table
    nodes = data.nodes
    links = data.links
    typedefs = data.typedefs
    named_type_hash = table.named_type_hash
    terminal_hash = table.terminal_hash
    pos = 0
    end = len(buf)
    u16 = struct.Struct("<H").unpack_from
    u32 = struct.Struct("<I").unpack_from
    # same-type links arrive in long runs (converter output is grouped);
    # caching the previous type's decoded string + interned hash removes
    # two dict probes and a utf-8 decode from most hot-path iterations
    last_type_raw = None
    last_type = last_nth = ""
    while pos < end:
        tag = buf[pos]
        pos += 1
        if tag == 3:  # link (hot path)
            (tlen,) = u16(buf, pos)
            pos += 2
            type_raw = buf[pos : pos + tlen]
            pos += tlen
            toplevel = buf[pos] != 0
            pos += 1
            (ne,) = u16(buf, pos)
            pos += 2
            kinds = buf[pos : pos + ne]
            pos += ne
            nterm = kinds.count(1)  # kind ∈ {0, 1}
            blk_chars = 32 * (3 + ne + nterm)
            blk = buf[pos : pos + blk_chars].decode("ascii")
            pos += blk_chars
            if type_raw == last_type_raw:
                named_type, nth = last_type, last_nth
            else:
                named_type = type_raw.decode("utf-8")
                nth = blk[:32]
                named_type_hash.setdefault(named_type, nth)
                last_type_raw, last_type, last_nth = type_raw, named_type, nth
            elements: List[str] = []
            composite_type: List = [nth]
            off = 32
            soff = 32 * (1 + ne)
            for kind in kinds:
                ehash = blk[off : off + 32]
                off += 32
                elements.append(ehash)
                if kind:
                    composite_type.append(blk[soff : soff + 32])
                    soff += 32
                else:
                    # sub-expression record always precedes its parent
                    composite_type.append(links[ehash].composite_type)
            ct_hash = blk[-64:-32]
            hash_code = blk[-32:]
            prev = links.get(hash_code)
            if prev is None:
                links[hash_code] = LinkRec(
                    named_type=named_type,
                    named_type_hash=nth,
                    composite_type=composite_type,
                    composite_type_hash=ct_hash,
                    elements=tuple(elements),
                    is_toplevel=toplevel,
                )
            elif toplevel:
                set_top = getattr(links, "set_toplevel", None)
                if set_top is not None:
                    # columnar view (a second load onto a columnar-backed
                    # store): the reconstructed LinkRec is a copy, so the
                    # flag must write through to the column
                    set_top(hash_code)
                else:
                    prev.is_toplevel = True
        elif tag == 2:  # terminal
            (slen,) = u16(buf, pos)
            pos += 2
            stype = buf[pos : pos + slen].decode("utf-8")
            pos += slen
            (nlen,) = u32(buf, pos)
            pos += 4
            name = buf[pos : pos + nlen].decode("utf-8")
            pos += nlen
            blk = buf[pos : pos + 64].decode("ascii")
            pos += 64
            stype_hash = blk[:32]
            h = blk[32:]
            named_type_hash.setdefault(stype, stype_hash)
            terminal_hash[(stype, name)] = h
            # like the MeTTa parser on a terminal declaration: later
            # transactions referencing the bare name must resolve
            table.named_types[name] = stype
            if h not in nodes:
                nodes[h] = NodeRec(
                    name=name, named_type=stype, named_type_hash=stype_hash
                )
        elif tag == 1:  # typedef
            (nlen,) = u16(buf, pos)
            pos += 2
            name = buf[pos : pos + nlen].decode("utf-8")
            pos += nlen
            (slen,) = u16(buf, pos)
            pos += 2
            stype = buf[pos : pos + slen].decode("utf-8")
            pos += slen
            blk = buf[pos : pos + 128].decode("ascii")
            pos += 128
            name_hash = blk[:32]
            stype_hash = blk[32:64]
            ct_hash = blk[64:96]
            hash_code = blk[96:]
            named_type_hash.setdefault(name, name_hash)
            named_type_hash.setdefault(stype, stype_hash)
            table.named_types[name] = stype
            table.parent_type[name_hash] = stype_hash
            table.symbol_hash[name] = hash_code
            if hash_code not in typedefs:
                typedefs[hash_code] = TypedefRec(
                    name=name,
                    name_hash=name_hash,
                    composite_type_hash=ct_hash,
                    designator_name=stype,
                )
        else:  # pragma: no cover — stream corruption
            raise NativeParseError(f"bad record tag {tag} at offset {pos - 1}")
    data._fin = None


def _buffer_bytes(ptr, size: int) -> bytes:
    """Copy a native buffer of ANY size.  `ctypes.string_at` declares its
    size parameter as a C int: a >2 GiB record stream (one flybase-scale
    file is ~4-5 GB) wrapped negative and raised SystemError deep inside
    PyBytes_FromStringAndSize."""
    if size < (1 << 31) - 1:
        return ctypes.string_at(ptr, size)
    return bytes((ctypes.c_char * size).from_address(
        ctypes.cast(ptr, ctypes.c_void_p).value
    ))


def _drain_result(lib: ctypes.CDLL, handle: int, data: AtomSpaceData) -> None:
    try:
        err = lib.das_error(handle)
        if err:
            raise NativeParseError(err.decode("utf-8", "replace"))
        size = ctypes.c_uint64()
        for i in range(lib.das_buffer_count(handle)):
            ptr = lib.das_buffer(handle, i, ctypes.byref(size))
            if size.value:
                buf = _buffer_bytes(ptr, size.value)
                lib.das_buffer_release(handle, i)  # free before decode:
                # buffer + copy would otherwise coexist for the whole
                # decode of a multi-GB stream
                _decode_into(buf, data)
            else:
                lib.das_buffer_release(handle, i)
    finally:
        lib.das_free(handle)


def load_canonical_files_native(
    paths: List[str],
    data: Optional[AtomSpaceData] = None,
    n_threads: Optional[int] = None,
) -> AtomSpaceData:
    """Parse canonical files with the native scanner (C++ threads), then
    replay the record streams into the store in input order.

    Files are processed in waves of `n_threads` so at most one wave's
    encoded record streams (which expand nested expressions) is resident
    at once — large multi-file KBs stay within host memory the way the
    streaming Python fallback does."""
    lib = get_lib()
    if lib is None:
        raise NativeParseError("native library unavailable")
    if data is None:
        data = AtomSpaceData()
    if not paths:
        return data
    workers = n_threads or min(len(paths), os.cpu_count() or 1)
    for start in range(0, len(paths), workers):
        wave = paths[start : start + workers]
        arr = (ctypes.c_char_p * len(wave))(*[p.encode("utf-8") for p in wave])
        handle = lib.das_parse_files(arr, len(wave), workers)
        _drain_result(lib, handle, data)
    return data


def _col_field(lib, handle, field: int):
    """(pointer, nbytes) of one columnar field in the native result."""
    ptr = ctypes.POINTER(ctypes.c_uint8)()
    size = ctypes.c_uint64()
    rc = lib.das_col_get(handle, field, ctypes.byref(ptr), ctypes.byref(size))
    if rc != 0:
        raise NativeParseError(f"bad columnar field {field}")
    return ptr, int(size.value)


def _col_array(lib, handle, field: int, dtype, width: int = 0):
    """ONE copy of a columnar field, straight off the native pointer into
    a numpy array ([n, width] when width > 0) — these are multi-GB at
    reference scale, so no intermediate bytes object."""
    import numpy as np

    ptr, nbytes = _col_field(lib, handle, field)
    if nbytes == 0:
        arr = np.empty(0, dtype=dtype)
    else:
        arr = np.ctypeslib.as_array(ptr, shape=(nbytes,)).view(dtype).copy()
    if width:
        arr = arr.reshape(-1, width)
    return arr


def _col_bytes(lib, handle, field: int) -> bytes:
    """ONE copy of a blob field as bytes."""
    ptr, nbytes = _col_field(lib, handle, field)
    return _buffer_bytes(ptr, nbytes) if nbytes else b""


def load_canonical_files_columnar(
    paths: List[str],
    data: Optional[AtomSpaceData] = None,
    n_threads: Optional[int] = None,
) -> AtomSpaceData:
    """Chunk-parallel columnar parse (native/src/das_columnar.cc): files are
    split at newline boundaries, parsed on C++ threads, deduped and
    index-resolved natively; Python receives flat numpy columns and builds
    the lazy-view store (storage/columnar.py) with zero per-record work."""
    import numpy as np

    from das_tpu.storage.columnar import ColumnarCore, attach_columnar

    lib = get_lib()
    if lib is None or not getattr(lib, "das_tpu_has_columnar", False):
        raise NativeParseError("columnar native scanner unavailable")
    if data is None:
        data = AtomSpaceData()
    if not paths:
        return data
    workers = n_threads or (os.cpu_count() or 1)
    arr = (ctypes.c_char_p * len(paths))(*[p.encode("utf-8") for p in paths])
    handle = lib.das_parse_files_columnar(arr, len(paths), workers)
    try:
        err = lib.das_col_error(handle)
        if err:
            raise NativeParseError(err.decode("utf-8", "replace"))
        type_off = _col_array(lib, handle, 0, np.uint32)
        type_blob = _col_bytes(lib, handle, 1)
        type_hash16 = _col_array(lib, handle, 2, np.uint8, width=16)
        type_names = [
            type_blob[type_off[i] : type_off[i + 1]].decode("utf-8")
            for i in range(len(type_off) - 1)
        ]
        core = ColumnarCore(
            type_names=type_names,
            type_hash16=type_hash16,
            td_name_tid=_col_array(lib, handle, 3, np.int32),
            td_stype_tid=_col_array(lib, handle, 4, np.int32),
            td_ct=_col_array(lib, handle, 5, np.uint8, width=16),
            td_hash=_col_array(lib, handle, 6, np.uint8, width=16),
            node_hash=_col_array(lib, handle, 7, np.uint8, width=16),
            node_tid=_col_array(lib, handle, 8, np.int32),
            node_name_off=_col_array(lib, handle, 9, np.uint64).astype(np.int64),
            node_name_blob=_col_bytes(lib, handle, 10),
            link_hash=_col_array(lib, handle, 11, np.uint8, width=16),
            link_tid=_col_array(lib, handle, 12, np.int32),
            link_ct=_col_array(lib, handle, 13, np.uint8, width=16),
            link_top=_col_array(lib, handle, 14, np.uint8),
            link_elem_off=_col_array(lib, handle, 15, np.uint64).astype(np.int64),
            link_elem=_col_array(lib, handle, 16, np.int32),
            dangling=[
                d.decode("ascii") for d in _chunk32(_col_bytes(lib, handle, 17))
            ],
        )
    finally:
        lib.das_col_free(handle)
    return attach_columnar(data, core)


def _chunk32(blob: bytes) -> List[bytes]:
    return [blob[i : i + 32] for i in range(0, len(blob), 32)]


def load_canonical_text_native(
    text: str, data: Optional[AtomSpaceData] = None
) -> AtomSpaceData:
    lib = get_lib()
    if lib is None:
        raise NativeParseError("native library unavailable")
    if data is None:
        data = AtomSpaceData()
    raw = text.encode("utf-8")
    handle = lib.das_parse_text(raw, len(raw))
    _drain_result(lib, handle, data)
    return data
