"""Atomese (.scm, OpenCog scheme) parser — dependency-free.

Same behavior as the reference PLY pair
(/root/reference/das/atomese_lex.py, atomese_yacc.py):

  * type names lose a trailing ``Node``/``Link`` suffix
    (``ConceptNode`` → ``Concept``);
  * ``(stv 0.9 0.8)`` truth-value sub-expressions are skipped;
  * node names become ``"{Type}:{name}"`` terminals;
  * typedefs are auto-generated on first sight of each type / node
    (every type inherits directly from Type);
  * ``;`` comments ignored.

Reuses the MettaParser hashing actions (ingest/metta.py) so handles are
identical to what the reference produces for the same .scm input.
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional

from das_tpu.core.exceptions import AtomeseLexerError, AtomeseSyntaxError
from das_tpu.core.expression import Expression
from das_tpu.core.schema import BASIC_TYPE
from das_tpu.ingest.metta import MettaParser, SymbolTable

_TOKEN_RE = re.compile(
    r"""
    (?P<WS>[ \t]+)
  | (?P<NL>\n+)
  | (?P<COMMENT>;[^\n]*)
  | (?P<OPEN>\()
  | (?P<CLOSE>\))
  | (?P<NAME>"[^"]+")
  | (?P<FLOAT>\d+\.\d+)
  | (?P<TYPE>[^\W0-9]\w*)
    """,
    re.VERBOSE,
)

_OPEN, _CLOSE, _NAME, _FLOAT, _TYPE, _STV = range(6)


def tokenize(text: str):
    pos, lineno, n = 0, 1, len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            near = text[pos : pos + 30]
            raise AtomeseLexerError(
                f"Illegal character at line {lineno}: '{text[pos]}' Near: '{near}...'"
            )
        pos = m.end()
        kind = m.lastgroup
        if kind in ("WS", "COMMENT"):
            continue
        if kind == "NL":
            lineno += len(m.group())
            continue
        if kind == "OPEN":
            yield (_OPEN, "(", lineno)
        elif kind == "CLOSE":
            yield (_CLOSE, ")", lineno)
        elif kind == "NAME":
            yield (_NAME, m.group()[1:-1], lineno)
        elif kind == "FLOAT":
            yield (_FLOAT, m.group(), lineno)
        else:
            value = m.group()
            if value in ("STV", "stv"):
                yield (_STV, value, lineno)
            else:
                if value.endswith("Node") or value.endswith("Link"):
                    value = value[:-4]
                yield (_TYPE, value, lineno)


class AtomeseParser(MettaParser):
    """Recursive-descent Atomese parser on top of the MeTTa hashing core."""

    def __init__(self, symbol_table: Optional[SymbolTable] = None, **callbacks):
        super().__init__(symbol_table=symbol_table, **callbacks)
        self._seen_types = set()
        self._seen_nodes = set()

    def _ensure_type(self, type_name: str) -> None:
        if type_name in self._seen_types:
            return
        self._seen_types.add(type_name)
        expr = self._typedef(type_name, BASIC_TYPE)
        expr.toplevel = True
        if self.on_typedef:
            self.on_typedef(expr)

    def _node(self, node_type: str, node_name: str) -> Expression:
        self._ensure_type(node_type)
        terminal_name = f"{node_type}:{node_name}"
        if terminal_name not in self._seen_nodes:
            self._seen_nodes.add(terminal_name)
            expr = self._typedef(terminal_name, node_type)
            expr.toplevel = True
            if self.on_typedef:
                self.on_typedef(expr)
            terminal = self._terminal(terminal_name)
            if self.on_terminal:
                self.on_terminal(terminal)
            return terminal
        return self._terminal(terminal_name)

    def parse(self, text: str) -> str:
        tokens = list(tokenize(text))
        pos, n = 0, len(tokens)

        def fail(msg, tok):
            raise AtomeseSyntaxError(f"Syntax error in line {tok[2]}: {msg}")

        def parse_atom(toplevel: bool) -> Expression:
            nonlocal pos
            tok = tokens[pos]
            if tok[0] != _OPEN:
                fail(f"expected '(' got {tok[1]!r}", tok)
            pos += 1
            tok = tokens[pos]
            if tok[0] != _TYPE:
                fail(f"expected atom type got {tok[1]!r}", tok)
            atom_type = tok[1]
            pos += 1
            # node?
            if tokens[pos][0] == _NAME:
                node_name = tokens[pos][1]
                pos += 1
                if tokens[pos][0] != _CLOSE:
                    fail("expected ')' after node name", tokens[pos])
                pos += 1
                return self._node(atom_type, node_name)
            # link: optional stv sub-expression, then target atoms
            targets: List[Expression] = []
            while tokens[pos][0] != _CLOSE:
                if (
                    tokens[pos][0] == _OPEN
                    and pos + 1 < n
                    and tokens[pos + 1][0] == _STV
                ):
                    # skip (stv f f)
                    pos += 2
                    while tokens[pos][0] == _FLOAT:
                        pos += 1
                    if tokens[pos][0] != _CLOSE:
                        fail("bad stv definition", tokens[pos])
                    pos += 1
                    continue
                target = parse_atom(False)
                targets.append(target)
                if target.elements is not None and self.on_expression and not toplevel:
                    pass  # nested links reported when consumed below
            pos += 1  # consume ')'
            if not targets:
                fail(f"link {atom_type} with no targets", tok)
            self._ensure_type(atom_type)
            head = self._symbol(atom_type)
            expr = self._nested([head, *targets])
            for target in targets:
                if target.elements is not None and self.on_expression:
                    self.on_expression(target)
            expr.toplevel = toplevel
            if toplevel and expr.elements is not None and self.on_toplevel:
                self.on_toplevel(expr)
            return expr

        while pos < n:
            parse_atom(True)
        self._finish()
        return "SUCCESS"
