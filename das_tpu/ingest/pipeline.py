"""Multi-file load pipeline.

The reference load path is a 10-thread relay (parse threads with 10s
staggered starts to dodge PLY's unsafe startup, four index-builder threads
shelling out to sort(1), Mongo/Redis uploader threads synchronized by
ok-counters — parser_threads.py:78-335, distributed_atom_space.py:138-168).

Here parsing is re-entrant and indexes are derived tensors, so the
pipeline collapses to: parse files concurrently (thread pool — useful when
the native C++ scanner releases the GIL; harmless otherwise), merge
records into the columnar store under one lock, then finalize + upload
once.  Failure semantics are deterministic: any parse error aborts the
whole load before the store is touched (the reference swallows duplicate
errors mid-upload, leaving partial state)."""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from threading import Lock
from typing import List, Optional

from das_tpu.core.expression import Expression
from das_tpu.ingest.canonical import CanonicalLoader
from das_tpu.ingest.metta import MettaParser
from das_tpu.storage.atom_table import AtomSpaceData
from das_tpu.utils.logger import logger


def knowledge_base_file_list(source: str) -> List[str]:
    """File-or-directory expansion (reference distributed_atom_space.py:81-99)."""
    answer = []
    if os.path.isfile(source):
        answer.append(source)
    elif os.path.isdir(source):
        for file_name in sorted(os.listdir(source)):
            path = os.path.join(source, file_name)
            if os.path.exists(path):
                answer.append(path)
    else:
        raise ValueError(f"Invalid knowledge base path: {source}")
    answer = [f for f in answer if f.endswith(".metta") or f.endswith(".scm")]
    if not answer:
        raise ValueError(f"No MeTTa files found in {source}")
    return answer


class _FileResult:
    def __init__(self):
        self.typedefs: List[Expression] = []
        self.terminals: List[Expression] = []
        self.regular: List[Expression] = []


def _parse_one(data: AtomSpaceData, path: str, lock: Lock) -> _FileResult:
    result = _FileResult()
    with open(path, "r") as fh:
        text = fh.read()
    if path.endswith(".scm"):
        from das_tpu.ingest.atomese import AtomeseParser

        parser = AtomeseParser(
            symbol_table=data.table,
            on_typedef=result.typedefs.append,
            on_terminal=result.terminals.append,
            on_expression=result.regular.append,
            on_toplevel=result.regular.append,
        )
    else:
        parser = MettaParser(
            symbol_table=data.table,
            on_typedef=result.typedefs.append,
            on_terminal=result.terminals.append,
            on_expression=result.regular.append,
            on_toplevel=result.regular.append,
        )
    # symbol table writes are dict inserts of deterministic values; shared
    # table + lock keeps cross-file type knowledge consistent
    with lock:
        parser.parse(text)
    return result


def load_knowledge_base(
    data: AtomSpaceData, source: str, max_workers: Optional[int] = None
) -> AtomSpaceData:
    """Parse .metta/.scm file(s) into the store (general parser path)."""
    files = knowledge_base_file_list(source)
    logger().info(f"Loading knowledge base: {len(files)} file(s)")
    lock = Lock()
    if len(files) == 1:
        results = [_parse_one(data, files[0], lock)]
    else:
        with ThreadPoolExecutor(max_workers=max_workers or min(8, len(files))) as ex:
            results = list(
                ex.map(lambda p: _parse_one(data, p, lock), files)
            )
    for result in results:
        for expr in result.typedefs:
            data.add_typedef(expr)
        for expr in result.terminals:
            data.add_terminal(expr)
        for expr in result.regular:
            data.add_link(expr)
    logger().info("Finished loading knowledge base")
    return data


def load_canonical_knowledge_base(data: AtomSpaceData, source: str) -> AtomSpaceData:
    """Canonical fast path (one toplevel expression per line; see
    das_tpu/ingest/canonical.py).  Files are processed in reverse-sorted
    order like the reference (distributed_atom_space.py:405).  Uses the
    native C++ scanner (GIL-free std::thread per file) when its library is
    available; the pure-Python scanner otherwise — record-identical paths
    (tests/test_native.py)."""
    files = sorted(knowledge_base_file_list(source), reverse=True)
    from das_tpu.ingest import native

    if native.native_available():
        empty = not (data.nodes or data.links or data.typedefs)
        if (
            empty
            and native.columnar_available()
            and os.environ.get("DAS_TPU_COLUMNAR", "1") != "0"
        ):
            # chunk-parallel columnar parse + lazy-view store: the fast
            # path for bulk loads (decode was the r03 bottleneck at
            # 21k expr/s; this path does zero per-record Python work)
            logger().info(f"Canonical KB (columnar scanner): {len(files)} file(s)")
            return native.load_canonical_files_columnar(files, data)
        logger().info(f"Canonical KB (native scanner): {len(files)} file(s)")
        return native.load_canonical_files_native(files, data)
    loader = CanonicalLoader(data)
    for path in files:
        logger().info(f"Canonical KB file: {path}")
        loader.parse_file(path)
    return data
