"""MeTTa knowledge-base parser (dependency-free).

Replaces the reference's PLY lexer+LALR grammar
(/root/reference/das/metta_lex.py, metta_yacc.py, base_yacc.py) with a
hand-rolled tokenizer and recursive-descent parser producing hash-identical
`Expression` records.  The grammar:

    START            -> TOP_LEVEL*
    TOP_LEVEL        -> '(' ':' NAME TYPE_DESIGNATOR ')'     (typedef)
                      | '(' EXPRESSION+ ')'                  (expression)
    EXPRESSION       -> '(' EXPRESSION+ ')' | SYMBOL | TERMINAL
    TERMINAL         -> '"' [^"]+ '"'
    SYMBOL           -> [^\\W0-9]\\w*            ('Type' is the basic type)

Hashing semantics (reference base_yacc.py:68-161):
  * typedef ``(: N D)``:   handle = md5-expr(h(':'), [h(N), h(D)]);
    registers N's parent type and, for terminals, N's named type.
  * terminal ``"n"`` of registered type T:  handle = md5("T n").
  * symbol ``S`` (head position): handle = its typedef's handle;
    named_type is S itself.
  * nested ``(S e1..ek)``:  handle = md5-expr(h(S), [handle(e1)..]);
    composite_type = [h(S), ct(e1).., ] with singleton lists unwrapped.

Forward references are legal: symbols/terminals/typedefs referring to
not-yet-defined names go onto pending lists resolved to a fixpoint at EOF
(reference base_yacc.py:163-201); anything still unresolved raises
`UndefinedSymbolError`.

Unlike the PLY machinery this parser is thread-safe and re-entrant (no
global parser tables), so the load pipeline needs no 10-second staggered
thread starts (reference distributed_atom_space.py:352-357).
"""

from __future__ import annotations

import re
from typing import Callable, List, Optional, Tuple

from das_tpu.core.exceptions import MettaLexerError, MettaSyntaxError, UndefinedSymbolError
from das_tpu.core.expression import Expression
from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.schema import BASIC_TYPE, TYPEDEF_MARK

#: the bare-SYMBOL token grammar — shared with convert/dump.py, which must
#: decide whether a typedef name can render unquoted
SYMBOL_PATTERN = r"[^\W0-9]\w*"

_TOKEN_RE = re.compile(
    r"""
    (?P<WS>[ \t]+)
  | (?P<NL>\n+)
  | (?P<OPEN>\()
  | (?P<CLOSE>\))
  | (?P<SETOPEN>\{)
  | (?P<SETCLOSE>\})
  | (?P<MARK>:)
  | (?P<TERMINAL>"[^"]+")
  | (?P<SYMBOL>"""
    + SYMBOL_PATTERN
    + r""")
    """,
    re.VERBOSE,
)

# token kinds
_OPEN, _CLOSE, _MARK, _TERMINAL, _SYMBOL, _SETOPEN, _SETCLOSE = range(7)


def tokenize(text: str):
    """Yield (kind, value, lineno) tuples; raises MettaLexerError on junk."""
    pos = 0
    lineno = 1
    n = len(text)
    while pos < n:
        m = _TOKEN_RE.match(text, pos)
        if m is None:
            near = text[pos : pos + 30]
            raise MettaLexerError(
                f"Illegal character at line {lineno}: '{text[pos]}' Near: '{near}...'"
            )
        pos = m.end()
        kind = m.lastgroup
        if kind == "WS":
            continue
        if kind == "NL":
            lineno += len(m.group())
            continue
        if kind == "OPEN":
            yield (_OPEN, "(", lineno)
        elif kind == "CLOSE":
            yield (_CLOSE, ")", lineno)
        elif kind == "SETOPEN":
            yield (_SETOPEN, "{", lineno)
        elif kind == "SETCLOSE":
            yield (_SETCLOSE, "}", lineno)
        elif kind == "MARK":
            yield (_MARK, TYPEDEF_MARK, lineno)
        elif kind == "TERMINAL":
            yield (_TERMINAL, m.group()[1:-1], lineno)
        else:
            yield (_SYMBOL, m.group(), lineno)


class SymbolTable:
    """Shared hashing caches (reference base_yacc.py:34-59).  May be shared
    across parser instances (e.g. incremental transaction commits reusing the
    store's accumulated type knowledge)."""

    def __init__(self):
        self.named_type_hash = {}   # type name -> md5
        self.named_types = {}       # defined name -> its type designator name
        self.symbol_hash = {}       # defined name -> typedef expression hash
        self.terminal_hash = {}     # (type, name) -> md5
        self.parent_type = {}       # type hash -> parent type hash
        #: optional fallback: terminal name -> type name for terminals the
        #: table has never parsed a declaration for.  The columnar ingest
        #: path sets this to a store probe (storage/columnar.py
        #: attach_columnar): it deliberately does NOT materialize millions
        #: of terminal symbols into these dicts, so a later transaction
        #: referencing a pre-loaded terminal (`(Inheritance "lion"
        #: "mammal")` style) resolves through the store instead of dying
        #: with UndefinedSymbolError.
        self.terminal_resolver = None
        basic = ExpressionHasher.named_type_hash(BASIC_TYPE)
        self.named_type_hash[BASIC_TYPE] = basic
        self.parent_type[basic] = basic

    def get_named_type_hash(self, name: str) -> str:
        h = self.named_type_hash.get(name)
        if h is None:
            h = ExpressionHasher.named_type_hash(name)
            self.named_type_hash[name] = h
        return h

    def get_terminal_hash(self, named_type: str, terminal_name: str) -> str:
        key = (named_type, terminal_name)
        h = self.terminal_hash.get(key)
        if h is None:
            h = ExpressionHasher.terminal_hash(named_type, terminal_name)
            self.terminal_hash[key] = h
        return h


class MettaParser:
    """Recursive-descent MeTTa parser with reference-identical hashing.

    Callbacks (all optional) mirror the reference ParserActions broker
    (/root/reference/das/parser_actions.py:7-31):
      on_typedef(expr)     — top-level ``(: N D)``
      on_terminal(expr)    — each terminal occurrence
      on_expression(expr)  — each non-toplevel nested expression
      on_toplevel(expr)    — each top-level regular expression
    """

    def __init__(
        self,
        symbol_table: Optional[SymbolTable] = None,
        on_typedef: Optional[Callable[[Expression], None]] = None,
        on_terminal: Optional[Callable[[Expression], None]] = None,
        on_expression: Optional[Callable[[Expression], None]] = None,
        on_toplevel: Optional[Callable[[Expression], None]] = None,
    ):
        self.table = symbol_table if symbol_table is not None else SymbolTable()
        self.on_typedef = on_typedef
        self.on_terminal = on_terminal
        self.on_expression = on_expression
        self.on_toplevel = on_toplevel
        self.pending_terminals: List[Tuple[str, Expression]] = []
        self.pending_symbols: List[Tuple[str, Expression]] = []
        self.pending_typedefs: List[Tuple[Tuple[str, str], Expression]] = []
        self.pending_expressions: List[Tuple[List[Expression], Expression]] = []
        # the implicit (: Type Type) root typedef
        root = self._typedef(BASIC_TYPE, BASIC_TYPE)
        if self.on_typedef:
            self.on_typedef(root)

    # -- hashing actions ---------------------------------------------------

    def _typedef(self, name: str, designator: str, expression: Optional[Expression] = None) -> Expression:
        if expression is None:
            expression = Expression()
        t = self.table
        designator_hash = t.named_type_hash.get(designator)
        if designator_hash is None:
            self.pending_typedefs.append(((name, designator), expression))
            return expression
        mark_hash = t.get_named_type_hash(TYPEDEF_MARK)
        name_hash = t.get_named_type_hash(name)
        t.parent_type[name_hash] = designator_hash
        t.named_types[name] = designator
        expression.typedef_name = name
        expression.typedef_name_hash = name_hash
        expression.named_type = TYPEDEF_MARK
        expression.named_type_hash = mark_hash
        expression.composite_type = [
            mark_hash,
            designator_hash,
            t.parent_type[designator_hash],
        ]
        expression.composite_type_hash = ExpressionHasher.composite_hash(
            expression.composite_type
        )
        expression.elements = [name_hash, designator_hash]
        expression.hash_code = ExpressionHasher.expression_hash(
            mark_hash, expression.elements
        )
        t.symbol_hash[name] = expression.hash_code
        return expression

    def _terminal(self, terminal_name: str, expression: Optional[Expression] = None) -> Expression:
        if expression is None:
            expression = Expression(terminal_name=terminal_name)
        t = self.table
        named_type = t.named_types.get(terminal_name)
        if named_type is None and t.terminal_resolver is not None:
            named_type = t.terminal_resolver(terminal_name)
            if named_type is not None:
                t.named_types[terminal_name] = named_type
        if named_type is None:
            self.pending_terminals.append((terminal_name, expression))
            return expression
        nth = t.get_named_type_hash(named_type)
        expression.named_type = named_type
        expression.named_type_hash = nth
        expression.composite_type = [nth]
        expression.composite_type_hash = nth
        expression.hash_code = t.get_terminal_hash(named_type, terminal_name)
        return expression

    def _symbol(self, name: str, expression: Optional[Expression] = None) -> Expression:
        if expression is None:
            expression = Expression()
        t = self.table
        named = t.named_types.get(name)
        if named is None and t.terminal_resolver is not None:
            # same store fallback as _terminal: a columnar-loaded
            # terminal's bare name must behave like it does on the
            # dict-backed loaders (which record every terminal)
            named = t.terminal_resolver(name)
            if named is not None:
                t.named_types[name] = named
        if named is None:
            self.pending_symbols.append((name, expression))
            return expression
        nth = t.get_named_type_hash(name)
        expression.symbol_name = name
        expression.named_type = name
        expression.named_type_hash = nth
        expression.composite_type = [nth]
        expression.composite_type_hash = nth
        h = t.symbol_hash.get(name)
        if h is None:
            # the canonical loaders record a terminal's TYPE without its
            # declaration hash (computing one md5 per terminal up front
            # would cost ~a minute at reference scale); the typedef
            # expression hash is a pure function of the names, so compute
            # it here — identical to what _typedef would have stored
            h = ExpressionHasher.expression_hash(
                t.get_named_type_hash(TYPEDEF_MARK),
                [nth, t.get_named_type_hash(t.named_types[name])],
            )
            t.symbol_hash[name] = h
        expression.hash_code = h
        return expression

    def _nested(self, subs: List[Expression], expression: Optional[Expression] = None, lineno: int = 0) -> Expression:
        if expression is None:
            expression = Expression()
        if any(s.hash_code is None for s in subs):
            self.pending_expressions.append((subs, expression))
            return expression
        head = subs[0]
        if head.named_type is None:
            raise MettaSyntaxError(
                f"Syntax error in line {lineno}: non-typed expressions are not supported"
            )
        expression.named_type = head.named_type
        expression.named_type_hash = head.named_type_hash
        expression.composite_type = [
            s.composite_type if len(s.composite_type) > 1 else s.composite_type[0]
            for s in subs
        ]
        expression.composite_type_hash = ExpressionHasher.composite_hash(
            [s.composite_type_hash for s in subs]
        )
        expression.elements = [s.hash_code for s in subs[1:]]
        expression.hash_code = ExpressionHasher.expression_hash(
            expression.named_type_hash, expression.elements
        )
        return expression

    # -- pending-symbol fixpoint (reference base_yacc.py:163-201) ----------

    def _revisit_pending(self):
        while True:
            pending = self.pending_typedefs
            self.pending_typedefs = []
            dirty = False
            for (name, designator), expr in pending:
                if self._typedef(name, designator, expr).hash_code is not None:
                    dirty = True
            if not dirty:
                break
        pending = self.pending_terminals
        self.pending_terminals = []
        for name, expr in pending:
            self._terminal(name, expr)
        pending = self.pending_symbols
        self.pending_symbols = []
        for name, expr in pending:
            self._symbol(name, expr)
        while True:
            pending = self.pending_expressions
            self.pending_expressions = []
            dirty = False
            for subs, expr in pending:
                if self._nested(subs, expr).hash_code is not None:
                    dirty = True
            if not dirty:
                break

    def _finish(self):
        self._revisit_pending()
        missing = [name for name, _ in self.pending_terminals]
        missing += [name for name, _ in self.pending_symbols]
        missing += [designator for (name, designator), _ in self.pending_typedefs]
        if missing:
            raise UndefinedSymbolError(sorted(set(missing)))
        assert not self.pending_expressions

    # -- recursive descent -------------------------------------------------

    def parse(self, text: str) -> str:
        tokens = list(tokenize(text))
        pos = 0
        n = len(tokens)

        def expect(kind):
            nonlocal pos
            if pos >= n or tokens[pos][0] != kind:
                got = tokens[pos] if pos < n else ("EOF", "EOF", -1)
                raise MettaSyntaxError(
                    f"Syntax error in line {got[2]}: unexpected token {got[1]!r}"
                )
            tok = tokens[pos]
            pos += 1
            return tok

        def parse_expr(toplevel: bool) -> Expression:
            nonlocal pos
            kind, value, lineno = tokens[pos]
            if kind == _TERMINAL:
                pos += 1
                expr = self._terminal(value)
                if self.on_terminal:
                    self.on_terminal(expr)
                return expr
            if kind == _SYMBOL:
                pos += 1
                return self._symbol(value)
            if kind == _SETOPEN:
                # `{a b ...}` multiset sugar (the atomese2metta converter's
                # MSet output, reference translator.py:63-71) — parsed as a
                # `Set` expression, the unordered link type
                pos += 1
                subs = [self._symbol("Set")]
                while pos < n and tokens[pos][0] != _SETCLOSE:
                    subs.append(parse_expr(False))
                expect(_SETCLOSE)
                if len(subs) == 1:
                    raise MettaSyntaxError(
                        f"Syntax error in line {lineno}: empty multiset"
                    )
                expr = self._nested(subs, lineno=lineno)
                expr.toplevel = toplevel
                if toplevel and self.on_toplevel:
                    self.on_toplevel(expr)
                elif not toplevel and self.on_expression:
                    self.on_expression(expr)
                return expr
            if kind != _OPEN:
                raise MettaSyntaxError(
                    f"Syntax error in line {lineno}: unexpected token {value!r}"
                )
            pos += 1  # consume '('
            if pos < n and tokens[pos][0] == _MARK:
                # typedef — legal only at top level (reference metta_yacc.py:137-149)
                if not toplevel:
                    raise MettaSyntaxError(
                        f"Error in line {tokens[pos][2]}: invalid nested type definition"
                    )
                pos += 1
                k, name, ln = tokens[pos]
                if k not in (_SYMBOL, _TERMINAL):
                    raise MettaSyntaxError(
                        f"Syntax error in line {ln}: bad typedef name {name!r}"
                    )
                pos += 1
                k, designator, ln = tokens[pos]
                if k != _SYMBOL:
                    raise MettaSyntaxError(
                        f"Syntax error in line {ln}: bad type designator {designator!r}"
                    )
                pos += 1
                if designator == BASIC_TYPE:
                    bh = self.table.get_named_type_hash(BASIC_TYPE)
                    self.table.parent_type[bh] = bh
                expect(_CLOSE)
                expr = self._typedef(name, designator)
                expr.toplevel = True
                if self.on_typedef:
                    self.on_typedef(expr)
                return expr
            subs = []
            while pos < n and tokens[pos][0] != _CLOSE:
                subs.append(parse_expr(False))
            expect(_CLOSE)
            if not subs:
                raise MettaSyntaxError(f"Syntax error in line {lineno}: empty expression")
            expr = self._nested(subs, lineno=lineno)
            expr.toplevel = toplevel
            if toplevel:
                if self.on_toplevel:
                    self.on_toplevel(expr)
            else:
                if self.on_expression:
                    self.on_expression(expr)
            return expr

        while pos < n:
            parse_expr(True)
        self._finish()
        return "SUCCESS"

    def parse_file(self, path: str) -> str:
        with open(path, "r") as fh:
            return self.parse(fh.read())

    def check(self, text: str) -> str:
        """Syntax-check only (no hashing side effects leak: uses a scratch
        parser on a copied symbol table).  type(self): a subclass (the
        Atomese parser) must check with ITS grammar, not MeTTa's."""
        scratch = type(self)()
        scratch.table.named_type_hash.update(self.table.named_type_hash)
        scratch.table.named_types.update(self.table.named_types)
        scratch.table.symbol_hash.update(self.table.symbol_hash)
        scratch.table.parent_type.update(self.table.parent_type)
        # columnar stores resolve pre-loaded terminals through the store
        # probe, never through named_types — a check() without it would
        # reject commits the real parse accepts
        scratch.table.terminal_resolver = self.table.terminal_resolver
        return scratch.parse(text)
