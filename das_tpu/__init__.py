"""das_tpu — TPU-native Distributed AtomSpace.

A knowledge-hypergraph store + conjunctive pattern-matching query engine
with the capabilities of the reference DAS (tanksha/das), re-designed for
TPU: the AtomSpace lives as device-resident int32/int64 tensors (row-id
link tables, sorted probe indexes, incoming-set CSR) and queries execute as
batched searchsorted range probes + vectorized binding-table joins, sharded
over a `jax.sharding.Mesh`.  See SURVEY.md for the reference analysis.
"""

import os

import jax

# Restore JAX's documented env semantics: the ambient TPU-tunnel
# sitecustomize pins `jax_platforms` via config AFTER env vars are read,
# so an explicit JAX_PLATFORMS (e.g. cpu for virtual-mesh tests) would be
# silently ignored without this re-application.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# Device handles and probe keys are int64 (md5-derived); enable wide ints.
# All kernels use explicit dtypes, so this does not change float behavior
# for user code that follows JAX's explicit-dtype conventions.
jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from das_tpu.core.config import DasConfig  # noqa: E402,F401
