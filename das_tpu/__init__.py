"""das_tpu — TPU-native Distributed AtomSpace.

A knowledge-hypergraph store + conjunctive pattern-matching query engine
with the capabilities of the reference DAS (tanksha/das), re-designed for
TPU: the AtomSpace lives as device-resident int32/int64 tensors (row-id
link tables, sorted probe indexes, incoming-set CSR) and queries execute as
batched searchsorted range probes + vectorized binding-table joins, sharded
over a `jax.sharding.Mesh`.  See SURVEY.md for the reference analysis.
"""

import os

import jax

# Restore JAX's documented env semantics: the ambient TPU-tunnel
# sitecustomize pins `jax_platforms` via config AFTER env vars are read,
# so an explicit JAX_PLATFORMS (e.g. cpu for virtual-mesh tests) would be
# silently ignored without this re-application.
if os.environ.get("JAX_PLATFORMS"):
    jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

# Device handles and probe keys are int64 (md5-derived); enable wide ints.
# All kernels use explicit dtypes, so this does not change float behavior
# for user code that follows JAX's explicit-dtype conventions.
jax.config.update("jax_enable_x64", True)

_compile_cache_checked = False


def enable_compile_cache() -> None:
    """Persistent XLA compilation cache: fused query programs are large
    (every probe/join/anti-join of a plan shape in one executable) and a
    cold TPU compile can take tens of seconds; caching across processes
    makes service restarts and repeated bench runs start warm.

    Called lazily at first device-table construction, when the backend is
    known: accelerator platforms only — XLA:CPU AOT results are
    machine-feature sensitive (reloading across feature-detection
    differences risks SIGILL) and CPU compiles are cheap anyway.  Override
    dir via DAS_TPU_XLA_CACHE; disable with DAS_TPU_XLA_CACHE=0."""
    global _compile_cache_checked
    if _compile_cache_checked:
        return
    _compile_cache_checked = True
    cache_dir = os.environ.get(
        "DAS_TPU_XLA_CACHE",
        os.path.join(
            os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
            "das_tpu", "xla",
        ),
    )
    if cache_dir == "0":
        return
    try:
        if jax.devices()[0].platform == "cpu":
            return
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    except Exception:  # older jax without the knobs: run uncached
        pass

__version__ = "0.1.0"

from das_tpu.core.config import DasConfig  # noqa: E402,F401
