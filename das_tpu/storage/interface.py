"""Abstract query surface the pattern matcher programs against.

Method-for-method parity with the reference `DBInterface`
(/root/reference/das/database/db_interface.py:7-71); every backend in
das_tpu/storage implements this.  `get_matched_links` and
`get_matched_type_template` return lists of ``(link_handle, (targets...))``
pairs except for the fully-grounded fast path which returns ``[handle]``.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, List, Tuple

from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD  # re-export

__all__ = ["DBInterface", "WILDCARD", "UNORDERED_LINK_TYPES"]


class DBInterface(ABC):
    def __repr__(self):
        return "<DBInterface>"

    @abstractmethod
    def node_exists(self, node_type: str, node_name: str) -> bool: ...

    @abstractmethod
    def link_exists(self, link_type: str, targets: List[str]) -> bool: ...

    @abstractmethod
    def get_node_handle(self, node_type: str, node_name: str) -> str: ...

    @abstractmethod
    def get_link_handle(self, link_type: str, target_handles: List[str]) -> str: ...

    @abstractmethod
    def get_link_targets(self, handle: str) -> List[str]: ...

    @abstractmethod
    def is_ordered(self, handle: str) -> bool: ...

    @abstractmethod
    def get_matched_links(self, link_type: str, target_handles: List[str]): ...

    @abstractmethod
    def get_all_nodes(self, node_type: str, names: bool = False) -> List[str]: ...

    @abstractmethod
    def get_matched_type_template(self, template: List[Any]) -> List[str]: ...

    @abstractmethod
    def get_matched_type(self, link_named_type: str): ...

    @abstractmethod
    def get_node_name(self, node_handle: str) -> str: ...

    @abstractmethod
    def get_matched_node_name(self, node_type: str, substring: str) -> str: ...

    # optional surface ----------------------------------------------------

    def get_atom_as_dict(self, handle: str, arity: int = -1):
        pass

    def get_atom_as_deep_representation(self, handle: str, arity: int = -1):
        pass

    def count_atoms(self) -> Tuple[int, int]:
        pass

    def prefetch(self) -> None:
        pass
