"""Columnar AtomSpace data: the single source of truth every backend reads.

The reference spreads the loaded KB over five Mongo collections and five
Redis key namespaces (SURVEY.md §2.2).  Here the whole AtomSpace is one
host-resident columnar structure:

  * `nodes`    — insertion-ordered dict  handle_hex -> NodeRec
  * `typedefs` — insertion-ordered dict  handle_hex -> TypedefRec
  * `links`    — insertion-ordered dict  handle_hex -> LinkRec

plus the accumulated `SymbolTable` (type hashes, parent types).  The
`finalize()` step derives the *device-facing* arrays: per-arity int64
buckets (type, composite-type, targets columns) with sorted permutations
for probe indexes — the tensor analogue of the Redis pattern/template/
incoming namespaces, except wildcard patterns are not materialized as 16
hash keys per link (reference parser_threads.py:183-219); probes compute
them by sorted-range intersection instead.

Host hex handles exist only here (API boundary); everything downstream of
`finalize()` is int64.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from das_tpu.core.expression import Expression
from das_tpu.core.hashing import ExpressionHasher, hex_to_i64
from das_tpu.ingest.metta import SymbolTable


@dataclass
class NodeRec:
    name: str
    named_type: str
    named_type_hash: str


@dataclass
class TypedefRec:
    name: str
    name_hash: str
    composite_type_hash: str
    designator_name: str


@dataclass
class LinkRec:
    named_type: str
    named_type_hash: str
    composite_type: list
    composite_type_hash: str
    elements: Tuple[str, ...]
    is_toplevel: bool


@dataclass
class LinkBucket:
    """Finalized int64 columns for one arity."""

    arity: int
    handles_hex: List[str]
    handle: np.ndarray          # [m] int64
    type: np.ndarray            # [m] int64 (named_type_hash)
    ctype: np.ndarray           # [m] int64 (composite_type_hash)
    targets: np.ndarray         # [m, arity] int64
    # sorted permutations for probes
    order_by_type: np.ndarray           # argsort of type
    order_by_ctype: np.ndarray          # argsort of ctype
    order_by_pos: List[np.ndarray]      # argsort of targets[:, p] per p
    order_by_type_pos: List[np.ndarray] # argsort of (type, targets[:, p])
    type_sorted: np.ndarray = None
    ctype_sorted: np.ndarray = None

    @property
    def size(self) -> int:
        return len(self.handles_hex)


class AtomSpaceData:
    """Mutable host store + derived columnar buckets."""

    def __init__(self, symbol_table: Optional[SymbolTable] = None):
        self.table = symbol_table if symbol_table is not None else SymbolTable()
        self.nodes: Dict[str, NodeRec] = {}
        self.typedefs: Dict[str, TypedefRec] = {}
        self.links: Dict[str, LinkRec] = {}
        self.incoming: Dict[str, List[str]] = {}   # atom hex -> link hexes
        self._buckets: Optional[Dict[int, LinkBucket]] = None
        self._i64_to_hex: Dict[int, str] = {}
        self.pattern_black_list: List[str] = []

    # -- ingestion ---------------------------------------------------------

    def add_typedef(self, expr: Expression) -> None:
        if expr.hash_code in self.typedefs:
            return
        self.typedefs[expr.hash_code] = TypedefRec(
            name=expr.typedef_name,
            name_hash=expr.typedef_name_hash,
            composite_type_hash=expr.composite_type_hash,
            designator_name=self.table.named_types.get(expr.typedef_name, ""),
        )

    def add_terminal(self, expr: Expression) -> None:
        if expr.hash_code in self.nodes:
            return
        self.nodes[expr.hash_code] = NodeRec(
            name=expr.terminal_name,
            named_type=expr.named_type,
            named_type_hash=expr.named_type_hash,
        )

    def add_link(self, expr: Expression) -> None:
        if expr.hash_code in self.links:
            # a link may be seen both nested and toplevel; keep toplevel flag
            if expr.toplevel:
                self.links[expr.hash_code].is_toplevel = True
            return
        rec = LinkRec(
            named_type=expr.named_type,
            named_type_hash=expr.named_type_hash,
            composite_type=expr.composite_type,
            composite_type_hash=expr.composite_type_hash,
            elements=tuple(expr.elements),
            is_toplevel=expr.toplevel,
        )
        self.links[expr.hash_code] = rec
        for element in rec.elements:
            self.incoming.setdefault(element, []).append(expr.hash_code)
        self._buckets = None  # invalidate derived arrays

    def add_expression(self, expr: Expression) -> None:
        """Route a completed parser record to the right table."""
        if expr.is_typedef:
            self.add_typedef(expr)
        elif expr.is_terminal:
            self.add_terminal(expr)
        else:
            self.add_link(expr)

    # -- finalization ------------------------------------------------------

    def finalize(self) -> Dict[int, LinkBucket]:
        """Build (or rebuild) the per-arity int64 buckets + sort indexes."""
        if self._buckets is not None:
            return self._buckets
        by_arity: Dict[int, List[Tuple[str, LinkRec]]] = {}
        for hex_handle, rec in self.links.items():
            by_arity.setdefault(len(rec.elements), []).append((hex_handle, rec))
        buckets: Dict[int, LinkBucket] = {}
        self._i64_to_hex = {}
        for hex_handle in self.nodes:
            self._i64_to_hex[int(hex_to_i64(hex_handle))] = hex_handle
        for arity, entries in by_arity.items():
            m = len(entries)
            handles_hex = [h for h, _ in entries]
            handle = np.empty(m, dtype=np.int64)
            type_col = np.empty(m, dtype=np.int64)
            ctype_col = np.empty(m, dtype=np.int64)
            targets = np.empty((m, arity), dtype=np.int64)
            for i, (h, rec) in enumerate(entries):
                hi = hex_to_i64(h)
                handle[i] = hi
                self._i64_to_hex[int(hi)] = h
                type_col[i] = hex_to_i64(rec.named_type_hash)
                ctype_col[i] = hex_to_i64(rec.composite_type_hash)
                for p, element in enumerate(rec.elements):
                    targets[i, p] = hex_to_i64(element)
            order_by_type = np.argsort(type_col, kind="stable")
            order_by_ctype = np.argsort(ctype_col, kind="stable")
            order_by_pos = [
                np.argsort(targets[:, p], kind="stable") for p in range(arity)
            ]
            order_by_type_pos = [
                np.lexsort((targets[:, p], type_col)) for p in range(arity)
            ]
            buckets[arity] = LinkBucket(
                arity=arity,
                handles_hex=handles_hex,
                handle=handle,
                type=type_col,
                ctype=ctype_col,
                targets=targets,
                order_by_type=order_by_type,
                order_by_ctype=order_by_ctype,
                order_by_pos=order_by_pos,
                order_by_type_pos=order_by_type_pos,
                type_sorted=type_col[order_by_type],
                ctype_sorted=ctype_col[order_by_ctype],
            )
        self._buckets = buckets
        return buckets

    def hex_of_i64(self, value: int) -> Optional[str]:
        if self._buckets is None:
            self.finalize()
        return self._i64_to_hex.get(int(value))

    # -- introspection -----------------------------------------------------

    def count_atoms(self) -> Tuple[int, int]:
        return (len(self.nodes), len(self.links))

    @property
    def named_type_hash_reverse(self) -> Dict[str, str]:
        return {v: k for k, v in self.table.named_type_hash.items()}


def load_metta_text(text: str, data: Optional[AtomSpaceData] = None) -> AtomSpaceData:
    """Parse MeTTa source straight into an AtomSpaceData."""
    from das_tpu.ingest.metta import MettaParser

    if data is None:
        data = AtomSpaceData()
    typedefs: List[Expression] = []
    terminals: List[Expression] = []
    regular: List[Expression] = []
    parser = MettaParser(
        symbol_table=data.table,
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_expression=regular.append,
        on_toplevel=regular.append,
    )
    parser.parse(text)
    # records may have been completed by the EOF fixpoint — route them now
    for expr in typedefs:
        data.add_typedef(expr)
    for expr in terminals:
        data.add_terminal(expr)
    for expr in regular:
        data.add_link(expr)
    data.finalize()
    return data


def load_metta_file(path: str, data: Optional[AtomSpaceData] = None) -> AtomSpaceData:
    with open(path, "r") as fh:
        return load_metta_text(fh.read(), data)
