"""Columnar AtomSpace data: the single source of truth every backend reads.

The reference spreads the loaded KB over five Mongo collections and five
Redis key namespaces (SURVEY.md §2.2).  Here the whole AtomSpace is one
host-resident columnar structure:

  * `nodes`    — insertion-ordered dict  handle_hex -> NodeRec
  * `typedefs` — insertion-ordered dict  handle_hex -> TypedefRec
  * `links`    — insertion-ordered dict  handle_hex -> LinkRec

plus the accumulated `SymbolTable` (type hashes, parent types).

`finalize()` derives the *device-facing* representation.  TPU-first design
decision: md5 handles never reach the device — every atom gets a dense
**int32 global row id** (nodes first, then links bucket-major), link targets
are stored as row-id columns, and named types get their own small int32
registry.  Probe indexes are argsort permutations over exact int64 keys
(``type_id << 32 | target_row``), so wildcard-pattern lookups are
`searchsorted` range scans — replacing the reference's materialized
16-keys-per-link Redis fan-out (parser_threads.py:183-219) with computed,
collision-free range intersections.  An incoming-set CSR replaces the
`incomming_set` Redis namespace.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from das_tpu.core.expression import Expression
from das_tpu.core.hashing import ExpressionHasher, hex_to_i64, hex_to_i64_bulk
from das_tpu.ingest.metta import SymbolTable


@dataclass
class NodeRec:
    name: str
    named_type: str
    named_type_hash: str


@dataclass
class TypedefRec:
    name: str
    name_hash: str
    composite_type_hash: str
    designator_name: str


@dataclass
class LinkRec:
    named_type: str
    named_type_hash: str
    composite_type: list
    composite_type_hash: str
    elements: Tuple[str, ...]
    is_toplevel: bool


@dataclass
class LinkBucket:
    """Finalized device-facing columns for one link arity.

    All row references are *global* atom row ids (int32).  `targets_sorted`
    is the per-row canonically sorted target matrix used by unordered
    (multiset) probes.  `order_*` are argsort permutations into this
    bucket's local rows; `key_*` the corresponding sorted key arrays.
    """

    arity: int
    rows: np.ndarray            # [m] int32 — global atom row of each link
    type_id: np.ndarray         # [m] int32
    ctype: np.ndarray           # [m] int64 — composite_type_hash
    targets: np.ndarray         # [m, arity] int32 — global rows of targets
    targets_sorted: np.ndarray  # [m, arity] int32

    order_by_type: np.ndarray
    key_type: np.ndarray        # int32 sorted
    order_by_ctype: np.ndarray
    key_ctype: np.ndarray       # int64 sorted
    order_by_type_pos: List[np.ndarray]    # per position p
    key_type_pos: List[np.ndarray]         # int64 (type_id<<32)|target sorted
    order_by_pos: List[np.ndarray]         # per position p (any type)
    key_pos: List[np.ndarray]              # int32 sorted
    # unordered (multiset) probe index over canonically sorted targets
    order_by_type_spos: List[np.ndarray]
    key_type_spos: List[np.ndarray]

    @property
    def size(self) -> int:
        return int(self.rows.shape[0])

    @cached_property
    def has_dangling(self) -> bool:
        """Whether ANY target in this segment is a dangling (-1) element —
        computed once per segment and cached, so grounded trivial counts
        (fused.py trivial_plan_count) skip their per-row dangling scan for
        segments known clean even when dangling hexes exist elsewhere in
        the store (ADVICE r4).  Segments are rebuilt on commit, so the
        cache can never go stale."""
        return bool((self.targets < 0).any())


@dataclass
class Finalized:
    """Everything derived by finalize(): registries + buckets + CSR."""

    atom_count: int
    node_count: int
    hex_of_row: List[str]
    row_of_hex: Dict[str, int]
    # type registry
    type_names: List[str]
    type_id_of_hash: Dict[str, int]      # named_type_hash hex -> id
    node_type_id: np.ndarray             # [node_count] int32
    buckets: Dict[int, LinkBucket]
    # incoming-set CSR over global rows
    incoming_offsets: np.ndarray         # [atom_count+1] int32
    incoming_links: np.ndarray           # [E] int32 (global link rows)
    # element hexes that resolved to no row (sentinel -1 targets); consulted
    # by the incremental commit path (tensor_db.py refresh)
    dangling_hexes: set = None
    # [nodes, links] already appended to the row registries.  Several
    # backends may share one cached Finalized (e.g. a ShardedDB and its
    # tree-fallback TensorDB over the same AtomSpaceData); delta interning
    # (storage/delta.py) consults these counters so each atom is appended
    # exactly once no matter which backend commits first.  None = set
    # lazily from node_count/atom_count (restored checkpoints).
    interned: list = None


def _combine_type_pos(type_id: np.ndarray, target: np.ndarray) -> np.ndarray:
    return (type_id.astype(np.int64) << 32) | target.astype(np.int64)


def build_bucket(
    arity: int,
    entries: List[Tuple[str, "LinkRec"]],
    row_of_hex: Dict[str, int],
    type_id,
    incoming_pairs: List[Tuple[np.ndarray, np.ndarray]],
    dangling: Optional[set] = None,
) -> LinkBucket:
    """Columnize one arity's link records and build its probe indexes.
    Shared by the full `finalize()` and the incremental delta path
    (storage/tensor_db.py refresh): a delta is just a small bucket whose
    indexes get merged into the device-resident ones.

    Columnization runs as COLUMN-WISE bulk passes (C-level `map` over the
    row dict, one vectorized hex→int64 decode, numpy masks for the
    incoming pairs) — at the 27.9M-link reference scale the old per-row
    Python loop dominated finalize time several-fold.  `incoming_pairs`
    receives (target_rows, link_rows) ARRAY chunks, not tuples."""
    m = len(entries)
    recs = [rec for _, rec in entries]
    rows = np.fromiter(
        map(row_of_hex.__getitem__, (h for h, _ in entries)),
        dtype=np.int32, count=m,
    )
    # type ids: intern each distinct hash once, then one bulk map pass
    first_seen: Dict[str, str] = {}
    for rec in recs:
        if rec.named_type_hash not in first_seen:
            first_seen[rec.named_type_hash] = rec.named_type
    tid_of = {h: type_id(h, nt) for h, nt in first_seen.items()}
    tids = np.fromiter(
        map(tid_of.__getitem__, (rec.named_type_hash for rec in recs)),
        dtype=np.int32, count=m,
    )
    # composite-type hashes repeat heavily (one per link-type/arity
    # template): decode each distinct hex once, then one bulk map pass
    ct_hexes = list({rec.composite_type_hash for rec in recs})
    ct_of = dict(zip(ct_hexes, hex_to_i64_bulk(ct_hexes).tolist()))
    ctype = np.fromiter(
        map(ct_of.__getitem__, (rec.composite_type_hash for rec in recs)),
        dtype=np.int64, count=m,
    )
    targets = np.empty((m, arity), dtype=np.int32)
    for p in range(arity):
        col = [rec.elements[p] for rec in recs]
        try:
            targets[:, p] = np.fromiter(
                map(row_of_hex.__getitem__, col), dtype=np.int32, count=m
            )
        except KeyError:
            # dangling target(s) (partial KB): park on a sentinel.  The
            # hex is recorded so a later commit that supplies the atom
            # can force a full re-finalize (the incremental path can't
            # retro-patch sorted positional indexes).
            for i, element in enumerate(col):
                trow = row_of_hex.get(element)
                if trow is None:
                    if dangling is not None:
                        dangling.add(element)
                    trow = -1
                targets[i, p] = trow
    return bucket_from_columns(arity, rows, tids, ctype, targets, incoming_pairs)


def bucket_from_columns(
    arity: int,
    rows: np.ndarray,
    tids: np.ndarray,
    ctype: np.ndarray,
    targets: np.ndarray,
    incoming_pairs: List[Tuple[np.ndarray, np.ndarray]],
) -> LinkBucket:
    """Build a LinkBucket straight from already-columnized arrays (the
    columnar ingest path, storage/columnar.py) — same probe-index
    semantics as build_bucket, no record objects."""
    for p in range(arity):
        mask = targets[:, p] >= 0
        if mask.all():
            incoming_pairs.append((targets[:, p], rows))
        else:
            incoming_pairs.append((targets[mask, p], rows[mask]))
    return _index_bucket(arity, rows, tids, ctype, targets)


def _index_bucket(arity, rows, tids, ctype, targets) -> LinkBucket:
    """The shared probe-index tail: argsort permutations + sorted keys."""
    targets_sorted = np.sort(targets, axis=1)

    order_by_type = np.argsort(tids, kind="stable")
    order_by_ctype = np.argsort(ctype, kind="stable")
    order_by_type_pos, key_type_pos = [], []
    order_by_pos, key_pos = [], []
    order_by_type_spos, key_type_spos = [], []
    for p in range(arity):
        k = _combine_type_pos(tids, targets[:, p])
        o = np.argsort(k, kind="stable")
        order_by_type_pos.append(o.astype(np.int32))
        key_type_pos.append(k[o])
        o2 = np.argsort(targets[:, p], kind="stable")
        order_by_pos.append(o2.astype(np.int32))
        key_pos.append(targets[:, p][o2])
        ks = _combine_type_pos(tids, targets_sorted[:, p])
        o3 = np.argsort(ks, kind="stable")
        order_by_type_spos.append(o3.astype(np.int32))
        key_type_spos.append(ks[o3])
    return LinkBucket(
        arity=arity,
        rows=rows,
        type_id=tids,
        ctype=ctype,
        targets=targets,
        targets_sorted=targets_sorted,
        order_by_type=order_by_type.astype(np.int32),
        key_type=tids[order_by_type],
        order_by_ctype=order_by_ctype.astype(np.int32),
        key_ctype=ctype[order_by_ctype],
        order_by_type_pos=order_by_type_pos,
        key_type_pos=key_type_pos,
        order_by_pos=order_by_pos,
        key_pos=key_pos,
        order_by_type_spos=order_by_type_spos,
        key_type_spos=key_type_spos,
    )


def host_segments(db, arity: int) -> List[LinkBucket]:
    """The backend's host-side column segments for one arity: base bucket
    plus incremental overlay segments when the backend provides them
    (IncrementalCommitMixin.host_bucket_segments), else the finalized
    bucket.  Their concatenation exactly mirrors the backend's merged
    device row space — shared by every host-side counting path
    (query/fused.py trivial_plan_count, query/starcount.py host fold)."""
    segments_of = getattr(db, "host_bucket_segments", None)
    if segments_of is not None:
        return segments_of(arity)
    b = db.fin.buckets.get(arity)
    return [b] if b is not None and b.size else []


def host_probe_locals(
    b: LinkBucket, type_id: int, fixed: Tuple[Tuple[int, int], ...]
) -> np.ndarray:
    """Bucket-local rows matching (type, grounded positions), probed on the
    host copies of the SAME sorted indexes the device kernels use: binary
    search the narrowest fixed position's (type<<32|target) range, then
    verify the remaining fixed positions with vectorized compares.  This is
    the one host-side probe algorithm — the fused single-term count and the
    star fold's sparse degree both call it, so probe semantics cannot
    diverge between editions."""
    best = None  # (range size, position, lo)
    for pos, val in fixed:
        key = (np.int64(type_id) << 32) | np.int64(val)
        keys = b.key_type_pos[pos]
        lo = int(np.searchsorted(keys, key, side="left"))
        hi = int(np.searchsorted(keys, key, side="right"))
        if best is None or hi - lo < best[0]:
            best = (hi - lo, pos, lo)
    n, pos, lo = best
    if n == 0:
        return np.empty(0, dtype=np.int32)
    local = b.order_by_type_pos[pos][lo : lo + n]
    ok = np.ones(n, dtype=bool)
    for q, v in fixed:
        if q != pos:
            ok &= b.targets[local, q] == v
    return local[ok]


class AtomSpaceData:
    """Mutable host store + derived columnar representation."""

    def __init__(self, symbol_table: Optional[SymbolTable] = None):
        self.table = symbol_table if symbol_table is not None else SymbolTable()
        self.nodes: Dict[str, NodeRec] = {}
        self.typedefs: Dict[str, TypedefRec] = {}
        self.links: Dict[str, LinkRec] = {}
        self._fin: Optional[Finalized] = None
        self.pattern_black_list: List[str] = []
        #: set by the columnar ingest path (storage/columnar.py
        #: attach_columnar): numpy-backed base records behind the lazy
        #: nodes/links views, with a vectorized finalize
        self.columnar = None

    # -- ingestion ---------------------------------------------------------

    def add_typedef(self, expr: Expression) -> None:
        if expr.hash_code in self.typedefs:
            return
        self.typedefs[expr.hash_code] = TypedefRec(
            name=expr.typedef_name,
            name_hash=expr.typedef_name_hash,
            composite_type_hash=expr.composite_type_hash,
            designator_name=self.table.named_types.get(expr.typedef_name, ""),
        )

    def add_terminal(self, expr: Expression) -> None:
        if expr.hash_code in self.nodes:
            return
        self.nodes[expr.hash_code] = NodeRec(
            name=expr.terminal_name,
            named_type=expr.named_type,
            named_type_hash=expr.named_type_hash,
        )
        self._fin = None

    def add_link(self, expr: Expression) -> None:
        if expr.hash_code in self.links:
            if expr.toplevel:
                set_top = getattr(self.links, "set_toplevel", None)
                if set_top is not None:
                    # columnar view: a reconstructed LinkRec is a copy, so
                    # the flag must be written through to the column
                    set_top(expr.hash_code)
                else:
                    self.links[expr.hash_code].is_toplevel = True
            return
        self.links[expr.hash_code] = LinkRec(
            named_type=expr.named_type,
            named_type_hash=expr.named_type_hash,
            composite_type=expr.composite_type,
            composite_type_hash=expr.composite_type_hash,
            elements=tuple(expr.elements),
            is_toplevel=expr.toplevel,
        )
        self._fin = None

    def add_expression(self, expr: Expression) -> None:
        if expr.is_typedef:
            self.add_typedef(expr)
        elif expr.is_terminal:
            self.add_terminal(expr)
        else:
            self.add_link(expr)

    # -- host-side incoming map (lazy, for miners / API) -------------------

    def incoming_of(self, handle: str) -> List[str]:
        fin = self.finalize()
        row = fin.row_of_hex.get(handle)
        if row is None:
            return []
        lo, hi = fin.incoming_offsets[row], fin.incoming_offsets[row + 1]
        return [fin.hex_of_row[r] for r in fin.incoming_links[lo:hi]]

    # -- finalization ------------------------------------------------------

    def finalize(self) -> Finalized:
        if self._fin is not None:
            return self._fin
        if self.columnar is not None:
            from das_tpu.storage.columnar import columnar_finalize

            self._fin = columnar_finalize(self)
            return self._fin

        node_hexes = list(self.nodes.keys())
        by_arity: Dict[int, List[Tuple[str, LinkRec]]] = {}
        for hex_handle, rec in self.links.items():
            by_arity.setdefault(len(rec.elements), []).append((hex_handle, rec))
        arities = sorted(by_arity)

        hex_of_row: List[str] = list(node_hexes)
        for arity in arities:
            hex_of_row.extend(h for h, _ in by_arity[arity])
        row_of_hex = {h: i for i, h in enumerate(hex_of_row)}
        atom_count = len(hex_of_row)
        node_count = len(node_hexes)

        # type registry
        type_names: List[str] = []
        type_id_of_hash: Dict[str, int] = {}

        def type_id(named_type_hash: str, named_type: str) -> int:
            tid = type_id_of_hash.get(named_type_hash)
            if tid is None:
                tid = len(type_names)
                type_id_of_hash[named_type_hash] = tid
                type_names.append(named_type)
            return tid

        node_type_id = np.empty(node_count, dtype=np.int32)
        for i, h in enumerate(node_hexes):
            rec = self.nodes[h]
            node_type_id[i] = type_id(rec.named_type_hash, rec.named_type)

        buckets: Dict[int, LinkBucket] = {}
        # (target_rows, link_rows) array chunks from each bucket build
        incoming_pairs: List[Tuple[np.ndarray, np.ndarray]] = []
        dangling: set = set()
        for arity in arities:
            buckets[arity] = build_bucket(
                arity, by_arity[arity], row_of_hex, type_id, incoming_pairs,
                dangling,
            )

        # incoming CSR
        trows = (
            np.concatenate([t for t, _ in incoming_pairs])
            if incoming_pairs else np.empty(0, dtype=np.int32)
        )
        lrows = (
            np.concatenate([l for _, l in incoming_pairs])
            if incoming_pairs else np.empty(0, dtype=np.int32)
        )
        incoming_offsets = np.zeros(atom_count + 1, dtype=np.int32)
        incoming_links = np.empty(trows.shape[0], dtype=np.int32)
        if trows.size:
            order = np.argsort(trows, kind="stable")
            incoming_links = lrows[order].copy()
            counts = np.bincount(trows, minlength=atom_count)
            incoming_offsets[1:] = np.cumsum(counts, dtype=np.int32)

        self._fin = Finalized(
            atom_count=atom_count,
            node_count=node_count,
            hex_of_row=hex_of_row,
            row_of_hex=row_of_hex,
            type_names=type_names,
            type_id_of_hash=type_id_of_hash,
            node_type_id=node_type_id,
            buckets=buckets,
            incoming_offsets=incoming_offsets,
            incoming_links=incoming_links,
            dangling_hexes=dangling,
            interned=[node_count, atom_count - node_count],
        )
        return self._fin

    # -- introspection -----------------------------------------------------

    def count_atoms(self) -> Tuple[int, int]:
        return (len(self.nodes), len(self.links))

    @property
    def named_type_hash_reverse(self) -> Dict[str, str]:
        return {v: k for k, v in self.table.named_type_hash.items()}


def load_metta_text(text: str, data: Optional[AtomSpaceData] = None) -> AtomSpaceData:
    """Parse MeTTa source straight into an AtomSpaceData."""
    from das_tpu.ingest.metta import MettaParser

    if data is None:
        data = AtomSpaceData()
    typedefs: List[Expression] = []
    terminals: List[Expression] = []
    regular: List[Expression] = []
    parser = MettaParser(
        symbol_table=data.table,
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_expression=regular.append,
        on_toplevel=regular.append,
    )
    parser.parse(text)
    # records may have been completed by the EOF fixpoint — route them now
    for expr in typedefs:
        data.add_typedef(expr)
    for expr in terminals:
        data.add_terminal(expr)
    for expr in regular:
        data.add_link(expr)
    return data


def load_metta_file(path: str, data: Optional[AtomSpaceData] = None) -> AtomSpaceData:
    with open(path, "r") as fh:
        return load_metta_text(fh.read(), data)
