"""Device-resident AtomSpace backend (the production TPU path).

Role of the reference RedisMongoDB (redis_mongo_db.py:49-335), re-designed
for HBM residency: at construction every finalized bucket (storage/
atom_table.py) is `device_put` to the target platform; wildcard-pattern,
type-template and type probes execute as jitted `searchsorted` range
kernels (das_tpu/ops/posting.py) with capacity-doubling retry; the host
only touches small result vectors for API materialization (hex handles).

Probe routing (host-side, static per query shape):
  * type + ≥1 grounded target  → exact (type<<32|target) key index
  * type only                  → type-sorted index
  * grounded target(s) only    → position-sorted index
  * nothing grounded           → full bucket scan (padded)
  * unordered link types       → union-over-sorted-positions probe +
                                 multiset verification (position-free)

The full DBInterface contract (including dict/deep representations) is
inherited from MemoryDB; only the probe surface is overridden to run on
device.  The compiled conjunctive path (query/compiler.py) reaches the
device arrays directly through `.dev`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from das_tpu.core.config import DasConfig
from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD
from das_tpu.ops import posting
from das_tpu.storage.atom_table import (
    AtomSpaceData,
    Finalized,
    LinkBucket,
)
from das_tpu.storage.delta import (
    FULL,
    NOOP,
    IncrementalCommitMixin,
    capacity_class,
    delta_class,
    merge_sorted_index,
)
from das_tpu.storage.memory_db import MemoryDB


@dataclass
class DeviceBucket:
    """Device arrays are CAPACITY-padded: length `capacity` >= `size` (real
    rows), with per-dtype sentinels in the slack (sorted keys pad with the
    dtype max so they sort last and no real probe key can hit them).
    Incremental commits scatter deltas into the slack with FIXED-shape
    programs, so neither the merge nor any compiled query executable
    recompiles per commit — shapes only change on rare capacity growth."""

    arity: int
    size: int        # real rows
    capacity: int    # array length
    rows: jax.Array
    type_id: jax.Array
    ctype: jax.Array
    targets: jax.Array
    targets_sorted: jax.Array
    order_by_type: jax.Array
    key_type: jax.Array
    order_by_ctype: jax.Array
    key_ctype: jax.Array
    order_by_type_pos: List[jax.Array]
    key_type_pos: List[jax.Array]
    order_by_pos: List[jax.Array]
    key_pos: List[jax.Array]
    order_by_type_spos: List[jax.Array]
    key_type_spos: List[jax.Array]


def _pad_rows(x: np.ndarray, capacity: int, fill) -> np.ndarray:
    n = x.shape[0]
    if n >= capacity:
        return x
    out = np.full((capacity, *x.shape[1:]), fill, dtype=x.dtype)
    out[:n] = x
    return out


def _key_pad(dtype) -> int:
    return np.iinfo(dtype).max


def upload_bucket(b: LinkBucket, device=None) -> DeviceBucket:
    """device_put every column/index of one finalized bucket, padded to
    its capacity class (see DeviceBucket)."""
    cap = capacity_class(b.size)
    put = lambda x, fill: jax.device_put(_pad_rows(x, cap, fill), device)
    return DeviceBucket(
        arity=b.arity,
        size=b.size,
        capacity=cap,
        rows=put(b.rows, -1),
        type_id=put(b.type_id, -1),
        ctype=put(b.ctype, _key_pad(np.int64)),
        targets=put(b.targets, -2),
        targets_sorted=put(b.targets_sorted, -2),
        order_by_type=put(b.order_by_type, 0),
        key_type=put(b.key_type, _key_pad(b.key_type.dtype)),
        order_by_ctype=put(b.order_by_ctype, 0),
        key_ctype=put(b.key_ctype, _key_pad(np.int64)),
        order_by_type_pos=[put(x, 0) for x in b.order_by_type_pos],
        key_type_pos=[put(x, _key_pad(np.int64)) for x in b.key_type_pos],
        order_by_pos=[put(x, 0) for x in b.order_by_pos],
        key_pos=[put(x, _key_pad(x.dtype)) for x in b.key_pos],
        order_by_type_spos=[put(x, 0) for x in b.order_by_type_spos],
        key_type_spos=[put(x, _key_pad(np.int64)) for x in b.key_type_spos],
    )


class DeviceTables:
    """All device-resident arrays for one AtomSpace."""

    def __init__(self, fin: Finalized, device=None):
        import das_tpu

        das_tpu.enable_compile_cache()
        put = lambda x: jax.device_put(x, device)
        self.node_type_id = put(fin.node_type_id)
        self.incoming_offsets = put(fin.incoming_offsets)
        self.incoming_links = put(fin.incoming_links)
        self.buckets: Dict[int, DeviceBucket] = {
            arity: upload_bucket(b, device) for arity, b in fin.buckets.items()
        }


# NOTE: deliberately NOT donating buffers in the commit kernels — a commit
# must be atomic.  A transient backend error (remote-compile tunnels drop
# large payloads occasionally) mid-way through the ~3*arity+2 merge calls
# would otherwise leave the live bucket referencing deleted buffers,
# bricking the store.  The transient cost is one extra copy of one array
# at a time.
@jax.jit
def _merge_padded(base_keys, base_perm, delta_keys, delta_perm):
    """Fixed-shape sorted-index merge into a capacity-padded base: delta
    pad entries (dtype-max keys) sort past the base's pad region and fall
    off the final slice, so the array length never changes.  Compiled once
    per (capacity, delta-class) shape — commits after the first reuse it."""
    cap = base_keys.shape[0]
    k, p = merge_sorted_index(base_keys, base_perm, delta_keys, delta_perm)
    return k[:cap], p[:cap]


@jax.jit
def _insert_rows(col, block, n):
    """Write a fixed-size delta block at (traced) row offset n — the
    column's shape is static, so this never recompiles per commit."""
    return jax.lax.dynamic_update_slice_in_dim(col, block, n, axis=0)


def _next_capacity(count: int, current: int, maximum: int) -> int:
    if count > maximum:
        from das_tpu.core.exceptions import CapacityOverflowError

        raise CapacityOverflowError(
            f"probe needs {count} rows > max_result_capacity {maximum}"
        )
    cap = max(current, 16)
    while cap < count:
        cap *= 2
    return min(cap, maximum)


class TensorDB(IncrementalCommitMixin, MemoryDB):
    # every scan-indexed get_matched_* is overridden with device probes
    # below, so MemoryDB.prefetch's handle lists are never read
    _needs_scan_indexes = False

    def __init__(self, data: Optional[AtomSpaceData] = None, config: Optional[DasConfig] = None, device=None):
        super().__init__(data)
        self.config = config or DasConfig()
        self._device = device
        self.fin: Finalized = self.data.finalize()
        self.dev = DeviceTables(self.fin, device=device)
        self._reset_delta_state()

    def __repr__(self):
        return "<TensorDB>"

    def refresh(self) -> None:
        """Re-sync the device store after host-side mutations (transaction
        commits).  Small deltas take the INCREMENTAL path: only the new
        records are columnized (a small delta bucket per arity), only those
        columns travel to the device, and each device-resident sorted probe
        index is extended by an O(n) two-sorted-array merge (merge-path
        positions from a handful of binary searches + one cumsum — no
        re-sort, no full re-upload).  The reference's update path is
        likewise incremental (das/das_update_test.py:141-192); a full
        re-finalize at millions of links costs minutes.  Deltas accumulate
        LSM-style; past config.delta_merge_threshold total new atoms the
        store is fully re-finalized and the overlay cleared.  The
        full-vs-delta decision and host-side interning are shared with the
        sharded backend (storage/delta.py).

        Every non-NOOP outcome advances `delta_version` (the mixin's
        commit counter): the incremental path bumps it in _apply_delta,
        and the FULL path replaces `self.dev` outright — which drops the
        cached fused executor AND its delta-version-guarded result cache
        (query/fused.py ResultCache), so no pre-commit answer can survive
        either route."""
        self.prefetch()
        action = self._plan_refresh()
        if action == NOOP:
            return
        if action == FULL:
            # WAL (ISSUE 15): a full rebuild consumes host mutations the
            # incremental log would otherwise miss — record the pending
            # tail (fsynced) BEFORE the rebuild becomes visible, same
            # version the _reset_delta_state bump will land on.  Replay
            # re-inserts the same atoms and lets ITS refresh pick
            # full-vs-incremental; content (and answers) are identical
            # either way.
            wal = self._wal
            if wal is not None:
                wal.append(self.data, self.delta_version + 1, kind="full")
            self.fin = self.data.finalize()
            self.dev = DeviceTables(self.fin, device=self._device)
            self._reset_delta_state()
            return
        self._commit_delta_with_retry(action)

    @classmethod
    def restore(cls, path: str, config: Optional[DasConfig] = None) -> "TensorDB":
        """Warm-state restore (ISSUE 15, storage/durable.py): newest
        VALID snapshot generation under `path` + WAL replay to head +
        warm bundle (CapStore capacities, planner degree statistics,
        count-cache entries) — the replica-fleet cold-start path.
        Commits on the restored store append to the generation's WAL."""
        from das_tpu.storage import durable

        return durable.restore(path, config=config, backend="tensor")

    # -- incremental delta machinery --------------------------------------
    # _apply_delta / _reset_delta_state / host_bucket_segments come from
    # IncrementalCommitMixin; the backend-specific part is the device merge:

    def _grow_bucket(self, base: DeviceBucket, new_cap: int) -> DeviceBucket:
        """Re-pad a bucket to a larger capacity class (rare: only when
        accumulated commits exhaust the ~6% slack).  Real rows — and real
        sorted keys/perms, which occupy the leading positions — are
        preserved; the new slack is sentinel-filled."""
        n = base.size

        def grow(arr, fill):
            pad = jnp.full(
                (new_cap - n, *arr.shape[1:]), fill, dtype=arr.dtype
            )
            return jnp.concatenate([arr[:n], pad], axis=0)

        kmax = lambda a: _key_pad(np.dtype(a.dtype))
        return DeviceBucket(
            arity=base.arity,
            size=n,
            capacity=new_cap,
            rows=grow(base.rows, -1),
            type_id=grow(base.type_id, -1),
            ctype=grow(base.ctype, kmax(base.ctype)),
            targets=grow(base.targets, -2),
            targets_sorted=grow(base.targets_sorted, -2),
            order_by_type=grow(base.order_by_type, 0),
            key_type=grow(base.key_type, kmax(base.key_type)),
            order_by_ctype=grow(base.order_by_ctype, 0),
            key_ctype=grow(base.key_ctype, kmax(base.key_ctype)),
            order_by_type_pos=[grow(x, 0) for x in base.order_by_type_pos],
            key_type_pos=[grow(x, kmax(x)) for x in base.key_type_pos],
            order_by_pos=[grow(x, 0) for x in base.order_by_pos],
            key_pos=[grow(x, kmax(x)) for x in base.key_pos],
            order_by_type_spos=[grow(x, 0) for x in base.order_by_type_spos],
            key_type_spos=[grow(x, kmax(x)) for x in base.key_type_spos],
        )

    def _stage_delta_merge(self, delta: LinkBucket):
        """COMPUTE a commit bucket's merge into the device tables and
        return (swap, became_base, slots): `swap` is the deferred pure
        assignment that makes the merged bucket visible (the
        stage-then-swap commit contract, storage/delta.py _apply_delta),
        became_base when the delta is the first bucket of its arity,
        slots = device rows occupied (flat layout — exactly the delta
        size).  Nothing here mutates `self.dev` — jax arrays are
        immutable, so a failure mid-compute leaves the pre-commit
        tables fully intact.

        Deltas land in the capacity slack with FIXED-shape programs
        (_merge_padded / _insert_rows): after the first commit in a
        capacity class, a commit is pure device work — no retrace, no
        recompile of the merge or of any cached query executable."""
        arity = delta.arity
        put = lambda x: jax.device_put(x, self._device)
        base = self.dev.buckets.get(arity)
        if base is None or base.size == 0:
            # first links of this arity: the delta IS the base
            merged = upload_bucket(delta, self._device)

            def swap():
                self.dev.buckets[arity] = merged

            return swap, True, delta.size
        n, d = base.size, delta.size
        dcap = delta_class(d)
        if n + dcap > base.capacity:
            base = self._grow_bucket(base, capacity_class(n + dcap))

        def dpad(x, fill):
            return put(_pad_rows(x, dcap, fill))

        n_dev = jnp.int32(n)

        def merge(bk, bo, dk, do):
            return _merge_padded(
                bk, bo,
                dpad(dk, _key_pad(dk.dtype)),
                dpad(do.astype(np.int32) + n, 0),
            )

        mt = [merge(base.key_type_pos[p], base.order_by_type_pos[p],
                    delta.key_type_pos[p], delta.order_by_type_pos[p])
              for p in range(arity)]
        mp = [merge(base.key_pos[p], base.order_by_pos[p],
                    delta.key_pos[p], delta.order_by_pos[p])
              for p in range(arity)]
        ms = [merge(base.key_type_spos[p], base.order_by_type_spos[p],
                    delta.key_type_spos[p], delta.order_by_type_spos[p])
              for p in range(arity)]
        kt, ot = merge(base.key_type, base.order_by_type,
                       delta.key_type, delta.order_by_type)
        kc, oc = merge(base.key_ctype, base.order_by_ctype,
                       delta.key_ctype, delta.order_by_ctype)
        ins = lambda col, block, fill: _insert_rows(
            col, dpad(block, fill), n_dev
        )
        merged = DeviceBucket(
            arity=arity,
            size=n + d,
            capacity=base.capacity,
            rows=ins(base.rows, delta.rows, -1),
            type_id=ins(base.type_id, delta.type_id, -1),
            ctype=ins(base.ctype, delta.ctype, _key_pad(np.int64)),
            targets=ins(base.targets, delta.targets, -2),
            targets_sorted=ins(base.targets_sorted, delta.targets_sorted, -2),
            order_by_type=ot,
            key_type=kt,
            order_by_ctype=oc,
            key_ctype=kc,
            order_by_type_pos=[o for _, o in mt],
            key_type_pos=[k for k, _ in mt],
            order_by_pos=[o for _, o in mp],
            key_pos=[k for k, _ in mp],
            order_by_type_spos=[o for _, o in ms],
            key_type_spos=[k for k, _ in ms],
        )

        def swap():
            self.dev.buckets[arity] = merged

        return swap, False, d

    # host_bucket_segments: backend-local base bucket + overlay segments —
    # provided by IncrementalCommitMixin (shared with the sharded backend)

    # -- low-level probes (shared with the query compiler) -----------------

    def _type_id(self, link_type: str) -> Optional[int]:
        h = self.data.table.get_named_type_hash(link_type)
        return self.fin.type_id_of_hash.get(h)

    def _row_of(self, handle_hex: str) -> Optional[int]:
        return self.fin.row_of_hex.get(handle_hex)

    def probe_ordered_padded(
        self,
        arity: int,
        type_id: Optional[int],
        fixed: Tuple[Tuple[int, int], ...],
    ):
        """Padded device probe with capacity retry: returns (local, mask)
        device arrays, or None when the bucket is empty."""
        db = self.dev.buckets.get(arity)
        if db is None or db.size == 0:
            return None
        cap = min(self.config.initial_result_capacity, max(db.size, 16))
        while True:
            local, mask, range_count = self._probe_ordered_padded(
                db, type_id, fixed, cap
            )
            # overflow is judged on the *range* count (the pre-verification
            # superset): candidates beyond `cap` were never verified
            if int(range_count) <= cap:
                return local, mask
            cap = _next_capacity(int(range_count), cap, self.config.max_result_capacity)

    def probe_ordered(
        self,
        arity: int,
        type_id: Optional[int],
        fixed: Tuple[Tuple[int, int], ...],
    ) -> np.ndarray:
        """Bucket-local rows matching a positional wildcard pattern.
        `fixed` = ((position, global_target_row), ...).  Returns int32[n]."""
        padded = self.probe_ordered_padded(arity, type_id, fixed)
        if padded is None:
            return np.empty(0, dtype=np.int32)
        local, mask = padded
        return np.asarray(local)[np.asarray(mask)]

    def _probe_ordered_padded(self, db: DeviceBucket, type_id, fixed, cap: int):
        """One padded probe round: returns (local, verified_mask, range_count)."""
        if type_id is not None and fixed:
            p0, v0 = fixed[0]
            key = (np.int64(type_id) << 32) | np.int64(v0)
            local, valid, range_count = posting.range_probe(
                db.key_type_pos[p0], db.order_by_type_pos[p0], key, cap
            )
            rest = tuple(fixed[1:])
            mask = posting.verify_positions(
                db.targets, db.type_id, local, valid, jnp.int32(-1), rest
            )
        elif type_id is not None:
            local, valid, range_count = posting.range_probe(
                db.key_type, db.order_by_type, np.int32(type_id), cap
            )
            mask = valid
        elif fixed:
            p0, v0 = fixed[0]
            local, valid, range_count = posting.range_probe(
                db.key_pos[p0], db.order_by_pos[p0], np.int32(v0), cap
            )
            rest = tuple(fixed[1:])
            mask = posting.verify_positions(
                db.targets, db.type_id, local, valid, jnp.int32(-1), rest
            )
        else:
            local, valid, range_count = posting.full_scan(np.int32(db.size), cap)
            mask = valid
        return local, mask, range_count

    def probe_unordered_padded(
        self,
        arity: int,
        type_id: Optional[int],
        required: Tuple[Tuple[int, int], ...],
    ):
        """Padded unordered (multiset) probe: returns (local, mask) device
        arrays, or None when the bucket is empty.  Candidates contain every
        required (global_row, count) with multiplicity, any position."""
        db = self.dev.buckets.get(arity)
        if db is None or db.size == 0:
            return None
        if not required:
            return self.probe_ordered_padded(arity, type_id, ())
        cap = min(self.config.initial_result_capacity, max(db.size * arity, 16))
        v0 = required[0][0]
        while True:
            locals_, valids, counts = [], [], []
            for p in range(arity):
                if type_id is not None:
                    key = (np.int64(type_id) << 32) | np.int64(v0)
                    local, valid, range_count = posting.range_probe(
                        db.key_type_spos[p], db.order_by_type_spos[p], key, cap
                    )
                else:
                    local, valid, range_count = posting.range_probe(
                        db.key_pos[p], db.order_by_pos[p], np.int32(v0), cap
                    )
                locals_.append(local)
                valids.append(valid)
                counts.append(range_count)
            max_range = max(int(c) for c in counts)
            if max_range > cap:
                cap = _next_capacity(max_range, cap, self.config.max_result_capacity)
                continue
            local = jnp.concatenate(locals_)
            valid = jnp.concatenate(valids)
            local, keep = posting.dedup_sorted(local, valid)
            mask = posting.verify_multiset(
                db.targets,
                db.type_id,
                local,
                keep,
                jnp.int32(-1 if type_id is None else type_id),
                tuple(required),
            )
            return local, mask

    def probe_unordered(
        self,
        arity: int,
        type_id: Optional[int],
        required: Tuple[Tuple[int, int], ...],
    ) -> np.ndarray:
        """Bucket-local rows containing every required (global_row, count)
        with multiplicity, irrespective of position."""
        padded = self.probe_unordered_padded(arity, type_id, required)
        if padded is None:
            return np.empty(0, dtype=np.int32)
        local, mask = padded
        return np.asarray(local)[np.asarray(mask)]

    def probe_ctype_padded(self, arity: int, ctype_i64: int):
        """Padded template-index probe for one arity bucket."""
        db = self.dev.buckets.get(arity)
        if db is None or db.size == 0:
            return None
        cap = min(self.config.initial_result_capacity, max(db.size, 16))
        while True:
            local, valid, count = posting.range_probe(
                db.key_ctype, db.order_by_ctype, np.int64(ctype_i64), cap
            )
            if int(count) <= cap:
                return local, valid
            cap = _next_capacity(int(count), cap, self.config.max_result_capacity)

    def probe_ctype(self, ctype_i64: int) -> Dict[int, np.ndarray]:
        """Rows per arity whose composite type hash matches (template index)."""
        out = {}
        for arity in self.dev.buckets:
            padded = self.probe_ctype_padded(arity, ctype_i64)
            if padded is None:
                continue
            local, valid = padded
            sel = np.asarray(local)[np.asarray(valid)]
            if sel.size:
                out[arity] = sel
        return out

    # -- materialization helpers ------------------------------------------

    def _materialize(self, arity: int, local_rows: np.ndarray):
        """Bucket-local rows -> (handle, target hexes); locals past the base
        bucket size index into the per-commit delta overlay segments."""
        segments = self.host_bucket_segments(arity)
        hexes = self.fin.hex_of_row
        out = []
        for i in local_rows:
            j = int(i)
            for b in segments:
                if j < b.size:
                    break
                j -= b.size
            row = int(b.rows[j])
            tg = tuple(
                hexes[int(t)] if int(t) >= 0 else WILDCARD
                for t in b.targets[j]
            )
            out.append((hexes[row], tg))
        return out

    # -- DBInterface probe overrides ---------------------------------------

    def get_matched_links(self, link_type: str, target_handles: List[str]):
        if link_type != WILDCARD and WILDCARD not in target_handles:
            handle = self.get_link_handle(link_type, target_handles)
            return [handle] if handle in self.data.links else []
        arity = len(target_handles)
        black_list = self.data.pattern_black_list
        if link_type == WILDCARD:
            type_id = None
        else:
            if link_type in black_list:
                return []  # no pattern index for blacklisted types
            type_id = self._type_id(link_type)
            if type_id is None:
                return []
        unordered = link_type in UNORDERED_LINK_TYPES and link_type != WILDCARD
        grounded: List[Tuple[int, int]] = []
        for p, h in enumerate(target_handles):
            if h == WILDCARD:
                continue
            row = self._row_of(h)
            if row is None:
                return []
            grounded.append((p, row))
        if unordered:
            counts: Dict[int, int] = {}
            for _, row in grounded:
                counts[row] = counts.get(row, 0) + 1
            local = self.probe_unordered(
                arity, type_id, tuple(sorted(counts.items()))
            )
        else:
            local = self.probe_ordered(arity, type_id, tuple(grounded))
        out = self._materialize(arity, local)
        if type_id is None and black_list:
            out = [
                (h, tg) for h, tg in out
                if self.data.links[h].named_type not in black_list
            ]
        return out

    def get_matched_type_template(self, template):
        hashed = self._hash_template(template)
        template_hash = self._flatten_template_hash(hashed)
        from das_tpu.core.hashing import hex_to_i64

        per_arity = self.probe_ctype(int(hex_to_i64(template_hash)))
        out = []
        for arity, local in sorted(per_arity.items()):
            out.extend(self._materialize(arity, local))
        return out

    def get_matched_type(self, link_type: str):
        type_id = self._type_id(link_type)
        if type_id is None:
            return []
        out = []
        for arity in sorted(self.dev.buckets):
            local = self.probe_ordered(arity, type_id, ())
            if local.size:
                out.extend(self._materialize(arity, local))
        return out

    # get_incoming: base CSR + delta overlay — provided by
    # IncrementalCommitMixin (shared with the sharded backend)
