"""AtomSpace checkpoint / resume.

The reference's only persistence is the external DBs plus ad-hoc
mongodump/canonical_load shell scripts and /tmp kv-file skip flags
(SURVEY.md §5 "Checkpoint / resume").  Here the checkpoint is first-class:

* ``records.msgpack`` — the mutable source of truth (`AtomSpaceData`
  node/typedef/link records + symbol table), sufficient to rebuild
  everything;
* ``indexes.npz`` — the finalized probe indexes (`Finalized` buckets +
  incoming CSR), saved so resume skips the argsort rebuild for large KBs.

`load()` verifies the npz against the records (atom counts) and silently
falls back to re-finalizing when absent or stale — a checkpoint is never
wrong, only possibly slower to open.  Backends re-upload to device on
construction, so a checkpoint is also the unit of host→device restore.

Durability (ISSUE 15, storage/durable.py): every write here flows
through `durable.atomic_write` (write-temp → fsync → rename; daslint
DL017 pins the discipline), and `load()` runs INTEGRITY verification
when the directory is a dasdur generation (a MANIFEST.json with
per-section CRC-32 digests is present — reads go through
`durable.verify_generation`, corrupt sections raise typed
`SnapshotCorruptError`).  A pre-dasdur checkpoint has no digests:
back-compat reads warn-and-accept ONCE (logged), and the manifest is
recorded on the next save — `load()` on a generation root picks the
newest VALID generation.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

import msgpack
import numpy as np

from das_tpu.ingest.metta import SymbolTable
from das_tpu.storage.atom_table import (
    AtomSpaceData,
    Finalized,
    LinkBucket,
    LinkRec,
    NodeRec,
    TypedefRec,
)

RECORDS_FILE = "records.msgpack"
INDEXES_FILE = "indexes.npz"
REGISTRY_FILE = "registry.msgpack"
FORMAT_VERSION = 1


def _records_payload(data: AtomSpaceData) -> Dict:
    t = data.table
    return {
        "version": FORMAT_VERSION,
        "nodes": {
            h: (r.name, r.named_type, r.named_type_hash)
            for h, r in data.nodes.items()
        },
        "typedefs": {
            h: (r.name, r.name_hash, r.composite_type_hash, r.designator_name)
            for h, r in data.typedefs.items()
        },
        "links": {
            h: (
                r.named_type,
                r.named_type_hash,
                r.composite_type,
                r.composite_type_hash,
                list(r.elements),
                r.is_toplevel,
            )
            for h, r in data.links.items()
        },
        "symbol_table": {
            "named_type_hash": t.named_type_hash,
            "named_types": t.named_types,
            "symbol_hash": t.symbol_hash,
            "terminal_hash": [[k[0], k[1], v] for k, v in t.terminal_hash.items()],
            "parent_type": t.parent_type,
        },
        "pattern_black_list": data.pattern_black_list,
    }


def _restore_records(payload: Dict) -> AtomSpaceData:
    if payload.get("version") != FORMAT_VERSION:
        raise ValueError(f"Unsupported checkpoint version: {payload.get('version')}")
    table = SymbolTable()
    st = payload["symbol_table"]
    table.named_type_hash.update(st["named_type_hash"])
    table.named_types.update(st["named_types"])
    table.symbol_hash.update(st["symbol_hash"])
    table.terminal_hash.update({(a, b): v for a, b, v in st["terminal_hash"]})
    table.parent_type.update(st["parent_type"])
    data = AtomSpaceData(table)
    for h, (name, named_type, nth) in payload["nodes"].items():
        data.nodes[h] = NodeRec(name, named_type, nth)
    for h, (name, nh, cth, desig) in payload["typedefs"].items():
        data.typedefs[h] = TypedefRec(name, nh, cth, desig)
    for h, (nt, nth, ct, cth, elements, top) in payload["links"].items():
        data.links[h] = LinkRec(nt, nth, ct, cth, tuple(elements), top)
    data.pattern_black_list = list(payload.get("pattern_black_list", []))
    return data


def _indexes_payload(fin: Finalized) -> Dict[str, np.ndarray]:
    arrays: Dict[str, np.ndarray] = {
        "node_type_id": fin.node_type_id,
        "incoming_offsets": fin.incoming_offsets,
        "incoming_links": fin.incoming_links,
        "arities": np.array(sorted(fin.buckets), dtype=np.int32),
        "atom_count": np.array([fin.atom_count], dtype=np.int64),
        "node_count": np.array([fin.node_count], dtype=np.int64),
    }
    for arity, b in fin.buckets.items():
        p = f"b{arity}_"
        arrays[p + "rows"] = b.rows
        arrays[p + "type_id"] = b.type_id
        arrays[p + "ctype"] = b.ctype
        arrays[p + "targets"] = b.targets
        arrays[p + "targets_sorted"] = b.targets_sorted
        arrays[p + "order_by_type"] = b.order_by_type
        arrays[p + "key_type"] = b.key_type
        arrays[p + "order_by_ctype"] = b.order_by_ctype
        arrays[p + "key_ctype"] = b.key_ctype
        for pos in range(arity):
            arrays[f"{p}order_by_type_pos{pos}"] = b.order_by_type_pos[pos]
            arrays[f"{p}key_type_pos{pos}"] = b.key_type_pos[pos]
            arrays[f"{p}order_by_pos{pos}"] = b.order_by_pos[pos]
            arrays[f"{p}key_pos{pos}"] = b.key_pos[pos]
            arrays[f"{p}order_by_type_spos{pos}"] = b.order_by_type_spos[pos]
            arrays[f"{p}key_type_spos{pos}"] = b.key_type_spos[pos]
    return arrays


def _restore_indexes(npz, registry: Dict, data: AtomSpaceData) -> Optional[Finalized]:
    """Rebuild a Finalized from saved arrays; None when stale."""
    atom_count = int(npz["atom_count"][0])
    node_count = int(npz["node_count"][0])
    if node_count != len(data.nodes) or atom_count != len(data.nodes) + len(data.links):
        return None  # stale — records changed since indexes were saved
    hex_of_row = registry["hex_of_row"]
    if len(hex_of_row) != atom_count:
        return None
    buckets: Dict[int, LinkBucket] = {}
    for arity in npz["arities"].tolist():
        p = f"b{arity}_"
        buckets[arity] = LinkBucket(
            arity=arity,
            rows=npz[p + "rows"],
            type_id=npz[p + "type_id"],
            ctype=npz[p + "ctype"],
            targets=npz[p + "targets"],
            targets_sorted=npz[p + "targets_sorted"],
            order_by_type=npz[p + "order_by_type"],
            key_type=npz[p + "key_type"],
            order_by_ctype=npz[p + "order_by_ctype"],
            key_ctype=npz[p + "key_ctype"],
            order_by_type_pos=[npz[f"{p}order_by_type_pos{i}"] for i in range(arity)],
            key_type_pos=[npz[f"{p}key_type_pos{i}"] for i in range(arity)],
            order_by_pos=[npz[f"{p}order_by_pos{i}"] for i in range(arity)],
            key_pos=[npz[f"{p}key_pos{i}"] for i in range(arity)],
            order_by_type_spos=[npz[f"{p}order_by_type_spos{i}"] for i in range(arity)],
            key_type_spos=[npz[f"{p}key_type_spos{i}"] for i in range(arity)],
        )
    # dangling element hexes are not persisted; if the restored store has
    # no sentinel targets the set is provably empty, otherwise None marks
    # it unknown (the incremental commit path then plays safe with a full
    # re-finalize on the first commit)
    has_sentinels = any(
        bool((b.targets < 0).any()) for b in buckets.values()
    )
    return Finalized(
        atom_count=atom_count,
        node_count=node_count,
        hex_of_row=hex_of_row,
        row_of_hex={h: i for i, h in enumerate(hex_of_row)},
        type_names=registry["type_names"],
        type_id_of_hash=registry["type_id_of_hash"],
        node_type_id=npz["node_type_id"],
        buckets=buckets,
        incoming_offsets=npz["incoming_offsets"],
        incoming_links=npz["incoming_links"],
        dangling_hexes=None if has_sentinels else set(),
    )


def _registry_payload(fin: Finalized) -> Dict:
    return {
        # list(): columnar stores serve hex_of_row lazily
        # (storage/columnar.py LazyHexRows)
        "hex_of_row": list(fin.hex_of_row),
        "type_names": fin.type_names,
        "type_id_of_hash": fin.type_id_of_hash,
    }


def _record_manifest(path: str, sections: Dict[str, Dict]) -> None:
    """Merge per-section digests into the dir's MANIFEST.json (created
    if absent) so the NEXT load verifies what this save wrote — the
    back-compat upgrade path for pre-dasdur checkpoints."""
    import json

    from das_tpu.storage import durable

    mpath = os.path.join(path, durable.MANIFEST_FILE)
    manifest = {
        "format": durable.MANIFEST_FORMAT,
        "generation": 0,
        "delta_version": 0,
        "sections": {},
    }
    if os.path.exists(mpath):
        try:
            manifest = durable.read_manifest(path)
        except Exception:  # noqa: BLE001 — a torn manifest is replaced
            pass
    manifest["sections"].update(sections)
    durable.atomic_write_bytes(
        mpath, json.dumps(manifest, sort_keys=True, indent=1).encode()
    )


def save(data: AtomSpaceData, path: str, with_indexes: bool = True) -> None:
    """Write a checkpoint directory — every file via the durable
    atomic-write helper (write-temp → fsync → rename, DL017): a crash
    mid-save leaves the previous file intact, never a torn hybrid.
    Per-section CRC-32 digests land in MANIFEST.json so load() can
    verify the bytes it reads."""
    from das_tpu.storage import durable

    os.makedirs(path, exist_ok=True)
    sections = {
        RECORDS_FILE: durable.atomic_write_bytes(
            os.path.join(path, RECORDS_FILE),
            msgpack.packb(_records_payload(data), use_bin_type=True),
        )
    }
    if with_indexes:
        fin = data.finalize()
        sections[INDEXES_FILE] = durable.atomic_write(
            os.path.join(path, INDEXES_FILE),
            lambda f: np.savez(f, **_indexes_payload(fin)),
        )
        sections[REGISTRY_FILE] = durable.atomic_write_bytes(
            os.path.join(path, REGISTRY_FILE),
            msgpack.packb(_registry_payload(fin), use_bin_type=True),
        )
    _record_manifest(path, sections)


SHARDED_FILE_FMT = "sharded_{}.npz"

#: slab field names saved per bucket (positional index families follow)
_SLAB_FIELDS = (
    "type_id", "ctype", "targets", "targets_sorted",
    "key_type", "order_by_type", "key_ctype", "order_by_ctype",
)
_SLAB_POS_FIELDS = (
    "key_type_pos", "order_by_type_pos", "key_pos", "order_by_pos",
)


def _content_sig(fin: Finalized) -> str:
    """Content fingerprint of the finalized store the slabs derive from:
    md5 over every bucket's defining columns.  Count-based staleness
    checks alone can be fooled by content changes that preserve counts
    (e.g. one renamed node); the sig cannot.  Deliberate cost: restore
    re-hashes the LIVE columns (~1-2s at 27.9M links) instead of trusting
    a saved-at-save-time sig — a saved sig only proves the npz matched
    the records file then, not that it matches the fin the caller is
    restoring onto now, and a wrong accept serves a superseded store."""
    import hashlib

    h = hashlib.md5()
    h.update(np.ascontiguousarray(fin.node_type_id).tobytes())
    for arity in sorted(fin.buckets):
        b = fin.buckets[arity]
        for arr in (b.rows, b.type_id, b.ctype, b.targets):
            h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _sharded_payload(db) -> Dict[str, np.ndarray]:
    """The per-shard slab arrays one `sharded_S.npz` section carries
    (shared by save_sharded and the dasdur generational snapshot,
    storage/durable.py write_snapshot)."""
    arrays: Dict[str, np.ndarray] = {
        "atom_count": np.array([db.fin.atom_count], dtype=np.int64),
        "node_count": np.array([db.fin.node_count], dtype=np.int64),
        "arities": np.array(sorted(db.tables.buckets), dtype=np.int32),
        "content_sig": np.frombuffer(
            bytes.fromhex(_content_sig(db.fin)), dtype=np.uint8
        ),
    }
    for arity, b in db.tables.buckets.items():
        p = f"b{arity}_"
        arrays[p + "meta"] = np.array([b.m_local, b.size], dtype=np.int64)
        arrays[p + "slab_sizes"] = b.slab_sizes
        for name in _SLAB_FIELDS:
            arrays[p + name] = np.asarray(getattr(b, name))
        for name in _SLAB_POS_FIELDS:
            cols = getattr(b, name)
            for pos in range(arity):
                arrays[f"{p}{name}{pos}"] = np.asarray(cols[pos])
    return arrays


def save_sharded(db, path: str) -> None:
    """Checkpoint a ShardedDB INCLUDING its shard-local slabs (VERDICT r03
    item 8): the standard records+indexes checkpoint plus one npz of the
    capacity-padded per-shard arrays and their slab-local sorted probe
    indexes.  Restore then device_puts the slabs directly — no host-global
    re-partition, no per-slab argsort rebuild."""
    from das_tpu.storage import durable

    save(db.data, path)
    arrays = _sharded_payload(db)
    name = SHARDED_FILE_FMT.format(db.tables.n_shards)
    digest = durable.atomic_write(
        os.path.join(path, name), lambda f: np.savez(f, **arrays)
    )
    _record_manifest(path, {name: digest})


def try_restore_sharded(path: str, fin: Finalized, mesh):
    """Shard-local restore: returns a ShardedTables built straight from the
    saved slabs, or None when no matching checkpoint exists (wrong mesh
    size, store changed since save) — the caller re-partitions then.  A
    sharded checkpoint is never wrong, only possibly absent/stale."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec

    from das_tpu.parallel.mesh import SHARD_AXIS
    from das_tpu.parallel.sharded_db import ShardedBucket, ShardedTables

    target = os.path.join(path, SHARDED_FILE_FMT.format(mesh.devices.size))
    if not os.path.exists(target):
        return None
    shard = NamedSharding(mesh, PartitionSpec(SHARD_AXIS))
    with np.load(target) as npz:
        if (
            int(npz["atom_count"][0]) != fin.atom_count
            or int(npz["node_count"][0]) != fin.node_count
        ):
            return None  # stale — records moved on without the slabs
        if (
            "content_sig" not in npz
            or npz["content_sig"].tobytes().hex() != _content_sig(fin)
        ):
            # counts alone can survive a content change (e.g. a renamed
            # node); the defining-column fingerprint cannot
            return None
        arities = npz["arities"].tolist()
        if sorted(arities) != sorted(fin.buckets):
            return None
        buckets = {}
        for arity in arities:
            p = f"b{arity}_"
            m_local, size = (int(x) for x in npz[p + "meta"])
            if size != fin.buckets[arity].size:
                return None
            put = lambda name: jax.device_put(npz[p + name], shard)
            buckets[arity] = ShardedBucket(
                arity=arity,
                n_shards=mesh.devices.size,
                m_local=m_local,
                size=size,
                slab_sizes=npz[p + "slab_sizes"].copy(),
                type_id=put("type_id"),
                ctype=put("ctype"),
                targets=put("targets"),
                targets_sorted=put("targets_sorted"),
                key_type=put("key_type"),
                order_by_type=put("order_by_type"),
                key_ctype=put("key_ctype"),
                order_by_ctype=put("order_by_ctype"),
                key_type_pos=[
                    jax.device_put(npz[f"{p}key_type_pos{i}"], shard)
                    for i in range(arity)
                ],
                order_by_type_pos=[
                    jax.device_put(npz[f"{p}order_by_type_pos{i}"], shard)
                    for i in range(arity)
                ],
                key_pos=[
                    jax.device_put(npz[f"{p}key_pos{i}"], shard)
                    for i in range(arity)
                ],
                order_by_pos=[
                    jax.device_put(npz[f"{p}order_by_pos{i}"], shard)
                    for i in range(arity)
                ],
            )
    return ShardedTables.from_buckets(buckets, mesh)


#: checkpoint dirs already warned about missing integrity digests —
#: the back-compat read is accepted ONCE per path per process, and the
#: next save records a manifest so later loads verify
_UNVERIFIED_WARNED = set()


def load(path: str, _verified: bool = False) -> AtomSpaceData:
    """Read a checkpoint; uses saved indexes when fresh, else re-finalizes.

    All reads go through the dasdur verification path (ISSUE 15):
      * a generational root (``gen-NNNNNN`` dirs, no top-level records
        file) loads the newest VALID generation — torn/corrupt ones are
        skipped with a typed warning;
      * a flat dir with a ``MANIFEST.json`` has every section CRC-checked
        (`SnapshotCorruptError` on mismatch — corruption is never
        silently served);
      * a pre-dasdur flat dir has no digests: warn-and-accept once, and
        the manifest is recorded on the next `save()`.
    `_verified` skips re-verification when the caller (durable.restore)
    already checked this exact directory."""
    from das_tpu.storage import durable
    from das_tpu.utils.logger import logger

    if not _verified:
        if not os.path.exists(os.path.join(path, RECORDS_FILE)):
            gens = durable.list_generations(path)
            if gens:
                data, manifest, gen_dir = durable.newest_valid_generation(
                    path
                )
                # the generation's WAL holds fsync-acknowledged commits
                # made AFTER the snapshot — a records-only read would
                # silently serve a stale store, so replay them at the
                # host-data level here (backends built from this data
                # finalize fresh anyway; durable.restore is the
                # delta_version-tracking spelling)
                records, _torn = durable.read_wal(
                    os.path.join(
                        gen_dir, manifest.get("wal", durable.WAL_FILE)
                    ),
                    truncate=False,
                )
                base_v = int(manifest.get("delta_version", 0))
                applied = 0
                seen_v = base_v
                for rec in records:
                    v = int(rec.get("v", 0))
                    if v <= seen_v:
                        continue  # pre-snapshot or a retried twin
                    durable._replay_record(data, rec)
                    seen_v = v
                    applied += 1
                if applied:
                    logger().info(
                        f"checkpoint {path!r}: replayed {applied} WAL "
                        f"commit(s) past generation "
                        f"{manifest.get('generation')}"
                    )
                return data
        if os.path.exists(os.path.join(path, durable.MANIFEST_FILE)):
            # flat checkpoint: absent optional sections (e.g. a deleted
            # indexes.npz) are the documented re-finalize slow path,
            # not corruption — only present bytes must match digests
            durable.verify_generation(path, missing_ok=True)
        elif path not in _UNVERIFIED_WARNED:
            _UNVERIFIED_WARNED.add(path)
            logger().warning(
                f"checkpoint {path!r} predates integrity digests "
                "(no MANIFEST.json): accepting unverified once; the next "
                "save records per-section CRCs"
            )
    with open(os.path.join(path, RECORDS_FILE), "rb") as f:
        data = _restore_records(
            msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
        )
    indexes = os.path.join(path, INDEXES_FILE)
    registry_path = os.path.join(path, REGISTRY_FILE)
    if os.path.exists(indexes) and os.path.exists(registry_path):
        with open(registry_path, "rb") as f:
            registry = msgpack.unpackb(f.read(), raw=False, strict_map_key=False)
        with np.load(indexes) as npz:
            fin = _restore_indexes(npz, registry, data)
        if fin is not None:
            data._fin = fin
    return data
