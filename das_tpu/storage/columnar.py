"""Columnar ingest core: numpy-backed AtomSpace with lazy record views.

Round-4 ingest redesign (VERDICT r03 weak #3).  The native scanner
(native/src/das_columnar.cc) parses canonical files chunk-parallel and
emits flat columns — type pool, node/link hash16 + type-id columns, a
flat resolved-element index array — with zero per-record Python work.
This module wraps those columns as the SAME `AtomSpaceData` surface the
dict-based loaders produce:

  * ``data.nodes`` / ``data.links`` become lazy dict views: ``in`` /
    ``get`` / ``[]`` probe the sorted digest columns with numpy
    searchsorted and reconstruct a NodeRec/LinkRec on demand; iteration
    yields hex handles computed from the binary digests.  Mutations
    (transaction commits) land in an insertion-ordered overlay dict, so
    the incremental-commit machinery (storage/delta.py) sees ordinary
    dict semantics.
  * ``finalize()`` takes a vectorized path (`columnar_finalize`): global
    row assignment, type-registry interning, bucket columnization and the
    incoming CSR are all bulk numpy ops over the columns — no
    per-record Python loop.  The resulting `Finalized` is
    order-identical and array-identical to the dict path's (asserted in
    tests/test_columnar.py), with `hex_of_row` / `row_of_hex` served
    lazily from the binary digests instead of 10^7 Python strings.

Documented divergence from the dict path: a link whose element never
resolves (dangling) reconstructs its `composite_type` entry for that
element as the element's own digest (the dict decoder records the
declared sub-type hash).  Dangling elements cannot occur in converter
output; probe semantics are unaffected (composite_type_hash is carried
verbatim).
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from das_tpu.core.hashing import EMPTY_I64, I64_PAD_MAX
from das_tpu.storage.atom_table import (
    AtomSpaceData,
    Finalized,
    LinkBucket,
    LinkRec,
    NodeRec,
    TypedefRec,
    bucket_from_columns,
)


def _be_i64(hash16: np.ndarray, offset: int = 0) -> np.ndarray:
    """Big-endian signed int64 from 8 bytes of an [n, 16] u8 digest array
    (columns offset..offset+8).  No sentinel remap — raw ordering key."""
    if hash16.size == 0:
        return np.empty(0, dtype=np.int64)
    return (
        np.ascontiguousarray(hash16[:, offset : offset + 8])
        .view(">i8")
        .reshape(-1)
        .astype(np.int64)
    )


def _le_i64(hash16: np.ndarray, offset: int = 0) -> np.ndarray:
    """LITTLE-endian int64 view of 8 digest bytes — copy-free on LE hosts,
    and explicitly '<i8' so the ordering agrees with _key_i64 on any
    platform (order differs from hex order, which the lookup structures
    never expose; _be_i64 stays for the device-handle path where
    bit-exactness with hex_to_i64 matters)."""
    if hash16.size == 0:
        return np.empty(0, dtype=np.int64)
    return (
        np.ascontiguousarray(hash16[:, offset : offset + 8])
        .view("<i8")
        .reshape(-1)
    )


def _key_i64(digest8: bytes) -> int:
    return int.from_bytes(digest8, "little", signed=True)


def hash16_to_i64(hash16: np.ndarray) -> np.ndarray:
    """Vectorized device-handle truncation from binary digests — bit-exact
    with core.hashing.hex_to_i64 (big-endian first 8 bytes + the two
    sentinel remaps)."""
    v = _be_i64(hash16)
    v[v == np.int64(EMPTY_I64)] += 1
    v[v == np.int64(I64_PAD_MAX)] -= 1
    return v


class _DigestIndex:
    """Sorted lookup over an [n, 16] u8 digest column: hex -> row index.

    Sorted by the first 8 digest bytes only, NATIVE endian (one int64
    view-copy + one argsort — a 2-key big-endian lexsort over 30M digests
    costs ~25s where this costs ~4s); the remaining 8 bytes disambiguate
    by scanning the equal-prefix run, whose expected length is
    1 + n²/2⁶⁵ ≈ 1 for any real store."""

    def __init__(self, hash16: np.ndarray):
        lo = _le_i64(hash16)
        self.hi = _le_i64(hash16, 8)
        self.perm = np.argsort(lo) if lo.size else np.empty(0, np.int64)
        self.lo_s = lo[self.perm]
        # `lo` itself is not retained: find() needs only the sorted copy,
        # the permutation, and the disambiguating half

    def find(self, hex_digest: str) -> int:
        """Row index of the digest, or -1."""
        try:
            b = bytes.fromhex(hex_digest)
        except ValueError:
            return -1
        if len(b) != 16 or self.lo_s.size == 0:
            return -1
        klo = _key_i64(b[:8])
        khi = _key_i64(b[8:])
        left = int(np.searchsorted(self.lo_s, klo, side="left"))
        right = int(np.searchsorted(self.lo_s, klo, side="right"))
        for pos in range(left, right):
            row = int(self.perm[pos])
            if self.hi[row] == khi:
                return row
        return -1


def _linear_find(hash16: np.ndarray, hex_digest: str) -> int:
    """Index-free lookup: one strided scan of the first-8-byte column
    (~10s of ms at 27.9M rows).  A handful of membership probes — a small
    transaction commit's `in` checks — must not pay the multi-second
    index build; heavy lookup traffic graduates to _DigestIndex."""
    try:
        b = bytes.fromhex(hex_digest)
    except ValueError:
        return -1
    if len(b) != 16 or hash16.shape[0] == 0:
        return -1
    key8 = np.frombuffer(b, dtype=np.uint8)
    cand = np.flatnonzero(
        (hash16[:, 0] == key8[0]) & (hash16[:, 1] == key8[1])
        & (hash16[:, 8] == key8[8])
    )
    for row in cand:
        if bytes(hash16[row]) == b:
            return int(row)
    return -1


class ColumnarCore:
    """The parsed columns plus lazy lookup/record reconstruction."""

    def __init__(
        self,
        type_names: List[str],
        type_hash16: np.ndarray,     # [T, 16] u8
        td_name_tid: np.ndarray,
        td_stype_tid: np.ndarray,
        td_ct: np.ndarray,           # [D, 16]
        td_hash: np.ndarray,         # [D, 16]
        node_hash: np.ndarray,       # [N, 16]
        node_tid: np.ndarray,        # [N] i32
        node_name_off: np.ndarray,   # [N+1] u64
        node_name_blob: bytes,
        link_hash: np.ndarray,       # [M, 16]
        link_tid: np.ndarray,        # [M] i32
        link_ct: np.ndarray,         # [M, 16]
        link_top: np.ndarray,        # [M] u8 (mutable)
        link_elem_off: np.ndarray,   # [M+1] u64
        link_elem: np.ndarray,       # [E] i32 (node i | n_nodes+link j | -1)
        dangling: List[str],
    ):
        self.type_names = type_names
        self.type_hash16 = type_hash16
        self.type_hash_hex = [
            type_hash16[i].tobytes().hex() for i in range(len(type_names))
        ]
        self.tid_of_name = {n: i for i, n in enumerate(type_names)}
        self.td_name_tid = td_name_tid
        self.td_stype_tid = td_stype_tid
        self.td_ct = td_ct
        self.td_hash = td_hash
        self.node_hash = node_hash
        self.node_tid = node_tid
        self.node_name_off = node_name_off
        self.node_name_blob = node_name_blob
        self.link_hash = link_hash
        self.link_tid = link_tid
        self.link_ct = link_ct
        self.link_top = link_top
        self.link_elem_off = link_elem_off
        self.link_elem = link_elem
        self.dangling = dangling
        # positions of -1 elements correspond 1:1 (in order) to `dangling`
        self._dangling_pos: Optional[Dict[int, str]] = None
        self._node_index: Optional[_DigestIndex] = None
        self._link_index: Optional[_DigestIndex] = None
        self._index_thread = None
        self._index_failed = False
        import threading

        self._index_build_lock = threading.Lock()

    # -- counts ------------------------------------------------------------

    @property
    def n_nodes(self) -> int:
        return int(self.node_tid.shape[0])

    @property
    def n_links(self) -> int:
        return int(self.link_tid.shape[0])

    # -- lookup ------------------------------------------------------------

    def _building(self) -> bool:
        t = self._index_thread
        return t is not None and t.is_alive()

    def node_index(self, hex_digest: str) -> int:
        if self._node_index is None:
            # first lookup kicks the BACKGROUND build (argsort releases the
            # GIL); this and the next few probes stay linear (~10s of ms
            # apiece) until it lands — nobody ever stalls on the ~4s
            # reference-scale argsort, and nobody pays linear scans forever
            # (a grounded query costs two lookups, so a query-only process
            # used to stay under any count threshold indefinitely)
            self.ensure_indexes()
            if self._node_index is None:  # in flight or failed: stay linear
                return _linear_find(self.node_hash, hex_digest)
        return self._node_index.find(hex_digest)

    def link_index(self, hex_digest: str) -> int:
        if self._link_index is None:
            self.ensure_indexes()
            if self._link_index is None:
                return _linear_find(self.link_hash, hex_digest)
        return self._link_index.find(hex_digest)

    def ensure_indexes(self, background: bool = True) -> None:
        """Build both digest indexes (the incremental-commit path calls
        this AFTER its first successful merge: the commit's own membership
        probes stay linear, every later commit and API lookup gets the
        sorted index at microseconds per probe).  Background by default —
        numpy's argsort releases the GIL and the process spends most of
        its time waiting on device round trips; lookups fall back to the
        linear scan while the build is in flight.  A failed build is
        logged once and not blindly retried (the store stays on linear
        scans — degraded, never wrong)."""
        with self._index_build_lock:
            if (
                (self._node_index is not None and self._link_index is not None)
                or self._building()
                or self._index_failed
            ):
                return

            def build():
                try:
                    ni = self._node_index or _DigestIndex(self.node_hash)
                    li = self._link_index or _DigestIndex(self.link_hash)
                    self._node_index, self._link_index = ni, li
                except Exception as exc:  # noqa: BLE001 — degrade, don't die
                    self._index_failed = True
                    from das_tpu.utils.logger import logger

                    logger().info(f"digest-index build failed: {exc!r}")

            if background:
                import threading

                self._index_thread = threading.Thread(target=build, daemon=True)
                self._index_thread.start()
            else:
                build()

    def wait_indexes(self) -> None:
        """Block until the digest indexes exist (or the build has failed
        for good): join an in-flight background build, else build here.
        For callers about to issue MANY probes — e.g. commit-path
        terminal resolution, where one blocking ~seconds argsort beats
        O(types x nodes) linear scans per unresolved terminal."""
        while True:
            t = self._index_thread
            if t is not None and t.is_alive():
                t.join()
            if self._index_failed or (
                self._node_index is not None and self._link_index is not None
            ):
                return
            # a build kicked between the read and the join would make a
            # bare synchronous call early-return on _building(); loop and
            # re-join until the indexes exist (or the build failed)
            self.ensure_indexes(background=False)

    def node_hex(self, i: int) -> str:
        return self.node_hash[i].tobytes().hex()

    def link_hex(self, j: int) -> str:
        return self.link_hash[j].tobytes().hex()

    # -- record reconstruction --------------------------------------------

    def node_name(self, i: int) -> str:
        o0, o1 = int(self.node_name_off[i]), int(self.node_name_off[i + 1])
        return self.node_name_blob[o0:o1].decode("utf-8")

    def node_rec(self, i: int) -> NodeRec:
        tid = int(self.node_tid[i])
        return NodeRec(
            name=self.node_name(i),
            named_type=self.type_names[tid],
            named_type_hash=self.type_hash_hex[tid],
        )

    def _elem_hex(self, flat_pos: int) -> str:
        e = int(self.link_elem[flat_pos])
        if e >= self.n_nodes:
            return self.link_hex(e - self.n_nodes)
        if e >= 0:
            return self.node_hex(e)
        if self._dangling_pos is None:
            pos = np.flatnonzero(self.link_elem == -1)
            self._dangling_pos = {
                int(p): h for p, h in zip(pos, self.dangling)
            }
        return self._dangling_pos[flat_pos]

    def _elem_composite_type(self, flat_pos: int):
        e = int(self.link_elem[flat_pos])
        if e >= self.n_nodes:
            return self.link_composite_type(e - self.n_nodes)
        if e >= 0:
            return self.type_hash_hex[int(self.node_tid[e])]
        return self._elem_hex(flat_pos)  # documented dangling divergence

    def link_composite_type(self, j: int) -> list:
        tid = int(self.link_tid[j])
        o0, o1 = int(self.link_elem_off[j]), int(self.link_elem_off[j + 1])
        out: list = [self.type_hash_hex[tid]]
        for p in range(o0, o1):
            out.append(self._elem_composite_type(p))
        return out

    def link_rec(self, j: int) -> LinkRec:
        tid = int(self.link_tid[j])
        o0, o1 = int(self.link_elem_off[j]), int(self.link_elem_off[j + 1])
        return LinkRec(
            named_type=self.type_names[tid],
            named_type_hash=self.type_hash_hex[tid],
            composite_type=self.link_composite_type(j),
            composite_type_hash=self.link_ct[j].tobytes().hex(),
            elements=tuple(self._elem_hex(p) for p in range(o0, o1)),
            is_toplevel=bool(self.link_top[j]),
        )


class _LazyRecDict:
    """Dict-like view: columnar base + insertion-ordered overlay.

    Supports exactly the operations the store's consumers use: len, in,
    get, [], []=, iteration (insertion order: base then overlay),
    reversed, keys/values/items.  Overlay shadows base on lookup (the
    add_* guards make base/overlay key collisions unreachable in
    practice)."""

    def __init__(self, core: ColumnarCore):
        self.core = core
        self.overlay: Dict[str, object] = {}

    # subclass hooks
    def _base_len(self) -> int:
        raise NotImplementedError

    def _base_find(self, key: str) -> int:
        raise NotImplementedError

    def _base_hex(self, i: int) -> str:
        raise NotImplementedError

    def _base_rec(self, i: int):
        raise NotImplementedError

    def __len__(self) -> int:
        return self._base_len() + len(self.overlay)

    def __contains__(self, key) -> bool:
        return key in self.overlay or self._base_find(key) >= 0

    def get(self, key, default=None):
        rec = self.overlay.get(key)
        if rec is not None:
            return rec
        i = self._base_find(key)
        return self._base_rec(i) if i >= 0 else default

    def __getitem__(self, key):
        rec = self.get(key)
        if rec is None:
            raise KeyError(key)
        return rec

    def __setitem__(self, key, value) -> None:
        self.overlay[key] = value

    def __iter__(self) -> Iterator[str]:
        for i in range(self._base_len()):
            yield self._base_hex(i)
        yield from self.overlay

    def __reversed__(self) -> Iterator[str]:
        yield from reversed(self.overlay)
        for i in range(self._base_len() - 1, -1, -1):
            yield self._base_hex(i)

    def keys(self):
        return iter(self)

    def values(self):
        for i in range(self._base_len()):
            yield self._base_rec(i)
        yield from self.overlay.values()

    def items(self):
        for i in range(self._base_len()):
            yield self._base_hex(i), self._base_rec(i)
        yield from self.overlay.items()


class LazyNodes(_LazyRecDict):
    def _base_len(self) -> int:
        return self.core.n_nodes

    def _base_find(self, key: str) -> int:
        return self.core.node_index(key)

    def _base_hex(self, i: int) -> str:
        return self.core.node_hex(i)

    def _base_rec(self, i: int) -> NodeRec:
        return self.core.node_rec(i)


class LazyLinks(_LazyRecDict):
    def _base_len(self) -> int:
        return self.core.n_links

    def _base_find(self, key: str) -> int:
        return self.core.link_index(key)

    def _base_hex(self, i: int) -> str:
        return self.core.link_hex(i)

    def _base_rec(self, i: int) -> LinkRec:
        return self.core.link_rec(i)

    def set_toplevel(self, key: str) -> None:
        """Persistently mark a link toplevel (add_link's re-add path; a
        reconstructed LinkRec is a copy, so attribute mutation on it would
        be lost)."""
        rec = self.overlay.get(key)
        if rec is not None:
            rec.is_toplevel = True
            return
        i = self.core.link_index(key)
        if i >= 0:
            self.core.link_top[i] = 1


# ---------------------------------------------------------------------------
# store construction
# ---------------------------------------------------------------------------


def attach_columnar(data: AtomSpaceData, core: ColumnarCore) -> AtomSpaceData:
    """Swap a (fresh) AtomSpaceData's record dicts for columnar views and
    populate its symbol table from the type pool + typedef columns."""
    if data.nodes or data.links or data.typedefs:
        raise ValueError("columnar attach requires an empty store")
    data.columnar = core
    data.nodes = LazyNodes(core)
    data.links = LazyLinks(core)
    # typedefs are few (one per declared type): materialize a real dict
    typedefs: Dict[str, TypedefRec] = {}
    t = data.table
    for name, h in zip(core.type_names, core.type_hash_hex):
        t.named_type_hash.setdefault(name, h)
    for k in range(core.td_name_tid.shape[0]):
        ntid = int(core.td_name_tid[k])
        stid = int(core.td_stype_tid[k])
        name = core.type_names[ntid]
        stype = core.type_names[stid]
        h = core.td_hash[k].tobytes().hex()
        t.named_types[name] = stype
        t.parent_type[core.type_hash_hex[ntid]] = core.type_hash_hex[stid]
        t.symbol_hash[name] = h
        if h not in typedefs:
            typedefs[h] = TypedefRec(
                name=name,
                name_hash=core.type_hash_hex[ntid],
                composite_type_hash=core.td_ct[k].tobytes().hex(),
                designator_name=stype,
            )
    data.typedefs = typedefs

    def resolve_terminal(name: str):
        """Terminal name -> type name by probing the node digest index
        across the (small) type pool — the columnar stand-in for the
        parser-populated `named_types` entries the dict path accumulates
        (one membership probe per type, microseconds once the digest
        index is built).  A name declared under SEVERAL types takes the
        type of the LATEST node row: node insertion order follows
        declaration order, so this reproduces the dict path's
        last-declaration-wins `named_types` overwrite.  Known tolerance:
        an A,B,A re-declaration SEQUENCE of the same (type, name) pair
        dedups to its first row here (the dict path would end on A) —
        converter output declares each terminal once, so the sequence
        cannot occur there."""
        from das_tpu.core.hashing import ExpressionHasher

        # one probe per type name: amortize the blocking index build up
        # front rather than risk O(types x nodes) linear scans when the
        # background build has not landed yet (ADVICE r4)
        core.wait_indexes()
        best = None  # (node row, type name)
        for tname in core.type_names:
            h = ExpressionHasher.terminal_hash(tname, name)
            row = core.node_index(h)
            if row >= 0 and (best is None or row > best[0]):
                best = (row, tname)
        return best[1] if best is not None else None

    t.terminal_resolver = resolve_terminal
    data._fin = None
    return data


# ---------------------------------------------------------------------------
# lazy row registries
# ---------------------------------------------------------------------------


class LazyHexRows:
    """`Finalized.hex_of_row` served from an [N, 16] digest array, with a
    plain-list tail for delta-appended atoms."""

    def __init__(self, hash_by_row: np.ndarray):
        self._base = hash_by_row
        self._tail: List[str] = []

    def __len__(self) -> int:
        return self._base.shape[0] + len(self._tail)

    def __getitem__(self, i: int) -> str:
        i = int(i)
        n = self._base.shape[0]
        if i < 0:
            i += len(self)
        if 0 <= i < n:
            return self._base[i].tobytes().hex()
        return self._tail[i - n]

    def append(self, hex_digest: str) -> None:
        self._tail.append(hex_digest)

    def __iter__(self) -> Iterator[str]:
        for i in range(self._base.shape[0]):
            yield self._base[i].tobytes().hex()
        yield from self._tail


class LazyRowOfHex:
    """`Finalized.row_of_hex` over the same digest array: numpy probe for
    base rows, overlay dict for delta-appended atoms.  The sort index is
    built in the BACKGROUND starting at the first lookup, not at finalize
    time: the first few probes pay a strided linear scan (~10s of ms at
    reference scale) while one daemon thread runs the ~4s argsort (GIL
    released), after which every probe is microseconds.  Nobody ever
    stalls on the build, and nobody pays linear scans forever — a
    query-only process (two grounded-node lookups per query) previously
    stayed under the old count threshold indefinitely, putting two
    ~250 ms scans inside every sequential query at 27.9M links."""

    def __init__(self, hash_by_row: np.ndarray):
        import threading

        self._hash_by_row = hash_by_row
        self._index: Optional[_DigestIndex] = None
        self._index_lock = threading.Lock()
        self._index_thread = None
        self._tail: Dict[str, int] = {}

    def prefetch(self) -> None:
        """Start the background index build now (idempotent).  Called at
        the end of columnar_finalize so the argsort overlaps device upload
        and the very first grounded query already probes in microseconds."""
        with self._index_lock:
            if self._index is None and self._index_thread is None:

                def build():
                    # attribute write is atomic; a failure leaves the
                    # thread object in place so we never respawn —
                    # degraded to linear scans, never wrong
                    try:
                        self._index = _DigestIndex(self._hash_by_row)
                    except Exception as exc:  # noqa: BLE001 — degrade
                        from das_tpu.utils.logger import logger

                        logger().info(f"row-index build failed: {exc!r}")

                import threading

                self._index_thread = threading.Thread(target=build, daemon=True)
                self._index_thread.start()

    def get(self, key, default=None):
        row = self._tail.get(key)
        if row is not None:
            return row
        idx = self._index
        if idx is None:
            self.prefetch()
            idx = self._index
        if idx is None:  # build in flight (or failed): linear fallback
            i = _linear_find(self._hash_by_row, key)
            return i if i >= 0 else default
        i = idx.find(key)
        return i if i >= 0 else default

    def __getitem__(self, key) -> int:
        row = self.get(key)
        if row is None:
            raise KeyError(key)
        return row

    def __setitem__(self, key, row: int) -> None:
        self._tail[key] = int(row)

    def __contains__(self, key) -> bool:
        return self.get(key) is not None


# ---------------------------------------------------------------------------
# vectorized finalize
# ---------------------------------------------------------------------------


def columnar_finalize(data: AtomSpaceData) -> Finalized:
    """`AtomSpaceData.finalize()` over a columnar core: identical output
    (row order, type-registry order, bucket arrays) to the dict path, all
    bulk numpy.  Overlay records (post-load commits that triggered a FULL
    rebuild) are appended per the dict path's insertion-order semantics."""
    import os as _os
    import sys as _sys
    import time as _time

    _verbose = _os.environ.get("DAS_TPU_FINALIZE_VERBOSE")
    _t = [_time.time()]

    def _lap(what):
        if not _verbose:
            return
        now = _time.time()
        print(f"[finalize] {what}: {now - _t[0]:.1f}s", file=_sys.stderr, flush=True)
        _t[0] = now

    core: ColumnarCore = data.columnar
    nodes_overlay: Dict[str, NodeRec] = data.nodes.overlay
    links_overlay: Dict[str, LinkRec] = data.links.overlay
    n_base = core.n_nodes
    m_base = core.n_links
    node_count = n_base + len(nodes_overlay)

    # ---- link grouping: arity -> (base selection, overlay entries) -------
    ne = np.diff(core.link_elem_off).astype(np.int64)
    base_arities = sorted(int(a) for a in np.unique(ne)) if m_base else []
    over_by_arity: Dict[int, List[Tuple[str, LinkRec]]] = {}
    for h, rec in links_overlay.items():
        over_by_arity.setdefault(len(rec.elements), []).append((h, rec))
    arities = sorted(set(base_arities) | set(over_by_arity))

    sel_of: Dict[int, np.ndarray] = {
        a: np.flatnonzero(ne == a) for a in base_arities
    }

    # ---- global row assignment -------------------------------------------
    # rows: base nodes, overlay nodes, then per arity (base links in file
    # order, overlay links in insertion order) — matching dict finalize's
    # insertion-ordered dicts exactly
    link_row_of_storage = np.full(m_base, -1, dtype=np.int64)
    row = node_count
    bucket_row0: Dict[int, int] = {}
    for a in arities:
        bucket_row0[a] = row
        sel = sel_of.get(a)
        nb = int(sel.shape[0]) if sel is not None else 0
        if nb:
            link_row_of_storage[sel] = row + np.arange(nb, dtype=np.int64)
        row += nb + len(over_by_arity.get(a, ()))
    atom_count = row

    # storage index -> global row (elements encode node i | n_base + link j)
    row_of_storage = np.concatenate([
        np.arange(n_base, dtype=np.int64),
        link_row_of_storage,
    ]) if (n_base + m_base) else np.empty(0, dtype=np.int64)

    # ---- registry: hex_of_row / row_of_hex -------------------------------
    pieces = [core.node_hash]
    if nodes_overlay:
        pieces.append(_hexes_to_bin(list(nodes_overlay.keys())))
    for a in arities:
        sel = sel_of.get(a)
        if sel is not None and sel.size:
            pieces.append(core.link_hash[sel])
        over = over_by_arity.get(a)
        if over:
            pieces.append(_hexes_to_bin([h for h, _ in over]))
    hash_by_row = (
        np.concatenate(pieces, axis=0)
        if pieces else np.empty((0, 16), dtype=np.uint8)
    )
    _lap('rows+registry-pieces')
    hex_of_row = LazyHexRows(hash_by_row)
    row_of_hex = LazyRowOfHex(hash_by_row)
    _lap('digest-index')

    # ---- type registry (dict-path first-use order) -----------------------
    type_names: List[str] = []
    type_id_of_hash: Dict[str, int] = {}
    new_of_pool = np.full(len(core.type_names), -1, dtype=np.int64)

    def intern_pool_first_use(tids: np.ndarray) -> None:
        if tids.size == 0:
            return
        uniq, first = np.unique(tids, return_index=True)
        for t in uniq[np.argsort(first)]:
            t = int(t)
            if new_of_pool[t] < 0:
                new_of_pool[t] = len(type_names)
                type_id_of_hash[core.type_hash_hex[t]] = len(type_names)
                type_names.append(core.type_names[t])

    def intern_hash(named_type_hash: str, named_type: str) -> int:
        tid = type_id_of_hash.get(named_type_hash)
        if tid is None:
            tid = len(type_names)
            type_id_of_hash[named_type_hash] = tid
            type_names.append(named_type)
        return tid

    _lap('type-registry-prep')
    intern_pool_first_use(core.node_tid)
    node_type_id = np.empty(node_count, dtype=np.int32)
    node_type_id[:n_base] = new_of_pool[core.node_tid]
    for k, rec in enumerate(nodes_overlay.values()):
        node_type_id[n_base + k] = intern_hash(rec.named_type_hash, rec.named_type)

    # ---- buckets ---------------------------------------------------------
    buckets: Dict[int, LinkBucket] = {}
    incoming_pairs: List[Tuple[np.ndarray, np.ndarray]] = []
    dangling: set = set(core.dangling)

    # resolve any dangling element that an overlay commit has since
    # supplied (dict finalize resolves at finalize time)
    elem = core.link_elem
    dangling_patch: Dict[int, int] = {}
    if core.dangling and (nodes_overlay or links_overlay):
        positions = np.flatnonzero(elem == -1)
        for p, h in zip(positions, core.dangling):
            r = row_of_hex.get(h)
            if r is not None:
                dangling_patch[int(p)] = int(r)
                dangling.discard(h)
    ct_i64_all = hash16_to_i64(core.link_ct) if m_base else np.empty(0, np.int64)
    _lap('node-types+ct')

    for a in arities:
        sel = sel_of.get(a, np.empty(0, dtype=np.int64))
        nb = int(sel.shape[0])
        over = over_by_arity.get(a, [])
        m = nb + len(over)
        intern_pool_first_use(core.link_tid[sel])
        tids = np.empty(m, dtype=np.int32)
        tids[:nb] = new_of_pool[core.link_tid[sel]]
        ctype = np.empty(m, dtype=np.int64)
        ctype[:nb] = ct_i64_all[sel]
        rows = np.empty(m, dtype=np.int32)
        rows[:nb] = np.arange(bucket_row0[a], bucket_row0[a] + nb, dtype=np.int32)
        targets = np.empty((m, a), dtype=np.int32)
        if nb:
            flat = (
                core.link_elem_off[sel][:, None] + np.arange(a, dtype=np.int64)
            ).reshape(-1)
            e = elem[flat].astype(np.int64)
            t = np.where(e >= 0, row_of_storage[np.clip(e, 0, None)], -1)
            if dangling_patch:
                for p, r in dangling_patch.items():
                    hit = np.flatnonzero(flat == p)
                    if hit.size:
                        t[hit] = r
            targets[:nb] = t.reshape(nb, a).astype(np.int32)
        if over:
            from das_tpu.core.hashing import hex_to_i64

            for k, (h, rec) in enumerate(over):
                i = nb + k
                tids[i] = intern_hash(rec.named_type_hash, rec.named_type)
                ctype[i] = hex_to_i64(rec.composite_type_hash)
                rows[i] = bucket_row0[a] + i
                for p, eh in enumerate(rec.elements):
                    r = row_of_hex.get(eh)
                    if r is None:
                        dangling.add(eh)
                        r = -1
                    targets[i, p] = r
        buckets[a] = bucket_from_columns(
            a, rows, tids, ctype, targets, incoming_pairs
        )

    _lap('buckets')
    # ---- incoming CSR ----------------------------------------------------
    trows = (
        np.concatenate([t for t, _ in incoming_pairs])
        if incoming_pairs else np.empty(0, dtype=np.int32)
    )
    lrows = (
        np.concatenate([l for _, l in incoming_pairs])
        if incoming_pairs else np.empty(0, dtype=np.int32)
    )
    incoming_offsets = np.zeros(atom_count + 1, dtype=np.int32)
    incoming_links = np.empty(trows.shape[0], dtype=np.int32)
    if trows.size:
        order = np.argsort(trows, kind="stable")
        incoming_links = lrows[order].copy()
        counts = np.bincount(trows, minlength=atom_count)
        incoming_offsets[1:] = np.cumsum(counts, dtype=np.int32)

    _lap('incoming-csr')
    # background index kicks: the row-index argsort and the node/link
    # digest indexes (commit-path membership probes) overlap the device
    # upload that follows finalize — by the first grounded query or the
    # first transaction commit they have long landed
    row_of_hex.prefetch()
    core.ensure_indexes()
    return Finalized(
        atom_count=atom_count,
        node_count=node_count,
        hex_of_row=hex_of_row,
        row_of_hex=row_of_hex,
        type_names=type_names,
        type_id_of_hash=type_id_of_hash,
        node_type_id=node_type_id,
        buckets=buckets,
        incoming_offsets=incoming_offsets,
        incoming_links=incoming_links,
        dangling_hexes=dangling,
        interned=[node_count, atom_count - node_count],
    )


def _hexes_to_bin(hexes: List[str]) -> np.ndarray:
    out = np.empty((len(hexes), 16), dtype=np.uint8)
    for i, h in enumerate(hexes):
        out[i] = np.frombuffer(bytes.fromhex(h), dtype=np.uint8)
    return out
