"""In-memory DBInterface backend over `AtomSpaceData`.

This is simultaneously (a) the hardware-free test backend (role of the
reference StubDB, /root/reference/das/database/stub_db.py:20-188) and (b) a
complete, correct production backend for small/medium KBs (role of
RedisMongoDB, /root/reference/das/database/redis_mongo_db.py:49-335) — same
md5 handles, same answer sets.

Two deliberate semantic consolidations vs. the reference pair (which
disagree with each other):

* Unordered (Set/Similarity) wildcard probes use *multiset containment
  with multiplicity*: a link matches iff every grounded probe target is
  present among the link's targets often enough.  The reference production
  path approximates this through probe-target sorting against a
  materialized key fan-out (redis_mongo_db.py:249-251) — identical answers
  whenever the KB stores the symmetric closure (as its sample/bench KBs
  do) — while its StubDB used membership without multiplicity, which
  crashes `Link._assign_variables` on duplicate grounded targets.
* Wildcard probes work at every arity.  The reference only materializes
  pattern keys for arity ≤ 3 (parser_threads.py:186-219), silently
  returning [] above; computed probes have no such cliff.  (The latent
  blacklist bug noted in SURVEY.md §7 — stale `keys` reuse — does not
  exist here because nothing is materialized.)
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Tuple, Union

from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.schema import UNORDERED_LINK_TYPES, WILDCARD
from das_tpu.storage.atom_table import AtomSpaceData, LinkRec
from das_tpu.storage.interface import DBInterface


class MemoryDB(DBInterface):
    #: subclasses that override every scan-based get_matched_* method with
    #: device probes (TensorDB) set this False so prefetch() skips building
    #: the handle scan lists — at columnar-ingest scale those lists would
    #: reconstruct tens of millions of records for indexes never read
    _needs_scan_indexes = True

    def __init__(self, data: Optional[AtomSpaceData] = None):
        self.data = data if data is not None else AtomSpaceData()
        self._by_type: Dict[str, List[str]] = {}
        self._by_ctype: Dict[str, List[str]] = {}
        self._by_arity: Dict[int, List[str]] = {}
        self._indexed_links = -1
        self.prefetch()

    def __repr__(self):
        return "<MemoryDB>"

    # -- index maintenance -------------------------------------------------

    def prefetch(self) -> None:
        """(Re)build type/template scan lists — the analogue of the
        reference's full-DB prefetch (redis_mongo_db.py:89-127).  Links are
        append-only (records are never removed outside clear_database,
        which replaces the whole AtomSpaceData), so an incremental pass
        over just the new tail keeps transaction commits O(delta)."""
        n = len(self.data.links)
        if self._indexed_links == n:
            return
        if not self._needs_scan_indexes:
            self._indexed_links = n
            return
        if self._indexed_links < 0 or self._indexed_links > n:
            self._by_type = {}
            self._by_ctype = {}
            self._by_arity = {}
            self._indexed_links = 0
        from itertools import islice

        new_handles = list(
            islice(reversed(self.data.links), n - self._indexed_links)
        )[::-1]
        for handle in new_handles:
            rec = self.data.links[handle]
            self._by_type.setdefault(rec.named_type_hash, []).append(handle)
            self._by_ctype.setdefault(rec.composite_type_hash, []).append(handle)
            self._by_arity.setdefault(len(rec.elements), []).append(handle)
        self._indexed_links = n

    def _type_hash(self, atom_type: str) -> str:
        return self.data.table.get_named_type_hash(atom_type)

    # -- DBInterface -------------------------------------------------------

    def node_exists(self, node_type: str, node_name: str) -> bool:
        return ExpressionHasher.terminal_hash(node_type, node_name) in self.data.nodes

    def link_exists(self, link_type: str, target_handles: List[str]) -> bool:
        handle = ExpressionHasher.expression_hash(
            self._type_hash(link_type), list(target_handles)
        )
        return handle in self.data.links

    def get_node_handle(self, node_type: str, node_name: str) -> str:
        return ExpressionHasher.terminal_hash(node_type, node_name)

    def get_link_handle(self, link_type: str, target_handles: List[str]) -> str:
        return ExpressionHasher.expression_hash(
            self._type_hash(link_type), list(target_handles)
        )

    def get_link_targets(self, link_handle: str) -> List[str]:
        rec = self.data.links.get(link_handle)
        if rec is None:
            raise ValueError(f"Invalid handle: {link_handle}")
        return list(rec.elements)

    def is_ordered(self, link_handle: str) -> bool:
        if link_handle not in self.data.links:
            raise ValueError(f"Invalid handle: {link_handle}")
        return True

    def _match_rec(
        self, rec: LinkRec, target_handles: List[str], unordered: bool
    ) -> bool:
        if unordered:
            remaining = list(rec.elements)
            for target in target_handles:
                if target == WILDCARD:
                    continue
                if target in remaining:
                    remaining.remove(target)
                else:
                    return False
            return True
        return all(
            probe == WILDCARD or probe == element
            for probe, element in zip(target_handles, rec.elements)
        )

    def get_matched_links(self, link_type: str, target_handles: List[str]):
        self.prefetch()
        if link_type != WILDCARD and WILDCARD not in target_handles:
            handle = self.get_link_handle(link_type, target_handles)
            return [handle] if handle in self.data.links else []
        # pattern_black_list: the reference never emits `patterns:` index
        # keys for blacklisted link types (parser_threads.py:41, 185), so
        # wildcard probes cannot see those links; grounded lookups and
        # template probes are unaffected.
        if link_type == WILDCARD:
            candidates = self._by_arity.get(len(target_handles), [])
            unordered = False
            # typed candidates are pre-vetted; only the type-wildcard scan
            # needs the per-record check (set: O(1) per candidate)
            black_list = set(self.data.pattern_black_list)
        else:
            if link_type in self.data.pattern_black_list:
                return []
            candidates = self._by_type.get(self._type_hash(link_type), [])
            unordered = link_type in UNORDERED_LINK_TYPES
            black_list = set()
        arity = len(target_handles)
        answer = []
        for handle in candidates:
            rec = self.data.links[handle]
            if len(rec.elements) != arity:
                continue
            if black_list and rec.named_type in black_list:
                continue
            if self._match_rec(rec, target_handles, unordered):
                answer.append((handle, tuple(rec.elements)))
        return answer

    def get_all_nodes(self, node_type: str, names: bool = False) -> List[str]:
        type_hash = self._type_hash(node_type)
        core = self.data.columnar
        if core is not None:
            # vectorized base scan + overlay filter (the lazy-view
            # iteration would reconstruct every record)
            import numpy as np

            tid = core.tid_of_name.get(node_type)
            sel = (
                np.flatnonzero(core.node_tid == tid)
                if tid is not None else np.empty(0, dtype=np.int64)
            )
            if names:
                out = [core.node_name(int(i)) for i in sel]
                out.extend(
                    rec.name
                    for rec in self.data.nodes.overlay.values()
                    if rec.named_type_hash == type_hash
                )
            else:
                out = [core.node_hex(int(i)) for i in sel]
                out.extend(
                    handle
                    for handle, rec in self.data.nodes.overlay.items()
                    if rec.named_type_hash == type_hash
                )
            return out
        if names:
            return [
                rec.name
                for rec in self.data.nodes.values()
                if rec.named_type_hash == type_hash
            ]
        return [
            handle
            for handle, rec in self.data.nodes.items()
            if rec.named_type_hash == type_hash
        ]

    def _hash_template(self, template: Union[str, List[Any]]):
        if isinstance(template, str):
            return self._type_hash(template)
        return [self._hash_template(el) for el in template]

    def _flatten_template_hash(self, hashed) -> str:
        if isinstance(hashed, str):
            return hashed
        return ExpressionHasher.composite_hash(
            [self._flatten_template_hash(el) for el in hashed]
        )

    def get_matched_type_template(self, template: List[Any]) -> List[Any]:
        self.prefetch()
        hashed = self._hash_template(template)
        template_hash = self._flatten_template_hash(hashed)
        return [
            (handle, tuple(self.data.links[handle].elements))
            for handle in self._by_ctype.get(template_hash, [])
        ]

    def get_matched_type(self, link_type: str) -> List[Any]:
        self.prefetch()
        return [
            (handle, tuple(self.data.links[handle].elements))
            for handle in self._by_type.get(self._type_hash(link_type), [])
        ]

    def get_node_name(self, node_handle: str) -> str:
        rec = self.data.nodes.get(node_handle)
        if rec is None:
            raise ValueError(f"Invalid handle: {node_handle}")
        return rec.name

    def get_matched_node_name(self, node_type: str, substring: str) -> List[str]:
        type_hash = self._type_hash(node_type)
        pattern = re.compile(substring)
        return [
            handle
            for handle, rec in self.data.nodes.items()
            if rec.named_type_hash == type_hash and pattern.search(rec.name)
        ]

    # -- optional surface --------------------------------------------------

    def _named_type_template(self, template) -> Any:
        reverse = self.data.named_type_hash_reverse
        if isinstance(template, str):
            return reverse.get(template)
        return [self._named_type_template(el) for el in template]

    def get_atom_as_dict(self, handle: str, arity: int = -1) -> dict:
        node = self.data.nodes.get(handle) if arity <= 0 else None
        if node is not None:
            return {"handle": handle, "type": node.named_type, "name": node.name}
        rec = self.data.links.get(handle)
        if rec is None:
            node = self.data.nodes.get(handle)
            if node is not None:
                return {"handle": handle, "type": node.named_type, "name": node.name}
            return {}
        return {
            "handle": handle,
            "type": rec.named_type,
            "template": self._named_type_template(rec.composite_type),
            "targets": list(rec.elements),
        }

    def get_atom_as_deep_representation(self, handle: str, arity: int = -1):
        node = self.data.nodes.get(handle)
        if node is not None:
            return {"type": node.named_type, "name": node.name}
        rec = self.data.links.get(handle)
        if rec is None:
            raise ValueError(f"Invalid handle: {handle}")
        return {
            "type": rec.named_type,
            "targets": [
                self.get_atom_as_deep_representation(t) for t in rec.elements
            ],
        }

    def count_atoms(self) -> Tuple[int, int]:
        return self.data.count_atoms()

    # convenience used by API layer / miners
    def get_link_type(self, link_handle: str) -> str:
        rec = self.data.links.get(link_handle)
        if rec is None:
            raise ValueError(f"Invalid handle: {link_handle}")
        return rec.named_type

    def get_node_type(self, node_handle: str) -> str:
        rec = self.data.nodes.get(node_handle)
        if rec is None:
            raise ValueError(f"Invalid handle: {node_handle}")
        return rec.named_type

    def get_incoming(self, handle: str) -> List[str]:
        return self.data.incoming_of(handle)
