"""dasdur — crash-consistent snapshots, checksummed write-ahead delta
log, verified warm-state restore (ISSUE 15 tentpole).

The ROADMAP's replica-fleet item needs "persist the warm state a
replica should inherit instead of recompute": a fresh process pays
minutes (FlyBase: 178 s build + 76 s finalize + XLA compiles) before
its first answer.  Before this module, `storage/checkpoint.py` wrote
snapshots with bare `open()`/`np.savez` (a crash mid-save corrupts the
only copy), verified nothing on load, and lost every commit made after
the snapshot.  This module is the durability substrate both backends
ride:

  * **Atomic generational snapshots** — `write_snapshot(db, root)`
    writes every section (records / indexes / registry / sharded slabs
    / warm bundle) write-temp -> fsync -> rename via `atomic_write`,
    into a `gen-NNNNNN` directory whose `MANIFEST.json` carries
    per-section CRC-32 digests, the backend's `delta_version`, the
    existing `_content_sig`, and the persistent-XLA-cache dir (so
    dasprof's `cold_start_s` measures the restore win end-to-end).
    The generation directory itself lands by one final fsync + rename,
    so a crash at ANY point leaves either the complete new generation
    or the untouched prior one — never a torn hybrid.  `restore()`
    verifies every section against the manifest, rejects torn/corrupt
    generations with typed `SnapshotCorruptError`, and falls back to
    the newest valid prior generation.

  * **Write-ahead delta log** — `DeltaLog.append` runs inside
    `IncrementalCommitMixin._apply_delta`'s stage-then-swap, AFTER
    staging and BEFORE the swap: a checksummed, length-prefixed
    msgpack record of the interned delta (atoms + the symbol-table
    tail) is fsynced before anything becomes visible.  `restore(root)`
    = newest valid snapshot + WAL replay to head, each replayed commit
    re-verified against `delta_version` continuity; a torn tail record
    (crash mid-append) is truncated safely, never replayed.

  * **Warm-state bundle** — CapStore learned capacities, planner
    degree statistics and count-cache entries persist beside the
    snapshot keyed by `delta_version` (query/fused.py
    export_warm_state / apply_warm_state); a stale bundle — the WAL
    replayed commits past the snapshot — is discarded on the existing
    delta_version guard, exactly like a result-cache entry.

Every new I/O path registers in FAULT_SITES (`snapshot_write`,
`snapshot_rename`, `wal_append`, `wal_fsync`, `restore_read`) and the
chaos-parity contract extends to it: inject a crash at any site,
recover, and query answers are bit-identical (tests/test_zdur.py).

Durability discipline is lint-enforced (daslint DL017): inside the
declared `PERSIST_SCOPES`, every byte written flows through the
`PERSIST_SITES` functions below (no bare `open(..., "w")` /
`np.savez(path)`), and any function that renames a file into place
provably fsyncs first.

Layout under the snapshot root (env DAS_TPU_SNAPSHOT_DIR):

    root/
      gen-000001/
        MANIFEST.json      format, generation, delta_version,
                           content_sig, sections {name: bytes, crc32},
                           wal, warm delta_version, xla_cache_dir
        records.msgpack    host records (checkpoint.py payload)
        indexes.npz        finalized probe indexes
        registry.msgpack   hex_of_row / type registry
        sharded_S.npz      (sharded backend) per-shard slabs
        warm.msgpack       warm-state bundle
        wal.log            commits SINCE this generation
      gen-000002/ ...      newer generations; DAS_TPU_SNAPSHOT_KEEP
                           bounds how many survive pruning
"""

from __future__ import annotations

import io
import json
import os
import struct
import time
import zlib
from itertools import islice
from typing import Callable, Dict, List, Optional, Tuple

import msgpack

from das_tpu.core.exceptions import SnapshotCorruptError

MANIFEST_FILE = "MANIFEST.json"
WAL_FILE = "wal.log"
WARM_FILE = "warm.msgpack"
SHARDED_PREFIX = "sharded_"
GEN_PREFIX = "gen-"
MANIFEST_FORMAT = 1

#: WAL record framing: "<III" = magic, payload length, payload CRC-32.
WAL_MAGIC = 0x5744_414C  # "WDAL"
_WAL_HEADER = struct.Struct("<III")

#: modules under durability discipline (daslint DL017): every write
#: beneath the snapshot/WAL root in these files must flow through the
#: PERSIST_SITES functions — a bare `open(..., "w")`/`np.savez(path)`
#: fails lint.  Matched by path suffix.
PERSIST_SCOPES = (
    "das_tpu/storage/durable.py",
    "das_tpu/storage/checkpoint.py",
    "das_tpu/service/seed_checkpoint.py",
)

#: the CLOSED set of functions allowed to open persist files for
#: writing (the FAULT_SITES/FETCH_SITES idiom applied to durability).
#: `atomic_write` is the write-temp -> fsync -> rename helper every
#: snapshot section and checkpoint file rides; `DeltaLog.append` is
#: the WAL's append-fsync path; `_truncate_wal` cuts a torn tail.
#: daslint DL017 pins this both ways: an undeclared write-open in a
#: persist scope fires, and a declared site with no write is stale.
PERSIST_SITES = (
    "atomic_write",
    "DeltaLog.append",
    "_truncate_wal",
    "_publish_generation",
)

#: process-wide durability telemetry (the FETCH_COUNTS idiom: plain
#: ints under the GIL, torn reads tolerated) — surfaced via
#: `coalescer_stats()["durability"]` and the Prometheus gauges
#: (service/server.py metrics_text).
DUR_STATS: Dict[str, object] = {
    "generation": 0,          # newest generation written/restored
    "snapshots": 0,           # write_snapshot completions this process
    "wal_records": 0,         # WAL records appended this process
    "recovery_replayed": 0,   # WAL records replayed by restore()
    "torn_tail_truncations": 0,
    "corrupt_generations": 0,  # generations rejected by verification
    "last_restore_s": None,   # wall seconds of the last restore()
}


def snapshot_stats() -> Dict[str, object]:
    """Copy of DUR_STATS for the service stats surface."""
    return dict(DUR_STATS)


def reset_stats() -> None:
    """Zero the counters (bench/test arms start from a clean window)."""
    DUR_STATS.update(
        generation=0, snapshots=0, wal_records=0, recovery_replayed=0,
        torn_tail_truncations=0, corrupt_generations=0, last_restore_s=None,
    )


# -- atomic write ------------------------------------------------------------


class _CrcWriter:
    """File wrapper tallying CRC-32 + byte count of everything written,
    so `atomic_write` returns the manifest digest without re-reading
    the file it just wrote."""

    __slots__ = ("f", "crc", "nbytes")

    def __init__(self, f):
        self.f = f
        self.crc = 0
        self.nbytes = 0

    def write(self, b):
        self.crc = zlib.crc32(b, self.crc)
        self.nbytes += len(b)
        return self.f.write(b)

    # np.savez wraps the target in a ZipFile; raising here makes
    # zipfile take its UNSEEKABLE-stream write path (every byte flows
    # through write(), so the running CRC sees the whole file) and
    # `read` merely needs to EXIST for numpy to accept a file object
    def read(self, *a):
        raise io.UnsupportedOperation("persist writers are write-only")

    def tell(self):
        raise io.UnsupportedOperation(
            "persist writers are append-only (CRC is a running digest)"
        )

    def seek(self, *a):
        raise io.UnsupportedOperation(
            "persist writers are append-only (CRC is a running digest)"
        )

    def flush(self):
        self.f.flush()

    @property
    def mode(self):
        return self.f.mode

    def fileno(self):
        return self.f.fileno()

    def seekable(self):
        return False

    def readable(self):
        return False

    def writable(self):
        return True


def _fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry survives power loss —
    the half of atomic-rename durability `os.replace` alone skips."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return  # platform without directory fds — best effort
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def atomic_write(path: str, writer: Callable) -> Dict[str, int]:
    """THE durable write path (DL017 `PERSIST_SITES`): stream
    `writer(fileobj)` into a temp file, flush + fsync, rename into
    place, fsync the parent directory.  A crash at any point leaves
    either the complete new file or the untouched old one.  Returns
    the manifest digest `{"bytes": n, "crc32": crc}` of what was
    written.  Fault seams: `snapshot_write` before any byte lands,
    `snapshot_rename` between fsync and the rename — the two torn
    states the chaos suite proves recoverable."""
    from das_tpu import fault

    fault.maybe_fail("snapshot_write")
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            cw = _CrcWriter(f)
            writer(cw)
            f.flush()
            os.fsync(f.fileno())
        fault.maybe_fail("snapshot_rename")
        os.replace(tmp, path)
    except BaseException:
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise
    _fsync_dir(os.path.dirname(path) or ".")
    return {"bytes": cw.nbytes, "crc32": cw.crc}


def atomic_write_bytes(path: str, data: bytes) -> Dict[str, int]:
    return atomic_write(path, lambda f: f.write(data))


def _publish_generation(tmp_dir: str, gen_dir: str, root: str) -> None:
    """Make a fully-written generation visible (DL017 `PERSIST_SITES`):
    fsync the temp directory (its entries are already individually
    fsynced by `atomic_write`), rename it into place, fsync the root.
    Until the rename lands, restore sees only prior generations; after
    it, the complete new one."""
    from das_tpu import fault

    fd = os.open(tmp_dir, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    fault.maybe_fail("snapshot_rename")
    os.replace(tmp_dir, gen_dir)
    _fsync_dir(root)


# -- write-ahead delta log ---------------------------------------------------

#: AtomSpaceData record dicts a WAL record captures the tail of
_DATA_DICTS = ("nodes", "links", "typedefs")
#: SymbolTable dicts captured alongside (a replayed replica must
#: resolve handles and parse follow-on transactions exactly like the
#: writer did)
_SYMBOL_DICTS = (
    "named_type_hash", "named_types", "symbol_hash", "terminal_hash",
    "parent_type",
)


def _data_sizes(data) -> Dict[str, int]:
    sizes = {k: len(getattr(data, k)) for k in _DATA_DICTS}
    for k in _SYMBOL_DICTS:
        sizes[k] = len(getattr(data.table, k))
    return sizes


def _dict_tail(d, prev: int) -> List:
    """Keys inserted after position `prev` of an insertion-ordered dict
    (the storage/delta.py `islice(reversed(...))` idiom)."""
    n = len(d) - prev
    if n <= 0:
        return []
    return list(islice(reversed(d), n))[::-1]


class DeltaLog:
    """Append-only checksummed log of incremental commits, one file per
    generation (`gen-NNNNNN/wal.log`).

    Each record frames a msgpack payload with `WAL_MAGIC`, its length
    and its CRC-32: a crash mid-append leaves a torn TAIL that
    `read_wal` detects and `_truncate_wal` cuts — the valid prefix
    replays, the torn bytes never do.  The payload carries the commit's
    post-apply `delta_version` plus the insertion-ordered TAIL of every
    record/symbol dict since the previous append, so replay re-inserts
    atoms in the writer's exact order (bit-identical row interning).

    Appends happen inside `_apply_delta` AFTER staging and BEFORE the
    swap (storage/delta.py): logged-but-not-swapped and
    swapped-and-logged are both consistent outcomes — replay applies
    the record either way, and a retried commit's duplicate record is
    deduplicated by its `delta_version` at replay.  With no WAL
    configured the mixin's `_wal` stays None and `_apply_delta` is
    byte-for-byte the pre-dasdur path (the disabled-path identity pin,
    tests/test_zdur.py)."""

    __slots__ = ("path", "_sizes")

    def __init__(self, path: str, data):
        self.path = path
        self._sizes = _data_sizes(data)

    def _capture(self, data) -> Tuple[Dict, Dict[str, int]]:
        """(payload fragment, new sizes) for everything inserted since
        the last append — pure read, sizes commit only after the
        record is durable."""
        sizes = _data_sizes(data)
        nodes = [
            [h, r.name, r.named_type, r.named_type_hash]
            for h, r in (
                (h, data.nodes[h])
                for h in _dict_tail(data.nodes, self._sizes["nodes"])
            )
        ]
        links = [
            [h, r.named_type, r.named_type_hash, r.composite_type,
             r.composite_type_hash, list(r.elements), r.is_toplevel]
            for h, r in (
                (h, data.links[h])
                for h in _dict_tail(data.links, self._sizes["links"])
            )
        ]
        typedefs = [
            [h, r.name, r.name_hash, r.composite_type_hash,
             r.designator_name]
            for h, r in (
                (h, data.typedefs[h])
                for h in _dict_tail(data.typedefs, self._sizes["typedefs"])
            )
        ]
        t = data.table
        symbols = {}
        for k in _SYMBOL_DICTS:
            d = getattr(t, k)
            tail = _dict_tail(d, self._sizes[k])
            if k == "terminal_hash":  # keys are (type, name) tuples
                symbols[k] = [[a, b, d[(a, b)]] for a, b in tail]
            else:
                symbols[k] = [[key, d[key]] for key in tail]
        return (
            {"nodes": nodes, "links": links, "typedefs": typedefs,
             "symbols": symbols},
            sizes,
        )

    def append(self, data, version: int, kind: str = "delta") -> None:
        """Frame + append + fsync one commit record.  Fault seams:
        `wal_append` before any byte is framed (a failed append leaves
        the file untouched), `wal_fsync` after the write and before
        the fsync (the record may or may not be durable — replay
        deduplicates the retry's twin by delta_version)."""
        from das_tpu import fault, obs

        fault.maybe_fail("wal_append")
        fragment, sizes = self._capture(data)
        fragment["v"] = int(version)
        fragment["kind"] = kind
        payload = msgpack.packb(fragment, use_bin_type=True)
        rec = _WAL_HEADER.pack(
            WAL_MAGIC, len(payload), zlib.crc32(payload)
        ) + payload
        with open(self.path, "ab") as f:
            f.write(rec)
            f.flush()
            fault.maybe_fail("wal_fsync")
            os.fsync(f.fileno())
        self._sizes = sizes
        DUR_STATS["wal_records"] = int(DUR_STATS["wal_records"]) + 1
        if obs.enabled():
            obs.event("dur.wal_append", version=version, kind=kind,
                      bytes=len(rec))
            obs.counter("dur.wal_records").inc()


def _truncate_wal(path: str, offset: int) -> None:
    """Cut a torn tail record at the last valid frame boundary (DL017
    `PERSIST_SITES`: the only in-place mutation of a persist file) and
    fsync, so the next append starts from a clean frame."""
    from das_tpu import obs

    with open(path, "r+b") as f:
        f.truncate(offset)
        f.flush()
        os.fsync(f.fileno())
    DUR_STATS["torn_tail_truncations"] = (
        int(DUR_STATS["torn_tail_truncations"]) + 1
    )
    if obs.enabled():
        obs.event("dur.wal_truncate", offset=offset)


def read_wal(path: str, truncate: bool = True) -> Tuple[List[Dict], bool]:
    """Parse a WAL into (records, torn): every frame is re-verified
    (magic, length, CRC).  A torn TAIL — the frame extends past EOF,
    i.e. the crash-mid-append case — is truncated in place when
    `truncate`, so it can never replay; `torn` reports the cut.
    MID-FILE corruption (a fully-present frame failing its CRC, or a
    bad magic with further bytes behind it) is categorically different:
    frames AFTER it were fsync-acknowledged commits, so silently
    truncating would destroy durable data — it raises typed
    `SnapshotCorruptError` instead and touches nothing.  Fault seam:
    `restore_read` (the read half of the chaos matrix)."""
    from das_tpu import fault

    if not os.path.exists(path):
        return [], False
    fault.maybe_fail("restore_read")
    with open(path, "rb") as f:
        buf = f.read()
    records: List[Dict] = []
    off = 0
    torn = False
    while off < len(buf):
        if len(buf) - off < _WAL_HEADER.size:
            torn = True  # header itself ran past EOF: torn append
            break
        magic, ln, crc = _WAL_HEADER.unpack_from(buf, off)
        payload = buf[off + _WAL_HEADER.size: off + _WAL_HEADER.size + ln]
        if magic == WAL_MAGIC and len(payload) < ln:
            torn = True  # framed length runs past EOF: torn append
            break
        if magic != WAL_MAGIC or zlib.crc32(payload) != crc:
            raise SnapshotCorruptError(
                f"WAL {path} corrupt at offset {off}: "
                f"{'bad magic' if magic != WAL_MAGIC else 'CRC mismatch'}"
                " on a fully-present frame — fsynced records may follow,"
                " refusing to truncate"
            )
        records.append(
            msgpack.unpackb(payload, raw=False, strict_map_key=False)
        )
        off += _WAL_HEADER.size + ln
    if torn and truncate:
        _truncate_wal(path, off)
    return records, torn


def _replay_record(data, rec: Dict) -> None:
    """Re-insert one WAL record's atoms + symbol-table tail into a host
    store, in the writer's exact insertion order (row interning — and
    with it positional answers — depends on it)."""
    from das_tpu.storage.atom_table import LinkRec, NodeRec, TypedefRec

    t = data.table
    for k in _SYMBOL_DICTS:
        d = getattr(t, k)
        for entry in rec["symbols"].get(k, ()):
            if k == "terminal_hash":
                a, b, v = entry
                d[(a, b)] = v
            else:
                key, v = entry
                d[key] = v
    for h, name, nh, cth, desig in rec.get("typedefs", ()):
        if h not in data.typedefs:
            data.typedefs[h] = TypedefRec(name, nh, cth, desig)
    for h, name, nt, nth in rec.get("nodes", ()):
        if h not in data.nodes:
            data.nodes[h] = NodeRec(name, nt, nth)
    for h, nt, nth, ct, cth, elements, top in rec.get("links", ()):
        if h not in data.links:
            data.links[h] = LinkRec(nt, nth, ct, cth, tuple(elements), top)
    data._fin = None


# -- generations -------------------------------------------------------------


def _gen_name(n: int) -> str:
    return f"{GEN_PREFIX}{n:06d}"


def list_generations(root: str) -> List[Tuple[int, str]]:
    """(number, absolute dir) of every COMPLETED generation, ascending.
    A generation is completed iff its directory was renamed into place
    (temp dirs carry a leading dot and never match)."""
    if not os.path.isdir(root):
        return []
    out = []
    for name in os.listdir(root):
        if not name.startswith(GEN_PREFIX):
            continue
        try:
            n = int(name[len(GEN_PREFIX):])
        except ValueError:
            continue
        out.append((n, os.path.join(root, name)))
    out.sort()
    return out


def _verified_bytes(path: str, meta: Dict) -> bytes:
    """Read one manifest section and verify byte count + CRC-32; a
    mismatch is a typed corruption, never a silently-served file."""
    from das_tpu import fault

    fault.maybe_fail("restore_read")
    with open(path, "rb") as f:
        b = f.read()
    if len(b) != int(meta["bytes"]) or zlib.crc32(b) != int(meta["crc32"]):
        raise SnapshotCorruptError(
            f"section {os.path.basename(path)} failed verification: "
            f"{len(b)} bytes / crc {zlib.crc32(b):#x} vs manifest "
            f"{meta['bytes']} / {int(meta['crc32']):#x}"
        )
    return b


def read_manifest(gen_dir: str) -> Dict:
    mpath = os.path.join(gen_dir, MANIFEST_FILE)
    if not os.path.exists(mpath):
        raise SnapshotCorruptError(f"{gen_dir}: no manifest (torn write)")
    try:
        with open(mpath, "rb") as f:
            manifest = json.loads(f.read().decode())
    except (ValueError, OSError) as exc:
        raise SnapshotCorruptError(f"{gen_dir}: unreadable manifest: {exc}")
    if manifest.get("format") != MANIFEST_FORMAT:
        raise SnapshotCorruptError(
            f"{gen_dir}: unsupported manifest format "
            f"{manifest.get('format')!r}"
        )
    return manifest


def verify_generation(gen_dir: str, missing_ok: bool = False) -> Dict:
    """Manifest + every section verified; returns the manifest.  Raises
    typed `SnapshotCorruptError` on the first mismatch — the caller
    (restore) falls back to the prior generation.  `missing_ok` is the
    FLAT-checkpoint mode (checkpoint.load): an operator may delete an
    optional section (indexes.npz) to force a re-finalize — absence is
    the documented slow path there, only present-but-mismatched bytes
    are corruption.  Real generations keep the strict default: their
    sections were written together and a missing one is a torn write."""
    manifest = read_manifest(gen_dir)
    for name, meta in manifest["sections"].items():
        path = os.path.join(gen_dir, name)
        if missing_ok and not os.path.exists(path):
            continue
        _verified_bytes(path, meta)
    return manifest


# -- snapshot write ----------------------------------------------------------


def _warm_payload(db) -> Optional[bytes]:
    """Warm-state bundle of a live backend: CapStore learned
    capacities, planner degree statistics, count-cache entries —
    everything a replica can inherit instead of re-learn (query/
    fused.py export_warm_state).  Best-effort: a cold store simply
    has no bundle."""
    try:
        from das_tpu.query.fused import export_warm_state

        state = export_warm_state(db)
    except Exception:  # noqa: BLE001 — warm state is a perf hint only
        return None
    if state is None:
        return None
    return msgpack.packb(state, use_bin_type=True)


def write_snapshot(db, root: str, keep: Optional[int] = None) -> str:
    """One atomic generational snapshot of a live backend (TensorDB or
    ShardedDB): build `gen-NNNNNN` in a dot-temp directory — records,
    finalized indexes, registry, (sharded) slabs, warm bundle, then
    the manifest LAST — fsync everything, and rename the directory
    into place.  Rotates the backend's WAL to the new generation and
    prunes generations beyond `keep` (DasConfig.snapshot_keep).
    Returns the generation directory."""
    from das_tpu import obs
    from das_tpu.storage import checkpoint

    cfg = getattr(db, "config", None)
    if keep is None:
        keep = int(getattr(cfg, "snapshot_keep", 2) or 2)
    os.makedirs(root, exist_ok=True)
    gens = list_generations(root)
    gen = (gens[-1][0] + 1) if gens else 1
    gen_dir = os.path.join(root, _gen_name(gen))
    tmp_dir = os.path.join(root, f".{_gen_name(gen)}.tmp{os.getpid()}")
    version = int(getattr(db, "delta_version", 0))
    with obs.span("dur.snapshot", generation=gen, version=version):
        os.makedirs(tmp_dir, exist_ok=True)
        try:
            data = db.data
            fin = data.finalize()
            sections: Dict[str, Dict[str, int]] = {}
            sections[checkpoint.RECORDS_FILE] = atomic_write_bytes(
                os.path.join(tmp_dir, checkpoint.RECORDS_FILE),
                msgpack.packb(
                    checkpoint._records_payload(data), use_bin_type=True
                ),
            )
            import numpy as np

            sections[checkpoint.INDEXES_FILE] = atomic_write(
                os.path.join(tmp_dir, checkpoint.INDEXES_FILE),
                lambda f: np.savez(f, **checkpoint._indexes_payload(fin)),
            )
            sections[checkpoint.REGISTRY_FILE] = atomic_write_bytes(
                os.path.join(tmp_dir, checkpoint.REGISTRY_FILE),
                msgpack.packb(
                    checkpoint._registry_payload(fin), use_bin_type=True
                ),
            )
            if hasattr(db, "tables"):
                # sharded slabs: restore device_puts them directly —
                # no host-global re-partition (checkpoint.py
                # try_restore_sharded; its content_sig guard degrades
                # a mismatched restore to re-partition, never to a
                # wrong store)
                name = checkpoint.SHARDED_FILE_FMT.format(
                    db.tables.n_shards
                )
                sections[name] = atomic_write(
                    os.path.join(tmp_dir, name),
                    lambda f: np.savez(
                        f, **checkpoint._sharded_payload(db)
                    ),
                )
            warm = _warm_payload(db)
            if warm is not None:
                sections[WARM_FILE] = atomic_write_bytes(
                    os.path.join(tmp_dir, WARM_FILE), warm
                )
            manifest = {
                "format": MANIFEST_FORMAT,
                "generation": gen,
                "delta_version": version,
                "content_sig": checkpoint._content_sig(fin),
                "sections": sections,
                "wal": WAL_FILE,
                "warm_delta_version": None if warm is None else version,
                "xla_cache_dir": os.environ.get("DAS_TPU_XLA_CACHE"),
                "created_unix": time.time(),
            }
            atomic_write_bytes(
                os.path.join(tmp_dir, MANIFEST_FILE),
                json.dumps(manifest, sort_keys=True, indent=1).encode(),
            )
            _publish_generation(tmp_dir, gen_dir, root)
        except BaseException:
            import shutil

            shutil.rmtree(tmp_dir, ignore_errors=True)
            raise
    # the new generation is durable: commits from here log into ITS wal
    if getattr(db, "_wal", None) is not None or wal_enabled(cfg):
        db._wal = DeltaLog(os.path.join(gen_dir, WAL_FILE), db.data)
    db._snapshot_root = root
    DUR_STATS["generation"] = gen
    DUR_STATS["snapshots"] = int(DUR_STATS["snapshots"]) + 1
    if obs.enabled():
        obs.counter("dur.snapshots").inc()
    prune_generations(root, keep)
    return gen_dir


def prune_generations(root: str, keep: int) -> None:
    """Drop the oldest completed generations beyond `keep` (each owns
    its WAL, so pruning can never strand replay state of a survivor)."""
    import shutil

    gens = list_generations(root)
    for _n, path in gens[:-keep] if keep > 0 else []:
        shutil.rmtree(path, ignore_errors=True)


def wal_enabled(config) -> bool:
    """DasConfig.wal (env DAS_TPU_WAL): "auto"/"on" arm the delta log
    whenever a snapshot root is attached; "off" disables it (snapshots
    still work, commits after the last snapshot are lost on crash)."""
    mode = str(getattr(config, "wal", "auto") or "auto").lower()
    return mode not in ("off", "0", "false")


# -- restore -----------------------------------------------------------------


def _load_generation(gen_dir: str):
    """Verify + parse one generation: (AtomSpaceData with restored
    indexes, manifest).  InjectedFault/IO flakes retry on the shared
    RetryPolicy (das_tpu/fault); verification failures are typed and
    NOT retryable — the caller falls back a generation."""
    from das_tpu import fault
    from das_tpu.storage import checkpoint

    def attempt():
        manifest = verify_generation(gen_dir)
        data = checkpoint.load(gen_dir, _verified=True)
        return data, manifest

    return fault.fetch_retry().run(attempt)


def newest_valid_generation(root: str):
    """(data, manifest, gen_dir) of the newest generation that passes
    verification, walking backwards past torn/corrupt ones.  Typed
    `SnapshotCorruptError` when nothing valid remains."""
    from das_tpu.utils.logger import logger

    gens = list_generations(root)
    if not gens:
        raise SnapshotCorruptError(f"no snapshot generations under {root}")
    last_exc: Optional[Exception] = None
    for _n, gen_dir in reversed(gens):
        try:
            data, manifest = _load_generation(gen_dir)
            return data, manifest, gen_dir
        except Exception as exc:  # noqa: BLE001 — typed + logged fallback
            DUR_STATS["corrupt_generations"] = (
                int(DUR_STATS["corrupt_generations"]) + 1
            )
            logger().warning(
                f"snapshot generation {gen_dir} rejected "
                f"({type(exc).__name__}: {exc}); falling back"
            )
            last_exc = exc
    raise SnapshotCorruptError(
        f"no valid snapshot generation under {root}: {last_exc}"
    )


def replay_wal(db, gen_dir: str, manifest: Dict) -> int:
    """Replay the generation's WAL onto a freshly restored backend:
    records at or below the snapshot's delta_version are skipped
    (duplicates of what the snapshot already holds — including a
    retried commit's twin record), later ones re-insert their atoms
    and run the backend's own `refresh()` commit path, re-verified
    against delta_version CONTINUITY: every applied record must land
    the store exactly on its recorded version, else the log lies and
    restore fails typed rather than serve a diverged store."""
    from das_tpu import fault

    records, _torn = fault.fetch_retry().run(
        lambda: read_wal(os.path.join(gen_dir, manifest["wal"]))
    )
    replayed = 0
    for rec in records:
        v = int(rec["v"])
        if v <= db.delta_version:
            continue  # predates the snapshot, or a retried commit's twin
        if v != db.delta_version + 1:
            raise SnapshotCorruptError(
                f"WAL continuity broken: record v{v} after store "
                f"v{db.delta_version}"
            )
        _replay_record(db.data, rec)
        db.refresh()
        if db.delta_version != v:
            raise SnapshotCorruptError(
                f"WAL replay diverged: store v{db.delta_version} after "
                f"applying record v{v}"
            )
        replayed += 1
    DUR_STATS["recovery_replayed"] = (
        int(DUR_STATS["recovery_replayed"]) + replayed
    )
    return replayed


def restore(root: str, config=None, backend: Optional[str] = None):
    """Warm-state restore: newest VALID snapshot generation + WAL
    replay to head + warm bundle — the replica-fleet cold-start path
    (`TensorDB.restore` / `ShardedDB.restore` delegate here).  Returns
    the live backend with durability re-attached (subsequent commits
    append to the restored generation's WAL)."""
    from das_tpu import obs
    from das_tpu.core.config import DasConfig

    t0 = time.perf_counter()
    config = config or DasConfig.from_env()
    backend = backend or config.backend
    with obs.span("dur.restore", backend=backend):
        data, manifest, gen_dir = newest_valid_generation(root)
        if backend == "sharded":
            from das_tpu.parallel.sharded_db import ShardedDB
            import dataclasses

            # checkpoint_path steers ShardedDB's existing slab-restore
            # path at the verified generation dir
            cfg = dataclasses.replace(config, checkpoint_path=gen_dir)
            db = ShardedDB(data, cfg)
        else:
            from das_tpu.storage.tensor_db import TensorDB

            db = TensorDB(data, config)
        db.delta_version = int(manifest["delta_version"])
        replayed = replay_wal(db, gen_dir, manifest)
        if wal_enabled(config):
            db._wal = DeltaLog(os.path.join(gen_dir, WAL_FILE), db.data)
        db._snapshot_root = root
        warm_applied = _apply_warm(db, gen_dir, manifest)
    elapsed = time.perf_counter() - t0
    DUR_STATS["generation"] = int(manifest["generation"])
    DUR_STATS["last_restore_s"] = round(elapsed, 4)
    if obs.enabled():
        obs.counter("dur.recovery_replayed").inc(replayed)
        obs.histogram("dur.restore_ms").observe(elapsed * 1e3)
    from das_tpu.utils.logger import logger

    logger().info(
        f"dasdur restore: gen {manifest['generation']} + {replayed} WAL "
        f"commits in {elapsed:.3f}s (warm bundle "
        f"{'applied' if warm_applied else 'absent/stale'})"
    )
    return db


def _apply_warm(db, gen_dir: str, manifest: Dict) -> bool:
    """Apply the warm-state bundle when its recorded delta_version
    still matches the restored store (the existing staleness guard:
    WAL replay past the snapshot makes the bundle stale, exactly like
    a result-cache entry — discarded, never trusted)."""
    warm_v = manifest.get("warm_delta_version")
    meta = manifest["sections"].get(WARM_FILE)
    if meta is None or warm_v is None:
        return False
    if int(warm_v) != int(db.delta_version):
        return False  # replayed past the snapshot: bundle is stale
    from das_tpu import fault

    try:
        payload = fault.fetch_retry().run(
            lambda: _verified_bytes(os.path.join(gen_dir, WARM_FILE), meta)
        )
        state = msgpack.unpackb(payload, raw=False, strict_map_key=False)
        from das_tpu.query.fused import apply_warm_state

        return apply_warm_state(db, state)
    except SnapshotCorruptError:
        raise
    except Exception:  # noqa: BLE001 — warm state is a perf hint only
        return False


# -- attach (live durability) ------------------------------------------------


def attach(db, root: str, config=None) -> str:
    """Arm durability on a live backend: make the root's newest
    generation REFLECT this store, then point the backend's delta log
    at its WAL.  An empty root gets the initial snapshot (the WAL
    needs a base to replay onto).  A populated root is reused ONLY
    when its newest generation provably describes this exact store
    (delta_version AND content fingerprint match — the restore path
    arms its own WAL, so a mismatch here means the caller attached a
    DIFFERENT store to an old root); anything else gets a fresh
    generation, because appending this store's delta_versions to
    another store's WAL would be silently skipped — or fail the
    continuity check — at replay.  Returns the active generation dir."""
    gens = list_generations(root)
    cfg = config if config is not None else getattr(db, "config", None)
    if gens:
        gen_dir = gens[-1][1]
        try:
            from das_tpu.storage import checkpoint

            manifest = read_manifest(gen_dir)
            # the WAL must also be EMPTY: any record means the lineage's
            # head is already PAST this snapshot — re-arming it would
            # append a second run's versions that replay dedups away
            # (silently dropped fsynced commits); a fresh generation
            # keeps every lineage single-writer-single-history
            wal_records, _torn = read_wal(
                os.path.join(gen_dir, manifest.get("wal", WAL_FILE)),
                truncate=False,
            )
            matches = (
                not wal_records
                and int(manifest.get("delta_version", -1))
                == int(getattr(db, "delta_version", 0))
                and manifest.get("content_sig")
                == checkpoint._content_sig(db.data.finalize())
            )
        except Exception:  # noqa: BLE001 — unreadable = not this store
            matches = False
        if matches:
            if wal_enabled(cfg):
                # position the log at the CURRENT store: appends from
                # here describe commits after attach (earlier state is
                # the snapshot + existing records' job)
                db._wal = DeltaLog(os.path.join(gen_dir, WAL_FILE), db.data)
            db._snapshot_root = root
            DUR_STATS["generation"] = gens[-1][0]
            return gen_dir
    return write_snapshot(db, root)
