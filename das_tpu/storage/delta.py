"""Shared incremental-commit machinery for device-resident backends.

The reference's update path is incremental: a transaction commit re-parses
only the new expressions and inserts them into the live Mongo collections
and Redis index sets (das/das_update_test.py:141-192,
distributed_atom_space.py:326-334).  The TPU analogue — re-finalizing and
re-uploading the whole store — would cost minutes at millions of links, so
both device backends (storage/tensor_db.py, parallel/sharded_db.py) commit
deltas instead:

  * the host-side part is IDENTICAL for both and lives here: decide
    whether a delta is safe (`plan_refresh`), intern the new atoms into
    the live `Finalized` registries (`intern_delta`), and maintain the
    delta incoming-set overlay consulted by `get_incoming`;
  * the device-side part differs by layout: TensorDB extends flat
    `[m]` sorted indexes, ShardedDB extends stacked `[S, m_local]`
    slab-local indexes under `shard_map` — both with the same O(n)
    two-sorted-array merge (`merge_sorted_index`: merge-path positions
    from |delta| binary searches plus one cumsum, no re-sort).

Deltas accumulate LSM-style; past `config.delta_merge_threshold` total new
atoms the caller fully re-finalizes and clears the overlay.
"""

from __future__ import annotations

from itertools import islice
from typing import Dict, List, Tuple

import jax.numpy as jnp


#: sentinel returned by plan_refresh when only a full rebuild is safe
FULL = "full"
#: sentinel returned by plan_refresh when nothing changed
NOOP = "noop"


def capacity_class(n: int) -> int:
    """Device-bucket capacity for n real rows: ~6% slack (min 64) absorbs
    commits without changing array shapes.  Shared by both backends so
    they grow/compact at the same ratio; deterministic so compile caches
    hit across processes for the same store size."""
    return n + max(64, n >> 4)


def delta_class(d: int) -> int:
    """Pow2 size class (min 64) for a commit's padded delta block — keeps
    the set of compiled fixed-shape merge programs small."""
    return max(64, 1 << (d - 1).bit_length()) if d > 1 else 64


def merge_sorted_index(base_keys, base_perm, delta_keys, delta_perm):
    """Extend a device-resident sorted index by a small sorted delta in
    O(n): merge-path positions come from |delta| binary searches into the
    base plus one cumsum over the base — no re-sort of the big side.
    Ties place base elements first (side='right'), preserving stability.
    delta_perm must already be offset into the merged row space."""
    nb = base_keys.shape[0]
    nd = delta_keys.shape[0]
    ins = jnp.searchsorted(base_keys, delta_keys, side="right").astype(jnp.int32)
    counts = jnp.zeros(nb + 1, dtype=jnp.int32).at[ins].add(1)
    shift = jnp.cumsum(counts)[:nb]          # deltas inserted at or before i
    pos_b = jnp.arange(nb, dtype=jnp.int32) + shift
    pos_d = ins + jnp.arange(nd, dtype=jnp.int32)
    keys = (
        jnp.zeros(nb + nd, dtype=base_keys.dtype)
        .at[pos_b].set(base_keys)
        .at[pos_d].set(delta_keys)
    )
    perm = (
        jnp.zeros(nb + nd, dtype=jnp.int32)
        .at[pos_b].set(base_perm)
        .at[pos_d].set(delta_perm)
    )
    return keys, perm


class IncrementalCommitMixin:
    """Host-side delta-commit state shared by TensorDB and ShardedDB.

    Expects the host class to provide `self.data` (AtomSpaceData),
    `self.fin` (the live Finalized), and `self.config` (DasConfig).
    """

    #: write-ahead delta log (ISSUE 15, storage/durable.py DeltaLog) —
    #: armed by durable.attach/restore when a snapshot root is
    #: configured.  The class-level None IS the disabled fast path:
    #: with no WAL, `_apply_delta` reads one attribute and branches —
    #: byte-for-byte the pre-dasdur commit behavior, no allocations
    #: (the disabled-path identity pin, tests/test_zdur.py).
    _wal = None
    #: snapshot root this backend persists under (durable.attach)
    _snapshot_root = None

    def _reset_delta_state(self) -> None:
        # monotone commit counter: bumps on every device-table mutation —
        # full rebuilds land here, incremental commits in _apply_delta.
        # Device-resident result caches (query/fused.py ResultCache) key
        # on it, so a commit invalidates exactly the entries written
        # against the pre-commit store and nothing else survives stale.
        self.delta_version = getattr(self, "delta_version", 0) + 1
        from das_tpu import obs

        if obs.enabled():
            # full (re)build: every cached answer and degree statistic
            # keyed on the previous version is now stale — the trace
            # event that explains a post-rebuild cold stretch
            obs.event("commit.rebuild", version=self.delta_version)
            obs.counter("commit.rebuilds").inc()
        self._base_counts = (len(self.data.nodes), len(self.data.links))
        self._delta_incoming: Dict[int, list] = {}  # target_row -> [link_rows]
        self._delta_total = 0
        # backend-LOCAL view of the finalized buckets: several backends may
        # share one Finalized, and each backend's delta segments must pair
        # with the base its own device tables were built from — a shared
        # fin.buckets entry must never be overwritten by whichever backend
        # commits a new arity first
        self._base_buckets: Dict[int, object] = dict(self.fin.buckets)
        self._host_delta: Dict[int, list] = {}  # arity -> overlay segments

    def host_bucket_segments(self, arity: int):
        """Host-side column segments — the backend's base bucket plus one
        overlay segment per incremental commit — for exact candidate
        estimates (query/fused.py estimate_plan_rows) and, on TensorDB,
        bucket-local row materialization.  Their concatenation (in order)
        mirrors this backend's merged device row space exactly."""
        out = []
        base = self._base_buckets.get(arity)
        if base is not None and base.size:
            out.append(base)
        out.extend(self._host_delta.get(arity, ()))
        return out

    def _plan_refresh(self):
        """Classify the pending host mutations: NOOP (nothing changed),
        FULL (only a rebuild is safe), or the (new_node_hexes,
        new_link_hexes) of an applicable incremental commit."""
        n_nodes, n_links = len(self.data.nodes), len(self.data.links)
        d_nodes = n_nodes - self._base_counts[0]
        d_links = n_links - self._base_counts[1]
        if d_nodes == 0 and d_links == 0:
            return NOOP
        if (
            d_nodes < 0
            or d_links < 0
            or self.fin.atom_count == 0  # bulk load onto an empty store
            or self._delta_total + d_nodes + d_links
            > self.config.delta_merge_threshold
        ):
            return FULL
        new_node_hexes = list(islice(reversed(self.data.nodes), d_nodes))[::-1]
        new_link_hexes = list(islice(reversed(self.data.links), d_links))[::-1]
        dangled_on = self.fin.dangling_hexes
        if dangled_on is None:
            # restored store with sentinel targets but no recorded set:
            # cannot prove the commit is safe -> rebuild once
            return FULL
        if dangled_on and any(
            h in dangled_on for h in (*new_node_hexes, *new_link_hexes)
        ):
            # an existing link's sentinel (-1) target just materialized;
            # sorted positional indexes can't be retro-patched in place
            return FULL
        return new_node_hexes, new_link_hexes

    def _intern_type(self, named_type_hash: str, named_type: str) -> int:
        tid = self.fin.type_id_of_hash.get(named_type_hash)
        if tid is None:
            tid = len(self.fin.type_names)
            self.fin.type_id_of_hash[named_type_hash] = tid
            self.fin.type_names.append(named_type)
        return tid

    def _intern_delta(
        self, new_node_hexes: List[str], new_link_hexes: List[str]
    ) -> Dict[int, list]:
        """Append the new atoms to the live row registries (nodes first,
        then links bucket-major, matching finalize()'s global row order)
        and return the new link records grouped by arity.

        IDEMPOTENT across backends: the Finalized may be shared (a
        ShardedDB and its tree-fallback TensorDB over one AtomSpaceData),
        so only atoms beyond `fin.interned` are appended — a backend whose
        device tables lag behind still gets its full per-device delta in
        the returned grouping, but never double-interns rows another
        backend already registered."""
        fin = self.fin
        if fin.interned is None:
            # restored checkpoint predating the counters: at restore time
            # the registry exactly covers the records (load() verifies)
            fin.interned = [fin.node_count, fin.atom_count - fin.node_count]
        n_nodes_new = len(self.data.nodes) - fin.interned[0]
        n_links_new = len(self.data.links) - fin.interned[1]
        # the tail of this backend's delta that nobody has interned yet
        # (new_*_hexes are the trailing entries of the insertion-ordered
        # record dicts, so the registry tail is a suffix of them)
        to_intern_nodes = new_node_hexes[len(new_node_hexes) - n_nodes_new:] if n_nodes_new > 0 else []
        to_intern_links = new_link_hexes[len(new_link_hexes) - n_links_new:] if n_links_new > 0 else []
        for h in to_intern_nodes:
            rec = self.data.nodes[h]
            self._intern_type(rec.named_type_hash, rec.named_type)
            fin.row_of_hex[h] = len(fin.hex_of_row)
            fin.hex_of_row.append(h)
        intern_by_arity: Dict[int, list] = {}
        for h in to_intern_links:
            rec = self.data.links[h]
            intern_by_arity.setdefault(len(rec.elements), []).append((h, rec))
        for arity in sorted(intern_by_arity):
            for h, _rec in intern_by_arity[arity]:
                fin.row_of_hex[h] = len(fin.hex_of_row)
                fin.hex_of_row.append(h)
        fin.atom_count = len(fin.hex_of_row)
        fin.interned = [len(self.data.nodes), len(self.data.links)]
        # the caller's device merge needs ALL of its new links, interned
        # here or by another backend earlier
        by_arity: Dict[int, list] = {}
        for h in new_link_hexes:
            rec = self.data.links[h]
            by_arity.setdefault(len(rec.elements), []).append((h, rec))
        return by_arity

    def _record_delta_incoming(self, incoming_pairs) -> None:
        """incoming_pairs: (target_rows, link_rows) numpy array chunks as
        produced by build_bucket."""
        for trows, lrows in incoming_pairs:
            for trow, lrow in zip(trows.tolist(), lrows.tolist()):
                self._delta_incoming.setdefault(trow, []).append(lrow)

    def _apply_delta(self, new_node_hexes: List[str], new_link_hexes: List[str]) -> None:
        """One incremental commit, STAGE-THEN-SWAP (ISSUE 13): intern the
        atoms (idempotent — see _intern_delta), columnize each arity's
        new links (storage/atom_table.py build_bucket), and COMPUTE every
        device merge via the backend's `_stage_delta_merge`, which
        returns (swap, became_base, slots) — jax arrays are immutable,
        so staging produces entirely new structures and the returned
        `swap` thunk is a pure reference assignment.  Only after every
        arity staged do the swaps, the incoming-overlay updates, and the
        `delta_version` bump run, so a failure ANYWHERE in the fallible
        half leaves the store exactly at the pre-commit state: version
        unbumped, result/tree caches still valid, device tables
        untouched — and re-running the same commit succeeds (the chaos
        atomicity pin, tests/test_zfault.py).  `fault.maybe_fail` marks
        the declared mid-commit crash point between the halves.
        Memory amplification is bounded STRUCTURALLY: both device layouts
        are capacity-padded with fixed slack, and a layout that can't
        absorb a commit triggers growth (tensor) or early LSM compaction
        (sharded) on its own — both raised while staging, i.e. before
        anything became visible."""
        from das_tpu import fault
        from das_tpu.storage.atom_table import build_bucket

        fin = self.fin
        by_arity = self._intern_delta(new_node_hexes, new_link_hexes)
        # -- fallible half: stage (no visible mutation) -------------------
        staged = []
        for arity, entries in sorted(by_arity.items()):
            # (target_rows, link_rows) array chunks from build_bucket
            incoming_pairs: list = []
            commit_bucket = build_bucket(
                arity, entries, fin.row_of_hex, self._intern_type,
                incoming_pairs, fin.dangling_hexes,
            )
            swap, became_base, slots = self._stage_delta_merge(commit_bucket)
            staged.append(
                (arity, commit_bucket, incoming_pairs, swap,
                 became_base, slots)
            )
        fault.maybe_fail("commit_apply")
        # -- write-ahead log (ISSUE 15): the interned delta is framed,
        # checksummed and FSYNCED before the swap makes anything
        # visible, so a crash on either side of the swap is recoverable
        # (logged-but-unswapped replays at restore; swapped-and-logged
        # is simply durable).  A WAL failure lands in the fallible half
        # — store untouched, the shared RetryPolicy re-stages, and a
        # retried append's duplicate record dedups by delta_version at
        # replay (durable.replay_wal).  No WAL configured (`_wal` is
        # the class-level None): one attribute read, zero new work.
        wal = self._wal
        if wal is not None:
            wal.append(self.data, self.delta_version + 1)
        # -- infallible half: swap (pure assignments) ---------------------
        slot_growth = 0
        for arity, commit_bucket, incoming_pairs, swap, became_base, \
                slots in staged:
            swap()
            self._record_delta_incoming(incoming_pairs)
            slot_growth += slots
            if became_base:
                # first links of this arity: the delta bucket is the base
                # for THIS backend (fin.buckets may be shared with another
                # backend whose device tables differ)
                self._base_buckets[arity] = commit_bucket
            else:
                self._host_delta.setdefault(arity, []).append(commit_bucket)
        self._base_counts = (len(self.data.nodes), len(self.data.links))
        self._delta_total += max(
            slot_growth, len(new_node_hexes) + len(new_link_hexes)
        )
        # the device tables just changed under any live executor: answers
        # cached against the pre-commit version must stop hitting
        self.delta_version += 1
        from das_tpu import obs

        if obs.enabled():
            obs.event(
                "commit.delta", version=self.delta_version,
                nodes=len(new_node_hexes), links=len(new_link_hexes),
            )
            obs.counter("commit.deltas").inc()
        if self.data.columnar is not None:
            # a commit happened, so more commits (and their membership
            # probes) are likely: build the digest indexes NOW — the
            # commit that just ran kept its own probes on the cheap
            # linear path, every later one gets microsecond lookups
            self.data.columnar.ensure_indexes()

    def _commit_delta_with_retry(self, action) -> None:
        """Both backends' refresh() commit entry: the shared
        fault.RetryPolicy (ISSUE 13) retries a transport-class apply
        failure — safe precisely because _apply_delta is
        stage-then-swap, so a failed attempt left no visible state.
        Non-retryable failures (SlabCapacityExhausted, semantic errors)
        propagate untouched to the backend's own recovery."""
        from das_tpu import fault

        fault.commit_retry().run(lambda: self._apply_delta(*action))

    def get_incoming(self, handle: str) -> List[str]:
        """Incoming set = base CSR rows + the delta overlay (links committed
        since the last full finalize)."""
        row = self.fin.row_of_hex.get(handle)
        if row is None:
            return []
        out: List[str] = []
        if row + 1 < self.fin.incoming_offsets.shape[0]:  # base CSR rows
            lo = int(self.fin.incoming_offsets[row])
            hi = int(self.fin.incoming_offsets[row + 1])
            out = [
                self.fin.hex_of_row[int(r)]
                for r in self.fin.incoming_links[lo:hi]
            ]
        for r in self._delta_incoming.get(row, ()):
            out.append(self.fin.hex_of_row[int(r)])
        return out
