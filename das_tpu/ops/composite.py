"""Device kernels for composite binding tables (unordered/multiset semantics).

The reference joins `UnorderedAssignment` / `CompositeAssignment` objects in
Python (pattern_matcher.py:158-368): an unordered (Set/Similarity) match is a
multiset of symbols and values without a committed pairing, and joins chain
viability checks (`contains_ordered`, `is_covered_by_ordered`, `compatible`)
between the ordered map and every multiset constraint.

Here a composite binding table is a padded int32 matrix whose columns split
into *ordered* variable columns plus one sorted-value block per unordered
constraint (the constraint's variable names are static; since every frozen
UnorderedAssignment binds k distinct variables exactly once, its value
multiset is k distinct values — the sorted block IS the canonical identity).
The reference's viability predicates become row-wise (or row-pair-wise, for
negation filtering) vectorized comparisons over those column blocks, unrolled
statically over the small column counts.

Every predicate cites the reference method it mirrors; answer parity is
asserted by tests/test_differential.py with the device path forced.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

_I32_MAX = jnp.int32(2**31 - 1)


# ---------------------------------------------------------------------------
# unordered term tables
# ---------------------------------------------------------------------------

def build_uterm_table(targets_sorted, local, mask, req_vals, n_required: int, k: int):
    """Project probed candidate links of an unordered pattern into a sorted
    value-block table (reference Link._assign_variables unordered branch,
    pattern_matcher.py:158-191 + ast.py:146-161 semantics).

    targets_sorted — [m_bucket, arity] canonically sorted target rows
    local/mask     — padded probe result (bucket-local rows + validity)
    req_vals       — traced int32[n_required] grounded target rows, with
                     multiplicity (one entry per required occurrence)
    k              — number of pattern variables (= arity - n_required)

    Per candidate: remove one occurrence of each required value from the
    sorted target multiset; the remaining k values (still sorted) are the
    value block.  A row survives only if every required value was found
    (multiset containment) and the k remaining values are pairwise distinct
    (UnorderedAssignment.freeze rejects any multiset whose value counts
    cannot match k distinct symbols, pattern_matcher.py:184-191).
    """
    safe = jnp.clip(local, 0, targets_sorted.shape[0] - 1)
    ts = targets_sorted[safe]                      # [cap, arity]
    arity = ts.shape[1]
    # run-rank r[p]: index of this occurrence within its equal-value run
    rank = jnp.zeros(ts.shape, dtype=jnp.int32)
    for p in range(1, arity):
        eq_prev = jnp.zeros(ts.shape[0], dtype=jnp.int32)
        for q in range(p):
            eq_prev = eq_prev + (ts[:, q] == ts[:, p]).astype(jnp.int32)
        rank = rank.at[:, p].set(eq_prev)
    # required multiplicity of each position's value
    if n_required:
        cnt_req = jnp.zeros(ts.shape, dtype=jnp.int32)
        for i in range(n_required):
            cnt_req = cnt_req + (ts == req_vals[i][None, None]).astype(jnp.int32)
        removed = rank < cnt_req
        mask = mask & (removed.sum(axis=1) == n_required)
    else:
        removed = jnp.zeros(ts.shape, dtype=bool)
    remaining = jnp.where(removed, _I32_MAX, ts)
    remaining = jnp.sort(remaining, axis=1)
    vals = remaining[:, :k]
    if k > 1:
        distinct = (vals[:, 1:] != vals[:, :-1]).all(axis=1)
        mask = mask & distinct
    vals = jnp.where(mask[:, None], vals, jnp.int32(0))
    return vals, mask


# ---------------------------------------------------------------------------
# row-wise predicates over ONE table (post-join condition masks)
#
# Each takes the joined output values matrix plus static column-index tuples
# and returns a bool[rows] mask.  Ordered blocks are (names, cols) pairs;
# unordered blocks hold k distinct values each (see module docstring).
# ---------------------------------------------------------------------------

def contains_ordered_mask(vals, unames, ucols, onames, ocols):
    """UnorderedAssignment.contains_ordered (pattern_matcher.py:199-208):
    every ordered variable is one of the constraint's symbols and the
    ordered values' counts fit inside the constraint's value multiset."""
    if not set(onames) <= set(unames):
        return jnp.zeros(vals.shape[0], dtype=bool)
    ok = jnp.ones(vals.shape[0], dtype=bool)
    for i in ocols:
        cnt_u = jnp.zeros(vals.shape[0], dtype=jnp.int32)
        for j in ucols:
            cnt_u = cnt_u + (vals[:, j] == vals[:, i]).astype(jnp.int32)
        cnt_om = jnp.zeros(vals.shape[0], dtype=jnp.int32)
        for i2 in ocols:
            cnt_om = cnt_om + (vals[:, i2] == vals[:, i]).astype(jnp.int32)
        ok = ok & (cnt_u >= cnt_om)
    return ok


def covered_by_ordered_mask(vals, unames, ucols, onames, ocols):
    """UnorderedAssignment.is_covered_by_ordered (pattern_matcher.py:210-218):
    the ordered map fully accounts for the constraint — symbols all appear as
    ordered variables and every constraint value's multiplicity is matched by
    the ordered values."""
    if not set(unames) <= set(onames):
        return jnp.zeros(vals.shape[0], dtype=bool)
    ok = jnp.ones(vals.shape[0], dtype=bool)
    for j in ucols:
        mult_u = jnp.zeros(vals.shape[0], dtype=jnp.int32)
        for j2 in ucols:
            mult_u = mult_u + (vals[:, j2] == vals[:, j]).astype(jnp.int32)
        mult_om = jnp.zeros(vals.shape[0], dtype=jnp.int32)
        for i in ocols:
            mult_om = mult_om + (vals[:, i] == vals[:, j]).astype(jnp.int32)
        ok = ok & (mult_u <= mult_om)
    return ok


def viability_mask(vals, unames, ucols, onames, ocols):
    """CompositeAssignment._ordered_viable per-constraint disjunction
    (pattern_matcher.py:294-305): contains_ordered OR is_covered_by_ordered."""
    return contains_ordered_mask(vals, unames, ucols, onames, ocols) | (
        covered_by_ordered_mask(vals, unames, ucols, onames, ocols)
    )


def compatible_mask(vals, names1, cols1, names2, cols2):
    """UnorderedAssignment.compatible (pattern_matcher.py:229-237).  With
    distinct values per constraint both `have` sums equal the intersection
    size, and both `need` sums equal the shared-symbol count."""
    need = len(set(names1) & set(names2))
    if need == 0:
        return jnp.ones(vals.shape[0], dtype=bool)
    inter = jnp.zeros(vals.shape[0], dtype=jnp.int32)
    for j1 in cols1:
        for j2 in cols2:
            inter = inter + (vals[:, j1] == vals[:, j2]).astype(jnp.int32)
    return inter >= need


# ---------------------------------------------------------------------------
# pairwise negation predicates: answer table A x tabu table T -> bool[A, T]
#
# These mirror the check_negation dispatch (pattern_matcher.py:142-146,
# 190-197, 305-317).  `excluded[a] = any_t pred(a, t)`; the caller keeps a
# row iff NOT excluded by any tabu row of any forbidden table.
# ---------------------------------------------------------------------------

def _eq(va, ca, vt, ct):
    return va[:, ca][:, None] == vt[:, ct][None, :]


def _false(va, vt):
    return jnp.zeros((va.shape[0], vt.shape[0]), dtype=bool)


def _true(va, vt):
    return jnp.ones((va.shape[0], vt.shape[0]), dtype=bool)


def pair_ordered_covers(va, a_names, a_cols, vt, t_names, t_cols):
    """OrderedAssignment.check_negation vs ordered tabu: excluded iff the
    tabu mapping is a sub-map of the answer (EQUAL / FIRST_COVERS_SECOND,
    pattern_matcher.py:142-145)."""
    if not set(t_names) <= set(a_names):
        return None  # statically never excludes
    out = _true(va, vt)
    for n, tc in zip(t_names, t_cols):
        ac = a_cols[a_names.index(n)]
        out = out & _eq(va, ac, vt, tc)
    return out


def pair_u_covered_by_ordered(va, a_onames, a_ocols, vt, t_unames, t_ucols):
    """negation.is_covered_by_ordered(self) for an unordered tabu against an
    ordered answer (pattern_matcher.py:146, 210-218)."""
    if not set(t_unames) <= set(a_onames):
        return None
    out = _true(va, vt)
    for j in t_ucols:
        mult_t = jnp.zeros(vt.shape[0], dtype=jnp.int32)
        for j2 in t_ucols:
            mult_t = mult_t + (vt[:, j2] == vt[:, j]).astype(jnp.int32)
        mult_a = jnp.zeros((va.shape[0], vt.shape[0]), dtype=jnp.int32)
        for i in a_ocols:
            mult_a = mult_a + _eq(va, i, vt, j).astype(jnp.int32)
        out = out & (mult_a >= mult_t[None, :])
    return out


def pair_u_contains_ordered(va, a_unames, a_ucols, vt, t_onames, t_ocols):
    """u.contains_ordered(tabu) with u on the answer side
    (pattern_matcher.py:199-208): tabu variables all symbols of u, tabu value
    counts fit in u's values."""
    if not set(t_onames) <= set(a_unames):
        return None
    out = _true(va, vt)
    for i in t_ocols:
        cnt_a = jnp.zeros((va.shape[0], vt.shape[0]), dtype=jnp.int32)
        for j in a_ucols:
            cnt_a = cnt_a + _eq(va, j, vt, i).astype(jnp.int32)
        cnt_t = jnp.zeros(vt.shape[0], dtype=jnp.int32)
        for i2 in t_ocols:
            cnt_t = cnt_t + (vt[:, i2] == vt[:, i]).astype(jnp.int32)
        out = out & (cnt_a >= cnt_t[None, :])
    return out


def pair_u_contains_unordered(va, a_unames, a_ucols, vt, t_unames, t_ucols):
    """u.contains_unordered(tabu_u) (pattern_matcher.py:220-227): symbol
    counts (static) and value counts both dominate the tabu's."""
    a_set = set(a_unames)
    if any(n not in a_set for n in t_unames):
        return None
    out = _true(va, vt)
    for j in t_ucols:
        present = _false(va, vt)
        for i in a_ucols:
            present = present | _eq(va, i, vt, j)
        out = out & present
    return out
