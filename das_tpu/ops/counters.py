"""Central registry of telemetry counter keys (daslint DL004).

Until round 8 the DISPATCH_COUNTS/ROUTE_COUNTS key strings were
scattered literals across seven modules — a typo'd key would count into
a fresh dict slot while the pinned key stayed zero, and the dispatch-
count regression pins only catch that for paths someone thought to pin.
These tuples are now the ONE declared set: the dicts are built from
them (`das_tpu/kernels/__init__.py`, `das_tpu/query/compiler.py`), the
analyzer (das_tpu/analysis, rule DL004) pins every counting literal
against them in both directions, and tests/test_zlint.py pins the
tuples themselves so a key rename cannot slip through unreviewed.

This module imports nothing — both counter owners (and the analyzer's
fixtures) can depend on it without cycles.
"""

#: host-side launches of compiled device programs, by path — the dict
#: lives in das_tpu/kernels/__init__.py (see its docstring for what each
#: key means); counting sites: kernels/__init__.py (staged per-stage
#: wrappers), ops/posting.py + ops/join.py ("lowered"), query/fused.py
#: (fused + count-batch), parallel/fused_sharded.py (mesh).
DISPATCH_KEYS = (
    "lowered",
    "kernel",
    "kernel_tiled",
    "fused",
    "fused_kernel",
    "fused_kernel_tiled",
    "sharded",
    "sharded_kernel",
    "sharded_kernel_tiled",
    "count",
    "count_kernel",
    "count_kernel_tiled",
)

#: per-query answer routes — the dict lives in query/compiler.py;
#: counting sites: query/compiler.py (the per-query router),
#: api/atomspace.py (batched settle), query/fused.py (count-batch
#: cache hits), mining/miner.py (star lanes).
ROUTE_KEYS = (
    "fused",
    "fused_kernel",
    "staged",
    "staged_kernel",
    "anti_kernel",
    "tree",
    "sharded",
    "sharded_kernel",
    "count_kernel",
    "host",
    "star",
)
