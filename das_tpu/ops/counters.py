"""Central registry of telemetry counter keys (daslint DL004).

Until round 8 the DISPATCH_COUNTS/ROUTE_COUNTS key strings were
scattered literals across seven modules — a typo'd key would count into
a fresh dict slot while the pinned key stayed zero, and the dispatch-
count regression pins only catch that for paths someone thought to pin.
These tuples are now the ONE declared set: the dicts are built from
them (`das_tpu/kernels/__init__.py`, `das_tpu/query/compiler.py`), the
analyzer (das_tpu/analysis, rule DL004) pins every counting literal
against them in both directions, and tests/test_zlint.py pins the
tuples themselves so a key rename cannot slip through unreviewed.

This module imports nothing — both counter owners (and the analyzer's
fixtures) can depend on it without cycles.
"""

#: host-side launches of compiled device programs, by path — the dict
#: lives in das_tpu/kernels/__init__.py (see its docstring for what each
#: key means); counting sites: kernels/__init__.py (staged per-stage
#: wrappers), ops/posting.py + ops/join.py ("lowered"), query/fused.py
#: (fused + count-batch), parallel/fused_sharded.py (mesh).
DISPATCH_KEYS = (
    "lowered",
    "kernel",
    "kernel_tiled",
    "fused",
    "fused_kernel",
    "fused_kernel_tiled",
    #: the fused program contained a k-way MULTIWAY intersection step
    #: (kernels/multiway.py) instead of a binary-join chain prefix —
    #: counted per dispatch in query/fused.py _ExecJob.dispatch; the
    #: sharded twin in parallel/fused_sharded.py _ShardedExecJob
    "fused_multiway",
    #: ONE whole-tree fused program answered an Or/negation plan tree —
    #: every conjunction site plus the in-program union/anti settles in
    #: a single dispatch where the tree executor pays one program per
    #: site (query/fused.py _TreeExecJob.dispatch); the mesh twin is
    #: sharded_tree_fused (parallel/fused_sharded.py _ShardedTreeExecJob)
    "fused_tree",
    "sharded",
    "sharded_kernel",
    "sharded_kernel_tiled",
    "sharded_multiway",
    "sharded_tree_fused",
    "count",
    "count_kernel",
    "count_kernel_tiled",
)

#: per-query answer routes — the dict lives in query/compiler.py;
#: counting sites: query/compiler.py (the per-query router),
#: api/atomspace.py (batched settle), query/fused.py (count-batch
#: cache hits), mining/miner.py (star lanes).  The cost-based planner
#: (das_tpu/planner) PREDICTS one of these per plan — daslint rule
#: DL008 pins every planner route literal against this tuple, so a
#: planner emitting a route no counter tracks fails lint.
ROUTE_KEYS = (
    "fused",
    "fused_kernel",
    #: planner routed the conjunction's star prefix through the k-way
    #: multiway kernel (das_tpu/planner/search.py emits it; counted at
    #: job settle in query/fused.py — cache hits skip it, exactly like
    #: the dispatch counters)
    "fused_multiway",
    #: the whole Or/negation plan tree settled as ONE fused program
    #: (in-program union + anti; counted at tree-job settle in
    #: query/fused.py — a fused-tree answer also counts "tree", its
    #: route family); the planner's plan_tree emits these two keys
    "fused_tree",
    "sharded_tree_fused",
    "staged",
    "staged_kernel",
    "anti_kernel",
    "tree",
    "sharded",
    "sharded_kernel",
    "sharded_multiway",
    "count_kernel",
    "host",
    "star",
)

#: cost-based planner telemetry — the dict (PLANNER_COUNTS) lives in
#: das_tpu/planner/__init__.py and is BUILT from this tuple; counting
#: sites: planner/__init__.py (record_planned, explain, settle
#: observation), query/fused.py and parallel/fused_sharded.py (the
#: _exec_job planner hooks + per-program dispatch accounting).
#: plan_conjunction itself counts NOTHING — explain() plans too, and
#: the planned/method decomposition must cover executor traffic only
#: (dp + greedy_tail + ref_order always sums to planned).
#: daslint rule DL008 pins every
#: PLANNER_COUNTS[...] literal against this tuple in both directions,
#: exactly like DL004 does for the two sets above.
#:   planned / greedy   — conjunctions ordered+seeded by the planner vs
#:                        the legacy heuristics (off, declined, count
#:                        paths)
#:   dp / greedy_tail / ref_order — which search produced the plan
#:   programs           — device programs dispatched for planned jobs
#:   round0 / retries   — planned jobs settled with no capacity retry /
#:                        total retry rounds planned jobs still paid
#:   est_rows / actual_rows — summed estimated vs actual join output
#:                        rows of settled planned jobs (estimator-error
#:                        observability: a drifting ratio means the
#:                        degree statistics no longer describe the data)
#:   explain            — explain() invocations
PLANNER_KEYS = (
    "planned",
    "greedy",
    "dp",
    "greedy_tail",
    "ref_order",
    "programs",
    "round0",
    "retries",
    "est_rows",
    "actual_rows",
    "explain",
)
