"""Vectorized binding-table joins (device kernels).

The reference joins variable assignments with a quadratic Python nested
loop (pattern_matcher.py:732-738).  Here a binding set is a padded int32
matrix — one row per candidate assignment, one column per variable (values
are global atom row ids) — and conjunction is a sort-merge equi-join:

  1. mix the shared columns of each side into a 64-bit key,
  2. argsort the right side, `searchsorted` the left keys into it,
  3. expand the [lo, hi) ranges positionally into a fixed-capacity pair
     vector (exact pair index arithmetic via cumulative offsets),
  4. verify the shared columns exactly (the mix is only a route, never
     trusted), and gather the output columns.

Everything is static-shape; `total` reports the exact pair count so the
host can retry on capacity overflow.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

_SENTINEL_L = jnp.int64(2**63 - 1)
_SENTINEL_R = jnp.int64(2**63 - 2)


def _cumsum_i64(x):
    """Inclusive int64 prefix sum via `associative_scan` (log-depth shifted
    adds) instead of `jnp.cumsum`.

    On TPU, a 64-bit cumsum lowers to a variadic (u32, u32) reduce-window
    — s64 is emulated as u32 pairs — and inside a fused `fori_loop` count
    body that reduce-window's stack allocation overflows the v5e 16MB
    scoped-vmem budget (observed: "reduce-window ... (u32[4,128],
    u32[4,128]) ... 19.10M and limit 16.00M", BENCH_r03 tail) even though
    the identical body compiles standalone.  associative_scan lowers to
    slice+add steps with no scoped scratch.  The summed arrays here are
    left-table row counts (≤ the term capacity), so the log-depth cost is
    noise."""
    if x.shape[0] <= 1:
        return x
    return jax.lax.associative_scan(jnp.add, x)


def _searchsorted_method(n_queries: int, n_keys: int) -> str:
    """Static per-shape choice of jnp.searchsorted lowering.  'sort' keeps
    MANY queries in the fast TPU sort unit (the scan default does a
    dependent-gather binary search per query — ~100ms at 10^5 queries),
    but it re-sorts the QUERY side together with the keys, which is
    catastrophic when the query side is small relative to a huge sorted
    table (e.g. a 64-row accumulated table joining into a 33M-row
    whole-table term at FlyBase scale: 'sort' pays a 33M-element sort per
    batch member, 'scan' pays 64 binary searches).  The cutover is
    relative: scan while queries are far fewer than keys."""
    return "sort" if n_queries > max(1024, n_keys // 16) else "scan"


def _mix_columns(vals, cols: Tuple[int, ...], valid, sentinel):
    """64-bit mix of the selected int32 columns; invalid rows get a
    side-specific sentinel so they can never pair up."""
    # golden-ratio multiplier 0x9E3779B97F4A7C15 as a signed int64
    mult = jnp.int64(-7046029254386353131)
    acc = jnp.zeros(vals.shape[0], dtype=jnp.int64)
    for c in cols:
        acc = acc * mult + vals[:, c].astype(jnp.int64)
        acc = acc ^ (acc >> 29)
    return jnp.where(valid, acc, sentinel)


@partial(jax.jit, static_argnames=("pairs", "right_extra", "capacity"))
def _join_tables_jit(left_vals, left_valid, right_vals, right_valid,
                     pairs, right_extra, capacity):
    return _join_tables_impl(
        left_vals, left_valid, right_vals, right_valid, pairs, right_extra, capacity
    )


def join_tables(
    left_vals,
    left_valid,
    right_vals,
    right_valid,
    pairs: Tuple[Tuple[int, int], ...],
    right_extra: Tuple[int, ...],
    capacity: int,
):
    """Equi-join two binding tables.

    pairs       — (left_col, right_col) equality constraints (shared vars)
    right_extra — right columns appended after all left columns
    Returns (out_vals[capacity, kL+len(right_extra)], out_valid, total).
    With no shared columns this degenerates to the cross product.
    """
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _join_tables_jit(
        left_vals, left_valid, right_vals, right_valid, pairs, right_extra, capacity
    )


@partial(jax.jit, static_argnames=("pairs",))
def _anti_join_jit(left_vals, left_valid, right_vals, right_valid, pairs):
    return _anti_join_impl(left_vals, left_valid, right_vals, right_valid, pairs)


def anti_join(left_vals, left_valid, right_vals, right_valid, pairs: Tuple[Tuple[int, int], ...]):
    """NOT-filtering: invalidate left rows whose shared-column projection
    matches any right row (the ordered-assignment `check_negation`
    semantics when the tabu variable set is a subset of the output's:
    tabu ⊆ assignment ⇒ excluded).  Uses the 64-bit mix as the match key;
    a false exclusion needs a full 64-bit collision (~2^-64 per pair) —
    documented engineering tolerance of the compiled path; the host
    algebra path is collision-free."""
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _anti_join_jit(left_vals, left_valid, right_vals, right_valid, pairs)


def _anti_join_impl(left_vals, left_valid, right_vals, right_valid, pairs):
    """Un-jitted anti-join core (callable inside shard_map)."""
    lcols = tuple(lc for lc, _ in pairs)
    rcols = tuple(rc for _, rc in pairs)
    key_l = _mix_columns(left_vals, lcols, left_valid, _SENTINEL_L)
    key_r = _mix_columns(right_vals, rcols, right_valid, _SENTINEL_R)
    key_r_sorted = jnp.sort(key_r)
    method = _searchsorted_method(key_l.shape[0], key_r_sorted.shape[0])
    lo = jnp.searchsorted(key_r_sorted, key_l, side="left", method=method)
    hi = jnp.searchsorted(key_r_sorted, key_l, side="right", method=method)
    found = hi > lo
    return left_valid & ~found


@partial(jax.jit, static_argnames=("var_cols", "eq_pairs"))
def _build_term_table_jit(targets, local, mask, var_cols, eq_pairs):
    return _build_term_table_impl(targets, local, mask, var_cols, eq_pairs)


def build_term_table(targets, local, mask, var_cols: Tuple[int, ...], eq_pairs: Tuple[Tuple[int, int], ...]):
    """Project probed candidate links into a binding table: one column per
    variable (first occurrence position); `eq_pairs` enforces same-variable
    repeated positions."""
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _build_term_table_jit(targets, local, mask, var_cols, eq_pairs)


def _build_term_table_impl(targets, local, mask, var_cols, eq_pairs):
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    rows = targets[safe]
    for p1, p2 in eq_pairs:
        mask = mask & (rows[:, p1] == rows[:, p2])
    vals = rows[:, jnp.array(var_cols, dtype=jnp.int32)]
    vals = jnp.where(mask[:, None], vals, jnp.int32(0))
    return vals, mask


def _join_tables_impl(left_vals, left_valid, right_vals, right_valid, pairs, right_extra, capacity):
    """Un-jitted join core (callable inside shard_map)."""
    lcols = tuple(lc for lc, _ in pairs)
    rcols = tuple(rc for _, rc in pairs)
    key_l = _mix_columns(left_vals, lcols, left_valid, _SENTINEL_L)
    key_r = _mix_columns(right_vals, rcols, right_valid, _SENTINEL_R)

    order = jnp.argsort(key_r)
    key_r_sorted = key_r[order]
    method = _searchsorted_method(key_l.shape[0], key_r_sorted.shape[0])
    lo = jnp.searchsorted(key_r_sorted, key_l, side="left", method=method).astype(jnp.int32)
    hi = jnp.searchsorted(key_r_sorted, key_l, side="right", method=method).astype(jnp.int32)
    # int64 totals: sum of per-row ranges can exceed 2^31 (cross-ish joins
    # of big tables), and a wrapped negative total would silently mask
    # every output row instead of triggering the overflow retry
    cnt = (hi - lo).astype(jnp.int64)
    offsets = _cumsum_i64(cnt)
    total = offsets[-1] if cnt.shape[0] > 0 else jnp.int64(0)

    # pair expansion: output slot j belongs to left row li where
    # prev[li] <= j < offsets[li].  Instead of binary-searching offsets per
    # slot, scatter a marker at each row's start and prefix-sum — pure
    # scatter+cumsum, runs at memory speed
    j = jnp.arange(capacity, dtype=jnp.int64)
    prev_all = offsets - cnt
    row_ids = jnp.arange(cnt.shape[0], dtype=jnp.int32)
    # rows with cnt>0 own distinct start slots; empty rows scatter -1 and
    # are skipped by the running max (exactly searchsorted's side='right')
    seg = jnp.full(capacity, -1, dtype=jnp.int32).at[prev_all].max(
        jnp.where(cnt > 0, row_ids, -1), mode="drop"
    )
    li = jax.lax.cummax(seg)
    li_safe = jnp.clip(li, 0, max(left_vals.shape[0] - 1, 0))
    prev = prev_all[li_safe]
    ri_sorted = lo[li_safe] + (j - prev).astype(jnp.int32)
    ri_safe = jnp.clip(ri_sorted, 0, max(right_vals.shape[0] - 1, 0))
    ri = order[ri_safe].astype(jnp.int32)

    out_valid = j < total
    for lc, rc in pairs:
        out_valid = out_valid & (left_vals[li_safe, lc] == right_vals[ri, rc])
    out_valid = out_valid & left_valid[li_safe] & right_valid[ri]

    parts = [left_vals[li_safe]]
    if right_extra:
        parts.append(right_vals[ri][:, jnp.array(right_extra, dtype=jnp.int32)])
    out_vals = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    out_vals = jnp.where(out_valid[:, None], out_vals, jnp.int32(0))
    return out_vals, out_valid, total


def _index_join_impl(
    left_vals, left_valid, keys_sorted, perm, targets, type_key,
    pairs, right_var_cols, right_extra, capacity,
):
    """Join the left table INTO a whole-type term via the prebuilt
    (type<<32|target) positional posting index — no term-table
    materialization, no re-sort of the big side.

    The right side is implicit: every link of one type, variable columns
    at `right_var_cols` positions.  For each left row, the shared
    variable's value keys a searchsorted range in `keys_sorted` (exact —
    the packed key is injective); ranges expand positionally exactly like
    _join_tables_impl; remaining shared pairs verify against the gathered
    target columns.  This is what makes joins against multi-million-row
    whole-table terms (FlyBase scale) capacity- and compile-cheap: buffers
    scale with the JOIN OUTPUT, never with the table."""
    lc0, rc0 = pairs[0]
    type_key = jnp.asarray(type_key, jnp.int64)
    probe = jnp.where(
        left_valid,
        (type_key << 32) | left_vals[:, lc0].astype(jnp.int64),
        jnp.int64(-1),
    )
    method = _searchsorted_method(probe.shape[0], keys_sorted.shape[0])
    lo = jnp.searchsorted(keys_sorted, probe, side="left", method=method).astype(jnp.int32)
    hi = jnp.searchsorted(keys_sorted, probe, side="right", method=method).astype(jnp.int32)
    # int64: per-row ranges against an UNCAPPED whole-type term (tens of
    # millions of rows) can sum past 2^31; a wrapped total would silently
    # zero the output instead of triggering the overflow retry
    cnt = jnp.where(left_valid, hi - lo, 0).astype(jnp.int64)
    offsets = _cumsum_i64(cnt)
    total = offsets[-1] if cnt.shape[0] > 0 else jnp.int64(0)

    j = jnp.arange(capacity, dtype=jnp.int64)
    prev_all = offsets - cnt
    row_ids = jnp.arange(cnt.shape[0], dtype=jnp.int32)
    seg = jnp.full(capacity, -1, dtype=jnp.int32).at[prev_all].max(
        jnp.where(cnt > 0, row_ids, -1), mode="drop"
    )
    li = jax.lax.cummax(seg)
    li_safe = jnp.clip(li, 0, max(left_vals.shape[0] - 1, 0))
    prev = prev_all[li_safe]
    ri_sorted = lo[li_safe] + (j - prev).astype(jnp.int32)
    local = perm[jnp.clip(ri_sorted, 0, keys_sorted.shape[0] - 1)]
    row_t = targets[jnp.clip(local, 0, targets.shape[0] - 1)]

    out_valid = (j < total) & left_valid[li_safe]
    for lc, rc in pairs[1:]:
        out_valid = out_valid & (
            row_t[:, right_var_cols[rc]] == left_vals[li_safe, lc]
        )
    parts = [left_vals[li_safe]]
    if right_extra:
        parts.append(
            row_t[:, jnp.array([right_var_cols[rc] for rc in right_extra], dtype=jnp.int32)]
        )
    out_vals = jnp.concatenate(parts, axis=1) if len(parts) > 1 else parts[0]
    out_vals = jnp.where(out_valid[:, None], out_vals, jnp.int32(0))
    return out_vals, out_valid, total


def _dedup_table_impl(vals, valid):
    """Unjitted dedup body — shared by the jitted single-device wrapper
    below and the shard-local mesh path (parallel/sharded_tree.py)."""
    k = vals.shape[1]
    big = jnp.where(valid[:, None], vals, jnp.int32(2**31 - 1))
    order = jnp.lexsort([big[:, c] for c in range(k - 1, -1, -1)])
    s = big[order]
    same_as_prev = jnp.concatenate(
        [jnp.zeros((1,), dtype=bool), (s[1:] == s[:-1]).all(axis=1)]
    )
    keep = ~same_as_prev & valid[order]
    return s, keep, keep.sum(dtype=jnp.int32)


@jax.jit
def _dedup_table_jit(vals, valid):
    return _dedup_table_impl(vals, valid)


def dedup_table(vals, valid):
    """Invalidate duplicate rows (exact: lexicographic sort over all
    columns, neighbor comparison).  Returns (vals_sorted, keep, count)."""
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _dedup_table_jit(vals, valid)
