"""Sorted-index probe primitives (device kernels).

The Redis pattern/template namespaces of the reference
(redis_mongo_db.py:147-151, 235-275) become `searchsorted` range probes over
argsort permutations built at finalize time (storage/atom_table.py).  Every
probe is a fixed-capacity kernel: it returns a padded candidate vector, a
validity mask and the *exact* match count, so the host can detect capacity
overflow and retry with a doubled buffer — the standard static-shape
escape hatch under XLA.

All kernels work on bucket-local int32 row indices; int64 appears only in
the probe keys (``type_id << 32 | target_row`` — exact, collision-free).
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp

INVALID_ROW = jnp.int32(2**31 - 1)


@partial(jax.jit, static_argnames=("capacity",))
def _range_probe_jit(sorted_keys, perm, probe_key, capacity: int):
    lo = jnp.searchsorted(sorted_keys, probe_key, side="left")
    hi = jnp.searchsorted(sorted_keys, probe_key, side="right")
    count = (hi - lo).astype(jnp.int32)
    offs = jnp.arange(capacity, dtype=jnp.int32)
    valid = offs < count
    idx = jnp.clip(lo.astype(jnp.int32) + offs, 0, sorted_keys.shape[0] - 1)
    local = jnp.where(valid, perm[idx], INVALID_ROW)
    return local, valid, count


def range_probe(sorted_keys, perm, probe_key, capacity: int):
    """Bucket-local rows whose sort key equals `probe_key`.

    Returns (local[capacity] int32, valid[capacity] bool, count int32).
    """
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _range_probe_jit(sorted_keys, perm, probe_key, capacity)


@partial(jax.jit, static_argnames=("capacity",))
def _full_scan_jit(size, capacity: int):
    offs = jnp.arange(capacity, dtype=jnp.int32)
    valid = offs < size
    return jnp.where(valid, offs, INVALID_ROW), valid, jnp.int32(size)


def full_scan(size, capacity: int):
    """All bucket rows as a padded candidate vector (type-and-targets all
    wildcard probes)."""
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _full_scan_jit(size, capacity)


@partial(jax.jit, static_argnames=("fixed",))
def _verify_positions_jit(targets, type_id, local, valid, probe_type, fixed):
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    mask = valid
    mask = jnp.where(probe_type >= 0, mask & (type_id[safe] == probe_type), mask)
    for pos, val in fixed:
        mask = mask & (targets[safe, pos] == val)
    return mask


def verify_positions(targets, type_id, local, valid, probe_type, fixed: Tuple[Tuple[int, int], ...]):
    """Positional wildcard-pattern verification: keep candidates whose
    type matches `probe_type` (pass -1 to skip) and whose target columns
    equal each (position, row) pair in `fixed`."""
    from das_tpu.kernels import record_dispatch

    record_dispatch("lowered")
    return _verify_positions_jit(targets, type_id, local, valid, probe_type, fixed)


@partial(jax.jit, static_argnames=("required",))
def verify_multiset(targets, type_id, local, valid, probe_type, required: Tuple[Tuple[int, int], ...]):
    """Unordered (Set/Similarity) verification: candidate must contain each
    required target row with at least the required multiplicity."""
    pair_vals = jnp.asarray([v for v, _ in required], dtype=jnp.int32)
    pair_cnts = jnp.asarray([c for _, c in required], dtype=jnp.int32)
    return verify_multiset_traced(
        targets, type_id, local, valid, probe_type,
        pair_vals, pair_cnts, len(required),
    )


def verify_multiset_traced(
    targets, type_id, local, valid, probe_type, pair_vals, pair_cnts, n_pairs: int
):
    """`verify_multiset` with the required (value, multiplicity) pairs as
    TRACED arrays instead of static tuples, so one compiled program serves
    every probe of the same shape (only `n_pairs` is baked in)."""
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    rows = targets[safe]
    mask = valid
    mask = jnp.where(probe_type >= 0, mask & (type_id[safe] == probe_type), mask)
    for i in range(n_pairs):
        mask = mask & ((rows == pair_vals[i]).sum(axis=1) >= pair_cnts[i])
    return mask


@jax.jit
def dedup_sorted(local, valid):
    """Sort candidates by row id and invalidate duplicates (used after
    union-over-position unordered probes).  Returns (sorted_local, keep)."""
    key = jnp.where(valid, local, INVALID_ROW)
    order = jnp.argsort(key)
    s = key[order]
    first = jnp.concatenate([jnp.ones((1,), dtype=bool), s[1:] != s[:-1]])
    keep = first & (s != INVALID_ROW)
    return s, keep


@jax.jit
def count_valid(valid):
    return valid.sum(dtype=jnp.int32)
