"""Low-overhead structured trace recorder (ISSUE 12 tentpole).

One process-wide `TraceRecorder` holds a bounded ring of span/instant
events.  A trace id is born at coalescer submit (`new_trace`), rides
the submit-queue tuple to the worker, and every deeper layer —
drain/group/plan/dispatch/settle-fetch/materialize-or-cache-hit down
to answer delivery — attaches either that id or the GROUP id the
worker publishes through a thread-local (`set_context`), so a
Perfetto/Chrome-trace view can line a query's answer up with the exact
device dispatch and settle transfer that produced it.

Disabled fast path (env `DAS_TPU_TRACE`, default off): `span()` returns
ONE shared no-op context manager and `event()` returns before touching
its arguments' containers — no span objects, no ring appends, no
timestamps (tests/test_zobs.py pins the no-allocation contract
structurally).  Hot call sites (the executor dispatch halves) guard on
`enabled()` so even their attribute packing is skipped.

Timing discipline: `time.perf_counter()` only — host-monotonic, no
device sync (DL001/DL010: the dispatch halves stay sync-free; the
recorder never calls into jax).  Ring bound: env `DAS_TPU_TRACE_RING`
(default 65536 events); past it the OLDEST events drop (a long-running
service keeps the recent window, which is the one the operator asks
for).

Lock discipline (daslint DL006): every post-__init__ recorder attribute
mutation happens under `_lock` — configure/reset swap whole structures
and new_trace bumps the id counter there; the ring deque's `append` is
a single atomic op on a maxlen deque, and readers (`events()`) snapshot
under the same lock.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

#: daslint DL006 — who may mutate each piece of post-__init__ recorder
#: state.  Everything structural is serialized on `_lock` (configure /
#: reset / new_trace are cold paths; the hot path only APPENDS to the
#: maxlen ring, which is atomic under the GIL and covered by the deque
#: itself).  A new mutable attribute fails lint until it declares its
#: owner here.
LOCK_DISCIPLINE = {
    "TraceRecorder.enabled": "_lock",
    "TraceRecorder.capacity": "_lock",
    "TraceRecorder._ring": "_lock",
    "TraceRecorder._next": "_lock",
    "TraceRecorder._t_origin": "_lock",
}

WORKER_METHODS: Dict[str, Tuple[str, ...]] = {}

#: the accepted "on" spellings for obs env switches — ONE definition
#: (jaxprof's DAS_TPU_TRACE_JAX gate reuses it), so the two flags
#: cannot drift in what they accept
TRUTHY = frozenset(("1", "on", "true", "yes"))


def env_truthy(name: str, default: str = "0") -> bool:
    return os.environ.get(name, default).lower() in TRUTHY


def _env_enabled() -> bool:
    return env_truthy("DAS_TPU_TRACE")


def _env_ring() -> int:
    raw = os.environ.get("DAS_TPU_TRACE_RING")
    try:
        n = int(raw) if raw else 65536
    except ValueError:
        n = 65536
    return max(16, n)


class _NoopSpan:
    """THE disabled-path span: one shared instance, no state, no
    timestamps.  `span()` hands this back when tracing is off, so the
    disabled path allocates nothing per call."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        return False

    def set(self, **_attrs):
        """No-op attribute update (mirrors _Span.set)."""


NOOP_SPAN = _NoopSpan()


class _Span:
    """One live span: created with its start timestamp, records itself
    on __exit__.  No post-construction mutation of recorder state —
    the single ring append happens at exit."""

    __slots__ = ("_rec", "name", "trace", "attrs", "t0")

    def __init__(self, rec: "TraceRecorder", name: str, trace: int, attrs):
        self._rec = rec
        self.name = name
        self.trace = trace
        self.attrs = attrs
        self.t0 = time.perf_counter()

    def set(self, **attrs) -> None:
        """Attach attributes discovered mid-span (e.g. the drained
        width, known only after the blocking get returns)."""
        self.attrs.update(attrs)

    def __enter__(self):
        return self

    def __exit__(self, *_exc):
        self._rec.record(
            self.name, "X", self.t0,
            time.perf_counter() - self.t0, self.trace, self.attrs,
        )
        return False


class TraceRecorder:
    """Bounded ring of (name, phase, t0, dur, trace, group, lane,
    thread, attrs) event tuples plus the trace-id source and the
    worker-published thread-local context."""

    def __init__(self, enabled: Optional[bool] = None,
                 capacity: Optional[int] = None):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.capacity = _env_ring() if capacity is None else max(16, capacity)
        self._ring: deque = deque(maxlen=self.capacity)
        self._next = 0
        self._lock = threading.Lock()
        self._tls = threading.local()
        #: perf_counter origin: exported timestamps are relative to
        #: recorder construction/reset so traces start near t=0
        self._t_origin = time.perf_counter()

    # -- configuration (tests / server) ---------------------------------

    def configure(self, enabled: Optional[bool] = None,
                  capacity: Optional[int] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)
            if capacity is not None:
                self.capacity = max(16, int(capacity))
                self._ring = deque(self._ring, maxlen=self.capacity)

    def reset(self) -> None:
        with self._lock:
            self._ring = deque(maxlen=self.capacity)
            self._next = 0
            # re-base so a post-reset trace starts near t=0 (the
            # "relative to construction/reset" contract below); spans
            # already open across a reset land at negative ts — reset
            # is a window boundary, not a mid-flight operation
            self._t_origin = time.perf_counter()

    # -- trace ids + worker context --------------------------------------

    def new_trace(self) -> int:
        """A fresh trace id (monotone, process-local); 0 when disabled —
        callers thread 0 around for free and nothing records."""
        if not self.enabled:
            return 0
        with self._lock:
            self._next += 1
            return self._next

    def set_context(self, lane: Optional[str] = None,
                    group: int = 0) -> None:
        """Publish the worker's current (tenant lane, group id): deeper
        spans recorded on this THREAD (executor dispatch/settle halves,
        cache events) inherit them without signature changes.  Lane maps
        to a Perfetto track; group links a device span back to the
        submit traces it served."""
        self._tls.lane = lane
        self._tls.group = group

    def context(self) -> Tuple[Optional[str], int]:
        tls = self._tls
        return getattr(tls, "lane", None), getattr(tls, "group", 0)

    # -- recording --------------------------------------------------------

    def record(self, name: str, phase: str, t0: float, dur: float,
               trace: int, attrs, lane: Optional[str] = None) -> None:
        """`lane` overrides the thread-local context lane for events
        that belong to a dedicated Perfetto track regardless of which
        tenant's thread produced them (the proflog compile lane)."""
        if not self.enabled:
            return
        ctx_lane, group = self.context()
        th = threading.current_thread()
        self._ring.append((
            name, phase, t0 - self._t_origin, dur, trace, group,
            lane if lane is not None else ctx_lane, th.name, attrs,
        ))

    def span(self, name: str, trace: int = 0, **attrs):
        if not self.enabled:
            return NOOP_SPAN
        return _Span(self, name, trace, attrs)

    def event(self, name: str, trace: int = 0, **attrs) -> None:
        if not self.enabled:
            return
        self.record(name, "i", time.perf_counter(), 0.0, trace, attrs)

    # -- readout ----------------------------------------------------------

    def events(self) -> List[Tuple]:
        with self._lock:
            return list(self._ring)

    def __len__(self) -> int:
        return len(self._ring)
