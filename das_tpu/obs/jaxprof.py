"""Optional jax.profiler integration (ISSUE 12): named host scopes the
XLA device timeline can be lined up against.

With env `DAS_TPU_TRACE_JAX=1`, `annotation(name)` wraps a block in
`jax.profiler.TraceAnnotation` — the dispatch and settle halves use it
so a captured device trace (Perfetto, via `jax.profiler.start_trace`)
shows which host-side dispatch enqueued which device program and where
the settle fetch sat relative to device execution.  Off (the default)
it returns ONE shared null context: no jax import, no allocation — the
recorder's disabled-path contract.

`maybe_start_trace(config)` / `maybe_stop_trace()` plumb
`DasConfig.profiler_trace_dir` (env `DAS_TPU_TRACE_DIR`) through to
`jax.profiler.start_trace`/`stop_trace`: the hardware-closeout runbook
is "set DAS_TPU_TRACE=1 DAS_TPU_TRACE_JAX=1 DAS_TPU_TRACE_DIR=/tmp/tb,
run the workload, open both the obs trace and the device trace in
Perfetto" (ARCHITECTURE §13).
"""

from __future__ import annotations

import os

from das_tpu.obs.recorder import NOOP_SPAN, TRUTHY

_started = {"dir": None}

#: memoized on the RAW env string: annotation() sits on the dispatch
#: and settle-fetch hot paths outside the obs.enabled() guard, so the
#: disabled path must cost one environ dict lookup — not a str.lower
#: + tuple scan per device-program enqueue.  A changed env value
#: (tests monkeypatch it) re-evaluates because the raw string moves.
_gate = {"raw": object(), "on": False}


def jax_annotations_enabled() -> bool:
    raw = os.environ.get("DAS_TPU_TRACE_JAX")
    if raw != _gate["raw"]:
        _gate["raw"] = raw
        _gate["on"] = (raw or "0").lower() in TRUTHY
    return _gate["on"]


def annotation(name: str):
    """A jax.profiler.TraceAnnotation when DAS_TPU_TRACE_JAX is on,
    else the shared no-op context.  Span names are registry members
    (obs/registry.py, DL014) so host trace and device trace agree on
    vocabulary."""
    if not jax_annotations_enabled():
        return NOOP_SPAN
    import jax

    return jax.profiler.TraceAnnotation(name)


def maybe_start_trace(config=None) -> bool:
    """Start a jax.profiler trace into `config.profiler_trace_dir` when
    configured (idempotent — a second call with a trace running is a
    no-op).  Returns True when a trace is running."""
    trace_dir = getattr(config, "profiler_trace_dir", None)
    if not trace_dir:
        return False
    if _started["dir"] is not None:
        return True
    import jax

    jax.profiler.start_trace(trace_dir)
    _started["dir"] = trace_dir
    return True


def maybe_stop_trace() -> bool:
    """Stop the running jax.profiler trace, if any."""
    if _started["dir"] is None:
        return False
    import jax

    jax.profiler.stop_trace()
    _started["dir"] = None
    return True
