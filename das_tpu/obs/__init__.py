"""das_tpu.obs — structured per-query tracing + typed metrics (ISSUE 12).

The serving engine's window into itself: a trace id born at coalescer
submit threads through drain/group/plan/dispatch/settle-fetch/
materialize-or-cache-hit to answer delivery, each stage recording a
host-monotonic span into a bounded ring (obs/recorder.py), while the
metric layer (obs/metrics.py) keeps counters and fixed log-bucket
latency histograms that answer p50/p95/p99 without sample retention.
Exporters (obs/export.py) render the ring as Perfetto-loadable Chrome
trace JSON (`scripts/dump_trace.py`) and the metrics as Prometheus
text exposition (service/server.py `metrics_text`); obs/jaxprof.py
optionally wraps the dispatch/settle halves in
`jax.profiler.TraceAnnotation` so host spans line up with the XLA
device timeline on hardware runs.

Everything is behind env `DAS_TPU_TRACE` (default OFF) with a
no-allocation disabled fast path: `span()` returns one shared no-op
context, `event()`/`mark()` return immediately, `new_trace()` returns
0.  Span/metric names are a closed declared set (obs/registry.py,
daslint rule DL014).  ARCHITECTURE §13 is the operator story.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

from das_tpu.obs import metrics as metrics  # noqa: F401 — public surface
from das_tpu.obs.export import (  # noqa: F401
    chrome_trace,
    dump_chrome_trace,
    prometheus_text,
)
from das_tpu.obs.jaxprof import (  # noqa: F401
    annotation,
    maybe_start_trace,
    maybe_stop_trace,
)
from das_tpu.obs.metrics import (  # noqa: F401
    counter,
    histogram,
    reset_metrics,
)
from das_tpu.obs.recorder import NOOP_SPAN, TraceRecorder  # noqa: F401
from das_tpu.obs.registry import (  # noqa: F401
    COUNTER_NAMES,
    HISTOGRAM_NAMES,
    SPAN_NAMES,
)

# the program ledger (ISSUE 14) — imported after the metric layer it
# records into; gated by its OWN env (DAS_TPU_PROFLOG), not DAS_TPU_TRACE
from das_tpu.obs import proflog as proflog  # noqa: F401, E402

#: THE process recorder — env-initialized, reconfigurable for tests and
#: long-running services (obs.configure)
REC = TraceRecorder()


def enabled() -> bool:
    """Hot-path guard: call sites that would otherwise pack attribute
    dicts (the executor dispatch halves) check this first so the
    disabled path costs one attribute read."""
    return REC.enabled


def configure(enabled: Optional[bool] = None,
              capacity: Optional[int] = None) -> None:
    REC.configure(enabled=enabled, capacity=capacity)


def reset() -> None:
    """Drop the ring and zero the metric layer (bench/test arms start
    from a clean window)."""
    REC.reset()
    reset_metrics()


def span(name: str, trace: int = 0, **attrs):
    """Context manager recording one complete span; the shared no-op
    when tracing is off.  `name` must be an obs/registry.py member
    (daslint DL014)."""
    return REC.span(name, trace, **attrs)


def event(name: str, trace: int = 0, **attrs) -> None:
    """One instant event; no-op when tracing is off."""
    REC.event(name, trace, **attrs)


def new_trace() -> int:
    return REC.new_trace()


def set_context(lane: Optional[str] = None, group: int = 0) -> None:
    REC.set_context(lane, group)


def mark() -> Optional[Tuple[int, float]]:
    """Birth certificate of one traced unit of work: (fresh trace id,
    perf_counter now) — or None when tracing is off, so carrying a mark
    through a queue costs nothing on the disabled path.  The coalescer
    attaches one per submitted query; answer delivery closes it
    (serve.answer event + serve.answer_ms histogram)."""
    if not REC.enabled:
        return None
    return REC.new_trace(), time.perf_counter()


def events():
    return REC.events()
