"""dasprof — the program ledger (ISSUE 14 tentpole).

Every PR since BENCH_r05 has been held on CPU A/Bs: the engine compiles
whole-plan programs, prices their VMEM by hand (kernels/budget.py), and
records nothing about what XLA actually did — compile wall time, FLOPs,
bytes accessed, HBM footprint are all dark.  This module closes the
device side of the observability story (dastrace, ARCHITECTURE §13,
closed the host side): a bounded per-signature **program ledger** that
records, for every instrumented jitted entry point, the first-compile
wall time plus the AOT `jax.jit(...).lower(...).compile()` statistics
where the backend provides them — `cost_analysis()` flops /
bytes-accessed and `memory_analysis()` argument / output / temp / peak
bytes — keyed by the plan-signature digest the executor caches already
use.

How instrumentation works: the program builders (`build_fused`,
`build_fused_tree`, `build_fused_exact`, the count-batch/count-loop
sites, and the sharded twins) pass their freshly-jitted callable through
`instrument(site, digest, fn)`.  Disabled (`DAS_TPU_PROFLOG` unset — the
default), `instrument` returns `fn` ITSELF: the serving path is
byte-for-byte the pre-ledger path (tests/test_zprof.py pins the
identity), no wrapper objects, no per-call overhead — the dastrace
no-allocation idiom.  Enabled, the returned `_InstrumentedProgram`
AOT-compiles on first call per argument-shape signature (`lower()` +
`compile()` — the SAME executable `jax.jit` would build, so answers are
bit-identical), records the ledger entry, and serves subsequent calls
from the compiled object (a "ledger hit").  Any AOT failure — an
exotic argument tree, a backend without AOT support — falls back to the
plain jitted path and records the error string instead of raising:
the ledger can cost accuracy, never answers.  Calls that arrive with
TRACER arguments (the count-loop body re-enters `build_fused`'s program
inside its own jit; `jax.eval_shape` probes it) delegate straight to
the jitted fn — a program nested inside another program is priced by
its parent's ledger entry.

Pallas launches (`kernels/common.py run_kernel / run_grid_kernel`) are
not separately AOT-compilable — they trace INSIDE a caller's program —
so they record a lighter `record_launch` note instead: launch counts
and per-launch trace wall time per (body, shape) key, kind "pallas" or
"discharge".  Trace wall is host tracing cost, NOT XLA compile time,
and the ledger keeps the two in separate columns.

Two consumers close standing ROADMAP loops:

  * **byte-model calibration** — builders pass a `model_bytes` callback
    (kernels/budget.py's combined per-stage footprint, the number the
    single/tiled/lowered route gate is decided on); the ledger divides
    it by the XLA `memory_analysis` actual (temp + output bytes) into
    `budget_vs_actual_ratio` per program shape — the planner's
    est-vs-actual idiom applied to memory.  On CPU the "actual" is XLA's
    host heap, so the CPU ratio is a sanity signal only; the
    calibration contract is for TPU runs (ARCHITECTURE §15).
  * **cold-start accounting** — a jax monitoring listener classifies
    each compile as fresh or served by the persistent XLA cache
    (`DAS_TPU_XLA_CACHE`); `snapshot()["cold_start_s"]` sums the wall
    time of the FRESH compiles only — the time-to-first-answer compile
    cost a warm replica (ROADMAP replica-fleet item) would not pay.

`PROGRAM_SITES` below is the closed registry of every scope in das_tpu/
that constructs a device program (`jax.jit` / `pl.pallas_call`), mapping
each to its ledger site label or None for declared-exempt scopes.
daslint rule DL016 pins it both ways against the actual program
construction sites — a new jit/pallas call in an undeclared scope fails
lint, an instrumented scope without its ledger hook fails lint, and a
stale entry fails full runs (the DL013 FETCH_SITES idiom).

Thread/lock discipline (daslint DL006): ledger mutation is serialized
on `_lock` (compiles are seconds-scale; the lock is noise), and the
per-compile persistent-cache event counters live in a THREAD-LOCAL so
concurrent tenant compiles cannot attribute each other's cache hits.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from das_tpu.obs.recorder import TRUTHY

#: daslint DL006 — post-__init__ ledger state owners.  Everything is
#: serialized on `_lock`; `enabled` flips only via configure() (tests /
#: bench arms).
LOCK_DISCIPLINE = {
    "ProgramLedger.enabled": "_lock",
    "ProgramLedger.capacity": "_lock",
    "ProgramLedger.entries": "_lock",
    "ProgramLedger.compiles": "_lock",
    "ProgramLedger.compile_s": "_lock",
    "ProgramLedger.cold_start_s": "_lock",
    "ProgramLedger.persistent_cache_hits": "_lock",
    "ProgramLedger.calls": "_lock",
    "ProgramLedger.hits": "_lock",
    "ProgramLedger.errors": "_lock",
    "ProgramLedger.launches": "_lock",
    "ProgramLedger._listener_on": "_lock",
    "_InstrumentedProgram._compiled": "_lock",
}

WORKER_METHODS: Dict[str, Tuple[str, ...]] = {}

#: THE closed registry of program-construction scopes (daslint DL016,
#: the DL013 FETCH_SITES idiom): every scope in das_tpu/ whose AST
#: references `jax.jit` or `pl.pallas_call`, attributed to its
#: OUTERMOST enclosing function ("module.func" / "module.Class.meth").
#: Value = the ledger site label the scope must pass to
#: `instrument(...)` / `record_launch(...)`, or None for
#: declared-exempt scopes — programs that either trace INSIDE an
#: instrumented program (the kernel impl wrappers), are per-op staged
#: programs already counted by DISPATCH_COUNTS, or are cold index/
#: bootstrap programs outside the serving path.  An entry here is a
#: reviewed decision; a jit call in an UNdeclared scope fails lint.
PROGRAM_SITES: Dict[str, Optional[str]] = {
    # -- instrumented: the whole-plan program builders -------------------
    "fused.build_fused": "fused",
    "fused.build_fused_tree": "fused_tree",
    "fused.build_fused_exact": "fused_exact",
    "fused.FusedExecutor._run_batch_group": "count_batch",
    "fused.FusedExecutor.build_count_loop": "count_loop",
    "fused_sharded._ShardedExecJob.dispatch": "sharded",
    "fused_sharded._ShardedTreeExecJob._build": "sharded_tree",
    # -- instrumented: the Pallas launch points (trace-wall notes) -------
    "common.run_kernel": "kernel",
    "common.run_grid_kernel": "kernel_grid",
    # -- declared-exempt: staged-path per-op programs (ops/posting.py,
    #    ops/join.py — one generic op each, counted by DISPATCH_COUNTS
    #    "lowered"; the staged pipeline is the retry/fallback tier, not
    #    the serving hot path) -------------------------------------------
    "posting._range_probe_jit": None,
    "posting._full_scan_jit": None,
    "posting._verify_positions_jit": None,
    "posting.verify_multiset": None,
    "posting.dedup_sorted": None,
    "posting.count_valid": None,
    "join._join_tables_jit": None,
    "join._anti_join_jit": None,
    "join._build_term_table_jit": None,
    "join._dedup_table_jit": None,
    # -- declared-exempt: kernel single-dispatch wrappers (their bodies
    #    trace INSIDE callers' programs on the fused route; standalone
    #    staged launches are counted by DISPATCH_COUNTS "kernel") -------
    "probe.probe_term_table_jit": None,
    "join.join_tables_jit": None,
    "join.anti_join_jit": None,
    # -- declared-exempt: star-count degree fold programs (count-only
    #    fast path, host-side fold by default — query/starcount.py) -----
    "starcount._deg_vector": None,
    "starcount._scatter_deg": None,
    "starcount._gather_col": None,
    "starcount._star_fold": None,
    # -- declared-exempt: store build/commit programs (ingest/commit
    #    time, outside query serving) -----------------------------------
    "tensor_db._merge_padded": None,
    "tensor_db._insert_rows": None,
    "sharded_db.ShardedTables.stage_delta": None,
}

#: ledger entry bound: past it the OLDEST entries drop (the recorder's
#: ring idiom — a long-running service keeps the recent window)
_MAX_ENTRIES = 1024


def _env_enabled() -> bool:
    return os.environ.get("DAS_TPU_PROFLOG", "0").lower() in TRUTHY


def sig_digest(*parts) -> str:
    """Stable digest of a plan signature (plus variant discriminators
    like count_only) — the executor-cache keys are frozen dataclasses
    with deterministic reprs, so this is the same identity the compiled
    -program caches already key on, folded to 16 hex chars."""
    return hashlib.md5(repr(parts).encode()).hexdigest()[:16]


class ProgramLedger:
    """Bounded map of (site, digest) -> per-program compile/cost/memory
    record, plus the aggregate counters coalescer_stats()["programs"]
    surfaces."""

    def __init__(self, enabled: Optional[bool] = None):
        self.enabled = _env_enabled() if enabled is None else bool(enabled)
        self.capacity = _MAX_ENTRIES
        self.entries: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self.compiles = 0
        self.compile_s = 0.0
        self.cold_start_s = 0.0
        self.persistent_cache_hits = 0
        self.calls = 0
        self.hits = 0
        self.errors = 0
        self.launches = 0
        # reentrant: record_* hold it while _entry takes it again (the
        # lexical with-block is what DL006 pins)
        self._lock = threading.RLock()
        self._tls = threading.local()
        self._listener_on = False

    # -- configuration ---------------------------------------------------

    def configure(self, enabled: Optional[bool] = None) -> None:
        with self._lock:
            if enabled is not None:
                self.enabled = bool(enabled)

    def reset(self) -> None:
        with self._lock:
            self.entries = {}
            self.compiles = 0
            self.compile_s = 0.0
            self.cold_start_s = 0.0
            self.persistent_cache_hits = 0
            self.calls = 0
            self.hits = 0
            self.errors = 0
            self.launches = 0

    # -- persistent-XLA-cache hit classification -------------------------

    def _ensure_listener(self) -> None:
        """Register ONE process-wide jax monitoring listener that feeds
        the calling thread's compile-window counters.  Private-API
        guarded: if the monitoring module moves, every compile simply
        classifies as fresh (cold_start_s upper-bounds, never lies
        low)."""
        if self._listener_on:
            return
        try:
            from jax._src import monitoring

            def _on_event(name: str, **_kw) -> None:
                win = getattr(self._tls, "cache_window", None)
                if win is None:
                    return
                if name == "/jax/compilation_cache/cache_hits":
                    win["hits"] += 1
                elif name == "/jax/compilation_cache/cache_misses":
                    win["misses"] += 1

            monitoring.register_event_listener(_on_event)
        except Exception:
            pass
        with self._lock:
            self._listener_on = True

    def _open_cache_window(self) -> None:
        self._ensure_listener()
        self._tls.cache_window = {"hits": 0, "misses": 0}

    def _close_cache_window(self) -> bool:
        """True = this compile was served by the persistent XLA cache:
        more cache-hit than cache-miss events in the window.  Majority
        vote, not all-hits — one executable triggers several
        sub-compiles (convert_element_type and friends) and a single
        cold helper must not reclassify a warm main program."""
        win = getattr(self._tls, "cache_window", None)
        self._tls.cache_window = None
        return bool(win and win["hits"] > win["misses"])

    # -- recording --------------------------------------------------------

    def _entry(self, site: str, digest: str, kind: str) -> Dict[str, Any]:
        with self._lock:
            key = (site, digest)
            e = self.entries.get(key)
            if e is not None:
                return e
            if len(self.entries) >= self.capacity:
                # drop oldest (insertion order) — recorder ring idiom
                self.entries.pop(next(iter(self.entries)))
            e = {
                "site": site,
                "digest": digest,
                "kind": kind,
                "compiles": 0,
                "compile_s": 0.0,
                "first_compile_s": None,
                "persistent_cache_hit": False,
                "flops": None,
                "bytes_accessed": None,
                "arg_bytes": None,
                "out_bytes": None,
                "temp_bytes": None,
                "peak_bytes": None,
                "modeled_bytes": None,
                "budget_vs_actual_ratio": None,
                "calls": 0,
                "hits": 0,
                "launches": 0,
                "trace_s": 0.0,
                "error": None,
            }
            self.entries[key] = e
            return e

    def record_compile(
        self, site: str, digest: str, wall_s: float,
        cost: Optional[Dict[str, float]],
        mem: Optional[Any],
        persistent_hit: bool,
        modeled_bytes: Optional[int],
    ) -> None:
        with self._lock:
            e = self._entry(site, digest, "jit")
            e["compiles"] += 1
            e["compile_s"] += wall_s
            if e["first_compile_s"] is None:
                e["first_compile_s"] = wall_s
            e["persistent_cache_hit"] = persistent_hit
            if cost:
                e["flops"] = cost.get("flops")
                e["bytes_accessed"] = cost.get("bytes accessed")
            if mem is not None:
                arg = getattr(mem, "argument_size_in_bytes", None)
                out = getattr(mem, "output_size_in_bytes", None)
                tmp = getattr(mem, "temp_size_in_bytes", None)
                ali = getattr(mem, "alias_size_in_bytes", 0) or 0
                e["arg_bytes"] = arg
                e["out_bytes"] = out
                e["temp_bytes"] = tmp
                if out is not None and tmp is not None:
                    # peak live-at-once estimate: outputs + temporaries
                    # (+ aliased) — arguments are the caller's resident
                    # store, not this program's allocation
                    e["peak_bytes"] = out + tmp + ali
            if modeled_bytes:
                e["modeled_bytes"] = int(modeled_bytes)
                actual = e["peak_bytes"]
                if actual:
                    # the planner's est-vs-actual idiom applied to
                    # memory: modeled combined kernel footprint over the
                    # XLA-reported allocation (§15 calibration contract)
                    e["budget_vs_actual_ratio"] = round(
                        int(modeled_bytes) / actual, 4
                    )
            self.compiles += 1
            self.compile_s += wall_s
            if persistent_hit:
                self.persistent_cache_hits += 1
            else:
                self.cold_start_s += wall_s
        from das_tpu import obs

        obs.counter("prof.compiles").inc()
        obs.histogram("prof.compile_ms").observe(wall_s * 1e3)
        # the compile lane (scripts/dump_trace.py): when dastrace is on
        # too, each compile lands as a span in a dedicated "compile"
        # Perfetto lane, duration = the wall time recorded above
        obs.REC.record(
            "prof.compile", "X", time.perf_counter() - wall_s, wall_s, 0,
            {"site": site, "digest": digest,
             "persistent_cache_hit": persistent_hit},
            lane="compile",
        )

    def record_error(self, site: str, digest: str, err: BaseException) -> None:
        with self._lock:
            e = self._entry(site, digest, "jit")
            e["error"] = repr(err)[:200]
            self.errors += 1

    def record_call(self, site: str, digest: str, hit: bool) -> None:
        with self._lock:
            e = self._entry(site, digest, "jit")
            e["calls"] += 1
            self.calls += 1
            if hit:
                e["hits"] += 1
                self.hits += 1

    def record_launch(
        self, site: str, digest: str, kind: str, wall_s: float
    ) -> None:
        with self._lock:
            e = self._entry(site, digest, kind)
            e["launches"] += 1
            e["trace_s"] += wall_s
            self.launches += 1

    # -- readout ----------------------------------------------------------

    def rows(
        self, site: Optional[str] = None, digest: Optional[str] = None
    ) -> List[Dict[str, Any]]:
        with self._lock:
            out = []
            for e in self.entries.values():
                if site is not None and e["site"] != site:
                    continue
                if digest is not None and e["digest"] != digest:
                    continue
                out.append(dict(e))
            return out

    def snapshot(self) -> Dict[str, Any]:
        """The coalescer_stats()["programs"] surface: compiles, total
        compile seconds, ledger hit rate, the cold-start decomposition,
        and the per-site budget-vs-actual calibration aggregate."""
        with self._lock:
            ratios: Dict[str, List[float]] = {}
            for e in self.entries.values():
                r = e["budget_vs_actual_ratio"]
                if r is not None:
                    ratios.setdefault(e["site"], []).append(r)
            return {
                "enabled": self.enabled,
                "compiles": self.compiles,
                "compile_s": round(self.compile_s, 4),
                "calls": self.calls,
                "ledger_hits": self.hits,
                "hit_rate": round(self.hits / self.calls, 4)
                if self.calls else None,
                "cold_start_s": round(self.cold_start_s, 4),
                "persistent_cache_hits": self.persistent_cache_hits,
                "errors": self.errors,
                "launches": self.launches,
                "entries": len(self.entries),
                "budget_vs_actual": {
                    site: round(sum(rs) / len(rs), 4)
                    for site, rs in sorted(ratios.items())
                },
            }


#: THE process ledger — env-initialized, reconfigurable (tests/bench)
LEDGER = ProgramLedger()


def enabled() -> bool:
    return LEDGER.enabled


def configure(enabled: Optional[bool] = None) -> None:
    LEDGER.configure(enabled=enabled)


def reset() -> None:
    LEDGER.reset()


def snapshot() -> Dict[str, Any]:
    return LEDGER.snapshot()


def rows(site: Optional[str] = None,
         digest: Optional[str] = None) -> List[Dict[str, Any]]:
    return LEDGER.rows(site=site, digest=digest)


def compile_totals() -> Tuple[int, float]:
    """(compiles, compile seconds) — the bench sections' delta basis."""
    return LEDGER.compiles, LEDGER.compile_s


def compile_delta(before: Tuple[int, float]) -> Dict[str, Any]:
    """Per-section ledger delta for the bench records: programs
    compiled and compile seconds paid since `before`
    (= compile_totals() at section start)."""
    c0, s0 = before
    return {
        "programs_compiled": LEDGER.compiles - c0,
        "compile_s": round(LEDGER.compile_s - s0, 3),
    }


class _InstrumentedProgram:
    """One instrumented jitted program: AOT-compiles per argument-shape
    signature, records the ledger entry, serves repeat calls from the
    compiled executable.  Never raises on ledger business: every
    failure path delegates to the plain jitted fn."""

    __slots__ = ("site", "digest", "fn", "model_bytes", "_compiled",
                 "_lock")

    def __init__(self, site: str, digest: str, fn,
                 model_bytes: Optional[Callable] = None):
        self.site = site
        self.digest = digest
        self.fn = fn
        self.model_bytes = model_bytes
        self._compiled: Dict[Tuple, Any] = {}
        self._lock = threading.Lock()

    def _shape_key(self, leaves) -> Optional[Tuple]:
        """Abstract signature of the call's argument leaves, or None
        when any leaf is a tracer (we are INSIDE someone else's trace —
        the nested program is priced by its parent's entry)."""
        import jax

        key = []
        for leaf in leaves:
            if isinstance(leaf, jax.core.Tracer):
                return None
            shape = getattr(leaf, "shape", None)
            if shape is not None:
                key.append((tuple(shape), str(getattr(leaf, "dtype", ""))))
            else:
                key.append(("py", type(leaf).__name__))
        return tuple(key)

    def _aot_compile(self, key: Tuple, args: Tuple):
        """lower().compile() with the ledger bookkeeping; None on any
        failure (the caller falls back to the jitted path)."""
        led = LEDGER
        led._open_cache_window()
        t0 = time.perf_counter()
        try:
            compiled = self.fn.lower(*args).compile()
        except Exception as err:
            led._close_cache_window()
            led.record_error(self.site, self.digest, err)
            return None
        wall = time.perf_counter() - t0
        persistent_hit = led._close_cache_window()
        cost: Optional[Dict[str, float]] = None
        mem = None
        try:
            ca = compiled.cost_analysis()
            cost = ca[0] if isinstance(ca, (list, tuple)) else ca
        except Exception:
            pass
        try:
            mem = compiled.memory_analysis()
        except Exception:
            pass
        modeled = None
        if self.model_bytes is not None:
            try:
                modeled = self.model_bytes(*args)
            except Exception:
                modeled = None
        led.record_compile(
            self.site, self.digest, wall, cost, mem, persistent_hit,
            modeled,
        )
        return compiled

    def __call__(self, *args):
        led = LEDGER
        if not led.enabled:
            return self.fn(*args)
        import jax

        leaves = jax.tree_util.tree_leaves(args)
        key = self._shape_key(leaves)
        if key is None:
            return self.fn(*args)
        compiled = self._compiled.get(key)
        hit = compiled is not None
        if compiled is None:
            # the compile itself runs under the wrapper lock: two
            # tenants racing the same uncached shape must not each pay
            # a seconds-scale duplicate AOT compile (and double-count
            # the ledger) — the loser of the race re-checks and hits
            with self._lock:
                compiled = self._compiled.get(key)
                hit = compiled is not None
                if compiled is None:
                    compiled = self._aot_compile(key, args)
                    if compiled is not None:
                        self._compiled[key] = compiled
            if compiled is None:
                return self.fn(*args)
        led.record_call(self.site, self.digest, hit=hit)
        try:
            return compiled(*args)
        except Exception:
            # an AOT-compiled executable is stricter about argument
            # placement than jit; never let that strictness cost an
            # answer — drop to the jitted path and stop using the entry
            with self._lock:
                self._compiled.pop(key, None)
            return self.fn(*args)


def instrument(site: str, digest: str, fn,
               model_bytes: Optional[Callable] = None):
    """Route one freshly-jitted program through the ledger.

    DISABLED (the default): returns `fn` unchanged — `instrument(s, d,
    fn) is fn` is the identity contract tests/test_zprof.py pins; the
    serving path allocates nothing and dispatch halves stay exactly the
    pre-ledger code (DL001/DL010).  Enabled: returns the AOT-compiling
    wrapper.  `site` must be a PROGRAM_SITES label (daslint DL016 pins
    the literal at the call site)."""
    if not LEDGER.enabled:
        return fn
    return _InstrumentedProgram(site, digest, fn, model_bytes)


def launch_mark() -> float:
    """perf_counter origin for a record_launch note; 0.0 when the
    ledger is off so the disabled path pays one attribute read and no
    clock call."""
    if not LEDGER.enabled:
        return 0.0
    return time.perf_counter()


def record_launch(site: str, body, out_shapes, t0: float,
                  pallas: bool) -> None:
    """Note one Pallas kernel launch (kernels/common.py): per-(body,
    shape) launch counts and trace wall time — kind "pallas" for a real
    pallas_call, "discharge" for the off-TPU direct-discharge path.
    Trace wall is host tracing cost, kept apart from compile_s.  No-op
    (one attribute read) when the ledger is off."""
    if not LEDGER.enabled or not t0:
        return
    wall = time.perf_counter() - t0
    digest = sig_digest(getattr(body, "__name__", repr(body)), out_shapes)
    LEDGER.record_launch(
        site, digest, "pallas" if pallas else "discharge", wall
    )
