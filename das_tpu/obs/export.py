"""Trace/metric exporters: Chrome trace-event JSON (Perfetto-loadable)
and Prometheus text exposition.

Chrome trace format (the subset Perfetto ingests): one "X" complete
event per span and one "i" instant event per point event, timestamps
and durations in MICROseconds, `pid` = the tenant lane and `tid` = the
recording thread — so the Perfetto timeline renders one process row
per tenant with one track per worker/RPC thread, and the submit →
drain → dispatch → settle → answer cascade reads left-to-right on the
worker track.  "M" metadata events name the lanes/threads; trace and
group ids ride in `args` so a flow can be followed by query.

Prometheus text exposition (the service/server.py hook): counters as
`das_tpu_obs_<name>_total`, histograms in the native histogram triple
(`_bucket{le=...}` cumulative, `_sum`, `_count`) — scrape-ready,
derivable p50/p95/p99 via `histogram_quantile`.
"""

from __future__ import annotations

import json
from typing import Dict, List, Optional, Tuple

from das_tpu.obs import metrics as _metrics


def chrome_trace(events: List[Tuple]) -> Dict:
    """Render recorder event tuples (TraceRecorder.events()) into a
    Chrome trace-event dict — `json.dumps` of it loads in Perfetto /
    chrome://tracing."""
    lanes: Dict[Optional[str], int] = {}
    threads: Dict[str, int] = {}
    out: List[Dict] = []
    for name, phase, t0, dur, trace, group, lane, thread, attrs in events:
        pid = lanes.setdefault(lane, len(lanes) + 1)
        tid = threads.setdefault(thread, len(threads) + 1)
        args = dict(attrs) if attrs else {}
        if trace:
            args["trace"] = trace
        if group:
            args["group"] = group
        ev = {
            "name": name,
            "ph": phase,
            "ts": round(t0 * 1e6, 3),
            "pid": pid,
            "tid": tid,
            "args": args,
        }
        if phase == "X":
            ev["dur"] = round(dur * 1e6, 3)
        else:
            ev["s"] = "t"  # thread-scoped instant
        out.append(ev)
    meta: List[Dict] = []
    for lane, pid in lanes.items():
        meta.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": lane or "das_tpu"},
        })
    for thread, tid in threads.items():
        for pid in lanes.values():
            meta.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": thread},
            })
    return {"traceEvents": meta + out, "displayTimeUnit": "ms"}


def dump_chrome_trace(events: List[Tuple], path: str) -> str:
    """Write the Perfetto-loadable JSON to `path`; returns the path."""
    with open(path, "w") as f:
        json.dump(chrome_trace(events), f)
    return path


def _prom_name(name: str) -> str:
    return "das_tpu_obs_" + name.replace(".", "_").replace("-", "_")


def prometheus_text(extra_gauges: Optional[Dict[str, float]] = None) -> str:
    """The metric layer in Prometheus text exposition format.  The
    serving facade (service/server.py metrics_text) folds its aggregate
    coalescer gauges in via `extra_gauges` — one scrape surface for the
    whole serving path."""
    lines: List[str] = []
    for name, c in sorted(_metrics.COUNTERS.items()):
        pn = _prom_name(name) + "_total"
        lines.append(f"# TYPE {pn} counter")
        lines.append(f"{pn} {c.value}")
    for name, h in sorted(_metrics.HISTOGRAMS.items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} histogram")
        cum = 0
        for upper, count in h.nonzero_buckets():
            cum += count
            lines.append(f'{pn}_bucket{{le="{upper:g}"}} {cum}')
        lines.append(f'{pn}_bucket{{le="+Inf"}} {h.total}')
        lines.append(f"{pn}_sum {h.sum_ms:g}")
        lines.append(f"{pn}_count {h.total}")
    for name, value in sorted((extra_gauges or {}).items()):
        pn = _prom_name(name)
        lines.append(f"# TYPE {pn} gauge")
        lines.append(f"{pn} {value:g}")
    return "\n".join(lines) + "\n"
