"""Central registry of span/metric names (daslint DL014, ISSUE 12).

The `ops/counters.py` idiom applied to the trace/metric layer: every
span or instant-event name passed to `obs.span(...)` / `obs.event(...)`
/ `obs.annotation(...)`, every counter name passed to `obs.counter(...)`
and every histogram name passed to `obs.histogram(...)` anywhere in
`das_tpu/` must be a member of these tuples — the metric dicts
(obs/metrics.py COUNTERS / HISTOGRAMS) are BUILT from them, the
analyzer (das_tpu/analysis, rule DL014) pins every literal against them
in both directions (an undeclared literal fires; a declared name with
no call site is a stale entry on full-set runs), and tests/test_zobs.py
pins the tuples themselves so a rename cannot slip through unreviewed.

A typo'd name would otherwise trace into a lane nobody watches while
the dashboards / Perfetto queries keyed on the declared name stay
silent — the exact failure mode DL004 closed for the dispatch counters.

This module imports nothing — the recorder, the metric layer, the
exporters and the analyzer's fixtures can all depend on it without
cycles.
"""

#: every span ("X" complete event) and instant-event name the recorder
#: accepts.  Naming: `<layer>.<stage>` — the serving pipeline's
#: lifecycle stages (service/coalesce.py + api/atomspace.py), the
#: executor halves (query/fused.py + parallel/fused_sharded.py), the
#: delta-versioned caches, the commit path, and the planner's
#: est-vs-actual observation.
SPAN_NAMES = (
    #: instant: one query accepted into the coalescer submit queue
    #: (service/coalesce.py submit) — the trace id is born here
    "serve.submit",
    #: instant: backpressure rejection at the queue bound
    "serve.reject",
    #: span: one worker drain — attrs: width limit, queries drained
    "serve.drain",
    #: span: drained batch split into (tenant, format) groups
    "serve.group",
    #: span: per-group query planning (api/atomspace.py _QueryManyJob)
    "serve.plan",
    #: span: per-group device enqueue under the tenant lock — attrs:
    #: group width, speculative flag, effective depth, dispatch EWMA
    "serve.dispatch",
    #: span: per-group streamed settle — attrs: streamed/fallback
    #: counts, settle rtt
    "serve.settle",
    #: instant: one query's future resolved — closes the trace id
    #: opened at serve.submit
    "serve.answer",
    #: span: one job's device-program enqueue (query/fused.py _ExecJob
    #: and _TreeExecJob dispatch halves + the sharded twins) — attrs:
    #: route, rounds, planner est rows
    "exec.dispatch",
    #: span: one settle round's host transfer — the tunnel RTT
    #: (query/fused.py settle_pending_iter, DL013's one-transfer site)
    "exec.settle_fetch",
    #: span: binding table -> frozen assignments (query/compiler.py)
    "exec.materialize",
    #: instants: delta-versioned result/tree/count cache traffic
    #: (query/fused.py ResultCache)
    "cache.hit",
    "cache.miss",
    "cache.invalidate",
    #: instants: commit-path delta_version bumps (storage/delta.py) —
    #: incremental commit vs full rebuild
    "commit.delta",
    "commit.rebuild",
    #: instant: planner est-vs-actual at job settle (das_tpu/planner)
    "planner.observe",
    #: instant: one query expired past its serving deadline
    #: (service/coalesce.py, DasConfig.query_deadline_ms)
    "serve.deadline",
    #: instant: tenant circuit-breaker state transition — attrs: frm/to
    #: (das_tpu/fault CircuitBreaker; closed/open/half_open)
    "serve.breaker",
    #: instant: one injected fault fired at a FAULT_SITES seam
    #: (das_tpu/fault maybe_fail, ISSUE 13)
    "fault.inject",
    #: span: one XLA program compile observed by the program ledger
    #: (das_tpu/obs/proflog.py, ISSUE 14) — rendered in a dedicated
    #: "compile" Perfetto lane; attrs carry site/digest and whether the
    #: persistent XLA cache served it
    "prof.compile",
    #: span: one atomic generational snapshot write (storage/durable.py
    #: write_snapshot, ISSUE 15) — attrs: generation, delta_version
    "dur.snapshot",
    #: span: one warm-state restore — newest valid generation + WAL
    #: replay + warm bundle (storage/durable.py restore)
    "dur.restore",
    #: instant: one write-ahead delta-log record appended + fsynced
    #: (storage/durable.py DeltaLog.append) — attrs: version, kind,
    #: framed bytes
    "dur.wal_append",
    #: instant: a torn WAL tail record truncated at the last valid
    #: frame boundary (storage/durable.py _truncate_wal)
    "dur.wal_truncate",
)

#: monotone counters (obs/metrics.py COUNTERS is built from this)
COUNTER_NAMES = (
    "serve.submitted",
    "serve.answers",
    "serve.rejections",
    "serve.speculative",
    "cache.hits",
    "cache.misses",
    "cache.invalidations",
    "commit.deltas",
    "commit.rebuilds",
    "exec.dispatches",
    "exec.fetches",
    #: queries expired past their serving deadline (service/coalesce.py)
    "serve.deadline_misses",
    #: circuit-breaker trips CLOSED->OPEN / recoveries HALF_OPEN->CLOSED
    #: (das_tpu/fault CircuitBreaker)
    "serve.breaker_trips",
    "serve.breaker_recoveries",
    #: injected faults fired / retry attempts taken (das_tpu/fault
    #: maybe_fail + RetryPolicy — the attempt counters ISSUE 13 pins)
    "fault.injected",
    "fault.retries",
    #: XLA program compiles recorded by the program ledger (ISSUE 14)
    "prof.compiles",
    #: dasdur durability counters (ISSUE 15, storage/durable.py):
    #: snapshot generations written / WAL records appended+fsynced /
    #: WAL records replayed by restore()
    "dur.snapshots",
    "dur.wal_records",
    "dur.recovery_replayed",
)

#: fixed log-bucket latency histograms (obs/metrics.py HISTOGRAMS) —
#: p50/p95/p99 without sample retention; all record wall milliseconds
HISTOGRAM_NAMES = (
    #: submit -> group dispatch (queue + drain + grouping wait)
    "serve.queue_ms",
    #: per-group host-side dispatch cost (the window formula's divisor)
    "serve.dispatch_ms",
    #: per-group streamed settle wall time
    "serve.settle_ms",
    #: submit -> answer delivery (the open-loop latency the bench
    #: derives its p50/p95/p99 headline from)
    "serve.answer_ms",
    #: one settle round's host transfer (the wire the adaptive window
    #: must hide)
    "exec.settle_fetch_ms",
    #: wall time of one XLA program compile (das_tpu/obs/proflog.py,
    #: ISSUE 14) — the compile-seconds histogram the Prometheus surface
    #: exports next to the ledger gauges
    "prof.compile_ms",
    #: wall time of one warm-state restore — snapshot verify + WAL
    #: replay + warm bundle (storage/durable.py restore, ISSUE 15):
    #: the replica-fleet cold-start figure
    "dur.restore_ms",
)
