"""Typed metric layer: counters + fixed log-bucket histograms.

The histograms answer "what are p50/p95/p99" WITHOUT retaining samples:
values land in geometrically-spaced buckets (ratio 2^(1/4) per bucket,
so any reported quantile is within ~19% of the exact sample quantile —
bounded by construction, tested against exact quantiles in
tests/test_zobs.py), and percentiles interpolate inside the bucket that
crosses the requested rank.  Memory per histogram is one fixed int
vector regardless of traffic, which is what lets the serving path
record every answer's latency at 256+ open-loop clients without the
recorder becoming the workload.

Names are DECLARED in obs/registry.py (COUNTER_NAMES /
HISTOGRAM_NAMES) and the dicts here are BUILT from the registry — the
DL004 idiom; daslint rule DL014 pins every `counter("...")` /
`histogram("...")` literal against the registry in both directions.

Thread-safety: counters use a plain int += under the GIL (torn reads
tolerated, the coalescer-stats idiom); histograms bump one list slot
per observe — the same tolerance.  Exact totals are not the contract;
distribution SHAPE is.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from das_tpu.obs.registry import COUNTER_NAMES, HISTOGRAM_NAMES

#: bucket ratio: 4 buckets per doubling — quantile error bound ~2^0.25
_BUCKET_RATIO = 2.0 ** 0.25
_LOG_RATIO = math.log(_BUCKET_RATIO)
#: lowest bucket upper edge (ms): 1 microsecond
_LOW_MS = 1e-3
#: bucket count: top edge 1e-3 * 2^(127/4) ms ≈ 55 minutes — far past
#: any latency the serving path can legitimately report (bench futures
#: time out at 600 s), so saturation tails land in real buckets instead
#: of clamping; beyond the edge values clamp to the last bucket
_N_BUCKETS = 128


def bucket_index(ms: float) -> int:
    """Bucket for a millisecond value; clamped to the fixed range."""
    if ms <= _LOW_MS:
        return 0
    idx = int(math.log(ms / _LOW_MS) / _LOG_RATIO) + 1
    return idx if idx < _N_BUCKETS else _N_BUCKETS - 1


def bucket_upper(idx: int) -> float:
    """Upper edge (ms) of bucket `idx`."""
    return _LOW_MS * (_BUCKET_RATIO ** idx)


class Counter:
    __slots__ = ("name", "value")

    def __init__(self, name: str):
        self.name = name
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n

    def reset(self) -> None:
        self.value = 0


class Histogram:
    """Fixed log-bucket histogram over millisecond samples."""

    __slots__ = ("name", "counts", "total", "sum_ms", "min_ms", "max_ms")

    def __init__(self, name: str = ""):
        self.name = name
        self.counts: List[int] = [0] * _N_BUCKETS
        self.total = 0
        self.sum_ms = 0.0
        self.min_ms: Optional[float] = None
        self.max_ms = 0.0

    def observe(self, ms: float) -> None:
        self.counts[bucket_index(ms)] += 1
        self.total += 1
        self.sum_ms += ms
        if self.min_ms is None or ms < self.min_ms:
            self.min_ms = ms
        if ms > self.max_ms:
            self.max_ms = ms

    def reset(self) -> None:
        self.counts = [0] * _N_BUCKETS
        self.total = 0
        self.sum_ms = 0.0
        self.min_ms = None
        self.max_ms = 0.0

    def percentile(self, q: float) -> Optional[float]:
        """Approximate q-quantile (q in [0, 1]): geometric interpolation
        inside the bucket whose cumulative count crosses rank q*total.
        None on an empty histogram.  The true min/max tighten the edge
        buckets, so p0/p100 are exact."""
        if self.total == 0:
            return None
        rank = q * self.total
        cum = 0
        for idx, c in enumerate(self.counts):
            if c == 0:
                continue
            prev = cum
            cum += c
            if cum >= rank:
                lo = bucket_upper(idx - 1) if idx > 0 else 0.0
                hi = bucket_upper(idx)
                if self.min_ms is not None:
                    lo = max(lo, self.min_ms) if prev == 0 else lo
                    hi = min(hi, self.max_ms)
                if hi <= lo:
                    return hi
                # linear interpolation of the rank within the bucket
                frac = (rank - prev) / c
                return lo + (hi - lo) * frac
        return self.max_ms

    def percentiles(self) -> Dict[str, Optional[float]]:
        """The serving headline triple."""
        return {
            "p50": self.percentile(0.50),
            "p95": self.percentile(0.95),
            "p99": self.percentile(0.99),
        }

    def nonzero_buckets(self) -> List[Tuple[float, int]]:
        """(upper edge ms, count) for occupied buckets — the compact
        bucket-vector form the full bench record carries."""
        return [
            (round(bucket_upper(i), 6), c)
            for i, c in enumerate(self.counts)
            if c
        ]


#: the metric dicts are BUILT from the registry (never literal dicts),
#: so the declared set and the live set cannot drift — DL004's idiom
COUNTERS: Dict[str, Counter] = {n: Counter(n) for n in COUNTER_NAMES}
HISTOGRAMS: Dict[str, Histogram] = {n: Histogram(n) for n in HISTOGRAM_NAMES}


def counter(name: str) -> Counter:
    """The declared counter — KeyError on an undeclared name (the
    runtime twin of daslint DL014's static pin)."""
    return COUNTERS[name]


def histogram(name: str) -> Histogram:
    """The declared histogram — KeyError on an undeclared name."""
    return HISTOGRAMS[name]


def reset_metrics() -> None:
    for c in COUNTERS.values():
        c.reset()
    for h in HISTOGRAMS.values():
        h.reset()
