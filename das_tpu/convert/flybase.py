"""FlyBase (PostgreSQL dump) → MeTTa converter.

Role of /root/reference/flybase2metta/sql_reader.py:77-646 — stream a full
``pg_dump`` SQL file and emit a MeTTa knowledge base — with the same
emission vocabulary (sql_reader.py:36-45): node types ``Concept``,
``Schema``, ``Number``, ``Verbatim``, link types ``Inheritance``,
``Execution``.  Differences from the reference, by design:

* schema discovery is a single streaming pass with stdlib parsing of
  ``CREATE TABLE`` / ``ALTER TABLE .. ADD CONSTRAINT`` / ``COPY`` blocks
  (the reference needs simple_ddl_parser + sqlparse + 5 passes);
* relevance filtering is either an explicit ``tables=`` allowlist or, with
  ``precomputed_dir=``, discovered from the release's precomputed report
  files by value-coverage column matching (das_tpu/convert/precomputed.py,
  role of the reference precomputed_tables.py) in one extra streaming pass.

Per data row the converter emits:
    (: "table:<pk>" Concept)                    row node
    (Inheritance "table:<pk>" "table")          row → table concept
    (Execution (Schema "table.column") "table:<pk>" <value>)
where <value> is a referenced row node for FK columns, a Number node for
numeric columns, else a Verbatim node.  Output is chunked into
``file_NNN.metta`` checkpoint files (sql_reader.py:147-207) so a crashed
conversion resumes at file granularity.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, TextIO, Tuple

ATOM_TYPES = ("Concept", "Schema", "Number", "Verbatim", "Inheritance", "Execution")

EXPRESSION_CHUNK_SIZE = 500_000

_NUMERIC_TYPES = (
    "integer", "bigint", "smallint", "numeric", "real", "double precision",
    "serial", "bigserial", "float",
)

_CREATE_TABLE = re.compile(r"^CREATE TABLE (\S+) \($")
_ALTER_ONLY = re.compile(r"^ALTER TABLE (?:ONLY )?(\S+)$")
_PRIMARY_KEY = re.compile(r"ADD CONSTRAINT \S+ PRIMARY KEY \(([^)]+)\)")
_FOREIGN_KEY = re.compile(
    r"ADD CONSTRAINT \S+ FOREIGN KEY \(([^)]+)\) REFERENCES (\S+)\(([^)]+)\)"
)
_COPY = re.compile(r"^COPY (\S+) \(([^)]+)\) FROM stdin;$")


@dataclass
class TableSchema:
    name: str
    columns: List[Tuple[str, str]] = field(default_factory=list)  # (name, sql_type)
    primary_key: Optional[str] = None
    foreign_keys: Dict[str, Tuple[str, str]] = field(default_factory=dict)

    def column_type(self, column: str) -> str:
        for name, sql_type in self.columns:
            if name == column:
                return sql_type
        return "text"


def short_name(table: str) -> str:
    return table.split(".")[-1]


class FlybaseConverter:
    def __init__(
        self,
        sql_path: str,
        output_dir: str,
        tables: Optional[Iterable[str]] = None,
        precomputed_dir: Optional[str] = None,
        chunk_size: int = EXPRESSION_CHUNK_SIZE,
    ):
        self.sql_path = sql_path
        self.output_dir = output_dir
        self.tables = set(tables) if tables else None
        self.precomputed_dir = precomputed_dir
        self.precomputed = None
        self.chunk_size = chunk_size
        self.schema: Dict[str, TableSchema] = {}
        self._out: Optional[TextIO] = None
        self._file_number = 0
        self._chunk_count = 0
        self._typedefs: set = set()
        self._nodes: set = set()
        self._links: List[str] = []
        self.row_count = 0

    # -- schema pass (streamed together with data) -------------------------

    def _parse_create_table(self, header_line: str, lines: Iterable[str]) -> None:
        name = short_name(_CREATE_TABLE.match(header_line).group(1))
        table = TableSchema(name)
        for raw in lines:
            line = raw.strip().rstrip(",")
            if line.startswith(")"):
                break
            if not line or line.upper().startswith(("CONSTRAINT", "PRIMARY", "FOREIGN", "UNIQUE", "CHECK")):
                continue
            parts = line.split()
            table.columns.append((parts[0], " ".join(parts[1:]).lower()))
        self.schema[name] = table

    def _parse_alter(self, header_line: str, lines: Iterable[str]) -> None:
        m = _ALTER_ONLY.match(header_line)
        table = self.schema.get(short_name(m.group(1))) if m else None
        for raw in lines:
            line = raw.strip()
            if not line:
                break
            if table is None:
                if line.endswith(";"):
                    break
                continue
            pk = _PRIMARY_KEY.search(line)
            if pk:
                table.primary_key = pk.group(1).split(",")[0].strip()
            fk = _FOREIGN_KEY.search(line)
            if fk:
                col = fk.group(1).split(",")[0].strip()
                table.foreign_keys[col] = (
                    short_name(fk.group(2)),
                    fk.group(3).split(",")[0].strip(),
                )
            if line.endswith(";"):
                break

    # -- emission ----------------------------------------------------------

    def _open_next_file(self) -> None:
        if self._out:
            self._out.close()
        self._file_number += 1
        path = os.path.join(
            self.output_dir, f"file_{self._file_number:03d}.metta"
        )
        self._out = open(path, "w")
        for t in ATOM_TYPES:
            self._out.write(f"(: {t} Type)\n")

    def _flush(self, reopen: bool) -> None:
        for line in sorted(self._typedefs):
            self._out.write(line + "\n")
        for line in sorted(self._nodes):
            self._out.write(line + "\n")
        for line in self._links:
            self._out.write(line + "\n")
        self._typedefs.clear()
        self._nodes.clear()
        self._links.clear()
        self._chunk_count = 0
        if reopen:
            self._open_next_file()

    def _node(self, node_type: str, name: str) -> str:
        quoted = f'"{name}"'
        self._nodes.add(f"(: {quoted} {node_type})")
        self._chunk_count += 1
        return quoted

    def _value_node(self, table: TableSchema, column: str, value: str) -> str:
        fk = table.foreign_keys.get(column)
        if fk is not None:
            ref_table, _ref_col = fk
            return self._node("Concept", f"{ref_table}:{value}")
        sql_type = table.column_type(column)
        if any(sql_type.startswith(t) for t in _NUMERIC_TYPES):
            return self._node("Number", value)
        return self._node("Verbatim", value)

    def _emit_row(self, table: TableSchema, columns: List[str], values: List[str]) -> None:
        row: Dict[str, str] = dict(zip(columns, values))
        pk = table.primary_key or columns[0]
        pk_value = row.get(pk, "")
        if pk_value in ("", "\\N"):
            return
        row_node = self._node("Concept", f"{table.name}:{pk_value}")
        table_node = self._node("Concept", table.name)
        self._links.append(f"(Inheritance {row_node} {table_node})")
        for column, value in row.items():
            if column == pk or value == "\\N" or value == "":
                continue
            schema_node = self._node("Schema", f"{table.name}.{column}")
            value_node = self._value_node(table, column, value)
            self._links.append(
                f"(Execution (Schema {schema_node}) {row_node} {value_node})"
            )
            self._chunk_count += 1
        self.row_count += 1
        if self._chunk_count >= self.chunk_size:
            self._flush(reopen=True)

    def _parse_copy(self, header_line: str, lines: Iterable[str]) -> None:
        m = _COPY.match(header_line)
        name = short_name(m.group(1))
        columns = [c.strip() for c in m.group(2).split(",")]
        table = self.schema.get(name)
        wanted = table is not None and (self.tables is None or name in self.tables)
        for raw in lines:
            line = raw.rstrip("\n")
            if line == "\\.":
                break
            if wanted:
                self._emit_row(table, columns, line.split("\t"))

    # -- driver ------------------------------------------------------------

    def discover_relevant_tables(self) -> None:
        """Value-coverage discovery pass (reference sql_reader's first
        passes + precomputed_tables.check_field_value): stream every COPY
        row once, feeding (table, field, value) observations to the
        precomputed-report matcher; resolved column mappings select the
        relevant SQL tables and persist to mapping.txt."""
        from das_tpu.convert.precomputed import PrecomputedTables

        self.precomputed = PrecomputedTables(self.precomputed_dir)
        if not self.precomputed.preloaded:
            with open(self.sql_path) as f:
                it = iter(f)
                for raw in it:
                    line = raw.rstrip("\n")
                    if _CREATE_TABLE.match(line):
                        self._parse_create_table(line, it)
                    elif _COPY.match(line):
                        m = _COPY.match(line)
                        name = short_name(m.group(1))
                        columns = [c.strip() for c in m.group(2).split(",")]
                        for data in it:
                            row = data.rstrip("\n")
                            if row == "\\.":
                                break
                            for col, value in zip(columns, row.split("\t")):
                                self.precomputed.observe(name, col, value)
            self.precomputed.resolve()
            self.precomputed.save_mapping()
        relevant = self.precomputed.relevant_sql_tables()
        if not relevant:
            raise ValueError(
                "precomputed-report discovery matched no SQL tables "
                f"(dir={self.precomputed_dir}): the report files likely "
                "belong to a different release than the dump — refusing to "
                "convert the whole dump unfiltered; pass tables= explicitly "
                "to override"
            )
        self.tables = relevant if self.tables is None else (self.tables | relevant)

    def run(self) -> Dict[str, int]:
        os.makedirs(self.output_dir, exist_ok=True)
        if self.precomputed_dir and self.tables is None:
            self.discover_relevant_tables()
        self._open_next_file()
        with open(self.sql_path) as f:
            it = iter(f)
            for raw in it:
                line = raw.rstrip("\n")
                if _CREATE_TABLE.match(line):
                    self._parse_create_table(line, it)
                elif _ALTER_ONLY.match(line):
                    self._parse_alter(line, it)
                elif _COPY.match(line):
                    self._parse_copy(line, it)
        self._flush(reopen=False)
        self._out.close()
        return {
            "tables": len(self.schema),
            "rows": self.row_count,
            "files": self._file_number,
        }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="FlyBase SQL dump -> MeTTa")
    ap.add_argument("sql_file")
    ap.add_argument("output_dir")
    ap.add_argument("--tables", nargs="*", help="allowlist of table names")
    ap.add_argument(
        "--precomputed-dir",
        help="FlyBase precomputed-report dir: discover relevant tables by "
        "value-coverage column matching instead of an allowlist",
    )
    ap.add_argument("--chunk-size", type=int, default=EXPRESSION_CHUNK_SIZE)
    args = ap.parse_args(argv)
    stats = FlybaseConverter(
        args.sql_file, args.output_dir, args.tables,
        precomputed_dir=args.precomputed_dir, chunk_size=args.chunk_size,
    ).run()
    print(stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
