"""FlyBase (PostgreSQL dump) → MeTTa converter.

Role of /root/reference/flybase2metta/sql_reader.py:77-646 — stream a full
``pg_dump`` SQL file and emit a MeTTa knowledge base — with the same
emission vocabulary (sql_reader.py:36-45): node types ``Concept``,
``Schema``, ``Number``, ``Verbatim``, link types ``Inheritance``,
``Execution``.  Differences from the reference, by design:

* schema discovery is a dedicated streaming pass with stdlib parsing of
  ``CREATE TABLE`` / ``ALTER TABLE .. ADD CONSTRAINT`` blocks, run BEFORE
  the data pass — real ``pg_dump`` output adds every PRIMARY KEY / FOREIGN
  KEY constraint AFTER the COPY data, so single-pass emission would see no
  keys at all (the reference needs simple_ddl_parser + sqlparse + 5
  passes for the same reason, sql_reader.py:645+ parse());
* relevance filtering is either an explicit ``tables=`` allowlist or, with
  ``precomputed_dir=``, discovered from the release's precomputed report
  files by value-coverage column matching (das_tpu/convert/precomputed.py,
  role of the reference precomputed_tables.py) in one extra streaming pass.

Dump-robustness semantics (each matched to the reference where its
behavior is well-defined):

* tables with NO primary key are discarded with a logged warning
  (sql_reader.py:589-592 "Discarded table ... No PRIMARY KEY defined");
* composite primary keys — the reference hard-asserts them away
  (sql_reader.py:222) — identify rows by ALL pk columns joined with ':';
* quoted identifiers (``"order"``, mixed case) are unquoted everywhere
  (table names, column lists, constraint columns);
* ``\\N`` SQL NULLs are skipped per column and rows with a NULL/empty
  primary key are dropped (sql_reader value handling);
* ``ALTER TABLE`` constraints parse whether they arrive on one line or
  spread across continuation lines, before or after the table's data.

Per data row the converter emits:
    (: "table:<pk>" Concept)                    row node
    (Inheritance "table:<pk>" "table")          row → table concept
    (Execution (Schema "table.column") "table:<pk>" <value>)
where <value> is a referenced row node for FK columns, a Number node for
numeric columns, else a Verbatim node.  Output is chunked into
``file_NNN.metta`` checkpoint files (sql_reader.py:147-207) so a crashed
conversion resumes at file granularity.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, TextIO, Tuple

ATOM_TYPES = ("Concept", "Schema", "Number", "Verbatim", "Inheritance", "Execution")

EXPRESSION_CHUNK_SIZE = 500_000

_NUMERIC_TYPES = (
    "integer", "bigint", "smallint", "numeric", "real", "double precision",
    "serial", "bigserial", "float",
)

_CREATE_TABLE = re.compile(r"^CREATE TABLE (\S+)\s*\($")
_ALTER_HEAD = re.compile(r"^ALTER TABLE (?:ONLY )?(\S+)(\s.*)?$")
_PRIMARY_KEY = re.compile(r"ADD CONSTRAINT \S+ PRIMARY KEY \(([^)]+)\)")
_FOREIGN_KEY = re.compile(
    r"ADD CONSTRAINT \S+ FOREIGN KEY \(([^)]+)\) REFERENCES (\S+)\s*\(([^)]+)\)"
)
_COPY = re.compile(r"^COPY (\S+) \((.+)\) FROM stdin;$")


def unquote(identifier: str) -> str:
    """Strip PostgreSQL double-quoting from an identifier (quoted names
    keep case and may be SQL keywords — e.g. ``"order"``)."""
    identifier = identifier.strip()
    if identifier.startswith('"') and identifier.endswith('"'):
        return identifier[1:-1].replace('""', '"')
    return identifier


@dataclass
class TableSchema:
    name: str
    columns: List[Tuple[str, str]] = field(default_factory=list)  # (name, sql_type)
    #: ALL primary-key columns (composite keys keep every column; rows are
    #: identified by the ':'-joined values)
    primary_key: List[str] = field(default_factory=list)
    #: single-column FKs: column -> (ref_table, ref_column)
    foreign_keys: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    #: composite FKs: (local_cols, ref_table) — referencing the target's
    #: compound row identity; per-column Concept refs would dangle
    composite_fks: List[Tuple[Tuple[str, ...], str]] = field(default_factory=list)

    def column_type(self, column: str) -> str:
        for name, sql_type in self.columns:
            if name == column:
                return sql_type
        return "text"


def short_name(table: str) -> str:
    return unquote(table.split(".")[-1])


class FlybaseConverter:
    def __init__(
        self,
        sql_path: str,
        output_dir: str,
        tables: Optional[Iterable[str]] = None,
        precomputed_dir: Optional[str] = None,
        chunk_size: int = EXPRESSION_CHUNK_SIZE,
    ):
        self.sql_path = sql_path
        self.output_dir = output_dir
        self.tables = set(tables) if tables else None
        self.precomputed_dir = precomputed_dir
        self.precomputed = None
        self.chunk_size = chunk_size
        self.schema: Dict[str, TableSchema] = {}
        self._out: Optional[TextIO] = None
        self._file_number = 0
        self._chunk_count = 0
        self._typedefs: set = set()
        self._nodes: set = set()
        self._links: List[str] = []
        self._discarded: set = set()
        self.row_count = 0

    # -- schema pass (streamed together with data) -------------------------

    def _parse_create_table(self, header_line: str, lines: Iterable[str]) -> None:
        name = short_name(_CREATE_TABLE.match(header_line).group(1))
        table = TableSchema(name)
        for raw in lines:
            line = raw.strip().rstrip(",")
            if line.startswith(")"):
                break
            upper = line.upper()
            if upper.startswith("PRIMARY KEY"):
                # inline table-level PK (hand-written SQL; pg_dump emits
                # it as a later ALTER) — skipping it would discard the
                # whole table at emission time
                m = re.search(r"\(([^)]+)\)", line)
                if m:
                    table.primary_key = [
                        unquote(c) for c in m.group(1).split(",")
                    ]
                continue
            if not line or upper.startswith(("CONSTRAINT", "FOREIGN", "UNIQUE", "CHECK", "EXCLUDE")):
                continue
            # quoted column names may contain spaces: take the identifier
            # by quote-aware split, the rest is the SQL type
            if line.startswith('"'):
                end = line.index('"', 1)
                while end + 1 < len(line) and line[end + 1] == '"':
                    end = line.index('"', end + 2)
                col, rest = line[: end + 1], line[end + 1 :]
            else:
                col, _, rest = line.partition(" ")
            table.columns.append((unquote(col), rest.strip().lower()))
        self.schema[name] = table

    def _apply_constraint(self, table: TableSchema, text: str) -> None:
        pk = _PRIMARY_KEY.search(text)
        if pk:
            table.primary_key = [
                unquote(c) for c in pk.group(1).split(",")
            ]
        fk = _FOREIGN_KEY.search(text)
        if fk:
            local = [unquote(c) for c in fk.group(1).split(",")]
            remote = [unquote(c) for c in fk.group(3).split(",")]
            ref_table = short_name(fk.group(2))
            if len(local) == 1:
                table.foreign_keys[local[0]] = (ref_table, remote[0])
            else:
                # a composite FK references the target's COMPOUND row
                # identity; mapping the columns individually would emit
                # Concept refs no row node carries
                table.composite_fks.append((tuple(local), ref_table))

    def _parse_alter(self, header_line: str, lines: Iterable[str]) -> None:
        m = _ALTER_HEAD.match(header_line)
        table = self.schema.get(short_name(m.group(1))) if m else None
        # accumulate the WHOLE statement to the terminating ';' first: a
        # PRIMARY KEY (a,\n b) clause broken across continuation lines
        # must still match (dropping it would discard the whole table)
        text = (m.group(2) or "").strip() if m else ""
        if not text.endswith(";"):
            for raw in lines:
                line = raw.strip()
                if not line:
                    break
                text = f"{text} {line}" if text else line
                if line.endswith(";"):
                    break
        if table is not None:
            self._apply_constraint(table, text)

    # -- emission ----------------------------------------------------------

    def _open_next_file(self) -> None:
        if self._out:
            self._out.close()
        self._file_number += 1
        path = os.path.join(
            self.output_dir, f"file_{self._file_number:03d}.metta"
        )
        self._out = open(path, "w")
        for t in ATOM_TYPES:
            self._out.write(f"(: {t} Type)\n")

    def _flush(self, reopen: bool) -> None:
        for line in sorted(self._typedefs):
            self._out.write(line + "\n")
        for line in sorted(self._nodes):
            self._out.write(line + "\n")
        for line in self._links:
            self._out.write(line + "\n")
        self._typedefs.clear()
        self._nodes.clear()
        self._links.clear()
        self._chunk_count = 0
        if reopen:
            self._open_next_file()

    def _node(self, node_type: str, name: str) -> str:
        quoted = f'"{name}"'
        self._nodes.add(f"(: {quoted} {node_type})")
        self._chunk_count += 1
        return quoted

    def _value_node(self, table: TableSchema, column: str, value: str) -> str:
        fk = table.foreign_keys.get(column)
        if fk is not None:
            ref_table, _ref_col = fk
            return self._node("Concept", f"{ref_table}:{value}")
        sql_type = table.column_type(column)
        if any(sql_type.startswith(t) for t in _NUMERIC_TYPES):
            return self._node("Number", value)
        return self._node("Verbatim", value)

    def _emit_row(self, table: TableSchema, columns: List[str], values: List[str]) -> None:
        row: Dict[str, str] = dict(zip(columns, values))
        pk_cols = table.primary_key
        pk_values = [row.get(c, "") for c in pk_cols]
        if any(v in ("", "\\N") for v in pk_values):
            return  # NULL/absent (part of a) primary key: no row identity
        pk_value = ":".join(pk_values)
        row_node = self._node("Concept", f"{table.name}:{pk_value}")
        table_node = self._node("Concept", table.name)
        self._links.append(f"(Inheritance {row_node} {table_node})")
        pk_set = set(pk_cols)
        comp_fk_cols = set()
        for local_cols, ref_table in table.composite_fks:
            vals = [row.get(c, "") for c in local_cols]
            if any(v in ("", "\\N") for v in vals):
                continue
            comp_fk_cols.update(local_cols)
            schema_node = self._node(
                "Schema", f"{table.name}.{':'.join(local_cols)}"
            )
            ref_node = self._node(
                "Concept", f"{ref_table}:{':'.join(vals)}"
            )
            self._links.append(
                f"(Execution (Schema {schema_node}) {row_node} {ref_node})"
            )
            self._chunk_count += 1
        for column, value in row.items():
            if column in pk_set or column in comp_fk_cols:
                continue
            if value == "\\N" or value == "":
                continue
            schema_node = self._node("Schema", f"{table.name}.{column}")
            value_node = self._value_node(table, column, value)
            self._links.append(
                f"(Execution (Schema {schema_node}) {row_node} {value_node})"
            )
            self._chunk_count += 1
        self.row_count += 1
        if self._chunk_count >= self.chunk_size:
            self._flush(reopen=True)

    def _table_wanted(self, name: str) -> Optional[TableSchema]:
        table = self.schema.get(name)
        if table is None or (self.tables is not None and name not in self.tables):
            return None
        if not table.primary_key:
            # reference parity: tables without a PRIMARY KEY are discarded
            # with a logged error (sql_reader.py:589-592)
            if name not in self._discarded:
                self._discarded.add(name)
                from das_tpu.utils.logger import logger

                logger().warning(
                    f"Discarded table {name}: no PRIMARY KEY defined"
                )
            return None
        return table

    def _parse_copy(self, header_line: str, lines: Iterable[str]) -> None:
        m = _COPY.match(header_line)
        name = short_name(m.group(1))
        columns = [unquote(c) for c in m.group(2).split(",")]
        table = self._table_wanted(name)
        for raw in lines:
            line = raw.rstrip("\n")
            if line == "\\.":
                break
            if table is not None:
                self._emit_row(table, columns, line.split("\t"))

    # -- driver ------------------------------------------------------------

    def discover_relevant_tables(self) -> None:
        """Value-coverage discovery (reference sql_reader's first passes +
        precomputed_tables.check_field_value): under run(), COPY
        observations were already fed to the report matcher DURING the
        schema pass (one shared read of the dump); called standalone, the
        matcher streams the dump itself here."""
        if self.precomputed is None:
            from das_tpu.convert.precomputed import PrecomputedTables

            self.precomputed = PrecomputedTables(self.precomputed_dir)
            if not self.precomputed.preloaded:
                self._schema_pass(observe=self.precomputed.observe)
        if not self.precomputed.preloaded:
            self.precomputed.resolve()
            self.precomputed.save_mapping()
        relevant = self.precomputed.relevant_sql_tables()
        if not relevant:
            raise ValueError(
                "precomputed-report discovery matched no SQL tables "
                f"(dir={self.precomputed_dir}): the report files likely "
                "belong to a different release than the dump — refusing to "
                "convert the whole dump unfiltered; pass tables= explicitly "
                "to override"
            )
        self.tables = relevant if self.tables is None else (self.tables | relevant)

    def _schema_pass(self, observe=None) -> None:
        """Stream the whole dump collecting CREATE TABLE columns and ALTER
        TABLE constraints.  Real pg_dump output puts every constraint
        AFTER the data, so emission cannot know primary or foreign keys
        until this pass completes.  COPY bodies are skimmed — or, when
        `observe` is given, fed to it as (table, column, value) for the
        precomputed-report matcher (sharing this read instead of adding a
        third pass over a multi-GB dump)."""
        with open(self.sql_path) as f:
            it = iter(f)
            for raw in it:
                line = raw.rstrip("\n")
                if _CREATE_TABLE.match(line):
                    self._parse_create_table(line, it)
                elif _ALTER_HEAD.match(line):
                    self._parse_alter(line, it)
                elif _COPY.match(line):
                    m = _COPY.match(line)
                    name = short_name(m.group(1))
                    columns = [unquote(c) for c in m.group(2).split(",")]
                    for data in it:
                        row = data.rstrip("\n")
                        if row == "\\.":
                            break
                        if observe is not None:
                            for col, value in zip(columns, row.split("\t")):
                                observe(name, col, value)

    def run(self) -> Dict[str, int]:
        os.makedirs(self.output_dir, exist_ok=True)
        observe = None
        if self.precomputed_dir and self.tables is None:
            from das_tpu.convert.precomputed import PrecomputedTables

            self.precomputed = PrecomputedTables(self.precomputed_dir)
            if not self.precomputed.preloaded:
                observe = self.precomputed.observe
        self._schema_pass(observe=observe)
        if self.precomputed is not None:
            self.discover_relevant_tables()
        self._open_next_file()
        with open(self.sql_path) as f:
            it = iter(f)
            for raw in it:
                line = raw.rstrip("\n")
                if _COPY.match(line):
                    self._parse_copy(line, it)
        self._flush(reopen=False)
        self._out.close()
        return {
            "tables": len(self.schema),
            "discarded_tables": len(self._discarded),
            "rows": self.row_count,
            "files": self._file_number,
        }


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="FlyBase SQL dump -> MeTTa")
    ap.add_argument("sql_file")
    ap.add_argument("output_dir")
    ap.add_argument("--tables", nargs="*", help="allowlist of table names")
    ap.add_argument(
        "--precomputed-dir",
        help="FlyBase precomputed-report dir: discover relevant tables by "
        "value-coverage column matching instead of an allowlist",
    )
    ap.add_argument("--chunk-size", type=int, default=EXPRESSION_CHUNK_SIZE)
    args = ap.parse_args(argv)
    stats = FlybaseConverter(
        args.sql_file, args.output_dir, args.tables,
        precomputed_dir=args.precomputed_dir, chunk_size=args.chunk_size,
    ).run()
    print(stats)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
