"""Multi-process paren-balanced parse fan-out (SURVEY.md §2.10 P3).

Role of /root/reference/das/atomese2metta/parser.py:47-130
(MultiprocessingParser): split an s-expression source at paren-balance-zero
boundaries into chunks of whole toplevel expressions — quoted strings are
blanked first so parentheses inside names don't skew the count — and parse
the chunks in a process pool.

Redesign notes (not a port): the reference pickles pyparsing trees through
temp files and reassembles them in waves of `multiprocessing.Process`; here
chunks go through a `multiprocessing.Pool` and each worker returns plain
nested-list s-expression trees (pickle-friendly), concatenated in input
order.  Hash computation happens AFTER the merge in the single-threaded
translator — parallelizing the *tokenize+tree* stage is where the
reference measured its win, and it keeps the symbol tables single-writer."""

from __future__ import annotations

import multiprocessing
from io import StringIO
from typing import Iterable, Iterator, List, Union

def _line_delta(line: str, in_string: bool) -> tuple:
    """Net parenthesis balance of one line and the carried-over in-string
    state.  ``;`` comments (outside strings) run to end of line; quoted
    strings may span lines (Scheme allows embedded newlines)."""
    delta = 0
    for ch in line:
        if in_string:
            if ch == '"':
                in_string = False
            continue
        if ch == '"':
            in_string = True
        elif ch == ";":
            break
        elif ch == "(":
            delta += 1
        elif ch == ")":
            delta -= 1
    return delta, in_string


def paren_delta(line: str) -> int:
    """Net parenthesis balance of one self-contained line (strings closed
    within the line), ignoring quoted strings and ``;`` comments."""
    return _line_delta(line, False)[0]


def split_balanced(
    source: Union[str, Iterable[str]], chunk_exprs: int = 1000
) -> Iterator[str]:
    """Yield chunks of whole toplevel expressions: a chunk boundary can
    only fall where the running paren balance returns to zero OUTSIDE any
    quoted string."""
    if isinstance(source, str):
        source = StringIO(source)
    balance = 0
    in_string = False
    exprs_done = 0
    buf: List[str] = []
    for line in source:
        stripped = line.rstrip("\n")
        if not stripped and balance == 0 and not in_string:
            continue
        delta, in_string = _line_delta(stripped, in_string)
        balance += delta
        if balance < 0:
            raise ValueError("unbalanced parentheses (negative balance)")
        buf.append(stripped)
        if balance == 0 and not in_string:
            exprs_done += 1
            if exprs_done >= chunk_exprs:
                yield "\n".join(buf)
                buf = []
                exprs_done = 0
    if balance != 0 or in_string:
        raise ValueError("unbalanced parentheses at end of input")
    if buf:
        yield "\n".join(buf)


def parse_sexpr_trees(chunk: str) -> List[list]:
    """One chunk -> list of nested-list trees.  Delegates to the serial
    atomese parser (single source of truth for comment/string handling),
    so multiprocess and serial paths cannot diverge."""
    from das_tpu.convert.atomese2metta import parse_sexpr

    return parse_sexpr(chunk)


def parse_multiprocess(
    source: Union[str, Iterable[str]],
    processes: int | None = None,
    chunk_exprs: int = 1000,
) -> List[list]:
    """Parse a whole source with a process pool; trees come back in input
    order.  Single-chunk inputs skip the pool entirely."""
    chunks = list(split_balanced(source, chunk_exprs))
    if len(chunks) <= 1:
        return parse_sexpr_trees(chunks[0]) if chunks else []
    processes = processes or multiprocessing.cpu_count()
    # forkserver: plain fork() of this (JAX-threaded) process is deprecated
    # on 3.12 and genuinely deadlock-prone.  The preload makes the
    # forkserver parent import this module (hence the das_tpu package and
    # jax) ONCE so workers fork with it loaded instead of re-importing jax
    # apiece.  That parent is NOT thread-free in general — the actual
    # contract is narrower: importing jax does not initialize a backend
    # (device threads start at first jax.devices()/dispatch, which nothing
    # in the preloaded import chain performs), so the parent holds no
    # locks a forked child could deadlock on — strictly safer than forking
    # the fully-threaded main process, which is what Pool() did before.
    try:
        ctx = multiprocessing.get_context("forkserver")
        ctx.set_forkserver_preload(["das_tpu.convert.chunked"])
    except ValueError:  # platform without forkserver
        ctx = multiprocessing.get_context("spawn")
    with ctx.Pool(min(processes, len(chunks))) as pool:
        parsed = pool.map(parse_sexpr_trees, chunks)
    return [tree for trees in parsed for tree in trees]
