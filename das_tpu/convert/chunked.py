"""Multi-process paren-balanced parse fan-out (SURVEY.md §2.10 P3).

Role of /root/reference/das/atomese2metta/parser.py:47-130
(MultiprocessingParser): split an s-expression source at paren-balance-zero
boundaries into chunks of whole toplevel expressions — quoted strings are
blanked first so parentheses inside names don't skew the count — and parse
the chunks in a process pool.

Redesign notes (not a port): the reference pickles pyparsing trees through
temp files and reassembles them in waves of `multiprocessing.Process`; here
chunks go through a `multiprocessing.Pool` and each worker returns plain
nested-list s-expression trees (pickle-friendly), concatenated in input
order.  Hash computation happens AFTER the merge in the single-threaded
translator — parallelizing the *tokenize+tree* stage is where the
reference measured its win, and it keeps the symbol tables single-writer."""

from __future__ import annotations

import multiprocessing
import re
from io import StringIO
from typing import Iterable, Iterator, List, Union

_QUOTED = re.compile(r"\"[^\"]*\"")


def strip_comment(line: str) -> str:
    """Drop a Scheme ``;`` comment, respecting double-quoted strings (a
    ``;`` inside a name is content, not a comment)."""
    in_string = False
    for i, ch in enumerate(line):
        if ch == '"':
            in_string = not in_string
        elif ch == ";" and not in_string:
            return line[:i]
    return line


def paren_delta(line: str) -> int:
    """Net parenthesis balance of one line, ignoring quoted strings and
    ``;`` comments."""
    text = _QUOTED.sub("", strip_comment(line))
    return text.count("(") - text.count(")")


def split_balanced(
    source: Union[str, Iterable[str]], chunk_exprs: int = 1000
) -> Iterator[str]:
    """Yield chunks of whole toplevel expressions: a chunk boundary can
    only fall where the running paren balance returns to zero."""
    if isinstance(source, str):
        source = StringIO(source)
    balance = 0
    exprs_done = 0
    buf: List[str] = []
    for line in source:
        stripped = line.rstrip("\n")
        if not stripped and balance == 0:
            continue
        balance += paren_delta(stripped)
        if balance < 0:
            raise ValueError("unbalanced parentheses (negative balance)")
        buf.append(stripped)
        if balance == 0:
            exprs_done += 1
            if exprs_done >= chunk_exprs:
                yield "\n".join(buf)
                buf = []
                exprs_done = 0
    if balance != 0:
        raise ValueError("unbalanced parentheses at end of input")
    if buf:
        yield "\n".join(buf)


def parse_sexpr_trees(chunk: str) -> List[list]:
    """One chunk -> list of nested-list trees (atoms are strings; quoted
    names keep their quotes so the caller can distinguish terminals).
    ``;`` comments are stripped line-wise before tokenizing."""
    text = "\n".join(strip_comment(line) for line in chunk.split("\n"))
    tokens = re.findall(r"\"[^\"]*\"|[()]|[^\s()\"]+", text)
    out: List[list] = []
    stack: List[list] = []
    for tok in tokens:
        if tok == "(":
            node: list = []
            if stack:
                stack[-1].append(node)
            stack.append(node)
        elif tok == ")":
            node = stack.pop()
            if not stack:
                out.append(node)
        else:
            if not stack:
                raise ValueError(f"atom outside expression: {tok!r}")
            stack[-1].append(tok)
    if stack:
        raise ValueError("unbalanced parentheses in chunk")
    return out


def parse_multiprocess(
    source: Union[str, Iterable[str]],
    processes: int | None = None,
    chunk_exprs: int = 1000,
) -> List[list]:
    """Parse a whole source with a process pool; trees come back in input
    order.  Single-chunk inputs skip the pool entirely."""
    chunks = list(split_balanced(source, chunk_exprs))
    if len(chunks) <= 1:
        return parse_sexpr_trees(chunks[0]) if chunks else []
    processes = processes or multiprocessing.cpu_count()
    with multiprocessing.Pool(min(processes, len(chunks))) as pool:
        parsed = pool.map(parse_sexpr_trees, chunks)
    return [tree for trees in parsed for tree in trees]
