"""Human-readable store export/import in the reference's mongoexport
format (interop tool).

The reference's `mongodump` script (/root/reference/mongodump:1-8) exports
the Mongo collections `nodes`, `links_2`, `atom_types` as one JSON document
per line and sorts each file with sort(1).  The document shapes are exactly
`Expression.to_dict()` (/root/reference/das/expression.py:25-53): terminals
carry {_id, composite_type_hash, name, named_type}; typedefs carry
{_id, composite_type_hash, named_type, named_type_hash}; regular
expressions additionally carry is_toplevel, composite_type and the
key_0/key_1 (arity <= 2) or keys (arity > 2) element split.

This module emits byte-compatible dumps from a das_tpu store — every Mongo
collection the reference populates (mongo_schema.py CollectionNames:
nodes, atom_types, links_1, links_2, links_n), each sorted with C-locale
(codepoint) order, i.e. `LC_ALL=C sort` — and loads such a dump back into
an `AtomSpaceData` by reconstructing canonical MeTTa text and re-running
the normal parser path, so every hash in the loaded store is re-derived
and re-verified rather than trusted.

A dump produced by the reference stack lacks one piece of information this
loader needs: the typedef's type-designator NAME (the document only holds
its md5 inside `_id`).  `_recover_designator` resolves it by hash-checking
every type name present in the dump (plus the basic marks) against the
document's `_id` — exact, since `_id` is the expression hash over
[mark, name_hash, designator_hash].
"""

from __future__ import annotations

import json
import os
import re
from typing import Dict, List

from das_tpu.core.expression import Expression
from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.schema import BASIC_TYPE, TYPEDEF_MARK

#: reference mongo_schema.py CollectionNames -> file suffixes used by the
#: reference's mongodump script ("$1.nodes" etc.)
COLLECTIONS = ("nodes", "atom_types", "links_1", "links_2", "links_n")

#: what the MeTTa lexer accepts as a bare SYMBOL (the lexer's own rule)
from das_tpu.ingest.metta import SYMBOL_PATTERN

_SYMBOL_RE = re.compile(SYMBOL_PATTERN)


def _node_doc(handle: str, rec) -> dict:
    # terminal composite_type_hash == named_type_hash (base_yacc.py:140-141)
    return Expression(
        terminal_name=rec.name,
        named_type=rec.named_type,
        composite_type_hash=rec.named_type_hash,
        hash_code=handle,
    ).to_dict()


def _typedef_doc(handle: str, rec) -> dict:
    return Expression(
        typedef_name=rec.name,
        typedef_name_hash=rec.name_hash,
        composite_type_hash=rec.composite_type_hash,
        hash_code=handle,
    ).to_dict()


def _link_doc(handle: str, rec) -> dict:
    return Expression(
        toplevel=rec.is_toplevel,
        named_type=rec.named_type,
        named_type_hash=rec.named_type_hash,
        composite_type=rec.composite_type,
        composite_type_hash=rec.composite_type_hash,
        elements=list(rec.elements),
        hash_code=handle,
    ).to_dict()


def _jsonl(doc: dict) -> str:
    # mongoexport is a Go program: its encoding/json writes raw UTF-8
    # (no \uXXXX for non-ASCII) but HTML-escapes < > & as \u003c \u003e
    # \u0026 (json.Marshal's SetEscapeHTML default) — reproduce both so
    # the byte-compat contract holds beyond ASCII names
    line = json.dumps(doc, separators=(",", ":"), ensure_ascii=False)
    return (
        line.replace("<", "\\u003c")
        .replace(">", "\\u003e")
        .replace("&", "\\u0026")
        # Go also escapes the JS line separators U+2028/U+2029
        .replace("\u2028", "\\u2028")
        .replace("\u2029", "\\u2029")
    )


def store_documents(data) -> Dict[str, List[str]]:
    """All mongoexport-shaped document lines of a store, keyed by
    collection name, UNSORTED (dump_store sorts at write time)."""
    out: Dict[str, List[str]] = {name: [] for name in COLLECTIONS}
    for handle, rec in data.nodes.items():
        out["nodes"].append(_jsonl(_node_doc(handle, rec)))
    for handle, rec in data.typedefs.items():
        out["atom_types"].append(_jsonl(_typedef_doc(handle, rec)))
    for handle, rec in data.links.items():
        arity = len(rec.elements)
        name = "links_1" if arity == 1 else (
            "links_2" if arity == 2 else "links_n"
        )
        out[name].append(_jsonl(_link_doc(handle, rec)))
    return out


def dump_store(data, prefix: str, include_empty: bool = False) -> List[str]:
    """Write `<prefix>.<collection>` files, each C-locale sorted (the
    reference pipes mongoexport through sort(1)).  Returns written paths;
    empty collections are skipped unless include_empty."""
    docs = store_documents(data)
    written = []
    for name in COLLECTIONS:
        lines = docs[name]
        if not lines and not include_empty:
            continue
        path = f"{prefix}.{name}"
        with open(path, "w", encoding="utf-8") as f:
            for line in sorted(lines):
                f.write(line + "\n")
        written.append(path)
    return written


# ---------------------------------------------------------------------------
# loading a dump back into a store
# ---------------------------------------------------------------------------


def _read_collection(prefix: str, name: str) -> List[dict]:
    path = f"{prefix}.{name}"
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return [json.loads(line) for line in f if line.strip()]


def _recover_designator(doc: dict, name_by_hash: Dict[str, str]) -> str:
    """Type-designator name of a typedef document, by exact hash check:
    _id == expression_hash(mark, [named_type_hash, designator_hash])
    (base_yacc.py:108-126)."""
    mark_hash = ExpressionHasher.named_type_hash(TYPEDEF_MARK)
    for cand_hash, cand_name in name_by_hash.items():
        if (
            ExpressionHasher.expression_hash(
                mark_hash, [doc["named_type_hash"], cand_hash]
            )
            == doc["_id"]
        ):
            return cand_name
    raise ValueError(
        f"cannot recover type designator of typedef {doc['named_type']!r} "
        f"({doc['_id']}): no known type name hashes to it"
    )


def _quote(name: str) -> str:
    if '"' in name or "\n" in name:
        raise ValueError(
            f"terminal name {name!r} is not representable in canonical "
            "MeTTa (embedded quote/newline)"
        )
    return f'"{name}"'


def read_dump(prefix: str) -> Dict[str, List[dict]]:
    """Parse every collection file of a dump ONCE.  Raises when no
    collection file exists at all — a typo'd prefix must not load as a
    valid empty store."""
    docs = {name: _read_collection(prefix, name) for name in COLLECTIONS}
    if not any(os.path.exists(f"{prefix}.{name}") for name in COLLECTIONS):
        raise FileNotFoundError(
            f"no dump files found at prefix {prefix!r} "
            f"(expected <prefix>.{{{','.join(COLLECTIONS)}}})"
        )
    return docs


def dump_to_metta(prefix: str, docs: Dict[str, List[dict]] = None) -> str:
    """Reconstruct canonical MeTTa text from a dump: typedefs first, then
    terminal declarations, then every TOPLEVEL expression with sub-links
    rendered inline (non-toplevel links exist in the dump exactly because
    a toplevel one references them)."""
    if docs is None:
        docs = read_dump(prefix)
    typedefs = docs["atom_types"]
    nodes = docs["nodes"]
    links = docs["links_1"] + docs["links_2"] + docs["links_n"]

    name_by_hash = {
        ExpressionHasher.named_type_hash(d["named_type"]): d["named_type"]
        for d in typedefs
    }
    for base in (BASIC_TYPE, TYPEDEF_MARK):
        name_by_hash.setdefault(ExpressionHasher.named_type_hash(base), base)

    lines: List[str] = []
    # a TERMINAL declaration `(: "human" Concept)` records BOTH a node and
    # a typedef (name hashed as a named type, base_yacc.py:108-126 /
    # metta.py _typedef) — the quoted node declaration below recreates
    # both records, so its typedef doc must NOT also be emitted as a bare
    # symbol line (the name may not even lex as a SYMBOL, e.g. "a<b")
    node_names = {(d["name"], d["named_type"]) for d in nodes}
    for d in typedefs:
        designator = _recover_designator(d, name_by_hash)
        if (d["named_type"], designator) not in node_names:
            name = d["named_type"]
            # a terminal DECLARED but never used leaves a typedef doc
            # with no node doc (true of reference dumps too: the node
            # atom is created on use, base_yacc.py:132-145).  The
            # typedef record is IDENTICAL for `(: x T)` and `(: "x" T)`
            # (name md5'd either way), so quote whenever the name cannot
            # lex as a bare SYMBOL — same record, and names like "a.b"
            # become expressible
            if _SYMBOL_RE.fullmatch(name) is None:
                name = _quote(name)
            lines.append(f"(: {name} {designator})")
    node_text = {d["_id"]: _quote(d["name"]) for d in nodes}
    # a link element may be a bare SYMBOL (the grammar allows it): its
    # handle is the typedef's own expression hash, rendered unquoted
    symbol_text = {d["_id"]: d["named_type"] for d in typedefs}
    for d in nodes:
        lines.append(f"(: {_quote(d['name'])} {d['named_type']})")

    link_by_id = {d["_id"]: d for d in links}

    def elements(d: dict) -> List[str]:
        if "keys" in d:
            return d["keys"]
        return [d["key_0"]] + ([d["key_1"]] if "key_1" in d else [])

    rendered: Dict[str, str] = {}

    def render(handle: str) -> str:
        if handle in node_text:
            return node_text[handle]
        if handle in symbol_text:
            return symbol_text[handle]
        if handle in rendered:
            return rendered[handle]
        d = link_by_id.get(handle)
        if d is None:
            raise ValueError(
                f"dump references unknown atom {handle}: corrupt dump"
            )
        inner = " ".join(render(e) for e in elements(d))
        text = f"({d['named_type']} {inner})"
        rendered[handle] = text
        return text

    for d in links:
        if d.get("is_toplevel"):
            lines.append(render(d["_id"]))
    return "\n".join(lines) + "\n"


def load_dump(prefix: str):
    """Parse a dump back into a fresh AtomSpaceData via the normal MeTTa
    parser path — all hashes re-derived, then VERIFIED against the dump's
    _id sets, so silent loss (e.g. the same terminal name declared under
    two types, which canonical MeTTa text cannot express — the parser's
    last-declaration-wins symbol table keeps one) fails loudly."""
    from das_tpu.storage.atom_table import AtomSpaceData, load_metta_text

    docs = read_dump(prefix)
    data = AtomSpaceData()
    load_metta_text(dump_to_metta(prefix, docs), data)

    node_ids = {d["_id"] for d in docs["nodes"]}
    link_ids = {
        d["_id"]
        for name in ("links_1", "links_2", "links_n")
        for d in docs[name]
    }
    typedef_ids = {d["_id"] for d in docs["atom_types"]}
    problems = []
    if set(data.nodes) != node_ids:
        problems.append(
            f"nodes: {len(node_ids - set(data.nodes))} lost, "
            f"{len(set(data.nodes) - node_ids)} extra"
        )
    if set(data.links) != link_ids:
        problems.append(
            f"links: {len(link_ids - set(data.links))} lost, "
            f"{len(set(data.links) - link_ids)} extra"
        )
    if not typedef_ids <= set(data.typedefs):  # parser may add base marks
        problems.append(
            f"atom_types: {len(typedef_ids - set(data.typedefs))} lost"
        )
    if problems:
        raise ValueError(
            "dump does not reconstruct faithfully ("
            + "; ".join(problems)
            + ") — e.g. a terminal name declared under several types "
            "cannot round-trip through canonical MeTTa text"
        )
    return data
