"""Atomese (.scm) → MeTTa document converter.

Role of /root/reference/das/atomese2metta/translator.py:100-266, built
over a single streaming s-expression walker instead of the reference's
Expression/AtomType object zoo:

* link/node **type whitelists** (same type names, with and without the
  ``Node``/``Link`` suffix) — unknown symbols raise `InvalidSymbol`;
* ``Node``/``Link`` suffixes stripped from type names
  (translator.py:183-184);
* ``SetLink`` → MeTTa multiset braces ``{...}`` (translator.py:63-71);
* ``stv`` truth-value annotations skipped (IGNORED_SYMBOLS,
  translator.py:134);
* node typedefs ``(: Concept Type)`` + node declarations
  ``(: "name" Concept)`` emitted before the body, deduplicated in first-
  seen order (MettaDocument.expressions, translator.py:232-239).

Output loads directly through `das_tpu.ingest.metta.MettaParser`.
"""

from __future__ import annotations

import re
from typing import Iterable, List, Optional, TextIO, Tuple, Union

from das_tpu.core.exceptions import DasError

ALLOWED_LINKS = (
    "ContextLink",
    "EvaluationLink",
    "InheritanceLink",
    "ListLink",
    "MemberLink",
    "SetLink",
    "SimilarityLink",
    "LazyExecutionOutputLink",
)

ALLOWED_NODES = (
    "CellNode",
    "ChebiNode",
    "ChebiOntologyNode",
    "PredicateNode",
    "BiologicalProcessNode",
    "CellularComponentNode",
    "ConceptNode",
    "MolecularFunctionNode",
    "NcbiTaxonomyNode",
    "GeneNode",
    "ReactomeNode",
    "SmpNode",
    "UberonNode",
    "EntrezNode",
    "EnstNode",
    "UniprotNode",
    "RefseqNode",
    "PharmGkbNode",
    "SchemaNode",
    "PatientNode",
)

IGNORED_SYMBOLS = ("stv",)

_SUFFIX = re.compile(r"\s*(Node|Link)$")


class InvalidSymbol(DasError):
    pass


def strip_suffix(symbol: str) -> str:
    """ConceptNode -> Concept, MemberLink -> Member."""
    return _SUFFIX.sub("", symbol)


def parse_sexpr(text: str) -> List[list]:
    """Parse scheme s-expressions into nested lists of str tokens.
    Comments (;...) are dropped; quoted strings are single tokens."""
    out: List[list] = []
    stack: List[list] = []
    token = []
    in_string = False
    in_comment = False
    for ch in text:
        if in_comment:
            if ch == "\n":
                in_comment = False
            continue
        if in_string:
            token.append(ch)
            if ch == '"':
                in_string = False
            continue
        if ch == ";":
            in_comment = True
            continue
        if ch == '"':
            token.append(ch)
            in_string = True
            continue
        if ch in "()" or ch.isspace():
            if token:
                (stack[-1] if stack else out).append("".join(token))
                token = []
            if ch == "(":
                new: list = []
                (stack[-1] if stack else out).append(new)
                stack.append(new)
            elif ch == ")":
                if not stack:
                    raise InvalidSymbol("unbalanced ')'")
                stack.pop()
            continue
        token.append(ch)
    if stack:
        raise InvalidSymbol("unbalanced '('")
    if token:
        out.append("".join(token))
    return out


class Translator:
    """Walks parsed Atomese trees, accumulating node typedefs and node
    declarations, and renders MeTTa body expressions."""

    def __init__(self):
        self.node_types: List[str] = []       # first-seen order
        self.nodes: List[Tuple[str, str]] = []  # (name, type)
        self._seen_types = set()
        self._seen_nodes = set()

    def _is_node(self, symbol: str) -> bool:
        return symbol in ALLOWED_NODES or symbol + "Node" in ALLOWED_NODES

    def _is_link(self, symbol: str) -> bool:
        return symbol in ALLOWED_LINKS or symbol + "Link" in ALLOWED_LINKS

    def _add_type(self, mtype: str) -> None:
        if mtype not in self._seen_types:
            self._seen_types.add(mtype)
            self.node_types.append(mtype)

    def _add_node(self, name: str, mtype: str) -> None:
        key = (name, mtype)
        if key not in self._seen_nodes:
            self._seen_nodes.add(key)
            self.nodes.append(key)

    def translate(self, tree: Union[str, list]) -> Optional[str]:
        """One Atomese tree -> MeTTa text (None for ignored subtrees)."""
        if isinstance(tree, str):
            raise InvalidSymbol(tree)
        if not tree:
            raise InvalidSymbol("()")
        head = tree[0]
        if isinstance(head, list):
            parts = [self.translate(sub) for sub in tree]
            return f"({' '.join(p for p in parts if p is not None)})"
        if head in IGNORED_SYMBOLS:
            return None
        mtype = strip_suffix(head)
        if self._is_node(head):
            if len(tree) < 2 or not isinstance(tree[1], str):
                raise InvalidSymbol(f"node {head} without a name")
            name = tree[1]
            if not (name.startswith('"') and name.endswith('"')):
                name = f'"{name}"'
            self._add_type(mtype)
            self._add_node(name, mtype)
            return name
        if self._is_link(head):
            parts = [self.translate(sub) for sub in tree[1:]]
            parts = [p for p in parts if p is not None]
            if mtype == "Set":
                self._add_type("Set")  # the implicit type of `{...}` sugar
                return "{" + " ".join(parts) + "}"
            self._add_type(mtype)
            return f"({mtype} {' '.join(parts)})"
        raise InvalidSymbol(head)

    def header_lines(self) -> Iterable[str]:
        for mtype in self.node_types:
            yield f"(: {mtype} Type)"
        for name, mtype in self.nodes:
            yield f"(: {name} {mtype})"


def translate_text(atomese_text: str, processes: int = 1) -> str:
    """Full document conversion: returns MeTTa text (typedefs, node
    declarations, then body expressions).  With processes > 1 the
    tokenize+tree stage fans out over paren-balanced chunks in a process
    pool (das_tpu/convert/chunked.py — SURVEY §2.10 P3); translation stays
    single-threaded (it owns the shared symbol tables)."""
    if processes > 1:
        from das_tpu.convert.chunked import parse_multiprocess

        trees = parse_multiprocess(atomese_text, processes=processes)
    else:
        trees = parse_sexpr(atomese_text)
    translator = Translator()
    body = []
    for tree in trees:
        rendered = translator.translate(tree)
        if rendered is not None:
            body.append(rendered)
    return "\n".join([*translator.header_lines(), *body]) + "\n"


def translate_file(scm_path: str, metta_path: str, processes: int = 1) -> None:
    with open(scm_path) as f:
        text = f.read()
    with open(metta_path, "w") as out:
        out.write(translate_text(text, processes=processes))


def main(argv=None) -> int:
    import argparse

    ap = argparse.ArgumentParser(description="Atomese .scm -> MeTTa converter")
    ap.add_argument("input")
    ap.add_argument("output")
    ap.add_argument(
        "--processes", type=int, default=1,
        help="fan the parse stage out over a process pool",
    )
    args = ap.parse_args(argv)
    translate_file(args.input, args.output, processes=args.processes)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
