"""Offline ETL converters (Atomese→MeTTa, FlyBase SQL→MeTTa).

Role of the reference's das/atomese2metta/ and flybase2metta/ side rails
(SURVEY.md §2.6): host-side text-to-text tooling feeding the ingest
pipeline; nothing here touches devices."""
