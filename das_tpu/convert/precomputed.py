"""FlyBase precomputed-report column matching (value-coverage discovery).

Role of /root/reference/flybase2metta/precomputed_tables.py:9-361: a
FlyBase release ships "precomputed files" — TSV reports (plus an ncRNA
JSON) whose columns are *unlabeled* with respect to the SQL schema.  To
reproduce the reference KB from a raw release, the converter must discover
which ``table.field`` of the SQL dump each report column corresponds to.

Discovery is by VALUE COVERAGE: while streaming the dump's COPY rows,
every (sql_table, sql_field, value) observation is checked against the
still-unmapped report columns; a column maps to the (table, field) whose
observed values cover at least ``NEAR_MATCH_THRESHOLD`` (90%, the
reference's check_near_match bar, precomputed_tables.py:86-102) of the
column's distinct values.  FlyBase identifiers are normalized to their
bare ``FBxx…`` accession before comparison (the reference's
``flybase_id_re``).  Resolved mappings persist to ``mapping.txt`` in the
reference's tab-separated format (file, column, table, field) so later
conversions preload instead of rediscovering.

The union of mapped tables is the converter's *relevant table* set — the
capability round 1 replaced with a hand-written allowlist."""

from __future__ import annotations

import csv
import glob
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

NEAR_MATCH_THRESHOLD = 0.9

_FLYBASE_ID = re.compile(r"^(\S+:)?(FB[a-zA-Z]{2}[0-9]{5,10})$")


def normalize_value(value: str) -> str:
    value = value.strip()
    m = _FLYBASE_ID.search(value)
    return m.group(2) if m is not None else value


class ReportTable:
    """One precomputed report: per-column distinct values plus, per
    candidate (sql_table, sql_field), the subset of values seen there."""

    def __init__(self, name: str):
        self.name = name
        self.header: List[str] = []
        self.values: Dict[str, Set[str]] = {}
        # column -> (sql_table, sql_field) -> covered value subset
        self.hits: Dict[str, Dict[Tuple[str, str], Set[str]]] = {}
        self.mapping: Dict[str, Tuple[str, str]] = {}

    def set_header(self, header: Iterable[str]) -> None:
        self.header = [h.strip() for h in header]
        for column in self.header:
            self.values[column] = set()
            self.hits[column] = {}

    def add_row(self, row: Iterable[str]) -> None:
        for column, value in zip(self.header, row):
            value = normalize_value(value)
            if value:
                self.values[column].add(value)

    @property
    def unmapped_columns(self) -> List[str]:
        return [c for c in self.header if c not in self.mapping]

    def observe(self, sql_table: str, sql_field: str, value: str) -> None:
        tag = (sql_table, sql_field)
        for column in self.header:
            if column in self.mapping:
                continue
            if value in self.values[column]:
                self.hits[column].setdefault(tag, set()).add(value)

    def resolve_near_matches(self) -> None:
        """Map every still-unmapped column whose best candidate covers
        >= NEAR_MATCH_THRESHOLD of its distinct values."""
        for column in self.unmapped_columns:
            total = len(self.values[column])
            if total == 0:
                continue
            best_tag, best_cover = None, 0
            for tag, covered in self.hits[column].items():
                if len(covered) > best_cover:
                    best_tag, best_cover = tag, len(covered)
            if best_tag is not None and best_cover >= NEAR_MATCH_THRESHOLD * total:
                self.mapping[column] = best_tag

    def all_mapped(self) -> bool:
        return bool(self.header) and not self.unmapped_columns


class PrecomputedTables:
    def __init__(self, dir_name: str):
        self.dir_name = dir_name
        self.tables: Dict[str, ReportTable] = {}
        self.preloaded = False
        # a NON-EMPTY mapping.txt short-circuits discovery entirely: report
        # files (GBs on a real release) are not even read — stub tables are
        # reconstructed from the mapping lines.  An empty file (a previous
        # run that resolved nothing) does NOT count as preloaded, so fixing
        # the release pairing and re-running rediscovers.  Delete
        # mapping.txt to force rediscovery.
        mapping_path = os.path.join(dir_name, "mapping.txt")
        if os.path.exists(mapping_path) and os.path.getsize(mapping_path) > 0:
            self.load_mapping(mapping_path)
            self.preloaded = bool(self.tables)
            if self.preloaded:
                return
        for path in sorted(glob.glob(os.path.join(dir_name, "*.tsv"))):
            table = ReportTable(os.path.basename(path))
            self._load_tsv(path, table)
            self.tables[table.name] = table
        for path in sorted(glob.glob(os.path.join(dir_name, "ncRNA_genes_*.json"))):
            for table in self._load_ncrna(path):
                self.tables[table.name] = table

    # -- loading -----------------------------------------------------------

    def _load_tsv(self, path: str, table: ReportTable) -> None:
        """FlyBase report TSVs carry the header as the LAST '#' comment
        line before the data (the reference's `previous` trick,
        precomputed_tables.py:190-204)."""
        previous: Optional[List[str]] = None
        with open(path, newline="") as fh:
            for row in csv.reader(fh, delimiter="\t", quotechar='"'):
                if not row:
                    continue
                if row[0].startswith("#"):
                    if not row[0].startswith("#-----"):
                        previous = row
                    continue
                if not table.header:
                    header = previous or [f"c{i}" for i in range(len(row))]
                    table.set_header([header[0].lstrip("#"), *header[1:]])
                table.add_row(row)

    def _load_ncrna(self, path: str) -> List[ReportTable]:
        """Flatten the ncRNA genes JSON into the reference's derived
        sub-tables (main + synonyms + related sequences + publications +
        genome locations, precomputed_tables.py:207-260)."""
        with open(path) as fh:
            doc = json.load(fh)
        main = ReportTable("ncRNA_main")
        main.set_header(
            ["primaryId", "symbol", "sequence", "taxonId", "soTermId",
             "gene_geneId", "gene_symbol", "gene_locusTag"]
        )
        synonyms = ReportTable("ncRNA_synonyms")
        synonyms.set_header(["symbol1", "symbol2"])
        publications = ReportTable("ncRNA_publications")
        publications.set_header(["primaryId", "publication"])
        related = ReportTable("ncRNA_related_sequences")
        related.set_header(["primaryId", "sequenceId", "relationship"])
        for row in doc.get("data", []):
            gene = row.get("gene", {})
            main.add_row([
                row.get("primaryId", ""), row.get("symbol", ""),
                row.get("sequence", ""), row.get("taxonId", ""),
                row.get("soTermId", ""), gene.get("geneId", ""),
                gene.get("symbol", ""), gene.get("locusTag", ""),
            ])
            for syn in row.get("symbolSynonyms", []):
                synonyms.add_row([row.get("symbol", ""), syn])
            for pub in row.get("publications", []):
                publications.add_row([row.get("primaryId", ""), pub])
            for rel in row.get("relatedSequences", []):
                related.add_row([
                    row.get("primaryId", ""),
                    rel.get("sequenceId", ""),
                    rel.get("relationship", ""),
                ])
        return [main, synonyms, publications, related]

    # -- discovery ---------------------------------------------------------

    def observe(self, sql_table: str, sql_field: str, value: str) -> None:
        value = normalize_value(value)
        if not value or value == "\\N":
            return
        for table in self.tables.values():
            if not table.all_mapped():
                table.observe(sql_table, sql_field, value)

    def resolve(self) -> None:
        for table in self.tables.values():
            table.resolve_near_matches()

    def relevant_sql_tables(self) -> Set[str]:
        out: Set[str] = set()
        for table in self.tables.values():
            for sql_table, _field in table.mapping.values():
                out.add(sql_table)
        return out

    # -- persistence (reference mapping.txt TSV format) --------------------

    def save_mapping(self, path: Optional[str] = None) -> str:
        path = path or os.path.join(self.dir_name, "mapping.txt")
        with open(path, "w") as fh:
            for name, table in sorted(self.tables.items()):
                for column, (sql_table, sql_field) in sorted(table.mapping.items()):
                    fh.write(f"{name}\t{column}\t{sql_table}\t{sql_field}\n")
        return path

    def load_mapping(self, path: str) -> None:
        with open(path) as fh:
            for line in fh:
                parts = line.rstrip("\n").split("\t")
                if len(parts) != 4:
                    continue
                fname, column, sql_table, sql_field = parts
                table = self.tables.get(fname)
                if table is None:
                    # preload without report files: stub table from mapping
                    table = ReportTable(fname)
                    self.tables[fname] = table
                if column not in table.header:
                    table.header.append(column)
                    table.values[column] = set()
                    table.hits[column] = {}
                table.mapping[column] = (sql_table, sql_field)

    def mappings_str(self) -> str:
        lines = []
        mapped = {n: t for n, t in self.tables.items() if t.all_mapped()}
        lines.append(f"Fully mapped tables: {len(mapped)}")
        for name, table in sorted(self.tables.items()):
            lines.append(name)
            for column in table.header:
                tag = table.mapping.get(column)
                tgt = f"{tag[0]} {tag[1]}" if tag else "???"
                lines.append(f"\t{column} -> {tgt}")
        return "\n".join(lines) + "\n"
