"""Differential battery for the Pallas fused query kernels
(das_tpu/kernels/): interpret-mode kernels must produce IDENTICAL
outputs to the lowered op chains they replace, over randomized posting
tables, binding tables and capacities — including the capacity-overflow
retry path — plus the end-to-end bio 3-var conjunctive query, and a
dispatch-count regression pin so a future refactor can't silently
re-fragment the fused pipeline.

Run standalone (e.g. on a TPU host, where the kernels compile instead of
interpreting): `ops/pytests.sh kernels`.

(The file sorts AFTER the seed suite on purpose: kernel programs cost
seconds of XLA compile each, and on hosts where the tier-1 wall-clock
budget is tight this suite should spend tail budget rather than displace
the seed tests' dots.)"""

import random

import numpy as np
import pytest

pytestmark = pytest.mark.kernels

import jax.numpy as jnp

from das_tpu import kernels
from das_tpu.core.config import DasConfig
from das_tpu.kernels.join import index_join_impl
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.ops import posting
from das_tpu.ops.join import (
    _build_term_table_impl,
    _index_join_impl,
    _join_tables_impl,
)
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, Node, PatternMatchingAnswer, Variable
from das_tpu.storage.tensor_db import TensorDB

#: every (shape, capacity, static-meta) combo is one compiled program per
#: side; data re-draws under the same combo are cache hits — coverage
#: scales with DRAWS at compile cost fixed by the combo lists
N_DRAWS = 3


def _lowered_probe_chain(keys, perm, targets, key, fvals, cap,
                         var_cols, eq_pairs, extra_fixed):
    """The exact op sequence the kernel replaces (ops/posting.py
    range_probe → positional verify → ops/join.py build_term_table)."""
    local, valid, cnt = posting.range_probe(keys, perm, key, cap)
    mask = valid
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    for i, pos in enumerate(extra_fixed):
        mask = mask & (targets[safe, pos] == fvals[i])
    vals, mask = _build_term_table_impl(targets, local, mask, var_cols, eq_pairs)
    return vals, mask, cnt


#: (n_rows, arity, capacity, var_cols, eq_pairs, extra_fixed) — covers
#: wildcard, grounded-extra, repeated-variable and tiny-capacity shapes
PROBE_COMBOS = [
    (48, 2, 16, (0, 1), (), ()),
    (33, 3, 8, (1, 2), (), (0,)),
    (48, 3, 6, (0, 1, 2), ((0, 2),), ()),
    (16, 2, 32, (1,), (), (0,)),
]


def test_probe_kernel_matches_lowered_fuzz():
    rng = np.random.default_rng(1234)
    for ci, (n, arity, cap, var_cols, eq_pairs, extra_fixed) in enumerate(
        PROBE_COMBOS
    ):
        for draw in range(N_DRAWS):
            keys = jnp.asarray(np.sort(rng.integers(0, 12, n)).astype(np.int64))
            perm = jnp.asarray(rng.permutation(n).astype(np.int32))
            targets = jnp.asarray(
                rng.integers(0, 10, (n, arity)).astype(np.int32)
            )
            key = np.int64(rng.integers(0, 14))  # present and absent keys
            fvals = jnp.asarray(
                rng.integers(0, 10, len(extra_fixed)).astype(np.int32)
            )
            label = f"combo={ci} draw={draw}"
            want = _lowered_probe_chain(
                keys, perm, targets, key, fvals, cap,
                var_cols, eq_pairs, extra_fixed,
            )
            got = kernels.probe_term_table(
                keys, perm, targets, key, fvals, cap,
                var_cols=var_cols, eq_pairs=eq_pairs, extra_fixed=extra_fixed,
            )
            assert int(got[2]) == int(want[2]), label
            assert np.array_equal(np.asarray(got[1]), np.asarray(want[1])), label
            assert np.array_equal(np.asarray(got[0]), np.asarray(want[0])), label
            if int(got[2]) > cap:
                # capacity-overflow retry: the exact count drives a
                # doubled re-probe exactly like the lowered retry loop
                # (cap2 is pinned per combo so the retry compiles once)
                cap2 = 64
                want2 = _lowered_probe_chain(
                    keys, perm, targets, key, fvals, cap2,
                    var_cols, eq_pairs, extra_fixed,
                )
                got2 = kernels.probe_term_table(
                    keys, perm, targets, key, fvals, cap2,
                    var_cols=var_cols, eq_pairs=eq_pairs,
                    extra_fixed=extra_fixed,
                )
                assert int(got2[2]) == int(want2[2]) <= cap2, label
                assert np.array_equal(
                    np.asarray(got2[0]), np.asarray(want2[0])
                ), label


#: (L, R, kl, kr, n_pairs, right_extra, capacity) — covers equi-join,
#: multi-pair, cross product (0 pairs), and undersized capacities
JOIN_COMBOS = [
    (40, 30, 2, 2, 1, (1,), 64),
    (25, 40, 3, 3, 2, (2,), 16),   # cap 16 forces the overflow report
    (12, 9, 1, 2, 0, (0, 1), 128),  # cross product
    (48, 48, 2, 1, 1, (), 96),
]


def test_join_kernel_matches_lowered_fuzz():
    rng = np.random.default_rng(99)
    for ci, (L, R, kl, kr, n_pairs, extra, cap) in enumerate(JOIN_COMBOS):
        pairs = tuple((i, i) for i in range(n_pairs))
        for draw in range(N_DRAWS):
            lv = jnp.asarray(rng.integers(0, 7, (L, kl)).astype(np.int32))
            rv = jnp.asarray(rng.integers(0, 7, (R, kr)).astype(np.int32))
            lm = jnp.asarray(rng.random(L) < 0.8)
            rm = jnp.asarray(rng.random(R) < 0.8)
            label = f"combo={ci} draw={draw}"
            want = _join_tables_impl(lv, lm, rv, rm, pairs, extra, cap)
            got = kernels.join_tables(lv, lm, rv, rm, pairs, extra, cap)
            assert int(got[2]) == int(want[2]), label
            assert np.array_equal(np.asarray(got[1]), np.asarray(want[1])), label
            assert np.array_equal(np.asarray(got[0]), np.asarray(want[0])), label
            if int(got[2]) > cap:
                cap2 = 4096  # fixed retry tier: one compile per combo
                want2 = _join_tables_impl(lv, lm, rv, rm, pairs, extra, cap2)
                got2 = kernels.join_tables(lv, lm, rv, rm, pairs, extra, cap2)
                assert int(got2[2]) == int(want2[2]) <= cap2, label
                assert np.array_equal(
                    np.asarray(got2[0]), np.asarray(want2[0])
                ), label


#: (n_rows, L, with_second_pair, capacity)
INDEX_COMBOS = [
    (50, 24, False, 64),
    (30, 16, True, 16),
]


def test_index_join_kernel_matches_lowered_fuzz():
    rng = np.random.default_rng(7)
    for ci, (m, L, second_pair, cap) in enumerate(INDEX_COMBOS):
        pairs = ((0, 0),) + (((1, 1),) if second_pair else ())
        right_var_cols = (0, 1)
        right_extra = (1,) if not second_pair else ()
        for draw in range(N_DRAWS):
            targets = rng.integers(0, 12, (m, 2)).astype(np.int32)
            type_key = 3
            keyarr = (np.int64(type_key) << 32) | targets[:, 0].astype(np.int64)
            perm = np.argsort(keyarr, kind="stable").astype(np.int32)
            keys_sorted = jnp.asarray(keyarr[perm])
            lv = jnp.asarray(rng.integers(0, 12, (L, 2)).astype(np.int32))
            lm = jnp.asarray(rng.random(L) < 0.85)
            label = f"combo={ci} draw={draw}"
            args = (
                lv, lm, keys_sorted, jnp.asarray(perm), jnp.asarray(targets),
                type_key, pairs, right_var_cols, right_extra, cap,
            )
            want = _index_join_impl(*args)
            got = index_join_impl(*args, interpret=True)
            assert int(got[2]) == int(want[2]), label
            assert np.array_equal(np.asarray(got[1]), np.asarray(want[1])), label
            assert np.array_equal(np.asarray(got[0]), np.asarray(want[0])), label


# -- end-to-end: the bio 3-var conjunctive query ---------------------------

@pytest.fixture(scope="module")
def bio_data():
    # sized so no capacity tier retries at initial_result_capacity=1024:
    # every extra tier is one more compiled program in this suite's budget
    data, _, _ = build_bio_atomspace(
        n_genes=30, n_processes=10, members_per_gene=3,
        n_interactions=40, n_evaluations=10,
    )
    return data


@pytest.fixture(scope="module")
def db_off(bio_data):
    return TensorDB(
        bio_data,
        DasConfig(use_pallas_kernels="off", initial_result_capacity=1024),
    )


@pytest.fixture(scope="module")
def db_on(bio_data):
    return TensorDB(
        bio_data,
        DasConfig(use_pallas_kernels="on", initial_result_capacity=1024),
    )


def _three_var():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


def _grounded(gene):
    return And([
        Link("Member", [Node("Gene", gene), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Node("Gene", gene), Variable("V2")], True),
    ])


def _answer_set(db, query):
    answer = PatternMatchingAnswer()
    matched = compiler.query_on_device(db, query, answer)
    assert matched is not None, "device path declined"
    return {a.hash for a in answer.assignments}


def test_kernel_path_bio_query_identity(db_off, db_on):
    """Kernel-routed execution returns the identical result set to the
    lowered path on the bio 3-var conjunctive query (the north-star query
    shape), fused and staged; the grounded variant (int64 type_pos probe
    keys + extra_fixed verification in-program) is held to count
    identity — its kernel routes are pinned value-exactly by the unit
    fuzz combos above, and every extra materializing program here is
    ~7 s of tier-1 compile budget."""
    q = _three_var()
    want = _answer_set(db_off, q)
    assert _answer_set(db_on, q) == want
    assert compiler.count_matches(db_on, q) == len(want)
    gene = db_off.get_all_nodes("Gene", names=True)[0]
    assert compiler.count_matches(db_on, _grounded(gene)) == (
        compiler.count_matches(db_off, _grounded(gene))
    )
    # staged pipeline (the fused path's fallback) through the kernels too
    plans = compiler.plan_query(db_on, q)
    staged = compiler.execute_plan(db_on, plans)
    assert staged.count == len(want)


def test_kernel_capacity_overflow_retry_end_to_end(bio_data, db_off):
    """A deliberately tiny initial capacity forces the overflow retry in
    both the fused program (stats-driven re-dispatch) and the staged
    probes — answers must still be exact."""
    db_small = TensorDB(
        bio_data,
        DasConfig(use_pallas_kernels="on", initial_result_capacity=16),
    )
    q = _three_var()
    assert _answer_set(db_small, q) == _answer_set(db_off, q)


def test_dispatch_count_regression(db_off):
    """Pin the per-query device-dispatch totals so a refactor can't
    silently re-fragment the pipeline:

      * fused executor: the WHOLE 3-var plan is ONE program dispatch;
      * staged pipeline: the kernel route strictly under-dispatches the
        lowered route (probe+verify+table fuse into one Pallas call per
        term; the join's sort-probe cascade into one per join).
    """
    from das_tpu.query.fused import get_executor

    db = db_off
    plans = compiler.plan_query(db, _three_var())
    ex = get_executor(db)

    # fused: warm (compile + capacity learning), then count one execution
    assert ex.execute(plans, count_only=True) is not None
    kernels.reset_dispatch_counts()
    res = ex.execute(plans, count_only=True)
    assert res is not None and not res.overflow
    assert kernels.DISPATCH_COUNTS["fused"] == 1, kernels.DISPATCH_COUNTS

    # staged, lowered: 3 terms x (probe + term-table + dedup) +
    # 2 joins x (join + dedup) = 13 single-op dispatches
    kernels.reset_dispatch_counts()
    table = compiler.execute_plan(db, plans)
    lowered = dict(kernels.DISPATCH_COUNTS)
    assert lowered["kernel"] == 0
    assert lowered["lowered"] == 13, lowered

    # staged, kernel route: probe chain fuses to 1 dispatch per term and
    # the join inner loop to 1 per join; only dedup stays lowered
    db.config.use_pallas_kernels = "on"
    try:
        kernels.reset_dispatch_counts()
        table_k = compiler.execute_plan(db, plans)
        kernel = dict(kernels.DISPATCH_COUNTS)
    finally:
        db.config.use_pallas_kernels = "off"
    assert kernel["kernel"] == 5, kernel          # 3 probes + 2 joins
    assert kernel["lowered"] == 5, kernel         # 5 dedup passes
    total_kernel = kernel["kernel"] + kernel["lowered"]
    total_lowered = lowered["kernel"] + lowered["lowered"]
    assert total_kernel < total_lowered, (kernel, lowered)
    assert table_k.count == table.count


def test_kernel_route_counter(db_on):
    compiler.reset_route_counts()
    answer = PatternMatchingAnswer()
    compiler.query_on_device(db_on, _three_var(), answer)
    assert compiler.ROUTE_COUNTS["fused"] == 1
    assert compiler.ROUTE_COUNTS["fused_kernel"] == 1


def test_pallas_interpreter_parity(monkeypatch):
    """The REAL Pallas interpreter (`interpret=True` pallas_call, forced
    via DAS_TPU_PALLAS_INTERPRET=1) agrees with the direct-discharge
    execution on a fixed probe and join shape — so the actual pallas_call
    lowering stays covered even though the suite's default off-TPU
    execution skips the interpreter's per-call-site compile cost.  Shapes
    here are unique to this test: a jit cache hit from an earlier test
    would bypass the env flag (it is read at trace time)."""
    rng = np.random.default_rng(5)
    n = 13
    keys = jnp.asarray(np.sort(rng.integers(0, 9, n)).astype(np.int64))
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    targets = jnp.asarray(rng.integers(0, 9, (n, 3)).astype(np.int32))
    fvals = jnp.asarray([4], dtype=np.int32)
    probe_args = dict(var_cols=(1, 2), eq_pairs=(), extra_fixed=(0,))
    # oracles via the LOWERED impls (not the kernel wrappers: a warm jit
    # cache entry for these shapes would short-circuit the env flag)
    want = _lowered_probe_chain(
        keys, perm, targets, np.int64(4), fvals, 9, (1, 2), (), (0,)
    )
    lvn, rvn = 11, 9
    lv = jnp.asarray(rng.integers(0, 5, (lvn, 2)).astype(np.int32))
    rv = jnp.asarray(rng.integers(0, 5, (rvn, 2)).astype(np.int32))
    lm = jnp.ones((lvn,), bool)
    rm = jnp.ones((rvn,), bool)
    want_j = _join_tables_impl(lv, lm, rv, rm, ((0, 0),), (1,), 77)

    monkeypatch.setenv("DAS_TPU_PALLAS_INTERPRET", "1")
    got = kernels.probe_term_table(
        keys, perm, targets, np.int64(4), fvals, 9, **probe_args
    )
    got_j = kernels.join_tables(lv, lm, rv, rm, ((0, 0),), (1,), 77)
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(got_j, want_j):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_route_label_and_enabled_resolution():
    assert kernels.enabled(DasConfig(use_pallas_kernels="on"))
    assert not kernels.enabled(DasConfig(use_pallas_kernels="off"))
    # auto follows the platform (off-TPU in this suite)
    auto = kernels.enabled(DasConfig(use_pallas_kernels="auto"))
    assert auto == (not kernels.interpret_mode())
    assert kernels.route_label(DasConfig(use_pallas_kernels="off")) == "off"
    on_label = kernels.route_label(DasConfig(use_pallas_kernels="on"))
    assert on_label in ("pallas", "pallas-interpret")
    if kernels.interpret_mode():
        assert on_label == "pallas-interpret"
