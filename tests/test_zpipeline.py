"""Serving pipeline + delta-versioned result cache (ISSUE 2).

Pins, in one place (marker `pipeline`, standalone via
`ops/pytests.sh pipeline`):

  * a result-cache hit issues ZERO device programs and zero host fetches;
  * pipelined coalescer execution (depth 2) issues exactly the same total
    device-program count as serial (depth 1) and identical answers — the
    pipeline changes overlap, never work;
  * cache invalidation across incremental commits: a query answered from
    cache before `intern_delta` reflects the new atoms after the commit,
    on BOTH TensorDB and ShardedDB (the delta_version key);
  * per-query failure isolation: one bad query in a coalesced batch fails
    only its own future;
  * the config knobs (pipeline_depth, result_cache_size) and the serving
    stats surface.

Compile-budget note (ROADMAP tier-1): every query here reuses ONE fused
plan shape on the small animals KB, so the suite costs a handful of XLA
compiles total.
"""

import threading
from concurrent.futures import Future

import pytest

from das_tpu import kernels
from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.models.animals import animals_metta
from das_tpu.query import compiler, fused
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.pipeline

#: extends _pair_query's answer set: chimp→mammal exists, so the new
#: platypus→chimp edge adds ($1=platypus, $2=chimp) exactly after commit
COMMIT = '(: "platypus" Concept)\n(Inheritance "platypus" "chimp")'


def _pair_query():
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Node("Concept", "mammal")], True),
    ])


def _tensor_das(config=None):
    data = load_metta_text(animals_metta())
    db = TensorDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zp", db=db), db


def _sharded_das(config=None):
    from das_tpu.parallel.sharded_db import ShardedDB

    data = load_metta_text(animals_metta())
    db = ShardedDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zps", db=db), db


# -- result cache ---------------------------------------------------------


def test_cache_hit_issues_zero_device_programs():
    """The acceptance pin: a repeated query through the serving path is a
    pure host dict lookup — no program dispatch, no host transfer."""
    das, db = _tensor_das()
    q = _pair_query()
    first = das.query_many([q, q])  # 1 program: in-batch dedup aliases #2
    ex = fused.get_executor(db)
    assert ex.results.stats["misses"] >= 1

    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = das.query_many([q, q])
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches, "cache hit paid a host fetch"
    assert kernels.DISPATCH_COUNTS["fused"] == 0, kernels.DISPATCH_COUNTS
    assert kernels.DISPATCH_COUNTS["kernel"] == 0
    assert kernels.DISPATCH_COUNTS["lowered"] == 0


def test_cache_disabled_by_zero_size():
    das, db = _tensor_das(DasConfig(result_cache_size=0))
    q = _pair_query()
    das.query_many([q, q])
    ex = fused.get_executor(db)
    assert ex.results.stats["hits"] == 0
    kernels.reset_dispatch_counts()
    das.query_many([q])
    assert kernels.DISPATCH_COUNTS["fused"] >= 1


def test_single_execute_stays_uncached_by_default():
    """test_zkernels' dispatch-count pins rely on bare execute() timing
    the device — the cache must be opt-in there."""
    das, db = _tensor_das()
    plans = compiler.plan_query(db, _pair_query())
    ex = fused.get_executor(db)
    assert ex.execute(plans, count_only=True) is not None
    kernels.reset_dispatch_counts()
    assert ex.execute(plans, count_only=True) is not None
    assert kernels.DISPATCH_COUNTS["fused"] == 1

    # ... and the opt-in flag caches: second call is dispatch-free
    assert ex.execute(plans, count_only=True, use_cache=True) is not None
    kernels.reset_dispatch_counts()
    assert ex.execute(plans, count_only=True, use_cache=True) is not None
    assert kernels.DISPATCH_COUNTS["fused"] == 0


def test_cache_invalidation_across_commit_tensor():
    das, db = _tensor_das()
    q = _pair_query()
    # content-addressed handle: computable before the node exists
    platypus = db.get_node_handle("Concept", "platypus")
    before = das.query_many([q, q])
    assert platypus not in before[0]
    version = db.delta_version
    das.load_metta_text(COMMIT)  # incremental commit (intern_delta)
    assert db.delta_version > version
    assert db._delta_total > 0, "commit must have taken the delta path"
    after = das.query_many([q, q])
    assert after != before and platypus in after[0]
    assert after == [das.query(q), das.query(q)]  # uncached ground truth
    ex = fused.get_executor(db)
    assert ex.results.stats["invalidations"] >= 1


def test_cache_invalidation_across_commit_sharded():
    das, db = _sharded_das()
    q = _pair_query()
    a1 = das.query(q)
    assert das.query(q) == a1
    ex = db.tables._fused_executor
    assert ex.results.stats["hits"] >= 1, "sharded repeat must hit"
    version = db.delta_version
    das.load_metta_text(COMMIT)
    assert db.delta_version > version
    a2 = das.query(q)
    assert a2 != a1 and db.get_node_handle("Concept", "platypus") in a2
    # ground truth: a fresh sharded store over the same data agrees
    from das_tpu.parallel.sharded_db import ShardedDB

    fresh = ShardedDB(das.data, config=db.config, mesh=db.mesh)
    fresh_das = DistributedAtomSpace(database_name="zps2", db=fresh)
    assert a2 == fresh_das.query(q)


# -- coalescer pipeline ---------------------------------------------------


class _FakeTenant:
    def __init__(self, das):
        self.das = das
        self.lock = threading.RLock()


def _drive(coalescer, tenant, queries, fmt=None):
    from das_tpu.api.atomspace import QueryOutputFormat

    fmt = fmt or QueryOutputFormat.HANDLE
    futs = [coalescer.submit(tenant, q, fmt) for q in queries]
    return [f.result(timeout=60) for f in futs]


def test_pipelined_matches_serial_answers_and_program_count():
    """Pipelining changes WHEN device programs run relative to host
    settle, never HOW MANY: depth 2 and depth 1 issue identical fused
    program counts and identical answers over the same workload.  Cache
    off so every query really exercises the device; DISTINCT groundings
    so neither in-batch dedup nor batch-formation noise can alias work;
    one warm-up pass first so capacity learning can't skew either arm."""
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenant = _FakeTenant(das)

    def grounded(concept):
        return And([
            Link("Inheritance", [Variable("$1"), Variable("$2")], True),
            Link("Inheritance", [Variable("$2"), Node("Concept", concept)], True),
        ])

    concepts = ["mammal", "animal", "reptile", "plant", "dinosaur", "monkey"]
    das.query_many([grounded(c) for c in concepts])  # warm compile + caps

    serial = QueryCoalescer(max_batch=2, pipeline_depth=1)
    kernels.reset_dispatch_counts()
    serial_answers = _drive(serial, tenant, [grounded(c) for c in concepts])
    serial_programs = kernels.DISPATCH_COUNTS["fused"]

    piped = QueryCoalescer(max_batch=2, pipeline_depth=2)
    kernels.reset_dispatch_counts()
    piped_answers = _drive(piped, tenant, [grounded(c) for c in concepts])
    piped_programs = kernels.DISPATCH_COUNTS["fused"]

    assert piped_answers == serial_answers
    assert serial_programs == len(concepts)  # cache really was off
    assert piped_programs == serial_programs, (piped_programs, serial_programs)


def test_pipeline_inflight_peak_reaches_depth():
    """Under a backlog the worker must actually run batches in flight
    concurrently (dispatch N+1 before settling N)."""
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.api.atomspace import QueryOutputFormat

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=1, pipeline_depth=2)
    # enqueue a backlog BEFORE the worker starts so the window can fill
    futs = [
        (c._queue.put((tenant, _pair_query(), QueryOutputFormat.HANDLE, f)), f)[1]
        for f in (Future() for _ in range(8))
    ]
    c._ensure_worker()
    answers = [f.result(timeout=60) for f in futs]
    assert len(set(answers)) == 1
    assert c.stats["inflight_peak"] >= 2, c.stats
    assert c.stats["pipeline_depth"] == 2


def test_commit_between_dispatch_and_settle_rerouted():
    """A commit landing between a batch's dispatch and its settle may
    re-intern global row ids (a FULL re-finalize moves every link row):
    settle must drop the pre-commit dispatched round and re-answer on the
    post-commit store instead of materializing stale rows."""
    # threshold 0 forces every commit onto the FULL re-finalize path —
    # the worst case, where row ids actually move
    das, db = _tensor_das(DasConfig(delta_merge_threshold=0))
    q = _pair_query()
    expected_before = das.query(q)
    job = das.query_many_dispatch([q, q])   # dispatched, not settled
    das.load_metta_text(COMMIT)             # FULL refresh races in
    out = job.settle()
    expected_after = das.query(q)
    assert expected_after != expected_before
    assert out == [expected_after, expected_after]

    # ... and a settle with NO intervening commit keeps the fast path
    job2 = das.query_many_dispatch([q])
    assert job2.settle() == [expected_after]


def test_multi_tenant_batch_honors_pipeline_depth():
    """A drained batch that splits into several (tenant, fmt) groups must
    not overshoot the configured in-flight bound: extra groups wait
    undispatched."""
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.api.atomspace import QueryOutputFormat

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenants = [_FakeTenant(das), _FakeTenant(das), _FakeTenant(das)]
    c = QueryCoalescer(max_batch=16, pipeline_depth=1)
    fmt = QueryOutputFormat.HANDLE
    futs = []
    for t in tenants:  # one backlog batch spanning three tenant groups
        for _ in range(2):
            f = Future()
            c._queue.put((t, _pair_query(), fmt, f))
            futs.append(f)
    c._ensure_worker()
    answers = [f.result(timeout=60) for f in futs]
    assert len(set(answers)) == 1
    assert c.stats["inflight_peak"] == 1, c.stats


def test_per_query_failure_isolated_to_its_future():
    """One bad query in a coalesced batch fails only its own future —
    batch-mates keep their answers (the _run_group-granularity swallow is
    gone)."""
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.api.atomspace import QueryOutputFormat

    class Boom:
        """Unplannable (falls to the host path) and then explodes."""

        def matched(self, db, answer):
            raise RuntimeError("poisoned query")

    das, db = _tensor_das()
    tenant = _FakeTenant(das)
    good = _pair_query()
    expected = das.query(good)
    c = QueryCoalescer(max_batch=3, pipeline_depth=1)
    fmt = QueryOutputFormat.HANDLE
    group = [
        (tenant, good, fmt, Future()),
        (tenant, Boom(), fmt, Future()),
        (tenant, good, fmt, Future()),
    ]
    entry = c._dispatch_group(tenant, fmt, group)
    c._settle_group(entry)
    assert group[0][3].result(timeout=5) == expected
    assert group[2][3].result(timeout=5) == expected
    with pytest.raises(RuntimeError, match="poisoned"):
        group[1][3].result(timeout=5)


def test_knobs_flow_from_config_and_env(monkeypatch):
    from das_tpu.service.coalesce import QueryCoalescer

    # dataclass defaults are the deployment defaults
    assert QueryCoalescer().pipeline_depth == DasConfig.pipeline_depth
    assert QueryCoalescer(pipeline_depth=1).pipeline_depth == 1
    assert QueryCoalescer(pipeline_depth=0).pipeline_depth == 1  # clamped

    monkeypatch.setenv("DAS_TPU_PIPELINE_DEPTH", "5")
    monkeypatch.setenv("DAS_TPU_RESULT_CACHE", "17")
    cfg = DasConfig.from_env()
    assert cfg.pipeline_depth == 5
    assert cfg.result_cache_size == 17


def test_serving_stats_surface():
    """coalescer_stats() exposes the whole pipeline: batch counters,
    in-flight peak, cache hit/miss, and route counters."""
    from das_tpu.service.server import DasService

    das, db = _tensor_das()
    service = DasService()
    token = service.attach_tenant("zp_stats", das)
    q = "Node n Concept mammal, Link Inheritance $1 $2, Link Inheritance $2 n, AND"
    for _ in range(3):
        reply = service.query(
            {"key": token, "query": q, "output_format": "HANDLE"}
        )
        assert reply["success"], reply["msg"]
    stats = service.coalescer_stats()
    for key in (
        "batches", "items", "max_batch", "max_batch_limit",
        "pipeline_depth", "inflight_peak",
        "cache_hits", "cache_misses", "cache_invalidations", "routes",
    ):
        assert key in stats, key
    assert stats["items"] >= 3
    assert stats["cache_hits"] >= 1, stats  # repeats hit the result cache
    assert stats["pipeline_depth"] == das.config.pipeline_depth
