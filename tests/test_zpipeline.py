"""Serving pipeline + delta-versioned result cache (ISSUE 2).

Pins, in one place (marker `pipeline`, standalone via
`ops/pytests.sh pipeline`):

  * a result-cache hit issues ZERO device programs and zero host fetches;
  * pipelined coalescer execution (depth 2) issues exactly the same total
    device-program count as serial (depth 1) and identical answers — the
    pipeline changes overlap, never work;
  * cache invalidation across incremental commits: a query answered from
    cache before `intern_delta` reflects the new atoms after the commit,
    on BOTH TensorDB and ShardedDB (the delta_version key);
  * per-query failure isolation: one bad query in a coalesced batch fails
    only its own future;
  * the config knobs (pipeline_depth, result_cache_size) and the serving
    stats surface.

Compile-budget note (ROADMAP tier-1): every query here reuses ONE fused
plan shape on the small animals KB, so the suite costs a handful of XLA
compiles total.
"""

import threading
from concurrent.futures import Future

import pytest

from das_tpu import kernels
from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.models.animals import animals_metta
from das_tpu.query import compiler, fused
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.pipeline

#: extends _pair_query's answer set: chimp→mammal exists, so the new
#: platypus→chimp edge adds ($1=platypus, $2=chimp) exactly after commit
COMMIT = '(: "platypus" Concept)\n(Inheritance "platypus" "chimp")'


def _pair_query():
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Node("Concept", "mammal")], True),
    ])


def _tensor_das(config=None):
    data = load_metta_text(animals_metta())
    db = TensorDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zp", db=db), db


def _sharded_das(config=None):
    from das_tpu.parallel.sharded_db import ShardedDB

    data = load_metta_text(animals_metta())
    db = ShardedDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zps", db=db), db


# -- result cache ---------------------------------------------------------


def test_cache_hit_issues_zero_device_programs():
    """The acceptance pin: a repeated query through the serving path is a
    pure host dict lookup — no program dispatch, no host transfer."""
    das, db = _tensor_das()
    q = _pair_query()
    first = das.query_many([q, q])  # 1 program: in-batch dedup aliases #2
    ex = fused.get_executor(db)
    assert ex.results.stats["misses"] >= 1

    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = das.query_many([q, q])
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches, "cache hit paid a host fetch"
    assert kernels.DISPATCH_COUNTS["fused"] == 0, kernels.DISPATCH_COUNTS
    assert kernels.DISPATCH_COUNTS["kernel"] == 0
    assert kernels.DISPATCH_COUNTS["lowered"] == 0


def test_cache_disabled_by_zero_size():
    das, db = _tensor_das(DasConfig(result_cache_size=0))
    q = _pair_query()
    das.query_many([q, q])
    ex = fused.get_executor(db)
    assert ex.results.stats["hits"] == 0
    kernels.reset_dispatch_counts()
    das.query_many([q])
    assert kernels.DISPATCH_COUNTS["fused"] >= 1


def test_single_execute_stays_uncached_by_default():
    """test_zkernels' dispatch-count pins rely on bare execute() timing
    the device — the cache must be opt-in there."""
    das, db = _tensor_das()
    plans = compiler.plan_query(db, _pair_query())
    ex = fused.get_executor(db)
    assert ex.execute(plans, count_only=True) is not None
    kernels.reset_dispatch_counts()
    assert ex.execute(plans, count_only=True) is not None
    assert kernels.DISPATCH_COUNTS["fused"] == 1

    # ... and the opt-in flag caches: second call is dispatch-free
    assert ex.execute(plans, count_only=True, use_cache=True) is not None
    kernels.reset_dispatch_counts()
    assert ex.execute(plans, count_only=True, use_cache=True) is not None
    assert kernels.DISPATCH_COUNTS["fused"] == 0


def test_cache_invalidation_across_commit_tensor():
    das, db = _tensor_das()
    q = _pair_query()
    # content-addressed handle: computable before the node exists
    platypus = db.get_node_handle("Concept", "platypus")
    before = das.query_many([q, q])
    assert platypus not in before[0]
    version = db.delta_version
    das.load_metta_text(COMMIT)  # incremental commit (intern_delta)
    assert db.delta_version > version
    assert db._delta_total > 0, "commit must have taken the delta path"
    after = das.query_many([q, q])
    assert after != before and platypus in after[0]
    assert after == [das.query(q), das.query(q)]  # uncached ground truth
    ex = fused.get_executor(db)
    assert ex.results.stats["invalidations"] >= 1


def test_cache_invalidation_across_commit_sharded():
    das, db = _sharded_das()
    q = _pair_query()
    a1 = das.query(q)
    assert das.query(q) == a1
    ex = db.tables._fused_executor
    assert ex.results.stats["hits"] >= 1, "sharded repeat must hit"
    version = db.delta_version
    das.load_metta_text(COMMIT)
    assert db.delta_version > version
    a2 = das.query(q)
    assert a2 != a1 and db.get_node_handle("Concept", "platypus") in a2
    # ground truth: a fresh sharded store over the same data agrees
    from das_tpu.parallel.sharded_db import ShardedDB

    fresh = ShardedDB(das.data, config=db.config, mesh=db.mesh)
    fresh_das = DistributedAtomSpace(database_name="zps2", db=fresh)
    assert a2 == fresh_das.query(q)


# -- coalescer pipeline ---------------------------------------------------


class _FakeTenant:
    def __init__(self, das):
        self.das = das
        self.lock = threading.RLock()


def _drive(coalescer, tenant, queries, fmt=None):
    from das_tpu.api.atomspace import QueryOutputFormat

    fmt = fmt or QueryOutputFormat.HANDLE
    futs = [coalescer.submit(tenant, q, fmt) for q in queries]
    return [f.result(timeout=60) for f in futs]


def test_pipelined_matches_serial_answers_and_program_count():
    """Pipelining changes WHEN device programs run relative to host
    settle, never HOW MANY: depth 2 and depth 1 issue identical fused
    program counts and identical answers over the same workload.  Cache
    off so every query really exercises the device; DISTINCT groundings
    so neither in-batch dedup nor batch-formation noise can alias work;
    one warm-up pass first so capacity learning can't skew either arm."""
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenant = _FakeTenant(das)

    def grounded(concept):
        return And([
            Link("Inheritance", [Variable("$1"), Variable("$2")], True),
            Link("Inheritance", [Variable("$2"), Node("Concept", concept)], True),
        ])

    concepts = ["mammal", "animal", "reptile", "plant", "dinosaur", "monkey"]
    das.query_many([grounded(c) for c in concepts])  # warm compile + caps

    serial = QueryCoalescer(max_batch=2, pipeline_depth=1)
    kernels.reset_dispatch_counts()
    serial_answers = _drive(serial, tenant, [grounded(c) for c in concepts])
    serial_programs = kernels.DISPATCH_COUNTS["fused"]

    piped = QueryCoalescer(max_batch=2, pipeline_depth=2)
    kernels.reset_dispatch_counts()
    piped_answers = _drive(piped, tenant, [grounded(c) for c in concepts])
    piped_programs = kernels.DISPATCH_COUNTS["fused"]

    assert piped_answers == serial_answers
    assert serial_programs == len(concepts)  # cache really was off
    assert piped_programs == serial_programs, (piped_programs, serial_programs)


def test_pipeline_inflight_peak_reaches_depth():
    """Under a backlog the worker must actually run batches in flight
    concurrently (dispatch N+1 before settling N)."""
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.api.atomspace import QueryOutputFormat

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=1, pipeline_depth=2)
    # enqueue a backlog BEFORE the worker starts so the window can fill
    futs = [
        (c._queue.put((tenant, _pair_query(), QueryOutputFormat.HANDLE, f)), f)[1]
        for f in (Future() for _ in range(8))
    ]
    c._ensure_worker()
    answers = [f.result(timeout=60) for f in futs]
    assert len(set(answers)) == 1
    assert c.stats["inflight_peak"] >= 2, c.stats
    assert c.stats["pipeline_depth"] == 2


def test_commit_between_dispatch_and_settle_rerouted():
    """A commit landing between a batch's dispatch and its settle may
    re-intern global row ids (a FULL re-finalize moves every link row):
    settle must drop the pre-commit dispatched round and re-answer on the
    post-commit store instead of materializing stale rows."""
    # threshold 0 forces every commit onto the FULL re-finalize path —
    # the worst case, where row ids actually move
    das, db = _tensor_das(DasConfig(delta_merge_threshold=0))
    q = _pair_query()
    expected_before = das.query(q)
    job = das.query_many_dispatch([q, q])   # dispatched, not settled
    das.load_metta_text(COMMIT)             # FULL refresh races in
    out = job.settle()
    expected_after = das.query(q)
    assert expected_after != expected_before
    assert out == [expected_after, expected_after]

    # ... and a settle with NO intervening commit keeps the fast path
    job2 = das.query_many_dispatch([q])
    assert job2.settle() == [expected_after]


def test_multi_tenant_batch_honors_pipeline_depth():
    """A drained batch that splits into several (tenant, fmt) groups must
    not overshoot the configured in-flight bound: extra groups wait
    undispatched."""
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.api.atomspace import QueryOutputFormat

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenants = [_FakeTenant(das), _FakeTenant(das), _FakeTenant(das)]
    c = QueryCoalescer(max_batch=16, pipeline_depth=1)
    fmt = QueryOutputFormat.HANDLE
    futs = []
    for t in tenants:  # one backlog batch spanning three tenant groups
        for _ in range(2):
            f = Future()
            c._queue.put((t, _pair_query(), fmt, f))
            futs.append(f)
    c._ensure_worker()
    answers = [f.result(timeout=60) for f in futs]
    assert len(set(answers)) == 1
    assert c.stats["inflight_peak"] == 1, c.stats


def test_per_query_failure_isolated_to_its_future():
    """One bad query in a coalesced batch fails only its own future —
    batch-mates keep their answers (the _run_group-granularity swallow is
    gone)."""
    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.api.atomspace import QueryOutputFormat

    class Boom:
        """Unplannable (falls to the host path) and then explodes."""

        def matched(self, db, answer):
            raise RuntimeError("poisoned query")

    das, db = _tensor_das()
    tenant = _FakeTenant(das)
    good = _pair_query()
    expected = das.query(good)
    c = QueryCoalescer(max_batch=3, pipeline_depth=1)
    fmt = QueryOutputFormat.HANDLE
    group = [
        (tenant, good, fmt, Future()),
        (tenant, Boom(), fmt, Future()),
        (tenant, good, fmt, Future()),
    ]
    entry = c._dispatch_group(tenant, fmt, group)
    c._settle_group(entry)
    assert group[0][3].result(timeout=5) == expected
    assert group[2][3].result(timeout=5) == expected
    with pytest.raises(RuntimeError, match="poisoned"):
        group[1][3].result(timeout=5)


def test_knobs_flow_from_config_and_env(monkeypatch):
    from das_tpu.service.coalesce import QueryCoalescer

    # dataclass defaults are the deployment defaults
    assert QueryCoalescer().pipeline_depth == DasConfig.pipeline_depth
    assert QueryCoalescer().pipeline_depth_max == DasConfig.pipeline_depth_max
    assert QueryCoalescer().queue_max == DasConfig.coalesce_queue_max
    assert QueryCoalescer(pipeline_depth=1).pipeline_depth == 1
    assert QueryCoalescer(pipeline_depth=0).pipeline_depth == 1  # clamped
    # the ceiling can never sit below the floor
    c = QueryCoalescer(pipeline_depth=5, pipeline_depth_max=2)
    assert c.pipeline_depth_max == 5

    monkeypatch.setenv("DAS_TPU_PIPELINE_DEPTH", "5")
    monkeypatch.setenv("DAS_TPU_PIPELINE_DEPTH_MAX", "11")
    monkeypatch.setenv("DAS_TPU_COALESCE_QUEUE_MAX", "33")
    monkeypatch.setenv("DAS_TPU_RESULT_CACHE", "17")
    cfg = DasConfig.from_env()
    assert cfg.pipeline_depth == 5
    assert cfg.pipeline_depth_max == 11
    assert cfg.coalesce_queue_max == 33
    assert cfg.result_cache_size == 17


def test_serving_stats_surface():
    """coalescer_stats() exposes the whole pipeline: batch counters,
    in-flight peak, the adaptive-window observables (ISSUE 6), cache
    hit/miss, and route counters."""
    from das_tpu.service.server import DasService

    das, db = _tensor_das()
    service = DasService()
    token = service.attach_tenant("zp_stats", das)
    q = "Node n Concept mammal, Link Inheritance $1 $2, Link Inheritance $2 n, AND"
    for _ in range(3):
        reply = service.query(
            {"key": token, "query": q, "output_format": "HANDLE"}
        )
        assert reply["success"], reply["msg"]
    stats = service.coalescer_stats()
    for key in (
        "batches", "items", "max_batch", "max_batch_limit",
        "pipeline_depth", "pipeline_depth_max", "effective_depth",
        "rtt_ewma_ms", "inflight_peak",
        "speculative_dispatches", "early_settles", "queue_rejections",
        "cache_hits", "cache_misses", "cache_invalidations", "routes",
    ):
        assert key in stats, key
    assert stats["items"] >= 3
    assert stats["cache_hits"] >= 1, stats  # repeats hit the result cache
    assert stats["pipeline_depth"] == das.config.pipeline_depth
    assert stats["effective_depth"] >= das.config.pipeline_depth
    assert stats["rtt_ewma_ms"] > 0.0  # settles actually fed the EWMA


# -- async end-to-end serving (ISSUE 6) -----------------------------------


def test_adaptive_depth_math():
    """The window-sizing formula: ceil(rtt / dispatch_cost) clamped to
    [pipeline_depth floor, pipeline_depth_max]; no samples → the floor;
    an explicit serial coalescer (depth 1) never adapts upward."""
    from das_tpu.service.coalesce import QueryCoalescer

    f = QueryCoalescer._depth_from
    assert f(0.0, 0.0, 2, 8) == 2        # no samples yet: the floor
    assert f(100.0, 30.0, 2, 8) == 4     # ceil(100/30)
    assert f(100.0, 1.0, 2, 8) == 8      # wants 100, clamped to the cap
    assert f(1.0, 5.0, 2, 8) == 2        # local dispatch: floor holds
    serial = QueryCoalescer(max_batch=1, pipeline_depth=1)
    serial.stats["rtt_ewma_ms"] = 500.0
    serial.stats["dispatch_ewma_ms"] = 1.0
    assert serial._effective_depth() == 1

    adaptive = QueryCoalescer(
        max_batch=1, pipeline_depth=2, pipeline_depth_max=6
    )
    adaptive.stats["rtt_ewma_ms"] = 90.0
    adaptive.stats["dispatch_ewma_ms"] = 10.0
    assert adaptive._effective_depth() == 6  # ceil(9) clamped to the cap
    adaptive.stats["rtt_ewma_ms"] = 45.0
    assert adaptive._effective_depth() == 5  # ceil(45/10) inside the band
    assert adaptive.stats["effective_depth"] == 5  # surfaced


def test_speculative_pipeline_matches_serial_program_count():
    """pipelined+SPECULATIVE == serial total program counts: a window
    deeper than one unsettled group changes WHEN dispatches happen
    relative to earlier settles, never HOW MANY programs run — and the
    dispatches issued past the first unsettled group are counted."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenant = _FakeTenant(das)

    def grounded(concept):
        return And([
            Link("Inheritance", [Variable("$1"), Variable("$2")], True),
            Link("Inheritance", [Variable("$2"), Node("Concept", concept)], True),
        ])

    concepts = ["mammal", "animal", "reptile", "plant", "dinosaur", "monkey"]
    das.query_many([grounded(c) for c in concepts])  # warm compile + caps

    serial = QueryCoalescer(max_batch=1, pipeline_depth=1)
    kernels.reset_dispatch_counts()
    serial_answers = _drive(serial, tenant, [grounded(c) for c in concepts])
    serial_programs = kernels.DISPATCH_COUNTS["fused"]

    # pre-queue the whole backlog so the depth-3 window actually fills
    # (submissions racing the worker could otherwise keep it starved)
    spec = QueryCoalescer(
        max_batch=1, pipeline_depth=3, pipeline_depth_max=6
    )
    kernels.reset_dispatch_counts()
    futs = []
    for c in concepts:
        f = Future()
        spec._queue.put((tenant, grounded(c), QueryOutputFormat.HANDLE, f))
        futs.append(f)
    spec._ensure_worker()
    spec_answers = [f.result(timeout=60) for f in futs]
    spec_programs = kernels.DISPATCH_COUNTS["fused"]

    assert spec_answers == serial_answers
    assert serial_programs == len(concepts)  # cache really was off
    assert spec_programs == serial_programs, (spec_programs, serial_programs)
    assert spec.stats["speculative_dispatches"] >= 1, spec.stats
    assert spec.stats["inflight_peak"] >= 3, spec.stats


def test_per_tenant_settle_order_preserved_under_speculation():
    """Settles stay FIFO however deep the window runs: a tenant's
    futures complete in dispatch order (max_batch=1 → one group per
    query, so completion order IS per-tenant settle order)."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das(DasConfig(result_cache_size=0))
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=1, pipeline_depth=4, pipeline_depth_max=8)
    order = []
    futs = []
    for n in range(6):
        f = Future()
        f.add_done_callback(lambda _f, n=n: order.append(n))
        c._queue.put((tenant, _pair_query(), QueryOutputFormat.HANDLE, f))
        futs.append(f)
    c._ensure_worker()
    answers = [f.result(timeout=60) for f in futs]
    assert len(set(answers)) == 1
    assert order == sorted(order), order


def test_commit_race_invalidation_under_speculation():
    """Two groups dispatched back-to-back — the second SPECULATIVE (the
    first never settled) — then a commit lands: each group's settle
    re-checks its dispatch-time delta version and re-answers on the
    post-commit store, however deep the window ran."""
    das, db = _tensor_das()
    q = _pair_query()
    platypus = db.get_node_handle("Concept", "platypus")
    before = das.query(q)
    job1 = das.query_many_dispatch([q, q])   # dispatched, not settled
    job2 = das.query_many_dispatch([q])      # speculative second group
    das.load_metta_text(COMMIT)              # commit races both windows
    expected = das.query(q)
    assert expected != before and platypus in expected
    assert job1.settle() == [expected, expected]
    assert job2.settle() == [expected]


def test_commit_mid_stream_invalidates_remaining_yields():
    """The PER-YIELD delta_version re-check: streaming paces settle to
    the consumer, so a commit can land BETWEEN yields — every entry not
    yet materialized must re-run on the post-commit store (the answers
    already yielded were consistent when they were delivered)."""
    das, db = _tensor_das()
    q = _pair_query()
    platypus = db.get_node_handle("Concept", "platypus")
    before = das.query(q)
    job = das.query_many_dispatch([q, q])
    it = job.settle_iter()
    first = next(it)                 # answered on the pre-commit store
    assert first == (0, before)
    das.load_metta_text(COMMIT)      # commit lands mid-stream
    expected = das.query(q)
    assert expected != before and platypus in expected
    assert dict(it) == {1: expected}


def test_fallback_only_groups_do_not_feed_rtt_ewma():
    """The rtt EWMA sizes the window from the STREAMED settle wait only.
    A group that degrades to the serial per-query fallback (dispatch
    failed, job=None) is host CPU work the single worker thread cannot
    overlap — feeding it into the estimator would deepen the window
    exactly when speculation buys nothing."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das()
    expected = das.query(_pair_query())
    tenant = _FakeTenant(das)

    def boom(*args, **kwargs):
        raise RuntimeError("no batched dispatch")

    das.query_many_dispatch = boom   # instance attr shadows the method
    c = QueryCoalescer(max_batch=4, pipeline_depth=2)
    fut = c.submit(tenant, _pair_query(), QueryOutputFormat.HANDLE)
    assert fut.result(timeout=60) == expected
    snap = c.snapshot()
    assert snap["rtt_ewma_ms"] == 0.0
    assert snap["dispatch_ewma_ms"] == 0.0  # no device enqueue happened
    assert snap["effective_depth"] == c.pipeline_depth


def test_early_settle_streams_before_group_completes():
    """The early-settle pin: settle_iter yields the fused-answered
    query's rows BEFORE the group's host-fallback member has even run —
    first rows one settle after the client's own dispatch, not after the
    whole group resolves."""
    from das_tpu.api.atomspace import QueryOutputFormat

    das, db = _tensor_das()

    class HostOnly:
        """Unplannable: resolves via the per-query dispatcher."""

        def matched(self, db_, answer):
            return False

    good = _pair_query()
    expected = das.query(good)
    calls = {"n": 0}
    real_query = das.query

    def counting_query(query, fmt=QueryOutputFormat.HANDLE):
        calls["n"] += 1
        return real_query(query, fmt)

    das.query = counting_query  # instance attr shadows the method
    try:
        job = das.query_many_dispatch([good, HostOnly()])
        it = job.settle_iter()
        first = next(it)
        assert first == (0, expected)
        assert calls["n"] == 0, "first rows must precede the fallback"
        rest = list(it)
    finally:
        del das.query
    assert [i for i, _ in rest] == [1]
    assert calls["n"] == 1  # exactly the host-fallback member


def test_early_settles_counted_for_wide_groups():
    """A streamed wide group counts every answer delivered before its
    group finished (all but the last), and the settle EWMA moves."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das()
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=4, pipeline_depth=1)
    fmt = QueryOutputFormat.HANDLE
    group = [(tenant, _pair_query(), fmt, Future()) for _ in range(3)]
    entry = c._dispatch_group(tenant, fmt, group)
    c._settle_group(entry)
    answers = [item[3].result(timeout=10) for item in group]
    assert len(set(answers)) == 1
    assert c.stats["early_settles"] == 2, c.stats
    assert c.stats["rtt_ewma_ms"] > 0.0
    assert c.stats["dispatch_ewma_ms"] > 0.0


def test_cache_hit_groups_do_not_feed_rtt_ewma():
    """The rtt estimator is fed the timed host TRANSFER only
    (settle_pending_iter times jax.device_get → job.settle_rtt_ms).  An
    all-hit group performs no fetch — reading its sub-ms streamed yields
    as the settle round-trip would collapse the adaptive window to the
    floor exactly on the hot cached workload — so it must leave the
    estimator untouched."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das()
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=4, pipeline_depth=2)
    fmt = QueryOutputFormat.HANDLE
    # first group: a real fetch populates the cache and feeds the EWMAs
    group = [(tenant, _pair_query(), fmt, Future())]
    c._settle_group(c._dispatch_group(tenant, fmt, group))
    first_answer = group[0][3].result(timeout=10)
    rtt_after_fetch = c.stats["rtt_ewma_ms"]
    dispatch_after_enqueue = c.stats["dispatch_ewma_ms"]
    assert rtt_after_fetch > 0.0
    assert dispatch_after_enqueue > 0.0
    # second group: pure cache hit, zero fetches, zero device enqueues —
    # NEITHER estimator may move toward the sub-ms hit latency (rtt
    # collapsing floors the window; dispatch collapsing pegs it at the
    # ceiling — both mis-size it on the hot cached workload)
    hit = [(tenant, _pair_query(), fmt, Future())]
    entry = c._dispatch_group(tenant, fmt, hit)
    c._settle_group(entry)
    assert hit[0][3].result(timeout=10) == first_answer
    assert entry[3].settle_rtt_ms is None, "all-hit group fetched nothing"
    assert c.stats["rtt_ewma_ms"] == rtt_after_fetch
    assert c.stats["dispatch_ewma_ms"] == dispatch_after_enqueue
    assert c.stats["early_settles"] == 0  # lone answers are never early


def test_cancelled_futures_do_not_count_as_early_settles():
    """Counter honesty: a client cancelling its future mid-settle still
    gets a yield from settle_iter, but nothing was DELIVERED — streamed
    and early_settles must only credit answers that actually reached a
    client."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das()
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=4, pipeline_depth=1)
    fmt = QueryOutputFormat.HANDLE
    group = [(tenant, _pair_query(), fmt, Future()) for _ in range(3)]
    entry = c._dispatch_group(tenant, fmt, group)
    assert group[0][3].cancel()      # client walks away mid-settle
    c._settle_group(entry)
    answers = [item[3].result(timeout=10) for item in group[1:]]
    assert len(set(answers)) == 1
    # 2 delivered, the last not early: 1 — NOT 2 (the cancelled yield)
    assert c.stats["early_settles"] == 1, c.stats
    # ... but when the CANCELLED yield comes last, the group kept
    # working after the final delivery, so both deliveries were early
    group2 = [(tenant, _pair_query(), fmt, Future()) for _ in range(3)]
    entry2 = c._dispatch_group(tenant, fmt, group2)
    assert group2[2][3].cancel()
    c._settle_group(entry2)
    assert group2[0][3].result(timeout=10) == answers[0]
    assert c.stats["early_settles"] == 1 + 2, c.stats


def test_settle_rtt_recorded_eagerly_mid_stream():
    """The settle round-trip is recorded at the FIRST post-fetch yield,
    not after the stream completes — a mid-stream failure abandoning the
    iterator must not drop the genuine wire sample (the estimator would
    hold a persistently-failing tenant at the floor despite a real
    ~100 ms wire)."""
    das, db = _tensor_das()
    job = das.query_many_dispatch([_pair_query()])
    it = job.settle_iter()
    next(it)                        # first post-fetch answer lands
    assert job.settle_rtt_ms is not None and job.settle_rtt_ms > 0.0
    sample = job.settle_rtt_ms
    it.close()                      # abandon mid-stream: sample survives
    assert job.settle_rtt_ms == sample


def test_commit_raced_groups_do_not_feed_rtt_ewma():
    """A commit landing between dispatch and settle drops the round to
    the per-query re-run path — host work with no fetch; the estimator
    must see None, not the re-run's compile+materialize time (which
    would peg effective_depth at the ceiling exactly when deeper
    speculation buys nothing)."""
    das, db = _tensor_das()
    platypus = db.get_node_handle("Concept", "platypus")
    job = das.query_many_dispatch([_pair_query()])
    das.load_metta_text(COMMIT)          # race: commit before settle
    answers = dict(job.settle_iter())    # re-answered post-commit
    assert platypus in answers[0]
    assert job.settle_rtt_ms is None


def test_early_settles_count_streams_before_fallback_resolutions():
    """A mid-stream settle failure hands the unresolved remainder to the
    per-query fallback loop — every answer that DID stream reached its
    client before the group finished, so all of them count as early
    (not streamed-minus-one, which undercounts exactly the mixed
    streamed+fallback groups where early delivery matters)."""
    from das_tpu.api.atomspace import QueryOutputFormat
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = _tensor_das()
    expected = das.query(_pair_query())
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=4, pipeline_depth=2)
    fmt = QueryOutputFormat.HANDLE
    group = [(tenant, _pair_query(), fmt, Future()) for _ in range(2)]

    class _OneThenBoom:
        """Streams the first answer, then dies: the second future must
        resolve via the coalescer's per-query fallback."""

        def settle_iter(self):
            yield 0, expected
            raise RuntimeError("stream died mid-group")

    c._settle_group((tenant, fmt, group, _OneThenBoom()))
    assert group[0][3].result(timeout=10) == expected
    assert group[1][3].result(timeout=10) == expected
    assert c.stats["early_settles"] == 1, c.stats


def test_queue_backpressure_rejects_beyond_bound():
    """Past coalesce_queue_max the submit queue REJECTS with an error
    future instead of growing host memory with the open-loop client
    count; rejections are counted."""
    from das_tpu.core.exceptions import CoalescerSaturatedError
    from das_tpu.service.coalesce import QueryCoalescer

    c = QueryCoalescer(max_batch=4, pipeline_depth=2, queue_max=2)
    # fill to the bound WITHOUT spawning the worker (submit would drain)
    c._queue.put_nowait((None, None, None, Future()))
    c._queue.put_nowait((None, None, None, Future()))
    fut = c.submit(None, _pair_query(), None)
    with pytest.raises(CoalescerSaturatedError):
        fut.result(timeout=5)
    assert c.snapshot()["queue_rejections"] == 1
    assert c._worker is None, "a rejected submit must not spawn the worker"
    # 0 = unbounded: the pre-bound behavior survives
    unbounded = QueryCoalescer(max_batch=4, pipeline_depth=2, queue_max=0)
    assert unbounded._queue.maxsize == 0
