"""MeTTa parser: tokenizing, hashing semantics, forward refs, errors."""

import pytest

from das_tpu.core.exceptions import (
    MettaLexerError,
    MettaSyntaxError,
    UndefinedSymbolError,
)
from das_tpu.core.hashing import ExpressionHasher
from das_tpu.ingest.metta import MettaParser, tokenize
from das_tpu.models.animals import animals_metta
from das_tpu.storage.atom_table import load_metta_text

SAMPLE = """
(: Similarity Type)
(: Concept Type)
(: "human" Concept)
(: "monkey" Concept)
(Similarity "human" "monkey")
"""


def collect(text):
    typedefs, terminals, toplevel, nested = [], [], [], []
    parser = MettaParser(
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_toplevel=toplevel.append,
        on_expression=nested.append,
    )
    assert parser.parse(text) == "SUCCESS"
    return typedefs, terminals, toplevel, nested


def test_tokenize_basic():
    toks = list(tokenize('(: "human" Concept)'))
    kinds = [t[0] for t in toks]
    assert kinds == [0, 2, 3, 4, 1]  # ( : terminal symbol )
    assert toks[2][1] == "human"


def test_tokenize_rejects_junk():
    with pytest.raises(MettaLexerError):
        list(tokenize("(@@@)"))


def test_terminal_handle_parity():
    _, terminals, _, _ = collect(SAMPLE)
    human = next(t for t in terminals if t.terminal_name == "human")
    assert human.hash_code == "af12f10f9ae2002a1607ba0b47ba8407"
    assert human.named_type == "Concept"
    assert human.composite_type == [ExpressionHasher.named_type_hash("Concept")]


def test_toplevel_link_hash_composition():
    _, _, toplevel, _ = collect(SAMPLE)
    assert len(toplevel) == 1
    link = toplevel[0]
    sim_h = ExpressionHasher.named_type_hash("Similarity")
    human_h = ExpressionHasher.terminal_hash("Concept", "human")
    monkey_h = ExpressionHasher.terminal_hash("Concept", "monkey")
    assert link.named_type == "Similarity"
    assert link.elements == [human_h, monkey_h]
    assert link.hash_code == ExpressionHasher.expression_hash(sim_h, [human_h, monkey_h])
    concept_h = ExpressionHasher.named_type_hash("Concept")
    assert link.composite_type == [sim_h, concept_h, concept_h]
    assert link.composite_type_hash == ExpressionHasher.composite_hash(
        [sim_h, concept_h, concept_h]
    )


def test_typedef_expression_hashing():
    typedefs, _, _, _ = collect(SAMPLE)
    # implicit (: Type Type) root + 4 explicit
    assert len(typedefs) == 5
    concept = next(t for t in typedefs if t.typedef_name == "Concept")
    mark_h = ExpressionHasher.named_type_hash(":")
    type_h = ExpressionHasher.named_type_hash("Type")
    concept_h = ExpressionHasher.named_type_hash("Concept")
    assert concept.named_type == ":"
    assert concept.elements == [concept_h, type_h]
    assert concept.hash_code == ExpressionHasher.expression_hash(
        mark_h, [concept_h, type_h]
    )


def test_forward_references_resolve_at_eof():
    # terminal used before its typedef appears
    text = """
(: Inheritance Type)
(Inheritance "a" "b")
(: Concept Type)
(: "a" Concept)
(: "b" Concept)
"""
    _, _, toplevel, _ = collect(text)
    link = toplevel[0]
    assert link.hash_code == ExpressionHasher.expression_hash(
        ExpressionHasher.named_type_hash("Inheritance"),
        [
            ExpressionHasher.terminal_hash("Concept", "a"),
            ExpressionHasher.terminal_hash("Concept", "b"),
        ],
    )


def test_undefined_symbol_raises():
    with pytest.raises(UndefinedSymbolError):
        collect('(: Concept Type)\n(Inheritance "a" "b")\n(: "a" Concept)\n(: "b" Concept)')


def test_nested_typedef_rejected():
    with pytest.raises(MettaSyntaxError):
        collect("(: Concept Type)\n(Concept (: Inner Type))")


def test_nested_expression_hashing():
    text = """
(: Evaluation Type)
(: List Type)
(: Concept Type)
(: "x" Concept)
(: "y" Concept)
(Evaluation (List "x" "y"))
"""
    _, _, toplevel, nested = collect(text)
    inner = nested[0]
    outer = toplevel[0]
    assert inner.named_type == "List"
    assert outer.elements == [inner.hash_code]
    eval_h = ExpressionHasher.named_type_hash("Evaluation")
    assert outer.hash_code == ExpressionHasher.expression_hash(
        eval_h, [inner.hash_code]
    )
    # composite type nests: [Evaluation_h, [List_h, Concept_h, Concept_h]]
    assert isinstance(outer.composite_type[1], list)


def test_animals_kb_counts():
    data = load_metta_text(animals_metta())
    nodes, links = data.count_atoms()
    assert nodes == 14
    assert links == 26
    assert "af12f10f9ae2002a1607ba0b47ba8407" in data.nodes


# ---------------------------------------------------------------------------
# Reference parser-test matrix (VERDICT r03 item 6): case-for-case port of
# /root/reference/das/metta_yacc_test.py:36-486 and metta_lex_test.py:27-99
# onto the recursive-descent parser.
# ---------------------------------------------------------------------------

# the reference lexer fixture (metta_lex_test.py:4-25)
LEX_TEST_DATA = """
    (: Evaluation Type)
    (: Predicate Type)
    (: Reactome Type)
    (: Concept Type)
    (: Set Type)
    (: "Predicate:has_name" Predicate)
    (: "Reactome:R-HSA-164843" Reactome)
    (: "Concept:2-LTR circle formation" Concept)
    (
        Evaluation
        "Predicate:has_name"
        (
            Evaluation
            "Predicate:has_name"
            (
                Set
                "Reactome:R-HSA-164843"
                "Concept:2-LTR circle formation"
            )
        )
    )"""

# our tokenizer's kind ids (ingest/metta.py)
_OPEN, _CLOSE, _MARK, _TERMINAL, _SYMBOL = 0, 1, 2, 3, 4


def test_lexer_token_stream():
    """metta_lex_test.py:27-99 — full expected token stream.  The reference
    lexer's EXPRESSION_NAME/BASIC_TYPE distinction and EOF token are PLY
    artifacts; the semantic stream (kind, text) must match 1:1."""
    toks = [(k, v) for k, v, _ in tokenize(LEX_TEST_DATA)]
    typedef = lambda name, t: [
        (_OPEN, "("), (_MARK, ":"), (_SYMBOL, name), (_SYMBOL, t), (_CLOSE, ")")
    ]
    terminal_typedef = lambda name, t: [
        (_OPEN, "("), (_MARK, ":"), (_TERMINAL, name), (_SYMBOL, t), (_CLOSE, ")")
    ]
    expected = (
        typedef("Evaluation", "Type")
        + typedef("Predicate", "Type")
        + typedef("Reactome", "Type")
        + typedef("Concept", "Type")
        + typedef("Set", "Type")
        + terminal_typedef("Predicate:has_name", "Predicate")
        + terminal_typedef("Reactome:R-HSA-164843", "Reactome")
        + terminal_typedef("Concept:2-LTR circle formation", "Concept")
        + [
            (_OPEN, "("), (_SYMBOL, "Evaluation"), (_TERMINAL, "Predicate:has_name"),
            (_OPEN, "("), (_SYMBOL, "Evaluation"), (_TERMINAL, "Predicate:has_name"),
            (_OPEN, "("), (_SYMBOL, "Set"), (_TERMINAL, "Reactome:R-HSA-164843"),
            (_TERMINAL, "Concept:2-LTR circle formation"),
            (_CLOSE, ")"), (_CLOSE, ")"), (_CLOSE, ")"),
        ]
    )
    assert toks == expected


def test_check_mode():
    """metta_yacc_test.py:36-39 — check() succeeds on the fixture."""
    assert MettaParser().check(LEX_TEST_DATA) == "SUCCESS"


class _CountingBroker:
    """The reference ActionBroker (metta_yacc_test.py:10-34) as callbacks."""

    def __init__(self):
        self.count_toplevel_expression = 0
        self.count_nested_expression = 0
        self.count_terminal = 0
        self.count_type = 0

    def parser(self, table=None):
        return MettaParser(
            symbol_table=table,
            on_typedef=lambda e: setattr(
                self, "count_type", self.count_type + 1
            ),
            on_terminal=lambda e: setattr(
                self, "count_terminal", self.count_terminal + 1
            ),
            on_expression=lambda e: setattr(
                self, "count_nested_expression", self.count_nested_expression + 1
            ),
            on_toplevel=lambda e: setattr(
                self, "count_toplevel_expression", self.count_toplevel_expression + 1
            ),
        )


def test_action_broker_counts():
    """metta_yacc_test.py:41-62 — check() fires no record actions beyond the
    implicit (: Type Type) root; parse() fires 9 typedefs + 1 toplevel."""
    broker = _CountingBroker()
    parser = broker.parser()
    assert broker.count_type == 1  # the implicit root typedef
    assert parser.check(LEX_TEST_DATA) == "SUCCESS"
    assert broker.count_toplevel_expression == 0
    assert broker.count_type == 1

    broker = _CountingBroker()
    assert broker.parser().parse(LEX_TEST_DATA) == "SUCCESS"
    assert broker.count_toplevel_expression == 1
    assert broker.count_type == 9


def test_terminal_hash_cache():
    """metta_yacc_test.py:64-104 — the (type, name) hash cache grows once
    per distinct pair and every pair hashes distinctly."""
    from das_tpu.ingest.metta import SymbolTable

    t = SymbolTable()
    pairs = [
        ("blah1", "bleh1"), ("blah2", "bleh2"),
        ("blah1", "bleh2"), ("blah2", "bleh1"),
    ]
    assert len(t.terminal_hash) == 0
    seen = []
    for i, (nt, name) in enumerate(pairs, start=1):
        h = t.get_terminal_hash(nt, name)
        assert len(t.terminal_hash) == i
        assert h == t.get_terminal_hash(nt, name)
        assert len(t.terminal_hash) == i
        assert h not in seen
        seen.append(h)


def test_named_type_hash_cache():
    """metta_yacc_test.py:106-124 — starts with BASIC_TYPE only; one entry
    per distinct name; stable and distinct."""
    from das_tpu.ingest.metta import SymbolTable

    t = SymbolTable()
    assert len(t.named_type_hash) == 1
    h1 = t.get_named_type_hash("blah1")
    assert len(t.named_type_hash) == 2
    assert h1 == t.get_named_type_hash("blah1")
    assert len(t.named_type_hash) == 2
    h2 = t.get_named_type_hash("blah2")
    assert len(t.named_type_hash) == 3
    assert h2 == t.get_named_type_hash("blah2")
    assert h1 != h2
    assert len(t.named_type_hash) == 3


def test_nested_expression_hash_composition():
    """metta_yacc_test.py:126-197 — _nested() composes composite types and
    hash codes; order changes the hash but not the composite type."""
    from das_tpu.core.expression import Expression

    parser = MettaParser()
    e1 = Expression(
        named_type="Similarity", named_type_hash="Similarity Hash",
        composite_type=["Typedef Similarity Type"],
        composite_type_hash="Typedef Similarity Type Hash",
        hash_code="h1",
    )
    e2 = Expression(
        terminal_name="c1", named_type="Concept", named_type_hash="Concept Hash",
        composite_type=["Concept"], composite_type_hash="Concept Hash",
        hash_code="h2",
    )
    e3 = Expression(
        terminal_name="c2", named_type="Concept", named_type_hash="Concept Hash",
        composite_type=["Concept"], composite_type_hash="Concept Hash",
        hash_code="h3",
    )
    c1 = parser._nested([e1, e2, e3])
    assert not c1.toplevel and c1.ordered and c1.terminal_name is None
    assert c1.named_type == "Similarity"
    assert c1.named_type_hash == "Similarity Hash"
    assert c1.composite_type == ["Typedef Similarity Type", "Concept", "Concept"]
    assert c1.composite_type_hash is not None
    assert c1.elements == ["h2", "h3"]
    assert c1.hash_code is not None

    c2 = parser._nested([e1, e3, e2])
    assert c2.composite_type_hash == c1.composite_type_hash
    assert c2.hash_code != c1.hash_code

    c3 = parser._nested([e1, c1, c2])
    assert not c3.toplevel and c3.ordered and c3.terminal_name is None
    assert c3.named_type == "Similarity"
    assert c3.composite_type == [
        "Typedef Similarity Type",
        ["Typedef Similarity Type", "Concept", "Concept"],
        ["Typedef Similarity Type", "Concept", "Concept"],
    ]
    assert c3.composite_type_hash not in (None, c1.composite_type_hash)
    assert c3.elements == [c1.hash_code, c2.hash_code]
    assert c3.hash_code not in (None, c1.hash_code, c2.hash_code)


def test_typedef_semantics():
    """metta_yacc_test.py:199-296 — _typedef() record fields, parent-type
    registration, idempotence, and subtype chains."""
    from das_tpu.core.schema import BASIC_TYPE, TYPEDEF_MARK

    parser = MettaParser()
    t = parser.table
    assert len(parser.pending_typedefs) == 0

    e1 = parser._typedef("Concept", "Type")
    mark_h = ExpressionHasher._compute_hash(TYPEDEF_MARK)
    basic_h = ExpressionHasher._compute_hash(BASIC_TYPE)
    concept_h = ExpressionHasher._compute_hash("Concept")
    assert len(parser.pending_typedefs) == 0
    assert not e1.toplevel and e1.ordered and e1.terminal_name is None
    assert e1.named_type == TYPEDEF_MARK
    assert e1.named_type_hash == mark_h
    assert e1.composite_type == [mark_h, basic_h, basic_h]
    assert e1.composite_type_hash == ExpressionHasher.expression_hash(
        mark_h, [basic_h, basic_h]
    )
    assert e1.elements == [concept_h, basic_h]
    assert e1.hash_code == ExpressionHasher.expression_hash(
        mark_h, [concept_h, basic_h]
    )
    # registry: Type, :, Concept
    assert len(t.named_type_hash) == 3
    h1 = t.get_named_type_hash("Type")

    e2 = parser._typedef("Concept", "Type")
    h2 = t.named_type_hash["Concept"]
    h3 = t.named_type_hash[":"]
    assert len(t.named_type_hash) == 3
    assert t.parent_type[h2] == h1
    assert e2.named_type == ":"
    assert e2.composite_type == [h3, h1, h1]
    assert e2.elements == [h2, h1]
    assert e2.hash_code is not None

    e3 = parser._typedef("Similarity", "Type")
    h4 = t.named_type_hash["Similarity"]
    assert len(t.named_type_hash) == 4
    assert t.parent_type[h4] == h1
    assert e3.composite_type == [h3, h1, h1]
    assert e3.composite_type_hash == e2.composite_type_hash
    assert e3.elements == [h4, h1]
    assert e3.hash_code != e2.hash_code

    e4 = parser._typedef("Concept", "Type")
    assert h2 == t.named_type_hash["Concept"]
    assert len(t.named_type_hash) == 4
    assert t.parent_type[h2] == h1
    assert e4 == e2

    # subtype chain: Similarity2's designator is Similarity, not Type
    e5 = parser._typedef("Similarity2", "Similarity")
    h5 = t.named_type_hash["Similarity2"]
    assert len(t.named_type_hash) == 5
    assert t.parent_type[h5] == h4
    assert e5.composite_type == [h3, h4, h1]
    assert e5.composite_type_hash != e2.composite_type_hash
    assert e5.elements == [h5, h4]
    assert e5.hash_code not in (e2.hash_code, e3.hash_code)


_PENDING_BODY = """
        (
            Evaluation
            "Predicate:has_name"
            (
                Evaluation
                "Predicate:has_name"
                (
                    {set_type}
                    "Reactome:R-HSA-164843"
                    "Concept:2-LTR circle formation"
                )
            )
        )
"""


def test_pending_types():
    """metta_yacc_test.py:298-391 — a type used before its typedef resolves
    at the EOF fixpoint; a type never defined raises with the missing
    symbol named."""
    header = """
        (: Evaluation Type)
        (: Predicate Type)
        (: Reactome Type)
        (: Concept Type)
        (: "Predicate:has_name" Predicate)
        (: "Reactome:R-HSA-164843" Reactome)
        (: "Concept:2-LTR circle formation" Concept)
    """
    body = _PENDING_BODY.format(set_type="Set")
    with pytest.raises(UndefinedSymbolError) as exc:
        _CountingBroker().parser().parse(header + body)
    assert "Set" in str(exc.value)

    broker = _CountingBroker()
    assert broker.parser().parse(header + body + "(: Set Type)") == "SUCCESS"
    assert broker.count_toplevel_expression == 1
    assert broker.count_type == 9

    # two-level forward chain: Set2's designator Set is itself delayed
    header2 = header.replace(
        '(: "Predicate:has_name" Predicate)',
        '(: Set2 Set)\n        (: "Predicate:has_name" Predicate)',
    )
    body2 = _PENDING_BODY.format(set_type="Set2")
    broker = _CountingBroker()
    assert broker.parser().parse(header2 + body2 + "(: Set Type)") == "SUCCESS"
    assert broker.count_toplevel_expression == 1
    assert broker.count_type == 10


def test_pending_terminal_names():
    """metta_yacc_test.py:393-486 — a TERMINAL whose type is defined after
    use resolves at EOF; never-defined raises."""
    header = """
        (: Evaluation Type)
        (: Reactome Type)
        (: Concept Type)
        (: Set Type)
        (: "Predicate:has_name" Predicate)
        (: "Reactome:R-HSA-164843" Reactome)
        (: "Concept:2-LTR circle formation" Concept)
    """
    body = _PENDING_BODY.format(set_type="Set")
    with pytest.raises(UndefinedSymbolError) as exc:
        _CountingBroker().parser().parse(header + body)
    assert "Predicate" in str(exc.value)

    broker = _CountingBroker()
    assert (
        broker.parser().parse(
            header + "(: Predicate Type)" + body
        ) == "SUCCESS"
    )
    assert broker.count_toplevel_expression == 1
    assert broker.count_type == 9

    # chained: Predicate's designator Predicate2 is defined after the body
    broker = _CountingBroker()
    assert (
        broker.parser().parse(
            header + "(: Predicate Predicate2)" + body + "(: Predicate2 Type)"
        ) == "SUCCESS"
    )
    assert broker.count_toplevel_expression == 1
    assert broker.count_type == 10


def test_animals_kb_reference_file_identical_atoms():
    """If the reference checkout is present, loading its animals.metta must
    produce the identical atom set (hash-for-hash) as our generated KB."""
    import os

    ref = "/root/reference/data/samples/animals.metta"
    if not os.path.exists(ref):
        pytest.skip("reference sample not available")
    ours = load_metta_text(animals_metta())
    with open(ref) as fh:
        theirs = load_metta_text(fh.read())
    assert set(ours.nodes) == set(theirs.nodes)
    assert set(ours.links) == set(theirs.links)
    assert set(ours.typedefs) == set(theirs.typedefs)
