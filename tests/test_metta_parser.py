"""MeTTa parser: tokenizing, hashing semantics, forward refs, errors."""

import pytest

from das_tpu.core.exceptions import (
    MettaLexerError,
    MettaSyntaxError,
    UndefinedSymbolError,
)
from das_tpu.core.hashing import ExpressionHasher
from das_tpu.ingest.metta import MettaParser, tokenize
from das_tpu.models.animals import animals_metta
from das_tpu.storage.atom_table import load_metta_text

SAMPLE = """
(: Similarity Type)
(: Concept Type)
(: "human" Concept)
(: "monkey" Concept)
(Similarity "human" "monkey")
"""


def collect(text):
    typedefs, terminals, toplevel, nested = [], [], [], []
    parser = MettaParser(
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_toplevel=toplevel.append,
        on_expression=nested.append,
    )
    assert parser.parse(text) == "SUCCESS"
    return typedefs, terminals, toplevel, nested


def test_tokenize_basic():
    toks = list(tokenize('(: "human" Concept)'))
    kinds = [t[0] for t in toks]
    assert kinds == [0, 2, 3, 4, 1]  # ( : terminal symbol )
    assert toks[2][1] == "human"


def test_tokenize_rejects_junk():
    with pytest.raises(MettaLexerError):
        list(tokenize("(@@@)"))


def test_terminal_handle_parity():
    _, terminals, _, _ = collect(SAMPLE)
    human = next(t for t in terminals if t.terminal_name == "human")
    assert human.hash_code == "af12f10f9ae2002a1607ba0b47ba8407"
    assert human.named_type == "Concept"
    assert human.composite_type == [ExpressionHasher.named_type_hash("Concept")]


def test_toplevel_link_hash_composition():
    _, _, toplevel, _ = collect(SAMPLE)
    assert len(toplevel) == 1
    link = toplevel[0]
    sim_h = ExpressionHasher.named_type_hash("Similarity")
    human_h = ExpressionHasher.terminal_hash("Concept", "human")
    monkey_h = ExpressionHasher.terminal_hash("Concept", "monkey")
    assert link.named_type == "Similarity"
    assert link.elements == [human_h, monkey_h]
    assert link.hash_code == ExpressionHasher.expression_hash(sim_h, [human_h, monkey_h])
    concept_h = ExpressionHasher.named_type_hash("Concept")
    assert link.composite_type == [sim_h, concept_h, concept_h]
    assert link.composite_type_hash == ExpressionHasher.composite_hash(
        [sim_h, concept_h, concept_h]
    )


def test_typedef_expression_hashing():
    typedefs, _, _, _ = collect(SAMPLE)
    # implicit (: Type Type) root + 4 explicit
    assert len(typedefs) == 5
    concept = next(t for t in typedefs if t.typedef_name == "Concept")
    mark_h = ExpressionHasher.named_type_hash(":")
    type_h = ExpressionHasher.named_type_hash("Type")
    concept_h = ExpressionHasher.named_type_hash("Concept")
    assert concept.named_type == ":"
    assert concept.elements == [concept_h, type_h]
    assert concept.hash_code == ExpressionHasher.expression_hash(
        mark_h, [concept_h, type_h]
    )


def test_forward_references_resolve_at_eof():
    # terminal used before its typedef appears
    text = """
(: Inheritance Type)
(Inheritance "a" "b")
(: Concept Type)
(: "a" Concept)
(: "b" Concept)
"""
    _, _, toplevel, _ = collect(text)
    link = toplevel[0]
    assert link.hash_code == ExpressionHasher.expression_hash(
        ExpressionHasher.named_type_hash("Inheritance"),
        [
            ExpressionHasher.terminal_hash("Concept", "a"),
            ExpressionHasher.terminal_hash("Concept", "b"),
        ],
    )


def test_undefined_symbol_raises():
    with pytest.raises(UndefinedSymbolError):
        collect('(: Concept Type)\n(Inheritance "a" "b")\n(: "a" Concept)\n(: "b" Concept)')


def test_nested_typedef_rejected():
    with pytest.raises(MettaSyntaxError):
        collect("(: Concept Type)\n(Concept (: Inner Type))")


def test_nested_expression_hashing():
    text = """
(: Evaluation Type)
(: List Type)
(: Concept Type)
(: "x" Concept)
(: "y" Concept)
(Evaluation (List "x" "y"))
"""
    _, _, toplevel, nested = collect(text)
    inner = nested[0]
    outer = toplevel[0]
    assert inner.named_type == "List"
    assert outer.elements == [inner.hash_code]
    eval_h = ExpressionHasher.named_type_hash("Evaluation")
    assert outer.hash_code == ExpressionHasher.expression_hash(
        eval_h, [inner.hash_code]
    )
    # composite type nests: [Evaluation_h, [List_h, Concept_h, Concept_h]]
    assert isinstance(outer.composite_type[1], list)


def test_animals_kb_counts():
    data = load_metta_text(animals_metta())
    nodes, links = data.count_atoms()
    assert nodes == 14
    assert links == 26
    assert "af12f10f9ae2002a1607ba0b47ba8407" in data.nodes


def test_animals_kb_reference_file_identical_atoms():
    """If the reference checkout is present, loading its animals.metta must
    produce the identical atom set (hash-for-hash) as our generated KB."""
    import os

    ref = "/root/reference/data/samples/animals.metta"
    if not os.path.exists(ref):
        pytest.skip("reference sample not available")
    ours = load_metta_text(animals_metta())
    with open(ref) as fh:
        theirs = load_metta_text(fh.read())
    assert set(ours.nodes) == set(theirs.nodes)
    assert set(ours.links) == set(theirs.links)
    assert set(ours.typedefs) == set(theirs.typedefs)
