"""Forced-device differential: the generalized tree executor (Or, unordered
links, nested And/Or, negation) must (a) accept every query in the
regression battery — no host fallback — and (b) produce answer sets
identical to the host algebra (which tests/test_differential.py already
proves identical to the reference engine)."""

import pytest

import das_tpu.query.ast as ast_mod
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Or,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)
from das_tpu.query.tree import query_tree
from tests.test_differential import QUERIES, build_query, canon


@pytest.fixture(scope="module")
def tensor_animals(animals_data):
    from das_tpu.storage.tensor_db import TensorDB

    return TensorDB(animals_data)


@pytest.mark.parametrize("spec", QUERIES, ids=[str(i) for i in range(len(QUERIES))])
def test_tree_matches_host(tensor_animals, animals_db, spec):
    query = build_query(ast_mod, spec)
    host_answer = PatternMatchingAnswer()
    host_matched = query.matched(animals_db, host_answer)

    dev_answer = PatternMatchingAnswer()
    dev_matched = query_tree(tensor_animals, build_query(ast_mod, spec), dev_answer)

    assert dev_matched is not None, f"tree executor declined {spec}"
    assert bool(dev_matched) == bool(host_matched), f"matched diverged for {spec}"
    assert dev_answer.negation == host_answer.negation
    host_set = {canon(a) for a in host_answer.assignments}
    dev_set = {canon(a) for a in dev_answer.assignments}
    assert dev_set == host_set, f"assignments diverged for {spec}"


def test_tree_handles_benchmark_query2_shape(tensor_animals, animals_db):
    """The benchmark layout-2 shape (And over a term and an Or of a nested
    And + a term, reference benchmark.py:95-113) on the animals KB."""
    v1 = Variable("V1")
    v2 = Variable("V2")
    tv1 = TypedVariable("V1", "Concept")
    tv2 = TypedVariable("V2", "Concept")
    tv3 = TypedVariable("V3", "Concept")

    def q():
        return And(
            [
                Link("Inheritance", [Node("Concept", "human"), v1], True),
                Or(
                    [
                        And(
                            [
                                Link("Inheritance", [Node("Concept", "monkey"), v2], True),
                                LinkTemplate("Inheritance", [tv2, tv3], True),
                                LinkTemplate("Inheritance", [tv1, tv3], True),
                            ]
                        ),
                        Link("Inheritance", [Node("Concept", "monkey"), v1], True),
                    ]
                ),
            ]
        )

    host_answer = PatternMatchingAnswer()
    host_matched = q().matched(animals_db, host_answer)
    dev_answer = PatternMatchingAnswer()
    dev_matched = query_tree(tensor_animals, q(), dev_answer)
    assert dev_matched is not None
    assert bool(dev_matched) == bool(host_matched)
    assert {canon(a) for a in dev_answer.assignments} == {
        canon(a) for a in host_answer.assignments
    }


def test_tree_reseed_quirk(tensor_animals, animals_db):
    """Disjoint-variable conjunction where an intermediate join can empty
    the accumulator: device must mirror the reference reseed behavior."""
    q = And(
        [
            Link("Inheritance", [Node("Concept", "human"), Variable("V1")], True),
            Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
            Link("Similarity", [Node("Concept", "snake"), Variable("V2")], False),
        ]
    )
    host_answer = PatternMatchingAnswer()
    host_matched = q.matched(animals_db, host_answer)
    dev_answer = PatternMatchingAnswer()
    dev_matched = query_tree(tensor_animals, q, dev_answer)
    assert dev_matched is not None
    assert bool(dev_matched) == bool(host_matched)
    assert {canon(a) for a in dev_answer.assignments} == {
        canon(a) for a in host_answer.assignments
    }
