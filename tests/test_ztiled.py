"""Grid-chunked kernel tiling battery (ISSUE 4): the bytes planner
(kernels/budget.py) and the chunked probe/join/index-join layouts.

What is pinned here:

  * planner routing — single-block under budget, grid-chunked past it,
    lowered past the tiled resident set; the COMBINED-footprint rule
    (the S×cap gathered right side inside shard_map that the old
    per-dimension fits() under-accounted); per-retry re-derivation is
    the same pure function, so route flips across capacities are pinned
    directly on the planner;
  * differential parity — chunked outputs bit-identical to BOTH the
    lowered op chains and the single-block kernels, over a small FIXED
    set of shape combos (tier-1's budget is tight: no randomized shape
    sweeps — every distinct shape is a fresh trace);
  * the >2^18 acceptance shapes — a probe against a >2^18-row posting
    table and a join materializing a 2^19-row window both execute on the
    kernel route (DISPATCH_COUNTS pins: kernel dispatches recorded, zero
    lowered fallbacks), which the old row bound (KERNEL_MAX_ROWS, 2^18)
    categorically refused;
  * executor threading — a fused execute() whose byte plan says tiled
    runs tiled (fused_kernel_tiled pin) with answers identical to the
    lowered route, on the single-device AND mesh executors;
  * exactly ONE DAS_TPU_PALLAS_INTERPRET=1 case per chunked kernel
    (probe, join, index join — the true pallas_call grid/BlockSpec
    lowering costs ~2-5 s XLA compile per call site on CPU, so the rest
    of the battery rides the direct discharge).

Run standalone: `ops/pytests.sh kernels` (shared marker with the PR-1
single-block battery — same suite on a TPU host compiles Mosaic).

(The file sorts after the seed suite on purpose, like test_zkernels.py:
kernel programs cost seconds of XLA compile each and should spend tail
budget rather than displace the seed tests' dots.)"""

import numpy as np
import pytest

pytestmark = pytest.mark.kernels

import jax.numpy as jnp

from das_tpu import kernels
from das_tpu.core.config import DasConfig
from das_tpu.kernels import budget
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.ops import posting
from das_tpu.ops.join import (
    _build_term_table_impl,
    _index_join_impl,
    _join_tables_impl,
)
from das_tpu.query import compiler
from das_tpu.query.fused import FusedTermSig, kernel_program_plan
from das_tpu.storage.tensor_db import TensorDB

#: a budget small enough that modest windows tile (keeps the chunked
#: traces cheap) but above the chunk floor's block bytes — the planner
#: unit tests and the forced-tiled parity combos both use it
SMALL_BUDGET = "262144"


def _lowered_probe(keys, perm, targets, key, fvals, cap,
                   var_cols, eq_pairs, extra_fixed):
    """The exact op sequence kernel 1 replaces (same oracle as
    test_zkernels.py)."""
    local, valid, cnt = posting.range_probe(keys, perm, key, cap)
    mask = valid
    safe = jnp.clip(local, 0, targets.shape[0] - 1)
    for i, pos in enumerate(extra_fixed):
        mask = mask & (targets[safe, pos] == fvals[i])
    vals, mask = _build_term_table_impl(targets, local, mask, var_cols, eq_pairs)
    return vals, mask, cnt


def _probe_inputs(rng, n, arity, key_span=5):
    keys = jnp.asarray(np.sort(rng.integers(0, key_span, n)).astype(np.int64))
    perm = jnp.asarray(rng.permutation(n).astype(np.int32))
    targets = jnp.asarray(rng.integers(0, 9, (n, arity)).astype(np.int32))
    return keys, perm, targets


def _index_inputs(rng, m, type_key=3):
    targets = rng.integers(0, 12, (m, 2)).astype(np.int32)
    keyarr = (np.int64(type_key) << 32) | targets[:, 0].astype(np.int64)
    perm = np.argsort(keyarr, kind="stable").astype(np.int32)
    return (
        jnp.asarray(keyarr[perm]), jnp.asarray(perm), jnp.asarray(targets)
    )


# -- planner unit battery --------------------------------------------------


def test_planner_single_tiled_lowered_ladder(monkeypatch):
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
    # tiny probe: everything fits one block
    assert budget.probe_plan(48, 48, 2, 2, 16).route == budget.ROUTE_SINGLE
    # same table, big window: the window tiles in chunk_rows blocks
    p = budget.probe_plan(30_000, 30_000, 3, 2, 9_000)
    assert p.route == budget.ROUTE_TILED and p.chunk_rows >= budget.MIN_CHUNK_ROWS
    # a probe window is always chunkable — at the DEFAULT budget even a
    # whole-table term with a huge index routes tiled (the FlyBase case
    # the old 2^18 bound refused); under the small test budget the same
    # window needs more than MAX_GRID_STEPS chunks and honestly lowers
    assert budget.probe_plan(1 << 21, 1 << 21, 2, 2, 1 << 20).route == (
        budget.ROUTE_LOWERED
    )
    monkeypatch.delenv("DAS_TPU_VMEM_BUDGET")
    big = budget.probe_plan(1 << 21, 1 << 21, 2, 2, 1 << 20)
    assert big.route == budget.ROUTE_TILED
    assert budget.probe_plan(1 << 23, 1 << 23, 2, 2, 64).route == (
        budget.ROUTE_LOWERED  # interpret guard: rows past 2^22 off-TPU
    )
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
    # sort-merge join: both tables are irreducibly resident — past the
    # budget the verdict is lowered (that shape is the index join's job)
    j = budget.join_plan(400_000, 2, 400_000, 2, 1, 3, 1 << 12)
    assert j.route == budget.ROUTE_LOWERED
    # ...but a big OUTPUT window over small tables tiles
    j = budget.join_plan(2_000, 2, 2_000, 2, 1, 3, 1 << 18)
    assert j.route == budget.ROUTE_TILED
    # per-retry re-derivation is this same pure function: the route
    # flips as the capacity (retry) grows, nothing is cached
    caps = [256, 1 << 14, 1 << 22]
    routes = [budget.join_plan(2_000, 2, 2_000, 2, 1, 3, c).route for c in caps]
    assert routes[0] == budget.ROUTE_SINGLE
    assert routes[1] == budget.ROUTE_TILED
    assert routes[2] == budget.ROUTE_LOWERED  # > MAX_GRID_STEPS chunks


def test_chunk_rows_lane_alignment(monkeypatch):
    """ISSUE 11 satellite (ARCHITECTURE §9 real-TPU item 3): every
    chunk the planner can emit is a multiple of the (8,128) tiling's
    128-row minor axis — swept over budgets, row sizes and windows,
    including sub-lane windows (the old power-of-two clamp emitted a
    64-row chunk for a 64-row window; now the window rounds UP to one
    lane multiple and the pad rows sit beyond every count, exactly the
    callers' existing pad+slice contract).  daslint DL011 pins the same
    property statically at every budget.py emission site."""
    assert budget.MIN_CHUNK_ROWS % budget.LANE_ROWS == 0
    for b in (131072, 262144, 1 << 20, 8 << 20):
        for row_bytes in (12, 16, 20, 24, 28, 36, 44, 52):
            for cap in (1, 64, 100, 1024, 4097, 9000, 1 << 18):
                chunk = budget.chunk_rows_for(row_bytes, cap, b)
                assert chunk % budget.LANE_ROWS == 0, (
                    row_bytes, cap, b, chunk,
                )
                assert chunk >= min(
                    budget.MIN_CHUNK_ROWS,
                    -(-cap // budget.LANE_ROWS) * budget.LANE_ROWS,
                )
    # ... and the routed plans agree: a tiled verdict's chunk is aligned
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
    p = budget.probe_plan(30_000, 30_000, 3, 2, 9_000)
    assert p.tiled and p.chunk_rows % budget.LANE_ROWS == 0
    j = budget.join_plan(2_000, 2, 2_000, 2, 1, 3, 1 << 14)
    assert j.tiled and j.chunk_rows % budget.LANE_ROWS == 0


def test_tiny_window_tiled_parity(monkeypatch):
    """Bit-parity re-pin for the lane-rounding change at its sharpest
    edge: a window SMALLER than one 128-lane row still pads to one
    aligned chunk and concatenates bit-identically to the single-block
    and lowered outputs (the one-step-grid contract)."""
    rng = np.random.default_rng(11)
    n, arity, cap = 3_000, 2, 100  # cap < LANE_ROWS
    keys, perm, targets = _probe_inputs(rng, n, arity)
    key = np.int64(3)
    fvals = jnp.asarray(np.zeros(0, np.int32))
    want = _lowered_probe(keys, perm, targets, key, fvals, cap,
                          (0, 1), (), ())
    kw = dict(var_cols=(0, 1), eq_pairs=(), extra_fixed=(), interpret=True)
    # tiny budget: even the 100-row window must grid-chunk
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "4096")
    plan = budget.probe_plan(n, n, arity, 2, cap)
    assert plan.tiled and plan.chunk_rows == budget.LANE_ROWS
    got_tiled = kernels.probe_term_table_impl(
        keys, perm, targets, key, fvals, cap, **kw
    )
    monkeypatch.delenv("DAS_TPU_VMEM_BUDGET")
    assert not budget.probe_plan(n, n, arity, 2, cap).tiled
    got_single = kernels.probe_term_table_impl(
        keys, perm, targets, key, fvals, cap, **kw
    )
    for a, b, c in zip(got_tiled, want, got_single):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(a), np.asarray(c))


def _two_term_sigs():
    t = dict(route="type", p0=-1, extra_fixed=(), eq_pairs=(), negated=False)
    return (
        FusedTermSig(arity=2, var_cols=(0, 1), var_names=("A", "B"), **t),
        FusedTermSig(arity=2, var_cols=(0, 1), var_names=("B", "C"), **t),
    )


def test_planner_combined_footprint_sxcap_regression(monkeypatch):
    """The eligibility under-accounting fix: inside shard_map the
    broadcast-gathered right side is S×cap rows IN THE SAME KERNEL as
    the accumulator and the output block.  Every dimension here is far
    below the old 2^18 row bound — the per-dimension fits() gate said
    "kernel" — but the combined byte footprint exceeds the budget, so
    the bytes planner must not pick the single-block layout."""
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "400000")
    sigs = _two_term_sigs()
    shapes = ((4096, 4096), (4096, 4096))
    term_caps, join_caps = (2048, 2048), (4096,)
    # single device: comfortably single-block under the same budget
    assert kernel_program_plan(
        sigs, shapes, term_caps, join_caps, (-1,)
    ) == budget.ROUTE_SINGLE
    # 8-shard mesh, broadcast-right join: the gathered side is 8×2048
    # rows — combined resident set alone overflows 400 KB, so the
    # single-block layout is OFF the table (tiling can't shrink a
    # resident table either: the verdict is lowered)
    sharded = kernel_program_plan(
        sigs, shapes, term_caps, join_caps, (-1,),
        n_shards=8, exch_caps=(0,),
    )
    assert sharded == budget.ROUTE_LOWERED
    # hash-partitioned exchange bounds the per-shard sides to S×q rows:
    # the same join with a small per-destination quota routes kernel
    assert kernel_program_plan(
        sigs, shapes, term_caps, join_caps, (-1,),
        n_shards=8, exch_caps=(128,),
    ) != budget.ROUTE_LOWERED


# -- differential parity: chunked vs lowered vs single-block ---------------

#: (n_rows, arity, capacity, var_cols, eq_pairs, extra_fixed) — FIXED
#: combos (one compile each); all force the tiled route under
#: SMALL_BUDGET and include non-chunk-multiple capacities (pad+slice)
TILED_PROBE_COMBOS = [
    (30_000, 3, 9_000, (1, 2), ((1, 2),), (0,)),
    (30_000, 2, 4_097, (0, 1), (), ()),
]


def test_tiled_probe_matches_lowered_and_single(monkeypatch):
    rng = np.random.default_rng(42)
    for ci, (n, arity, cap, var_cols, eq_pairs, extra_fixed) in enumerate(
        TILED_PROBE_COMBOS
    ):
        keys, perm, targets = _probe_inputs(rng, n, arity)
        key = np.int64(3)
        fvals = jnp.asarray(
            rng.integers(0, 9, len(extra_fixed)).astype(np.int32)
        )
        want = _lowered_probe(
            keys, perm, targets, key, fvals, cap,
            var_cols, eq_pairs, extra_fixed,
        )
        kw = dict(
            var_cols=var_cols, eq_pairs=eq_pairs, extra_fixed=extra_fixed,
            interpret=True,
        )
        monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
        assert budget.probe_plan(n, n, arity, len(var_cols), cap).tiled, ci
        got_tiled = kernels.probe_term_table_impl(
            keys, perm, targets, key, fvals, cap, **kw
        )
        monkeypatch.delenv("DAS_TPU_VMEM_BUDGET")
        assert not budget.probe_plan(n, n, arity, len(var_cols), cap).tiled
        got_single = kernels.probe_term_table_impl(
            keys, perm, targets, key, fvals, cap, **kw
        )
        for a, b, c in zip(got_tiled, want, got_single):
            assert np.array_equal(np.asarray(a), np.asarray(b)), ci
            assert np.array_equal(np.asarray(a), np.asarray(c)), ci


def test_tiled_join_matches_lowered_and_single(monkeypatch):
    rng = np.random.default_rng(7)
    L, R, cap = 900, 800, 6_001  # non-chunk-multiple capacity
    lv = jnp.asarray(rng.integers(0, 5, (L, 2)).astype(np.int32))
    rv = jnp.asarray(rng.integers(0, 5, (R, 3)).astype(np.int32))
    lm = jnp.asarray(rng.random(L) < 0.8)
    rm = jnp.asarray(rng.random(R) < 0.8)
    args = (lv, lm, rv, rm, ((0, 0),), (1, 2), cap)
    want = _join_tables_impl(*args)
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
    assert budget.join_plan(L, 2, R, 3, 1, 4, cap).tiled
    got_tiled = kernels.join_tables_impl(*args, interpret=True)
    monkeypatch.delenv("DAS_TPU_VMEM_BUDGET")
    assert not budget.join_plan(L, 2, R, 3, 1, 4, cap).tiled
    got_single = kernels.join_tables_impl(*args, interpret=True)
    for a, b, c in zip(got_tiled, want, got_single):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(a), np.asarray(c))


def test_tiled_index_join_matches_lowered_and_single(monkeypatch):
    rng = np.random.default_rng(11)
    m, L, cap = 20_000, 700, 9_000
    keys_sorted, perm, targets = _index_inputs(rng, m)
    lv = jnp.asarray(rng.integers(0, 12, (L, 2)).astype(np.int32))
    lm = jnp.asarray(rng.random(L) < 0.85)
    args = (
        lv, lm, keys_sorted, perm, targets, 3,
        ((0, 0),), (0, 1), (1,), cap,
    )
    want = _index_join_impl(*args)
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
    assert budget.index_join_plan(L, 2, m, m, 2, 3, cap).tiled
    got_tiled = kernels.index_join_impl(*args, interpret=True)
    monkeypatch.delenv("DAS_TPU_VMEM_BUDGET")
    got_single = kernels.index_join_impl(*args, interpret=True)
    for a, b, c in zip(got_tiled, want, got_single):
        assert np.array_equal(np.asarray(a), np.asarray(b))
        assert np.array_equal(np.asarray(a), np.asarray(c))


def test_anti_join_kernel_parity():
    from das_tpu.ops.join import _anti_join_impl

    rng = np.random.default_rng(13)
    L, R = 900, 800
    lv = jnp.asarray(rng.integers(0, 5, (L, 2)).astype(np.int32))
    rv = jnp.asarray(rng.integers(0, 5, (R, 3)).astype(np.int32))
    lm = jnp.asarray(rng.random(L) < 0.8)
    rm = jnp.asarray(rng.random(R) < 0.8)
    pairs = ((0, 0), (1, 1))
    want = _anti_join_impl(lv, lm, rv, rm, pairs)
    got = kernels.anti_join_impl(lv, lm, rv, rm, pairs, interpret=True)
    assert np.array_equal(np.asarray(got), np.asarray(want))
    # the staged-path wrapper is one "kernel" dispatch
    kernels.reset_dispatch_counts()
    got_w = kernels.anti_join(lv, lm, rv, rm, pairs)
    assert np.array_equal(np.asarray(got_w), np.asarray(want))
    assert kernels.DISPATCH_COUNTS["kernel"] == 1
    assert kernels.DISPATCH_COUNTS["lowered"] == 0


# -- the >2^18 acceptance shapes ------------------------------------------


def test_past_2e18_probe_stays_on_kernel_route():
    """A probe against a >2^18-row posting table — the FlyBase-scale
    whole-table term the old KERNEL_MAX_ROWS gate categorically kicked
    to the lowered chain — executes on the kernel route with
    bit-identical results, and DISPATCH_COUNTS shows zero lowered
    fallbacks."""
    rng = np.random.default_rng(21)
    n = 300_000  # > 2^18 = 262144
    keys, perm, targets = _probe_inputs(rng, n, 2, key_span=40)
    key = np.int64(17)
    fvals = jnp.zeros((0,), jnp.int32)
    cap = 16_384
    plan = budget.probe_plan(n, n, 2, 2, cap)
    assert plan.kernel  # the byte model admits what the row bound refused
    want = _lowered_probe(keys, perm, targets, key, fvals, cap, (0, 1), (), ())
    kernels.reset_dispatch_counts()
    got = kernels.probe_term_table(
        keys, perm, targets, key, fvals, cap,
        var_cols=(0, 1), eq_pairs=(), extra_fixed=(),
    )
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert kernels.DISPATCH_COUNTS["kernel"] == 1
    assert kernels.DISPATCH_COUNTS["lowered"] == 0


def test_past_2e18_join_window_tiles_on_kernel_route(monkeypatch):
    """A join materializing a 2^19-row output window (past the old bound)
    grid-chunks on the kernel route: kernel_tiled dispatch recorded, no
    lowered fallback, outputs bit-identical to the lowered join.  (A
    16 MB budget keeps the verdict tiled at 8 chunks instead of the
    default's 16 — halves this test's trace size, same machinery.)"""
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", str(16 * 1024 * 1024))
    rng = np.random.default_rng(23)
    L = R = 2_048
    cap = 1 << 19
    # ~256 matches per left row => ~2^19 total pairs: the window is real
    lv = jnp.asarray(rng.integers(0, 8, (L, 2)).astype(np.int32))
    rv = jnp.asarray(rng.integers(0, 8, (R, 2)).astype(np.int32))
    lm = jnp.asarray(np.ones(L, bool))
    rm = jnp.asarray(np.ones(R, bool))
    plan = budget.join_plan(L, 2, R, 2, 1, 3, cap)
    assert plan.tiled
    args = (lv, lm, rv, rm, ((0, 0),), (1,), cap)
    want = _join_tables_impl(*args)
    assert int(want[2]) > (1 << 18)  # the pair count itself is >2^18
    kernels.reset_dispatch_counts()
    got = kernels.join_tables(*args)
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(b))
    assert kernels.DISPATCH_COUNTS["kernel"] == 1
    assert kernels.DISPATCH_COUNTS["kernel_tiled"] == 1
    assert kernels.DISPATCH_COUNTS["lowered"] == 0


# -- executor threading ----------------------------------------------------


@pytest.fixture(scope="module")
def bio_data():
    data, _, _ = build_bio_atomspace(
        n_genes=30, n_processes=10, members_per_gene=3,
        n_interactions=40, n_evaluations=10,
    )
    return data


def _three_var():
    from das_tpu.query.ast import And, Link, Variable

    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


def test_fused_executor_tiled_route_parity(bio_data, monkeypatch):
    """End-to-end threading: with a small byte budget and a large
    capacity seed the fused program's byte plan says GRID-CHUNKED — the
    dispatch records fused_kernel_tiled, the per-retry planner call sees
    the same verdict, and the answer count is identical to the lowered
    route."""
    from das_tpu.query.fused import get_executor

    want_db = TensorDB(
        bio_data,
        DasConfig(use_pallas_kernels="off", initial_result_capacity=1024),
    )
    plans = compiler.plan_query(want_db, _three_var())
    want = compiler._execute_fused(want_db, plans)
    assert want is not None

    # 512 KB: the 8192-row join windows overflow (tiled) while the
    # second join's 8192-row LEFT table still fits resident — a tighter
    # budget would honestly lower the whole program instead
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "524288")
    db = TensorDB(
        bio_data,
        DasConfig(use_pallas_kernels="on", initial_result_capacity=8192),
    )
    plans_k = compiler.plan_query(db, _three_var())
    ex = get_executor(db)
    res = ex.execute(plans_k, count_only=True)  # warm: compile + caps
    assert res is not None
    kernels.reset_dispatch_counts()
    res = ex.execute(plans_k, count_only=True)
    assert res is not None and res.count == want.count
    assert kernels.DISPATCH_COUNTS["fused"] == 1
    assert kernels.DISPATCH_COUNTS["fused_kernel"] == 1
    assert kernels.DISPATCH_COUNTS["fused_kernel_tiled"] == 1
    assert kernels.DISPATCH_COUNTS["lowered"] == 0


@pytest.mark.slow
def test_sharded_executor_tiled_route_parity(bio_data, monkeypatch):
    """Mesh pendant: the shard-local join window tiles under a small
    budget (sharded_kernel_tiled pin) and the mesh answer count matches
    the lowered mesh route.  Two terms, one index join: the gathered
    LEFT (S×term-cap rows) stays small while the 32768-row per-shard
    join window overflows the 128 KB budget — the tiled sweet spot.

    Marked slow (a virtual-8-device shard_map compile is ~40 s of the
    tier-1 870 s budget): `ops/pytests.sh kernels` still runs it — the
    sharded planner ACCOUNTING (the S×cap combined-footprint rule) is
    tier-1-pinned above without a mesh compile."""
    from das_tpu.parallel.fused_sharded import get_sharded_executor
    from das_tpu.parallel.sharded_db import ShardedDB
    from das_tpu.query.ast import And, Link, Variable

    q = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
    ])
    # parity anchor from the SINGLE-DEVICE lowered executor (mesh-vs-flat
    # count identity is already pinned by the sharded suites; a second
    # mesh program compile here would only re-buy that at ~20 s)
    want_db = TensorDB(bio_data, DasConfig(use_pallas_kernels="off"))
    from das_tpu.query.fused import get_executor

    want = get_executor(want_db).execute(
        compiler.plan_query(want_db, q), count_only=True
    )
    assert want is not None

    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "131072")
    sdb = ShardedDB(
        bio_data,
        DasConfig(use_pallas_kernels="on", initial_result_capacity=262144),
    )
    plans_k = compiler.plan_query(sdb, q)
    ex = get_sharded_executor(sdb)
    res = ex.execute(plans_k, count_only=True)  # warm
    assert res is not None
    kernels.reset_dispatch_counts()
    res = ex.execute(plans_k, count_only=True)
    assert res is not None and res.count == want.count
    assert kernels.DISPATCH_COUNTS["sharded"] == 1
    assert kernels.DISPATCH_COUNTS["sharded_kernel"] == 1
    assert kernels.DISPATCH_COUNTS["sharded_kernel_tiled"] == 1


# -- the true Pallas interpreter: one case per chunked kernel --------------


def test_pallas_interpreter_tiled_parity(monkeypatch):
    """DAS_TPU_PALLAS_INTERPRET=1 runs the REAL pallas_call grid +
    BlockSpec lowering (chunk-blocked outputs, carried count block) for
    each chunked kernel ONCE — shapes unique to this test so no warm jit
    cache entry bypasses the env flag (it is read at trace time)."""
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", SMALL_BUDGET)
    monkeypatch.setenv("DAS_TPU_PALLAS_INTERPRET", "1")
    rng = np.random.default_rng(5)

    n, cap = 28_111, 8_501
    keys, perm, targets = _probe_inputs(rng, n, 3)
    fvals = jnp.asarray([4], jnp.int32)
    assert budget.probe_plan(n, n, 3, 2, cap).tiled
    want = _lowered_probe(
        keys, perm, targets, np.int64(2), fvals, cap, (1, 2), (), (0,)
    )
    got = kernels.probe_term_table_impl(
        keys, perm, targets, np.int64(2), fvals, cap,
        var_cols=(1, 2), eq_pairs=(), extra_fixed=(0,), interpret=True,
    )
    for a, b in zip(got, want):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    L, R, capj = 911, 787, 5_003
    lv = jnp.asarray(rng.integers(0, 5, (L, 2)).astype(np.int32))
    rv = jnp.asarray(rng.integers(0, 5, (R, 3)).astype(np.int32))
    lm = jnp.asarray(rng.random(L) < 0.8)
    rm = jnp.asarray(rng.random(R) < 0.8)
    args = (lv, lm, rv, rm, ((0, 0),), (1, 2), capj)
    assert budget.join_plan(L, 2, R, 3, 1, 4, capj).tiled
    want_j = _join_tables_impl(*args)
    got_j = kernels.join_tables_impl(*args, interpret=True)
    for a, b in zip(got_j, want_j):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    m, L2, capi = 19_009, 701, 8_009
    keys_sorted, perm2, targets2 = _index_inputs(rng, m)
    lv2 = jnp.asarray(rng.integers(0, 12, (L2, 2)).astype(np.int32))
    lm2 = jnp.asarray(rng.random(L2) < 0.85)
    args_i = (
        lv2, lm2, keys_sorted, perm2, targets2, 3,
        ((0, 0),), (0, 1), (1,), capi,
    )
    assert budget.index_join_plan(L2, 2, m, m, 2, 3, capi).tiled
    want_i = _index_join_impl(*args_i)
    got_i = kernels.index_join_impl(*args_i, interpret=True)
    for a, b in zip(got_i, want_i):
        assert np.array_equal(np.asarray(a), np.asarray(b))
