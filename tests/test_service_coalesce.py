"""Serving-edge query coalescing (service/coalesce.py): correctness under
concurrent gRPC load, and proof that concurrent singles actually batch."""

import threading

import grpc
import pytest

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.service.server import serve
from das_tpu.service.service_spec import das_pb2, das_pb2_grpc
from das_tpu.storage.tensor_db import TensorDB


@pytest.fixture(scope="module")
def served():
    data, _, _ = build_bio_atomspace(
        n_genes=60, n_processes=8, members_per_gene=4,
        n_interactions=60, n_evaluations=10,
    )
    db = TensorDB(data, DasConfig())
    das = DistributedAtomSpace(database_name="coal", db=db)
    server, service = serve(port=0, block=False)
    token = service.attach_tenant("coal", das)
    yield server, service, token, das, db
    server.stop(0)


def _dsl(gene: str) -> str:
    return (
        f"Node n1 Gene {gene}, Link Member n1 $3, "
        "Link Member $2 $3, Link Interacts n1 $2, AND"
    )


def _ast(gene: str):
    return And([
        Link("Member", [Node("Gene", gene), Variable("$3")], True),
        Link("Member", [Variable("$2"), Variable("$3")], True),
        Link("Interacts", [Node("Gene", gene), Variable("$2")], True),
    ])


def test_concurrent_grpc_queries_coalesce_and_match(served):
    server, service, token, das, db = served
    genes = db.get_all_nodes("Gene", names=True)[:16]
    # ground truth through the single-query path
    expected = {g: das.query(_ast(g)) for g in genes}
    assert any(expected.values()), "KB too sparse to prove anything"

    port = server.bound_port
    results = {}
    errors = []
    start = threading.Barrier(len(genes))

    def worker(gene):
        try:
            start.wait()
            with grpc.insecure_channel(f"localhost:{port}") as channel:
                stub = das_pb2_grpc.ServiceDefinitionStub(channel)
                for _ in range(3):  # sequential singles per client
                    reply = stub.query(
                        das_pb2.Query(
                            key=token, query=_dsl(gene), output_format="HANDLE"
                        )
                    )
                    assert reply.success, reply.msg
                    results[gene] = reply.msg
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=worker, args=(g,)) for g in genes]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors[:2]
    for g in genes:
        assert results[g] == expected[g], g
    # 16 concurrent clients x 3 queries: the natural-batching worker must
    # have formed at least one multi-query batch
    stats = service.coalescer_stats()
    assert stats["items"] >= len(genes) * 3
    assert stats["max_batch"] > 1, stats


def test_coalesced_errors_surface_as_status(served):
    server, service, token, das, db = served
    port = server.bound_port
    with grpc.insecure_channel(f"localhost:{port}") as channel:
        stub = das_pb2_grpc.ServiceDefinitionStub(channel)
        reply = stub.query(
            das_pb2.Query(key="bogus", query=_dsl("x"), output_format="HANDLE")
        )
        assert not reply.success
        reply = stub.query(
            das_pb2.Query(key=token, query="garbage !", output_format="HANDLE")
        )
        assert not reply.success


def test_query_many_matches_singles(served):
    _, _, _, das, db = served
    genes = db.get_all_nodes("Gene", names=True)[:8]
    queries = [_ast(g) for g in genes]
    batched = das.query_many(queries)
    singles = [das.query(q) for q in queries]
    assert batched == singles


def test_max_batch_comes_from_config(served):
    """The drain ceiling is DasConfig.coalesce_max_batch (env
    DAS_TPU_COALESCE_MAX_BATCH), not a hardcoded constant, and the stats
    surface it so operators can tell "never batched wider than N" from
    "capped at N"."""
    from types import SimpleNamespace

    from das_tpu.service.coalesce import QueryCoalescer
    from das_tpu.service.server import DasService, _Tenant

    server, service, token, das, db = served
    # default wiring: tenant coalescer ceiling == the das config's value
    stats = service.coalescer_stats()
    assert stats["max_batch_limit"] == das.config.coalesce_max_batch

    # explicit config flows through the tenant wiring
    fake = SimpleNamespace(config=DasConfig(coalesce_max_batch=7))
    tenant = _Tenant("t", fake)
    assert tenant.get_coalescer().max_batch == 7
    assert tenant.get_coalescer().stats["max_batch_limit"] == 7

    # aggregate stats report the widest configured ceiling
    svc = DasService()
    svc.tenants["t"] = tenant
    tenant.get_coalescer()
    assert svc.coalescer_stats()["max_batch_limit"] == 7

    # a bare coalescer tracks the deployment default (one source of truth)
    assert QueryCoalescer().max_batch == DasConfig.coalesce_max_batch
