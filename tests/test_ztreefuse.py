"""Whole-tree fused execution (ISSUE 10; marker `treefuse`, standalone
via `ops/pytests.sh treefuse`).

Pins, in order of load-bearing-ness:

  * BIT-IDENTICAL assignment sets fused-tree vs the tree executor on
    the bio Or/negation suite — positive unions, 3-branch Ors, the
    de-Morgan difference branch, nested positive Ors — on the
    single-device executor AND the sharded mesh (the host-set dedup
    semantics contract: a fused-tree bug may cost a fallback, never
    answers);
  * the acceptance pin: an eligible 3-branch Or executes in ONE device
    program on the fused-tree route where the tree executor dispatches
    one fused program per site (DISPATCH_COUNTS asserted both arms);
  * fallback-to-tree-executor on shapes outside the homogeneous subset
    (unordered links, heterogeneous variable universes) — answered
    correctly with ZERO fused_tree dispatches;
  * cache-hit 0-dispatch on the fused-tree `tree_results` entry and
    exact invalidation on commit (the delta_version guard);
  * FusedTreeSig / ShardedTreeSig field distinctness (cache-key
    honesty, the DL002 contract).

Compile-budget note: KBs are small; each arm compiles a handful of
fused shapes at serving-scale capacities.
"""

import dataclasses

import pytest

from das_tpu import kernels
from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query.ast import And, Link, Node, Not, Or, Variable
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.treefuse


def _bio_data(**kw):
    data, _genes, _procs = build_bio_atomspace(**kw)
    return data


def _tensor_das(data, config, monkeypatch, tag="ztf"):
    # CapStore off: learned capacities persisted by an earlier run (or
    # the other arm) would pre-seed the retry ladder and blind the pins
    monkeypatch.setenv("DAS_TPU_XLA_CACHE", "0")
    monkeypatch.delenv("DAS_TPU_TREE_FUSION", raising=False)
    db = TensorDB(data, config)
    return DistributedAtomSpace(database_name=tag, db=db), db


def _sharded_das(data, config, monkeypatch, tag="ztfs"):
    from das_tpu.parallel.sharded_db import ShardedDB

    monkeypatch.setenv("DAS_TPU_XLA_CACHE", "0")
    monkeypatch.delenv("DAS_TPU_TREE_FUSION", raising=False)
    db = ShardedDB(data, config)
    return DistributedAtomSpace(database_name=tag, db=db), db


def _branch(gene):
    return And([
        Link("Member", [Node("Gene", gene), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
    ])


def _suite(names):
    return [
        # plain 2-branch union
        Or([_branch(names[0]), _branch(names[2])]),
        # 3-branch union (the acceptance shape)
        Or([_branch(g) for g in names]),
        # single-term branches sharing the universe with a conjunction
        Or([
            _branch(names[0]),
            And([
                Link("Member", [Node("Gene", names[1]), Variable("V3")], True),
                Link("Member", [Variable("V2"), Variable("V3")], True),
            ]),
        ]),
        # the de-Morgan difference branch (joint negative minus union)
        Or([_branch(names[0]), Not(_branch(names[1]))]),
        Or([_branch(names[0]), _branch(names[2]), Not(_branch(names[1]))]),
        # nested positive Or flattens into the same union
        Or([_branch(names[0]), Or([_branch(names[1]), _branch(names[2])])]),
        # in-branch negated term (anti-join inside one site)
        Or([
            _branch(names[0]),
            And([
                Link("Member", [Node("Gene", names[1]), Variable("V3")], True),
                Link("Member", [Variable("V2"), Variable("V3")], True),
                Not(Link("Interacts",
                         [Node("Gene", names[1]), Variable("V2")], True)),
            ]),
        ]),
    ]


def _kb():
    return _bio_data(
        n_genes=60, n_processes=15, members_per_gene=4, n_interactions=80,
        seed=7,
    )


# -- bit-identical answers fused-tree vs the tree executor ---------------


def test_tree_fused_bit_identical_tensor(monkeypatch):
    data = _kb()
    das_on, db_on = _tensor_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztf_on"
    )
    das_off, _db = _tensor_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch, "ztf_off"
    )
    names = db_on.get_all_nodes("Gene", names=True)[:3]
    fused_answers = 0
    for q in _suite(names):
        kernels.reset_dispatch_counts()
        m_on, a_on = das_on.query_answer(q)
        fused_answers += kernels.DISPATCH_COUNTS["fused_tree"]
        m_off, a_off = das_off.query_answer(q)
        assert m_on == m_off
        assert a_on.assignments == a_off.assignments, q
        assert a_on.negation == a_off.negation
    # no silent fallback across the suite: every shape above is in the
    # homogeneous subset and must actually ride the fused route
    assert fused_answers >= len(_suite(names))


def test_tree_fused_bit_identical_sharded(monkeypatch):
    data = _kb()
    das_on, db_on = _sharded_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztfs_on"
    )
    das_off, _db = _sharded_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch, "ztfs_off"
    )
    names = db_on.get_all_nodes("Gene", names=True)[:3]
    fused_answers = 0
    for q in _suite(names):
        kernels.reset_dispatch_counts()
        m_on, a_on = das_on.query_answer(q)
        fused_answers += kernels.DISPATCH_COUNTS["sharded_tree_fused"]
        m_off, a_off = das_off.query_answer(q)
        assert m_on == m_off
        assert a_on.assignments == a_off.assignments, q
        assert a_on.negation == a_off.negation
    assert fused_answers >= len(_suite(names))


# -- the acceptance pin: one program where the tree executor pays >= N ---


def test_three_branch_or_one_program(monkeypatch):
    data = _kb()
    das_off, db_off = _tensor_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch, "ztf3_off"
    )
    names = db_off.get_all_nodes("Gene", names=True)[:3]
    q = Or([_branch(g) for g in names])
    kernels.reset_dispatch_counts()
    m_off, a_off = das_off.query_answer(q)
    tree_programs = kernels.DISPATCH_COUNTS["fused"]
    assert tree_programs >= 3, (
        "the tree executor pays one fused program per Or branch; "
        f"dispatches={kernels.DISPATCH_COUNTS}"
    )

    das_on, _db = _tensor_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztf3_on"
    )
    from das_tpu.query import compiler as qc

    qc.reset_route_counts()
    kernels.reset_dispatch_counts()
    m_on, a_on = das_on.query_answer(q)
    assert kernels.DISPATCH_COUNTS["fused_tree"] == 1, (
        kernels.DISPATCH_COUNTS
    )
    assert kernels.DISPATCH_COUNTS["fused"] == 0  # no per-site programs
    assert 1 < tree_programs  # the acceptance criterion
    assert m_on == m_off and a_on.assignments == a_off.assignments
    # per-ANSWER route telemetry: ONE fused_tree answer, and the site
    # jobs must not leak per-site route counts (count_route=False)
    assert qc.ROUTE_COUNTS["fused_tree"] == 1
    assert qc.ROUTE_COUNTS["fused_multiway"] == 0


# -- fallback on shapes outside the homogeneous subset -------------------


def test_unordered_shapes_fall_back(monkeypatch, animals_data):
    """An Or carrying an unordered (Similarity) branch is outside the
    homogeneous subset: the tree executor must answer (zero fused_tree
    dispatches), identically to the fusion-off arm."""
    das_on, _db = _tensor_das(
        animals_data, DasConfig(use_tree_fusion="on"), monkeypatch,
        "ztf_u_on",
    )
    das_off, _db2 = _tensor_das(
        animals_data, DasConfig(use_tree_fusion="off"), monkeypatch,
        "ztf_u_off",
    )
    q = Or([
        And([
            Link("Inheritance", [Node("Concept", "human"), Variable("V1")],
                 True),
            Link("Inheritance", [Variable("V2"), Variable("V1")], True),
        ]),
        Link("Similarity", [Node("Concept", "human"), Variable("V1")],
             False),
    ])
    kernels.reset_dispatch_counts()
    m_on, a_on = das_on.query_answer(q)
    assert kernels.DISPATCH_COUNTS["fused_tree"] == 0
    m_off, a_off = das_off.query_answer(q)
    assert m_on == m_off
    assert a_on.assignments == a_off.assignments


def test_heterogeneous_universe_falls_back(monkeypatch):
    """Branches binding DIFFERENT variable sets keep separate CTable
    groups in the tree executor — outside the shared-universe subset."""
    data = _kb()
    das_on, db_on = _tensor_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztf_h_on"
    )
    das_off, _db = _tensor_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch, "ztf_h_off"
    )
    names = db_on.get_all_nodes("Gene", names=True)[:2]
    q = Or([
        _branch(names[0]),  # binds {V2, V3}
        Link("Interacts", [Node("Gene", names[1]), Variable("V5")], True),
    ])
    kernels.reset_dispatch_counts()
    m_on, a_on = das_on.query_answer(q)
    assert kernels.DISPATCH_COUNTS["fused_tree"] == 0
    m_off, a_off = das_off.query_answer(q)
    assert m_on == m_off
    assert a_on.assignments == a_off.assignments


def test_sharded_tree_fallback_mode_gates_fusion(monkeypatch):
    """Review fix: sharded_tree_fallback="host" promises NO device tree
    programs — the fused-tree intercept must honor it (and "tensor"
    keeps the single-chip replica path, where the single-device fused
    tree applies instead)."""
    data = _kb()
    das, db = _sharded_das(
        data,
        DasConfig(use_tree_fusion="on", sharded_tree_fallback="host"),
        monkeypatch, "ztfs_host",
    )
    names = db.get_all_nodes("Gene", names=True)[:2]
    # a negated Or dodges the per-branch decomposition: in "host" mode
    # it must reach the host algebra with zero mesh tree programs
    q = Or([_branch(names[0]), Not(_branch(names[1]))])
    kernels.reset_dispatch_counts()
    m, a = das.query_answer(q)
    assert kernels.DISPATCH_COUNTS["sharded_tree_fused"] == 0, (
        kernels.DISPATCH_COUNTS
    )
    das_mesh, _db2 = _sharded_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztfs_mesh"
    )
    m2, a2 = das_mesh.query_answer(q)
    assert m == m2 and a.assignments == a2.assignments


# -- cache: 0-dispatch hits, exact invalidation on commit ----------------


def test_tree_fused_cache_hit_and_commit_invalidation(monkeypatch):
    data = _kb()
    das, db = _tensor_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztf_cache"
    )
    names = db.get_all_nodes("Gene", names=True)[:3]
    q = Or([_branch(names[0]), Not(_branch(names[1]))])
    _m1, a1 = das.query_answer(q)
    kernels.reset_dispatch_counts()
    _m2, a2 = das.query_answer(q)
    assert sum(kernels.DISPATCH_COUNTS.values()) == 0, (
        "a fused-tree cache hit must issue ZERO device programs"
    )
    assert a2.assignments == a1.assignments
    assert a2.negation == a1.negation

    # commit: delta_version bumps, the entry is stale, the next query
    # re-dispatches and sees the new row
    procs = db.get_all_nodes("BiologicalProcess", names=True)[:1]
    das.load_metta_text(
        '(: "GENE:ZTF" Gene)\n'
        + f'(: "{procs[0]}" BiologicalProcess)\n'
        + f'(Member "GENE:ZTF" "{procs[0]}")\n'
    )
    kernels.reset_dispatch_counts()
    _m3, a3 = das.query_answer(q)
    assert kernels.DISPATCH_COUNTS["fused_tree"] >= 1, (
        "a commit must invalidate the fused-tree entry"
    )
    # parity against the tree executor on the post-commit store
    das_off, _db = _tensor_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch, "ztf_c_off"
    )
    das_off.load_metta_text(
        '(: "GENE:ZTF" Gene)\n'
        + f'(: "{procs[0]}" BiologicalProcess)\n'
        + f'(Member "GENE:ZTF" "{procs[0]}")\n'
    )
    _m4, a4 = das_off.query_answer(q)
    assert a3.assignments == a4.assignments


def test_declined_fused_tree_memoized(monkeypatch):
    """Review fix: a declined fused attempt (per-site reseed verdict or
    capacity ceiling) is memoized in `tree_results` for the current
    delta version — repeat queries skip straight to the staged tree
    executor (whose own cache answers with zero dispatches) instead of
    re-executing and discarding the whole fused program every time."""
    from das_tpu.query import fused as fused_mod

    data = _kb()
    das, db = _tensor_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztf_dec"
    )
    names = db.get_all_nodes("Gene", names=True)[:3]
    q = Or([_branch(g) for g in names])
    ex = fused_mod.get_executor(db)
    calls = {"n": 0}

    def declining(pos_sites, neg_plans=None):
        calls["n"] += 1
        return None

    monkeypatch.setattr(ex, "execute_tree", declining)
    m1, a1 = das.query_answer(q)  # fused declines -> tree executor answers
    m2, a2 = das.query_answer(q)  # memoized decline + staged cache hit
    assert calls["n"] == 1, "the decline must be memoized per delta version"
    assert m1 == m2 and a1.assignments == a2.assignments
    das_off, _db2 = _tensor_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch, "ztf_dec_off"
    )
    _m3, a3 = das_off.query_answer(q)
    assert a1.assignments == a3.assignments


def test_sharded_tree_fused_cache_hit(monkeypatch):
    data = _kb()
    das, db = _sharded_das(
        data, DasConfig(use_tree_fusion="on"), monkeypatch, "ztfs_cache"
    )
    names = db.get_all_nodes("Gene", names=True)[:3]
    q = Or([_branch(g) for g in names])
    das.query_answer(q)
    kernels.reset_dispatch_counts()
    das.query_answer(q)
    assert sum(kernels.DISPATCH_COUNTS.values()) == 0


# -- sig-field distinctness (cache-key honesty, DL002) -------------------


def test_tree_sig_field_distinctness():
    from das_tpu.parallel.fused_sharded import ShardedPlanSig, ShardedTreeSig
    from das_tpu.query.fused import FusedPlanSig, FusedTreeSig

    site_a = FusedPlanSig((), (16,), ())
    site_b = FusedPlanSig((), (32,), ())
    assert FusedTreeSig((site_a,)) != FusedTreeSig((site_b,))
    # a negative site is part of the key: union-only and difference
    # programs for the same positive sites must cache side by side
    assert FusedTreeSig((site_a,), None) != FusedTreeSig((site_a,), site_b)
    assert hash(FusedTreeSig((site_a,), None)) != hash(
        FusedTreeSig((site_a,), site_b)
    )
    s_site = ShardedPlanSig((), (16,), (), (), 8)
    s_site2 = ShardedPlanSig((), (32,), (), (), 8)
    assert ShardedTreeSig((s_site,)) != ShardedTreeSig((s_site2,))
    assert ShardedTreeSig((s_site,), None) != ShardedTreeSig(
        (s_site,), s_site2
    )
    # frozen: tree sigs are cache keys and must hash by value (DL002
    # pins the dataclass mechanics; this pins the field semantics)
    assert dataclasses.fields(FusedTreeSig)[0].name == "sites"
    assert dataclasses.fields(ShardedTreeSig)[0].name == "sites"
