"""Differential battery: run the *reference* pattern matcher (imported
read-only from /root/reference) and our engine over the SAME backend data,
and require identical answer sets on the full regression query suite
(mirrors /root/reference/scripts/regression.py).  Skipped when the
reference checkout is absent."""

import pytest

import das_tpu.query.ast as my
from das_tpu.query.ast import PatternMatchingAnswer


class RefDBAdapter:
    """Expose our MemoryDB through the reference DBInterface duck-type.
    Targets are copied to fresh lists because the reference engine mutates
    them in place (pattern_matcher.py:484)."""

    def __init__(self, db):
        self.db = db

    def node_exists(self, t, n):
        return self.db.node_exists(t, n)

    def link_exists(self, t, targets):
        return self.db.link_exists(t, list(targets))

    def get_node_handle(self, t, n):
        return self.db.get_node_handle(t, n)

    def get_link_handle(self, t, targets):
        return self.db.get_link_handle(t, list(targets))

    def get_link_targets(self, h):
        return list(self.db.get_link_targets(h))

    def is_ordered(self, h):
        return self.db.is_ordered(h)

    def get_matched_links(self, t, targets):
        out = []
        for item in self.db.get_matched_links(t, list(targets)):
            if isinstance(item, str):
                out.append(item)
            else:
                handle, tgts = item
                out.append([handle, list(tgts)])
        return out

    def get_all_nodes(self, t, names=False):
        return self.db.get_all_nodes(t, names)

    def get_matched_type_template(self, template):
        return [
            [handle, list(tgts)]
            for handle, tgts in self.db.get_matched_type_template(template)
        ]

    def get_matched_type(self, t):
        return [
            [handle, list(tgts)] for handle, tgts in self.db.get_matched_type(t)
        ]

    def get_node_name(self, h):
        return self.db.get_node_name(h)

    def get_matched_node_name(self, t, s):
        return self.db.get_matched_node_name(t, s)


def canon(assignment):
    """Canonical, engine-independent form of an assignment object (works for
    both implementations because field names coincide)."""
    if hasattr(assignment, "unordered_mappings"):
        om = assignment.ordered_mapping
        return (
            "C",
            canon(om) if om is not None else None,
            tuple(sorted(canon(u) for u in assignment.unordered_mappings)),
        )
    if hasattr(assignment, "symbols"):
        return (
            "U",
            tuple(sorted(assignment.symbols.items())),
            tuple(sorted(assignment.values.items())),
        )
    return ("O", tuple(sorted(assignment.mapping.items())))


def build_query(factory, spec):
    """Build the same query AST in either implementation from a spec tree."""
    kind = spec[0]
    if kind == "node":
        return factory.Node(spec[1], spec[2])
    if kind == "var":
        return factory.Variable(spec[1])
    if kind == "tvar":
        return factory.TypedVariable(spec[1], spec[2])
    if kind == "link":
        return factory.Link(spec[1], [build_query(factory, s) for s in spec[3]], spec[2])
    if kind == "template":
        return factory.LinkTemplate(
            spec[1], [build_query(factory, s) for s in spec[3]], spec[2]
        )
    if kind == "and":
        return factory.And([build_query(factory, s) for s in spec[1]])
    if kind == "or":
        return factory.Or([build_query(factory, s) for s in spec[1]])
    if kind == "not":
        return factory.Not(build_query(factory, spec[1]))
    raise ValueError(kind)


def N(name):
    return ("node", "Concept", name)


def V(name):
    return ("var", name)


# the regression.py battery as spec trees ---------------------------------
QUERIES = [
    ("link", "Inheritance", True, [N("human"), N("mammal")]),
    ("link", "Similarity", False, [N("human"), N("mammal")]),
    ("link", "Similarity", False, [N("snake"), N("earthworm")]),
    ("link", "Similarity", False, [N("earthworm"), N("snake")]),
    ("link", "Inheritance", True, [V("V1"), N("mammal")]),
    ("link", "Inheritance", True, [V("V1"), V("V2")]),
    ("link", "Inheritance", True, [V("V1"), V("V1")]),
    ("link", "Inheritance", True, [V("V2"), V("V1")]),
    ("link", "Inheritance", True, [N("mammal"), V("V1")]),
    ("link", "Inheritance", True, [N("animal"), V("V1")]),
    ("link", "Similarity", False, [V("V1"), V("V2")]),
    ("link", "Similarity", False, [N("human"), V("V1")]),
    ("link", "Similarity", False, [V("V1"), N("human")]),
    ("not", ("link", "Inheritance", True, [N("human"), N("mammal")])),
    ("not", ("link", "Inheritance", True, [V("V1"), N("mammal")])),
    ("not", ("link", "Inheritance", True, [V("V1"), N("human")])),
    ("and", [
        ("link", "Inheritance", True, [V("V1"), V("V2")]),
        ("link", "Inheritance", True, [V("V2"), V("V3")]),
    ]),
    ("and", [
        ("link", "Inheritance", True, [V("V1"), V("V2")]),
        ("link", "Similarity", False, [V("V1"), V("V2")]),
    ]),
    ("and", [
        ("link", "Inheritance", True, [V("V1"), V("V3")]),
        ("link", "Inheritance", True, [V("V2"), V("V3")]),
        ("link", "Similarity", False, [V("V1"), V("V2")]),
    ]),
    ("and", [
        ("link", "Inheritance", True, [V("V1"), V("V3")]),
        ("link", "Inheritance", True, [V("V2"), V("V3")]),
        ("not", ("link", "Similarity", False, [V("V1"), V("V2")])),
    ]),
    ("or", [
        ("link", "Inheritance", True, [V("V1"), N("plant")]),
        ("link", "Similarity", False, [V("V1"), N("snake")]),
    ]),
    ("or", [
        ("not", ("link", "Inheritance", True, [V("V1"), V("V2")])),
        ("link", "Inheritance", True, [V("V1"), N("mammal")]),
    ]),
    ("template", "Inheritance", True, [("tvar", "V1", "Concept"), ("tvar", "V2", "Concept")]),
    ("template", "Similarity", False, [("tvar", "V1", "Concept"), ("tvar", "V2", "Concept")]),
    ("template", "List", True, [("tvar", "V1", "Concept"), ("tvar", "V2", "Concept")]),
    ("and", [
        ("template", "Inheritance", True, [("tvar", "V1", "Concept"), ("tvar", "V2", "Concept")]),
        ("link", "Similarity", False, [V("V1"), V("V2")]),
    ]),
]


@pytest.mark.parametrize("spec", QUERIES, ids=[str(i) for i in range(len(QUERIES))])
def test_differential_vs_reference(animals_db, reference_modules, spec):
    ref_pm, _ = reference_modules
    adapter = RefDBAdapter(animals_db)

    ref_query = build_query(ref_pm, spec)
    ref_answer = ref_pm.PatternMatchingAnswer()
    ref_matched = ref_query.matched(adapter, ref_answer)

    my_query = build_query(my, spec)
    my_answer = PatternMatchingAnswer()
    my_matched = my_query.matched(animals_db, my_answer)

    assert my_matched == ref_matched, f"matched flag diverged for {spec}"
    assert my_answer.negation == ref_answer.negation
    ref_set = {canon(a) for a in ref_answer.assignments}
    my_set = {canon(a) for a in my_answer.assignments}
    assert my_set == ref_set, f"assignments diverged for {spec}"
