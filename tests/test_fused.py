"""Fused single-dispatch executor: answer parity with the host algebra,
batched counting, capacity learning, and the staged-path fallbacks that
keep the reference reseed quirk exact."""

import numpy as np
import pytest

import das_tpu.query.compiler as compiler
from das_tpu.query.ast import (
    And,
    Link,
    Node,
    Not,
    PatternMatchingAnswer,
    Variable,
)
from das_tpu.query.fused import FusedExecutor, _pow2_at_least
from das_tpu.storage.tensor_db import TensorDB


@pytest.fixture(scope="module")
def tdb(animals_data):
    return TensorDB(animals_data)


@pytest.fixture(scope="module")
def ex(tdb):
    return FusedExecutor(tdb)


def _answers(db, query):
    host = PatternMatchingAnswer()
    query.matched(db, host)
    dev = PatternMatchingAnswer()
    compiler.query_on_device(db, query, dev)
    return host, dev


def test_pow2():
    assert _pow2_at_least(0) == 16
    assert _pow2_at_least(16) == 16
    assert _pow2_at_least(17) == 32
    assert _pow2_at_least(100000) == 131072


def test_estimates_are_exact(tdb, ex):
    plans = compiler.plan_query(
        tdb, Link("Inheritance", [Variable("V1"), Variable("V2")], True)
    )
    assert ex._estimate(plans[0]) == 12  # 12 Inheritance edges in animals
    plans = compiler.plan_query(
        tdb,
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
    )
    # 4 links end at mammal: human/monkey/chimp/rhino
    assert ex._estimate(plans[0]) == 4


def test_order_policy(tdb, ex):
    # connected-in-reference-order plans KEEP reference order (the program
    # is then the reference fold; zero counts are definitive)
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),      # 12
        Link("Inheritance", [Variable("V2"), Node("Concept", "animal")], True),  # 2
    ])
    plans = compiler.plan_query(tdb, q)
    ordered = ex._order(plans)
    assert [p is q for p, q in zip(ordered, plans)] == [True, True]
    # disconnected plans fall back to greedy smallest-first
    q2 = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),      # 12
        Link("Similarity", [Variable("V3"), Variable("V4")], True),       # 14
        Link("Inheritance", [Variable("V3"), Node("Concept", "animal")], True),  # 2
    ])
    plans2 = compiler.plan_query(tdb, q2)
    ordered2 = ex._order(plans2)
    assert ex._estimate(ordered2[0]) == min(ex._estimate(p) for p in plans2)
    # negated terms always run last
    q3 = And([
        Not(Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)),
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
    ])
    plans3 = compiler.plan_query(tdb, q3)
    assert ex._order(plans3)[-1].negated


def test_fused_execute_matches_host(tdb, ex):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    host, dev = _answers(tdb, q)
    assert host.assignments == dev.assignments
    res = ex.execute(compiler.plan_query(tdb, q))
    assert res is not None
    assert res.count == len(host.assignments)


def test_count_only_matches_full(tdb, ex):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    full = ex.execute(plans)
    counted = ex.execute(plans, count_only=True)
    assert counted.vals is None and counted.valid is None
    assert counted.count == full.count


def test_empty_positive_term_is_definitive_no_match(tdb, ex):
    # plant has no outgoing Inheritance: an empty POSITIVE TERM fails the
    # whole And in the reference (term.matched False -> return False), so
    # the fused path answers count=0 WITHOUT a reseed fallback — zero-answer
    # queries stay on the single-dispatch path (critical for batch counting)
    q = And([
        Link("Inheritance", [Node("Concept", "plant"), Variable("V1")], True),
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    res = ex.execute(plans)
    assert res is not None and not res.reseed_needed and res.count == 0
    # and the public path still agrees with the host algebra
    host, dev = _answers(tdb, q)
    assert host.assignments == dev.assignments


def test_join_emptied_accumulator_still_defers(tdb, ex):
    # both terms non-empty but the join is empty AND a positive term
    # remains -> the reference reseed quirk can fire; fused must defer
    q = And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Node("Concept", "earthworm"), Variable("V1")], True),
        Link("Similarity", [Variable("V2"), Variable("V3")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    if plans is None:
        return  # shape outside the fused subset on this KB — nothing to check
    res = ex.execute(plans)
    assert res is None or res.reseed_needed or res.count > 0
    host, dev = _answers(tdb, q)
    assert host.assignments == dev.assignments


def test_caps_learned_and_reused(tdb):
    ex2 = FusedExecutor(tdb)
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    ex2.execute(plans)
    assert len(ex2._caps) == 1
    (tc, jc), = ex2._caps.values()
    ex2.execute(plans)  # second run seeds from memo — still correct
    assert ex2._caps[next(iter(ex2._caps))] == (tc, jc)


def test_count_batch_matches_individual(tdb, ex):
    queries = [
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V1"), Node("Concept", "animal")], True),
        Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
        Link("Similarity", [Variable("V1"), Variable("V2")], False),  # unordered
        And([
            Link("Inheritance", [Variable("V1"), Variable("V3")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        ]),
    ]
    plans_list = [compiler.plan_query(tdb, q) for q in queries]
    fusable = [p for p in plans_list if p is not None]
    batch = ex.count_batch(fusable)
    # single-term queries can never need the reseed fallback, so the batch
    # path must actually answer them — guards against a vacuous pass where
    # count_batch declines everything
    assert sum(g is not None for g in batch) >= 3
    it = iter(batch)
    for q, plans in zip(queries, plans_list):
        if plans is None:
            continue
        got = next(it)
        expected = compiler.count_matches(tdb, q)
        if got is not None:
            assert got == expected, repr(q)


def test_count_batch_groups_same_shape(tdb, ex):
    # three same-shape queries must produce exactly one batch group
    queries = [
        Link("Inheritance", [Variable("V1"), Node("Concept", c)], True)
        for c in ("mammal", "animal", "reptile")
    ]
    plans_list = [compiler.plan_query(tdb, q) for q in queries]
    counts = ex.count_batch(plans_list)
    # mammal ← human/monkey/chimp/rhino; animal ← mammal/reptile/earthworm;
    # reptile ← snake/dinosaur
    assert counts == [4, 3, 2]


# -- exact (reference-order, in-program reseed) variant ---------------------

RESEED_SHAPES = [
    # join empties mid-way, later term reseeds (suffix answer)
    And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Node("Concept", "earthworm"), Variable("V1")], True),
        Link("Inheritance", [Variable("V2"), Node("Concept", "animal")], True),
    ]),
    # reseeds twice: two disjoint empty joins
    And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Node("Concept", "earthworm"), Variable("V1")], True),
        Link("Inheritance", [Variable("V2"), Node("Concept", "reptile")], True),
        Link("Inheritance", [Node("Concept", "vine"), Variable("V2")], True),
        Link("Inheritance", [Variable("V3"), Node("Concept", "plant")], True),
    ]),
    # empties at the FINAL join: definitive empty answer, no reseed
    And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Node("Concept", "earthworm"), Variable("V1")], True),
    ]),
    # reseed + negation: tabu covers only the suffix variable set
    And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Node("Concept", "earthworm"), Variable("V1")], True),
        Link("Inheritance", [Variable("V2"), Node("Concept", "animal")], True),
        Not(Link("Inheritance", [Variable("V2"), Node("Concept", "animal")], True)),
    ]),
]


@pytest.mark.parametrize("qi", range(len(RESEED_SHAPES)))
def test_exact_variant_matches_host_on_reseed_shapes(tdb, ex, qi):
    q = RESEED_SHAPES[qi]
    host, dev = _answers(tdb, q)
    assert dev.assignments == host.assignments
    # the exact program itself (not the staged fallback) must answer it
    plans = compiler.plan_query(tdb, q)
    assert plans is not None
    res = ex.execute_exact(plans)
    assert res is not None and not res.reseed_needed
    host_count = len(host.assignments)
    assert res.count == host_count


def test_count_batch_exact_pass_answers_reseed_queries(tdb, ex):
    queries = RESEED_SHAPES[:3]
    plans_list = [compiler.plan_query(tdb, q) for q in queries]
    assert all(p is not None for p in plans_list)
    batch = ex.count_batch(plans_list)
    for got, q in zip(batch, queries):
        assert got is not None, f"exact pass declined {q}"
        host = __import__("das_tpu.query.ast", fromlist=["PatternMatchingAnswer"]).PatternMatchingAnswer()
        q.matched(tdb, host)
        assert got == len(host.assignments)


def test_index_join_routing_and_parity(tdb, ex):
    """A whole-type ungrounded right term routes through the posting-index
    join (never materialized: its term cap stays at the 16-row token) and
    answers stay host-identical."""
    from das_tpu.query.fused import plan_index_joins

    q = And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),  # whole-type
    ])
    plans = compiler.plan_query(tdb, q)
    ordered = ex._order(plans)
    mapped = [ex._term_args(p) for p in ordered]
    sigs = tuple(m[0] for m in mapped)
    index_joins, index_right = plan_index_joins(sigs)
    assert any(p >= 0 for p in index_joins), "index join did not activate"
    host, dev = _answers(tdb, q)
    assert dev.assignments == host.assignments
    res = ex.execute(plans)
    assert res is not None and res.count == len(host.assignments)


def test_count_loop_matches_individual(tdb, ex):
    """The single-dispatch fori_loop count program (bench.py's device-only
    latency probe) returns exactly the per-query device counts for both
    distinct grounded queries and identical repeated queries, with
    capacities settled in-builder (no silent truncation)."""
    grounded = [
        And([
            Link("Inheritance", [Node("Concept", name), Variable("V1")], True),
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        ])
        for name in ("human", "monkey", "chimp", "rhino")
    ]
    plans_list = [compiler.plan_query(tdb, q) for q in grounded]
    assert all(p is not None for p in plans_list)
    run, w = ex.build_count_loop(plans_list)
    counts, mx = run()
    assert w == 4
    for got, q in zip(counts, grounded):
        assert got == compiler.count_matches(tdb, q)

    # identical repeats: the loop-carried dependence defeats hoisting and
    # every iteration reports the same exact count
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    p = compiler.plan_query(tdb, q)
    expected = compiler.count_matches(tdb, q)
    run, w = ex.build_count_loop([p] * 8)
    counts, _ = run()
    assert list(counts) == [expected] * 8


# -- host single-term counting (the miner's candidate shape) ----------------


TRI_METTA = """(: Rel Type)
(: Concept Type)
(: "a" Concept)
(: "b" Concept)
(: "c" Concept)
(: "d" Concept)
(: "e" Concept)
(: "x" Concept)
(Rel "a" "b" "c")
(Rel "a" "b" "d")
(Rel "a" "e" "c")
(Rel "x" "b" "c")
(Rel "x" "e" "d")
"""


@pytest.fixture(scope="module")
def tri_db():
    from das_tpu.storage.atom_table import load_metta_text

    return TensorDB(load_metta_text(TRI_METTA))


def _grounded_cases(db):
    yield Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True), db
    yield Link("Inheritance", [Node("Concept", "human"), Variable("V1")], True), db
    yield Link("Inheritance", [Node("Concept", "plant"), Variable("V1")], True), db


def test_host_single_term_count_matches_device_and_host(tdb, tri_db, ex, monkeypatch):
    """The host-side exact count for grounded single-term patterns (the
    miner's wildcard-variant shape) agrees with BOTH the device path and
    the host algebra, across one- and multi-fixed shapes."""
    from das_tpu.query.fused import trivial_plan_count

    cases = [
        (q, db) for q, db in _grounded_cases(tdb)
    ] + [
        # multi-fixed arity-3 variants: narrowest-position probe + verify
        (Link("Rel", [Node("Concept", "a"), Node("Concept", "b"), Variable("V1")], True), tri_db),
        (Link("Rel", [Node("Concept", "a"), Variable("V1"), Node("Concept", "c")], True), tri_db),
        (Link("Rel", [Variable("V1"), Node("Concept", "b"), Node("Concept", "c")], True), tri_db),
        (Link("Rel", [Node("Concept", "x"), Variable("V1"), Variable("V2")], True), tri_db),
        (Link("Rel", [Variable("V1"), Variable("V2"), Node("Concept", "d")], True), tri_db),
    ]
    for q, db in cases:
        plans = compiler.plan_query(db, q)
        assert plans is not None
        n = trivial_plan_count(db, plans)
        assert n is not None, repr(q)
        # host algebra
        host = PatternMatchingAnswer()
        matched = q.matched(db, host)
        assert n == (len(host.assignments) if matched else 0), repr(q)
        # device (staged pipeline — shortcut-independent)
        assert n == compiler.count_matches_staged(db, plans), repr(q)
        # and the device BATCH path with the shortcut disabled
        monkeypatch.setenv("DAS_TPU_HOST_COUNT", "0")
        try:
            from das_tpu.query.fused import FusedExecutor

            dev = FusedExecutor(db).count_batch([plans])[0]
        finally:
            monkeypatch.delenv("DAS_TPU_HOST_COUNT")
        if dev is not None:
            assert n == dev, repr(q)


def test_host_single_term_count_sees_commit():
    """Counts must include incremental-delta overlay segments: the host
    route sums over host_bucket_segments, exactly mirroring the merged
    device index."""
    from das_tpu.api.atomspace import DistributedAtomSpace
    from das_tpu.models.animals import animals_metta
    from das_tpu.query.fused import trivial_plan_count

    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    q = Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    assert trivial_plan_count(das.db, compiler.plan_query(das.db, q)) == 4

    tx = das.open_transaction()
    tx.add('(: "lion" Concept)')
    tx.add('(Inheritance "lion" "mammal")')
    das.commit_transaction(tx)
    plans = compiler.plan_query(das.db, q)
    assert trivial_plan_count(das.db, plans) == 5
    host = PatternMatchingAnswer()
    q.matched(das.db, host)
    assert len(host.assignments) == 5


def test_host_single_term_count_dangling_defers():
    """A dangling (-1) element in a variable position could make two
    distinct links bind identical tuples — the host route must defer to
    the device path (None) instead of answering without dedup."""
    from das_tpu.query.fused import trivial_plan_count
    from das_tpu.storage.atom_table import load_metta_text

    data = load_metta_text(
        '(: Rel Type)(: Concept Type)(: "a" Concept)(: "b" Concept)\n'
        '(Rel "a" "b")'
    )
    # forge a link whose second element resolves to no row
    rec = next(iter(data.links.values()))
    from das_tpu.storage.atom_table import LinkRec

    data.links["f" * 32] = LinkRec(
        named_type=rec.named_type,
        named_type_hash=rec.named_type_hash,
        composite_type=rec.composite_type,
        composite_type_hash=rec.composite_type_hash,
        elements=(rec.elements[0], "e" * 32),  # unknown handle -> dangling
        is_toplevel=True,
    )
    db = TensorDB(data)
    assert db.fin.dangling_hexes  # the forged ghost element
    q = Link("Rel", [Node("Concept", "a"), Variable("V1")], True)
    plans = compiler.plan_query(db, q)
    assert trivial_plan_count(db, plans) is None
