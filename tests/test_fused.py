"""Fused single-dispatch executor: answer parity with the host algebra,
batched counting, capacity learning, and the staged-path fallbacks that
keep the reference reseed quirk exact."""

import numpy as np
import pytest

import das_tpu.query.compiler as compiler
from das_tpu.query.ast import (
    And,
    Link,
    Node,
    Not,
    PatternMatchingAnswer,
    Variable,
)
from das_tpu.query.fused import FusedExecutor, _pow2_at_least
from das_tpu.storage.tensor_db import TensorDB


@pytest.fixture(scope="module")
def tdb(animals_data):
    return TensorDB(animals_data)


@pytest.fixture(scope="module")
def ex(tdb):
    return FusedExecutor(tdb)


def _answers(db, query):
    host = PatternMatchingAnswer()
    query.matched(db, host)
    dev = PatternMatchingAnswer()
    compiler.query_on_device(db, query, dev)
    return host, dev


def test_pow2():
    assert _pow2_at_least(0) == 16
    assert _pow2_at_least(16) == 16
    assert _pow2_at_least(17) == 32
    assert _pow2_at_least(100000) == 131072


def test_estimates_are_exact(tdb, ex):
    plans = compiler.plan_query(
        tdb, Link("Inheritance", [Variable("V1"), Variable("V2")], True)
    )
    assert ex._estimate(plans[0]) == 12  # 12 Inheritance edges in animals
    plans = compiler.plan_query(
        tdb,
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
    )
    # 4 links end at mammal: human/monkey/chimp/rhino
    assert ex._estimate(plans[0]) == 4


def test_greedy_order_puts_smallest_first(tdb, ex):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),      # 12
        Link("Inheritance", [Variable("V2"), Node("Concept", "animal")], True),  # 2
    ])
    plans = compiler.plan_query(tdb, q)
    ordered = ex._order(plans)
    assert ex._estimate(ordered[0]) <= ex._estimate(ordered[1])
    # negated terms always run last
    q2 = And([
        Not(Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)),
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
    ])
    plans2 = compiler.plan_query(tdb, q2)
    assert ex._order(plans2)[-1].negated


def test_fused_execute_matches_host(tdb, ex):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    host, dev = _answers(tdb, q)
    assert host.assignments == dev.assignments
    res = ex.execute(compiler.plan_query(tdb, q))
    assert res is not None
    assert res.count == len(host.assignments)


def test_count_only_matches_full(tdb, ex):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    full = ex.execute(plans)
    counted = ex.execute(plans, count_only=True)
    assert counted.vals is None and counted.valid is None
    assert counted.count == full.count


def test_empty_multi_term_defers_to_staged(tdb, ex):
    # plant has no outgoing Inheritance: join is empty => the fused path
    # must flag reseed so the caller replays reference order exactly
    q = And([
        Link("Inheritance", [Node("Concept", "plant"), Variable("V1")], True),
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    res = ex.execute(plans)
    assert res is None or res.reseed_needed
    # and the public path still agrees with the host algebra
    host, dev = _answers(tdb, q)
    assert host.assignments == dev.assignments


def test_caps_learned_and_reused(tdb):
    ex2 = FusedExecutor(tdb)
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    plans = compiler.plan_query(tdb, q)
    ex2.execute(plans)
    assert len(ex2._caps) == 1
    (tc, jc), = ex2._caps.values()
    ex2.execute(plans)  # second run seeds from memo — still correct
    assert ex2._caps[next(iter(ex2._caps))] == (tc, jc)


def test_count_batch_matches_individual(tdb, ex):
    queries = [
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V1"), Node("Concept", "animal")], True),
        Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
        Link("Similarity", [Variable("V1"), Variable("V2")], False),  # unordered
        And([
            Link("Inheritance", [Variable("V1"), Variable("V3")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        ]),
    ]
    plans_list = [compiler.plan_query(tdb, q) for q in queries]
    fusable = [p for p in plans_list if p is not None]
    batch = ex.count_batch(fusable)
    # single-term queries can never need the reseed fallback, so the batch
    # path must actually answer them — guards against a vacuous pass where
    # count_batch declines everything
    assert sum(g is not None for g in batch) >= 3
    it = iter(batch)
    for q, plans in zip(queries, plans_list):
        if plans is None:
            continue
        got = next(it)
        expected = compiler.count_matches(tdb, q)
        if got is not None:
            assert got == expected, repr(q)


def test_count_batch_groups_same_shape(tdb, ex):
    # three same-shape queries must produce exactly one batch group
    queries = [
        Link("Inheritance", [Variable("V1"), Node("Concept", c)], True)
        for c in ("mammal", "animal", "reptile")
    ]
    plans_list = [compiler.plan_query(tdb, q) for q in queries]
    counts = ex.count_batch(plans_list)
    # mammal ← human/monkey/chimp/rhino; animal ← mammal/reptile/earthworm;
    # reptile ← snake/dinosaur
    assert counts == [4, 3, 2]
