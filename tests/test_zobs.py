"""Observability layer (ISSUE 12): dastrace spans, metric histograms,
exporters, and the DL014 name-registry discipline.

Pins, in one place (marker `obs`, standalone via `ops/pytests.sh obs`):

  * end-to-end span coverage for a coalesced query: every lifecycle
    stage (submit → drain → group → plan → dispatch → settle → answer)
    lands in the ring, spans nest/order correctly, and the trace id
    born at submit is the one closed at answer;
  * cache-hit and commit-invalidation events, with the commit path's
    delta_version bump visible;
  * histogram percentile math vs exact quantiles on known samples
    (the fixed log-bucket error bound);
  * the DISABLED mode is structurally a no-op: `span()` returns THE
    shared no-op singleton (no span objects allocated), `mark()` is
    None, the ring stays empty through a served workload;
  * Perfetto (Chrome trace-event) and Prometheus exporter golden
    shapes;
  * daslint DL014 — clean tree, bad/good fixtures, and a mutated-copy
    regression on a real instrumentation site;
  * the coalescer's last-K (rtt, dispatch, depth) window-history ring
    (the ARCHITECTURE §10 window-formula evidence).

Compile-budget note: every served query here reuses ONE fused plan
shape on the small animals KB (the test_zpipeline idiom).
"""

import json
import re
import time
from pathlib import Path

import pytest

from das_tpu import obs
from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
from das_tpu.core.config import DasConfig
from das_tpu.models.animals import animals_metta
from das_tpu.obs.metrics import Histogram
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.service.coalesce import QueryCoalescer
from das_tpu.service.server import _Tenant
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.obs

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"

COMMIT = '(: "platypus" Concept)\n(Inheritance "platypus" "chimp")'


def _pair_query():
    """Empty on the seed KB; gains its first answer after COMMIT (the
    test_zpipeline idiom)."""
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Node("Concept", "mammal")], True),
    ])


def _matching_query():
    """Non-empty on the seed KB: ($1 inherits $2, $2 inherits animal) —
    e.g. (human, mammal), (snake, reptile) — so materialization runs."""
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Node("Concept", "animal")], True),
    ])


def _tensor_das(config=None):
    data = load_metta_text(animals_metta())
    db = TensorDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zobs", db=db), db


@pytest.fixture
def traced():
    """Tracing ON for the test body, clean ring before and after, OFF
    again on exit — the suite's other files must keep running against
    the no-op fast path."""
    obs.configure(enabled=True, capacity=8192)
    obs.reset()
    yield
    obs.reset()
    obs.configure(enabled=False)


def _serve(das, queries, coal=None, tenant=None):
    """Run queries through a real coalescer worker and wait for the new
    settle span(s) to land: futures resolve INSIDE the serve.settle
    span (and before the window-history append), so the ring/history
    writes race a thread that only waited on the futures."""
    tenant = tenant or _Tenant("zobs", das)
    coal = coal or QueryCoalescer(max_batch=64, pipeline_depth=2)
    before = sum(1 for e in obs.events() if e[0] == "serve.settle")
    futs = [
        coal.submit(tenant, q, QueryOutputFormat.HANDLE) for q in queries
    ]
    for f in futs:
        f.result(timeout=120)
    deadline = time.time() + 10
    while time.time() < deadline:
        now = sum(1 for e in obs.events() if e[0] == "serve.settle")
        if now > before:
            break
        time.sleep(0.01)
    return coal, tenant, [f.result() for f in futs]


# -- end-to-end span coverage ---------------------------------------------


def test_coalesced_query_full_lifecycle(traced):
    das, _db = _tensor_das()
    q = _matching_query()
    coal, _tenant, answers = _serve(das, [q, q, q])
    assert all(a == answers[0] for a in answers) and answers[0]
    names = {e[0] for e in obs.events()}
    for stage in ("serve.submit", "serve.drain", "serve.group",
                  "serve.plan", "serve.dispatch", "serve.settle",
                  "serve.answer", "exec.dispatch", "exec.settle_fetch",
                  "exec.materialize", "cache.miss"):
        assert stage in names, f"lifecycle stage {stage} missing: {names}"
    # every span/event name the ring holds is a declared registry member
    assert names <= set(obs.SPAN_NAMES)


def test_trace_id_threads_submit_to_answer(traced):
    das, _db = _tensor_das()
    _coal, _tenant, _ = _serve(das, [_pair_query()])
    evs = obs.events()
    submits = {e[4] for e in evs if e[0] == "serve.submit"}
    answers = {e[4] for e in evs if e[0] == "serve.answer"}
    assert submits and submits == answers, (submits, answers)


def test_spans_nest_and_order(traced):
    """The group id links the worker's dispatch span to the executor
    spans recorded under it; timestamps order submit < dispatch <=
    settle, and the exec.dispatch span nests inside serve.dispatch."""
    das, _db = _tensor_das()
    _coal, _tenant, _ = _serve(das, [_pair_query()])
    evs = obs.events()

    def spans(name):
        return [e for e in evs if e[0] == name]

    disp = spans("serve.dispatch")[0]
    settle = spans("serve.settle")[0]
    submit = spans("serve.submit")[0]
    gid = disp[4]  # serve.dispatch records trace=group id
    assert settle[4] == gid, "settle span must carry its group id"
    assert submit[2] <= disp[2] <= settle[2]
    # executor spans recorded on the worker thread inherit the group
    ex_disp = [e for e in spans("exec.dispatch") if e[5] == gid]
    assert ex_disp, "exec.dispatch must link to its serving group"
    e = ex_disp[0]
    assert disp[2] <= e[2] and e[2] + e[3] <= disp[2] + disp[3] + 1e-6, (
        "exec.dispatch must nest inside serve.dispatch"
    )
    # dispatch attributes: the window state the §10 decision reads
    for key in ("effective_depth", "rtt_ewma_ms", "dispatch_ewma_ms",
                "delta_version", "speculative", "traces"):
        assert key in disp[8], disp[8]
    # executor attributes: route + planner estimates
    assert e[8]["route"] in ("fused", "fused_kernel", "fused_multiway")
    assert "est_join_rows" in e[8]


def test_planner_observe_carries_est_vs_actual(traced):
    das, _db = _tensor_das()
    _coal, _tenant, _ = _serve(das, [_pair_query()])
    evs = [e for e in obs.events() if e[0] == "planner.observe"]
    assert evs, "planned settle must emit planner.observe"
    attrs = evs[0][8]
    assert attrs["per_step_est"] and attrs["per_step_actual"]
    assert attrs["retry_rounds"] >= 0


# -- cache + commit events ------------------------------------------------


def test_cache_hit_and_commit_invalidation_events(traced):
    das, db = _tensor_das()
    q = _pair_query()
    coal, tenant, _ = _serve(das, [q])
    obs.reset()
    _serve(das, [q], coal=coal, tenant=tenant)  # repeat: pure cache hit
    names = [e[0] for e in obs.events()]
    assert "cache.hit" in names
    assert "exec.dispatch" not in names, "a cache hit dispatched a program"
    assert obs.counter("cache.hits").value >= 1

    obs.reset()
    before = db.delta_version
    das.load_metta_text(COMMIT)  # incremental commit
    evs = obs.events()
    deltas = [e for e in evs if e[0] == "commit.delta"]
    assert deltas and deltas[0][8]["version"] == db.delta_version
    assert db.delta_version > before
    # the post-commit repeat must invalidate, then miss, then dispatch
    _serve(das, [q], coal=coal, tenant=tenant)
    names = [e[0] for e in obs.events()]
    assert "cache.invalidate" in names
    assert "cache.miss" in names


# -- histogram percentile math --------------------------------------------


def test_histogram_percentiles_vs_exact_quantiles():
    import random

    rng = random.Random(7)
    for dist in (
        [rng.lognormvariate(1.0, 1.0) for _ in range(4000)],
        [rng.uniform(0.5, 500.0) for _ in range(4000)],
    ):
        h = Histogram("t")
        for v in dist:
            h.observe(v)
        s = sorted(dist)
        for q in (0.5, 0.95, 0.99):
            exact = s[max(0, int(q * len(s)) - 1)]
            approx = h.percentile(q)
            # fixed log buckets at ratio 2^(1/4): ~19% worst-case
            # relative error by construction
            assert abs(approx - exact) / exact < 0.2, (q, exact, approx)
        assert h.total == len(dist)
        assert abs(h.sum_ms - sum(dist)) < 1e-6 * sum(dist)


def test_histogram_edges():
    h = Histogram("t")
    assert h.percentile(0.5) is None  # empty
    h.observe(3.0)
    # single sample: min/max tighten the bucket to the sample itself
    assert abs(h.percentile(0.5) - 3.0) < 0.7
    assert h.percentile(0.99) <= h.max_ms + 1e-9
    h2 = Histogram("t2")
    h2.observe(0.0)      # below the lowest edge: clamps, never throws
    h2.observe(1e12)     # above the highest edge: clamps, never throws
    assert h2.total == 2


def test_histogram_percentiles_monotone():
    import random

    rng = random.Random(3)
    h = Histogram("t")
    for _ in range(1000):
        h.observe(rng.expovariate(0.1))
    ps = [h.percentile(q) for q in (0.1, 0.5, 0.9, 0.95, 0.99)]
    assert ps == sorted(ps)


# -- disabled mode: structurally a no-op ----------------------------------


def test_disabled_mode_allocates_no_span_objects():
    """THE acceptance pin: with DAS_TPU_TRACE off, span() hands back the
    one shared no-op singleton (identity — no per-call span objects),
    mark() is None, new_trace() is 0, and a full served workload leaves
    the ring empty and the metric layer untouched."""
    assert not obs.enabled()
    assert obs.span("serve.drain", width=4) is obs.NOOP_SPAN
    assert obs.span("exec.dispatch") is obs.NOOP_SPAN
    assert obs.mark() is None
    assert obs.new_trace() == 0
    counters_before = {k: c.value for k, c in obs.metrics.COUNTERS.items()}
    das, _db = _tensor_das()
    coal = QueryCoalescer(max_batch=8, pipeline_depth=2)
    tenant = _Tenant("zobs-off", das)
    futs = [
        coal.submit(tenant, _pair_query(), QueryOutputFormat.HANDLE)
        for _ in range(3)
    ]
    for f in futs:
        f.result(timeout=120)
    assert obs.events() == []
    assert {
        k: c.value for k, c in obs.metrics.COUNTERS.items()
    } == counters_before
    # the queue tuple carries None instead of a mark: no trace state
    snap = coal.snapshot()
    assert snap["items"] == 3


# -- exporters -------------------------------------------------------------


def test_chrome_trace_golden_shape(traced):
    das, _db = _tensor_das()
    _serve(das, [_pair_query()])
    doc = obs.chrome_trace(obs.events())
    # must round-trip as JSON (the Perfetto contract is plain JSON)
    doc = json.loads(json.dumps(doc))
    evs = doc["traceEvents"]
    assert evs, "empty trace"
    phases = {e["ph"] for e in evs}
    assert phases <= {"X", "i", "M"}
    for e in evs:
        assert isinstance(e["name"], str)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] == "X":
            assert e["dur"] >= 0 and e["ts"] >= 0
        if e["ph"] == "i":
            assert e["s"] == "t"
    # one lane per tenant: the tenant name appears as a process_name
    lanes = {
        e["args"]["name"] for e in evs
        if e["ph"] == "M" and e["name"] == "process_name"
    }
    assert "zobs" in lanes


def test_prometheus_text_golden_shape(traced):
    das, _db = _tensor_das()
    _serve(das, [_pair_query()])
    text = obs.prometheus_text(extra_gauges={"serving.effective_depth": 2})
    line_re = re.compile(
        r'^(# (TYPE|HELP) .*|[a-zA-Z_][a-zA-Z0-9_]*(\{[^}]*\})? '
        r'[-+0-9.eE]+)$'
    )
    for line in text.strip().splitlines():
        assert line_re.match(line), f"bad exposition line: {line!r}"
    assert "das_tpu_obs_serve_submitted_total 1" in text
    assert "das_tpu_obs_serving_effective_depth 2" in text
    # histogram triple: cumulative buckets, +Inf == count, sum present
    h = obs.histogram("serve.answer_ms")
    assert f'das_tpu_obs_serve_answer_ms_bucket{{le="+Inf"}} {h.total}' \
        in text
    assert "das_tpu_obs_serve_answer_ms_count" in text
    assert "das_tpu_obs_serve_answer_ms_sum" in text
    cums = [
        int(m.group(1)) for m in re.finditer(
            r'das_tpu_obs_serve_answer_ms_bucket\{le="[^+][^"]*"\} (\d+)',
            text,
        )
    ]
    assert cums == sorted(cums), "bucket counts must be cumulative"


def test_server_metrics_text_surface(traced):
    from das_tpu.service.server import DasService

    das, _db = _tensor_das()
    service = DasService()
    service.attach_tenant("zobs-metrics", das)
    text = service.metrics_text()
    assert "das_tpu_obs_serving_batches" in text
    assert "das_tpu_obs_exec_dispatches_total" in text


# -- the window-history ring (satellite) -----------------------------------


def test_window_history_ring(traced):
    das, _db = _tensor_das()
    q = _pair_query()
    cfg = DasConfig(result_cache_size=0)  # every round pays the wire
    das.config = cfg
    _db.config = cfg
    coal, tenant, _ = _serve(das, [q, q])
    for _ in range(3):
        _serve(das, [q], coal=coal, tenant=tenant)
    snap = coal.snapshot()
    hist = snap["window_history"]
    assert hist, "wire-fed settles must append history samples"
    for rtt, disp, depth in hist:
        assert rtt >= 0.0 and disp >= 0.0 and depth >= 1
    # the last sample mirrors the current EWMAs/depth surface
    assert hist[-1][0] == snap["rtt_ewma_ms"]
    from das_tpu.service.coalesce import _HISTORY_K

    assert len(hist) <= _HISTORY_K


def test_window_history_in_service_stats(traced):
    from das_tpu.service.server import DasService

    das, _db = _tensor_das()
    service = DasService()
    service.attach_tenant("zobs-hist", das)
    tenant = next(iter(service.tenants.values()))
    _serve(das, [_pair_query()], tenant=tenant,
           coal=tenant.get_coalescer())
    stats = service.coalescer_stats()
    per = stats["tenants"]["zobs-hist"]
    assert "window_history" in per
    assert all(len(s) == 3 for s in per["window_history"])


# -- DL014 ----------------------------------------------------------------


def test_dl014_clean_tree():
    from das_tpu.analysis import run_analysis

    findings = run_analysis([REPO / "das_tpu"], rules=["DL014"])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_dl014_fixture_corpus():
    from das_tpu.analysis import run_analysis

    bad = run_analysis([FIXTURES / "dl014_bad.py"], rules=["DL014"])
    msgs = "\n".join(f.message for f in bad)
    assert "serve.fetchh" in msgs, msgs          # undeclared span literal
    assert "serve.rows_ms" in msgs, msgs         # undeclared histogram
    assert "serve.retired" in msgs, msgs         # stale registry entry
    assert len(bad) == 3, msgs
    good = run_analysis([FIXTURES / "dl014_good.py"], rules=["DL014"])
    assert good == [], "\n".join(f.render() for f in good)


def test_dl014_partial_suppresses_stale_only():
    from das_tpu.analysis import run_analysis

    partial = run_analysis(
        [FIXTURES / "dl014_bad.py"], rules=["DL014"], partial=True
    )
    msgs = "\n".join(f.message for f in partial)
    assert "serve.fetchh" in msgs and "serve.rows_ms" in msgs
    assert "serve.retired" not in msgs, (
        "--changed-only runs must skip the stale-entry leg"
    )


def test_dl014_catches_typo_on_real_instrumentation_site(tmp_path):
    """Mutated-copy regression: typo ONE span literal in the real
    coalescer next to the real registry — DL014 must fire on exactly
    that literal."""
    from das_tpu.analysis import run_analysis

    src = (REPO / "das_tpu/service/coalesce.py").read_text()
    needle = 'obs.span("serve.drain", width=width)'
    assert src.count(needle) == 1, "coalesce.py layout changed"
    mutated = tmp_path / "coalesce.py"
    mutated.write_text(src.replace(
        needle, 'obs.span("serve.drian", width=width)', 1
    ))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/obs/registry.py"],
        rules=["DL014"], partial=True,
    )
    assert any("serve.drian" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )
    # the committed module next to the registry stays clean
    clean = run_analysis(
        [REPO / "das_tpu/service/coalesce.py",
         REPO / "das_tpu/obs/registry.py"],
        rules=["DL014"], partial=True,
    )
    assert clean == [], "\n".join(f.render() for f in clean)


def test_obs_registries_pinned():
    """The declared name sets themselves (the DL004-idiom test leg): a
    rename or deletion must be a reviewed change here, not a silent
    drift of the dashboard vocabulary."""
    assert set(obs.SPAN_NAMES) >= {
        "serve.submit", "serve.drain", "serve.group", "serve.plan",
        "serve.dispatch", "serve.settle", "serve.answer",
        "exec.dispatch", "exec.settle_fetch", "exec.materialize",
        "cache.hit", "cache.miss", "cache.invalidate",
        "commit.delta", "commit.rebuild", "planner.observe",
        "serve.deadline", "serve.breaker", "fault.inject",
    }
    assert set(obs.COUNTER_NAMES) >= {
        "serve.submitted", "serve.answers", "serve.rejections",
        "cache.hits", "cache.misses", "cache.invalidations",
        "commit.deltas", "exec.dispatches", "exec.fetches",
        "serve.deadline_misses", "serve.breaker_trips",
        "serve.breaker_recoveries", "fault.injected", "fault.retries",
    }
    assert set(obs.HISTOGRAM_NAMES) >= {
        "serve.queue_ms", "serve.dispatch_ms", "serve.settle_ms",
        "serve.answer_ms", "exec.settle_fetch_ms",
    }
    # the metric dicts are BUILT from the registry
    assert set(obs.metrics.COUNTERS) == set(obs.COUNTER_NAMES)
    assert set(obs.metrics.HISTOGRAMS) == set(obs.HISTOGRAM_NAMES)


# -- jax.profiler integration gate ----------------------------------------


def test_jax_annotation_gate(monkeypatch):
    """DAS_TPU_TRACE_JAX off (default): the shared no-op, no jax
    import; on: a real jax.profiler.TraceAnnotation (enterable even
    with no device trace running)."""
    from das_tpu.obs import jaxprof

    monkeypatch.delenv("DAS_TPU_TRACE_JAX", raising=False)
    assert jaxprof.annotation("exec.dispatch") is obs.NOOP_SPAN
    monkeypatch.setenv("DAS_TPU_TRACE_JAX", "1")
    ann = jaxprof.annotation("exec.dispatch")
    assert ann is not obs.NOOP_SPAN
    with ann:
        pass


def test_profiler_trace_dir_plumbed():
    """DasConfig.profiler_trace_dir rides env DAS_TPU_TRACE_DIR
    (obs.maybe_start_trace consumes it); no dir configured = no trace
    started."""
    assert obs.maybe_start_trace(DasConfig()) is False
    import os

    os.environ["DAS_TPU_TRACE_DIR"] = "/tmp/zobs-trace-dir"
    try:
        cfg = DasConfig.from_env()
        assert cfg.profiler_trace_dir == "/tmp/zobs-trace-dir"
    finally:
        del os.environ["DAS_TPU_TRACE_DIR"]


# -- backpressure + rejection event ---------------------------------------


def test_reject_event_and_counter(traced):
    das, _db = _tensor_das()
    coal = QueryCoalescer(max_batch=4, pipeline_depth=1, queue_max=1)
    tenant = _Tenant("zobs-reject", das)
    # saturate: the queue bound is 1 and no worker is draining yet —
    # fill it, then the next submit must reject
    import queue as _q

    coal._queue.put_nowait((tenant, _pair_query(),
                            QueryOutputFormat.HANDLE, None, None))
    before = obs.counter("serve.rejections").value
    fut = coal.submit(tenant, _pair_query(), QueryOutputFormat.HANDLE)
    with pytest.raises(Exception):
        fut.result(timeout=5)
    assert obs.counter("serve.rejections").value == before + 1
    assert any(e[0] == "serve.reject" for e in obs.events())
    # unblock the stuffed queue entry so the worker (spawned by the
    # rejected submit path? no — rejects never spawn) stays idle
    coal._queue.get_nowait()
