"""Unit battery for the daslint v2 call-graph/dataflow core
(das_tpu/analysis/callgraph.py) — marker `lint`, rides ops/pytests.sh
lint with the rule suite.

Pins the resolution semantics the DL010-DL013 rules lean on: bare-name
and imported-name calls, `self.method` resolution through repo-local
base classes (the _TreeExecJob / _ShardedTreeExecJob split), nested
defs folding into their owner, cycle-safe reachability with shortest
paths, and the module-naming rules (das_tpu dotted names, __init__ ->
package, loose-file stems)."""

from pathlib import Path

import pytest

from das_tpu.analysis.callgraph import (
    CallGraph,
    callgraph,
    module_dotted,
    module_table,
    scope_module,
)
from das_tpu.analysis.core import AnalysisContext, collect_files

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent


def _graph(tmp_path, sources):
    files = []
    for name, src in sources.items():
        p = tmp_path / name
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src)
        files.append(p)
    sfs = collect_files(files)
    return CallGraph(sfs), {sf.name: sf for sf in sfs}


def _reached(graph, sf, node, cls=None):
    return {info.qname: path for info, path in graph.walk(sf, node, cls)}


def test_module_naming():
    sfs = collect_files([
        REPO / "das_tpu/query/fused.py",
        REPO / "das_tpu/planner/__init__.py",
    ])
    assert module_dotted(sfs[0]) == "das_tpu.query.fused"
    assert module_dotted(sfs[1]) == "das_tpu.planner"
    assert scope_module(sfs[0]) == "fused"
    assert scope_module(sfs[1]) == "planner"


def test_cycles_terminate_and_paths_are_shortest(tmp_path):
    graph, sfs = _graph(tmp_path, {"loop.py": (
        "def a():\n    b()\n"
        "def b():\n    a()\n    c()\n"
        "def c():\n    pass\n"
        "def root():\n    a()\n    c()\n"
    )})
    sf = sfs["loop"]
    root = module_table(sf).defs["root"]
    reached = _reached(graph, sf, root)
    assert set(reached) == {"loop::a", "loop::b", "loop::c"}
    # c is a direct callee of root: one hop, not the a->b->c detour
    assert len(reached["loop::c"]) == 1
    assert len(reached["loop::b"]) == 2


def test_method_resolution_through_base(tmp_path):
    graph, sfs = _graph(tmp_path, {
        "basemod.py": (
            "from helpers import transfer\n"
            "class Base:\n"
            "    def shared(self):\n"
            "        return transfer()\n"
        ),
        "helpers.py": "def transfer():\n    return 1\n",
        "derived.py": (
            "from basemod import Base\n"
            "class Derived(Base):\n"
            "    def dispatch(self):\n"
            "        return self.shared()\n"
        ),
    })
    sf = sfs["derived"]
    node = module_table(sf).methods["Derived"]["dispatch"]
    reached = _reached(graph, sf, node, "Derived")
    assert "basemod::Base.shared" in reached
    assert "helpers::transfer" in reached
    # the path threads the inherited method, then the import
    assert [q for _l, q in reached["helpers::transfer"]] == [
        "basemod::Base.shared", "helpers::transfer",
    ]


def test_nested_defs_fold_into_owner(tmp_path):
    graph, sfs = _graph(tmp_path, {"nested.py": (
        "def helper():\n    pass\n"
        "def owner():\n"
        "    def inner():\n"
        "        helper()\n"
        "    return inner\n"
    )})
    sf = sfs["nested"]
    owner = module_table(sf).defs["owner"]
    assert "nested::helper" in _reached(graph, sf, owner)


def test_imported_module_attribute_calls(tmp_path):
    graph, sfs = _graph(tmp_path, {
        "pkgmod.py": "def vmem_budget():\n    return 8\n",
        "user.py": (
            "import pkgmod\n"
            "from pkgmod import vmem_budget as vb\n"
            "def go():\n"
            "    pkgmod.vmem_budget()\n"
            "def go2():\n"
            "    vb()\n"
        ),
    })
    sf = sfs["user"]
    t = module_table(sf)
    assert "pkgmod::vmem_budget" in _reached(graph, sf, t.defs["go"])
    assert "pkgmod::vmem_budget" in _reached(graph, sf, t.defs["go2"])


def test_constructor_resolves_to_init(tmp_path):
    graph, sfs = _graph(tmp_path, {"ctor.py": (
        "class Job:\n"
        "    def __init__(self):\n"
        "        prep()\n"
        "def prep():\n    pass\n"
        "def spawn():\n    return Job()\n"
    )})
    sf = sfs["ctor"]
    reached = _reached(graph, sf, module_table(sf).defs["spawn"])
    assert "ctor::Job.__init__" in reached
    assert "ctor::prep" in reached


def test_unresolvable_calls_do_not_invent_edges(tmp_path):
    graph, sfs = _graph(tmp_path, {"opaque.py": (
        "import numpy as np\n"
        "def target():\n    pass\n"
        "def go(cb):\n"
        "    cb()\n"              # parameter-held callable
        "    np.asarray([1])\n"   # foreign module
        "    obj = object()\n"
        "    obj.dispatch\n"
    )})
    sf = sfs["opaque"]
    assert _reached(graph, sf, module_table(sf).defs["go"]) == {}


def test_context_caches_one_graph():
    files = collect_files([REPO / "das_tpu/analysis/callgraph.py"])
    ctx = AnalysisContext(files, None)
    assert callgraph(ctx) is callgraph(ctx)


def test_real_tree_dispatch_reaches_builder():
    """On the real repo: _ExecJob.dispatch -> build_fused resolves, and
    the whole-tree job's inherited _dispatch_common edge threads the
    subclass (the resolution DL010 depends on)."""
    files = collect_files([REPO / "das_tpu"])
    graph = CallGraph(files)
    fused = next(sf for sf in files if sf.posix.endswith("query/fused.py"))
    t = module_table(fused)
    dispatch = t.methods["_ExecJob"]["dispatch"]
    reached = {
        info.qname for info, _p in graph.walk(fused, dispatch, "_ExecJob")
    }
    assert "das_tpu.query.fused::build_fused" in reached
    sharded = next(
        sf for sf in files if sf.posix.endswith("parallel/fused_sharded.py")
    )
    st = module_table(sharded)
    tree_dispatch = st.methods["_ShardedTreeExecJob"]["dispatch"]
    reached = {
        info.qname
        for info, _p in graph.walk(
            sharded, tree_dispatch, "_ShardedTreeExecJob"
        )
    }
    assert "das_tpu.query.fused::_TreeExecJob._dispatch_common" in reached
