"""Converters (atomese2metta, flybase SQL) + checkpoint round-trip tests."""

import numpy as np
import pytest

from das_tpu.convert.atomese2metta import (
    InvalidSymbol,
    Translator,
    parse_sexpr,
    strip_suffix,
    translate_text,
)
from das_tpu.convert.flybase import FlybaseConverter
from das_tpu.storage import checkpoint
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.memory_db import MemoryDB

SCM = """
; a comment
(EvaluationLink (stv 1.0 0.99)
  (PredicateNode "has_name")
  (ListLink
    (GeneNode "FBgn0000001")
    (ConceptNode "gene one")))
(InheritanceLink
  (GeneNode "FBgn0000001")
  (ConceptNode "gene"))
(SetLink
  (GeneNode "FBgn0000001")
  (GeneNode "FBgn0000002"))
"""


class TestAtomese2Metta:
    def test_strip_suffix(self):
        assert strip_suffix("ConceptNode") == "Concept"
        assert strip_suffix("MemberLink") == "Member"
        assert strip_suffix("Concept") == "Concept"

    def test_parse_sexpr_comments_and_strings(self):
        trees = parse_sexpr('(A "x; (not a comment)") ; trailing\n(B y)')
        assert trees == [["A", '"x; (not a comment)"'], ["B", "y"]]

    def test_translate_document(self):
        text = translate_text(SCM)
        lines = text.strip().split("\n")
        # typedefs first, then node declarations, then body
        assert "(: Predicate Type)" in lines
        assert "(: Gene Type)" in lines
        assert '(: "FBgn0000001" Gene)' in lines
        assert any(line.startswith("(Evaluation ") for line in lines)
        # stv skipped
        assert "stv" not in text
        # SetLink renders as multiset braces
        assert '{"FBgn0000001" "FBgn0000002"}' in text

    def test_output_loads_through_metta_parser(self):
        data = load_metta_text(translate_text(SCM))
        nodes, links = data.count_atoms()
        assert nodes == 5  # has_name, 2 genes, 2 concepts
        assert links == 4  # Evaluation, nested List, Inheritance, {set}

    def test_unknown_symbol_raises(self):
        with pytest.raises(InvalidSymbol):
            translate_text("(BogusLink (ConceptNode \"x\"))")


SQL = """\
CREATE TABLE public.gene (
    gene_id integer NOT NULL,
    name character varying(255),
    organism_id integer
);
ALTER TABLE ONLY public.gene
    ADD CONSTRAINT gene_pkey PRIMARY KEY (gene_id);
ALTER TABLE ONLY public.gene
    ADD CONSTRAINT gene_org_fk FOREIGN KEY (organism_id) REFERENCES public.organism(organism_id);
CREATE TABLE public.organism (
    organism_id integer NOT NULL,
    genus character varying(255)
);
ALTER TABLE ONLY public.organism
    ADD CONSTRAINT organism_pkey PRIMARY KEY (organism_id);
COPY public.organism (organism_id, genus) FROM stdin;
7227\tDrosophila
\\.
COPY public.gene (gene_id, name, organism_id) FROM stdin;
1\twhite\t7227
2\t\\N\t7227
\\.
"""


class TestFlybase:
    def test_convert_and_load(self, tmp_path):
        sql = tmp_path / "dump.sql"
        sql.write_text(SQL)
        out = tmp_path / "out"
        stats = FlybaseConverter(str(sql), str(out)).run()
        assert stats["tables"] == 2
        assert stats["rows"] == 3
        text = (out / "file_001.metta").read_text()
        assert '(Inheritance "gene:1" "gene")' in text
        # FK column resolves to the referenced row node
        assert '(Execution (Schema "gene.organism_id") "gene:1" "organism:7227")' in text
        # null (\\N) column skipped
        assert '"gene.name") "gene:2"' not in text
        # numeric typing
        assert '(: "Drosophila" Verbatim)' in text
        data = load_metta_text(text)
        nodes, links = data.count_atoms()
        assert links > 0 and nodes > 0

    def test_table_allowlist(self, tmp_path):
        sql = tmp_path / "dump.sql"
        sql.write_text(SQL)
        out = tmp_path / "out"
        stats = FlybaseConverter(str(sql), str(out), tables=["organism"]).run()
        assert stats["rows"] == 1


class TestCheckpoint:
    def test_round_trip(self, tmp_path, animals_data):
        path = tmp_path / "ckpt"
        checkpoint.save(animals_data, str(path))
        restored = checkpoint.load(str(path))
        assert restored.count_atoms() == animals_data.count_atoms()
        # indexes restored without re-finalize: _fin is already set
        assert restored._fin is not None
        a, b = animals_data.finalize(), restored._fin
        assert a.atom_count == b.atom_count
        assert a.hex_of_row == b.hex_of_row
        assert a.type_names == b.type_names
        for arity, bucket in a.buckets.items():
            np.testing.assert_array_equal(bucket.targets, b.buckets[arity].targets)
            np.testing.assert_array_equal(bucket.key_type, b.buckets[arity].key_type)
        # restored store answers queries identically
        db = MemoryDB(restored)
        assert db.get_node_handle("Concept", "human") == (
            "af12f10f9ae2002a1607ba0b47ba8407"
        )

    def test_fallback_without_indexes(self, tmp_path, animals_data):
        path = tmp_path / "ckpt"
        checkpoint.save(animals_data, str(path), with_indexes=False)
        restored = checkpoint.load(str(path))
        assert restored._fin is None  # falls back to lazy finalize
        assert restored.count_atoms() == animals_data.count_atoms()
        assert restored.finalize().atom_count == animals_data.finalize().atom_count

    def test_stale_indexes_rejected(self, tmp_path, animals_data):
        from das_tpu.storage.atom_table import NodeRec

        path = tmp_path / "ckpt"
        checkpoint.save(animals_data, str(path))
        # corrupt: drop a node from records only
        import msgpack

        rec_path = path / "records.msgpack"
        payload = msgpack.unpackb(rec_path.read_bytes(), raw=False)
        first = next(iter(payload["nodes"]))
        del payload["nodes"][first]
        rec_path.write_bytes(msgpack.packb(payload, use_bin_type=True))
        # an OUT-OF-BAND edit is corruption since ISSUE 15: the manifest
        # digest no longer matches and load() refuses typed instead of
        # serving unverified bytes
        import json
        import zlib

        import pytest

        from das_tpu.core.exceptions import SnapshotCorruptError

        with pytest.raises(SnapshotCorruptError):
            checkpoint.load(str(path))
        # a LEGITIMATE records-only rewrite (manifest digest updated in
        # step) still hits the staleness check: records load, the now
        # count-inconsistent indexes are refused, not trusted
        mpath = path / "MANIFEST.json"
        manifest = json.loads(mpath.read_text())
        b = rec_path.read_bytes()
        manifest["sections"]["records.msgpack"] = {
            "bytes": len(b), "crc32": zlib.crc32(b),
        }
        mpath.write_text(json.dumps(manifest))
        restored = checkpoint.load(str(path))
        assert restored._fin is None  # stale indexes refused, not trusted
