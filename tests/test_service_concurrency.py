"""Service-edge concurrency (VERDICT r02 item 10): the per-tenant-lock
claim, proven over a real gRPC channel against device backends.

The reference server serializes EVERY RPC behind one global Condition
(/root/reference/service/server.py:114-115): any long operation on tenant
A blocks tenant B entirely.  Here each tenant has its own RLock, so:

* while tenant A's lock is held (exactly what the server holds during an
  RPC on A), tenant B's RPCs complete normally — deterministic
  no-cross-tenant-serialization proof, no timing heuristics;
* an RPC on A itself stays pending until the lock frees, then completes;
* a commit thread interleaved with gRPC readers on the SAME tenant always
  yields consistent snapshots (counts step through exact pre/post-commit
  values, never a torn state), with correct final answers.
"""

import threading
import time

import pytest

from das_tpu.query.ast import Link, Node, Variable

HUMAN = "af12f10f9ae2002a1607ba0b47ba8407"


@pytest.fixture(scope="module")
def service_stack(tmp_path_factory):
    from das_tpu.models.animals import write_animals_metta
    from das_tpu.service.client import DasClient
    from das_tpu.service.server import serve

    kb = tmp_path_factory.mktemp("kb") / "animals.metta"
    write_animals_metta(str(kb))
    server, service = serve(port=0, backend="tensor", block=False)
    client = DasClient(port=server.bound_port)
    tokens = {}
    for name in ("tenant-a", "tenant-b"):
        token = client.create(name)["msg"]
        assert client.load_knowledge_base(token, f"file://{kb}")["success"]
        for _ in range(120):
            if client.check_das_status(token)["msg"] == "Ready":
                break
            time.sleep(0.25)
        assert client.check_das_status(token)["msg"] == "Ready"
        tokens[name] = token
    yield client, service, tokens
    client.close()
    server.stop(0)


def test_tenant_b_not_blocked_by_tenant_a_lock(service_stack):
    client, service, tokens = service_stack
    tenant_a = service.tenants[tokens["tenant-a"]]
    with tenant_a.lock:  # tenant A mid-RPC
        t0 = time.monotonic()
        result = client.count(tokens["tenant-b"])
        elapsed = time.monotonic() - t0
    assert result["success"] and result["msg"] == "(14, 26)"
    # B's RPC ran while A's lock was held; generous bound, but a global
    # lock would deadlock here (we hold A until the call returns)
    assert elapsed < 30


def test_tenant_a_rpc_waits_for_its_own_lock(service_stack):
    client, service, tokens = service_stack
    tenant_a = service.tenants[tokens["tenant-a"]]
    done = threading.Event()
    result = {}

    def call_a():
        result.update(client.count(tokens["tenant-a"]))
        done.set()

    tenant_a.lock.acquire()
    try:
        threading.Thread(target=call_a, daemon=True).start()
        # the RPC must be pending while A's lock is held
        assert not done.wait(timeout=1.0)
    finally:
        tenant_a.lock.release()
    assert done.wait(timeout=30)
    assert result["success"] and result["msg"] == "(14, 26)"


def test_interleaved_commits_yield_consistent_snapshots(service_stack):
    client, service, tokens = service_stack
    token = tokens["tenant-b"]
    tenant = service.tenants[token]
    n_commits = 8
    valid_counts = {f"({14 + i}, {26 + 2 * i})" for i in range(n_commits + 1)}
    stop = threading.Event()
    errors = []

    def committer():
        try:
            for i in range(n_commits):
                tx = tenant.das.open_transaction()
                tx.add(f'(: "beast{i}" Concept)')
                tx.add(f'(Inheritance "beast{i}" "mammal")')
                tx.add(f'(Similarity "beast{i}" "human")')
                with tenant.lock:  # the server-side mutation discipline
                    tenant.das.commit_transaction(tx)
                time.sleep(0.02)
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)
        finally:
            stop.set()

    seen = []
    thread = threading.Thread(target=committer, daemon=True)
    thread.start()
    while not stop.is_set():
        out = client.count(token)
        assert out["success"]
        seen.append(out["msg"])
    thread.join(timeout=60)
    assert not errors, errors
    # every snapshot is an exact commit boundary — no torn reads
    assert set(seen) <= valid_counts
    # final state reflects all commits, and the new atoms answer queries
    assert client.count(token)["msg"] == f"({14 + n_commits}, {26 + 2 * n_commits})"
    q = client.query(
        token, f"Node n1 Concept beast{n_commits - 1}, Link Similarity n1 $1"
    )
    assert q["success"] and HUMAN in q["msg"]


def test_concurrent_queries_two_tenants_correct(service_stack):
    client, _, tokens = service_stack
    errors = []

    def worker(token):
        try:
            for _ in range(10):
                out = client.query(
                    token, "Node n1 Concept human, Link Inheritance n1 $1"
                )
                assert out["success"]
                assert "bdfe4e7a431f73386f37c6448afe5840" in out["msg"]
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [
        threading.Thread(target=worker, args=(tokens[name],), daemon=True)
        for name in ("tenant-a", "tenant-b")
        for _ in range(3)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
