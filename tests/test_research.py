"""Research/legacy layer (das_tpu/research/): the reference's own cache
and heap test matrices ported case-for-case
(/root/reference/das/research/cache.py:112-253, heap.py:120-173), plus
the incoming-set builder differentially checked against the finalized
device CSR."""

from das_tpu.research.cache import CachedKVClient, FakeKVClient
from das_tpu.research.heap import Heap, PrioritizedItem


# -- heap matrix (reference heap.py:120-173) --------------------------------


def test_heap_should_behave_like_a_heap():
    v = Heap()
    n = 1000
    for i in range(n):
        v.heap_push(PrioritizedItem(key=str(i), size=i, value=""))
    assert v[0].size == 0
    for i in range(n // 2):
        left, right = 2 * i + 1, 2 * i + 2
        if left < n:
            assert v[i] <= v[left]
        if right < n:
            assert v[i] <= v[right]


def test_fix_down_should_keep_heap_constraints():
    v = Heap()
    n = 1000
    for i in range(n):
        v.heap_push(PrioritizedItem(key=str(i), size=i, value=""))
    # raise a mid-heap item's priority in place, then repair
    v[13].size = n + 1
    v.fix_down(v[13])
    for i in range(n // 2):
        left, right = 2 * i + 1, 2 * i + 2
        if left < n:
            assert v[i] <= v[left]
        if right < n:
            assert v[i] <= v[right]


def test_heap_pop_should_return_items_in_order():
    h = Heap()
    for size in (3, 2, 7, 4, 1, 5, 6):
        h.heap_push(PrioritizedItem(key=str(size), size=size, value=""))
    for i in range(1, 8):
        assert h.heap_pop().size == i


# -- cache matrix (reference cache.py:112-253) ------------------------------


def test_cached_client_should_return_values_from_embedded_client():
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=3)
    fake.add("1", [1])
    fake.add("2", [2, 2])
    fake.add("3", [3, 3, 3])
    assert cached.get("1") == [1]
    assert cached.get("2") == [2, 2]
    assert cached.get("3") == [3, 3, 3]
    assert fake.total_add_calls == 3


def test_cached_client_should_update_value_without_updating_actual_client():
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=3)
    fake.add("1", [1])
    fake.add("2", [2, 2])
    fake.add("3", [3, 3, 3])
    assert cached.get("1") == [1]
    cached.add("1", [10], size=1)
    assert cached.current_size == 1
    cached.get("1")
    cached.add("1", [10, 10], size=2)
    assert cached.current_size == 2
    e = cached.get("2")
    e.append(2)
    assert e == [2, 2, 2]  # reads are copies; the store is untouched
    assert fake.total_add_calls == 3


def test_cached_client_should_call_actual_client_if_threshold():
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=7)
    fake.add("1", [1])
    fake.add("2", [2])
    fake.add("3", [3])
    item = cached.get("1")
    item.extend([1, 1])
    cached.add("1", item, 3)
    assert cached.current_size == 3
    assert fake.total_add_calls == 3
    assert fake.get("1") == [1]  # still the old value: write deferred
    item = cached.get("2")
    item.extend([2, 2])
    cached.add("2", item, 3)
    assert cached.current_size == 6
    assert fake.total_add_calls == 3


def test_cached_should_not_call_actual_client_without_limit_being_achieved():
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=8)
    cached.add("1", [1], size=1)
    cached.add("2", [2], size=1)
    v2 = cached.get("2")
    v2.append(2)
    cached.add("2", v2, size=len(v2))
    assert cached.current_size == 3
    v2 = cached.get("2")
    v2.append(2)
    cached.add("2", v2, size=len(v2))
    assert cached.current_size == 4
    cached.add("3", [3], size=1)
    v3 = cached.get("3")
    v3.append(3)
    cached.add("3", v3, size=len(v3))
    v3 = cached.get("3")
    v3.append(3)
    cached.add("3", v3, size=len(v3))
    assert cached.current_size == 7
    assert fake.total_add_calls == 0
    cached.add("4", [4, 4], size=2)  # budget exceeded: smallest evicts
    assert fake.total_add_calls == 1
    assert cached.current_size == 8


def test_cached_should_flush_correctly():
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=8)
    cached.add("1", [1], size=1)
    cached.add("2", [2], size=1)
    cached.add("3", [3], size=1)
    assert fake.total_add_calls == 0
    cached.flush()
    assert fake.total_add_calls == 3
    assert cached.current_size == 0 and len(cached.heap) == 0


def test_cached_should_just_call_embedded_client_if_size_greater_than_limit():
    for limit in (1, 0):
        fake = FakeKVClient()
        cached = CachedKVClient(fake, limit=limit)
        cached.add("1", [1, 2], size=2)
        assert fake.total_add_calls == 1
        assert cached.current_size == 0
        assert cached.get("1") == [1, 2]


def test_update_during_eviction_does_not_self_evict():
    """Updating the heap-minimum key while over budget must not evict the
    key under update (the reference's add raises KeyError here,
    cache.py:73-97 — a documented departure)."""
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=6)
    cached.add("x", ["h1", "h2"], size=2)
    cached.add("y", ["a", "b", "c"], size=3)
    cached.add("x", ["h1", "h2", "h3", "h4", "h5"], size=5)
    assert cached.get("x") == ["h1", "h2", "h3", "h4", "h5"]
    assert cached.current_size <= 6


def test_write_through_invalidates_stale_cache_entry():
    """A write-through update of a cached key must drop the old cached
    copy: flush() would otherwise clobber the newer backend value with
    the stale one (second documented departure from the reference)."""
    fake = FakeKVClient()
    cached = CachedKVClient(fake, limit=4)
    cached.add("z", ["h1", "h2"], size=2)
    cached.add("z", ["h1", "h2", "h3", "h4", "h5"], size=5)  # > limit
    assert fake.get("z") == ["h1", "h2", "h3", "h4", "h5"]
    assert cached.get("z") == ["h1", "h2", "h3", "h4", "h5"]
    cached.flush()
    assert fake.get("z") == ["h1", "h2", "h3", "h4", "h5"]


# -- incoming/outgoing builder vs the device CSR ----------------------------


def test_populate_sets_matches_finalized_csr(animals_data):
    from das_tpu.research.incoming_builder import populate_sets, read_sets

    fake = FakeKVClient()
    stats = populate_sets(animals_data, fake, cache_limit=64)
    assert len(stats["incoming_size"].samples) > 0
    fin = animals_data.finalize()
    for handle, rec in animals_data.links.items():
        outgoing, _ = read_sets(fake, handle)
        assert outgoing == sorted(set(rec.elements))
    # every atom's incoming set equals the CSR slice
    for row, handle in enumerate(fin.hex_of_row):
        lo, hi = fin.incoming_offsets[row], fin.incoming_offsets[row + 1]
        expected = sorted({fin.hex_of_row[r] for r in fin.incoming_links[lo:hi]})
        _, incoming = read_sets(fake, handle)
        assert incoming == expected, handle
