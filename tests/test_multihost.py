"""Multi-host (DCN) path: two actual OS processes join one JAX distributed
runtime through `das_tpu.parallel.mesh.multihost_initialize`, build a
global mesh spanning both hosts' devices, and run a sharded query step
whose collectives cross the process boundary.

This is the P6 axis the reference covers with a 3-node Redis cluster
(SURVEY.md §2.10); here the transport is jax.distributed's gRPC
coordination + cross-process collectives (DCN stand-in on CPU devices)."""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.full  # heavy block: excluded from `pytest -m quick`

WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, os.getcwd())
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    import das_tpu  # noqa: F401  (env plumbing)
    from das_tpu.parallel.mesh import SHARD_AXIS, multihost_initialize
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    coordinator = sys.argv[1]
    pid = int(sys.argv[2])
    multihost_initialize(
        coordinator_address=coordinator, num_processes=2, process_id=pid
    )
    assert jax.process_count() == 2, jax.process_count()
    devices = jax.devices()          # global: 2 hosts x 2 cpu devices
    assert len(devices) == 4, devices
    mesh = Mesh(np.array(devices), (SHARD_AXIS,))

    # cross-host collective: every device contributes its shard's sum
    from das_tpu.parallel.mesh import shard_map  # version-compat shim
    x = jnp.arange(8, dtype=jnp.int32)
    fn = jax.jit(
        shard_map(
            lambda a: jax.lax.psum(a.sum(), SHARD_AXIS)[None],
            mesh=mesh,
            in_specs=P(SHARD_AXIS),
            out_specs=P(SHARD_AXIS),
        ),
        out_shardings=NamedSharding(mesh, P(SHARD_AXIS)),
    )
    with mesh:
        out = fn(jax.device_put(x, NamedSharding(mesh, P(SHARD_AXIS))))
    local = [np.asarray(s.data)[0] for s in out.addressable_shards]
    assert all(v == 28 for v in local), local  # full-mesh psum on each host

    # -- a REAL distributed query over the 2-process mesh ------------------
    # both processes hold identical host records (the reference's analogue:
    # every client sees the same Mongo/Redis state); the sharded store is
    # partitioned over the GLOBAL mesh, probes run slab-local on each
    # host's devices, and the fused program's join collectives + psum'd
    # stats cross the process boundary over DCN.  The count-only path is
    # multi-controller-safe: the stats vector is replicated, so every
    # process reads its own addressable copy — no cross-host fetch.
    from das_tpu.models.animals import animals_metta
    from das_tpu.parallel.fused_sharded import get_sharded_executor
    from das_tpu.parallel.sharded_db import ShardedDB
    from das_tpu.query import compiler as qc
    from das_tpu.query.ast import And, Link, PatternMatchingAnswer, Variable
    from das_tpu.storage.atom_table import load_metta_text

    data = load_metta_text(animals_metta())
    db = ShardedDB(data, mesh=mesh)
    query = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    plans = qc.plan_query(db, query)
    res = get_sharded_executor(db).execute(plans, count_only=True)
    assert res is not None and not res.reseed_needed
    host = PatternMatchingAnswer()
    query.matched(db, host)
    assert res.count == len(host.assignments), (res.count, len(host.assignments))
    print(f"proc {pid} query count {res.count} OK", flush=True)
    print(f"proc {pid} OK", flush=True)
""")


@pytest.mark.slow
def test_two_process_dcn_mesh(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    coordinator = f"127.0.0.1:{port}"
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), coordinator, str(pid)],
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            cwd=os.getcwd(), env=env, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=300)
            outs.append(out)
    finally:
        # a worker dying mid-collective leaves its peer blocked forever:
        # never leak the pair past a timeout
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out}"
        assert f"proc {pid} OK" in out
