"""Differential tests: native C++ canonical scanner vs the pure-Python loader.

The native scanner (native/src/das_native.cc, bound in
das_tpu/ingest/native.py) must produce record-identical AtomSpaceData —
same handles, same composite types, same symbol tables — for every
canonical input the Python loader (das_tpu/ingest/canonical.py) accepts,
and report errors (with line numbers) for inputs it rejects.
"""

import hashlib
import os

import pytest

from das_tpu.ingest import native
from das_tpu.ingest.canonical import CanonicalFormatError, load_canonical_text

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native library unavailable"
)

NESTED = """(: Evaluation Type)
(: Predicate Type)
(: Reactome Type)
(: Concept Type)
(: "Predicate:has_name" Predicate)
(: "Reactome:R-HSA-164843" Reactome)
(: "Concept:2-LTR circle formation" Concept)
(Evaluation "Predicate Predicate:has_name" (Evaluation "Predicate Predicate:has_name" "Reactome Reactome:R-HSA-164843"))
(Evaluation "Predicate Predicate:has_name" "Concept Concept:2-LTR circle formation")
"""


def generated_corpus() -> str:
    lines = [
        "(: Member Type)",
        "(: Interacts Type)",
        "(: List Type)",
        "(: Gene Type)",
        "(: Proc Type)",
    ]
    genes = [f"G{i} alpha" for i in range(120)]
    procs = [f"P{i}" for i in range(30)]
    lines += [f'(: "{g}" Gene)' for g in genes]
    lines += [f'(: "{p}" Proc)' for p in procs]
    for i, g in enumerate(genes):
        p = procs[i % len(procs)]
        lines.append(f'(Member "Gene {g}" "Proc {p}")')
        if i % 3 == 0:
            g2 = genes[(i * 7 + 1) % len(genes)]
            lines.append(f'(Interacts "Gene {g}" (List "Gene {g2}" "Proc {p}"))')
    return "\n".join(lines) + "\n"


def assert_identical(d_py, d_nat):
    assert list(d_py.nodes) == list(d_nat.nodes)
    assert list(d_py.links) == list(d_nat.links)
    assert list(d_py.typedefs) == list(d_nat.typedefs)
    for h in d_py.links:
        a, b = d_py.links[h], d_nat.links[h]
        assert a.named_type == b.named_type
        assert a.named_type_hash == b.named_type_hash
        assert a.composite_type == b.composite_type
        assert a.composite_type_hash == b.composite_type_hash
        assert a.elements == b.elements
        assert a.is_toplevel == b.is_toplevel
    for h in d_py.nodes:
        a, b = d_py.nodes[h], d_nat.nodes[h]
        assert (a.name, a.named_type, a.named_type_hash) == (
            b.name,
            b.named_type,
            b.named_type_hash,
        )
    for h in d_py.typedefs:
        a, b = d_py.typedefs[h], d_nat.typedefs[h]
        assert (a.name, a.name_hash, a.composite_type_hash) == (
            b.name,
            b.name_hash,
            b.composite_type_hash,
        )
    assert d_py.table.named_type_hash == d_nat.table.named_type_hash
    assert d_py.table.named_types == d_nat.table.named_types
    assert d_py.table.parent_type == d_nat.table.parent_type
    assert d_py.table.symbol_hash == d_nat.table.symbol_hash
    assert d_py.table.terminal_hash == d_nat.table.terminal_hash


def test_md5_parity():
    for s in [b"", b"a", b"Concept human", b"x" * 55, b"y" * 56, b"z" * 64, b"w" * 1000]:
        assert native.native_md5_hex(s) == hashlib.md5(s).hexdigest()


def test_nested_differential():
    assert_identical(load_canonical_text(NESTED), native.load_canonical_text_native(NESTED))


def test_generated_corpus_differential():
    text = generated_corpus()
    assert_identical(load_canonical_text(text), native.load_canonical_text_native(text))


def test_multi_file_threaded(tmp_path):
    text = generated_corpus()
    pa, pb = tmp_path / "a.metta", tmp_path / "b.metta"
    pa.write_text(text)
    pb.write_text(NESTED)
    d_nat = native.load_canonical_files_native([str(pa), str(pb)], n_threads=2)
    d_py = load_canonical_text(text)
    load_canonical_text(NESTED, d_py)
    assert_identical(d_py, d_nat)


def test_error_reporting():
    bad = "(: A Type)\n(: \"A a\" A)\n(Member \"A a\"\n"
    with pytest.raises(native.NativeParseError) as ei:
        native.load_canonical_text_native(bad)
    assert "line 3" in str(ei.value)
    with pytest.raises(CanonicalFormatError):
        load_canonical_text(bad)


def test_api_uses_native(tmp_path):
    from das_tpu.api.atomspace import DistributedAtomSpace

    path = tmp_path / "kb.metta"
    path.write_text(NESTED)
    das = DistributedAtomSpace(backend="memory")
    das.load_canonical_knowledge_base(str(path))
    assert das.count_atoms() == (3, 3)


def test_env_gate(monkeypatch, tmp_path):
    """DAS_TPU_NO_NATIVE forces the Python scanner (fresh module state)."""
    import importlib

    import das_tpu.ingest.native as native_mod

    monkeypatch.setenv("DAS_TPU_NO_NATIVE", "1")
    fresh = importlib.reload(native_mod)
    try:
        assert not fresh.native_available()
    finally:
        monkeypatch.delenv("DAS_TPU_NO_NATIVE")
        importlib.reload(native_mod)


def test_multi_file_python_fallback_state_reset(tmp_path):
    """Two complete canonical files through the production Python-fallback
    path (shared CanonicalLoader) must load like the native path: the
    three-state scanner resets per file (reference canonical_parser.py:324)."""
    from das_tpu.ingest.canonical import CanonicalLoader

    text = generated_corpus()
    pa, pb = tmp_path / "a.metta", tmp_path / "b.metta"
    pa.write_text(text)
    pb.write_text(NESTED)
    loader = CanonicalLoader()
    loader.parse_file(str(pa))
    loader.parse_file(str(pb))  # would raise before the per-file reset fix
    d_nat = native.load_canonical_files_native([str(pa), str(pb)], n_threads=2)
    assert_identical(loader.data, d_nat)


def _handle_set(data):
    fin = data.finalize()
    return set(fin.hex_of_row)


def test_bio_canonical_writer_reproduces_builder(tmp_path):
    """write_bio_canonical streams the exact KB build_bio_atomspace
    constructs: identical counts and identical handle sets after loading
    the file through BOTH scanners."""
    from das_tpu.ingest.canonical import load_canonical_file
    from das_tpu.models.bio import build_bio_atomspace, write_bio_canonical
    from das_tpu.storage.atom_table import AtomSpaceData

    cfg = dict(n_genes=120, n_processes=30, members_per_gene=4,
               n_interactions=80, n_evaluations=50, seed=11)
    built, _, _ = build_bio_atomspace(**cfg)
    path = str(tmp_path / "bio.metta")
    write_bio_canonical(path, **cfg)

    py_data = load_canonical_file(path)
    assert py_data.count_atoms() == built.count_atoms()
    assert _handle_set(py_data) == _handle_set(built)

    nat_data = AtomSpaceData()
    native.load_canonical_files_native([path], nat_data)
    assert nat_data.count_atoms() == built.count_atoms()
    assert _handle_set(nat_data) == _handle_set(built)


@pytest.mark.slow
@pytest.mark.full
def test_native_scanner_million_expressions(tmp_path):
    """>=1M-expression canonical file through the native scanner (VERDICT
    r02 item 4): counts match the pure-Python loader on the same file."""
    from das_tpu.ingest.canonical import load_canonical_file
    from das_tpu.models.bio import write_bio_canonical
    from das_tpu.storage.atom_table import AtomSpaceData

    cfg = dict(n_genes=100_000, n_processes=5_000, members_per_gene=8,
               n_interactions=120_000, n_evaluations=30_000, seed=3)
    path = str(tmp_path / "million.metta")
    lines = write_bio_canonical(path, **cfg)
    assert lines >= 1_000_000

    nat_data = AtomSpaceData()
    native.load_canonical_files_native([path], nat_data)
    nodes, links = nat_data.count_atoms()
    assert nodes == 100_000 + 5_000 + 1
    assert links >= 1_000_000  # dedup removes repeated random draws only

    py_data = load_canonical_file(path)
    assert py_data.count_atoms() == (nodes, links)


def test_bio_skewed_writer_reproduces_builder(tmp_path):
    """The skew>0 power-law profile must keep the builder and the
    canonical writer on the same rng sequence: identical handle sets."""
    from das_tpu.ingest.canonical import load_canonical_file
    from das_tpu.models.bio import build_bio_atomspace, write_bio_canonical

    cfg = dict(n_genes=150, n_processes=40, members_per_gene=4,
               n_interactions=100, n_evaluations=30, seed=13, skew=1.2)
    built, _, _ = build_bio_atomspace(**cfg)
    path = str(tmp_path / "bio_skew.metta")
    write_bio_canonical(path, **cfg)
    py_data = load_canonical_file(path)
    assert py_data.count_atoms() == built.count_atoms()
    assert _handle_set(py_data) == _handle_set(built)
