"""Precomputed-report column matching + multiprocess paren-balanced parse
(the two converter capabilities VERDICT r1 #6 flagged as dropped)."""

import os
import textwrap

import pytest

from das_tpu.convert.chunked import (
    parse_multiprocess,
    parse_sexpr_trees,
    split_balanced,
)
from das_tpu.convert.flybase import FlybaseConverter
from das_tpu.convert.precomputed import PrecomputedTables, normalize_value

SQL_DUMP = textwrap.dedent("""\
    CREATE TABLE public.gene (
        gene_id integer,
        uniquename character varying(255),
        symbol character varying(255)
    );
    CREATE TABLE public.organism (
        organism_id integer,
        genus character varying(255)
    );
    ALTER TABLE ONLY public.gene
        ADD CONSTRAINT gene_pkey PRIMARY KEY (gene_id);
    ALTER TABLE ONLY public.organism
        ADD CONSTRAINT organism_pkey PRIMARY KEY (organism_id);
    COPY public.gene (gene_id, uniquename, symbol) FROM stdin;
    1\tFBgn0000001\tw
    2\tFBgn0000002\tcn
    3\tFBgn0000003\tvg
    4\tFBgn0000004\tsd
    5\tFBgn0000005\tdpp
    \\.
    COPY public.organism (organism_id, genus) FROM stdin;
    1\tDrosophila
    2\tHomo
    \\.
""")

REPORT_TSV = textwrap.dedent("""\
    ## FlyBase report
    #gene_fbid\tgene_symbol
    #-----------------------
    FLYBASE:FBgn0000001\tw
    FLYBASE:FBgn0000002\tcn
    FLYBASE:FBgn0000003\tvg
    FLYBASE:FBgn0000004\tsd
    FLYBASE:FBgn0000005\tdpp
""")


def test_normalize_value_strips_flybase_prefix():
    assert normalize_value("FLYBASE:FBgn0000001") == "FBgn0000001"
    assert normalize_value(" FBgn0012345 ") == "FBgn0012345"
    assert normalize_value("plain") == "plain"


@pytest.fixture()
def release(tmp_path):
    sql = tmp_path / "dump.sql"
    sql.write_text(SQL_DUMP)
    pre = tmp_path / "precomputed"
    pre.mkdir()
    (pre / "genes_report.tsv").write_text(REPORT_TSV)
    out = tmp_path / "out"
    return str(sql), str(pre), str(out)


def test_value_coverage_discovers_mapping(release):
    sql, pre, out = release
    conv = FlybaseConverter(sql, out, precomputed_dir=pre)
    conv.discover_relevant_tables()
    table = conv.precomputed.tables["genes_report.tsv"]
    assert table.mapping["gene_fbid"] == ("gene", "uniquename")
    assert table.mapping["gene_symbol"] == ("gene", "symbol")
    assert table.all_mapped()
    # relevance: only the matched SQL table is selected — organism is not
    assert conv.tables == {"gene"}
    # persisted in the reference mapping.txt format
    mapping = open(os.path.join(pre, "mapping.txt")).read()
    assert "genes_report.tsv\tgene_fbid\tgene\tuniquename" in mapping


def test_mapping_preload_skips_rediscovery(release):
    sql, pre, out = release
    FlybaseConverter(sql, out, precomputed_dir=pre).discover_relevant_tables()
    # second converter must preload mapping.txt (no discovery pass)
    conv2 = FlybaseConverter(sql, out, precomputed_dir=pre)
    conv2.discover_relevant_tables()
    assert conv2.precomputed.preloaded
    assert conv2.tables == {"gene"}


def test_end_to_end_conversion_with_precomputed(release):
    """Both ways: raw dump + reports -> relevant tables -> MeTTa files the
    canonical loader round-trips into a queryable atomspace."""
    sql, pre, out = release
    stats = FlybaseConverter(sql, out, precomputed_dir=pre).run()
    assert stats["rows"] == 5  # gene rows only; organism filtered out
    import glob

    text = "".join(open(p).read() for p in sorted(glob.glob(out + "/*.metta")))
    assert '(: "gene:1" Concept)' in text
    assert "(Inheritance" in text and "(Execution" in text
    assert "organism" not in text

    from das_tpu.api.atomspace import DistributedAtomSpace

    das = DistributedAtomSpace(backend="memory")
    for p in sorted(glob.glob(out + "/*.metta")):
        das.load_knowledge_base(p)
    nodes, links = das.count_atoms()
    assert nodes >= 5 and links >= 10
    assert das.get_node("Concept", "gene:1")


def test_no_near_match_below_threshold_refuses_unfiltered(tmp_path):
    sql = tmp_path / "dump.sql"
    sql.write_text(SQL_DUMP)
    pre = tmp_path / "precomputed"
    pre.mkdir()
    # only 2 of 5 values exist in the dump: 40% < 90% threshold
    (pre / "weak.tsv").write_text(
        "#a\n#----\nFBgn0000001\nFBgn0000002\nFBgn9999991\nFBgn9999992\nFBgn9999993\n"
    )
    conv = FlybaseConverter(str(sql), str(tmp_path / "o"), precomputed_dir=str(pre))
    # refusing to convert the whole dump unfiltered is the contract
    with pytest.raises(ValueError, match="matched no SQL tables"):
        conv.discover_relevant_tables()
    assert not conv.precomputed.tables["weak.tsv"].mapping
    # the failed run must NOT poison later runs: its empty mapping.txt is
    # ignored and discovery re-runs from the report files
    conv2 = FlybaseConverter(str(sql), str(tmp_path / "o"), precomputed_dir=str(pre))
    with pytest.raises(ValueError, match="matched no SQL tables"):
        conv2.discover_relevant_tables()
    assert not conv2.precomputed.preloaded


# -- paren-balanced multiprocess parsing ------------------------------------

SCM = "\n".join(
    [
        '(ConceptNode "n%d")' % i if i % 3 else
        '(InheritanceLink\n  (ConceptNode "a%d")\n  (ConceptNode "b (tricky)")\n)' % i
        for i in range(100)
    ]
)


def test_split_balanced_boundaries():
    chunks = list(split_balanced(SCM, chunk_exprs=7))
    assert len(chunks) > 2
    # every chunk independently balanced
    from das_tpu.convert.chunked import paren_delta

    for c in chunks:
        assert sum(paren_delta(line) for line in c.split("\n")) == 0
    # no expression lost or reordered
    rejoined = [t for c in chunks for t in parse_sexpr_trees(c)]
    assert rejoined == parse_sexpr_trees(SCM)


def test_parse_multiprocess_matches_serial():
    serial = parse_sexpr_trees(SCM)
    parallel = parse_multiprocess(SCM, processes=4, chunk_exprs=9)
    assert parallel == serial
    assert len(serial) == 100


def test_split_balanced_rejects_unbalanced():
    with pytest.raises(ValueError):
        list(split_balanced("(a (b)", chunk_exprs=1))


def test_comments_and_tricky_strings():
    """';' comments (incl. ones containing parens) and ';' inside quoted
    names must parse identically to the serial atomese parser."""
    from das_tpu.convert.atomese2metta import parse_sexpr

    scm = "\n".join([
        "; header comment (with parens",
        '(ConceptNode "a;b")  ; trailing (note 1',
        "; another ) comment",
        '(InheritanceLink (ConceptNode "x") (ConceptNode "y"))',
    ])
    serial = parse_sexpr(scm)
    assert parse_sexpr_trees(scm) == serial
    assert parse_multiprocess(scm, processes=2, chunk_exprs=1) == serial
    assert serial[0] == ["ConceptNode", '"a;b"']


def test_multiline_string_spanning_chunk_lines():
    """Quoted strings may contain newlines and parens; the balance scanner
    must carry in-string state across lines (serial-parser parity)."""
    from das_tpu.convert.atomese2metta import parse_sexpr

    scm = '(ConceptNode "foo\nbar)")\n(ConceptNode "ok")'
    serial = parse_sexpr(scm)
    assert parse_multiprocess(scm, processes=2, chunk_exprs=1) == serial
    chunks = list(split_balanced(scm, chunk_exprs=1))
    assert len(chunks) == 2  # the multi-line string stays in one chunk


def test_translate_text_multiprocess_equivalent():
    from das_tpu.convert.atomese2metta import translate_text

    scm = "\n".join(
        f'(InheritanceLink (ConceptNode "a{i}") (ConceptNode "b{i}"))'
        for i in range(40)
    )
    assert translate_text(scm, processes=3) == translate_text(scm)


NASTY_DUMP = r'''--
-- Realistic pg_dump shape: constraints arrive AFTER the data, quoted
-- identifiers, composite PKs, a no-PK table, \N NULLs, numeric sizes.
--
CREATE TABLE public.gene (
    gene_id integer NOT NULL,
    "Name" character varying(255),
    score numeric(10,2),
    organism_id integer
);

CREATE TABLE public."order" (
    "order_id" integer NOT NULL,
    label text
);

CREATE TABLE public.gene_synonym (
    gene_id integer NOT NULL,
    synonym_id integer NOT NULL,
    note text
);

CREATE TABLE public.scratch (
    junk text
);

COPY public.gene (gene_id, "Name", score, organism_id) FROM stdin;
1	alpha	1.50	7
2	\N	\N	7
3	gamma	2.25	\N
\.

COPY public."order" ("order_id", label) FROM stdin;
10	first
11	\N
\.

COPY public.gene_synonym (gene_id, synonym_id, note) FROM stdin;
1	100	primary
1	101	\N
2	100	alt
\N	102	broken
\.

COPY public.scratch (junk) FROM stdin;
garbage
\.

ALTER TABLE ONLY public.gene
    ADD CONSTRAINT gene_pkey PRIMARY KEY (gene_id);

ALTER TABLE ONLY public."order" ADD CONSTRAINT order_pkey PRIMARY KEY ("order_id");

ALTER TABLE ONLY public.gene_synonym
    ADD CONSTRAINT gene_synonym_pkey PRIMARY KEY (gene_id, synonym_id);

ALTER TABLE ONLY public.gene_synonym
    ADD CONSTRAINT gene_synonym_gene_fkey FOREIGN KEY (gene_id) REFERENCES public.gene(gene_id);
'''


def test_nasty_dump_constraints_after_data(tmp_path):
    """Real pg_dump ordering: every PK/FK lands after the COPY blocks.
    Rows must still get PK identities and FK columns must still resolve
    to Concept references (a single-pass reader would see no keys at
    all)."""
    sql = tmp_path / "nasty.sql"
    sql.write_text(NASTY_DUMP)
    out = tmp_path / "out"
    stats = FlybaseConverter(str(sql), str(out)).run()
    import glob

    text = "".join(open(p).read() for p in sorted(glob.glob(str(out) + "/*.metta")))

    # gene rows keyed by the ALTER-added pk
    assert '(: "gene:1" Concept)' in text
    assert '(: "gene:3" Concept)' in text
    # \N values skipped but the row survives (gene 2 has only organism_id)
    assert '(Execution (Schema "gene.organism_id") "gene:2" "gene:7")' not in text
    # quoted identifiers: table "order", column "Name" resolve unquoted
    assert '(: "order:10" Concept)' in text
    assert '"gene.Name"' in text
    # numeric sizes recognized -> Number node for score
    assert '(: "1.50" Number)' in text
    # composite PK: compound ':'-joined identity, pk columns not re-emitted
    assert '(: "gene_synonym:1:100" Concept)' in text
    assert '(: "gene_synonym:2:100" Concept)' in text
    # NULL in any pk component drops the row
    assert "gene_synonym:\\N" not in text and ":102" not in text
    # no-PK table discarded (reference sql_reader.py:589-592 parity)
    assert "scratch" not in text
    assert stats["discarded_tables"] == 1
    # composite-PK FK columns are pk members -> not re-emitted as values;
    # the non-pk note column is
    assert '(Execution (Schema "gene_synonym.note") "gene_synonym:1:100" "primary")' in text


def test_nasty_dump_fk_resolution_after_data(tmp_path):
    """An FK declared after the data still turns the referencing column
    into a Concept reference, not a Number."""
    sql = tmp_path / "fk.sql"
    sql.write_text(r'''CREATE TABLE public.organism (
    organism_id integer NOT NULL,
    genus text
);
CREATE TABLE public.gene (
    gene_id integer NOT NULL,
    organism_id integer
);
COPY public.organism (organism_id, genus) FROM stdin;
7	Drosophila
\.
COPY public.gene (gene_id, organism_id) FROM stdin;
1	7
\.
ALTER TABLE ONLY public.organism ADD CONSTRAINT o_pkey PRIMARY KEY (organism_id);
ALTER TABLE ONLY public.gene ADD CONSTRAINT g_pkey PRIMARY KEY (gene_id);
ALTER TABLE ONLY public.gene
    ADD CONSTRAINT g_fkey FOREIGN KEY (organism_id) REFERENCES public.organism(organism_id);
''')
    out = tmp_path / "out"
    FlybaseConverter(str(sql), str(out)).run()
    import glob

    text = "".join(open(p).read() for p in sorted(glob.glob(str(out) + "/*.metta")))
    # FK column resolves to the referenced row's Concept node, not Number
    assert '(Execution (Schema "gene.organism_id") "gene:1" "organism:7")' in text
    assert '(: "organism:7" Concept)' in text


def test_multiline_constraint_clause(tmp_path):
    """A PRIMARY KEY column list broken across continuation lines still
    parses (a dropped PK would silently discard the whole table)."""
    sql = tmp_path / "ml.sql"
    sql.write_text(
        "CREATE TABLE public.pair (\n"
        "    a integer NOT NULL,\n"
        "    b integer NOT NULL,\n"
        "    note text\n"
        ");\n"
        "COPY public.pair (a, b, note) FROM stdin;\n"
        "1\t2\thello\n"
        "\\.\n"
        "ALTER TABLE ONLY public.pair\n"
        "    ADD CONSTRAINT pair_pkey PRIMARY KEY (a,\n"
        "    b);\n"
    )
    out = tmp_path / "out"
    stats = FlybaseConverter(str(sql), str(out)).run()
    assert stats["discarded_tables"] == 0
    import glob

    text = "".join(open(p).read() for p in sorted(glob.glob(str(out) + "/*.metta")))
    assert '(: "pair:1:2" Concept)' in text
    assert '(Execution (Schema "pair.note") "pair:1:2" "hello")' in text


def test_inline_primary_key_and_composite_fk(tmp_path):
    """Hand-written SQL with a table-level PRIMARY KEY inside CREATE TABLE
    still converts, and a composite FK references the target's COMPOUND
    row identity instead of emitting per-column dangling Concepts."""
    sql = tmp_path / "inline.sql"
    sql.write_text(r'''CREATE TABLE public.pair (
    a integer NOT NULL,
    b integer NOT NULL,
    note text,
    PRIMARY KEY (a, b)
);
CREATE TABLE public.child (
    child_id integer NOT NULL,
    a integer,
    b integer,
    PRIMARY KEY (child_id)
);
COPY public.pair (a, b, note) FROM stdin;
1	2	hello
\.
COPY public.child (child_id, a, b) FROM stdin;
9	1	2
\.
ALTER TABLE ONLY public.child
    ADD CONSTRAINT child_fkey FOREIGN KEY (a, b) REFERENCES public.pair(a, b);
''')
    out = tmp_path / "out"
    stats = FlybaseConverter(str(sql), str(out)).run()
    assert stats["discarded_tables"] == 0
    import glob

    text = "".join(open(p).read() for p in sorted(glob.glob(str(out) + "/*.metta")))
    # inline PK parsed -> rows exist
    assert '(: "pair:1:2" Concept)' in text
    assert '(: "child:9" Concept)' in text
    # composite FK -> ONE compound reference to the real row node
    assert '(Execution (Schema "child.a:b") "child:9" "pair:1:2")' in text
    # no dangling per-column refs
    assert '"pair:1"' not in text.replace('"pair:1:2"', "")
    assert '(Execution (Schema "child.a")' not in text
