"""Sharded-store checkpoint + shard-local restore (VERDICT r03 item 8):
a ShardedDB round-trips through storage/checkpoint.py without a
host-global re-partition, on the 8-virtual-device CPU mesh, and the
restored store still takes incremental commits."""

import numpy as np
import pytest

from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.parallel.sharded_db import ShardedDB
from das_tpu.query.ast import And, Link, Node, PatternMatchingAnswer, Variable
from das_tpu.storage import checkpoint
from das_tpu.storage.atom_table import load_metta_text


def _query():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


@pytest.fixture(scope="module")
def saved(tmp_path_factory):
    data, _, _ = build_bio_atomspace(
        n_genes=80, n_processes=8, members_per_gene=4,
        n_interactions=60, n_evaluations=15,
    )
    db = ShardedDB(data, DasConfig())
    path = str(tmp_path_factory.mktemp("ckpt") / "sharded")
    checkpoint.save_sharded(db, path)
    a = PatternMatchingAnswer()
    db.query_sharded(_query(), a)
    return path, db, a.assignments


def _restore(path):
    data = checkpoint.load(path)
    cfg = DasConfig(checkpoint_path=path)
    return ShardedDB(data, cfg)


def test_restore_is_shard_local_and_answer_identical(saved):
    path, db, expected = saved
    db2 = _restore(path)
    assert db2.tables.restored, "restore must take the slab path, not rebuild"
    assert db2.tables.n_shards == db.tables.n_shards
    for arity, b in db.tables.buckets.items():
        b2 = db2.tables.buckets[arity]
        assert b2.m_local == b.m_local and b2.size == b.size
        assert np.array_equal(b2.slab_sizes, b.slab_sizes)
        assert np.array_equal(np.asarray(b2.targets), np.asarray(b.targets))
        assert np.array_equal(np.asarray(b2.key_type), np.asarray(b.key_type))
        for p in range(arity):
            assert np.array_equal(
                np.asarray(b2.key_type_pos[p]), np.asarray(b.key_type_pos[p])
            )
    a = PatternMatchingAnswer()
    db2.query_sharded(_query(), a)
    assert a.assignments == expected and expected


def test_post_restore_incremental_commit(saved):
    path, _db, _expected = saved
    db2 = _restore(path)
    assert db2.tables.restored
    tables_before = db2.tables
    commit = "\n".join(
        ['(: "CKG_%d" Gene)' % i for i in range(4)]
        + ['(Interacts "CKG_%d" "CKG_%d")' % (i, (i + 1) % 4) for i in range(4)]
    )
    load_metta_text(commit, db2.data)
    db2.refresh()
    # the commit must extend the restored slabs, not re-partition
    assert db2.tables is tables_before, "commit fell back to a full rebuild"
    q = And([Link("Interacts", [Node("Gene", "CKG_0"), Variable("V")], True)])
    a = PatternMatchingAnswer()
    db2.query_sharded(q, a)
    assert len(a.assignments) == 1


def test_stale_checkpoint_falls_back_to_rebuild(saved, tmp_path):
    path, db, _expected = saved
    # records move on (new atoms) but the slab npz stays: restore must
    # detect the count mismatch and re-partition
    data = checkpoint.load(path)
    load_metta_text(
        '(: "STALE_G" Gene)\n(Interacts "STALE_G" "STALE_G")', data
    )
    cfg = DasConfig(checkpoint_path=path)
    db2 = ShardedDB(data, cfg)
    assert not db2.tables.restored
    # wrong mesh-size file name: also a clean rebuild
    import os

    other = str(tmp_path / "othermesh")
    os.makedirs(other, exist_ok=True)
    checkpoint.save(db.data, other)
    data3 = checkpoint.load(other)
    db3 = ShardedDB(data3, DasConfig(checkpoint_path=other))
    assert not db3.tables.restored


def test_api_save_checkpoint_routes_sharded(saved, tmp_path):
    from das_tpu.api.atomspace import DistributedAtomSpace

    path, db, _expected = saved
    das = DistributedAtomSpace(database_name="ck", db=db)
    out = str(tmp_path / "api_ckpt")
    das.save_checkpoint(out)
    import os

    assert os.path.exists(
        os.path.join(out, checkpoint.SHARDED_FILE_FMT.format(db.tables.n_shards))
    )
