"""Incremental device updates: a transaction commit must take the delta
path (no full re-finalize/re-upload), leave every probe surface — wildcard
patterns, templates, type scans, compiled conjunctions, incoming sets —
immediately consistent, and merge LSM-style past the threshold.

Role of the reference update battery (das/das_update_test.py:141-192),
which commits new expressions and re-checks patterns/templates include
them."""

import pytest

from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.core.schema import WILDCARD
from das_tpu.models.animals import animals_metta
from das_tpu.query.ast import And, Link, Node, PatternMatchingAnswer, Variable
from das_tpu.storage.tensor_db import TensorDB

def _committed_das(backend, config=None):
    das = DistributedAtomSpace(backend=backend, config=config)
    das.load_metta_text(animals_metta())
    tx = das.open_transaction()
    tx.add('(: "lion" Concept)')
    tx.add('(: "tiger" Concept)')
    tx.add('(Inheritance "lion" "mammal")')
    tx.add('(Inheritance "tiger" "mammal")')
    tx.add('(Similarity "lion" "tiger")')
    tx.add('(Similarity "tiger" "lion")')
    das.commit_transaction(tx)
    return das


def test_commit_takes_incremental_path():
    das = _committed_das("tensor")
    db = das.db
    assert db._delta_total == 6  # 2 nodes + 4 links, no full rebuild
    assert das.count_atoms() == (16, 30)


def test_incremental_probes_see_new_atoms():
    das = _committed_das("tensor")
    db = das.db
    lion = db.get_node_handle("Concept", "lion")
    mammal = db.get_node_handle("Concept", "mammal")

    # grounded existence
    assert db.link_exists("Inheritance", [lion, mammal])
    # wildcard pattern probe (the patterns namespace)
    matches = db.get_matched_links("Inheritance", [WILDCARD, mammal])
    handles = {h for h, _ in matches}
    assert len(matches) == 6  # human/monkey/chimp/rhino + lion + tiger
    assert db.get_link_handle("Inheritance", [lion, mammal]) in handles
    # template probe (the templates namespace)
    tmpl = db.get_matched_type_template(["Inheritance", "Concept", "Concept"])
    assert len(tmpl) == 14  # 12 base + 2 new
    # type scan
    assert len(db.get_matched_type("Similarity")) == 16
    # incoming set includes the delta links
    incoming = db.get_incoming(lion)
    assert len(incoming) == 3  # Inheritance + 2 Similarity


def test_incremental_compiled_query_parity():
    das = _committed_das("tensor")
    # fresh build over the same data = ground truth
    fresh = TensorDB(das.data)
    q = And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Similarity", [Variable("V1"), Variable("V2")], False),
    ])
    got_matched, got = das.query_answer(q)
    want = PatternMatchingAnswer()
    want_matched = q.matched(fresh, want)
    assert bool(got_matched) == bool(want_matched)
    assert got.assignments == want.assignments
    # lion/tiger must actually appear in the answers
    def handles_of(a):
        if hasattr(a, "mapping"):
            return list(a.mapping.values())
        out = list((a.ordered_mapping.mapping if a.ordered_mapping else {}).values())
        for u in a.unordered_mappings:
            out.extend(u.values)
        return out

    names = {
        das.db.get_node_name(h)
        for a in got.assignments
        for h in handles_of(a)
        if h in das.data.nodes
    }
    assert {"lion", "tiger"} <= names


def test_multiple_commits_accumulate():
    das = _committed_das("tensor")
    tx = das.open_transaction()
    tx.add('(: "bear" Concept)')
    tx.add('(Inheritance "bear" "mammal")')
    das.commit_transaction(tx)
    db = das.db
    assert db._delta_total == 8  # 6 + (1 node + 1 link)
    matches = db.get_matched_links("Inheritance", [WILDCARD, db.get_node_handle("Concept", "mammal")])
    assert len(matches) == 7


def test_threshold_forces_full_merge():
    cfg = DasConfig(delta_merge_threshold=4)
    das = _committed_das("tensor", config=cfg)  # delta of 6 > 4 -> merge
    db = das.db
    assert db._delta_total == 0  # fully re-finalized
    assert not db._host_delta
    matches = db.get_matched_links(
        "Inheritance", [WILDCARD, db.get_node_handle("Concept", "mammal")]
    )
    assert len(matches) == 6


def test_new_arity_bucket_via_commit():
    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    tx = das.open_transaction()
    tx.add("(: List Type)")
    tx.add('(List "human" "monkey" "chimp")')
    das.commit_transaction(tx)
    db = das.db
    human = db.get_node_handle("Concept", "human")
    matches = db.get_matched_links("List", [human, WILDCARD, WILDCARD])
    assert len(matches) == 1


def test_sharded_backend_sees_commit():
    das = _committed_das("sharded")
    db = das.db
    lion = das.get_node("Concept", "lion")
    assert lion is not None
    q = Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    matched, answer = das.query_answer(q)
    assert matched
    names = {
        das.db.get_node_name(h)
        for a in answer.assignments
        for h in a.mapping.values()
        if h in das.data.nodes
    }
    assert "lion" in names and "tiger" in names


def test_sharded_commit_takes_incremental_path():
    das = _committed_das("sharded")
    db = das.db
    # delta merge, not a re-partition (6 = 2 nodes + 4 links; fixed slab
    # capacities bound memory structurally, so the charge is real atoms)
    assert db._delta_total == 6
    # the device tables grew in place: Inheritance arity-2 bucket holds
    # base 26-row slab stack + the 4 delta links
    assert db.tables.buckets[2].size == 30
    # incoming overlay (no CSR rebuild happened)
    lion = db.get_node_handle("Concept", "lion")
    assert len(db.get_incoming(lion)) == 3  # Inheritance + 2 Similarity


def test_sharded_incremental_device_query_parity():
    """After a delta merge, the SHARDED device pipeline (fused + staged)
    must answer identically to a freshly partitioned store."""
    from das_tpu.parallel.sharded_db import ShardedDB

    das = _committed_das("sharded")
    db = das.db
    fresh = ShardedDB(das.data, config=db.config, mesh=db.mesh)
    assert fresh._delta_total == 0  # fresh partition = ground truth
    queries = [
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        And([
            Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
            Link("Similarity", [Variable("V1"), Variable("V2")], True),
        ]),
        And([
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        ]),
    ]
    for q in queries:
        got = PatternMatchingAnswer()
        want = PatternMatchingAnswer()
        got_m = db.query_sharded(q, got)
        want_m = fresh.query_sharded(q, want)
        assert got_m is not None and want_m is not None  # device path ran
        assert bool(got_m) == bool(want_m)
        assert got.assignments == want.assignments


def test_sharded_staged_pipeline_on_delta_store():
    """The per-stage sharded pipeline probes the merged slab indexes."""
    das = _committed_das("sharded")
    db = das.db
    from das_tpu.query import compiler as qc

    q = And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V2"), Node("Concept", "mammal")], True),
    ])
    plans = qc.plan_query(db, q)
    assert plans is not None
    table = db.sharded_execute(plans)
    answer = PatternMatchingAnswer()
    assert db.materialize(table, answer)
    host = PatternMatchingAnswer()
    q.matched(db, host)
    assert answer.assignments == host.assignments


def test_sharded_new_arity_bucket_via_commit():
    das = DistributedAtomSpace(backend="sharded")
    das.load_metta_text(animals_metta())
    tx = das.open_transaction()
    tx.add("(: List Type)")
    tx.add('(List "human" "monkey" "chimp")')
    das.commit_transaction(tx)
    db = das.db
    # 1 new link (the typedef is neither node nor link): incremental, and
    # the arity-3 bucket is born from the delta
    assert db._delta_total == 1
    assert db.tables.buckets[3].size == 1
    human = db.get_node_handle("Concept", "human")
    matches = db.get_matched_links("List", [human, WILDCARD, WILDCARD])
    assert len(matches) == 1
    # the new bucket is probeable by the sharded device pipeline
    q = Link("List", [Variable("A"), Variable("B"), Variable("C")], True)
    answer = PatternMatchingAnswer()
    assert db.query_sharded(q, answer)
    assert len(answer.assignments) == 1


def test_sharded_multiple_commits_then_threshold_merge():
    cfg = DasConfig(delta_merge_threshold=7)
    das = _committed_das("sharded", config=cfg)  # delta 6 <= 7: incremental
    db = das.db
    assert db._delta_total == 6
    tx = das.open_transaction()
    tx.add('(: "bear" Concept)')
    tx.add('(Inheritance "bear" "mammal")')
    das.commit_transaction(tx)
    db = das.db
    assert db._delta_total == 0  # 6 + 2 > 7 -> full re-partition
    mammal = db.get_node_handle("Concept", "mammal")
    matches = db.get_matched_links("Inheritance", [WILDCARD, mammal])
    assert len(matches) == 7


def test_dangling_target_resolution_forces_merge():
    """A commit supplying an atom that an existing link dangled on must
    full-rebuild (sentinel targets can't be retro-patched incrementally):
    probes grounded on the late-arriving atom then find the old link."""
    from das_tpu.core.expression import Expression
    from das_tpu.core.hashing import ExpressionHasher

    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    # "ghost" referenced before it exists -> sentinel target (the MeTTa
    # parser refuses undefined symbols, so build the record directly — the
    # canonical loader's partial-KB path produces exactly this shape)
    t = das.data.table
    inh = t.get_named_type_hash("Inheritance")
    concept = t.get_named_type_hash("Concept")
    human = ExpressionHasher.terminal_hash("Concept", "human")
    ghost = ExpressionHasher.terminal_hash("Concept", "ghost")
    elements = [human, ghost]
    das.data.add_link(Expression(
        toplevel=True,
        named_type="Inheritance",
        named_type_hash=inh,
        composite_type=[inh, concept, concept],
        composite_type_hash=ExpressionHasher.composite_hash([inh, concept, concept]),
        elements=elements,
        hash_code=ExpressionHasher.expression_hash(inh, elements),
    ))
    das._refresh()
    db = das.db
    assert db.fin.dangling_hexes  # the ghost terminal hash
    tx = das.open_transaction()
    tx.add('(: "ghost" Concept)')
    tx.add('(Inheritance "ghost" "mammal")')
    das.commit_transaction(tx)
    db = das.db
    assert db._delta_total == 0  # full rebuild, not incremental
    ghost = db.get_node_handle("Concept", "ghost")
    matches = db.get_matched_links("Inheritance", [WILDCARD, ghost])
    assert len(matches) == 1  # the once-dangling Inheritance(human, ghost)
    # incoming = element containment: the resolved link + the committed one
    assert len(db.get_incoming(ghost)) == 2


def test_shared_finalized_no_double_intern():
    """Two device backends over ONE AtomSpaceData (a ShardedDB plus its
    lazily-built tree-fallback TensorDB, or user-constructed back-to-back
    backends) may share a cached Finalized.  A commit processed by both
    backends' delta paths must intern each atom exactly once, and grounded
    probes on the committed atoms must keep answering on every backend.
    Regression: double-interning remapped row_of_hex to rows no device
    target references, silently answering 0."""
    from das_tpu.core.config import DasConfig
    from das_tpu.query.ast import Or

    # legacy replica mode: the scenario under test is the REPLICA adopting
    # the shared cached Finalized (the default mesh tree never builds one)
    das = DistributedAtomSpace(
        backend="sharded", config=DasConfig(sharded_tree_fallback="tensor")
    )
    das.load_metta_text(animals_metta())
    # unordered-link branch -> outside the branch-by-branch mesh subset,
    # so this lazily builds the tree-fallback TensorDB replica over the
    # SAME das.data
    q_or = Or([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Similarity", [Variable("V1"), Node("Concept", "human")], False),
    ])
    matched, answer = das.query_answer(q_or)
    assert matched and len(answer.assignments) == 7  # 4 mammals + 3 similar
    assert hasattr(das.db, "_tree_tensor_db"), "replica path must be used"
    base_rows = len(das.db.fin.hex_of_row)

    tx = das.open_transaction()
    tx.add('(: "lion" Concept)')
    tx.add('(Inheritance "lion" "mammal")')
    das.commit_transaction(tx)
    # second Or query refreshes the tree replica's own delta path
    matched, answer = das.query_answer(q_or)
    assert matched and len(answer.assignments) == 8  # + lion

    # exactly 2 new registry rows across ALL backends, no duplicates
    sharded_fin = das.db.fin
    tree_fin = das.db._tree_tensor_db.fin
    for fin in (sharded_fin, tree_fin):
        assert len(fin.hex_of_row) == len(set(fin.hex_of_row))
    assert len(sharded_fin.hex_of_row) == base_rows + 2

    # grounded device query on the committed atom: host truth everywhere
    q = Link("Inheritance", [Node("Concept", "lion"), Variable("V")], True)
    got = PatternMatchingAnswer()
    dev_matched = das.db.query_sharded(q, got)
    host = PatternMatchingAnswer()
    host_matched = q.matched(das.db, host)
    assert bool(dev_matched) == bool(host_matched)
    assert got.assignments == host.assignments
    assert len(got.assignments) == 1


def test_count_batch_sees_commit(monkeypatch):
    """Batched counting programs cache per plan shape; the bucket arrays
    must be call arguments, not baked closures — a cached batch entry
    created BEFORE a commit has to read the post-commit store.  (Baked
    closures also serialize the whole store into every compile payload:
    multi-GB at reference scale.)  The host single-term shortcut would
    answer this query without touching the device cache — disable it so
    the test keeps exercising the batched program."""
    from das_tpu.query import compiler
    from das_tpu.query.fused import get_executor

    monkeypatch.setenv("DAS_TPU_HOST_COUNT", "0")
    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    db = das.db
    q = Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    ex = get_executor(db)
    plans = [compiler.plan_query(db, q)]
    before = ex.count_batch(plans)
    assert before == [4]

    tx = das.open_transaction()
    tx.add('(: "lion" Concept)')
    tx.add('(Inheritance "lion" "mammal")')
    das.commit_transaction(tx)
    plans = [compiler.plan_query(das.db, q)]
    after = get_executor(das.db).count_batch(plans)
    assert after == [5], f"cached batch entry answered stale store: {after}"


def test_sharded_slab_exhaustion_compacts():
    """When a commit no longer fits the per-shard capacity slack, the
    backend performs an early LSM compaction (full re-partition) and the
    committed atoms remain immediately queryable."""
    das = DistributedAtomSpace(backend="sharded")
    das.load_metta_text(animals_metta())
    base_m = das.db.tables.buckets[2].m_local
    # 26 base links over 8 shards -> slab_sizes <= 4, m_local = 4+64 = 68;
    # one commit of > 8*64 links overflows every dcap class that fits
    tx = das.open_transaction()
    n = das.db.tables.n_shards * (base_m + 64)
    for i in range(n):
        tx.add(f'(: "z{i}" Concept)')
    for i in range(n):
        tx.add(f'(Inheritance "z{i}" "mammal")')
    das.commit_transaction(tx)
    db = das.db
    assert db._delta_total == 0  # compaction happened (state reset)
    mammal = db.get_node_handle("Concept", "mammal")
    matches = db.get_matched_links("Inheritance", [WILDCARD, mammal])
    assert len(matches) == 4 + n
    q = Link("Inheritance", [Node("Concept", "z0"), Variable("V")], True)
    answer = PatternMatchingAnswer()
    assert db.query_sharded(q, answer) and len(answer.assignments) == 1


def test_tensor_capacity_growth():
    """Commits that exhaust the tensor bucket's capacity slack trigger
    in-place growth (arrays re-padded to a larger class); sorted indexes
    and probes stay correct across the growth boundary."""
    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    cap0 = das.db.dev.buckets[2].capacity
    total = 0
    k = 0
    while das.db.dev.buckets[2].capacity == cap0:
        tx = das.open_transaction()
        for i in range(40):
            tx.add(f'(: "g{k}_{i}" Concept)')
        for i in range(40):
            tx.add(f'(Inheritance "g{k}_{i}" "mammal")')
        das.commit_transaction(tx)
        total += 40
        k += 1
        assert k < 20, "growth never triggered"
    db = das.db
    assert db.dev.buckets[2].size == 26 + total
    mammal = db.get_node_handle("Concept", "mammal")
    assert len(db.get_matched_links("Inheritance", [WILDCARD, mammal])) == 4 + total
    # compiled path across the growth boundary, vs fresh ground truth
    fresh = TensorDB(das.data)
    q = Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    from das_tpu.query import compiler

    got = PatternMatchingAnswer()
    want = PatternMatchingAnswer()
    assert compiler.query_on_device(db, q, got)
    assert compiler.query_on_device(fresh, q, want)
    assert got.assignments == want.assignments
