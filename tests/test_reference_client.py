"""Protobuf wire parity (VERDICT r02 item 2): the UNMODIFIED reference
CLI client (/root/reference/service/client.py:29-163) completes the full
ops/SERVICE.md walkthrough against the das_tpu server — create → load →
check → count=(14, 26) → atom/search incl. `af12f10f…` → query — over a
real gRPC channel with the reference's own protobuf messages.

The client subprocess resolves `das_pb2`/`das_pb2_grpc` from our
service_spec (protoc-built from the carried das.proto + hand-written
stubs), `das.*` from the compat shim, and `server` from the reference's
own directory (its module-level `os.environ['COUCHBASE_SETUP_DIR']` is
satisfied by env, not code changes).
"""

import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
REFERENCE_CLIENT = "/root/reference/service/client.py"
HUMAN = "af12f10f9ae2002a1607ba0b47ba8407"
MAMMAL = "bdfe4e7a431f73386f37c6448afe5840"


@pytest.fixture(scope="module")
def das_server():
    from das_tpu.service.server import serve

    server, service = serve(port=0, backend="tensor", block=False)
    yield server.bound_port
    server.stop(0)


def _client(port, *args, timeout=120):
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env.update(
        PYTHONPATH=(
            f"{REPO}/compat:{REPO}:{REPO}/das_tpu/service/service_spec"
        ),
        JAX_PLATFORMS="cpu",
        COUCHBASE_SETUP_DIR="/tmp",
    )
    proc = subprocess.run(
        [sys.executable, REFERENCE_CLIENT, "--port", str(port), *args],
        capture_output=True, text=True, timeout=timeout, env=env,
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    return proc.stdout.strip()


def test_reference_client_full_walkthrough(das_server):
    port = das_server
    token = _client(port, "create", "--new-das-name", "ref-client-das")
    assert len(token) == 20 and token.isalpha()

    out = _client(
        port, "load", "--das-key", token,
        "--url", f"file://{REPO}/data/samples/animals.metta",
    )
    assert "Load request submitted" in out

    for _ in range(60):
        status = _client(port, "check", "--das-key", token)
        if status == "Ready":
            break
        assert "Loading" in status or "Ready" in status, status
        time.sleep(1.0)
    assert status == "Ready"

    assert _client(port, "count", "--das-key", token) == "(14, 26)"

    assert _client(port, "atom", "--das-key", token, "--handle", HUMAN) == HUMAN
    atom_dict = _client(
        port, "atom", "--das-key", token, "--handle", HUMAN,
        "--output-format", "DICT",
    )
    assert "'type': 'Concept'" in atom_dict and "'name': 'human'" in atom_dict

    nodes = _client(
        port, "search_nodes", "--das-key", token,
        "--node-type", "Concept", "--node-name", "human",
    )
    assert nodes == f"['{HUMAN}']"

    links = _client(
        port, "search_links", "--das-key", token,
        "--link-type", "Similarity", "--targets", f"{HUMAN},*",
    )
    # production-DB semantics (redis_mongo_db.py:249-252): the unordered
    # probe hashes SORTED handles and matches stored order, so
    # Similarity [human, *] answers links with human in SECOND position —
    # Similarity(monkey, human) is in, Similarity(human, monkey) is NOT
    # (the reference's own distributed_atom_space_test pins these counts)
    assert "2a8a69c01305563932b957de4b3a9ba6" in links  # Sim(monkey, human)
    assert "16f7e407087bfa0b35b13d13a1aadcae" not in links

    query = _client(
        port, "query", "--das-key", token,
        "--query", "Node n1 Concept human, Link Inheritance n1 $1",
    )
    assert MAMMAL in query

    conj = _client(
        port, "query", "--das-key", token,
        "--query",
        "Node n1 Concept human, Node n2 Concept chimp, "
        "Link Similarity n1 $1, Link Similarity n2 $1, AND",
    )
    assert "1cdffc6b0b89ff41d68bec237481d1e1" in conj  # monkey


def test_reference_client_invalid_key_fails(das_server):
    env_proc = subprocess.run(
        [sys.executable, REFERENCE_CLIENT, "--port", str(das_server),
         "count", "--das-key", "nosuchkey"],
        capture_output=True, text=True, timeout=120,
        env={
            **{k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"},
            "PYTHONPATH": f"{REPO}/compat:{REPO}:{REPO}/das_tpu/service/service_spec",
            "JAX_PLATFORMS": "cpu",
            "COUCHBASE_SETUP_DIR": "/tmp",
        },
    )
    # the client asserts response.success — an invalid key must surface
    assert env_proc.returncode != 0
    assert "Invalid DAS key" in env_proc.stderr
