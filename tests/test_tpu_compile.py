"""TPU AOT-compile smoke tests (VERDICT r03 weak #2).

The CPU suite cannot catch v5e scoped-vmem compile failures (the 16MB
stack budget is a TPU-compiler property: r03's fori_loop count body died
with "reduce-window ... exceeded scoped vmem limit" while the identical
program compiled and ran everywhere else).  These tests AOT-lower the
fused count programs — standalone AND wrapped in the sequential
fori_loop — at the LARGEST learned capacity classes, on the real TPU
only.  On CPU they skip: the lowering being exercised does not exist
there."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.query.fused import get_executor
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.skipif(
    jax.devices()[0].platform == "cpu",
    reason="TPU-compiler scoped-vmem behavior; no TPU device",
)

LARGE = dict(
    n_genes=20000, n_processes=2000, members_per_gene=5,
    n_interactions=15000, n_evaluations=5000,
)


def _grounded(g):
    return And([
        Link("Member", [Node("Gene", g), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Node("Gene", g), Variable("V2")], True),
    ])


@pytest.fixture(scope="module")
def large_db():
    data, _, _ = build_bio_atomspace(**LARGE)
    return TensorDB(data, DasConfig(initial_result_capacity=1 << 16))


def test_count_loop_compiles_and_matches(large_db):
    """The r03 failure mode verbatim: the fori_loop count program at the
    capacities the executor actually learns.  Must compile, run, and agree
    with the per-query counts."""
    db = large_db
    genes = db.get_all_nodes("Gene", names=True)
    ex = get_executor(db)
    plans = [compiler.plan_query(db, _grounded(g)) for g in genes[:16]]
    run, W = ex.build_count_loop(plans)
    counts, _mx = run()
    assert W == 16
    expected = [compiler.count_matches(db, _grounded(g)) for g in genes[:16]]
    assert list(counts) == expected


def test_join_kernels_compile_at_max_capacity(large_db):
    """AOT-lower the pair-expansion join at the largest capacity class the
    config allows (the scoped-vmem-sensitive int64 cumsum scales with the
    LEFT table, the cummax with the output capacity)."""
    from das_tpu.ops.join import _join_tables_impl

    cap = int(large_db.config.max_result_capacity)
    left = jax.ShapeDtypeStruct((1 << 16, 3), jnp.int32)
    lmask = jax.ShapeDtypeStruct((1 << 16,), jnp.bool_)
    right = jax.ShapeDtypeStruct((1 << 20, 2), jnp.int32)
    rmask = jax.ShapeDtypeStruct((1 << 20,), jnp.bool_)

    def f(lv, lm, rv, rm):
        return _join_tables_impl(lv, lm, rv, rm, ((0, 0),), (1,), cap)

    jax.jit(f).lower(left, lmask, right, rmask).compile()


def test_whole_query_compiles_on_all_variable_shape(large_db):
    """The all-variable 3-clause conjunction (the headline query) end to
    end on the device — count + result-set dispatch both compile."""
    db = large_db
    q = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])
    n = compiler.count_matches(db, q)
    assert n >= 0
