"""Columnar ingest path (native/src/das_columnar.cc + storage/columnar.py).

Differential against the dict-based loaders: the columnar store must be
indistinguishable — identical Finalized arrays (row order, type registry,
bucket indexes, CSR), identical record reconstruction, identical query
results, and identical incremental-commit behavior."""

import os

import numpy as np
import pytest

from das_tpu.core.config import DasConfig
from das_tpu.ingest import native
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, Node, PatternMatchingAnswer, Variable
from das_tpu.storage.atom_table import AtomSpaceData, load_metta_text
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.skipif(
    not native.native_available(), reason="native library unavailable"
)

CANONICAL = """\
(: Concept Type)
(: Predicate Type)
(: Similarity Type)
(: "human" Concept)
(: "monkey" Concept)
(: "chimp" Concept)
(: "dinosaur" Concept)
(: "likes" Predicate)
(Similarity "Concept human" "Concept monkey")
(Similarity "Concept human" "Concept chimp")
(Similarity "Concept monkey" "Concept chimp")
(Inheritance "Concept human" "Concept dinosaur")
(Evaluation "Predicate likes" (Inheritance "Concept human" "Concept dinosaur"))
(Evaluation "Predicate likes" (List "Concept human" "Concept monkey" "Concept chimp"))
(Similarity "Concept human" "Concept monkey")
(List "Concept human" "Concept monkey" "Concept chimp")
"""


def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


def _both(paths):
    d1 = native.load_canonical_files_native(list(paths))
    d2 = native.load_canonical_files_columnar(list(paths))
    return d1, d2


def _assert_finalized_equal(f1, f2):
    assert f1.atom_count == f2.atom_count
    assert f1.node_count == f2.node_count
    assert list(f1.hex_of_row) == list(f2.hex_of_row)
    assert f1.type_names == f2.type_names
    assert f1.type_id_of_hash == f2.type_id_of_hash
    assert np.array_equal(f1.node_type_id, f2.node_type_id)
    assert set(f1.buckets) == set(f2.buckets)
    for a in f1.buckets:
        b1, b2 = f1.buckets[a], f2.buckets[a]
        for name in (
            "rows", "type_id", "ctype", "targets", "targets_sorted",
            "order_by_type", "key_type", "order_by_ctype", "key_ctype",
        ):
            assert np.array_equal(getattr(b1, name), getattr(b2, name)), (a, name)
        for name in (
            "order_by_type_pos", "key_type_pos", "order_by_pos", "key_pos",
            "order_by_type_spos", "key_type_spos",
        ):
            for x, y in zip(getattr(b1, name), getattr(b2, name)):
                assert np.array_equal(x, y), (a, name)
    assert np.array_equal(f1.incoming_offsets, f2.incoming_offsets)
    assert np.array_equal(f1.incoming_links, f2.incoming_links)
    assert f1.dangling_hexes == f2.dangling_hexes


def test_finalize_parity_nested_dups_unordered(tmp_path):
    d1, d2 = _both([_write(tmp_path, "kb.metta", CANONICAL)])
    assert d1.count_atoms() == d2.count_atoms()
    _assert_finalized_equal(d1.finalize(), d2.finalize())


def test_record_reconstruction_parity(tmp_path):
    d1, d2 = _both([_write(tmp_path, "kb.metta", CANONICAL)])
    assert list(d1.nodes) == list(d2.nodes)
    assert list(d1.links) == list(d2.links)
    for h in d1.nodes:
        assert d1.nodes[h] == d2.nodes[h]
    for h in d1.links:
        r1, r2 = d1.links[h], d2.links[h]
        assert r1 == r2, (h, r1, r2)
    assert d1.typedefs == d2.typedefs
    # toplevel OR-merge: the nested (Inheritance ...) re-added at toplevel
    inh = [h for h, r in d1.links.items() if r.named_type == "Inheritance"]
    assert len(inh) == 1 and d2.links[inh[0]].is_toplevel


def test_lazy_view_semantics(tmp_path):
    d1, d2 = _both([_write(tmp_path, "kb.metta", CANONICAL)])
    v = d2.links
    assert len(v) == len(d1.links)
    some = next(iter(d1.links))
    assert some in v and v.get(some) is not None
    assert "0" * 32 not in v and v.get("0" * 32) is None
    with pytest.raises(KeyError):
        v["0" * 32]
    assert list(reversed(v)) == list(reversed(list(d1.links)))
    assert [h for h, _ in v.items()] == list(d1.links)


def test_multi_file_order_and_dedup(tmp_path):
    f1 = _write(tmp_path, "a.metta", CANONICAL)
    f2 = _write(
        tmp_path,
        "b.metta",
        '(: Concept Type)\n(: "human" Concept)\n(: "dog" Concept)\n'
        '(Similarity "Concept human" "Concept dog")\n'
        '(Similarity "Concept human" "Concept monkey")\n',
    )
    d1, d2 = _both([f1, f2])
    assert d1.count_atoms() == d2.count_atoms()
    _assert_finalized_equal(d1.finalize(), d2.finalize())


def test_dangling_elements(tmp_path):
    # "monkey" is never declared: the link's element dangles
    text = (
        '(: Concept Type)\n(: "human" Concept)\n'
        '(Similarity "Concept human" (List "Concept monkey"))\n'
    )
    d1, d2 = _both([_write(tmp_path, "kb.metta", text)])
    f1, f2 = d1.finalize(), d2.finalize()
    assert f1.dangling_hexes == f2.dangling_hexes and f1.dangling_hexes
    for a in f1.buckets:
        assert np.array_equal(f1.buckets[a].targets, f2.buckets[a].targets)
    assert sum((f2.buckets[a].targets == -1).sum() for a in f2.buckets) == 1
    # elements still reconstruct the dangling hex
    h = next(iter(d1.links))
    assert d1.links[h].elements == d2.links[h].elements


def test_chunk_parallel_large(tmp_path):
    # enough lines that correctness does not depend on single-chunk parsing
    # (chunks are 16 MB; this exercises the dedup/merge paths at least via
    # multiple C++ worker threads on one chunk list)
    lines = ["(: Concept Type)"]
    lines += [f'(: "n{i}" Concept)' for i in range(2000)]
    lines += [
        f'(Similarity "Concept n{i}" "Concept n{(i * 7 + 1) % 2000}")'
        for i in range(4000)
    ]
    lines += [f'(Inheritance "Concept n{i}" "Concept n0")' for i in range(1000)]
    d1, d2 = _both([_write(tmp_path, "kb.metta", "\n".join(lines) + "\n")])
    assert d1.count_atoms() == d2.count_atoms()
    _assert_finalized_equal(d1.finalize(), d2.finalize())


def test_queries_on_columnar_store(tmp_path):
    from das_tpu.models.bio import write_bio_canonical

    p = str(tmp_path / "bio.metta")
    write_bio_canonical(
        p, n_genes=120, n_processes=12, members_per_gene=4,
        n_interactions=90, n_evaluations=20,
    )
    d1, d2 = _both([p])
    db1 = TensorDB(d1, DasConfig())
    db2 = TensorDB(d2, DasConfig())
    q = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])
    assert compiler.count_matches(db1, q) == compiler.count_matches(db2, q)
    a1, a2 = PatternMatchingAnswer(), PatternMatchingAnswer()
    compiler.query_on_device(db1, q, a1)
    compiler.query_on_device(db2, q, a2)
    assert a1.assignments == a2.assignments and a1.assignments
    # node-name surface
    g1 = db1.get_all_nodes("Gene", names=True)
    g2 = db2.get_all_nodes("Gene", names=True)
    assert g1 == g2
    assert db1.get_all_nodes("Gene") == db2.get_all_nodes("Gene")
    h = db2.get_node_handle("Gene", g2[0])
    assert db2.get_node_name(h) == g2[0]
    assert db2.node_exists("Gene", g2[0])


def test_incremental_commit_on_columnar(tmp_path):
    from das_tpu.models.bio import write_bio_canonical

    p = str(tmp_path / "bio.metta")
    write_bio_canonical(
        p, n_genes=60, n_processes=8, members_per_gene=3,
        n_interactions=40, n_evaluations=10,
    )
    d1, d2 = _both([p])
    db1 = TensorDB(d1, DasConfig())
    db2 = TensorDB(d2, DasConfig())
    commit = "\n".join(
        ['(: "NGX_%d" Gene)' % i for i in range(5)]
        + ['(Interacts "NGX_%d" "NGX_%d")' % (i, (i + 1) % 5) for i in range(5)]
    )
    for db in (db1, db2):
        load_metta_text(commit, db.data)
        db.refresh()
    assert db1.count_atoms() == db2.count_atoms()
    q = And([
        Link("Interacts", [Node("Gene", "NGX_0"), Variable("V")], True),
    ])
    a1, a2 = PatternMatchingAnswer(), PatternMatchingAnswer()
    compiler.query_on_device(db1, q, a1)
    compiler.query_on_device(db2, q, a2)
    assert a1.assignments == a2.assignments and a1.assignments
    # committed atoms are visible through the lazy views
    h = db2.get_node_handle("Gene", "NGX_0")
    assert h in db2.data.nodes
    assert db2.get_all_nodes("Gene", names=True).count("NGX_0") == 1


def test_memory_db_over_columnar(tmp_path):
    d1, d2 = _both([_write(tmp_path, "kb.metta", CANONICAL)])
    m1, m2 = MemoryDB(d1), MemoryDB(d2)
    human1 = m1.get_node_handle("Concept", "human")
    assert m2.node_exists("Concept", "human")
    got1 = m1.get_matched_links("Similarity", [human1, "*"])
    got2 = m2.get_matched_links("Similarity", [human1, "*"])
    assert sorted(got1) == sorted(got2) and got1


def test_second_load_toplevel_upgrade_writes_through(tmp_path):
    """A second canonical load onto a columnar-backed store takes the
    record-stream decode path; a link known only as a sub-expression that
    the second file declares TOPLEVEL must upgrade in the column, not on
    a throwaway reconstructed record."""
    first = (
        "(: Concept Type)\n"
        '(: "a" Concept)\n(: "b" Concept)\n'
        # Inheritance exists ONLY nested here
        '(Evaluation (Inheritance "Concept a" "Concept b"))\n'
    )
    second = (
        "(: Concept Type)\n"
        '(: "a" Concept)\n(: "b" Concept)\n'
        '(Inheritance "Concept a" "Concept b")\n'
    )
    f1 = _write(tmp_path, "one.metta", first)
    f2 = _write(tmp_path, "two.metta", second)
    d = native.load_canonical_files_columnar([f1])
    inh = [h for h, r in d.links.items() if r.named_type == "Inheritance"]
    assert len(inh) == 1 and not d.links[inh[0]].is_toplevel
    native.load_canonical_files_native([f2], d)  # record-stream path
    assert d.links[inh[0]].is_toplevel


def test_section_order_errors(tmp_path):
    bad = '(: Concept Type)\n(: "x" Concept)\n(: Predicate Type)\n'
    with pytest.raises(Exception):
        native.load_canonical_files_columnar([_write(tmp_path, "bad.metta", bad)])
    bad2 = "(Similarity x y)\n"
    with pytest.raises(Exception):
        native.load_canonical_files_columnar([_write(tmp_path, "bad2.metta", bad2)])


def test_columnar_env_gate(tmp_path, monkeypatch):
    from das_tpu.ingest.pipeline import load_canonical_knowledge_base

    p = _write(tmp_path, "kb.metta", CANONICAL)
    data = load_canonical_knowledge_base(AtomSpaceData(), p)
    assert data.columnar is not None
    monkeypatch.setenv("DAS_TPU_COLUMNAR", "0")
    data2 = load_canonical_knowledge_base(AtomSpaceData(), p)
    assert data2.columnar is None
    assert data.count_atoms() == data2.count_atoms()


def test_commit_referencing_preloaded_terminal(tmp_path):
    """A transaction referencing a terminal that arrived through the
    columnar scanner must resolve it through the store (the columnar
    path deliberately never materializes terminal symbols into the
    parser table — without the resolver this raised
    UndefinedSymbolError on the reference's own `(Inheritance "lion"
    "mammal")`-style commit shape)."""
    from das_tpu.core.config import DasConfig
    from das_tpu.ingest.pipeline import load_canonical_knowledge_base
    from das_tpu.models.bio import write_bio_canonical
    from das_tpu.query import compiler
    from das_tpu.query.ast import Link, Node, PatternMatchingAnswer, Variable
    from das_tpu.storage.atom_table import AtomSpaceData, load_metta_text
    from das_tpu.storage.tensor_db import TensorDB

    p = str(tmp_path / "kb.metta")
    write_bio_canonical(p, n_genes=50, n_processes=10, members_per_gene=3,
                        n_interactions=20, n_evaluations=5)
    data = AtomSpaceData()
    load_canonical_knowledge_base(data, p)
    if data.columnar is None:
        pytest.skip("native scanner unavailable")
    db = TensorDB(data, DasConfig())
    load_metta_text(
        '(: "NGX_0" Gene)\n(Interacts "NGX_0" "GENE:0000000")', db.data
    )
    db.refresh()
    q = Link("Interacts", [Node("Gene", "NGX_0"), Variable("V1")], True)
    a = PatternMatchingAnswer()
    assert compiler.query_on_device(db, q, a)
    assert len(a.assignments) == 1
    # an actually-unknown terminal still fails loudly
    from das_tpu.core.exceptions import UndefinedSymbolError

    with pytest.raises(UndefinedSymbolError):
        load_metta_text('(Interacts "NGX_0" "NO_SUCH_GENE")', db.data)


def test_terminal_resolver_last_declaration_wins(tmp_path):
    """A terminal name declared under TWO types resolves to the latest
    declaration — matching the dict path's named_types overwrite."""
    from das_tpu.ingest.canonical import load_canonical_file
    from das_tpu.ingest.native import load_canonical_files_native, native_available
    from das_tpu.storage.atom_table import AtomSpaceData, load_metta_text

    if not native_available():
        pytest.skip("native scanner unavailable")
    text = (
        "(: Gene Type)\n(: Protein Type)\n(: Rel Type)\n"
        '(: "P53" Gene)\n(: "P53" Protein)\n(: "other" Gene)\n'
        '(Rel "Gene other" "Gene other")\n'
    )
    p = str(tmp_path / "kb.metta")
    open(p, "w").write(text)
    from das_tpu.ingest.native import columnar_available, load_canonical_files_columnar

    loaded = [load_canonical_file(p)]
    rec = AtomSpaceData()
    load_canonical_files_native([p], rec)
    loaded.append(rec)
    if columnar_available():
        col = AtomSpaceData()
        load_canonical_files_columnar([p], col)
        loaded.append(col)
    commit = '(Rel "P53" "other")'
    for d in loaded:
        load_metta_text(commit, d)
    # identical link handles on every loader: P53 resolved to Protein
    # (the LAST declaration), "other" to Gene, everywhere
    for d in loaded[1:]:
        assert set(d.links) == set(loaded[0].links)


def test_bare_symbol_use_of_canonical_terminal(tmp_path):
    """Using a canonical-loaded terminal's bare name as a head symbol must
    behave exactly like the dict parser path (which records a typedef
    hash per declaration): same link handles, no KeyError."""
    from das_tpu.ingest.canonical import load_canonical_file
    from das_tpu.storage.atom_table import load_metta_text

    text = '(: Concept Type)\n(: Rel Type)\n(: "mammal" Concept)\n(: "x" Concept)\n(Rel "Concept mammal" "Concept x")\n'
    p = str(tmp_path / "kb.metta")
    open(p, "w").write(text)
    canon = load_canonical_file(p)
    # the dict-parser path over equivalent declarations
    parsed = load_metta_text(
        '(: Concept Type)(: Rel Type)(: "mammal" Concept)(: "x" Concept)'
    )
    commit = "(mammal mammal)"
    load_metta_text(commit, canon)
    load_metta_text(commit, parsed)
    assert set(canon.links) >= set(parsed.links)
    # and the COLUMNAR path resolves the bare name through the store
    from das_tpu.ingest.native import columnar_available, load_canonical_files_columnar
    from das_tpu.storage.atom_table import AtomSpaceData

    if columnar_available():
        col = AtomSpaceData()
        load_canonical_files_columnar([p], col)
        load_metta_text(commit, col)
        assert set(col.links) >= set(parsed.links)


def test_check_resolves_columnar_terminals(tmp_path):
    """MettaParser.check must accept text the real parse accepts on a
    columnar store (the scratch table carries the resolver)."""
    from das_tpu.ingest.metta import MettaParser
    from das_tpu.ingest.native import columnar_available, load_canonical_files_columnar
    from das_tpu.models.bio import write_bio_canonical
    from das_tpu.storage.atom_table import AtomSpaceData

    if not columnar_available():
        pytest.skip("columnar scanner unavailable")
    p = str(tmp_path / "kb.metta")
    write_bio_canonical(p, n_genes=30, n_processes=5, members_per_gene=2,
                        n_interactions=10)
    data = AtomSpaceData()
    load_canonical_files_columnar([p], data)
    parser = MettaParser(symbol_table=data.table)
    parser.check('(Interacts "GENE:0000000" "GENE:0000001")')  # no raise
