"""Pattern-matcher behavior on the animals KB (hardware-free backend).

Mirrors the coverage of the reference pattern_matcher_test.py +
scripts/regression.py battery, with expectations stated in terms of node
names so the test is self-describing.
"""

import pytest

from das_tpu.query.assignment import OrderedAssignment, UnorderedAssignment
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Not,
    Or,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)


def node_handle(db, name):
    return db.get_node_handle("Concept", name)


def run(db, query):
    answer = PatternMatchingAnswer()
    matched = query.matched(db, answer)
    return matched, answer


def ordered_mappings(db, answer):
    """Set of frozenset({var: name}) for ordered assignments."""
    out = set()
    reverse = {node_handle(db, n): n for n in _names(db)}
    for a in answer.assignments:
        assert isinstance(a, OrderedAssignment)
        out.add(frozenset((k, reverse.get(v, v)) for k, v in a.mapping.items()))
    return out


def _names(db):
    return db.get_all_nodes("Concept", names=True)


def m(**kw):
    return frozenset(kw.items())


class TestGroundedMatching:
    def test_node_exists(self, animals_db):
        assert run(animals_db, Node("Concept", "human"))[0]
        assert not run(animals_db, Node("Concept", "dog"))[0]

    def test_grounded_link(self, animals_db):
        q = Link(
            "Inheritance",
            [Node("Concept", "human"), Node("Concept", "mammal")],
            True,
        )
        assert run(animals_db, q)[0]

    def test_grounded_link_wrong_direction(self, animals_db):
        q = Link(
            "Inheritance",
            [Node("Concept", "mammal"), Node("Concept", "human")],
            True,
        )
        assert not run(animals_db, q)[0]

    def test_grounded_similarity_both_orders(self, animals_db):
        # the KB stores the symmetric closure, so both orders exist
        for a, b in [("snake", "earthworm"), ("earthworm", "snake")]:
            q = Link("Similarity", [Node("Concept", a), Node("Concept", b)], False)
            assert run(animals_db, q)[0]


class TestWildcardMatching:
    def test_inheritance_into_mammal(self, animals_db):
        q = Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
        matched, answer = run(animals_db, q)
        assert matched
        assert ordered_mappings(animals_db, answer) == {
            m(V1="human"), m(V1="monkey"), m(V1="chimp"), m(V1="rhino"),
        }

    def test_all_inheritance_pairs(self, animals_db):
        q = Link("Inheritance", [Variable("V1"), Variable("V2")], True)
        matched, answer = run(animals_db, q)
        assert matched
        assert len(answer.assignments) == 12

    def test_same_variable_twice_no_self_loops(self, animals_db):
        q = Link("Inheritance", [Variable("V1"), Variable("V1")], True)
        matched, answer = run(animals_db, q)
        assert not matched

    def test_similarity_with_grounded_first(self, animals_db):
        q = Link("Similarity", [Node("Concept", "human"), Variable("V1")], False)
        matched, answer = run(animals_db, q)
        assert matched
        values = set()
        for a in answer.assignments:
            assert isinstance(a, UnorderedAssignment)
            values |= set(a.values)
        names = {
            n
            for n in _names(animals_db)
            if node_handle(animals_db, n) in values
        }
        assert names == {"monkey", "chimp", "ent"}

    def test_unordered_probe_is_symmetric(self, animals_db):
        q1 = Link("Similarity", [Node("Concept", "human"), Variable("V1")], False)
        q2 = Link("Similarity", [Variable("V1"), Node("Concept", "human")], False)
        _, a1 = run(animals_db, q1)
        _, a2 = run(animals_db, q2)
        assert a1.assignments == a2.assignments


class TestLogicalOperators:
    def test_and_chained_inheritance(self, animals_db):
        q = And([
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        ])
        matched, answer = run(animals_db, q)
        assert matched
        expected = {
            m(V1="human", V2="mammal", V3="animal"),
            m(V1="monkey", V2="mammal", V3="animal"),
            m(V1="chimp", V2="mammal", V3="animal"),
            m(V1="rhino", V2="mammal", V3="animal"),
            m(V1="snake", V2="reptile", V3="animal"),
            m(V1="dinosaur", V2="reptile", V3="animal"),
            m(V1="triceratops", V2="dinosaur", V3="reptile"),
        }
        assert ordered_mappings(animals_db, answer) == expected

    def test_and_inheritance_and_similarity(self, animals_db):
        q = And([
            Link("Inheritance", [Variable("V1"), Variable("V3")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
            Link("Similarity", [Variable("V1"), Variable("V2")], False),
        ])
        matched, answer = run(animals_db, q)
        assert matched
        # siblings under the same parent that are also similar
        pairs = set()
        reverse = {node_handle(animals_db, n): n for n in _names(animals_db)}
        for a in answer.assignments:
            om = a.ordered_mapping if hasattr(a, "ordered_mapping") else a
            pairs.add(
                (reverse[om.mapping["V1"]], reverse[om.mapping["V2"]], reverse[om.mapping["V3"]])
            )
        assert ("human", "monkey", "mammal") in pairs
        assert ("monkey", "human", "mammal") in pairs
        assert ("rhino", "triceratops", "mammal") not in pairs  # different parents

    def test_not_grounded(self, animals_db):
        matched, answer = run(
            animals_db,
            Not(Link("Inheritance", [Node("Concept", "human"), Node("Concept", "mammal")], True)),
        )
        assert matched
        assert answer.negation

    def test_and_with_not(self, animals_db):
        q = And([
            Link("Inheritance", [Variable("V1"), Variable("V3")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
            Not(Link("Similarity", [Variable("V1"), Variable("V2")], False)),
        ])
        matched, answer = run(animals_db, q)
        assert matched
        reverse = {node_handle(animals_db, n): n for n in _names(animals_db)}
        for a in answer.assignments:
            v1 = reverse[a.mapping["V1"]]
            v2 = reverse[a.mapping["V2"]]
            assert (v1, v2) not in {
                ("human", "monkey"), ("monkey", "human"),
                ("human", "chimp"), ("chimp", "human"),
                ("chimp", "monkey"), ("monkey", "chimp"),
                ("rhino", "triceratops"), ("triceratops", "rhino"),
            }

    def test_or_union(self, animals_db):
        q = Or([
            Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
            Link("Inheritance", [Variable("V1"), Node("Concept", "dinosaur")], True),
        ])
        matched, answer = run(animals_db, q)
        assert matched
        assert ordered_mappings(animals_db, answer) == {
            m(V1="vine"), m(V1="ent"), m(V1="triceratops"),
        }

    def test_empty_and_or(self, animals_db):
        assert not run(animals_db, And([]))[0]
        assert not run(animals_db, Or([]))[0]


class TestLinkTemplates:
    def test_inheritance_template(self, animals_db):
        q = LinkTemplate(
            "Inheritance",
            [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
            True,
        )
        matched, answer = run(animals_db, q)
        assert matched
        assert len(answer.assignments) == 12

    def test_similarity_template_unordered(self, animals_db):
        q = LinkTemplate(
            "Similarity",
            [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
            False,
        )
        matched, answer = run(animals_db, q)
        assert matched
        # 14 similarity links stored, each unordered assignment {V1,V2}<->{a,b}
        # dedups the two orientations to the same multiset
        assert len(answer.assignments) == 7

    def test_unknown_template_type(self, animals_db):
        q = LinkTemplate(
            "List",
            [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
            True,
        )
        matched, _ = run(animals_db, q)
        assert not matched
