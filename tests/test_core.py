"""Core identity layer: md5 parity, int64 handles, expression records."""

import numpy as np

from das_tpu.core.hashing import (
    EMPTY_I64,
    ExpressionHasher,
    hex_to_i64,
    i64_hash_str,
    splitmix64,
)


def test_terminal_hash_reference_parity():
    # known handles from the reference acceptance fixtures
    # (scripts/service_regression_test.sh:24-38)
    assert (
        ExpressionHasher.terminal_hash("Concept", "human")
        == "af12f10f9ae2002a1607ba0b47ba8407"
    )
    assert (
        ExpressionHasher.terminal_hash("Concept", "mammal")
        == "bdfe4e7a431f73386f37c6448afe5840"
    )


def test_composite_hash_singleton_collapse():
    assert ExpressionHasher.composite_hash(["abc"]) == "abc"
    assert ExpressionHasher.composite_hash("abc") == "abc"
    multi = ExpressionHasher.composite_hash(["a", "b"])
    assert len(multi) == 32


def test_expression_hash_matches_manual_md5():
    from hashlib import md5

    th = ExpressionHasher.named_type_hash("Inheritance")
    h1 = ExpressionHasher.terminal_hash("Concept", "human")
    h2 = ExpressionHasher.terminal_hash("Concept", "mammal")
    expected = md5(f"{th} {h1} {h2}".encode()).hexdigest()
    assert ExpressionHasher.expression_hash(th, [h1, h2]) == expected


def test_hex_to_i64_roundtrip_determinism():
    a = hex_to_i64("af12f10f9ae2002a1607ba0b47ba8407")
    b = hex_to_i64("af12f10f9ae2002a1607ba0b47ba8407")
    assert a == b
    assert a != hex_to_i64("bdfe4e7a431f73386f37c6448afe5840")
    assert a != EMPTY_I64


def test_hex_to_i64_never_produces_sentinel():
    assert hex_to_i64("80000000000000000000000000000000") != EMPTY_I64


def test_i64_hash_str():
    assert i64_hash_str("Concept") == hex_to_i64(
        ExpressionHasher.named_type_hash("Concept")
    )


def test_splitmix64_is_a_bijection_sample():
    xs = np.arange(1000, dtype=np.int64)
    ys = splitmix64(xs)
    assert len(np.unique(ys)) == 1000
