"""The driver-facing bench output contract (VERDICT r04 item 1): the
final stdout line must be ONE complete JSON line that fits a 2000-char
tail capture with margin.  compact_headline is the pure function behind
it — pinned here so a field addition cannot silently outgrow the tail."""

import json
import sys

sys.path.insert(0, ".")

import bench


def _full_extra():
    return {
        "platform": "tpu",
        "device_only_method": "host_visible_minus_rtt",
        "host_visible_p50_ms": 99999.999,
        "transport_rtt_ms": 99999.999,
        "batched_ms_per_query": 99999.999,
        "batched_wide_ms_per_query": 99999.999,
        "served_ms_per_query": 99999.999,
        "kernel_ab": {
            "lowered_ms": 99999.999,
            "kernel_ms": 99999.999,
            "interpret": True,
            "route": "pallas-interpret",
            "staged_dispatches": {"lowered": 999, "kernel": 999},
        },
        "tiled_kernel_ab": {
            "interpret": True,
            "rows": 99_999_999,
            "probe_cap": 99_999_999,
            "join_cap": 99_999_999,
            "route": "tiled",
            "tiled_route": {
                "probe": "tiled", "join": "tiled", "chunk_rows": 999_999,
            },
            "probe_lowered_ms": 99999.999,
            "probe_kernel_ms": 99999.999,
            "join_lowered_ms": 99999.999,
            "join_kernel_ms": 99999.999,
            "tiled_vs_lowered_ms": [99999.999, 99999.999],
            "parity": True,
            "no_lowered_fallback": True,
        },
        "sharded_serving": {
            "n_shards": 999,
            "clients": 999,
            "distinct_queries": 999,
            "per_client": 999,
            "interpret": True,
            "serial_qps": 999999.9,
            "pipelined_qps": 999999.9,
            "pipeline_speedup": 99.999,
            "inflight_peak": 999,
            "served_ms_per_query": 99999.999,
            "time_to_first_row_ms": 99999.999,
            "effective_depth": 999,
            "speculative_dispatches": 9_999_999,
            "early_settles": 9_999_999,
            "queue_rejections": 9_999_999,
            "open_loop_p50_ms": 99999.999,
            "open_loop_p95_ms": 99999.999,
            "open_loop_p99_ms": 99999.999,
            "latency_buckets": [[99999.999, 999_999]] * 12,
            "count_lowered_ms": 99999.999,
            "count_kernel_ms": 99999.999,
            "count_kernel_engaged": True,
            "count_parity": True,
        },
        "serving": {
            "clients": 999,
            "distinct_queries": 999,
            "per_client": 999,
            "interpret": True,
            "serial_qps": 999999.9,
            "pipelined_qps": 999999.9,
            "pipeline_depth": 99,
            "pipeline_speedup": 99.999,
            "inflight_peak": 999,
            "max_batch": 999,
            "served_ms_per_query": 99999.999,
            "time_to_first_row_ms": 99999.999,
            "effective_depth": 999,
            "pipeline_depth_max": 999,
            "rtt_ewma_ms": 99999.9999,
            "speculative_dispatches": 9_999_999,
            "early_settles": 9_999_999,
            "queue_rejections": 9_999_999,
            "open_loop_p50_ms": 99999.999,
            "open_loop_p95_ms": 99999.999,
            "open_loop_p99_ms": 99999.999,
            "latency_buckets": [[99999.999, 999_999]] * 12,
            "cached_qps": 999999.9,
            "cache_hit_rate": 1.0,
            "cache_hit_ms": 99999.9999,
            "device_path_ms": 99999.9999,
            "cache_speedup": 99999.9,
        },
        "chaos": {
            "clients": 999,
            "per_client": 999,
            "fault_spec": "seed=17;sites=settle_fetch;rate=0.05;max=999",
            "interpret": True,
            "clean_qps": 999999.9,
            "chaos_qps": 999999.9,
            "chaos_qps_ratio": 9.999,
            "typed_errors": 999_999,
            "answered": 999_999,
            "injected": {"settle_fetch": 999_999},
            "deadline_ms": 999,
            "deadline_miss_rate": 1.0,
            "breaker_trips": 999_999,
            "breaker_recoveries": 999_999,
            "breaker_recovery_ms": 99999.9,
        },
        "planner_ab": {
            "clauses": 999,
            "skew": 9.9,
            "planner_first_contact_ms": 99999.999,
            "greedy_first_contact_ms": 99999.999,
            "planner_programs": 999_999,
            "greedy_programs": 999_999,
            "planner_ms": 99999.999,
            "greedy_ms": 99999.999,
            "planner_route": "fused_kernel",
            "retry_rounds_avoided": 999_999,
            "parity": True,
            "planner_stats": {
                "planned": 9_999_999, "greedy": 9_999_999,
                "round0": 9_999_999, "retries": 9_999_999,
                "est_rows": 9_999_999_999, "actual_rows": 9_999_999_999,
                "actual_vs_est_ratio": 9999.9999,
            },
        },
        "multiway_ab": {
            "skew": 9.9,
            "interpret": True,
            "multiway_first_contact_ms": 99999.999,
            "chain_first_contact_ms": 99999.999,
            "multiway_programs": 999_999,
            "chain_programs": 999_999,
            "multiway_ms": 99999.999,
            "chain_ms": 99999.999,
            "multiway_route": "fused_multiway",
            "chain_retry_rounds_avoided": 999_999,
            "parity": True,
            "multiway_stats": {
                "planned": 9_999_999, "round0": 9_999_999,
                "retries": 9_999_999,
                "est_rows": 9_999_999_999, "actual_rows": 9_999_999_999,
                "actual_vs_est_ratio": 9999.9999,
            },
        },
        "tree_fused_ab": {
            "branches": [9, 9, 9],
            "queries": 9,
            "interpret": True,
            "fused_first_contact_ms": 99999.999,
            "tree_first_contact_ms": 99999.999,
            "fused_programs": 999_999,
            "tree_programs": 999_999,
            "fused_ms": 99999.999,
            "tree_ms": 99999.999,
            "tree_fused_route": "fused_tree",
            "tree_programs_avoided": 999_999,
            "parity": True,
        },
        "durability": {
            "interpret": True,
            "commits": 999,
            "snapshot_s": 99999.999,
            "rebuild_s": 99999.999,
            "restore_s": 99999.999,
            "restore_vs_rebuild": 99999.99,
            "wal_records_replayed": 999_999,
            "wal_replay_commits_per_s": 999999.9,
            "chaos_crash_typed": True,
            "chaos_recovery_ms": 99999.9,
        },
        "programs": {
            "enabled": True,
            "compiles": 999_999,
            "compile_s": 99999.999,
            "calls": 9_999_999,
            "ledger_hits": 9_999_999,
            "hit_rate": 1.0,
            "cold_start_s": 99999.999,
            "persistent_cache_hits": 999_999,
            "errors": 999_999,
            "launches": 9_999_999,
            "entries": 9_999,
            "budget_vs_actual": {"fused": 9999.9999, "sharded": 9999.9999},
        },
        "kb_nodes": 999_999_999,
        "kb_links": 99_999_999_999,
        "matches": 999_999_999,
        "flybase_scale": {
            "kb_links": 99_999_999_999,
            "flybase_scale_factor": 1.0,
            "ingest_expressions_per_s": 999_999_999,
            "sequential_p50_ms": 99999.999,
            "sequential_device_only_ms": 99999.999,
            "batched_ms_per_query": 99999.999,
            "batched_fresh_ms_per_query": 99999.999,
            "miner_ms_per_link": 99999.99,
            "commit_10_expressions_steady_s": 99999.9999,
            "error": "x" * 500,  # must be truncated to 16
        },
    }


def test_compact_headline_fits_tail_with_margin():
    result = {
        "metric": "bio_atomspace 3-var conjunctive query latency (device-only)",
        "value": 99999.999,
        "unit": "ms",
        "vs_baseline": 9_999_999.9,
        "extra": _full_extra(),
    }
    line = json.dumps(bench.compact_headline(result))
    assert len(line) < 1500, f"compact line {len(line)} bytes"
    parsed = json.loads(line)
    assert parsed["metric"] == result["metric"]
    assert len(parsed["extra"]["flybase"]["error"]) == 16
    # the Pallas A/B record must survive compaction
    assert parsed["extra"]["kernel_route"] == "pallas-interpret"
    assert parsed["extra"]["kernel_vs_lowered_ms"] == [99999.999, 99999.999]
    # the grid-chunked >2^18 A/B must survive compaction (ISSUE 4:
    # planner route at the synthetic large term, summed kernel-vs-lowered)
    assert parsed["extra"]["tiled_route"] == "tiled"
    assert parsed["extra"]["tiled_vs_lowered_ms"] == [99999.999, 99999.999]
    # the serving pipeline + result-cache record must survive compaction
    # (ISSUE 2: pipelined-vs-serial qps, depth, hit rate, hit-vs-device ms)
    assert parsed["extra"]["serving_qps"] == [999999.9, 999999.9]
    assert parsed["extra"]["pipeline_depth"] == 99
    assert parsed["extra"]["cache_hit_rate"] == 1.0
    assert parsed["extra"]["cache_vs_device_ms"] == [99999.9999, 99999.9999]
    # the sharded serving parity record must survive compaction (ISSUE 3:
    # mesh pipelined-vs-serial qps, count-batch kernel-vs-lowered ms)
    assert parsed["extra"]["sharded_qps"] == [999999.9, 999999.9]
    assert parsed["extra"]["count_kernel_vs_lowered_ms"] == [
        99999.999, 99999.999,
    ]
    # the 256-client open-loop record must survive compaction (ISSUE 6:
    # ms/query, time-to-first-row, the adaptive window's reached depth)
    assert parsed["extra"]["open_loop_ms_per_query"] == 99999.999
    assert parsed["extra"]["time_to_first_row_ms"] == 99999.999
    assert parsed["extra"]["effective_depth"] == 999
    # the histogram-derived open-loop tail must survive compaction
    # (ISSUE 12: p99 from the obs log-bucket histogram layer; p50/p95
    # and the bucket vectors stay in the full record)
    assert parsed["extra"]["open_loop_p99_ms"] == 99999.999
    # the cost-based planner A/B must survive compaction (ISSUE 8: the
    # planner's chosen route, warm [planner, greedy] ms, and the
    # capacity-retry compiles the costed seeds eliminated)
    assert parsed["extra"]["planner_route"] == "fused_kernel"
    assert parsed["extra"]["planner_vs_greedy_ms"] == [99999.999, 99999.999]
    assert parsed["extra"]["retry_rounds_avoided"] == 999_999
    # the multiway join A/B must survive compaction (ISSUE 9: the
    # k-way route, warm [multiway, chain] ms, and the capacity-retry
    # compiles the exact intersection seed eliminated on the skew star)
    assert parsed["extra"]["multiway_route"] == "fused_multiway"
    assert parsed["extra"]["multiway_vs_chain_ms"] == [99999.999, 99999.999]
    assert parsed["extra"]["chain_retry_rounds_avoided"] == 999_999
    # the whole-tree fused A/B must survive compaction (ISSUE 10: the
    # whole-tree route, warm [fused, tree] ms, and the per-site
    # dispatch/settle round trips the one-program route eliminated)
    assert parsed["extra"]["tree_fused_route"] == "fused_tree"
    assert parsed["extra"]["tree_fused_vs_tree_ms"] == [99999.999, 99999.999]
    assert parsed["extra"]["tree_programs_avoided"] == 999_999
    # the chaos serving record must survive compaction (ISSUE 13:
    # degraded-qps ratio at a fixed injected fault rate + the breaker
    # recoveries the half-open probes achieved)
    assert parsed["extra"]["chaos_qps_ratio"] == 9.999
    assert parsed["extra"]["breaker_recoveries"] == 999_999
    # the program-ledger headline must survive compaction (ISSUE 14:
    # total XLA compile seconds; the decomposition stays in the full
    # record's `programs` snapshot + per-section fields)
    assert parsed["extra"]["compile_s"] == 99999.999
    # the durability headline must survive compaction (ISSUE 15:
    # verified warm-restore wall seconds; the rebuild arm, WAL replay
    # throughput and chaos-recovery wall time stay in the full record)
    assert parsed["extra"]["restore_s"] == 99999.999


def test_compact_headline_minimal_and_null_record():
    minimal = {"metric": "m", "value": 1, "unit": "ms", "vs_baseline": 2}
    line = json.dumps(bench.compact_headline(minimal, None))
    parsed = json.loads(line)
    assert parsed["extra"]["full_record"] is None
    assert parsed["extra"]["flybase"] is None
    assert len(line) < 1500
