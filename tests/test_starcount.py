"""Star-count route (query/starcount.py): closed-form degree-product
counts must equal the general join path and the host algebra on every
star-shaped conjunction the miner emits."""

import pytest

from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query import compiler, starcount
from das_tpu.query.ast import And, Link, Node, PatternMatchingAnswer, Variable
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB


@pytest.fixture(params=["host", "device"], autouse=True)
def star_fold_edition(request, monkeypatch):
    """Every case runs under BOTH fold editions: the host fold (sparse
    supports + symbolic whole-table terms) and the device degree-vector
    fold — they must be count-identical everywhere, including the
    reseed/empty-term quirks."""
    monkeypatch.setenv("DAS_TPU_STAR_FOLD", request.param)
    return request.param


@pytest.fixture(scope="module")
def bio_db():
    data, _, _ = build_bio_atomspace(
        n_genes=120, n_processes=10, members_per_gene=4,
        n_interactions=150, n_evaluations=30,
    )
    return TensorDB(data, DasConfig())


def _star(terms):
    return And(terms)


def _general_count(db, q, monkeypatch_env=None):
    """The same query through the general executors (star disabled)."""
    import os

    old = os.environ.get("DAS_TPU_STAR")
    os.environ["DAS_TPU_STAR"] = "0"
    try:
        return compiler.count_matches(db, q)
    finally:
        if old is None:
            del os.environ["DAS_TPU_STAR"]
        else:
            os.environ["DAS_TPU_STAR"] = old


def _host_count(db, q):
    host = MemoryDB(db.data)
    a = PatternMatchingAnswer()
    matched = q.matched(host, a)
    return len(a.assignments) if matched else 0


CASES = []


def _case(fn):
    CASES.append(fn)
    return fn


@_case
def _all_whole_table(db):
    return _star([
        Link("Member", [Variable("V0"), Variable("T0_V1")], True),
        Link("Interacts", [Variable("V0"), Variable("T1_V1")], True),
    ])


@_case
def _three_way(db):
    return _star([
        Link("Member", [Variable("V0"), Variable("T0_V1")], True),
        Link("Member", [Variable("V0"), Variable("T1_V1")], True),
        Link("Interacts", [Variable("V0"), Variable("T2_V1")], True),
    ])


@_case
def _structurally_identical_terms(db):
    # the diagonal counts too: ordered pairs of Member links per gene
    return _star([
        Link("Member", [Variable("V0"), Variable("A")], True),
        Link("Member", [Variable("V0"), Variable("B")], True),
    ])


@_case
def _with_grounded(db):
    procs = db.get_all_nodes("BiologicalProcess", names=True)
    return _star([
        Link("Member", [Variable("V0"), Node("BiologicalProcess", procs[0])], True),
        Link("Interacts", [Variable("V0"), Variable("T1_V1")], True),
    ])


@_case
def _two_probed(db):
    procs = db.get_all_nodes("BiologicalProcess", names=True)
    return _star([
        Link("Member", [Variable("V0"), Node("BiologicalProcess", procs[0])], True),
        Link("Member", [Variable("V0"), Node("BiologicalProcess", procs[1])], True),
        Link("Member", [Variable("V0"), Variable("T2_V1")], True),
    ])


@_case
def _shared_in_second_position(db):
    return _star([
        Link("Member", [Variable("T0_V1"), Variable("V0")], True),
        Link("Member", [Variable("T1_V1"), Variable("V0")], True),
    ])


@pytest.mark.parametrize("case", CASES, ids=lambda f: f.__name__)
def test_star_matches_general_and_host(bio_db, case):
    q = case(bio_db)
    plans = compiler.plan_query(bio_db, q)
    lane = starcount.plan_star(bio_db, plans)
    assert lane is not None, "case must be star-shaped"
    n_star = starcount.star_count_many(bio_db, [lane])[0]
    assert n_star == _general_count(bio_db, q)
    assert n_star == _host_count(bio_db, q)
    assert n_star > 0  # vacuous parity would prove nothing


def test_non_star_shapes_fall_through(bio_db):
    # path shape (two shared variables) must NOT take the star route
    q = And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])
    plans = compiler.plan_query(bio_db, q)
    assert starcount.plan_star(bio_db, plans) is None
    # single term is not a star either
    q1 = Link("Member", [Variable("V0"), Variable("V1")], True)
    assert starcount.plan_star(bio_db, compiler.plan_query(bio_db, q1)) is None


def test_count_matches_routes_star(bio_db):
    q = _three_way(bio_db)
    compiler.reset_route_counts()
    n = compiler.count_matches(bio_db, q)
    assert compiler.ROUTE_COUNTS["star"] == 1
    assert n == _host_count(bio_db, q)


@pytest.mark.full
def test_miner_equivalence_with_star_disabled(bio_db, monkeypatch):
    """mine() must produce identical results with and without the route."""
    from das_tpu.mining.miner import PatternMiner

    def run():
        miner = PatternMiner(bio_db, halo_length=2, link_rate=0.5, seed=11)
        genes = bio_db.get_all_nodes("Gene", names=True)[:2]
        seeds = [bio_db.get_node_handle("Gene", g) for g in genes]
        miner.expand_halo(seeds)
        miner.build_patterns()
        best = miner.mine(ngram=3, epochs=12)
        return (best.count, best.isurprisingness, best.term_handles) if best else None

    with_star = run()
    monkeypatch.setenv("DAS_TPU_STAR", "0")
    without = run()
    assert with_star == without and with_star is not None


def test_midfold_reseed_computed_in_program(bio_db):
    """A DISJOINT join in the middle of the fold fires the reference's
    reseed quirk — the in-program fold must reproduce the reseeded answer
    exactly (no general-path fallback)."""
    procs = bio_db.get_all_nodes("BiologicalProcess", names=True)
    genes = bio_db.get_all_nodes("Gene", names=True)
    q = _star([
        # V0 = genes in procs[0]
        Link("Member", [Variable("V0"), Node("BiologicalProcess", procs[0])], True),
        # V0 = processes of genes[0] — disjoint domain; join 2 empties
        Link("Member", [Node("Gene", genes[0]), Variable("V0")], True),
        Link("Member", [Variable("T2_V1"), Variable("V0")], True),
    ])
    plans = compiler.plan_query(bio_db, q)
    lane = starcount.plan_star(bio_db, plans)
    assert lane is not None
    n_host = _host_count(bio_db, q)
    assert n_host > 0  # the quirk actually fired here
    assert starcount.star_count_many(bio_db, [lane]) == [n_host]
    assert compiler.count_matches(bio_db, q) == n_host


def test_final_join_zero_is_certified(bio_db):
    """The FINAL join emptying leaves no term to reseed from — the
    reference answers 0 too, and the cascade certifies it without the
    general path (prefixes nonempty, last total zero)."""
    procs = bio_db.get_all_nodes("BiologicalProcess", names=True)
    genes = bio_db.get_all_nodes("Gene", names=True)
    q = _star([
        Link("Member", [Variable("V0"), Node("BiologicalProcess", procs[0])], True),
        Link("Member", [Variable("V0"), Variable("T1_V1")], True),
        # disjoint only at the LAST fold step
        Link("Member", [Node("Gene", genes[0]), Variable("V0")], True),
    ])
    plans = compiler.plan_query(bio_db, q)
    lane = starcount.plan_star(bio_db, plans)
    assert lane is not None
    assert starcount.star_count_many(bio_db, [lane]) == [0]
    assert _host_count(bio_db, q) == 0


def test_two_term_disjoint_is_exact_zero(bio_db):
    """With n=2 a disjoint join IS the final join: exact 0, no decline."""
    procs = bio_db.get_all_nodes("BiologicalProcess", names=True)
    genes = bio_db.get_all_nodes("Gene", names=True)
    q = _star([
        Link("Member", [Variable("V0"), Node("BiologicalProcess", procs[0])], True),
        Link("Member", [Node("Gene", genes[0]), Variable("V0")], True),
    ])
    lane = starcount.plan_star(bio_db, compiler.plan_query(bio_db, q))
    assert starcount.star_count_many(bio_db, [lane]) == [0]
    assert _host_count(bio_db, q) == 0


def test_empty_positive_term_is_exact_zero(bio_db):
    """A term with ZERO matching rows makes the reference And fail
    outright (Link.matched is False before any join/reseed) — the guard
    must answer 0 even though the fold would reseed past it."""
    genes = bio_db.get_all_nodes("Gene", names=True)
    # find a gene with no outgoing Interacts: its grounded term is empty
    for g in genes:
        probe = _star([
            Link("Interacts", [Node("Gene", g), Variable("V0")], True),
            Link("Member", [Variable("V0"), Variable("T1_V1")], True),
        ])
        plans = compiler.plan_query(bio_db, probe)
        lane = starcount.plan_star(bio_db, plans)
        host = _host_count(bio_db, probe)
        assert starcount.star_count_many(bio_db, [lane]) == [host]
        if host == 0:
            # found the empty-term case and the guard answered it
            a = compiler.count_matches(bio_db, probe)
            assert a == 0
            return
    pytest.skip("every gene interacts; KB too dense for the empty case")


def test_missing_bucket_term_is_exact_zero(bio_db):
    """A term whose (arity, type) bucket does not exist at all (unknown
    arity) short-circuits to 0 before any dispatch."""
    q = _star([
        Link("Member", [Variable("V0"), Variable("A"), Variable("B"),
                        Variable("C"), Variable("D"), Variable("E")], True),
        Link("Member", [Variable("V0"), Variable("F")], True),
    ])
    plans = compiler.plan_query(bio_db, q)
    if plans is None:
        pytest.skip("6-ary plan declined upstream")
    lane = starcount.plan_star(bio_db, plans)
    assert lane is not None
    assert starcount.star_count_many(bio_db, [lane]) == [0]
    assert _host_count(bio_db, q) == 0


def test_deg_cache_stale_length_after_mixed_arity_commit():
    """A commit that grows atom_count while leaving one arity's bucket
    untouched must not serve that arity's cached degree vector at the old
    length (the fold would shape-mismatch or undercount)."""
    from das_tpu.storage.atom_table import AtomSpaceData, load_metta_text

    text = "\n".join(
        ["(: Concept Type)", "(: List Type)", "(: Pair Type)"]
        + [f'(: "c{i}" Concept)' for i in range(6)]
        + [f'(List "c{i}")' for i in range(6)]
        + [f'(Pair "c{i}" "c{(i + 1) % 6}")' for i in range(6)]
    )
    db = TensorDB(load_metta_text(text), DasConfig())
    q = _star([
        Link("List", [Variable("V0")], True),
        Link("Pair", [Variable("V0"), Variable("A")], True),
    ])
    lane = starcount.plan_star(db, compiler.plan_query(db, q))
    assert lane is not None
    before = starcount.star_count_many(db, [lane])[0]
    assert before == _host_count(db, q) > 0
    # commit: new node + arity-2 link ONLY — the arity-1 bucket object
    # survives while atom_count grows
    load_metta_text(
        '(: "c_new" Concept)\n(Pair "c_new" "c0")', db.data
    )
    db.refresh()
    lane2 = starcount.plan_star(db, compiler.plan_query(db, q))
    after = starcount.star_count_many(db, [lane2])[0]
    assert after == _host_count(db, q)


def test_deg_cache_invalidates_on_commit(bio_db, star_fold_edition):
    """An incremental commit swaps buckets; the cached degree vectors must
    not serve stale counts.  (bio_db is module-scoped and both fold
    editions run against it — the commit names carry the edition so the
    second run's delta is not a dedup no-op.)"""
    from das_tpu.storage.atom_table import load_metta_text

    q = _star([
        Link("Interacts", [Variable("V0"), Variable("A")], True),
        Link("Interacts", [Variable("V0"), Variable("B")], True),
    ])
    before = compiler.count_matches(bio_db, q)
    tag = star_fold_edition
    commit = "\n".join(
        [f'(: "SGX_{tag}_{i}" Gene)' for i in range(3)]
        + [f'(Interacts "SGX_{tag}_0" "SGX_{tag}_1")',
           f'(Interacts "SGX_{tag}_0" "SGX_{tag}_2")']
    )
    load_metta_text(commit, bio_db.data)
    bio_db.refresh()
    after = compiler.count_matches(bio_db, q)
    assert after == _host_count(bio_db, q)
    assert after > before


def test_dangling_whole_table_term_matches_dense_edition(monkeypatch):
    """A whole-table term whose rows dangle at the shared position must
    contribute the DENSE degree sum (danglings excluded), not the raw row
    count: the symbolic total feeds the empty-positive-term guard and any
    reseed landing on the term.  Both fold editions must agree."""
    import numpy as np

    from das_tpu.storage.atom_table import LinkRec, load_metta_text

    data = load_metta_text(
        "\n".join(
            ["(: Rel Type)", "(: Tab Type)", "(: Concept Type)"]
            + [f'(: "c{i}" Concept)' for i in range(4)]
            + ['(Rel "c0" "c1")', '(Rel "c0" "c2")', '(Tab "c3" "c0")']
        )
    )
    # forge Tab links dangling at position 0 (the shared-variable side)
    tab = next(rec for rec in data.links.values() if rec.named_type == "Tab")
    for i in range(2):
        data.links[f"{i:x}" * 32] = LinkRec(
            named_type=tab.named_type,
            named_type_hash=tab.named_type_hash,
            composite_type=tab.composite_type,
            composite_type_hash=tab.composite_type_hash,
            elements=("e" * 31 + str(i), tab.elements[1]),  # ghost col 0
            is_toplevel=True,
        )
    db = TensorDB(data)
    assert db.fin.dangling_hexes
    # star lane: two probed terms with an empty product, then the Tab
    # whole-table term sharing V0 at its DANGLING position — the reseed
    # lands on the symbolic table term
    q = _star([
        Link("Rel", [Node("Concept", "c0"), Variable("V0")], True),
        Link("Rel", [Variable("V0"), Node("Concept", "c1")], True),
        Link("Tab", [Variable("V0"), Variable("T2_V1")], True),
    ])
    plans = compiler.plan_query(db, q)
    lane = starcount.plan_star(db, plans)
    assert lane is not None
    monkeypatch.setenv("DAS_TPU_STAR_FOLD", "host")
    n_host = starcount.star_count_many(db, [lane])[0]
    monkeypatch.setenv("DAS_TPU_STAR_FOLD", "device")
    db._star_deg_cache = {}
    n_dev = starcount.star_count_many(db, [lane])[0]
    assert n_host == n_dev, (n_host, n_dev)
    # the dense degree sum of Tab at position 0 is 1 (only the real link);
    # the raw row count is 3 — a reseed returning the raw count would
    # answer 3 here
    assert n_host == 1


def test_skewed_kb_star_counts_match_host(monkeypatch):
    """Power-law (hub-heavy) degree profile — the shape of real
    annotation data (VERDICT r03 weak #7): the star fold and the device
    paths stay exact when one process hub dominates Member and one gene
    hub dominates Interacts."""
    import numpy as np

    from das_tpu.models.bio import build_bio_atomspace

    data, genes, procs = build_bio_atomspace(
        n_genes=400, n_processes=60, members_per_gene=4,
        n_interactions=500, n_evaluations=0, seed=5, skew=1.5,
    )
    db = TensorDB(data, DasConfig())
    # the profile is actually skewed: top process degree >> median
    b = db.fin.buckets[2]
    member_tid = None
    for h, tid in db.fin.type_id_of_hash.items():
        if db.fin.type_names[tid] == "Member":
            member_tid = tid
    col = b.targets[b.type_id == member_tid, 1]
    degs = np.bincount(col, minlength=db.fin.atom_count)
    assert degs.max() >= 8 * max(1, int(np.median(degs[degs > 0])))

    q = _star([
        Link("Member", [Variable("V0"), Node("BiologicalProcess", "GO:0000000")], True),
        Link("Member", [Variable("V0"), Variable("T1_V1")], True),
        Link("Interacts", [Variable("V0"), Variable("T2_V1")], True),
    ])
    plans = compiler.plan_query(db, q)
    lane = starcount.plan_star(db, plans)
    assert lane is not None
    n = starcount.star_count_many(db, [lane])[0]
    assert n == _host_count(db, q) > 0


def test_evict_oldest_is_fifo_and_partial():
    """ADVICE r4: cache eviction keeps the newest entries of the matching
    class (FIFO over dict insertion order) instead of wiping the class,
    and never touches non-matching keys."""
    cache = {}
    for i in range(300):
        cache[("sparse", i)] = i
    cache[("dense", 0)] = "keep"
    starcount._evict_oldest(cache, lambda k: k[0] == "sparse", 192)
    sparse_left = [k for k in cache if k[0] == "sparse"]
    assert len(sparse_left) == 192
    # the SURVIVORS are the newest 192, in original order
    assert sparse_left == [("sparse", i) for i in range(108, 300)]
    assert cache[("dense", 0)] == "keep"
