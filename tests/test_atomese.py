"""Atomese (.scm) parser behavior."""

import pytest

from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.expression import Expression
from das_tpu.ingest.atomese import AtomeseParser
from das_tpu.storage.atom_table import AtomSpaceData
from das_tpu.storage.memory_db import MemoryDB

SCM = """
; a comment line
(InheritanceLink (ConceptNode "Allen") (ConceptNode "human"))
(SimilarityLink (stv 0.9 0.8) (ConceptNode "Allen") (ConceptNode "Bob"))
(EvaluationLink
    (PredicateNode "likes")
    (ListLink (ConceptNode "Allen") (ConceptNode "Bob")))
"""


def load_scm(text):
    data = AtomSpaceData()
    typedefs, terminals, regular = [], [], []
    parser = AtomeseParser(
        symbol_table=data.table,
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_expression=regular.append,
        on_toplevel=regular.append,
    )
    assert parser.parse(text) == "SUCCESS"
    for e in typedefs:
        data.add_typedef(e)
    for e in terminals:
        data.add_terminal(e)
    for e in regular:
        data.add_link(e)
    return data


def test_node_naming_and_type_suffix_stripping():
    data = load_scm(SCM)
    db = MemoryDB(data)
    # ConceptNode "Allen" -> terminal "Concept:Allen" of type Concept
    assert db.node_exists("Concept", "Concept:Allen")
    assert db.node_exists("Concept", "Concept:Bob")
    assert db.node_exists("Predicate", "Predicate:likes")
    nodes, links = data.count_atoms()
    assert nodes == 4  # Allen, human, Bob, likes
    # Inheritance, Similarity, Evaluation toplevel + nested List
    assert links == 4


def test_stv_skipped_and_hash_parity():
    data = load_scm(SCM)
    allen = ExpressionHasher.terminal_hash("Concept", "Concept:Allen")
    bob = ExpressionHasher.terminal_hash("Concept", "Concept:Bob")
    sim = ExpressionHasher.expression_hash(
        ExpressionHasher.named_type_hash("Similarity"), [allen, bob]
    )
    assert sim in data.links


def test_auto_typedefs():
    data = load_scm(SCM)
    # every type + every node generated a typedef record
    names = {t.name for t in data.typedefs.values()}
    assert {"Concept", "Inheritance", "Similarity", "Evaluation", "Predicate",
            "List", "Concept:Allen", "Type"} <= names


def test_reference_sample_file():
    import os

    path = "/root/reference/data/samples/toy-example-mining.scm"
    if not os.path.exists(path):
        pytest.skip("reference sample not available")
    with open(path) as fh:
        data = load_scm(fh.read())
    nodes, links = data.count_atoms()
    assert nodes == 25
    assert links == 60
    db = MemoryDB(data)
    assert db.node_exists("Concept", "Concept:human")
