"""Atomese (.scm) parser behavior."""

import pytest

from das_tpu.core.hashing import ExpressionHasher
from das_tpu.core.expression import Expression
from das_tpu.ingest.atomese import AtomeseParser
from das_tpu.storage.atom_table import AtomSpaceData
from das_tpu.storage.memory_db import MemoryDB

SCM = """
; a comment line
(InheritanceLink (ConceptNode "Allen") (ConceptNode "human"))
(SimilarityLink (stv 0.9 0.8) (ConceptNode "Allen") (ConceptNode "Bob"))
(EvaluationLink
    (PredicateNode "likes")
    (ListLink (ConceptNode "Allen") (ConceptNode "Bob")))
"""


def load_scm(text):
    data = AtomSpaceData()
    typedefs, terminals, regular = [], [], []
    parser = AtomeseParser(
        symbol_table=data.table,
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_expression=regular.append,
        on_toplevel=regular.append,
    )
    assert parser.parse(text) == "SUCCESS"
    for e in typedefs:
        data.add_typedef(e)
    for e in terminals:
        data.add_terminal(e)
    for e in regular:
        data.add_link(e)
    return data


def test_node_naming_and_type_suffix_stripping():
    data = load_scm(SCM)
    db = MemoryDB(data)
    # ConceptNode "Allen" -> terminal "Concept:Allen" of type Concept
    assert db.node_exists("Concept", "Concept:Allen")
    assert db.node_exists("Concept", "Concept:Bob")
    assert db.node_exists("Predicate", "Predicate:likes")
    nodes, links = data.count_atoms()
    assert nodes == 4  # Allen, human, Bob, likes
    # Inheritance, Similarity, Evaluation toplevel + nested List
    assert links == 4


def test_stv_skipped_and_hash_parity():
    data = load_scm(SCM)
    allen = ExpressionHasher.terminal_hash("Concept", "Concept:Allen")
    bob = ExpressionHasher.terminal_hash("Concept", "Concept:Bob")
    sim = ExpressionHasher.expression_hash(
        ExpressionHasher.named_type_hash("Similarity"), [allen, bob]
    )
    assert sim in data.links


def test_auto_typedefs():
    data = load_scm(SCM)
    # every type + every node generated a typedef record
    names = {t.name for t in data.typedefs.values()}
    assert {"Concept", "Inheritance", "Similarity", "Evaluation", "Predicate",
            "List", "Concept:Allen", "Type"} <= names


def test_reference_sample_file():
    import os

    path = "/root/reference/data/samples/toy-example-mining.scm"
    if not os.path.exists(path):
        pytest.skip("reference sample not available")
    with open(path) as fh:
        data = load_scm(fh.read())
    nodes, links = data.count_atoms()
    assert nodes == 25
    assert links == 60
    db = MemoryDB(data)
    assert db.node_exists("Concept", "Concept:human")


def _reference_lex_test_data() -> str:
    """The reference's own fixture (atomese_lex_test.py:4-30), extracted
    from the source file at runtime (the module itself imports PLY-bound
    code and cannot be imported)."""
    import ast as pyast
    import os

    path = "/root/reference/das/atomese_lex_test.py"
    if not os.path.exists(path):
        pytest.skip("reference checkout not available")
    src = open(path).read()
    for node in pyast.walk(pyast.parse(src)):
        if (
            isinstance(node, pyast.Assign)
            and any(
                getattr(t, "id", None) == "lex_test_data"
                for t in node.targets
            )
        ):
            return pyast.literal_eval(node.value)
    raise AssertionError("lex_test_data not found in reference file")


def test_reference_action_broker_counts():
    """Case-for-case port of atomese_yacc_test.py:34-61: on the
    reference's own fixture, the parse actions fire EXACTLY 11 terminals,
    7 nested expressions, 4 toplevel expressions, and 10 + 11 typedefs
    (one per distinct type + one auto-typedef per terminal)."""
    text = _reference_lex_test_data()
    data = AtomSpaceData()
    typedefs, terminals, nested, toplevel = [], [], [], []
    parser = AtomeseParser(
        symbol_table=data.table,
        on_typedef=typedefs.append,
        on_terminal=terminals.append,
        on_expression=nested.append,
        on_toplevel=toplevel.append,
    )
    assert parser.parse(text) == "SUCCESS"
    assert len(terminals) == 11
    assert len(nested) == 7
    assert len(toplevel) == 4
    assert len(typedefs) == 10 + len(terminals)


def test_reference_check_mode_no_side_effects():
    """atomese_yacc_test.py:29-43 check() path: a syntax check fires no
    terminal/expression actions and leaves no atoms behind."""
    text = _reference_lex_test_data()
    data = AtomSpaceData()
    terminals, nested, toplevel = [], [], []
    parser = AtomeseParser(
        symbol_table=data.table,
        on_terminal=terminals.append,
        on_expression=nested.append,
        on_toplevel=toplevel.append,
    )
    assert parser.check(text) == "SUCCESS"
    assert terminals == [] and nested == [] and toplevel == []
    assert data.count_atoms() == (0, 0)
