"""DL010 good fixture: dispatch reaches only transfer-free helpers;
the host transfer lives in the settle half, where it belongs."""

import numpy as np


def _shape_caps(caps):
    return tuple(max(int(c), 16) for c in caps)


def _fetch(outs):
    return np.asarray(outs)  # settle-side: legitimate


class _ExecJob:
    def dispatch(self):
        caps = _shape_caps((16, 32))
        return caps

    def settle(self, host_out, dev_out):
        return _fetch(dev_out) is not None


def dispatch_many(jobs):
    return [_shape_caps(j) for j in jobs]
