"""DL014 bad fixture: an undeclared span literal, an undeclared
histogram literal, and a stale registry entry nothing records."""

from das_tpu import obs

SPAN_NAMES = (
    "serve.fetch",
    "serve.retired",  # stale: no recording site uses it
)

COUNTER_NAMES = ("serve.fetches",)

HISTOGRAM_NAMES = ("serve.fetch_ms",)


def fetch(job):
    with obs.span("serve.fetch"):
        out = job.run()
    obs.counter("serve.fetches").inc()
    obs.histogram("serve.fetch_ms").observe(out.ms)
    # typo'd span name: records into a lane no dashboard reads
    obs.event("serve.fetchh", rows=out.rows)
    # undeclared histogram: the percentile headline never sees it
    obs.histogram("serve.rows_ms").observe(out.ms)
    return out
