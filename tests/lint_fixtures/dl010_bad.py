"""DL010 bad fixture: the dispatch half is SYNTACTICALLY clean (DL001
passes) but reaches a host transfer through two repo-local helper hops
— the silent-serialization refactor the call-graph rule exists for."""

import numpy as np


def _summarize(outs):
    # innocent-looking indirection: one more hop hides the sync
    return _to_host(outs)


def _to_host(outs):
    return np.asarray(outs)  # blocks on the device value


class _ExecJob:
    def dispatch(self):
        outs = object()
        return _summarize(outs)

    def settle(self, host_out, dev_out):
        return True


def dispatch_many(jobs):
    return [_summarize(j) for j in jobs]
