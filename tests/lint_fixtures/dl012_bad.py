"""DL012 bad fixture: a per-request dict reaching a traced closure,
and a jitted program constructed with no reviewable cache keying."""

import jax

PROGRAMS = []


def build_leaky(sig, opts: dict):
    # builder by name, but the dict closes into the traced fn: its
    # content changes per request and keys nothing
    def fn(x):
        return x * opts["scale"]

    return jax.jit(fn)


def handle_request(payload):
    # neither returned, called here, nor stored under a cache key —
    # a fresh executable per request
    fn = jax.jit(lambda x: x + 1)
    PROGRAMS.append(fn)
