"""DL005 bad: a kernel body grew a scratch Ref the byte-model manifest
(and therefore the VMEM byte model) never priced, plus a stale entry."""

KERNEL_BUFFERS = {
    "dl005_bad._probe_body": ("keys_ref", "vals_ref", "cnt_ref"),
    "dl005_bad._retired_body": ("gone_ref",),      # matches nothing
}


def _probe_body(capacity):
    def kernel(keys_ref, scratch_ref, vals_ref, cnt_ref):
        # scratch_ref is VMEM the model never accounted for
        scratch_ref[:] = keys_ref[:]
        vals_ref[:] = scratch_ref[:]
        cnt_ref[0] = capacity

    return kernel


def _unlisted_body():
    def kernel(in_ref, out_ref):
        out_ref[:] = in_ref[:]

    return kernel
