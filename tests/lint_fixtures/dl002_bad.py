"""DL002 bad: routing reads a field the plan signature never declared,
the sig is mutable, and one field opts out of the cache key."""

from dataclasses import dataclass, field
from typing import Optional, Tuple


@dataclass
class MutablePlanSig:            # not frozen: unhashable-by-value key
    terms: Tuple[int, ...]


@dataclass(frozen=True)
class LeakyPlanSig:
    terms: Tuple[int, ...]
    term_caps: Tuple[int, ...]
    use_kernels: bool = False
    # a routing input excluded from __eq__/__hash__: cache poisoning
    vmem_budget: int = field(default=0, compare=False)


def build_leaky(sig: LeakyPlanSig, count_only: bool = False):
    if sig.use_kernels and sig.tiled:    # `tiled` was never declared
        return ("tiled", sig.terms)
    if getattr(sig, "chunk_rows", 0):    # default hides the omission
        return ("chunked", sig.terms)
    return ("single", sig.term_caps)


def maybe_build(sig: Optional[LeakyPlanSig]):
    # Optional wrapping must not lose the read check
    return None if sig is None else sig.chunk_rows


def make(terms, caps):
    # constructor drift: 4 positional args for 4 fields is fine, but an
    # unknown keyword means the field was deleted out from under a caller
    return LeakyPlanSig(terms, caps, use_kernels=True, tiled=True)


def make_qualified(mod, terms, caps):
    # module-qualified construction gets the same keyword check
    return mod.LeakyPlanSig(terms, caps, chunk=4)
