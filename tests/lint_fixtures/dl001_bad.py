"""DL001 bad: host syncs inside dispatch-path functions."""

import numpy as np


class _Job:
    def dispatch(self):
        out = self.fn(self.args)
        self.peek = int(out[0])          # device coercion: blocks
        return out

    def settle(self, host, out):
        return True


class _CtorJob:
    def __init__(self, db, queries):
        self.pending = np.asarray(db.enqueue(queries))  # transfer at dispatch

    def settle(self):
        return self.pending


def dispatch_many(jobs):
    outs = [j.dispatch() for j in jobs]
    return [o.item() for o in outs]      # .item() syncs every job


def execute_many_dispatch(db, plans):
    import jax

    handle = db.enqueue(plans)
    jax.device_get(handle)               # the settle half's job
    return handle
