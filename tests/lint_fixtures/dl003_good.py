"""DL003 good: reads <-> registry agree; the external name is declared."""

import os

ENV_REGISTRY = {
    "DAS_TPU_FIXTURE_KNOWN": (None, "a declared flag"),
    "DAS_TPU_FIXTURE_SUBSCRIPT": (None, "read via os.environ[...]"),
    "DAS_TPU_FIXTURE_EXTERNAL": (None, "read by an out-of-tree harness"),
}

ENV_DECLARED_EXTERNAL = ("DAS_TPU_FIXTURE_EXTERNAL",)


def flags():
    known = os.environ.get("DAS_TPU_FIXTURE_KNOWN", "0")
    sub = os.environ["DAS_TPU_FIXTURE_SUBSCRIPT"]
    return known, sub
