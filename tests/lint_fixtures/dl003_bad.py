"""DL003 bad: an env read the registry never declared, and a registered
flag nothing reads."""

import os

ENV_REGISTRY = {
    "DAS_TPU_FIXTURE_KNOWN": (None, "a declared flag"),
    "DAS_TPU_FIXTURE_DEAD": (None, "declared but read by nothing"),
}


def flags():
    known = os.environ.get("DAS_TPU_FIXTURE_KNOWN", "0")
    mystery = os.environ.get("DAS_TPU_FIXTURE_MYSTERY")   # undeclared
    return known, mystery
