"""DL011 bad fixture: every Mosaic-readiness hazard in one module —
an unaligned chunk_rows_for / StagePlan emission, a kernel body with
python control flow on a traced value, a raw ref handed to jnp, and a
float64 cast."""

import jax.numpy as jnp

ROUTE_TILED = "tiled"

MIN_CHUNK_ROWS = 1000  # not a multiple of the 128-lane tiling


class StagePlan:
    def __init__(self, route, chunk_rows, resident, block):
        self.route = route
        self.chunk_rows = chunk_rows


def chunk_rows_for(row_bytes, capacity, budget):
    # raw division: nothing rounds to the (8,128) tiling
    return max(budget // 4 // max(row_bytes, 1), 1)


def plan(resident, per_row, capacity, budget):
    chunk = max(capacity // 7, MIN_CHUNK_ROWS)
    return StagePlan(ROUTE_TILED, chunk, resident, per_row * chunk)


def _kernel_body(capacity):
    def kernel(vals_ref, mask_ref, out_ref):
        vals = vals_ref[:]
        count = mask_ref[0]
        if count > 0:  # python branch on a traced value
            vals = vals + 1
        wide = vals.astype(jnp.float64)  # unpriced dtype
        out_ref[:] = jnp.sum(mask_ref)  # raw ref handed to jnp
        return wide

    return kernel
