"""DL016 good fixture: every program-construction scope declared in
PROGRAM_SITES — instrumented scopes carry their ledger hook with the
declared label, exempt scopes carry None."""

import jax

from das_tpu.obs import proflog

PROGRAM_SITES = {
    "dl016_good.build_program": "prog",
    "dl016_good.launch_block": "blk",
    "dl016_good._tiny_op": None,
}


def build_program(sig):
    def fn(x):
        return x + 1

    return proflog.instrument(
        "prog", proflog.sig_digest(sig), jax.jit(fn)
    )


def launch_block(body, shapes, inputs):
    from jax.experimental import pallas as pl

    t0 = proflog.launch_mark()
    out = pl.pallas_call(body, out_shape=shapes)(*inputs)
    proflog.record_launch("blk", body, shapes, t0, pallas=True)
    return out


@jax.jit
def _tiny_op(x):
    return x * 2
