"""DL009 bad fixture: a collective outside every declared scope, plus a
stale registry entry pointing at a helper with no collective left."""

from jax import lax

SHARD_AXIS = "shards"

#: declares ONE legitimate helper and ONE stale entry
COLLECTIVE_SITES = (
    "dl009_bad._gather_helper",
    "dl009_bad._stale_helper",
)


def _gather_helper(vals):
    # declared: fine
    return lax.all_gather(vals, SHARD_AXIS, tiled=True)


def _stale_helper(vals):
    # declared but the collective is gone — stale registry entry
    return vals + 1


def shard_local_body(vals, mask):
    # UNDECLARED scope: a psum smuggled into a shard-local body — the
    # cross-shard byte leaves the reviewable COLLECTIVE_SITES list
    return lax.psum(mask.sum(), SHARD_AXIS)
