"""DL002 good: every routing input is a declared, hashed field."""

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class TightPlanSig:
    terms: Tuple[int, ...]
    term_caps: Tuple[int, ...]
    use_kernels: bool = False
    tiled: bool = False
    vmem_budget: int = 0

    def describe(self) -> str:           # methods are fine to call
        return f"{len(self.terms)} terms"


def build_tight(sig: TightPlanSig, count_only: bool = False):
    if sig.use_kernels and sig.tiled:
        return ("tiled", sig.vmem_budget, sig.describe())
    if getattr(sig, "use_kernels", False):
        return ("kernel", sig.terms)
    return ("single", sig.term_caps)


def make(terms, caps):
    return TightPlanSig(terms, caps, use_kernels=True, tiled=False)
