"""DL004 bad: counting sites using keys the registry never declared,
a dead registry key, and a dict literal drifting from the registry."""

DISPATCH_KEYS = ("fixture_kernel", "fixture_dead")
ROUTE_KEYS = ("fixture_fused",)

# drifted literal: missing fixture_dead, smuggles fixture_extra
DISPATCH_COUNTS = {"fixture_kernel": 0, "fixture_extra": 0}
ROUTE_COUNTS = {k: 0 for k in ROUTE_KEYS}


def record_dispatch(kind, n=1):
    DISPATCH_COUNTS[kind] = DISPATCH_COUNTS.get(kind, 0) + n


def run(route_ok):
    record_dispatch("fixture_kernel")
    record_dispatch("fixture_kernal")        # the canonical typo
    route = "fixture_fused" if route_ok else "fixture_mystery"
    ROUTE_COUNTS[route] += 1                 # resolves both literals
