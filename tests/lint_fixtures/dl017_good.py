"""DL017 good fixture: every persist write flows through the declared
atomic writers, fsync-before-rename held, no stale registry entries,
reads stay free."""

import json
import os

import numpy as np

PERSIST_SITES = ("atomic_write", "Log.append")


def atomic_write(path, writer):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        writer(f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class Log:
    def __init__(self, path):
        self.path = path

    def append(self, payload):
        with open(self.path, "ab") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())


def save_sections(path, arrays, manifest):
    # handing the atomic writer's file object to np.savez is the
    # approved route — only PATH-taking savez bypasses the helper
    atomic_write(path + ".npz", lambda f: np.savez(f, **arrays))
    atomic_write(
        path + ".json", lambda f: f.write(json.dumps(manifest).encode())
    )


def load_sections(path):
    with open(path + ".json") as f:  # reads are free
        return json.load(f)
