"""DL012 good fixture: the blessed idioms — module-level statics, the
frozen-*Sig builder, the keyed-cache store, and construct-and-call."""

from dataclasses import dataclass
from functools import partial

import jax

_CACHE = {}


@dataclass(frozen=True)
class LeanPlanSig:
    capacity: int
    tiled: bool


@partial(jax.jit, static_argnames=("capacity",))
def probe(x, *, capacity):
    return x[:capacity]


def build_program(sig: LeanPlanSig, count_only: bool = False):
    def fn(x):
        y = x[: sig.capacity]
        return y.sum() if count_only else y

    return jax.jit(fn)


def cached_program(sig: LeanPlanSig):
    entry = _CACHE.get(sig)
    if entry is None:
        entry = jax.jit(lambda x: x[: sig.capacity])
        _CACHE[sig] = entry
    return entry


def run_once(x, mesh):
    fn = jax.jit(lambda v: v + 1)
    return fn(x)  # constructed and consumed in place — no stale keying
