"""DL016 bad fixture: an undeclared program-construction scope, a
declared-instrumented scope missing its ledger hook, an undeclared hook
label, a stale PROGRAM_SITES entry, an import-time compile, and a
bare-name `jit` in an undeclared scope."""

import jax
from jax import jit

from das_tpu.obs import proflog

PROGRAM_SITES = {
    "dl016_bad.build_uninstrumented": "prog",
    "dl016_bad.retired_builder": "old",  # stale: no jit lives there
}


def build_uninstrumented(sig):
    # declared with label "prog" but no instrument("prog", ...) call —
    # the ledger coverage the registry promises does not exist
    def fn(x):
        return x + 1

    return jax.jit(fn)


def surprise_builder(sig):
    # undeclared scope constructing a program: its compiles go dark
    def fn(x):
        return x - 1

    return proflog.instrument(
        # and the label is undeclared too — records into a lane nobody
        # aggregates
        "typo_site", proflog.sig_digest(sig), jax.jit(fn)
    )


def bare_name_builder(fn):
    # a `from jax import jit` binding is still program construction —
    # the bare name must not slip past the registry
    return jit(fn)


# import-time compile: fires unconditionally, no declarable scope
TOP_PROGRAM = jax.jit(lambda x: x)
