"""DL005 good: manifest and kernel-body signatures agree exactly."""

KERNEL_BUFFERS = {
    "dl005_good._probe_body": ("keys_ref", "vals_ref", "cnt_ref"),
    "dl005_good._tiled_probe_body": ("keys_ref", "vals_ref", "cnt_ref"),
}


def _probe_body(capacity):
    def kernel(keys_ref, vals_ref, cnt_ref):
        vals_ref[:] = keys_ref[:]
        cnt_ref[0] = capacity

    return kernel


def _tiled_probe_body(chunk):
    def kernel(g, keys_ref, vals_ref, cnt_ref):   # grid index g: not a ref
        vals_ref[:] = keys_ref[:]
        cnt_ref[0] = g * chunk

    return kernel
