"""DL001 good: dispatch halves stay asynchronous; settle transfers."""

import numpy as np


class _Job:
    def dispatch(self):
        return self.fn(self.args)        # enqueue only, no host sync

    def settle(self, host, out):
        stats = np.asarray(host)         # settle MAY transfer
        self.count = int(stats[0])
        return True


def dispatch_many(jobs):
    return [j.dispatch() for j in jobs]


def settle_many(pending):
    import jax

    fetched = jax.device_get(tuple(pending))   # the one settle transfer
    return [float(x[0]) for x in fetched]


def dispatch(db, query, answer):
    # a bare module-level `dispatch` is the per-query ROUTER, not a
    # device-dispatch half — host work here is legitimate (unscanned)
    return int(db.run(query))
