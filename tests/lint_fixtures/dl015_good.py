"""DL015 good fixture: every maybe_fail literal declared in FAULT_SITES,
injection only at host-side recovery seams (never in a dispatch half or
under das_tpu/kernels/)."""

from das_tpu import fault

FAULT_SITES = (
    "settle_seam",
    "commit_seam",
)


def settle_rounds(outs):
    fault.maybe_fail("settle_seam")
    return list(outs)


class Store:
    def apply_commit(self, staged):
        fault.maybe_fail("commit_seam")
        for swap in staged:
            swap()
