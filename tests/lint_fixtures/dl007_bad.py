"""DL007 bad: result-cache inserts that skip or defeat the
delta_version guard — a commit racing dispatch→settle poisons these."""


class Executor:
    def finish(self, key, result):
        # no version argument at all: the insert lands unconditionally,
        # silently undoing a racing commit's invalidation
        self.results.put(key, result)
        # version computed AT INSERT TIME: reads the post-commit version
        # for a pre-commit answer — guarded-looking, unguarded
        self.results.put(key, result, self.results.version())

    def finish_tree(self, cache, key, entry):
        cache.put(key, entry, version=cache.version())
