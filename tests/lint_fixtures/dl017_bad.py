"""DL017 bad fixture: persist writes bypassing the atomic helpers.

Declares its own PERSIST_SITES (the DL015 fixture idiom) so the module
is a persist scope.  Expected findings:
  * `sneaky_save` — bare write-mode open() outside PERSIST_SITES;
  * `save_arrays` — np.savez handed a PATH outside PERSIST_SITES;
  * `swap_in` — os.replace outside PERSIST_SITES;
  * `writer` — declared site renaming with NO earlier os.fsync;
  * `ghost` — stale PERSIST_SITES entry (no such writer exists).
"""

import os

import numpy as np

PERSIST_SITES = ("writer", "ghost")


def writer(path, payload):
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
    os.replace(tmp, path)  # no fsync first: lost on power cut


def sneaky_save(path, payload):
    with open(path, "w") as f:
        f.write(payload)


def save_arrays(path, arrays):
    np.savez(path + ".npz", **arrays)


def swap_in(tmp, path):
    os.replace(tmp, path)
