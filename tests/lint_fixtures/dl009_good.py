"""DL009 good fixture: every collective lives in a declared lowered
helper (nested closure bodies charge to the OUTERMOST function), and
every declared scope still contains one."""

from jax import lax

SHARD_AXIS = "shards"

COLLECTIVE_SITES = (
    "dl009_good._gather_helper",
    "dl009_good._exchange_helper",
    "dl009_good.MeshOps._replicate_fn",
)


def _gather_helper(vals):
    return lax.all_gather(vals, SHARD_AXIS, tiled=True)


def _exchange_helper(buf):
    # nested closures charge to the outermost function
    def body(x):
        return lax.all_to_all(x, SHARD_AXIS, split_axis=0, concat_axis=0)

    return body(buf)


class MeshOps:
    def _replicate_fn(self):
        def build():
            def body(v):
                return lax.all_gather(v, SHARD_AXIS, tiled=True)

            return body

        return build()

    def shard_local(self, vals, mask):
        # no collectives here: pure per-shard compute is always fine
        return vals.sum() + mask.sum()
