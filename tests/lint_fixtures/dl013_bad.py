"""DL013 bad fixture: an undeclared device_get, a declared scope that
fetches without tallying FETCH_COUNTS, and a stale registry entry."""

import jax

FETCH_COUNTS = {"n": 0}

FETCH_SITES = (
    "dl013_bad.settle_rounds",
    "dl013_bad.untallied_fetch",
    "dl013_bad.retired_helper",  # stale: no device_get lives there
)


def settle_rounds(outs):
    FETCH_COUNTS["n"] += 1
    return jax.device_get(tuple(outs))


def untallied_fetch(out):
    # declared, but the fetches-per-query telemetry never sees it
    return jax.device_get(out)


def debug_peek(table):
    # undeclared transfer: a silent extra RTT per query
    return jax.device_get(table.vals)


def retired_helper(outs):
    return list(outs)
