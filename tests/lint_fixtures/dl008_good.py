"""DL008 good: every planner route literal is declared in ROUTE_KEYS,
every PLANNER_COUNTS key declared and counted, dict built from the
registry."""

ROUTE_KEYS = ("fixture_fused", "fixture_sharded")
PLANNER_KEYS = ("fixture_planned", "fixture_dp")

PLANNER_COUNTS = {k: 0 for k in PLANNER_KEYS}


class PlannedProgram:
    def __init__(self, route):
        self.route = route


def plan(kernel, exact):
    route = "fixture_fused" if kernel else "fixture_sharded"
    method = "fixture_dp" if exact else "fixture_planned"
    PLANNER_COUNTS[method] += 1
    PLANNER_COUNTS["fixture_planned"] += 0  # both keys have static sites
    return PlannedProgram(route=route)
