"""DL006 bad: threaded state mutated from the wrong side of its
declared discipline, plus undeclared mutable state."""

import threading

LOCK_DISCIPLINE = {
    "Pipeline._worker": "_lock",
    "Pipeline.stats": "worker",
    "Pipeline.depth": "init",
}

WORKER_METHODS = {
    "Pipeline": ("_run",),
}


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = None
        self.stats = {"items": 0}
        self.depth = 2

    def submit(self, item):
        self.stats["items"] += 1          # RPC thread bumping worker state
        if self._worker is None:
            self._worker = threading.Thread(target=self._run)  # no lock
        self.depth = 3                    # init-only attr mutated later
        self.burst = True                 # undeclared mutable state

    def rescale(self):
        with self._lock:
            self.stats["scale"] = 2       # holding A lock doesn't make a
                                          # worker-confined attr shareable
        with self._other:
            self._worker = None           # wrong lock entirely

    def classify(self, kind):
        match kind:
            case "burst":
                self.stats["burst"] += 1  # match arm is no hiding place
            case _:
                pass

    def _run(self):
        self.stats["items"] += 1          # fine — but submit() isn't


class SideCar:
    """A second class in a declaring module is covered too — threaded
    state must not dodge the rule by moving next door."""

    def __init__(self):
        self.entries = {}

    def put(self, k, v):
        self.entries[k] = v               # undeclared post-init mutation
