"""DL014 good fixture: every recorded span/counter/histogram literal is
a declared registry member and every declared name records somewhere."""

from das_tpu import obs

SPAN_NAMES = (
    "serve.fetch",
    "serve.done",
)

COUNTER_NAMES = ("serve.fetches",)

HISTOGRAM_NAMES = ("serve.fetch_ms",)


def fetch(job):
    with obs.span("serve.fetch"), obs.annotation("serve.fetch"):
        out = job.run()
    obs.counter("serve.fetches").inc()
    obs.histogram("serve.fetch_ms").observe(out.ms)
    obs.event("serve.done", rows=out.rows)
    return out
