"""DL007 good: the capture-then-pass idiom — the version is read
BEFORE dispatch and threaded through to the settle-time insert."""


class Executor:
    def begin(self, key):
        self.version = self.results.version()  # dispatch-time capture
        self.enqueue(key)

    def finish(self, key, result):
        self.results.put(key, result, self.version)
        self.results.put(key, result, version=self.version)

    def finish_batch(self, results_cache, pending, key, result):
        results_cache.put(key, result, pending.version)

    def unrelated(self, queue, item):
        queue.put(item)  # not a result cache: out of scope
