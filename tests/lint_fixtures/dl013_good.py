"""DL013 good fixture: every device_get declared in FETCH_SITES and
tallied into FETCH_COUNTS — one reviewable transfer list."""

import jax

FETCH_COUNTS = {"n": 0}

FETCH_SITES = (
    "dl013_good.settle_rounds",
    "dl013_good.Executor.execute",
)


def settle_rounds(outs):
    FETCH_COUNTS["n"] += 1
    return jax.device_get(tuple(outs))


class Executor:
    def execute(self, job):
        out = job.dispatch()
        FETCH_COUNTS["n"] += 1
        return job.settle(jax.device_get(out), out)

    def materialize(self, result):
        return result.host_vals  # prefetched: no transfer here
