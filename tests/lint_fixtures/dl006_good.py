"""DL006 good: every post-__init__ mutation honors the declared map."""

import threading

LOCK_DISCIPLINE = {
    "Pipeline._worker": "_lock",
    "Pipeline.stats": "worker",
}

WORKER_METHODS = {
    "Pipeline": ("_run", "_drain"),
}


class Pipeline:
    def __init__(self):
        self._lock = threading.Lock()
        self._worker = None
        self.stats = {"items": 0, "batches": 0}

    def ensure_worker(self):
        with self._lock:
            if self._worker is None:
                self._worker = threading.Thread(target=self._run)

    def _run(self):
        self.stats["batches"] += 1
        self._drain()

    def _drain(self):
        self.stats["items"] += 1
