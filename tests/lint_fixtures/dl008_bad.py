"""DL008 bad: a planner emitting a route the registry never declared,
an undeclared planner counter key, a dead registry key, and a drifted
PLANNER_COUNTS literal."""

ROUTE_KEYS = ("fixture_fused", "fixture_sharded")
PLANNER_KEYS = ("fixture_planned", "fixture_dead")

# drifted literal: missing fixture_dead, smuggles fixture_extra
PLANNER_COUNTS = {"fixture_planned": 0, "fixture_extra": 0}


class PlannedProgram:
    def __init__(self, route):
        self.route = route


def plan(kernel):
    route = "fixture_fused" if kernel else "fixture_warp"  # undeclared
    PLANNER_COUNTS["fixture_planned"] += 1
    PLANNER_COUNTS["fixture_mystery"] += 1               # undeclared key
    return PlannedProgram(route="fixture_hyperspace")    # undeclared
