"""DL015 bad fixture: an undeclared injection site, a maybe_fail inside
a dispatch half, and a stale FAULT_SITES entry."""

from das_tpu import fault

FAULT_SITES = (
    "good_seam",
    "retired_seam",  # stale: no maybe_fail injects there
)


def recovery_seam(batch):
    # undeclared site: the chaos sweep can never schedule it
    fault.maybe_fail("surprise_seam")
    return list(batch)


class _ExecJob:
    def dispatch(self):
        # banned: injection inside a dispatch half — dispatch must stay
        # purely asynchronous and raise-free (DL001/DL010)
        fault.maybe_fail("good_seam")
        return self

    def settle(self, host, out):
        return True
