"""DL004 good: every counting literal is declared, every key counted,
dicts built from the registry.  (The test passes no tests-dir for the
fixture runs, so the referenced-by-a-test leg is exercised on the real
tree instead.)"""

DISPATCH_KEYS = ("fixture_kernel", "fixture_tiled")
ROUTE_KEYS = ("fixture_fused", "fixture_staged")

DISPATCH_COUNTS = {k: 0 for k in DISPATCH_KEYS}
ROUTE_COUNTS = {k: 0 for k in ROUTE_KEYS}


def record_dispatch(kind, n=1):
    DISPATCH_COUNTS[kind] = DISPATCH_COUNTS.get(kind, 0) + n


def run(tiled, fused):
    record_dispatch("fixture_kernel")
    if tiled:
        record_dispatch("fixture_tiled")
    route = "fixture_fused" if fused else "fixture_staged"
    ROUTE_COUNTS[route] += 1
