"""DL011 good fixture: lane-aligned chunk emission, disciplined refs,
compare/select instead of python branches, priced dtypes only."""

import jax.numpy as jnp

ROUTE_TILED = "tiled"

LANE_ROWS = 128
MIN_CHUNK_ROWS = 1024


class StagePlan:
    def __init__(self, route, chunk_rows, resident, block):
        self.route = route
        self.chunk_rows = chunk_rows


def _lane_floor(n):
    return (int(n) // LANE_ROWS) * LANE_ROWS


def chunk_rows_for(row_bytes, capacity, budget):
    chunk = _lane_floor(budget // 4 // max(row_bytes, 1))
    return max(chunk, MIN_CHUNK_ROWS)


def plan(resident, per_row, capacity, budget):
    chunk = chunk_rows_for(per_row, capacity, budget)
    return StagePlan(ROUTE_TILED, chunk, resident, per_row * chunk)


def _emit(base, chunk, vals_ref):
    # helper keeps the *_ref naming, so forwarding stays checkable
    return vals_ref[base:base + chunk]


def _kernel_body(capacity):
    def kernel(vals_ref, mask_ref, out_ref):
        vals = _emit(0, capacity, vals_ref)
        mask = mask_ref[:]
        picked = jnp.where(mask > 0, vals + 1, vals)  # select, not branch
        out_ref[:] = picked.astype(jnp.int32)

    return kernel
