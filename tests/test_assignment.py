"""Assignment algebra: join/compatibility/negation semantics."""

import pytest

from das_tpu.query.assignment import (
    CompositeAssignment,
    Compatibility,
    OrderedAssignment,
    UnorderedAssignment,
)


def ordered(**mapping):
    a = OrderedAssignment()
    for k, v in mapping.items():
        assert a.assign(k, v)
    assert a.freeze()
    return a


def unordered(pairs):
    a = UnorderedAssignment()
    for k, v in pairs:
        assert a.assign(k, v)
    return a


def frozen_unordered(pairs):
    a = unordered(pairs)
    assert a.freeze()
    return a


class TestOrdered:
    def test_assign_conflict(self):
        a = OrderedAssignment()
        assert a.assign("V1", "x")
        assert not a.assign("V1", "y")
        assert a.assign("V1", "x")

    def test_freeze_and_hash_equality(self):
        a = ordered(V1="x", V2="y")
        b = ordered(V2="y", V1="x")
        assert a == b
        assert hash(a) == hash(b)

    def test_compatibility_matrix(self):
        a = ordered(V1="x", V2="y")
        assert a.compatibility(ordered(V1="x", V2="y")) == Compatibility.EQUAL
        assert a.compatibility(ordered(V1="x")) == Compatibility.FIRST_COVERS_SECOND
        assert (
            ordered(V1="x").compatibility(a) == Compatibility.SECOND_COVERS_FIRST
        )
        assert a.compatibility(ordered(V1="z")) == Compatibility.INCOMPATIBLE
        assert a.compatibility(ordered(V3="z")) == Compatibility.NO_COVERING

    def test_join_union(self):
        j = ordered(V1="x").join(ordered(V2="y"))
        assert j is not None
        assert j.mapping == {"V1": "x", "V2": "y"}

    def test_join_incompatible(self):
        assert ordered(V1="x").join(ordered(V1="y")) is None

    def test_join_covering_returns_larger(self):
        big = ordered(V1="x", V2="y")
        assert big.join(ordered(V1="x")) is big
        assert ordered(V1="x").join(big) is big

    def test_check_negation(self):
        a = ordered(V1="x", V2="y")
        assert not a.check_negation(ordered(V1="x", V2="y"))   # equal -> excluded
        assert not a.check_negation(ordered(V1="x"))           # covered -> excluded
        assert a.check_negation(ordered(V1="z"))               # incompatible -> kept
        assert a.check_negation(ordered(V1="x", V3="z"))       # no covering -> kept


class TestUnordered:
    def test_freeze_fails_on_count_mismatch(self):
        a = unordered([("V1", "x"), ("V2", "x")])
        # two symbols (1,1) vs one value with count 2 -> (2,) mismatch... counts
        # are sorted tuples (1,1) vs (2,)
        assert not a.freeze()

    def test_freeze_ok(self):
        a = frozen_unordered([("V1", "x"), ("V2", "y")])
        assert a.hash

    def test_duplicate_variable_rejected(self):
        a = unordered([("V1", "x")])
        assert not a.assign("V1", "y")

    def test_contains_ordered(self):
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        assert u.contains_ordered(ordered(V1="x"))
        assert u.contains_ordered(ordered(V1="y", V2="x"))  # any pairing
        assert not u.contains_ordered(ordered(V3="x"))
        assert not u.contains_ordered(ordered(V1="z"))

    def test_is_covered_by_ordered(self):
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        assert u.is_covered_by_ordered(ordered(V1="x", V2="y"))
        assert u.is_covered_by_ordered(ordered(V1="y", V2="x"))
        assert not u.is_covered_by_ordered(ordered(V1="x"))

    def test_contains_unordered(self):
        big = frozen_unordered([("V1", "x"), ("V2", "y"), ("V3", "z")])
        small = frozen_unordered([("V1", "x"), ("V2", "y")])
        assert big.contains_unordered(small)
        assert not small.contains_unordered(big)

    def test_join_produces_composite(self):
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        j = u.join(ordered(V1="x"))
        assert isinstance(j, CompositeAssignment)

    def test_join_ordered_conflicting_value_fails(self):
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        assert u.join(ordered(V1="z")) is None


class TestComposite:
    def test_join_two_unordered(self):
        u1 = frozen_unordered([("V1", "x"), ("V2", "y")])
        u2 = frozen_unordered([("V2", "y"), ("V3", "z")])
        j = u1.join(u2)
        assert isinstance(j, CompositeAssignment)
        assert len(j.unordered_mappings) == 2

    def test_ordered_then_unordered_viability(self):
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        c = u.join(ordered(V1="x", V2="y"))
        assert c is not None
        # now an unordered constraint that contradicts the ordered mapping
        bad = frozen_unordered([("V1", "q"), ("V2", "r")])
        assert c.join(bad) is None

    def test_join_disjoint_ordered_fails_viability(self):
        # an ordered mapping sharing no variables with the unordered
        # constraint is not viable (reference pattern_matcher.py:294-305)
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        assert u.join(ordered(V3="q")) is None

    def test_check_negation_ordered(self):
        u = frozen_unordered([("V1", "x"), ("V2", "y")])
        c = u.join(ordered(V1="x"))
        assert c is not None
        assert c.check_negation(ordered(V3="zzz"))
        assert not c.check_negation(ordered(V1="x"))

    def test_hash_stability(self):
        u1 = frozen_unordered([("V1", "x"), ("V2", "y")])
        u2 = frozen_unordered([("V1", "x"), ("V2", "y")])
        c1 = u1.join(ordered(V1="x"))
        c2 = u2.join(ordered(V1="x"))
        assert c1 == c2
