"""Case-for-case port of the reference's last two test files onto this
framework's equivalents (closing the reference test-matrix inventory):

* /root/reference/tests/test_parser.py:5-79 — Parser / LexParser /
  MultiprocessingParser.  The reference file is unrunnable even upstream
  (`from lex import Lex` imports a module that does not exist, and
  LexParser's own module import chain is broken — parser.py:8), so the
  CASES are ported instead of shimmed: the chunk-splitting expectations
  onto `convert/chunked.split_balanced`, the parse-tree expectations
  onto `parse_sexpr_trees` / `parse_multiprocess` (all three reference
  parser classes asserted pairwise-equal on identical input, mirrored
  here as serial == multiprocess).

* /root/reference/tests/test_translator.py:1-13 — Expression/MList/MSet
  string rendering (`()`, `()`, `{}`).  This framework's translator is
  a streaming walker with no container object zoo, so the equivalent
  observable — the EMITTED text — is asserted: empty expression renders
  as `()` and a SetLink renders with `{}` braces.
"""

from das_tpu.convert.chunked import (
    parse_multiprocess,
    parse_sexpr_trees,
    split_balanced,
)

TWO_EVAL = (
    '(EvaluationLink\n'
    '    (PredicateNode "P1")\n'
    '    (ListLink\n'
    '        (CellNode "CL1")\n'
    '        (ConceptNode "CC1")))\n'
    '(EvaluationLink\n'
    '    (PredicateNode "P2")\n'
    '    (ListLink\n'
    '        (CellNode "CL2")\n'
    '        (ConceptNode "CC2")))\n'
)


def test_split_to_two_chunks():
    # reference test_parser.py:4-21: each toplevel expression becomes its
    # own chunk at chunk_exprs=1 (whitespace preserved as written, not
    # flattened — the splitter never rewrites content)
    chunks = list(split_balanced(TWO_EVAL, chunk_exprs=1))
    assert len(chunks) == 2
    assert chunks[0].startswith("(EvaluationLink") and '"P1"' in chunks[0]
    assert chunks[1].startswith("(EvaluationLink") and '"P2"' in chunks[1]
    assert '"P2"' not in chunks[0] and '"P1"' not in chunks[1]


def test_split_to_one_chunk():
    # reference test_parser.py:24-27 equivalent: a chunk size covering
    # both expressions yields one chunk carrying both
    chunks = list(split_balanced(TWO_EVAL, chunk_exprs=2))
    assert len(chunks) == 1
    assert '"P1"' in chunks[0] and '"P2"' in chunks[0]


def test_parse_two_expressions():
    # reference test_parser.py:30-38
    text = '(PredicateNode "P1")\n(PredicateNode "P2")\n'
    assert parse_sexpr_trees(text) == [
        ["PredicateNode", '"P1"'],
        ["PredicateNode", '"P2"'],
    ]


def test_parse_single_expression_multiprocessing():
    # reference test_parser.py:41-45
    assert parse_multiprocess('(PredicateNode "P1")\n', processes=1) == [
        ["PredicateNode", '"P1"']
    ]


def test_parse_two_expressions_multiprocessing():
    # reference test_parser.py:48-56 (chunk_exprs=1 forces the pool path)
    text = '(PredicateNode "P1")\n(PredicateNode "P2")\n'
    assert parse_multiprocess(text, processes=2, chunk_exprs=1) == [
        ["PredicateNode", '"P1"'],
        ["PredicateNode", '"P2"'],
    ]


def test_serial_and_multiprocess_parsers_agree():
    # reference test_parser.py:59-79 (Parser == MultiprocessingParser ==
    # LexParser pairwise; here: the one serial source of truth vs the
    # pool path)
    assert parse_sexpr_trees(TWO_EVAL) == parse_multiprocess(
        TWO_EVAL, processes=2, chunk_exprs=1
    )


def test_translator_rendering_empty_and_set():
    # reference test_translator.py:4-13: Expression -> "()",
    # MList -> "()", MSet -> "{}".  Observable equivalent here: the
    # emitted MeTTa — a SetLink renders with curly braces, list-shaped
    # links with parens.
    import pytest

    from das_tpu.convert.atomese2metta import (
        InvalidSymbol,
        Translator,
        translate_text,
    )

    text = translate_text('(SetLink (ConceptNode "a") (ConceptNode "b"))\n')
    assert "{" in text and "}" in text
    text2 = translate_text('(ListLink (ConceptNode "a") (ConceptNode "b"))\n')
    assert "{" not in text2 and '(List "a" "b")' in text2
    # the reference's empty Expression() -> "()" is an internal container
    # artifact unreachable from any .scm input; the streaming walker has
    # no such object, and an empty TREE is rejected loudly instead of
    # silently rendering "()" — the documented divergence
    with pytest.raises(InvalidSymbol):
        Translator().translate([])
