"""Store export/import interop (VERDICT r04 item 5).

The dump files must be byte-identical to what the reference's `mongodump`
script would export for the same store: one `Expression.to_dict()` JSON
document per line (expression.py:25-53), C-locale sorted per collection
(mongodump:1-8 pipes mongoexport through sort(1)).  The differential
oracle below builds every expected line with the REFERENCE'S OWN
`das.expression.Expression.to_dict` (imported from /root/reference, pure
module) and compares whole files.

The loader proves the reverse direction: a dump — including a
reference-produced one, which lacks the typedef designator names —
reconstructs a store whose re-dump is byte-identical (every hash
re-derived through the parser, so corruption cannot pass).
"""

import importlib.util
import json
import os
import sys

import pytest

from das_tpu.convert import dump as dump_mod
from das_tpu.ingest.pipeline import load_knowledge_base
from das_tpu.query.ast import Link, Node, PatternMatchingAnswer, Variable
from das_tpu.storage.atom_table import AtomSpaceData
from das_tpu.storage.memory_db import MemoryDB

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ANIMALS = f"{REPO}/data/samples/animals.metta"


@pytest.fixture(scope="module")
def animals_data():
    return load_knowledge_base(AtomSpaceData(), ANIMALS)


def _reference_expression_cls():
    """Import the reference's pure das/expression.py WITHOUT putting
    /root/reference on sys.path (which would shadow the compat shim)."""
    spec = importlib.util.spec_from_file_location(
        "_ref_expression", "/root/reference/das/expression.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.Expression


def test_dump_matches_reference_to_dict_byte_for_byte(animals_data, tmp_path):
    """Every dump line equals json of the REFERENCE Expression.to_dict for
    the same atom — field names, field ORDER, and bool rendering included."""
    RefExpression = _reference_expression_cls()
    prefix = str(tmp_path / "animals")
    written = dump_mod.dump_store(animals_data, prefix)
    assert sorted(written) == [f"{prefix}.atom_types", f"{prefix}.links_2",
                               f"{prefix}.nodes"]

    expected = {"nodes": [], "atom_types": [], "links_2": []}
    for handle, rec in animals_data.nodes.items():
        e = RefExpression(
            terminal_name=rec.name, named_type=rec.named_type,
            composite_type_hash=rec.named_type_hash, hash_code=handle,
        )
        expected["nodes"].append(json.dumps(e.to_dict(), separators=(",", ":")))
    for handle, rec in animals_data.typedefs.items():
        e = RefExpression(
            typedef_name=rec.name, typedef_name_hash=rec.name_hash,
            composite_type_hash=rec.composite_type_hash, hash_code=handle,
        )
        expected["atom_types"].append(
            json.dumps(e.to_dict(), separators=(",", ":"))
        )
    for handle, rec in animals_data.links.items():
        e = RefExpression(
            toplevel=rec.is_toplevel, named_type=rec.named_type,
            named_type_hash=rec.named_type_hash,
            composite_type=rec.composite_type,
            composite_type_hash=rec.composite_type_hash,
            elements=list(rec.elements), hash_code=handle,
        )
        expected["links_2"].append(
            json.dumps(e.to_dict(), separators=(",", ":"))
        )

    for name, lines in expected.items():
        with open(f"{prefix}.{name}") as f:
            got = f.read()
        assert got == "\n".join(sorted(lines)) + "\n", f"{name} differs"


def test_dump_load_round_trip_byte_identical(animals_data, tmp_path):
    prefix = str(tmp_path / "animals")
    dump_mod.dump_store(animals_data, prefix)
    reloaded = dump_mod.load_dump(prefix)
    assert reloaded.count_atoms() == animals_data.count_atoms() == (14, 26)
    prefix2 = str(tmp_path / "reloaded")
    dump_mod.dump_store(reloaded, prefix2)
    for name in ("nodes", "atom_types", "links_2"):
        with open(f"{prefix}.{name}") as a, open(f"{prefix2}.{name}") as b:
            assert a.read() == b.read(), f"{name} changed across round trip"


def test_reference_style_dump_loads_without_designators(animals_data, tmp_path):
    """A reference-produced dump carries no typedef designator names; the
    loader recovers them by exact hash check against _id."""
    prefix = str(tmp_path / "animals")
    dump_mod.dump_store(animals_data, prefix)
    text = dump_mod.dump_to_metta(prefix)
    # the recovered typedefs land as (: Name Type) lines
    assert "(: Concept Type)" in text
    assert "(: Similarity Type)" in text
    assert "(: Inheritance Type)" in text
    assert '(: "human" Concept)' in text


def test_loaded_dump_answers_queries(animals_data, tmp_path):
    prefix = str(tmp_path / "animals")
    dump_mod.dump_store(animals_data, prefix)
    db = MemoryDB(dump_mod.load_dump(prefix))
    q = Link(
        "Inheritance",
        [Variable("V1"), Node("Concept", "mammal")],
        True,
    )
    answer = PatternMatchingAnswer()
    assert q.matched(db, answer)
    assert len(answer.assignments) == 4  # human, monkey, chimp, rhino


def test_nested_and_high_arity_links_round_trip(tmp_path):
    """keys split (arity > 2) and non-toplevel sub-link rendering."""
    from das_tpu.storage.atom_table import load_metta_text

    text = (
        "(: List Type)\n"
        "(: Concept Type)\n"
        '(: "a" Concept)\n'
        '(: "b" Concept)\n'
        '(: "c" Concept)\n'
        '(List "a" "b" "c")\n'
        '(List (List "a" "b" "c") "c")\n'
    )
    data = load_metta_text(text)
    prefix = str(tmp_path / "nested")
    written = dump_mod.dump_store(data, prefix)
    assert f"{prefix}.links_n" in written and f"{prefix}.links_2" in written
    with open(f"{prefix}.links_n") as f:
        (line,) = [ln for ln in f.read().splitlines() if ln]
    doc = json.loads(line)
    assert len(doc["keys"]) == 3 and "key_0" not in doc
    reloaded = dump_mod.load_dump(prefix)
    assert reloaded.count_atoms() == data.count_atoms()
    prefix2 = str(tmp_path / "nested2")
    dump_mod.dump_store(reloaded, prefix2)
    for name in ("nodes", "atom_types", "links_2", "links_n"):
        with open(f"{prefix}.{name}") as a, open(f"{prefix2}.{name}") as b:
            assert a.read() == b.read()


def test_symbol_element_links_round_trip(tmp_path):
    """A link whose element is a bare SYMBOL (typedef hash) renders
    unquoted and round-trips (code-review r5 finding 1)."""
    from das_tpu.storage.atom_table import load_metta_text

    text = (
        "(: Concept Type)\n"
        "(: Eval Type)\n"
        '(: "x" Concept)\n'
        '(Eval Concept "x")\n'
    )
    data = load_metta_text(text)
    prefix = str(tmp_path / "sym")
    dump_mod.dump_store(data, prefix)
    reconstructed = dump_mod.dump_to_metta(prefix)
    assert '(Eval Concept "x")' in reconstructed
    reloaded = dump_mod.load_dump(prefix)
    assert set(reloaded.links) == set(data.links)
    prefix2 = str(tmp_path / "sym2")
    dump_mod.dump_store(reloaded, prefix2)
    for name in ("nodes", "atom_types", "links_2"):
        with open(f"{prefix}.{name}") as a, open(f"{prefix2}.{name}") as b:
            assert a.read() == b.read()


def test_same_name_two_types_fails_loudly(tmp_path):
    """Canonical MeTTa text cannot express one terminal name under two
    types; the loader must refuse rather than silently collapse
    (code-review r5 finding 2)."""
    from das_tpu.storage.atom_table import load_metta_text

    data = load_metta_text(
        "(: Concept Type)\n(: Number Type)\n(: Rel Type)\n"
        '(: "x" Concept)\n(Rel "x" "x")\n'
    )
    # second store contributes the same name under ANOTHER type
    load_metta_text('(: Number Type)\n(: Rel Type)\n(: "x" Number)\n(Rel "x" "x")\n', data)
    assert len(data.nodes) == 2
    prefix = str(tmp_path / "dup")
    dump_mod.dump_store(data, prefix)
    with pytest.raises(ValueError, match="does not reconstruct faithfully"):
        dump_mod.load_dump(prefix)


def test_missing_prefix_raises(tmp_path):
    with pytest.raises(FileNotFoundError, match="no dump files"):
        dump_mod.load_dump(str(tmp_path / "no_such_prefix"))


def test_non_ascii_and_html_chars_escape_like_mongoexport(tmp_path):
    """Go's encoding/json (mongoexport) writes raw UTF-8 but HTML-escapes
    < > & — our lines must match byte-for-byte (code-review r5)."""
    from das_tpu.storage.atom_table import load_metta_text

    data = load_metta_text(
        '(: Concept Type)\n(: Rel Type)\n'
        '(: "café" Concept)\n(: "a<b&c" Concept)\n'
        '(Rel "café" "a<b&c")\n'
    )
    prefix = str(tmp_path / "uni")
    dump_mod.dump_store(data, prefix)
    raw = open(f"{prefix}.nodes", "rb").read().decode("utf-8")
    assert "café" in raw            # raw UTF-8, not é
    assert "\\u00e9" not in raw
    assert "a\\u003cb\\u0026c" in raw  # HTML chars escaped Go-style
    reloaded = dump_mod.load_dump(prefix)
    assert set(reloaded.nodes) == set(data.nodes)
    assert set(reloaded.links) == set(data.links)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_store_round_trip(seed, tmp_path):
    """Property: any store built from generated MeTTa (random types,
    names with spaces/unicode, arities 1-4, nested links, duplicate
    expressions) dumps and reloads byte-identically."""
    import random

    rng = random.Random(seed)
    types = [f"T{i}" for i in range(rng.randint(2, 5))]
    names = [
        rng.choice(["n", "x y", "café", "a.b", "N0"]) + str(i)
        for i in range(rng.randint(3, 10))
    ]
    lines = [f"(: {t} Type)" for t in types]
    decls = [(n, rng.choice(types)) for n in names]
    lines += [f'(: "{n}" {t})' for n, t in decls]
    def term():
        return f'"{rng.choice(names)}"'
    exprs = []
    for _ in range(rng.randint(4, 15)):
        arity = rng.randint(1, 4)
        elems = [term() for _ in range(arity)]
        if exprs and rng.random() < 0.4:
            elems[rng.randrange(arity)] = rng.choice(exprs)
        expr = f"({rng.choice(types)} {' '.join(elems)})"
        exprs.append(expr)
        lines.append(expr)
    if exprs:
        lines.append(rng.choice(exprs))  # duplicate toplevel dedups

    from das_tpu.storage.atom_table import load_metta_text

    data = load_metta_text("\n".join(lines) + "\n")
    p1 = str(tmp_path / "a")
    dump_mod.dump_store(data, p1)
    reloaded = dump_mod.load_dump(p1)
    assert reloaded.count_atoms() == data.count_atoms()
    p2 = str(tmp_path / "b")
    written2 = dump_mod.dump_store(reloaded, p2)
    for path2 in written2:
        path1 = p1 + path2[len(p2):]
        with open(path1) as a, open(path2) as b:
            assert a.read() == b.read(), f"{path2} diverged (seed {seed})"
