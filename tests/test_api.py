"""DistributedAtomSpace facade: API parity checks (role of the reference
distributed_atom_space_test.py + das_update_test.py, DB-free)."""

import json

import pytest

from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
from das_tpu.core.schema import WILDCARD
from das_tpu.models.animals import animals_metta
from das_tpu.query.ast import And, Link, Node, Variable

HUMAN = "af12f10f9ae2002a1607ba0b47ba8407"
MAMMAL = "bdfe4e7a431f73386f37c6448afe5840"


@pytest.fixture(scope="module", params=["memory", "tensor"])
def das(request):
    das = DistributedAtomSpace(backend=request.param)
    das.load_metta_text(animals_metta())
    return das


def test_count_atoms(das):
    assert das.count_atoms() == (14, 26)


def test_get_node_handle(das):
    assert das.get_node("Concept", "human") == HUMAN
    assert das.get_node("Concept", "mammal") == MAMMAL
    assert das.get_node("Concept", "dog") is None


def test_get_node_atom_info(das):
    info = das.get_node("Concept", "human", QueryOutputFormat.ATOM_INFO)
    assert info == {"handle": HUMAN, "type": "Concept", "name": "human"}


def test_get_nodes(das):
    assert len(das.get_nodes("Concept")) == 14
    assert das.get_nodes("Concept", "human") == [HUMAN]
    assert das.get_nodes("blah") == []


def test_get_link(das):
    handle = das.get_link("Inheritance", [HUMAN, MAMMAL])
    assert handle is not None
    assert das.get_link_targets(handle) == [HUMAN, MAMMAL]
    assert das.get_link_type(handle) == "Inheritance"
    assert das.get_link("Inheritance", [MAMMAL, HUMAN]) is None


def test_get_links_by_targets(das):
    handles = das.get_links("Inheritance", targets=[WILDCARD, MAMMAL])
    assert len(handles) == 4


def test_get_links_by_target_types(das):
    handles = das.get_links("Inheritance", target_types=["Concept", "Concept"])
    assert len(handles) == 12


def test_get_links_by_type_only(das):
    handles = das.get_links("Similarity")
    assert len(handles) == 14


def test_get_links_json(das):
    out = das.get_links(
        "Inheritance", targets=[HUMAN, MAMMAL], output_format=QueryOutputFormat.JSON
    )
    decoded = json.loads(out)
    assert decoded[0]["type"] == "Inheritance"
    assert decoded[0]["targets"][0] == {"type": "Concept", "name": "human"}


def test_get_atom(das):
    assert das.get_atom(HUMAN) == HUMAN
    info = das.get_atom(HUMAN, QueryOutputFormat.ATOM_INFO)
    assert info["name"] == "human"


def test_get_node_name_and_type(das):
    assert das.get_node_name(HUMAN) == "human"
    assert das.get_node_type(HUMAN) == "Concept"


def test_query_string_output(das):
    q = Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    out = das.query(q)
    assert "V1" in out
    assert HUMAN in out


def test_query_answer_structured(das):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    matched, answer = das.query_answer(q)
    assert matched
    assert len(answer.assignments) == 7


@pytest.mark.parametrize("backend", ["memory", "tensor"])
def test_transaction_update(backend):
    # fresh instance: commits must not leak into the shared module fixture
    das = DistributedAtomSpace(backend=backend)
    das.load_metta_text(animals_metta())
    before_nodes, before_links = das.count_atoms()
    tx = das.open_transaction()
    tx.add('(: "dog" Concept)')
    tx.add('(Inheritance "dog" "mammal")')
    tx.add('(Similarity "dog" "human")')
    das.commit_transaction(tx)
    nodes, links = das.count_atoms()
    assert nodes == before_nodes + 1
    assert links == before_links + 2
    # new atoms visible through every index surface
    dog = das.get_node("Concept", "dog")
    assert dog is not None
    assert len(das.get_links("Inheritance", targets=[WILDCARD, MAMMAL])) == 5
    assert len(das.get_links("Inheritance", target_types=["Concept", "Concept"])) == 13
    matched, answer = das.query_answer(
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    )
    assert matched
    values = {list(a.mapping.values())[0] for a in answer.assignments}
    assert dog in values


def test_clear_database():
    das = DistributedAtomSpace(backend="memory")
    das.load_metta_text(animals_metta())
    assert das.count_atoms() == (14, 26)
    das.clear_database()
    assert das.count_atoms() == (0, 0)


def test_load_knowledge_base_from_file(tmp_path):
    from das_tpu.models.animals import write_animals_metta

    path = tmp_path / "animals.metta"
    write_animals_metta(str(path))
    das = DistributedAtomSpace(backend="tensor")
    das.load_knowledge_base(str(path))
    assert das.count_atoms() == (14, 26)


def test_canonical_loader_roundtrip(tmp_path):
    text = """(: Evaluation Type)
(: Predicate Type)
(: Reactome Type)
(: Concept Type)
(: "Predicate:has_name" Predicate)
(: "Reactome:R-HSA-164843" Reactome)
(: "Concept:2-LTR circle formation" Concept)
(Evaluation "Predicate Predicate:has_name" (Evaluation "Predicate Predicate:has_name" "Reactome Reactome:R-HSA-164843"))
(Evaluation "Predicate Predicate:has_name" "Concept Concept:2-LTR circle formation")
"""
    path = tmp_path / "canon.metta"
    path.write_text(text)
    das = DistributedAtomSpace(backend="tensor")
    das.load_canonical_knowledge_base(str(path))
    nodes, links = das.count_atoms()
    assert nodes == 3
    assert links == 3  # outer, nested, second toplevel
    from das_tpu.core.hashing import ExpressionHasher

    rh = das.get_node("Reactome", "Reactome:R-HSA-164843")
    assert rh == ExpressionHasher.terminal_hash("Reactome", "Reactome:R-HSA-164843")
    handles = das.get_links("Evaluation")
    assert len(handles) == 3


def test_capacity_overflow_falls_back_to_host():
    """A join that exceeds max_result_capacity must degrade to the host
    algebra with correct answers, not crash the API (VERDICT r1 weak #3)."""
    from das_tpu.core.config import DasConfig
    from das_tpu.query.ast import PatternMatchingAnswer
    from das_tpu.query import compiler as qc

    cfg = DasConfig(initial_result_capacity=16, max_result_capacity=16)
    das = DistributedAtomSpace(backend="tensor", config=cfg)
    das.load_metta_text(animals_metta())
    v1, v2 = Variable("V1"), Variable("V2")
    v3, v4 = Variable("V3"), Variable("V4")
    # disjoint-variable cross product: 12x12 = 144 rows > every device cap
    query = And(
        [
            Link("Inheritance", [v1, v2], True),
            Link("Inheritance", [v3, v4], True),
        ]
    )
    qc.reset_route_counts()
    matched, answer = das.query_answer(query)
    assert qc.ROUTE_COUNTS["host"] == 1  # fell back, did not crash
    assert matched
    # answers identical to a pure-host run
    ref = DistributedAtomSpace(backend="memory")
    ref.load_metta_text(animals_metta())
    ref_answer = PatternMatchingAnswer()
    ref_matched = query.matched(ref.db, ref_answer)
    assert bool(matched) == bool(ref_matched)
    assert {repr(a) for a in answer.assignments} == {
        repr(a) for a in ref_answer.assignments
    }


@pytest.mark.parametrize("backend", ["memory", "tensor"])
def test_pattern_black_list_suppresses_wildcard_probes(backend):
    """Blacklisted link types emit no pattern index (reference
    parser_threads.py:41,185): wildcard probes can't see them, grounded
    lookups and template probes still can."""
    from das_tpu.core.config import DasConfig
    from das_tpu.query.ast import PatternMatchingAnswer

    cfg = DasConfig(pattern_black_list=["Similarity"])
    das = DistributedAtomSpace(backend=backend, config=cfg)
    das.load_metta_text(animals_metta())

    # wildcard probe on the blacklisted type: invisible
    assert das.db.get_matched_links("Similarity", [WILDCARD, WILDCARD]) == []
    q = Link("Similarity", [Variable("V1"), Variable("V2")], False)
    matched, answer = das.query_answer(q)
    assert not matched and not answer.assignments

    # other types unaffected
    assert len(das.db.get_matched_links("Inheritance", [WILDCARD, WILDCARD])) == 12

    # grounded lookup still works (patterns index not involved)
    human = das.get_node("Concept", "human")
    monkey = das.get_node("Concept", "monkey")
    assert das.db.get_matched_links("Similarity", [human, monkey])

    # template probe (templates namespace) unaffected by the blacklist
    assert len(das.db.get_matched_type_template(["Similarity", "Concept", "Concept"])) == 14


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_get_links_production_semantics_fuzz(seed):
    """Property (production unordered probe, redis_mongo_db.py:249-252):
    for an unordered link type with a wildcard in targets, get_links
    answers exactly the links whose STORED targets match the SORTED probe
    positionally — brute-force oracle over random Similarity stores, on
    both in-process backends."""
    import random

    from das_tpu.storage.atom_table import load_metta_text
    from das_tpu.storage.memory_db import MemoryDB
    from das_tpu.storage.tensor_db import TensorDB

    rng = random.Random(seed)
    names = [f"n{i}" for i in range(rng.randint(3, 6))]
    lines = ["(: Concept Type)", "(: Similarity Type)"]
    lines += [f'(: "{n}" Concept)' for n in names]
    pairs = set()
    for _ in range(rng.randint(3, 12)):
        a, b = rng.choice(names), rng.choice(names)
        if a != b:
            pairs.add((a, b))
    lines += [f'(Similarity "{a}" "{b}")' for a, b in sorted(pairs)]
    data = load_metta_text("\n".join(lines) + "\n")

    for make in (lambda: MemoryDB(data), lambda: TensorDB(data)):
        das = DistributedAtomSpace(db=make())
        by_handle = {
            h: tuple(rec.elements) for h, rec in data.links.items()
        }
        for probe_name in names:
            probe_h = das.db.get_node_handle("Concept", probe_name)
            for probe in ([probe_h, WILDCARD], [WILDCARD, probe_h]):
                got = set(das.get_links("Similarity", targets=probe))
                sp = sorted(probe)
                want = {
                    h
                    for h, elems in by_handle.items()
                    if len(elems) == 2
                    and all(
                        p == WILDCARD or p == e for p, e in zip(sp, elems)
                    )
                }
                assert got == want, (
                    f"seed {seed} probe {probe} on "
                    f"{type(das.db).__name__}: {got} != {want}"
                )
