"""daslint suite (marker `lint`, standalone: ops/pytests.sh lint).

Pins, in order of load-bearing-ness:
  * the analyzer runs CLEAN over das_tpu/ (baseline-grandfathered
    findings allowed; the baseline is currently empty) — the invariant
    contracts of ARCHITECTURE §11 hold on the committed tree;
  * each rule still FIRES on its known-bad fixture and stays quiet on
    the known-good one (tests/lint_fixtures/) — a refactor of the
    analyzer cannot silently lobotomize a rule;
  * re-introducing the two historical bug classes — deleting a
    plan-signature field that routing reads (the PR-4 `tiled` class)
    and counting into an undeclared counter key — is caught on REAL
    source, by mutating copies of query/fused.py / query/compiler.py;
  * the CLI contract (`python -m das_tpu.analysis`): exit 0 clean,
    1 on findings and on stale baseline entries, plus suppression and
    baseline mechanics;
  * the counter registries and generated env table stay in sync (the
    registry pin below is also DL004's "referenced by at least one
    test" witness for the cold-path keys the behavior suites don't
    exercise: count_kernel_tiled, staged, staged_kernel, anti_kernel,
    tree).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

from das_tpu.analysis import run_analysis
from das_tpu.analysis.core import apply_baseline, iter_rules, load_baseline

pytestmark = pytest.mark.lint

REPO = Path(__file__).resolve().parent.parent
FIXTURES = Path(__file__).resolve().parent / "lint_fixtures"
RULES = (
    "DL001", "DL002", "DL003", "DL004", "DL005", "DL006", "DL007", "DL008",
    "DL009", "DL010", "DL011", "DL012", "DL013", "DL014", "DL015", "DL016",
    "DL017",
)


# -- the tentpole pin: the committed tree honors every contract ----------


def test_tree_is_clean():
    findings = run_analysis(
        [REPO / "das_tpu"], tests_dir=REPO / "tests"
    )
    baseline = load_baseline(REPO / "daslint.baseline.json")
    new, _kept, stale = apply_baseline(findings, baseline)
    assert not new, "new daslint findings:\n" + "\n".join(
        f.render() for f in new
    )
    assert not stale, "stale baseline entries: " + str(
        [(b.rule, b.path) for b in stale]
    )


def test_all_rules_registered():
    assert [rid for rid, _ in iter_rules()] == list(RULES)


# -- per-rule fixture corpus ---------------------------------------------


@pytest.mark.parametrize("rule", RULES)
def test_bad_fixture_trips(rule):
    path = FIXTURES / f"{rule.lower()}_bad.py"
    findings = run_analysis([path], rules=[rule])
    assert findings, f"{path.name} tripped nothing for {rule}"
    assert all(f.rule == rule for f in findings)


@pytest.mark.parametrize("rule", RULES)
def test_good_fixture_clean(rule):
    path = FIXTURES / f"{rule.lower()}_good.py"
    findings = run_analysis([path], rules=[rule])
    assert not findings, "\n".join(f.render() for f in findings)


def test_fixture_messages_name_the_contract():
    """Spot-pin that the findings explain the hazard, not just point."""
    f1 = run_analysis([FIXTURES / "dl001_bad.py"], rules=["DL001"])
    assert any("transfer-free" in f.message for f in f1)
    f5 = run_analysis([FIXTURES / "dl005_bad.py"], rules=["DL005"])
    assert any("unaccounted=['scratch_ref']" in f.message for f in f5)


# -- regression: re-introduce the historical bug classes on REAL code ----


def test_dl002_catches_removed_plan_sig_field(tmp_path):
    """Delete FusedPlanSig.use_kernels (the PR-4 `tiled`-class omission):
    build_fused still reads sig.use_kernels, so DL002 must fire on the
    mutated copy of the real module."""
    src = (REPO / "das_tpu/query/fused.py").read_text()
    field_line = "    use_kernels: bool = False\n"
    assert src.count(field_line) == 1, "fused.py layout changed"
    mutated = tmp_path / "fused_mutated.py"
    mutated.write_text(src.replace(field_line, ""))
    findings = run_analysis([mutated], rules=["DL002"])
    hits = [f for f in findings if "use_kernels" in f.message]
    assert hits, "DL002 missed the removed plan-sig field:\n" + "\n".join(
        f.render() for f in findings
    )


def test_dl004_catches_undeclared_counter_key(tmp_path):
    """Typo a ROUTE_COUNTS key in a copy of the real compiler module:
    the literal no longer matches ops/counters.py's registry."""
    src = (REPO / "das_tpu/query/compiler.py").read_text()
    needle = 'ROUTE_COUNTS["staged"]'
    assert needle in src, "compiler.py layout changed"
    mutated = tmp_path / "compiler_mutated.py"
    mutated.write_text(src.replace(needle, 'ROUTE_COUNTS["stagedd"]', 1))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/ops/counters.py"], rules=["DL004"]
    )
    assert any("'stagedd'" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )


def test_dl007_catches_unguarded_cache_insert(tmp_path):
    """Mutate the REAL streaming-settle insert site (query/fused.py
    settle_pending_iter) to re-read the version at insert time — the
    exact bug shape the delta_version guard exists to prevent, now that
    speculative dispatch widens the dispatch→insert window."""
    src = (REPO / "das_tpu/query/fused.py").read_text()
    needle = "results_cache.put(key, job.result, pending.version)"
    assert src.count(needle) == 1, "fused.py layout changed"
    mutated = tmp_path / "fused_mutated.py"
    mutated.write_text(src.replace(
        needle,
        "results_cache.put(key, job.result, results_cache.version())",
        1,
    ))
    findings = run_analysis([mutated], rules=["DL007"])
    assert any(
        "AT INSERT TIME" in f.message for f in findings
    ), "\n".join(f.render() for f in findings)
    # ... and dropping the argument entirely is the other bug shape
    unversioned = tmp_path / "fused_unversioned.py"
    unversioned.write_text(src.replace(
        needle, "results_cache.put(key, job.result)", 1
    ))
    findings = run_analysis([unversioned], rules=["DL007"])
    assert any(
        "without a dispatch-time version" in f.message for f in findings
    ), "\n".join(f.render() for f in findings)


def test_dl008_catches_undeclared_planner_route(tmp_path):
    """Mutate the REAL planner search module to emit a route ROUTE_KEYS
    never declared (the ISSUE-8 named candidate rule): the costed plan
    would then claim a route no counter tracks and no pin could verify."""
    src = (REPO / "das_tpu/planner/search.py").read_text()
    needle = 'route = "fused_kernel"'
    assert src.count(needle) == 1, "search.py layout changed"
    mutated = tmp_path / "search_mutated.py"
    mutated.write_text(src.replace(
        needle, 'route = "warp_fused"', 1
    ))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/ops/counters.py"], rules=["DL008"]
    )
    assert any("'warp_fused'" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )
    # ... and an undeclared planner counter key is the other bug shape
    csrc = (REPO / "das_tpu/planner/__init__.py").read_text()
    cneedle = 'PLANNER_COUNTS["planned"] += 1'
    assert csrc.count(cneedle) == 1, "planner/__init__.py layout changed"
    typo = tmp_path / "planner_typo.py"
    typo.write_text(csrc.replace(
        cneedle, 'PLANNER_COUNTS["planed"] += 1', 1
    ))
    findings = run_analysis(
        [typo, REPO / "das_tpu/ops/counters.py"], rules=["DL008"]
    )
    assert any("'planed'" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )


def test_dl009_catches_collective_in_kernel_body(tmp_path):
    """Mutate a COPY of the real multiway kernel module (placed under a
    kernels/ dir, as the rule attributes by path) to smuggle a psum into
    the shard-local body — the ISSUE-10 named candidate rule: a
    collective in a kernel body deadlocks or silently diverges between
    the interpret/discharge/Mosaic lowerings."""
    src = (REPO / "das_tpu/kernels/multiway.py").read_text()
    needle = "def multiway_join_impl("
    assert src.count(needle) == 1, "multiway.py layout changed"
    kdir = tmp_path / "kernels"
    kdir.mkdir()
    mutated = kdir / "multiway_mutated.py"
    mutated.write_text(src.replace(
        needle,
        'def _leak(x):\n'
        '    import jax\n'
        '    return jax.lax.psum(x, "shards")\n\n\n'
        + needle,
        1,
    ))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/parallel/mesh.py"], rules=["DL009"]
    )
    assert any("shard-local kernel body" in f.message for f in findings), (
        "\n".join(f.render() for f in findings)
    )


def test_dl009_catches_undeclared_collective_scope(tmp_path):
    """Mutate a COPY of the real sharded executor: a psum added to a
    scope COLLECTIVE_SITES never declared must fail — otherwise
    cross-shard bytes leave the one reviewable list."""
    src = (REPO / "das_tpu/parallel/fused_sharded.py").read_text()
    needle = "def _repartition("
    assert src.count(needle) == 1, "fused_sharded.py layout changed"
    mutated = tmp_path / "fused_sharded_mutated.py"
    mutated.write_text(src.replace(
        needle,
        'def _rogue_reduce(x):\n'
        '    return lax.psum(x, SHARD_AXIS)\n\n\n'
        + needle,
        1,
    ))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/parallel/mesh.py"], rules=["DL009"]
    )
    assert any("_rogue_reduce" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )
    # ... and a clean SAME-STEM copy stays quiet next to the real
    # registry (only the registry's stale-entry leg may fire, for the
    # sharded_db/sharded_tree scopes absent from this partial set)
    clean = tmp_path / "fused_sharded.py"
    clean.write_text(src)
    findings = run_analysis(
        [clean, REPO / "das_tpu/parallel/mesh.py"], rules=["DL009"]
    )
    assert not [
        f for f in findings
        if "undeclared scope" in f.message or "kernel body" in f.message
    ], "\n".join(f.render() for f in findings)


def test_dl010_catches_sync_through_helper(tmp_path):
    """Route the REAL dispatch half through a syncing helper: the body
    stays DL001-clean (the banned call moved one hop away) but the
    call-graph scan must still reach it and render the path."""
    src = (REPO / "das_tpu/query/fused.py").read_text()
    needle = '        record_dispatch("fused")\n'
    assert src.count(needle) == 1, "fused.py layout changed"
    mutated = tmp_path / "fused_mutated.py"
    mutated.write_text(
        src.replace(
            needle,
            '        record_dispatch("fused")\n'
            "        _flush_telemetry(self.arrays)\n",
            1,
        )
        + "\n\ndef _flush_telemetry(arrays):\n"
        "    return np.asarray(arrays)\n"
    )
    findings = run_analysis([mutated], rules=["DL010"])
    hits = [f for f in findings if "_flush_telemetry" in f.message]
    assert hits, "DL010 missed the helper-hop sync:\n" + "\n".join(
        f.render() for f in findings
    )
    assert any("_ExecJob.dispatch" in f.message for f in hits)
    # ... and DL001 alone stays quiet on it: the hop defeats the
    # syntactic rule, which is exactly why DL010 exists
    direct = [
        f for f in run_analysis([mutated], rules=["DL001"])
        if "_flush_telemetry" in f.message
    ]
    assert not direct


def test_dl011_catches_dealigned_chunk_constant(tmp_path):
    """De-align MIN_CHUNK_ROWS in a copy of the real budget module: the
    chunk_rows_for return is no longer provably lane-tiled."""
    src = (REPO / "das_tpu/kernels/budget.py").read_text()
    needle = "MIN_CHUNK_ROWS = 1024"
    assert src.count(needle) == 1, "budget.py layout changed"
    mutated = tmp_path / "budget_mutated.py"
    mutated.write_text(src.replace(needle, "MIN_CHUNK_ROWS = 1000", 1))
    findings = run_analysis([mutated], rules=["DL011"])
    assert any(
        "128-lane tiling" in f.message for f in findings
    ), "\n".join(f.render() for f in findings)
    # the committed module proves aligned (the ISSUE 11 source fix)
    clean = run_analysis(
        [REPO / "das_tpu/kernels/budget.py"], rules=["DL011"]
    )
    assert not clean, "\n".join(f.render() for f in clean)


def test_dl011_catches_kernel_branch_on_traced(tmp_path):
    """Smuggle a python branch on a ref-derived value into a copy of
    the real probe kernel body."""
    src = (REPO / "das_tpu/kernels/probe.py").read_text()
    needle = "        keys = keys_ref[:]\n        key = key_ref[0]\n"
    assert src.count(needle) == 1, "probe.py layout changed"
    mutated = tmp_path / "probe.py"
    mutated.write_text(src.replace(
        needle,
        needle + "        if key > 0:\n            key = key + 0\n",
        1,
    ))
    findings = run_analysis([mutated], rules=["DL011"])
    assert any(
        "python `if` on a traced" in f.message for f in findings
    ), "\n".join(f.render() for f in findings)


def test_dl012_catches_per_request_dict_keying_jit(tmp_path):
    """Key the REAL fused builder's trace on a per-request dict (the
    DL002 lesson, dynamic edition): the annotation flip makes the
    closure's count_only a mutable per-request value."""
    src = (REPO / "das_tpu/query/fused.py").read_text()
    needle = "def build_fused(sig: FusedPlanSig, count_only: bool = False):"
    assert src.count(needle) == 1, "fused.py layout changed"
    mutated = tmp_path / "fused_mutated.py"
    mutated.write_text(src.replace(
        needle,
        "def build_fused(sig: FusedPlanSig, count_only: dict = False):",
        1,
    ))
    findings = run_analysis([mutated], rules=["DL012"])
    assert any(
        "count_only" in f.message for f in findings
    ), "\n".join(f.render() for f in findings)
    # the committed module is clean
    assert not run_analysis(
        [REPO / "das_tpu/query/fused.py"], rules=["DL012"]
    )


def test_dl013_catches_undeclared_device_get(tmp_path):
    """Add an undeclared jax.device_get to a same-stem copy of the real
    tree module (run against the real FETCH_SITES registry): the new
    transfer site must fail, the declared ones must not."""
    src = (REPO / "das_tpu/query/tree.py").read_text()
    needle = "def materialize_tables("
    assert src.count(needle) == 1, "tree.py layout changed"
    mutated = tmp_path / "tree.py"  # stem must stay `tree` for the scopes
    mutated.write_text(src.replace(
        needle,
        "def _rogue_fetch(t):\n"
        "    return jax.device_get(t.vals)\n\n\n" + needle,
        1,
    ))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/query/fused.py"], rules=["DL013"],
        partial=True,
    )
    assert any("_rogue_fetch" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )
    # ... and the clean same-stem copy passes next to the registry
    # (partial=True: the other declared scopes' modules aren't in set)
    clean = tmp_path / "clean" / "tree.py"
    clean.parent.mkdir()
    clean.write_text(src)
    findings = run_analysis(
        [clean, REPO / "das_tpu/query/fused.py"], rules=["DL013"],
        partial=True,
    )
    assert not [
        f for f in findings if "undeclared scope" in f.message
    ], "\n".join(f.render() for f in findings)


def test_dl013_partial_suppresses_stale_only():
    """A partial set must still report presence violations but skip the
    stale-entry leg (the --changed-only contract); the full-set run
    keeps it."""
    fused = REPO / "das_tpu/query/fused.py"
    partial = run_analysis([fused], rules=["DL013"], partial=True)
    assert not partial, "\n".join(f.render() for f in partial)
    full_subset = run_analysis([fused], rules=["DL013"])
    assert any("stale entry" in f.message for f in full_subset), (
        "fused.py alone declares scopes for other modules — the "
        "non-partial run must flag them stale"
    )


def test_dl005_catches_new_kernel_ref(tmp_path):
    """Grow the real probe kernel body a scratch ref without touching
    budget.py: the manifest cross-check must fire."""
    src = (REPO / "das_tpu/kernels/probe.py").read_text()
    needle = "    def kernel(key_ref, fvals_ref, keys_ref, perm_ref, targets_ref,\n               vals_ref, mask_ref, cnt_ref):"
    assert needle in src, "probe.py layout changed"
    mutated = tmp_path / "probe.py"  # stem must stay `probe` for the key
    mutated.write_text(src.replace(
        needle, needle.replace("cnt_ref):", "cnt_ref, scratch_ref):"), 1
    ))
    findings = run_analysis(
        [mutated, REPO / "das_tpu/kernels/budget.py"], rules=["DL005"]
    )
    assert any("scratch_ref" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )


# -- suppression + baseline mechanics ------------------------------------


def test_per_file_suppression(tmp_path):
    bad = (FIXTURES / "dl003_bad.py").read_text()
    suppressed = tmp_path / "suppressed.py"
    suppressed.write_text("# daslint: disable=DL003\n" + bad)
    assert run_analysis([suppressed], rules=["DL003"]) == []


def test_suppression_requires_a_comment_line(tmp_path):
    """Quoting the syntax in a docstring or string literal must NOT
    disable anything — only a real comment token counts, including when
    the quote sits on its own line inside a multi-line docstring."""
    bad = (FIXTURES / "dl003_bad.py").read_text()
    documented = tmp_path / "documented.py"
    documented.write_text(
        '"""Docs may mention `# daslint: disable=DL003` harmlessly."""\n'
        'EXAMPLE = "# daslint: disable=DL003"\n' + bad
    )
    assert run_analysis([documented], rules=["DL003"])
    multiline = tmp_path / "multiline.py"
    multiline.write_text(
        '"""Docs.\n# daslint: disable=DL003\n"""\n' + bad
    )
    assert run_analysis([multiline], rules=["DL003"])


def test_dl006_sees_mutations_inside_with_blocks():
    """Regression: a mutation that is a DIRECT statement of a `with`
    block must be checked (holding some lock does not satisfy worker
    confinement, and the wrong lock does not satisfy lock ownership)."""
    findings = run_analysis([FIXTURES / "dl006_bad.py"], rules=["DL006"])
    msgs = "\n".join(f.message for f in findings)
    assert "Pipeline.rescale" in msgs
    assert "`self._worker` mutated outside `with self._lock:` in " \
           "Pipeline.rescale" in msgs


def test_dl006_covers_undeclared_classes_in_declaring_module():
    """Regression: a second class in a module that declares a
    LOCK_DISCIPLINE is covered even though no map entry names it."""
    findings = run_analysis([FIXTURES / "dl006_bad.py"], rules=["DL006"])
    msgs = "\n".join(f.message for f in findings)
    assert "`self.entries` mutated in SideCar.put" in msgs


def test_dl006_sees_mutations_inside_match_cases():
    """Regression: a mutation inside a `match` arm must be checked like
    any other compound statement — `classify` is not a worker method."""
    findings = run_analysis([FIXTURES / "dl006_bad.py"], rules=["DL006"])
    msgs = "\n".join(f.message for f in findings)
    assert "`self.stats` is worker-thread-confined but Pipeline.classify" \
        in msgs


def test_dl004_nested_def_counts_once(tmp_path):
    """Regression: a counting site inside a nested function is reported
    exactly once, and the nested scope's dynamic-key names do not pick
    up same-named locals from the enclosing function."""
    mod = tmp_path / "nested.py"
    mod.write_text(
        "DISPATCH_KEYS = ()\n"
        "DISPATCH_COUNTS = {}\n"
        "def outer():\n"
        "    k = 'outer_key'\n"
        "    def inner():\n"
        "        k = 'inner_key'\n"
        "        DISPATCH_COUNTS[k] += 1\n"
        "    inner()\n"
    )
    findings = run_analysis([mod], rules=["DL004"])
    inner = [f for f in findings if "'inner_key'" in f.message]
    assert len(inner) == 1, "\n".join(f.render() for f in findings)
    assert not any("'outer_key'" in f.message for f in findings), \
        "\n".join(f.render() for f in findings)


def test_dl002_checks_qualified_constructor():
    """Regression: `mod.LeakyPlanSig(...)` gets the same keyword check
    as a bare-name construction."""
    findings = run_analysis([FIXTURES / "dl002_bad.py"], rules=["DL002"])
    assert any("`chunk`" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )


def test_dl002_sees_optional_annotated_consumers():
    """Regression: Optional[Sig]-annotated params keep the read check."""
    findings = run_analysis([FIXTURES / "dl002_bad.py"], rules=["DL002"])
    assert any(
        "chunk_rows" in f.message and f.line > 30 for f in findings
    ), "\n".join(f.render() for f in findings)


def test_cli_rules_subset_skips_other_rules_baseline(tmp_path):
    """Regression: a --rules subset run must not report other rules'
    grandfathered entries as stale."""
    import shutil

    from das_tpu.analysis.__main__ import main

    work = tmp_path / "fx"
    work.mkdir()
    shutil.copy(FIXTURES / "dl006_good.py", work / "dl006_good.py")
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [{
        "rule": "DL001", "path": "somewhere.py", "message": "kept",
        "justification": "belongs to an unselected rule",
    }]}))
    assert main([
        str(work), "--rules", "DL006", "--baseline", str(bl),
    ]) == 0


def test_baseline_grandfathers_and_goes_stale(tmp_path):
    findings = run_analysis([FIXTURES / "dl003_bad.py"], rules=["DL003"])
    assert findings
    entries = [
        {
            "rule": f.rule, "path": f.path, "message": f.message,
            "justification": "fixture keep",
        }
        for f in findings
    ]
    # one extra entry that matches nothing -> stale
    entries.append({
        "rule": "DL003", "path": "nowhere.py", "message": "gone",
        "justification": "stale on purpose",
    })
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": entries}))
    new, kept, stale = apply_baseline(findings, load_baseline(bl))
    assert not new and len(kept) == len(findings) and len(stale) == 1


def test_baseline_requires_justification(tmp_path):
    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [
        {"rule": "DL001", "path": "x.py", "message": "m"}
    ]}))
    with pytest.raises(ValueError):
        load_baseline(bl)


# -- CLI contract --------------------------------------------------------


def test_cli_exit_codes_inprocess(capsys):
    from das_tpu.analysis.__main__ import main

    assert main([str(FIXTURES / "dl006_good.py"), "--rules", "DL006"]) == 0
    assert main([str(FIXTURES / "dl006_bad.py"), "--rules", "DL006"]) == 1
    assert main(["--list-rules"]) == 0
    assert main([str(REPO / "does_not_exist.py")]) == 2
    # an EXPLICIT --baseline that does not exist must not silently skip
    # the stale-entry check (the default path may be absent)
    assert main([
        str(FIXTURES / "dl006_good.py"), "--rules", "DL006",
        "--baseline", str(REPO / "no_such_baseline.json"),
    ]) == 2
    out = capsys.readouterr().out
    assert "DL006" in out


def test_cli_json_output(capsys):
    from das_tpu.analysis.__main__ import main

    rc = main([str(FIXTURES / "dl001_bad.py"), "--rules", "DL001", "--json"])
    assert rc == 1
    record = json.loads(capsys.readouterr().out)
    assert record["findings"] and not record["stale_baseline"]
    assert {"rule", "path", "line", "message"} <= set(
        record["findings"][0]
    )


def test_cli_subprocess_whole_tree():
    """The acceptance command, end to end: exits 0 on the final tree."""
    proc = subprocess.run(
        [sys.executable, "-m", "das_tpu.analysis", "das_tpu"],
        cwd=REPO, capture_output=True, text=True,
        env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
             "HOME": str(Path.home())},
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stdout


def test_cli_select_ignore_and_unknown_ids(tmp_path, capsys):
    from das_tpu.analysis.__main__ import main

    bad = str(FIXTURES / "dl013_bad.py")
    # --select is the --rules alias with the same semantics
    assert main([bad, "--select", "DL013"]) == 1
    # --ignore carves the selected rule back out -> nothing runs -> clean
    assert main([bad, "--select", "DL013", "--ignore", "DL013"]) == 0
    # an unknown id in either flag is a usage error, not a silent no-op
    assert main([bad, "--select", "DL999"]) == 2
    assert main([bad, "--ignore", "DL0XX"]) == 2
    capsys.readouterr()


def test_cli_allow_partial_skips_stale_baseline(tmp_path, capsys):
    """--changed-only's analyzer contract: a baseline entry whose file
    is outside the partial path set must NOT fail the run as stale —
    staleness is the full run's verdict (which must still flag it)."""
    from das_tpu.analysis.__main__ import main

    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [{
        "rule": "DL006", "path": "somewhere/else.py", "message": "kept",
        "justification": "its module is not in the partial set",
    }]}))
    args = [
        str(FIXTURES / "dl006_good.py"), "--select", "DL006",
        "--baseline", str(bl),
    ]
    assert main(args + ["--allow-partial"]) == 0
    assert main(args) == 1  # the full-set semantics keep the teeth
    capsys.readouterr()


def test_dl013_flags_module_level_fetch(tmp_path):
    """An import-time device_get has no declarable scope and must fire
    even though it sits in no function body."""
    mod = tmp_path / "import_fetch.py"
    mod.write_text(
        "import jax\n"
        "FETCH_SITES = ()\n"
        "FETCH_COUNTS = {'n': 0}\n"
        "_SNAP = jax.device_get(42)\n"
    )
    findings = run_analysis([mod], rules=["DL013"])
    assert any(
        "outside any function" in f.message for f in findings
    ), "\n".join(f.render() for f in findings)


def test_cli_sarif_reports_stale_baseline(tmp_path, capsys):
    """A stale entry fails the run, so the SARIF consumer must see it —
    an empty results array on a red build explains nothing."""
    from das_tpu.analysis.__main__ import main

    bl = tmp_path / "baseline.json"
    bl.write_text(json.dumps({"findings": [{
        "rule": "DL006", "path": "gone.py", "message": "vanished",
        "justification": "stale on purpose",
    }]}))
    rc = main([
        str(FIXTURES / "dl006_good.py"), "--select", "DL006",
        "--baseline", str(bl), "--format", "sarif",
    ])
    assert rc == 1
    record = json.loads(capsys.readouterr().out)
    results = record["runs"][0]["results"]
    assert any("stale baseline entry" in r["message"]["text"]
               for r in results)


def test_cli_sarif_output(capsys):
    from das_tpu.analysis.__main__ import main

    rc = main([
        str(FIXTURES / "dl001_bad.py"), "--select", "DL001",
        "--format", "sarif",
    ])
    assert rc == 1
    record = json.loads(capsys.readouterr().out)
    assert record["version"] == "2.1.0"
    run = record["runs"][0]
    assert run["tool"]["driver"]["name"] == "daslint"
    assert run["results"], "no SARIF results for a bad fixture"
    r0 = run["results"][0]
    assert r0["ruleId"] == "DL001"
    loc = r0["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("dl001_bad.py")
    assert loc["region"]["startLine"] > 0
    assert any(
        rule["id"] == "DL001" for rule in run["tool"]["driver"]["rules"]
    )


def test_file_cache_reuses_and_invalidates(tmp_path):
    """The (path, mtime, size) parse cache returns the SAME SourceFile
    for an unchanged file and re-parses after an edit."""
    from das_tpu.analysis.core import collect_files

    mod = tmp_path / "cached.py"
    mod.write_text("X = 1\n")
    first = collect_files([mod])[0]
    again = collect_files([mod])[0]
    assert again is first, "unchanged file was re-parsed"
    import os

    mod.write_text("X = 2  # changed\n")
    # belt and braces on coarse filesystem clocks: bump mtime explicitly
    st = mod.stat()
    os.utime(mod, ns=(st.st_atime_ns, st.st_mtime_ns + 1))
    fresh = collect_files([mod])[0]
    assert fresh is not first, "edited file served from cache"
    assert "changed" in fresh.text


# -- registries + generated docs stay pinned -----------------------------


def test_counter_registry_pins():
    """THE test reference for every counter key (DL004's third leg):
    a key rename/add/remove must consciously edit this pin, and the
    dicts must be built from the registry."""
    from das_tpu import kernels
    from das_tpu.ops import counters
    from das_tpu.query import compiler

    assert counters.DISPATCH_KEYS == (
        "lowered", "kernel", "kernel_tiled",
        "fused", "fused_kernel", "fused_kernel_tiled", "fused_multiway",
        "fused_tree",
        "sharded", "sharded_kernel", "sharded_kernel_tiled",
        "sharded_multiway", "sharded_tree_fused",
        "count", "count_kernel", "count_kernel_tiled",
    )
    assert counters.ROUTE_KEYS == (
        "fused", "fused_kernel", "fused_multiway",
        "fused_tree", "sharded_tree_fused",
        "staged", "staged_kernel", "anti_kernel",
        "tree", "sharded", "sharded_kernel", "sharded_multiway",
        "count_kernel", "host", "star",
    )
    assert tuple(kernels.DISPATCH_COUNTS) == counters.DISPATCH_KEYS
    assert tuple(compiler.ROUTE_COUNTS) == counters.ROUTE_KEYS
    from das_tpu import planner

    assert counters.PLANNER_KEYS == (
        "planned", "greedy", "dp", "greedy_tail", "ref_order",
        "programs", "round0", "retries", "est_rows", "actual_rows",
        "explain",
    )
    assert tuple(planner.PLANNER_COUNTS) == counters.PLANNER_KEYS


def test_coalescer_declares_lock_discipline():
    from das_tpu.service import coalesce

    assert "QueryCoalescer.stats" in coalesce.LOCK_DISCIPLINE
    assert "_run" in coalesce.WORKER_METHODS["QueryCoalescer"]


def test_env_table_in_sync():
    """ARCHITECTURE.md's operator table is generated from ENV_REGISTRY;
    editing either side alone must fail (the gen script's --check)."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "gen_env_table", REPO / "scripts/gen_env_table.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    doc = (REPO / "ARCHITECTURE.md").read_text()
    assert mod.splice(doc, mod.render_table()) == doc
