"""dasfault robustness suite (marker `fault`, standalone:
ops/pytests.sh fault) — ISSUE 13.

Pins, in order of load-bearing-ness:
  * CHAOS-PARITY: a seeded sweep injecting every FAULT_SITES entry over
    the bio suite, on both device backends — every query returns either
    bit-identical answers to the fault-free run or a typed DasError
    subclass; zero stranded futures; the worker survives every
    schedule;
  * commit atomicity under an injected mid-commit failure: the
    stage-then-swap ordering (storage/delta.py) leaves delta_version
    unbumped, the result caches uninvalidated, and the SAME delta
    commits cleanly afterwards;
  * deadline expiry in the queued / grouped / in-flight states, typed;
  * breaker lifecycle: trip on repeated retryable failures, reject
    retryable (with a retry-after hint) while open, half-open probe
    restores — and the real degraded mode still serves cache hits;
  * RetryPolicy determinism + the per-attempt FETCH_COUNTS accounting
    the DL013 tally leg pins;
  * the disabled fast path (no schedule armed) is the identity no-op;
  * DL015 on a real site: renaming a maybe_fail literal in a mutated
    copy of query/fused.py fires the analyzer.
"""

import threading
import time
from concurrent.futures import Future
from pathlib import Path
from types import SimpleNamespace

import pytest

from das_tpu import fault
from das_tpu.analysis import run_analysis
from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
from das_tpu.core.config import DasConfig
from das_tpu.core.exceptions import (
    BreakerOpenError,
    CoalescerSaturatedError,
    DasDeadlineError,
    DasError,
    InjectedFault,
)
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.query.ast import And, Link, Node, Variable
from das_tpu.service.coalesce import QueryCoalescer
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.fault

REPO = Path(__file__).resolve().parent.parent
HANDLE = QueryOutputFormat.HANDLE


@pytest.fixture(autouse=True)
def _disarm():
    """Every test leaves the process with injection OFF."""
    yield
    fault.configure(None)


def _bio_data():
    data, _, _ = build_bio_atomspace(
        n_genes=40, n_processes=6, members_per_gene=3,
        n_interactions=40, n_evaluations=8,
    )
    return data


@pytest.fixture(scope="module")
def tensor_served():
    data = _bio_data()
    db = TensorDB(data, DasConfig())
    das = DistributedAtomSpace(database_name="zfault", db=db)
    genes = db.get_all_nodes("Gene", names=True)[:6]
    queries = [_ast(g) for g in genes]
    baseline = [das.query(q) for q in queries]
    assert any(baseline), "KB too sparse to prove anything"
    return das, db, queries, baseline


@pytest.fixture(scope="module")
def sharded_served():
    from das_tpu.parallel.sharded_db import ShardedDB

    data = _bio_data()
    db = ShardedDB(data, DasConfig())
    das = DistributedAtomSpace(database_name="zfault_mesh", db=db)
    genes = db.get_all_nodes("Gene", names=True)[:4]
    queries = [_ast(g) for g in genes]
    baseline = [das.query(q) for q in queries]
    assert any(baseline)
    return das, db, queries, baseline


def _ast(gene: str):
    return And([
        Link("Member", [Node("Gene", gene), Variable("$3")], True),
        Link("Member", [Variable("$2"), Variable("$3")], True),
        Link("Interacts", [Node("Gene", gene), Variable("$2")], True),
    ])


def _tenant(das):
    return SimpleNamespace(das=das, lock=threading.RLock(), name="t")


def _coalescer(**kw):
    base = dict(max_batch=8, pipeline_depth=2, pipeline_depth_max=4,
                queue_max=0, deadline_ms=0, breaker_threshold=0,
                breaker_cooldown_ms=100)
    base.update(kw)
    return QueryCoalescer(**base)


def _poll(predicate, timeout=20.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if predicate():
            return True
        time.sleep(0.01)
    return False


# -- the tentpole pin: chaos-parity over every declared site -------------


def _chaos_sweep(das, queries, baseline, site, seed):
    tenant = _tenant(das)
    coal = _coalescer()
    fault.configure(f"seed={seed};sites={site};every=2;max=3")
    futs = [
        coal.submit(tenant, q, HANDLE) for q in queries + queries
    ]
    expected = baseline + baseline
    wrong = []
    for fut, expect in zip(futs, expected):
        # zero stranded futures: every result lands inside the bound
        try:
            got = fut.result(timeout=120)
        except Exception as exc:  # noqa: BLE001 — typed-or-identical
            if not isinstance(exc, DasError):
                wrong.append((site, type(exc).__name__, str(exc)[:120]))
            continue
        if got != expect:
            wrong.append((site, "WRONG_ANSWER", got[:80], expect[:80]))
    assert not wrong, wrong
    # worker alive after the schedule: disarm and serve one more
    fault.configure(None)
    again = coal.submit(tenant, queries[0], HANDLE)
    assert again.result(timeout=120) == baseline[0]


@pytest.mark.parametrize("site", fault.FAULT_SITES)
def test_chaos_parity_tensor(tensor_served, site):
    das, _db, queries, baseline = tensor_served
    _chaos_sweep(das, queries, baseline, site, seed=11)


@pytest.mark.parametrize("site", fault.FAULT_SITES)
def test_chaos_parity_sharded(sharded_served, site):
    das, _db, queries, baseline = sharded_served
    _chaos_sweep(das, queries, baseline, site, seed=13)


def test_disabled_fast_path_is_identity():
    """No schedule armed: maybe_fail is one global read + a None check —
    the obs NOOP_SPAN idiom, pinned by identity (`plan() is None`) and
    by the untouched counters."""
    fault.configure(None)
    assert fault.plan() is None
    assert fault._PLAN is None
    before = dict(fault.INJECT_COUNTS)
    for site in fault.FAULT_SITES:
        assert fault.maybe_fail(site) is None
    assert fault.INJECT_COUNTS == before


def test_schedule_is_deterministic():
    spec = "seed=3;sites=settle_fetch;rate=0.5;max=100"

    def fired():
        fault.configure(spec)
        out = []
        for i in range(64):
            try:
                fault.maybe_fail("settle_fetch")
            except InjectedFault:
                out.append(i)
        return out

    first, second = fired(), fired()
    assert first and first == second


def test_spec_validation():
    with pytest.raises(fault.FaultSpecError):
        fault.parse_spec("seed=1")  # no sites
    with pytest.raises(fault.FaultSpecError):
        fault.parse_spec("sites=not_a_site")
    with pytest.raises(fault.FaultSpecError):
        fault.parse_spec("sites=*;wat=1")
    with pytest.raises(fault.FaultSpecError):
        fault.parse_spec("sites=*;mode=chaotic")
    assert fault.parse_spec(None) is None
    assert fault.parse_spec("") is None
    plan = fault.parse_spec("sites=*")
    assert plan.sites == frozenset(fault.FAULT_SITES)


# -- commit atomicity under injected failure -----------------------------


def test_commit_atomicity_under_injected_failure():
    from das_tpu.models.animals import animals_metta
    from das_tpu.query.fused import result_cache_stats

    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    db = das.db
    q = And([
        Link("Inheritance", [Variable("$x"), Node("Concept", "mammal")],
             True),
    ])
    # serve the query through the batched path so the answer lands in
    # the delta-versioned result cache
    ans0 = das.query_many([q, q])[0]
    v0 = db.delta_version
    cache0 = result_cache_stats(db)
    assert cache0["misses"] >= 1

    tx = das.open_transaction()
    tx.add('(: "lion" Concept)')
    tx.add('(Inheritance "lion" "mammal")')
    # every commit_apply attempt fails: RetryPolicy (3 attempts) must
    # exhaust and surface the TYPED injected fault
    fault.configure("seed=1;sites=commit_apply;every=1;max=10")
    with pytest.raises(InjectedFault):
        das.commit_transaction(tx)
    # the atomicity pin (stage-then-swap): version unbumped, caches NOT
    # invalidated, the cached answer still identical
    assert db.delta_version == v0
    cache1 = result_cache_stats(db)
    assert cache1["invalidations"] == cache0["invalidations"]
    assert das.query_many([q, q])[0] == ans0
    assert result_cache_stats(db)["hits"] > cache0["hits"]

    # ... and the SAME delta commits cleanly once injection stops
    fault.configure(None)
    das.commit_transaction(tx)
    assert db.delta_version == v0 + 1
    lion = db.get_node_handle("Concept", "lion")
    mammal = db.get_node_handle("Concept", "mammal")
    assert db.link_exists("Inheritance", [lion, mammal])
    assert lion in das.query(q)


def test_commit_retry_recovers_transient_failure():
    """One injected failure, then success: the shared RetryPolicy
    retries the whole staged commit and the caller never sees an
    error."""
    from das_tpu.models.animals import animals_metta

    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text(animals_metta())
    v0 = das.db.delta_version
    tx = das.open_transaction()
    tx.add('(: "lynx" Concept)')
    tx.add('(Inheritance "lynx" "mammal")')
    fault.configure("seed=1;sites=commit_apply;every=1;max=1")
    das.commit_transaction(tx)  # attempt 1 injected, attempt 2 lands
    assert fault.INJECT_COUNTS["commit_apply"] >= 1
    assert das.db.delta_version == v0 + 1
    lynx = das.db.get_node_handle("Concept", "lynx")
    assert das.db.link_exists(
        "Inheritance", [lynx, das.db.get_node_handle("Concept", "mammal")]
    )


# -- retry policy ---------------------------------------------------------


def test_retry_policy_determinism_and_classes():
    p1 = fault.RetryPolicy(max_attempts=4, base_ms=1.0, seed=5)
    p2 = fault.RetryPolicy(max_attempts=4, base_ms=1.0, seed=5)
    seq = [p1.backoff_ms(a) for a in (1, 2, 3)]
    assert seq == [p2.backoff_ms(a) for a in (1, 2, 3)]
    assert seq[0] < seq[1] < seq[2]  # exponential under bounded jitter
    assert fault.RetryPolicy(seed=6).backoff_ms(1) != p1.backoff_ms(1)

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise InjectedFault("settle_fetch", calls["n"])
        return "ok"

    assert fault.RetryPolicy(max_attempts=3, base_ms=0.01).run(flaky) == "ok"
    assert calls["n"] == 3

    # non-retryable classes surface immediately
    def hard():
        calls["n"] += 1
        raise ValueError("semantic")

    calls["n"] = 0
    with pytest.raises(ValueError):
        fault.RetryPolicy(max_attempts=3, base_ms=0.01).run(hard)
    assert calls["n"] == 1

    # exhaustion re-raises the LAST typed failure
    def always():
        raise InjectedFault("settle_fetch", 0)

    with pytest.raises(InjectedFault):
        fault.RetryPolicy(max_attempts=2, base_ms=0.01).run(always)


def test_settle_fetch_retry_counts_every_attempt(tensor_served):
    """The generalized settle-fetch retry (the old fused.py retry-once)
    keeps per-attempt FETCH_COUNTS accounting: an injected first
    attempt + its successful retry are TWO tallied fetches (DL013's
    tally leg), and answers stay bit-identical."""
    from das_tpu.query.fused import FETCH_COUNTS

    src, _db, queries, baseline = tensor_served
    # cache OFF: every run must pay its settle fetches, so the injected
    # attempt is provably an EXTRA wire trip, not a cache artifact
    db = TensorDB(src.db.data, DasConfig(result_cache_size=0))
    das = DistributedAtomSpace(database_name="zfault_nocache", db=db)
    # fault-free fetch cost of the batch, measured on this exact state
    assert das.query_many(queries) == baseline
    n0 = FETCH_COUNTS["n"]
    assert das.query_many(queries) == baseline
    clean_fetches = FETCH_COUNTS["n"] - n0
    assert clean_fetches >= 1

    fault.configure("seed=2;sites=settle_fetch;every=1;max=1")
    inj0 = fault.INJECT_COUNTS["settle_fetch"]
    n1 = FETCH_COUNTS["n"]
    assert das.query_many(queries) == baseline
    faulted_fetches = FETCH_COUNTS["n"] - n1
    assert fault.INJECT_COUNTS["settle_fetch"] == inj0 + 1
    assert faulted_fetches >= clean_fetches + 1


# -- deadlines ------------------------------------------------------------


class _SlowDas:
    """Fake tenant store: every coalesced dispatch stalls, the
    per-query path answers — the queued-deadline scenario."""

    def __init__(self, dispatch_s: float):
        self.dispatch_s = dispatch_s
        self.config = DasConfig()

    def query_many_dispatch(self, queries, fmt, cache_only=False):
        time.sleep(self.dispatch_s)
        raise RuntimeError("no batch path")  # settle falls back per query

    def query(self, q, fmt):
        return f"ans:{q}"


def test_deadline_expires_queued_entries():
    das = _SlowDas(dispatch_s=0.25)
    tenant = _tenant(das)
    coal = _coalescer(max_batch=1, pipeline_depth=1, pipeline_depth_max=1,
                      deadline_ms=50)
    first = coal.submit(tenant, "q0", None)
    assert _poll(lambda: coal.stats["batches"] >= 1)
    late = [coal.submit(tenant, f"q{i}", None) for i in (1, 2, 3)]
    results = []
    for fut in [first] + late:
        try:
            results.append(fut.result(timeout=30))
        except Exception as exc:  # noqa: BLE001
            results.append(exc)
    # nothing stranded, and every resolution is an answer or TYPED
    # expiry: the backlog expired while queued behind the stalled
    # dispatch (the first entry may expire in flight — same contract)
    assert all(
        isinstance(r, DasDeadlineError) or r == f"ans:q{i}"
        for i, r in enumerate(results)
    ), results
    assert all(isinstance(r, DasDeadlineError) for r in results[1:]), results
    assert coal.stats["deadline_expired"] >= 3
    # a fresh submit after the stall clears answers — deadlines degrade
    # the backlog, never the worker
    das.dispatch_s = 0.0
    assert coal.submit(tenant, "q9", None).result(timeout=30) == "ans:q9"


def test_deadline_expiry_grouped_and_inflight_states():
    """Direct-harness legs (the coalesce test idiom): an entry expired
    while GROUPED never dispatches; an entry expiring IN FLIGHT is
    abandoned host-side at settle instead of paying the per-query
    fallback."""
    das = _SlowDas(dispatch_s=0.0)
    tenant = _tenant(das)
    coal = _coalescer(deadline_ms=10)

    # grouped: already past deadline when the group reaches dispatch
    fut = Future()
    expired = (tenant, "q", None, fut, None, time.monotonic() - 0.01)
    entry = coal._dispatch_group(tenant, None, [expired])
    assert entry[3] is None and entry[2] == []
    assert isinstance(fut.exception(timeout=1), DasDeadlineError)

    # in flight: alive at dispatch, dead by settle — the fallback loop
    # expires it without running das.query
    fut2 = Future()
    item = (tenant, "q2", None, fut2, None, time.monotonic() + 0.02)
    entry = coal._dispatch_group(tenant, None, [item])
    time.sleep(0.05)
    coal._settle_group(entry)
    assert isinstance(fut2.exception(timeout=1), DasDeadlineError)
    assert coal.stats["deadline_expired"] >= 2


def test_deadline_rides_config(tensor_served):
    """DasConfig.query_deadline_ms (env DAS_TPU_DEADLINE_MS) is the one
    source of truth; 0 keeps every deadline path disabled."""
    import os

    das, _db, _queries, _baseline = tensor_served
    assert QueryCoalescer().deadline_ms == DasConfig.query_deadline_ms
    assert _coalescer(deadline_ms=0)._deadline_of(
        (None, None, None, None, None, None)
    ) is None
    os.environ["DAS_TPU_DEADLINE_MS"] = "125"
    try:
        assert DasConfig.from_env().query_deadline_ms == 125
    finally:
        del os.environ["DAS_TPU_DEADLINE_MS"]


# -- circuit breaker ------------------------------------------------------


class _FlakyDas:
    """Fake tenant store whose per-query path fails retryable on
    demand — drives the breaker without any device."""

    def __init__(self):
        self.mode = "fail"
        self.config = DasConfig()

    def query_many_dispatch(self, queries, fmt, cache_only=False):
        raise RuntimeError("no batch path")

    def query(self, q, fmt):
        if self.mode == "fail":
            raise InjectedFault("settle_fetch", 0)
        return f"ans:{q}"


def test_breaker_trips_and_rejects_retryable():
    das = _FlakyDas()
    tenant = _tenant(das)
    coal = _coalescer(max_batch=1, breaker_threshold=2,
                      breaker_cooldown_ms=60_000)
    for name in ("a", "b"):
        exc = coal.submit(tenant, name, None).exception(timeout=30)
        assert isinstance(exc, InjectedFault)
    assert _poll(lambda: coal.stats["breaker_state"] == fault.OPEN)
    assert coal.stats["breaker_trips"] == 1

    das.mode = "ok"  # healthy again — but the breaker is still open
    exc = coal.submit(tenant, "c", None).exception(timeout=30)
    assert isinstance(exc, BreakerOpenError)
    assert exc.retry_after_ms is not None and exc.retry_after_ms > 0
    assert coal.stats["breaker_rejections"] >= 1
    # degraded mode holds the window at its floor (speculation off)
    assert coal.stats["effective_depth"] == 1


def test_breaker_halfopen_probe_restores():
    das = _FlakyDas()
    tenant = _tenant(das)
    coal = _coalescer(max_batch=1, breaker_threshold=1,
                      breaker_cooldown_ms=30)
    exc = coal.submit(tenant, "a", None).exception(timeout=30)
    assert isinstance(exc, InjectedFault)
    assert _poll(lambda: coal.stats["breaker_state"] == fault.OPEN)

    das.mode = "ok"
    time.sleep(0.05)  # past the cooldown: next group is the probe
    got = coal.submit(tenant, "b", None).result(timeout=30)
    assert got == "ans:b"
    assert _poll(lambda: coal.stats["breaker_state"] == fault.CLOSED)
    assert coal.stats["breaker_recoveries"] == 1
    assert coal.stats["breaker_probes"] >= 1


def test_breaker_reopen_on_failed_probe():
    b = fault.CircuitBreaker(failure_threshold=1, cooldown_ms=5)
    b.record_failure()
    assert b.state == fault.OPEN
    time.sleep(0.01)
    assert b.allow() and b.state == fault.HALF_OPEN
    b.record_failure()  # the probe failed
    assert b.state == fault.OPEN and b.recoveries == 0
    assert not b.allow()  # cooldown restarted
    assert b.retry_after_ms() > 0


def test_degraded_mode_serves_cache_hits(tensor_served):
    """The real-stack degraded contract: with the breaker OPEN, a query
    whose answer is in the delta-versioned result cache still answers
    bit-identically with ZERO device dispatch; a cold query rejects
    retryable with the breaker's retry-after hint."""
    das, db, _queries, _baseline = tensor_served
    tenant = _tenant(das)
    coal = _coalescer(breaker_threshold=1, breaker_cooldown_ms=60_000)
    # genes the earlier sweeps never served: their answers are NOT in
    # the result cache yet, so hit-vs-miss under the open breaker is
    # fully controlled by THIS test
    g_hot, g_trip, g_cold = db.get_all_nodes("Gene", names=True)[6:9]
    q_hot, q_trip, q_cold = _ast(g_hot), _ast(g_trip), _ast(g_cold)
    expect_hot = das.query(q_hot)  # single path: answers, never caches

    # 1. warm the cache through the healthy serving path (settle put)
    hot = coal.submit(tenant, q_hot, HANDLE)
    assert hot.result(timeout=120) == expect_hot

    # 2. trip the breaker: every settle fetch fails (RetryPolicy
    #    exhausts), the group degrades to per-query fallbacks (answers
    #    stay correct) and the settle failure trips the threshold
    fault.configure("seed=4;sites=settle_fetch;every=1;max=1000")
    trip = coal.submit(tenant, q_trip, HANDLE)
    assert trip.result(timeout=120) == das.query(q_trip)
    fault.configure(None)
    assert _poll(lambda: coal.stats["breaker_state"] == fault.OPEN)

    # 3. open breaker: the cached answer still serves...
    hot2 = coal.submit(tenant, q_hot, HANDLE)
    assert hot2.result(timeout=120) == expect_hot
    # ...while a cold query is rejected retryable, typed
    exc = coal.submit(tenant, q_cold, HANDLE).exception(timeout=120)
    assert isinstance(exc, BreakerOpenError)
    assert exc.retry_after_ms is not None


# -- service surface: typed retryable statuses ----------------------------


def test_server_maps_typed_retryable_statuses():
    from das_tpu.service import protocol
    from das_tpu.service.server import DasService

    svc = DasService()
    st = svc._map_failure(CoalescerSaturatedError("queue at bound"))
    parsed = protocol.parse_retryable(st["msg"])
    assert not st["success"] and parsed["kind"] == "saturated"

    st = svc._map_failure(DasDeadlineError(deadline_ms=75_000))
    parsed = protocol.parse_retryable(st["msg"])
    # the hint is the short capacity-return beat, NOT the expired
    # deadline's duration — a 75 s deadline miss must not park clients
    # for 75 s
    assert parsed["kind"] == "deadline" and parsed["retry_after_ms"] == 50

    st = svc._map_failure(BreakerOpenError(retry_after_ms=120))
    parsed = protocol.parse_retryable(st["msg"])
    assert parsed["kind"] == "breaker_open"
    assert parsed["retry_after_ms"] == 120

    # a generic failure stays a generic (non-retryable) status
    try:
        raise ValueError("semantic")
    except ValueError as exc:
        st = svc._map_failure(exc)
    assert protocol.parse_retryable(st["msg"]) is None


def test_client_honors_retryable_with_one_bounded_backoff():
    from das_tpu.service import protocol
    from das_tpu.service.client import DasClient

    client = DasClient.__new__(DasClient)  # no channel: stub call()
    replies = [protocol.retryable_status("breaker_open", 20),
               {"success": True, "msg": "ok"}]
    calls = []
    client.call = lambda rpc, **req: (calls.append(rpc), replies.pop(0))[1]
    out = DasClient.call_with_retry(client, "query", key="k", query="q")
    assert out["success"] and calls == ["query", "query"]

    # ONE retry only, even if the server keeps rejecting
    replies = [protocol.retryable_status("saturated", 1)] * 3
    calls.clear()
    out = DasClient.call_with_retry(client, "query", key="k", query="q")
    assert not out["success"] and len(calls) == 2

    # a non-retryable failure never retries
    replies = [{"success": False, "msg": "hard failure"}]
    calls.clear()
    out = DasClient.call_with_retry(client, "query", key="k", query="q")
    assert not out["success"] and len(calls) == 1


def test_coalescer_stats_surface_robustness_counters(tensor_served):
    from das_tpu.service.server import DasService, _Tenant

    das, _db, _queries, _baseline = tensor_served
    svc = DasService()
    tenant = _Tenant("t", das)
    svc.tenants["t"] = tenant
    tenant.get_coalescer()
    stats = svc.coalescer_stats()
    for key in ("deadline_expired", "breaker_rejections", "breaker_trips",
                "breaker_recoveries", "breaker_open_tenants"):
        assert key in stats, key
    per = stats["tenants"]["t"]
    assert per["breaker_state"] == fault.CLOSED
    # the metrics exposition carries the new gauges
    text = svc.metrics_text()
    assert "serving_breaker_trips" in text
    assert "serving_deadline_expired" in text


# -- DL015 on fixtures and a real site ------------------------------------


def test_dl015_fires_on_renamed_real_site(tmp_path):
    """Mutated-copy regression (the DL004/DL007 idiom): rename a REAL
    maybe_fail literal in query/fused.py — the analyzer must fire on
    the undeclared site."""
    src = (REPO / "das_tpu/query/fused.py").read_text()
    needle = 'fault.maybe_fail("settle_fetch")'
    assert src.count(needle) == 2, "fused.py layout changed"
    mutated = tmp_path / "fused_mutated.py"
    mutated.write_text(
        src.replace(needle, 'fault.maybe_fail("settle_fetchh")', 1)
    )
    findings = run_analysis(
        [mutated, REPO / "das_tpu/fault/__init__.py"],
        rules=["DL015"], partial=True,
    )
    assert any("settle_fetchh" in f.message for f in findings), "\n".join(
        f.render() for f in findings
    )
    # the committed module next to the registry stays clean
    clean = run_analysis(
        [REPO / "das_tpu/query/fused.py",
         REPO / "das_tpu/fault/__init__.py"],
        rules=["DL015"], partial=True,
    )
    assert clean == [], "\n".join(f.render() for f in clean)


def test_dl015_bans_injection_in_dispatch_half(tmp_path):
    """Injecting inside a dispatch half must fail lint even when the
    site name is declared — the DL001/DL010 async contract."""
    fixture = tmp_path / "mod.py"
    fixture.write_text(
        'FAULT_SITES = ("seam",)\n'
        "class _Job:\n"
        "    def dispatch(self):\n"
        '        maybe_fail("seam")\n'
        "        return self\n"
        "    def settle(self, host, out):\n"
        "        return True\n"
    )
    findings = run_analysis([fixture], rules=["DL015"])
    assert any("dispatch half" in f.message for f in findings)
