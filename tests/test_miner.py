"""Pattern-miner tests over the animals KB and the bio atomspace."""

import pytest

from das_tpu.mining import PatternMiner
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB

HUMAN = "af12f10f9ae2002a1607ba0b47ba8407"


@pytest.fixture(scope="module")
def miner(animals_data):
    db = MemoryDB(animals_data)
    m = PatternMiner(db, halo_length=2, link_rate=1.0, seed=3)
    m.expand_halo([HUMAN])
    return m


def test_halo_expansion(miner):
    # level 0: every link touching human (2 Inheritance + Similarity closure)
    assert len(miner.levels[0]) > 0
    assert all(h not in miner.levels[1] for h in miner.levels[0])
    assert miner.universe_size == sum(len(l) for l in miner.levels)
    # halo of the whole 26-link KB can never exceed the KB
    assert miner.universe_size <= 26


def test_build_patterns_counts(miner):
    total = miner.build_patterns()
    assert total > 0
    # every candidate respects the support threshold and its count is exact
    for level in miner.candidates:
        for c in level:
            assert c.count >= 1
            assert miner.count(c.pattern) == c.count


def test_mine_stochastic(miner):
    if not miner.candidates:
        miner.build_patterns()
    best = miner.mine(ngram=2, epochs=30)
    assert best is not None
    assert best.count >= 1


def test_mine_exhaustive_beats_or_ties_stochastic(miner):
    if not miner.candidates:
        miner.build_patterns()
    sto = miner.mine(ngram=2, epochs=30)
    exh = miner.mine_exhaustive(ngram=2)
    assert exh is not None
    assert exh.isurprisingness >= sto.isurprisingness


def test_device_counting_path(animals_data):
    db = TensorDB(animals_data)
    m = PatternMiner(db, halo_length=1, link_rate=1.0)
    m.expand_halo([HUMAN])
    m.build_patterns()
    best = m.mine(ngram=2, epochs=20)
    assert best is not None
    # cross-check the winning composite on the host algebra
    from das_tpu.query.ast import PatternMatchingAnswer

    host_db = MemoryDB(animals_data)
    answer = PatternMatchingAnswer()
    matched = best.pattern.matched(host_db, answer)
    assert (len(answer.assignments) if matched else 0) == best.count


def _fake_candidate(name, count):
    from das_tpu.mining.miner import _Candidate
    from das_tpu.query.ast import Link, Variable

    return _Candidate(Link(name, [Variable("V1"), Variable("V2")], True), count, 0)


def test_isurprisingness_negative_branch(miner):
    """Anti-correlated pair: joint far below independence scores positive
    via the min(est) - p branch (notebook cell 5 two-sided formula)."""
    a = _fake_candidate("TA", 400)
    b = _fake_candidate("TB", 400)
    saved = miner.universe_size
    miner.universe_size = 1000
    try:
        # independence: 0.4 * 0.4 = 0.16; observed p = 10/1000 = 0.01
        score = miner.isurprisingness(10, [a, b])
        assert score == pytest.approx(0.16 - 0.01)
        # normalized divides by p
        score_n = miner.isurprisingness(10, [a, b], normalized=True)
        assert score_n == pytest.approx((0.16 - 0.01) / 0.01)
    finally:
        miner.universe_size = saved


def test_isurprisingness_22_partitions(miner):
    """At n=4 the (2,2) binary partitions participate in the estimate band
    (notebook cell 5 n==4 branch): two correlated pairs, independent of
    each other, are NOT surprising."""
    terms = [_fake_candidate(f"T{i}", 100) for i in range(4)]
    saved, saved_cache = miner.universe_size, dict(miner._joint_count_cache)
    miner.universe_size = 1000
    miner._joint_count_cache.clear()
    key = lambda idxs: frozenset(repr(terms[i].pattern) for i in idxs)
    # pairs (0,1) and (2,3) strongly correlated; all other joints tiny
    joints = {
        (0, 1): 100, (2, 3): 100,
        (0, 2): 10, (0, 3): 10, (1, 2): 10, (1, 3): 10,
        (0, 1, 2): 10, (0, 1, 3): 10, (0, 2, 3): 10, (1, 2, 3): 10,
    }
    try:
        for idxs, n in joints.items():
            miner._joint_count_cache[key(idxs)] = n
        # observed joint = 10/1000 = 0.01 == prob(01)*prob(23) = 0.1*0.1
        score = miner.isurprisingness(10, terms)
        assert score == pytest.approx(0.0, abs=1e-12)
    finally:
        miner.universe_size = saved
        miner._joint_count_cache = saved_cache


def test_joint_count_memoized(miner):
    if not miner.candidates:
        miner.build_patterns()
    miner._joint_count_cache.clear()
    calls = []
    original = miner.count

    def counting(q):
        calls.append(q)
        return original(q)

    miner.count = counting
    try:
        flat = [c for level in miner.candidates for c in level][:3]
        if len(flat) == 3:
            miner.isurprisingness(1, flat)
            first = len(calls)
            miner.isurprisingness(1, flat)
            assert len(calls) == first  # all subset joints served from cache
    finally:
        miner.count = original


@pytest.mark.full
def test_sharded_backend_counting_path(animals_data):
    """The miner on the mesh-sharded backend: host closed forms (trivial
    single-term counts + the star fold) answer the hot loops with zero
    device work — the ShardedDB has no single-chip `.dev` buffers, and
    the old gate silently dropped it to the pure host algebra."""
    from das_tpu.core.config import DasConfig
    from das_tpu.parallel.mesh import make_mesh
    from das_tpu.parallel.sharded_db import ShardedDB
    from das_tpu.query import compiler
    from das_tpu.query.ast import PatternMatchingAnswer

    sdb = ShardedDB(animals_data, DasConfig(), mesh=make_mesh(8))
    m = PatternMiner(sdb, halo_length=1, link_rate=1.0)
    m.expand_halo([HUMAN])
    compiler.reset_route_counts()
    m.build_patterns()
    best = m.mine(ngram=2, epochs=20)
    assert best is not None
    assert compiler.ROUTE_COUNTS["star"] > 0  # joints took the host fold
    # identical mining outcome on the single-chip backend
    t = PatternMiner(TensorDB(animals_data), halo_length=1, link_rate=1.0)
    t.expand_halo([HUMAN])
    t.build_patterns()
    t_best = t.mine(ngram=2, epochs=20)
    assert (best.count, best.term_handles) == (t_best.count, t_best.term_handles)
    # cross-check the winner on the host algebra
    host = MemoryDB(animals_data)
    answer = PatternMatchingAnswer()
    matched = best.pattern.matched(host, answer)
    assert (len(answer.assignments) if matched else 0) == best.count


def test_sharded_star_fold_device_env_takes_host_fold(animals_data, monkeypatch):
    """DAS_TPU_STAR_FOLD=device must not crash on the mesh store (it has
    no single-chip buffers) — the star route falls to the host fold."""
    from das_tpu.core.config import DasConfig
    from das_tpu.parallel.mesh import make_mesh
    from das_tpu.parallel.sharded_db import ShardedDB
    from das_tpu.query import compiler, starcount
    from das_tpu.query.ast import Link, PatternMatchingAnswer, Variable

    monkeypatch.setenv("DAS_TPU_STAR_FOLD", "device")
    sdb = ShardedDB(animals_data, DasConfig(), mesh=make_mesh(8))
    from das_tpu.query.ast import And

    q = And([
        Link("Inheritance", [Variable("V0"), Variable("A")], True),
        Link("Inheritance", [Variable("V0"), Variable("B")], True),
    ])
    plans = compiler.plan_query(sdb, q)
    lane = starcount.plan_star(sdb, plans)
    assert lane is not None
    n = starcount.star_count_many(sdb, [lane])[0]
    host = MemoryDB(animals_data)
    a = PatternMatchingAnswer()
    matched = q.matched(host, a)
    assert n == (len(a.assignments) if matched else 0) > 0
