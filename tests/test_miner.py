"""Pattern-miner tests over the animals KB and the bio atomspace."""

import pytest

from das_tpu.mining import PatternMiner
from das_tpu.storage.memory_db import MemoryDB
from das_tpu.storage.tensor_db import TensorDB

HUMAN = "af12f10f9ae2002a1607ba0b47ba8407"


@pytest.fixture(scope="module")
def miner(animals_data):
    db = MemoryDB(animals_data)
    m = PatternMiner(db, halo_length=2, link_rate=1.0, seed=3)
    m.expand_halo([HUMAN])
    return m


def test_halo_expansion(miner):
    # level 0: every link touching human (2 Inheritance + Similarity closure)
    assert len(miner.levels[0]) > 0
    assert all(h not in miner.levels[1] for h in miner.levels[0])
    assert miner.universe_size == sum(len(l) for l in miner.levels)
    # halo of the whole 26-link KB can never exceed the KB
    assert miner.universe_size <= 26


def test_build_patterns_counts(miner):
    total = miner.build_patterns()
    assert total > 0
    # every candidate respects the support threshold and its count is exact
    for level in miner.candidates:
        for c in level:
            assert c.count >= 1
            assert miner.count(c.pattern) == c.count


def test_mine_stochastic(miner):
    if not miner.candidates:
        miner.build_patterns()
    best = miner.mine(ngram=2, epochs=30)
    assert best is not None
    assert best.count >= 1
    assert best.isurprisingness >= 0.0


def test_mine_exhaustive_beats_or_ties_stochastic(miner):
    if not miner.candidates:
        miner.build_patterns()
    sto = miner.mine(ngram=2, epochs=30)
    exh = miner.mine_exhaustive(ngram=2)
    assert exh is not None
    assert exh.isurprisingness >= sto.isurprisingness


def test_device_counting_path(animals_data):
    db = TensorDB(animals_data)
    m = PatternMiner(db, halo_length=1, link_rate=1.0)
    m.expand_halo([HUMAN])
    m.build_patterns()
    best = m.mine(ngram=2, epochs=20)
    assert best is not None
    # cross-check the winning composite on the host algebra
    from das_tpu.query.ast import PatternMatchingAnswer

    host_db = MemoryDB(animals_data)
    answer = PatternMatchingAnswer()
    matched = best.pattern.matched(host_db, answer)
    assert (len(answer.assignments) if matched else 0) == best.count
