"""TensorDB device-probe parity vs MemoryDB, on the virtual-CPU platform."""

import pytest

import das_tpu.query.ast as q
from das_tpu.core.schema import WILDCARD
from das_tpu.query.ast import PatternMatchingAnswer
from das_tpu.storage.tensor_db import TensorDB


@pytest.fixture(scope="module")
def tensor_db(animals_data):
    return TensorDB(animals_data)


def H(db, name):
    return db.get_node_handle("Concept", name)


def as_set(matches):
    return {
        (m if isinstance(m, str) else (m[0], tuple(m[1]))) for m in matches
    }


PROBES = [
    ("Inheritance", lambda db: [H(db, "human"), H(db, "mammal")]),
    ("Inheritance", lambda db: [WILDCARD, H(db, "mammal")]),
    ("Inheritance", lambda db: [H(db, "mammal"), WILDCARD]),
    ("Inheritance", lambda db: [WILDCARD, WILDCARD]),
    ("Similarity", lambda db: [H(db, "human"), WILDCARD]),
    ("Similarity", lambda db: [WILDCARD, H(db, "human")]),
    ("Similarity", lambda db: [WILDCARD, WILDCARD]),
    (WILDCARD, lambda db: [H(db, "human"), H(db, "mammal")]),
    (WILDCARD, lambda db: [H(db, "human"), WILDCARD]),
    (WILDCARD, lambda db: [WILDCARD, WILDCARD]),
    ("Inheritance", lambda db: [H(db, "nonexistent"), WILDCARD]),
    ("UnknownType", lambda db: [WILDCARD, WILDCARD]),
]


@pytest.mark.parametrize("idx", range(len(PROBES)))
def test_get_matched_links_parity(animals_db, tensor_db, idx):
    link_type, mk = PROBES[idx]
    targets = mk(animals_db)
    assert as_set(tensor_db.get_matched_links(link_type, list(targets))) == as_set(
        animals_db.get_matched_links(link_type, list(targets))
    )


def test_template_probe_parity(animals_db, tensor_db):
    for template in (
        ["Inheritance", "Concept", "Concept"],
        ["Similarity", "Concept", "Concept"],
        ["List", "Concept", "Concept"],
    ):
        assert as_set(tensor_db.get_matched_type_template(template)) == as_set(
            animals_db.get_matched_type_template(template)
        )


def test_matched_type_parity(animals_db, tensor_db):
    for t in ("Inheritance", "Similarity", "Nope"):
        assert as_set(tensor_db.get_matched_type(t)) == as_set(
            animals_db.get_matched_type(t)
        )


def test_incoming_parity(animals_db, tensor_db):
    h = H(animals_db, "mammal")
    assert set(tensor_db.get_incoming(h)) == set(animals_db.get_incoming(h))
    assert len(tensor_db.get_incoming(h)) == 5  # 4 in + 1 out-link... see KB


def test_full_engine_over_tensor_db(animals_db, tensor_db):
    """The host evaluator over TensorDB must equal MemoryDB answers."""
    queries = [
        q.Link("Inheritance", [q.Variable("V1"), q.Variable("V2")], True),
        q.Link("Similarity", [q.Node("Concept", "human"), q.Variable("V1")], False),
        q.And([
            q.Link("Inheritance", [q.Variable("V1"), q.Variable("V3")], True),
            q.Link("Inheritance", [q.Variable("V2"), q.Variable("V3")], True),
            q.Link("Similarity", [q.Variable("V1"), q.Variable("V2")], False),
        ]),
        q.LinkTemplate(
            "Inheritance",
            [q.TypedVariable("V1", "Concept"), q.TypedVariable("V2", "Concept")],
            True,
        ),
    ]
    for query in queries:
        a1, a2 = PatternMatchingAnswer(), PatternMatchingAnswer()
        m1 = query.matched(animals_db, a1)
        # fresh AST per backend (handles memoized on the atom objects)
        m2 = query.matched(tensor_db, a2)
        assert m1 == m2
        assert a1.assignments == a2.assignments


def test_capacity_retry(animals_data):
    from das_tpu.core.config import DasConfig

    db = TensorDB(animals_data, DasConfig(initial_result_capacity=2))
    matches = db.get_matched_links("Inheritance", [WILDCARD, WILDCARD])
    assert len(matches) == 12
