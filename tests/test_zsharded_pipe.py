"""Sharded serving parity (ISSUE 3).

Pins, in one place (markers `sharded` + `pipeline`, standalone via
`ops/pytests.sh sharded`):

  * mesh tenants ride the dispatch/settle pipeline: pipelined (depth 2)
    and serial (depth 1) coalescer execution issue IDENTICAL shard_map
    program counts and identical answers on a ShardedDB tenant;
  * a repeated mesh query through the serving path is a pure host dict
    lookup — zero shard_map programs, zero host fetches;
  * the sharded kernel route (ShardedPlanSig.use_kernels) produces
    BIT-IDENTICAL binding tables vs the lowered shard-local bodies, with
    pinned dispatch counts (sharded=1 program per query, sharded_kernel
    counting the kernel-routed subset);
  * the widened ResultCache scope: tree-composite entries (query/tree.py)
    and count-batch entries (query/fused.py count_batch) hit at zero
    device dispatches and invalidate exactly on commit — on TensorDB and
    (tree path) on ShardedDB.

Compile-budget note (ROADMAP tier-1): every query here reuses a handful
of fixed plan shapes on the small animals KB — no per-test interpret-mode
compiles (off-TPU the kernel route runs by direct discharge).
"""

import threading
from concurrent.futures import Future

import numpy as np
import pytest

from das_tpu import kernels
from das_tpu.api.atomspace import DistributedAtomSpace, QueryOutputFormat
from das_tpu.core.config import DasConfig
from das_tpu.models.animals import animals_metta
from das_tpu.query import compiler, fused
from das_tpu.query.ast import And, Link, Node, Not, Or, Variable
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.tensor_db import TensorDB

pytestmark = [pytest.mark.sharded, pytest.mark.pipeline]

#: extends the pair query's answer set: chimp→mammal exists, so the new
#: platypus→chimp edge adds ($1=platypus, $2=chimp) exactly after commit
COMMIT = '(: "platypus" Concept)\n(Inheritance "platypus" "chimp")'


def _pair_query(concept="mammal"):
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Node("Concept", concept)], True),
    ])


def _chain_query():
    return And([
        Link("Inheritance", [Variable("$1"), Variable("$2")], True),
        Link("Inheritance", [Variable("$2"), Variable("$3")], True),
    ])


def _neg_query():
    return And([
        Link("Inheritance", [Variable("$1"), Node("Concept", "mammal")], True),
        Not(Link("Inheritance", [Variable("$1"), Node("Concept", "animal")], True)),
    ])


def _sharded_das(config=None):
    from das_tpu.parallel.sharded_db import ShardedDB

    data = load_metta_text(animals_metta())
    db = ShardedDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zsp", db=db), db


def _tensor_das(config=None):
    data = load_metta_text(animals_metta())
    db = TensorDB(data, config or DasConfig())
    return DistributedAtomSpace(database_name="zspt", db=db), db


@pytest.fixture(scope="module")
def env():
    """One shared mesh store for the non-mutating tests, so the module
    pays each shard_map compile once."""
    return _sharded_das()


class _FakeTenant:
    def __init__(self, das):
        self.das = das
        self.lock = threading.RLock()


def _drive(coalescer, tenant, queries):
    futs = [
        coalescer.submit(tenant, q, QueryOutputFormat.HANDLE)
        for q in queries
    ]
    return [f.result(timeout=120) for f in futs]


# -- mesh pipeline --------------------------------------------------------


def test_mesh_pipelined_matches_serial_answers_and_program_count(env):
    """The tentpole pin: pipelining the mesh path changes WHEN shard_map
    programs run relative to host settle, never HOW MANY — depth 2 and
    depth 1 issue identical sharded program counts and identical answers
    over distinct groundings (cache off so every query pays the mesh)."""
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = env
    tenant = _FakeTenant(das)
    concepts = ["mammal", "animal", "reptile", "plant"]
    queries = [_pair_query(c) for c in concepts]
    prev = db.config.result_cache_size
    db.config.result_cache_size = 0
    try:
        das.query_many(queries)  # warm compile + caps

        serial = QueryCoalescer(max_batch=2, pipeline_depth=1)
        kernels.reset_dispatch_counts()
        serial_answers = _drive(serial, tenant, queries)
        serial_programs = kernels.DISPATCH_COUNTS["sharded"]

        piped = QueryCoalescer(max_batch=2, pipeline_depth=2)
        kernels.reset_dispatch_counts()
        piped_answers = _drive(piped, tenant, queries)
        piped_programs = kernels.DISPATCH_COUNTS["sharded"]
    finally:
        db.config.result_cache_size = prev

    assert piped_answers == serial_answers
    assert serial_programs == len(concepts)  # cache really was off
    assert piped_programs == serial_programs, (piped_programs, serial_programs)
    # the batch went through the mesh job pipeline, not per-query queries
    assert all(a == das.query(q) for a, q in zip(piped_answers, queries))


def test_mesh_pipeline_inflight_peak_reaches_depth(env):
    """Under a backlog the worker actually keeps mesh batches in flight
    (dispatches N+1 before settling N) — sharded parity of the zpipeline
    pin."""
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = env
    tenant = _FakeTenant(das)
    c = QueryCoalescer(max_batch=1, pipeline_depth=2)
    futs = [
        (c._queue.put((tenant, _pair_query(), QueryOutputFormat.HANDLE, f)), f)[1]
        for f in (Future() for _ in range(8))
    ]
    c._ensure_worker()
    answers = [f.result(timeout=120) for f in futs]
    assert len(set(answers)) == 1
    assert c.stats["inflight_peak"] >= 2, c.stats


def test_mesh_query_many_cache_hit_zero_programs(env):
    """A repeated mesh query through the serving path is a host dict
    lookup: zero shard_map programs, zero host fetches."""
    das, db = env
    q = _pair_query()
    first = das.query_many([q, q])  # one program: in-batch dedup aliases
    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = das.query_many([q, q])
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches, "mesh cache hit paid a fetch"
    assert kernels.DISPATCH_COUNTS["sharded"] == 0, kernels.DISPATCH_COUNTS


def test_mesh_commit_invalidates_serving_cache():
    das, db = _sharded_das()
    q = _pair_query()
    before = das.query_many([q])
    version = db.delta_version
    das.load_metta_text(COMMIT)
    assert db.delta_version > version
    after = das.query_many([q])
    assert after != before
    assert after == [das.query(q)]  # post-commit ground truth


# -- sharded kernel route -------------------------------------------------


def test_sharded_kernel_route_bit_identical_with_pinned_dispatches(env):
    """Fixed fuzz shape-combos (grounded pair, ungrounded chain, negation)
    through the SAME executor: the kernel-routed shard_map program must
    return bit-identical binding tables and counts vs the lowered one,
    each answered in exactly ONE sharded program."""
    from das_tpu.parallel.fused_sharded import get_sharded_executor

    das, db = env
    ex = get_sharded_executor(db)
    combos = [_pair_query(), _pair_query("animal"), _chain_query(), _neg_query()]
    prev = db.config.use_pallas_kernels
    try:
        for qi, q in enumerate(combos):
            plans = compiler.plan_query(db, q)
            assert plans is not None

            db.config.use_pallas_kernels = "off"
            ex.execute(plans)  # warm caps so the pinned runs are 1 dispatch
            kernels.reset_dispatch_counts()
            low = ex.execute(plans)
            assert kernels.DISPATCH_COUNTS["sharded"] == 1, (qi, kernels.DISPATCH_COUNTS)
            assert kernels.DISPATCH_COUNTS["sharded_kernel"] == 0

            db.config.use_pallas_kernels = "on"
            kernels.reset_dispatch_counts()
            ker = ex.execute(plans)
            assert kernels.DISPATCH_COUNTS["sharded"] == 1, (qi, kernels.DISPATCH_COUNTS)
            assert kernels.DISPATCH_COUNTS["sharded_kernel"] == 1

            assert ker.count == low.count, qi
            assert ker.var_names == low.var_names, qi
            assert np.array_equal(np.asarray(ker.valid), np.asarray(low.valid)), qi
            assert np.array_equal(np.asarray(ker.vals), np.asarray(low.vals)), qi
    finally:
        db.config.use_pallas_kernels = prev


def test_sharded_kernel_route_counts_in_dispatch(env):
    """ROUTE_COUNTS gains the sharded_kernel route: a mesh query answered
    with the kernel route enabled counts under both sharded and
    sharded_kernel (the fused/fused_kernel convention)."""
    das, db = env
    prev = db.config.use_pallas_kernels
    try:
        db.config.use_pallas_kernels = "on"
        compiler.reset_route_counts()
        das.query(_pair_query("reptile"))
        assert compiler.ROUTE_COUNTS["sharded"] == 1
        assert compiler.ROUTE_COUNTS["sharded_kernel"] == 1
        db.config.use_pallas_kernels = "off"
        compiler.reset_route_counts()
        das.query(_pair_query("plant"))
        assert compiler.ROUTE_COUNTS["sharded"] == 1
        assert compiler.ROUTE_COUNTS["sharded_kernel"] == 0
    finally:
        db.config.use_pallas_kernels = prev


# -- widened result-cache scope: tree composites --------------------------


def test_tree_composite_cache_hit_zero_dispatch_tensor():
    """An Or query runs through the generalized tree executor; its cached
    composite tables answer the repeat with zero device programs and zero
    host fetches, and a commit invalidates exactly the stale entry."""
    das, db = _tensor_das()
    q = Or([
        Link("Inheritance", [Variable("$1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("$1"), Node("Concept", "reptile")], True),
    ])
    first = das.query(q)
    ex = fused.get_executor(db)
    assert ex.tree_results.stats["misses"] >= 1

    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = das.query(q)
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches, "tree hit paid a host fetch"
    assert sum(kernels.DISPATCH_COUNTS.values()) == 0, kernels.DISPATCH_COUNTS
    assert ex.tree_results.stats["hits"] >= 1

    # commit invalidation: platypus→mammal lands in the Or's answer set
    das.load_metta_text('(: "platypus" Concept)\n(Inheritance "platypus" "mammal")')
    after = das.query(q)
    assert after != first
    assert db.get_node_handle("Concept", "platypus") in after
    assert ex.tree_results.stats["invalidations"] >= 1


def test_tree_composite_cache_sharded_unordered(env):
    """The mesh tree executor (ShardedTreeOps — incl. the check_vma-shimmed
    replicate path) shares the cache scope: an unordered Similarity probe
    repeats with zero shard_map programs."""
    das, db = env
    q = Link("Similarity", [Variable("$1"), Node("Concept", "human")], False)
    first = das.query(q)
    ex = db.tables._fused_executor
    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = das.query(q)
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches
    assert sum(kernels.DISPATCH_COUNTS.values()) == 0, kernels.DISPATCH_COUNTS
    assert ex.tree_results.stats["hits"] >= 1


# -- widened result-cache scope: count batches ----------------------------


def test_count_batch_cache_hit_and_commit_invalidation():
    das, db = _tensor_das()
    ex = fused.get_executor(db)
    plans_list = [
        compiler.plan_query(db, _pair_query(c)) for c in ("mammal", "animal")
    ]
    first = ex.count_batch(plans_list)
    assert all(n is not None for n in first)

    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = ex.count_batch(plans_list)
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches, "count hit paid a device fetch"
    assert sum(kernels.DISPATCH_COUNTS.values()) == 0, kernels.DISPATCH_COUNTS

    das.load_metta_text(COMMIT)  # platypus→chimp→mammal: +1 pair
    after = ex.count_batch(
        [compiler.plan_query(db, _pair_query(c)) for c in ("mammal", "animal")]
    )
    assert after[0] == first[0] + 1, (first, after)


def test_count_batch_kernel_route_parity():
    """count_many's vmapped group programs route through the kernels
    behind use_pallas_kernels: identical counts, count_kernel telemetry in
    ROUTE_COUNTS and DISPATCH_COUNTS."""
    das, db = _tensor_das(DasConfig(result_cache_size=0))
    ex = fused.get_executor(db)
    queries = [_pair_query(c) for c in ("mammal", "animal", "reptile")]
    plans_of = lambda: [compiler.plan_query(db, q) for q in queries]  # noqa: E731

    db.config.use_pallas_kernels = "off"
    lowered = ex.count_batch(plans_of())

    db.config.use_pallas_kernels = "on"
    compiler.reset_route_counts()
    kernels.reset_dispatch_counts()
    kerneled = ex.count_batch(plans_of())
    assert kerneled == lowered
    assert compiler.ROUTE_COUNTS["count_kernel"] == len(queries)
    assert kernels.DISPATCH_COUNTS["count_kernel"] >= 1
    assert kernels.DISPATCH_COUNTS["count"] == kernels.DISPATCH_COUNTS["count_kernel"]


def test_miner_count_many_rides_the_caches():
    """The miner's joint counts repeat across the stochastic loop: the
    second count_many answers the non-trivial entries from the cache."""
    from das_tpu.mining.miner import PatternMiner

    das, db = _tensor_das()
    miner = PatternMiner(db)
    queries = [_pair_query("mammal"), _pair_query("animal")]
    first = miner.count_many(queries)
    kernels.reset_dispatch_counts()
    fetches = fused.FETCH_COUNTS["n"]
    again = miner.count_many(queries)
    assert again == first
    assert fused.FETCH_COUNTS["n"] == fetches
    assert sum(kernels.DISPATCH_COUNTS.values()) == 0, kernels.DISPATCH_COUNTS


# -- serving stats --------------------------------------------------------


def test_service_stats_surface_sharded_and_tenants(env):
    """coalescer_stats() surfaces the sharded routes and a per-tenant
    breakdown with inflight_peak."""
    from das_tpu.service.server import DasService

    das, db = env
    service = DasService()
    token = service.attach_tenant("zsp_stats", das)
    q = "Node n Concept mammal, Link Inheritance $1 $2, Link Inheritance $2 n, AND"
    for _ in range(3):
        reply = service.query(
            {"key": token, "query": q, "output_format": "HANDLE"}
        )
        assert reply["success"], reply["msg"]
    stats = service.coalescer_stats()
    assert "sharded" in stats["routes"] and "sharded_kernel" in stats["routes"]
    assert stats["routes"]["sharded"] >= 1
    per = stats["tenants"]["zsp_stats"]
    assert per["items"] >= 3
    assert "inflight_peak" in per and "cache_hits" in per
    assert stats["cache_hits"] >= 1  # repeats hit the mesh result cache


# -- async end-to-end serving on the mesh (ISSUE 6) -----------------------


def test_mesh_speculative_dispatch_keeps_program_count(env):
    """Mesh parity of the speculation pin: a depth-3 window dispatching
    groups before earlier settles land issues IDENTICAL shard_map
    program counts to serial, with the speculative dispatches counted.
    Same plan shape as the module's other tests — no new mesh compiles."""
    from das_tpu.service.coalesce import QueryCoalescer

    das, db = env
    tenant = _FakeTenant(das)
    concepts = ["mammal", "animal", "reptile", "plant"]
    queries = [_pair_query(c) for c in concepts]
    prev = db.config.result_cache_size
    db.config.result_cache_size = 0
    try:
        das.query_many(queries)  # warm compile + caps

        serial = QueryCoalescer(max_batch=1, pipeline_depth=1)
        kernels.reset_dispatch_counts()
        serial_answers = _drive(serial, tenant, queries)
        serial_programs = kernels.DISPATCH_COUNTS["sharded"]

        # pre-queue the backlog so the window actually fills past one
        # unsettled group (speculation), then drain
        spec = QueryCoalescer(
            max_batch=1, pipeline_depth=3, pipeline_depth_max=6
        )
        kernels.reset_dispatch_counts()
        futs = []
        for q in queries:
            f = Future()
            spec._queue.put((tenant, q, QueryOutputFormat.HANDLE, f))
            futs.append(f)
        spec._ensure_worker()
        spec_answers = [f.result(timeout=120) for f in futs]
        spec_programs = kernels.DISPATCH_COUNTS["sharded"]
    finally:
        db.config.result_cache_size = prev

    assert spec_answers == serial_answers
    assert serial_programs == len(concepts)  # cache really was off
    assert spec_programs == serial_programs, (spec_programs, serial_programs)
    assert spec.stats["speculative_dispatches"] >= 1, spec.stats


def test_mesh_streaming_settle_yields_incrementally(env):
    """Mesh tenants ride the streaming settle: settle_iter yields each
    query's answer as its verdict lands, identical to the blocking
    settle()/query() ground truth."""
    das, db = env
    queries = [_pair_query("mammal"), _pair_query("animal")]
    expected = [das.query(q) for q in queries]
    job = das.query_many_dispatch(queries)
    seen = []
    for i, answer in job.settle_iter():
        assert not isinstance(answer, Exception), answer
        seen.append((i, answer))
    assert len(seen) == len(queries)
    assert [a for _, a in sorted(seen)] == expected
