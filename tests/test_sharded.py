"""Sharded backend over the 8-virtual-device CPU mesh: answers must be
identical to the host algebra."""

import jax
import pytest

from das_tpu.core.config import DasConfig
from das_tpu.parallel.mesh import make_mesh
from das_tpu.parallel.sharded_db import ShardedDB
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Not,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)


@pytest.fixture(scope="module")
def sdb(animals_data):
    assert len(jax.devices()) >= 8, "conftest must provide 8 virtual devices"
    return ShardedDB(animals_data, DasConfig(), mesh=make_mesh(8))


QUERIES = [
    lambda: Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
    lambda: Link("Inheritance", [Variable("V1"), Variable("V2")], True),
    lambda: And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
    lambda: And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        Not(Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)),
    ]),
    lambda: LinkTemplate(
        "Inheritance",
        [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
        True,
    ),
    lambda: And([
        LinkTemplate(
            "Inheritance",
            [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
            True,
        ),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
]


@pytest.mark.parametrize("idx", range(len(QUERIES)))
def test_sharded_matches_host(sdb, animals_db, idx):
    a_host = PatternMatchingAnswer()
    m_host = QUERIES[idx]().matched(animals_db, a_host)
    a_shard = PatternMatchingAnswer()
    m_shard = sdb.query_sharded(QUERIES[idx](), a_shard)
    assert m_shard is not None, "query should be compilable on the mesh"
    assert m_shard == m_host
    assert a_shard.assignments == a_host.assignments


def test_sharded_small_capacity(animals_data):
    sdb = ShardedDB(
        animals_data, DasConfig(initial_result_capacity=2), mesh=make_mesh(8)
    )
    a = PatternMatchingAnswer()
    m = sdb.query_sharded(
        And([
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
            Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        ]),
        a,
    )
    assert m
    assert len(a.assignments) == 7


def test_sharded_via_facade(animals_data):
    from das_tpu.api.atomspace import DistributedAtomSpace
    from das_tpu.models.animals import animals_metta

    das = DistributedAtomSpace(backend="sharded")
    das.load_metta_text(animals_metta())
    assert das.count_atoms() == (14, 26)
    matched, answer = das.query_answer(
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)
    )
    assert matched
    assert len(answer.assignments) == 4
