"""The native regression battery (scripts/regression.py) enumerates the
reference's full ~55-query list (/root/reference/scripts/regression.py:20-312)
case-for-case, and its normalized output is machine-diffed here against the
reference script ITSELF running through the compat shim — on every backend
(VERDICT r04 item 6).

The reference script's memory-vs-tensor identity is already proven by
test_reference_shim.py; diffing each native backend against the shimmed
reference/memory output therefore closes the chain for all three."""

import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.full  # heavy block: excluded from `pytest -m quick`

from tests.test_reference_shim import _shim_env, normalize_regression_output

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(cmd, env, timeout=900):
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=timeout, cwd=REPO,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


@pytest.fixture(scope="module")
def reference_blocks():
    out = _run(
        [sys.executable, "/root/reference/scripts/regression.py"],
        _shim_env(DAS_TPU_BACKEND="memory"),
    )
    blocks = normalize_regression_output(out)
    assert len(blocks) == 56
    return blocks


@pytest.mark.parametrize("backend", ["memory", "tensor", "sharded"])
def test_native_battery_matches_reference_script(reference_blocks, backend):
    env = _shim_env()
    if backend == "sharded":
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    out = _run(
        [sys.executable, "scripts/regression.py", "--backend", backend],
        env,
        timeout=1800,
    )
    native = normalize_regression_output(out)
    assert len(native) == len(reference_blocks) == 56
    for i, (a, b) in enumerate(zip(native, reference_blocks)):
        assert a == b, f"block {i} ({b[0] if b[0] else 'list'}) differs on {backend}"
