"""Worst-case-optimal k-way multiway join kernel (ISSUE 9; markers
`kernels` + `multiway`, standalone via `ops/pytests.sh multiway`).

Pins, in order of load-bearing-ness:

  * BIT-IDENTICAL outputs of the k-way leapfrog kernel vs the lowered
    binary-join chain on randomized tables — POSITIONAL equality of the
    emitted rows, masks, AND the partial pair totals (the chain's
    would-be intermediate sizes), k=2..4, empty intersections and
    non-chunk-multiple capacities included;
  * grid-chunked == single-block == chain under a shrunk VMEM budget,
    plus exactly ONE DAS_TPU_PALLAS_INTERPRET=1 case (the real
    pallas_call grid/BlockSpec lowering);
  * the bio suite end-to-end on the multiway route (fused AND sharded
    shard-local): assignment sets identical to the binary chain, with
    the fused_multiway / sharded_multiway dispatch pins proving the
    route actually ran (no silent fallback);
  * the acceptance pin: ZERO capacity-retry rounds on a skew-heavy hub
    fan-out star where the binary chain pays >=1 retry tier — strictly
    fewer compiled programs, exact est-vs-actual;
  * the capacity-seed floor (ISSUE 9 satellite, the PR-8
    `_join_cap_seed` bug class): an operator-shrunk
    initial_result_capacity cannot clamp the multiway output seed below
    the exact k-way intersection bound (stats.multiway_rows);
  * the off-TPU discharge prologue hoist (satellite): a tiled-join
    launch traces its sort/search prologue ONCE, not once per chunk.

Compile-budget note: KBs are small; the acceptance arm runs count-only
programs (DAS_TPU_STAR=0 forces them off the closed-form star counter
onto the executors whose capacities are the thing under test).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from das_tpu import kernels, planner
from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.ops.join import _join_tables_impl
from das_tpu.query import compiler
from das_tpu.query.ast import And, Link, Node, Not, Variable
from das_tpu.storage.tensor_db import TensorDB

pytestmark = [pytest.mark.kernels, pytest.mark.multiway]


# -- kernel-level differential: k-way vs the lowered binary chain --------


def _chain(lv, lm, tails, vcol0, tail_meta, cap, inter_cap=1 << 17):
    """The lowered left-deep fold the multiway kernel replaces: one
    binary sort-merge join per tail, intermediates materialized at
    `inter_cap` (ample — the differential wants the chain's SETTLED
    output, the thing a retried chain converges to)."""
    acc_v, acc_m = jnp.asarray(lv), jnp.asarray(lm)
    totals = []
    for t, ((tv, tm), (vcol, extras)) in enumerate(zip(tails, tail_meta)):
        c = cap if t == len(tails) - 1 else inter_cap
        acc_v, acc_m, tot = _join_tables_impl(
            acc_v, acc_m, jnp.asarray(tv), jnp.asarray(tm),
            ((vcol0, vcol),), tuple(extras), c,
        )
        totals.append(int(tot))
    return np.asarray(acc_v), np.asarray(acc_m), totals


def _random_star(rng, k, n_left_max=40, n_tail_max=50, domain=8):
    n_left = int(rng.integers(1, n_left_max))
    lv = rng.integers(0, domain, (n_left, 2)).astype(np.int32)
    lm = rng.random(n_left) < 0.8
    tails, meta = [], []
    for _ in range(k - 1):
        n = int(rng.integers(1, n_tail_max))
        w = int(rng.integers(1, 4))
        tv = rng.integers(0, domain, (n, w)).astype(np.int32)
        tm = rng.random(n) < 0.8
        vcol = int(rng.integers(0, w))
        extras = tuple(c for c in range(w) if c != vcol)
        tails.append((jnp.asarray(tv), jnp.asarray(tm)))
        meta.append((vcol, extras))
    return jnp.asarray(lv), jnp.asarray(lm), tails, tuple(meta)


def test_multiway_kernel_vs_chain_randomized():
    rng = np.random.default_rng(42)
    for trial in range(8):
        k = 2 + trial % 3  # k = 2, 3, 4
        lv, lm, tails, meta = _random_star(rng, k)
        cap = 512
        ov, om, tots = kernels.multiway_join_impl(
            lv, lm, tails, 1, meta, cap, interpret=True,
        )
        cv, cm, ctots = _chain(lv, lm, tails, 1, meta, cap)
        assert [int(t) for t in np.asarray(tots)] == ctots, trial
        assert np.array_equal(np.asarray(om), cm[:cap]), trial
        assert np.array_equal(np.asarray(ov), cv[:cap]), trial


def test_multiway_kernel_empty_intersection():
    """Disjoint v domains: zero rows, zero totals, all-invalid mask —
    and an all-invalid left side behaves identically."""
    rng = np.random.default_rng(3)
    lv = rng.integers(0, 4, (16, 2)).astype(np.int32)
    lm = np.ones(16, bool)
    tv = (rng.integers(0, 4, (20, 2)) + 100).astype(np.int32)  # disjoint
    tails = [(jnp.asarray(tv), jnp.asarray(np.ones(20, bool)))] * 2
    meta = ((0, (1,)), (0, (1,)))
    ov, om, tots = kernels.multiway_join_impl(
        jnp.asarray(lv), jnp.asarray(lm), tails, 1, meta, 64,
        interpret=True,
    )
    assert not np.asarray(om).any()
    assert [int(t) for t in np.asarray(tots)] == [0, 0]
    ov2, om2, tots2 = kernels.multiway_join_impl(
        jnp.asarray(lv), jnp.asarray(np.zeros(16, bool)), tails, 1, meta,
        64, interpret=True,
    )
    assert not np.asarray(om2).any()
    assert [int(t) for t in np.asarray(tots2)] == [0, 0]


def test_multiway_tiled_parity_non_chunk_multiple(monkeypatch):
    """A shrunk VMEM budget grid-chunks the output window (capacity NOT
    a chunk multiple): chunks must concatenate bit-identically to the
    single-block layout and to the chain."""
    from das_tpu.kernels import budget

    rng = np.random.default_rng(7)
    n_left = 2000
    lv = rng.integers(0, 30, (n_left, 2)).astype(np.int32)
    lm = rng.random(n_left) < 0.9
    tails, meta = [], []
    for _ in range(2):
        tv = rng.integers(0, 30, (1500, 2)).astype(np.int32)
        tm = rng.random(1500) < 0.9
        tails.append((jnp.asarray(tv), jnp.asarray(tm)))
        meta.append((0, (1,)))
    meta = tuple(meta)
    cap = 5000  # not a multiple of any pow2 chunk
    args = (jnp.asarray(lv), jnp.asarray(lm), tails, 1, meta, cap)
    o1, m1, t1 = kernels.multiway_join_impl(*args, interpret=True)
    # ~80k true pairs at this density: the chain arm needs an
    # intermediate capacity ABOVE that, or its clipped intermediate
    # under-reports the second join's total (exactly the blow-up the
    # multiway route exists to delete)
    cv, cm, ctots = _chain(lv, lm, tails, 1, meta, cap, inter_cap=1 << 18)
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "400000")
    plan = budget.multiway_plan(n_left, 2, ((1500, 2), (1500, 2)), 4, cap)
    assert plan.route == budget.ROUTE_TILED and plan.chunk_rows > 0
    o2, m2, t2 = kernels.multiway_join_impl(*args, interpret=True)
    assert np.array_equal(np.asarray(o1), np.asarray(o2))
    assert np.array_equal(np.asarray(m1), np.asarray(m2))
    assert np.array_equal(np.asarray(t1), np.asarray(t2))
    assert np.array_equal(np.asarray(o1), cv[:cap])
    assert [int(t) for t in np.asarray(t1)] == ctots


def test_multiway_pallas_interpreter(monkeypatch):
    """THE DAS_TPU_PALLAS_INTERPRET=1 case: the real pallas_call grid +
    BlockSpec lowering (chunk-blocked outputs, carried totals block)
    once, on a fixed tiled shape."""
    from das_tpu.kernels import budget

    rng = np.random.default_rng(11)
    lv = rng.integers(0, 12, (600, 2)).astype(np.int32)
    lm = rng.random(600) < 0.9
    tv = rng.integers(0, 12, (500, 2)).astype(np.int32)
    tm = rng.random(500) < 0.9
    tails = [(jnp.asarray(tv), jnp.asarray(tm))] * 2
    meta = ((0, (1,)), (0, (1,)))
    cap = 3000
    args = (jnp.asarray(lv), jnp.asarray(lm), tails, 1, meta, cap)
    want = kernels.multiway_join_impl(*args, interpret=True)
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "150000")
    assert budget.multiway_plan(600, 2, ((500, 2), (500, 2)), 4, cap).tiled
    monkeypatch.setenv("DAS_TPU_PALLAS_INTERPRET", "1")
    got = kernels.multiway_join_impl(*args, interpret=True)
    for w, g in zip(want, got):
        assert np.array_equal(np.asarray(w), np.asarray(g))


# -- satellite: the off-TPU discharge hoists the tiled-join prologue -----


def test_tiled_join_prologue_hoisted_once_per_launch(monkeypatch):
    """PR 4 recorded the off-TPU tiled-join discharge honestly as
    slower-than-lowered on CPU because the sort/search prologue re-ran
    every chunk; run_grid_kernel's per-launch memo now computes it ONCE
    and reuses it across the python-loop grid steps."""
    from das_tpu.kernels import budget, join as kjoin

    calls = {"n": 0}
    real = kjoin._join_prologue

    def counting(*a, **kw):
        calls["n"] += 1
        return real(*a, **kw)

    monkeypatch.setattr(kjoin, "_join_prologue", counting)
    monkeypatch.setenv("DAS_TPU_VMEM_BUDGET", "200000")
    rng = np.random.default_rng(13)
    lv = jnp.asarray(rng.integers(0, 9, (800, 2)).astype(np.int32))
    lm = jnp.asarray(np.ones(800, bool))
    rv = jnp.asarray(rng.integers(0, 9, (800, 2)).astype(np.int32))
    rm = jnp.asarray(np.ones(800, bool))
    cap = 1 << 15
    plan = budget.join_plan(800, 2, 800, 2, 1, 3, cap)
    assert plan.tiled and -(-cap // plan.chunk_rows) > 1  # a real grid
    kernels.join_tables_impl(
        lv, lm, rv, rm, ((0, 0),), (1,), cap, interpret=True,
    )
    assert calls["n"] == 1, (
        f"tiled-join prologue ran {calls['n']}x for one "
        f"{-(-cap // plan.chunk_rows)}-step discharge launch"
    )


# -- end-to-end: the bio suite on the multiway route ---------------------


def _bio_data(**kw):
    data, _g, _p = build_bio_atomspace(**kw)
    return data


def _star3():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Member", [Variable("V4"), Variable("V3")], True),
    ])


def _suite(db):
    names = db.get_all_nodes("Gene", names=True)[:2]
    return [
        _star3(),
        # the bio 3-var triangle: multiway grounds the 2-clause star
        # prefix on V3, the Interacts tail joins binary
        And([
            Link("Member", [Variable("V1"), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
            Link("Interacts", [Variable("V1"), Variable("V2")], True),
        ]),
        And([
            Link("Member", [Node("Gene", names[0]), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
            Link("Interacts", [Node("Gene", names[0]), Variable("V2")], True),
        ]),
        And([
            Link("Member", [Variable("V2"), Variable("V3")], True),
            Link("Member", [Node("Gene", names[1]), Variable("V3")], True),
            Not(Link("Interacts", [Node("Gene", names[1]), Variable("V2")],
                     True)),
        ]),
    ]


def _no_env_arms(monkeypatch):
    # config decides the arm; an exported env var must not collapse both
    # arms onto one route (the planner_ab idiom), and learned caps must
    # not leak across processes
    monkeypatch.setenv("DAS_TPU_XLA_CACHE", "0")
    monkeypatch.delenv("DAS_TPU_MULTIWAY", raising=False)
    monkeypatch.delenv("DAS_TPU_PLANNER", raising=False)


def test_multiway_bio_end_to_end_fused(monkeypatch):
    _no_env_arms(monkeypatch)
    data = _bio_data(
        n_genes=60, n_processes=15, members_per_gene=4, n_interactions=80,
        seed=7,
    )
    db_on = TensorDB(data, DasConfig(use_multiway="on"))
    das_on = DistributedAtomSpace(database_name="zmw_on", db=db_on)
    db_off = TensorDB(data, DasConfig(use_multiway="off"))
    das_off = DistributedAtomSpace(database_name="zmw_off", db=db_off)
    kernels.reset_dispatch_counts()
    for q_on, q_off in zip(_suite(db_on), _suite(db_off)):
        m_on, a_on = das_on.query_answer(q_on)
        m_off, a_off = das_off.query_answer(q_off)
        assert m_on == m_off
        assert a_on.assignments == a_off.assignments, q_on
        assert a_on.negation == a_off.negation
    # the route genuinely ran (no silent chain fallback)
    assert kernels.DISPATCH_COUNTS["fused_multiway"] >= 4
    assert compiler.ROUTE_COUNTS["fused_multiway"] >= 4
    # explain surfaces the decision: the 3-clause star fuses whole
    ex = planner.explain(db_on, _star3())
    assert ex["route"] == "fused_multiway"
    assert ex["multiway"] == 3
    assert len(ex["join_cap_seeds"]) == 1  # ONE output buffer, no chain


def test_multiway_bio_end_to_end_sharded(monkeypatch):
    from das_tpu.parallel.sharded_db import ShardedDB

    _no_env_arms(monkeypatch)
    data = _bio_data(
        n_genes=60, n_processes=15, members_per_gene=4, n_interactions=80,
        seed=7,
    )
    db_on = ShardedDB(data, DasConfig(use_multiway="on"))
    das_on = DistributedAtomSpace(database_name="zmws_on", db=db_on)
    db_off = ShardedDB(data, DasConfig(use_multiway="off"))
    das_off = DistributedAtomSpace(database_name="zmws_off", db=db_off)
    kernels.reset_dispatch_counts()
    for q_on, q_off in zip(_suite(db_on)[:2], _suite(db_off)[:2]):
        m_on, a_on = das_on.query_answer(q_on)
        m_off, a_off = das_off.query_answer(q_off)
        assert m_on == m_off
        assert a_on.assignments == a_off.assignments, q_on
    assert kernels.DISPATCH_COUNTS["sharded_multiway"] >= 2
    assert compiler.ROUTE_COUNTS["sharded_multiway"] >= 2


# -- the acceptance pin: zero retries where the chain pays a tier --------


def _skew_kb():
    """120 genes x 3 memberships over 40 processes at skew 1.1: hub
    processes own degrees far above the median.  The chain's FIRST
    intermediate seeds exactly (pairwise degree dot), but its SECOND
    rides the independence model — Σ deg³ concentrates on the hubs far
    past est × CAP_MARGIN, a guaranteed retry tier.  The multiway
    route's ONE output buffer seeds from the exact k-way intersection
    product instead."""
    data, _g, _p = build_bio_atomspace(
        n_genes=120, n_processes=40, members_per_gene=3,
        n_interactions=0, seed=17, skew=1.1,
    )
    return data


def test_multiway_zero_retries_where_chain_pays(monkeypatch):
    _no_env_arms(monkeypatch)
    # off the closed-form star counter: the executors' capacities (the
    # thing under test) only engage on the fused count path
    monkeypatch.setenv("DAS_TPU_STAR", "0")
    data = _skew_kb()
    q = _star3()

    # chain arm: planner OFF — with it on, the ISSUE-10 satellite reuses
    # the exact k-way statistic for the chain's deeper star seeds too
    # (test_chain_star_seeds_settle_round0 pins that), so the retry tier
    # this pin needs only survives on the legacy blind seeds
    db_chain = TensorDB(
        data, DasConfig(use_multiway="off", use_planner="off")
    )
    kernels.reset_dispatch_counts()
    n_chain = compiler.count_matches(db_chain, q)
    chain_programs = kernels.DISPATCH_COUNTS["fused"]
    assert chain_programs >= 2, (
        "the chain was expected to pay a capacity-retry tier on this "
        f"skew shape; dispatches={kernels.DISPATCH_COUNTS}"
    )

    db_mw = TensorDB(data, DasConfig(use_multiway="auto"))
    planner.reset_planner_counts()
    kernels.reset_dispatch_counts()
    n_mw = compiler.count_matches(db_mw, q)
    mw_programs = kernels.DISPATCH_COUNTS["fused"]
    assert n_mw == n_chain  # same answer
    assert kernels.DISPATCH_COUNTS["fused_multiway"] >= 1  # route ran
    assert mw_programs == 1, kernels.DISPATCH_COUNTS
    assert mw_programs < chain_programs  # strictly fewer compiles
    assert planner.PLANNER_COUNTS["round0"] >= 1
    assert planner.PLANNER_COUNTS["retries"] == 0
    # margin-free exact seed: est == actual on the multiway step
    assert planner.snapshot()["actual_vs_est_ratio"] == 1.0


def test_chain_star_seeds_settle_round0(monkeypatch):
    """ISSUE 10 satellite (the ROADMAP multiway remainder): when the
    CHAIN route is chosen over multiway, its deeper star-prefix
    intermediates reuse the exact `stats.multiway_rows` k-way statistic
    instead of the independence model — the residual retry tier on
    skew-heavy star prefixes dies even with the k-way kernel declined.
    Same skew shape as the acceptance pin above: the chain must now
    settle in ONE program with the planner on."""
    _no_env_arms(monkeypatch)
    monkeypatch.setenv("DAS_TPU_STAR", "0")
    data = _skew_kb()
    q = _star3()

    db = TensorDB(data, DasConfig(use_multiway="off"))
    plans = compiler.plan_query(db, q)
    from das_tpu.planner.stats import estimator_for

    exact_rows, exact = estimator_for(db).multiway_rows(plans, "V3")
    assert exact
    planned = planner.plan_conjunction(db, plans)
    assert planned is not None and planned.multiway == 0  # chain route
    # the DEEPER seed (second intermediate) now bounds the exact k-way
    # figure — the independence model sat far under it on this skew
    assert planned.join_cap_seeds[1] >= exact_rows
    assert planned.est_join_rows[1] == int(exact_rows)

    planner.reset_planner_counts()
    kernels.reset_dispatch_counts()
    compiler.count_matches(db, q)
    assert kernels.DISPATCH_COUNTS["fused"] == 1, kernels.DISPATCH_COUNTS
    assert planner.PLANNER_COUNTS["round0"] >= 1
    assert planner.PLANNER_COUNTS["retries"] == 0
    assert planner.snapshot()["actual_vs_est_ratio"] == 1.0


# -- the capacity-seed floor (the PR-8 _join_cap_seed bug class) ---------


def test_shrunk_capacity_cannot_clamp_multiway_seed(monkeypatch):
    """An operator-shrunk initial_result_capacity must not clamp the
    multiway output seed below the exact k-way intersection bound
    (stats.multiway_rows) — that would be a GUARANTEED retry round, the
    exact bug class the PR-8 `_join_cap_seed` fix closed for binary
    joins."""
    from das_tpu.planner.stats import estimator_for

    _no_env_arms(monkeypatch)
    data = _bio_data(
        n_genes=50, n_processes=10, members_per_gene=3, n_interactions=0,
        seed=5,
    )
    cfg = DasConfig(use_multiway="on", initial_result_capacity=64)
    db = TensorDB(data, cfg)
    das = DistributedAtomSpace(database_name="zmw_seed", db=db)
    q = _star3()
    plans = compiler.plan_query(db, q)
    est = estimator_for(db)
    shared = "V3"
    exact_rows, exact = est.multiway_rows(plans, shared)
    assert exact and exact_rows > cfg.initial_result_capacity  # bug setup
    planned = planner.plan_conjunction(db, plans)
    assert planned is not None and planned.multiway == 3
    assert planned.join_cap_seeds[0] >= exact_rows, (
        "the configured clamp must not force the multiway seed under "
        f"the exact bound: seed={planned.join_cap_seeds[0]} "
        f"rows={exact_rows}"
    )
    kernels.reset_dispatch_counts()
    das.query(q)
    assert kernels.DISPATCH_COUNTS["fused"] == 1, kernels.DISPATCH_COUNTS


def test_multiway_rows_exact_vs_brute_force(monkeypatch):
    """stats.multiway_rows == the brute-force Σ_v Π_j deg_j(v) over the
    support intersection, and folds to the estimate when a clause has
    no support extraction."""
    from das_tpu.planner.stats import estimator_for

    _no_env_arms(monkeypatch)
    data = _bio_data(
        n_genes=40, n_processes=12, members_per_gene=3, n_interactions=0,
        seed=9,
    )
    db = TensorDB(data, DasConfig())
    plans = compiler.plan_query(db, _star3())
    est = estimator_for(db)
    rows, exact = est.multiway_rows(plans, "V3")
    assert exact
    # brute force over the host copies
    from collections import Counter

    from das_tpu.storage.atom_table import host_segments

    deg = Counter()
    for b in host_segments(db, plans[0].arity):
        keys = b.key_type
        import numpy as _np

        lo = int(_np.searchsorted(keys, _np.int32(plans[0].type_id), "left"))
        hi = int(_np.searchsorted(keys, _np.int32(plans[0].type_id), "right"))
        rows_local = b.order_by_type[lo:hi]
        vcol = plans[0].var_cols[plans[0].var_names.index("V3")]
        for r in _np.asarray(rows_local):
            deg[int(b.targets[r, vcol])] += 1
    want = sum(d ** 3 for d in deg.values())
    assert int(rows) == want
    # memoized second call
    assert est.multiway_rows(plans, "V3") == (rows, True)


# -- DL002 cache-key honesty for the new signature field -----------------


def test_multiway_field_in_plan_signatures():
    from das_tpu.parallel.fused_sharded import ShardedPlanSig
    from das_tpu.query import fused

    f_names = [f.name for f in dataclasses.fields(fused.FusedPlanSig)]
    s_names = [f.name for f in dataclasses.fields(ShardedPlanSig)]
    assert "multiway" in f_names
    assert "multiway" in s_names
    a = fused.FusedPlanSig((), (), (), multiway=2)
    b = fused.FusedPlanSig((), (), (), multiway=0)
    assert a != b and hash(a) != hash(b)
