"""Compiled device path vs host algebra: identical answers on every
compilable query; graceful fallback (None) otherwise."""

import pytest

import das_tpu.query.compiler as compiler
from das_tpu.query.ast import (
    And,
    Link,
    LinkTemplate,
    Node,
    Not,
    Or,
    PatternMatchingAnswer,
    TypedVariable,
    Variable,
)
from das_tpu.storage.tensor_db import TensorDB


@pytest.fixture(scope="module")
def tdb(animals_data):
    return TensorDB(animals_data)


def host_answer(db, query):
    a = PatternMatchingAnswer()
    m = query.matched(db, a)
    return m, a


def device_answer(db, query):
    a = PatternMatchingAnswer()
    m = compiler.query_on_device(db, query, a)
    return m, a


COMPILABLE = [
    lambda: Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
    lambda: Link("Inheritance", [Variable("V1"), Variable("V2")], True),
    lambda: Link("Inheritance", [Variable("V1"), Variable("V1")], True),
    lambda: Link("Inheritance", [Node("Concept", "mammal"), Variable("V1")], True),
    lambda: And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
    lambda: And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
    lambda: And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        Not(Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)),
    ]),
    lambda: LinkTemplate(
        "Inheritance",
        [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
        True,
    ),
    lambda: And([
        LinkTemplate(
            "Inheritance",
            [TypedVariable("V1", "Concept"), TypedVariable("V2", "Concept")],
            True,
        ),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
    lambda: And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Not(Link("Inheritance", [Variable("V3"), Variable("V4")], True)),
    ]),
]


@pytest.mark.parametrize("idx", range(len(COMPILABLE)))
def test_device_matches_host(tdb, idx):
    m_host, a_host = host_answer(tdb, COMPILABLE[idx]())
    m_dev, a_dev = device_answer(tdb, COMPILABLE[idx]())
    assert m_dev is not None, "query should be compilable"
    assert m_dev == m_host
    assert a_dev.assignments == a_host.assignments


FALLBACK = [
    lambda: Link("Similarity", [Variable("V1"), Variable("V2")], False),
    lambda: Or([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
    lambda: Node("Concept", "human"),
    lambda: And([Not(Link("Inheritance", [Variable("V1"), Variable("V2")], True))]),
]


@pytest.mark.parametrize("idx", range(len(FALLBACK)))
def test_non_compilable_returns_none(tdb, idx):
    assert compiler.plan_query(tdb, FALLBACK[idx]()) is None


def test_count_matches(tdb):
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    a = PatternMatchingAnswer()
    q2 = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    q2.matched(tdb, a)
    assert compiler.count_matches(tdb, q) == len(a.assignments)


def test_tiny_capacity_still_correct(animals_data):
    from das_tpu.core.config import DasConfig

    small = TensorDB(animals_data, DasConfig(initial_result_capacity=4))
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    m, a = device_answer(small, q)
    q2 = And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    m2, a2 = host_answer(small, q2)
    assert m == m2
    assert a.assignments == a2.assignments
