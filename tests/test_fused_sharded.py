"""Fused sharded execution (parallel/fused_sharded.py): single-dispatch
shard_map programs with size-adaptive collectives.

VERDICT r1 #5 'done when': parity on an 8-virtual-device mesh at >=10^6
links, at most ONE data collective per broadcast join (two all_to_alls for
a hash-partitioned join — each table moves once), sharded
capacity-overflow retry, and a hub-heavy (skewed join key) workload."""

import numpy as np
import pytest

import das_tpu.query.compiler as qc
from das_tpu.core.config import DasConfig
from das_tpu.models.animals import animals_metta
from das_tpu.parallel import fused_sharded as fs
from das_tpu.parallel.sharded_db import ShardedDB
from das_tpu.query.ast import (
    And,
    Link,
    Node,
    Not,
    Or,
    PatternMatchingAnswer,
    Variable,
)
from das_tpu.storage.atom_table import load_metta_text


@pytest.fixture(scope="module")
def sharded_animals(animals_data):
    return ShardedDB(animals_data)


def _host_answer(db, q):
    a = PatternMatchingAnswer()
    matched = q.matched(db, a)
    return matched, a


ANIMAL_QUERIES = [
    Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
    And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ]),
    And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
    ]),  # zero answers: empty-positive-term definitive
    And([
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),
        Not(Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True)),
    ]),
]


@pytest.mark.parametrize("qi", range(len(ANIMAL_QUERIES)))
def test_fused_sharded_parity(sharded_animals, qi):
    q = ANIMAL_QUERIES[qi]
    host_matched, host = _host_answer(sharded_animals, q)
    answer = PatternMatchingAnswer()
    got = sharded_animals.query_sharded(q, answer)
    assert got is not None
    assert bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments


def test_fused_sharded_single_dispatch_counts(sharded_animals):
    """The fused executor must answer directly (no staged fallback) for
    ordinary conjunctions, including definitive zero answers."""
    ex = fs.get_sharded_executor(sharded_animals)
    plans = qc.plan_query(sharded_animals, ANIMAL_QUERIES[1])
    res = ex.execute(plans)
    assert res is not None and not res.reseed_needed
    host_matched, host = _host_answer(sharded_animals, ANIMAL_QUERIES[1])
    assert res.count == len(host.assignments)
    plans0 = qc.plan_query(sharded_animals, ANIMAL_QUERIES[2])
    res0 = ex.execute(plans0)
    assert res0 is not None and not res0.reseed_needed and res0.count == 0


def count_prims(jaxpr, names):
    out = {n: 0 for n in names}
    todo = [jaxpr]
    while todo:
        jx = todo.pop()
        for eqn in jx.eqns:
            if eqn.primitive.name in out:
                out[eqn.primitive.name] += 1
            for v in eqn.params.values():
                vs = v if isinstance(v, (list, tuple)) else [v]
                for x in vs:
                    if hasattr(x, "eqns"):        # raw Jaxpr
                        todo.append(x)
                    elif hasattr(x, "jaxpr"):     # ClosedJaxpr
                        todo.append(x.jaxpr)
    return out


def test_collectives_per_join():
    """Broadcast joins move ONE all_gather; hash-partitioned joins move
    each side once (two all_to_alls).  Counted in the traced jaxpr, which
    is what actually lowers."""
    import jax

    S = 4
    term = lambda negated=False: fs.FusedTermSig(
        arity=2, route=fs.ROUTE_TYPE_POS, p0=1, extra_fixed=(),
        var_cols=(0,), eq_pairs=(), var_names=("V1",), negated=negated,
    )
    term2 = fs.FusedTermSig(
        arity=2, route=fs.ROUTE_TYPE_POS, p0=1, extra_fixed=(),
        var_cols=(0,), eq_pairs=(), var_names=("V1",), negated=False,
    )
    from das_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(S)

    def trace(exch):
        sig = fs.ShardedPlanSig(
            terms=(term(), term2), term_caps=(16, 16), join_caps=(64,),
            exch_caps=(exch,), n_shards=S,
        )
        fn, _ = fs.build_fused_sharded(sig, mesh, count_only=True)
        arrays = tuple(
            (
                np.zeros((S, 8), np.int64), np.zeros((S, 8), np.int32),
                np.zeros((S, 8, 2), np.int32), np.zeros((S, 8), np.int32),
            )
            for _ in range(2)
        )
        keys = (np.int64(1), np.int64(2))
        fvals = (np.zeros(0, np.int32), np.zeros(0, np.int32))
        return count_prims(
            jax.make_jaxpr(fn)(arrays, keys, fvals).jaxpr,
            ("all_gather", "all_to_all"),
        )

    broadcast = trace(0)
    assert broadcast["all_gather"] == 1  # one data collective for the join
    assert broadcast["all_to_all"] == 0
    partitioned = trace(16)
    assert partitioned["all_gather"] == 0
    assert partitioned["all_to_all"] == 2  # each side moves exactly once


def test_sharded_capacity_overflow_retry(animals_data):
    cfg = DasConfig(initial_result_capacity=16)
    db = ShardedDB(animals_data, cfg)
    q = And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
    ])
    host_matched, host = _host_answer(db, q)
    answer = PatternMatchingAnswer()
    got = db.query_sharded(q, answer)
    assert bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments


@pytest.mark.full
def test_hub_heavy_partitioned_join(monkeypatch):
    """Skewed join key: almost every link shares one hub target, so the
    hash-partitioned exchange funnels nearly everything to one shard —
    exercises per-destination overflow retry.  Answers stay host-exact.
    Index-join routing is disabled so the partitioned path actually runs
    (whole-type right sides would otherwise take the index join)."""
    import das_tpu.query.fused as qf

    # apply_index_joins resolves plan_index_joins from query.fused's module
    # globals — patch it THERE (patching the name once re-exported into
    # fused_sharded would be a no-op and silently skip the partitioned path)
    monkeypatch.setattr(
        qf, "plan_index_joins",
        lambda sigs, start=0: (
            tuple([-1] * max(
                0, sum(1 for s in sigs if not s.negated) - 1 - start
            )),
            {},
        ),
    )
    lines = ["(: Concept Type)", "(: Edge Type)", '(: "hub" Concept)']
    n = 300
    for i in range(n):
        lines.append(f'(: "n{i}" Concept)')
    for i in range(n):
        lines.append(f'(Edge "n{i}" "hub")')  # hub-heavy
    for i in range(0, n, 50):
        lines.append(f'(Edge "n{i}" "n{i + 1}")')
    data = load_metta_text("\n".join(lines))
    # small caps force several retries; broadcast_limit=0 forces the
    # hash-partitioned all_to_all join even for this table size
    db = ShardedDB(data, DasConfig(initial_result_capacity=32))
    ex = fs.get_sharded_executor(db)
    ex.broadcast_limit = 0
    q = And([
        Link("Edge", [Variable("V1"), Variable("V3")], True),
        Link("Edge", [Variable("V2"), Variable("V3")], True),
    ])
    host_matched, host = _host_answer(db, q)
    answer = PatternMatchingAnswer()
    got = db.query_sharded(q, answer)
    assert bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments
    assert len(host.assignments) >= n * n * 0.9  # hub join really is big


@pytest.mark.slow
def test_million_link_parity_and_scaling():
    """>=10^6 links on the 8-virtual-device mesh: grounded conjunction
    answers match the single-device tensor backend exactly."""
    from das_tpu.models.bio import build_bio_atomspace
    from das_tpu.storage.tensor_db import TensorDB

    data, _, _ = build_bio_atomspace(
        n_genes=150_000, n_processes=15_000, members_per_gene=5,
        n_interactions=150_000, n_evaluations=0,
    )
    nodes, links = data.count_atoms()
    assert links >= 1_000_000
    db = ShardedDB(data)
    tdb = TensorDB(data)
    genes = db.get_all_nodes("Gene", names=True)[:3]
    for g in genes:
        q = And([
            Link("Member", [Node("Gene", g), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
        ])
        sharded_answer = PatternMatchingAnswer()
        got = db.query_sharded(q, sharded_answer)
        assert got is not None
        want = qc.count_matches(tdb, q)
        assert len(sharded_answer.assignments) == want


@pytest.mark.full
def test_sharded_or_unordered_run_on_device_tree(sharded_animals):
    """Or / unordered / nested queries on the sharded backend route to the
    MESH tree evaluator (round 2 used a replicated single-chip tree copy,
    VERDICT r02 item 5; round 1 silently ran single-threaded host
    Python)."""
    queries = [
        Or([
            Link("Inheritance", [Variable("V1"), Node("Concept", "plant")], True),
            Link("Similarity", [Variable("V1"), Node("Concept", "snake")], False),
        ]),
        Link("Similarity", [Variable("V1"), Variable("V2")], False),  # unordered
    ]
    for q in queries:
        host_matched, host = _host_answer(sharded_animals, q)
        answer = PatternMatchingAnswer()
        got = sharded_animals.query_sharded(q, answer)
        assert got is not None, f"fell back to host for {q}"
        assert bool(got) == bool(host_matched)
        assert answer.assignments == host.assignments
    assert not hasattr(sharded_animals, "_tree_tensor_db"), (
        "unordered/Or shapes must run on the mesh, not the replica"
    )


def test_sharded_index_join_parity_and_single_collective(sharded_animals):
    """Whole-type right sides broadcast the LEFT once and probe each
    shard's slab posting index — answers host-exact, exactly one data
    collective for the join."""
    import jax

    q = And([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V1"), Variable("V2")], True),  # whole-type
    ])
    host_matched, host = _host_answer(sharded_animals, q)
    answer = PatternMatchingAnswer()
    got = sharded_animals.query_sharded(q, answer)
    assert bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments

    # the compiled program for this shape used an index join...
    ex = fs.get_sharded_executor(sharded_animals)
    index_sigs = [
        ps for ps, _count_only in ex._cache
        if any(p >= 0 for p in ps.index_joins)
    ]
    assert index_sigs, "sharded index join did not activate"
    # ...and its traced program moves exactly ONE data collective
    sig = index_sigs[0]
    fn, _names = fs.build_fused_sharded(sig, sharded_animals.mesh, count_only=True)
    sb = sharded_animals.tables.buckets[2]
    p = next(p for p in sig.index_joins if p >= 0)
    arrays = (
        (sb.key_type_pos[1], sb.order_by_type_pos[1], sb.targets, sb.type_id),
        (sb.key_type_pos[p], sb.order_by_type_pos[p], sb.targets, sb.type_id),
    )
    keys = (np.int64(1), np.int64(0))
    fvals = (np.zeros(0, np.int32), np.zeros(0, np.int32))
    counts = count_prims(
        jax.make_jaxpr(fn)(arrays, keys, fvals).jaxpr,
        ("all_gather", "all_to_all"),
    )
    assert counts == {"all_gather": 1, "all_to_all": 0}


def test_or_of_conjunctions_runs_on_mesh(animals_data):
    """An all-positive Or of compilable conjunctions executes branch-by-
    branch on the mesh (union of materialized sets) — WITHOUT building the
    single-device tree replica."""
    db = ShardedDB(animals_data, DasConfig())
    q = Or([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        And([
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
            Link("Inheritance", [Variable("V2"), Node("Concept", "animal")], True),
        ]),
    ])
    answer = PatternMatchingAnswer()
    matched = db.query_sharded(q, answer)
    assert matched is not None
    host = PatternMatchingAnswer()
    host_matched = q.matched(db, host)
    assert bool(matched) == bool(host_matched)
    assert answer.assignments == host.assignments
    assert not hasattr(db, "_tree_tensor_db"), "must not build the replica"
    # a branch grounded on a nonexistent atom is statically empty: the
    # OTHER branches still run on the mesh (no replica)
    q_ghost = Or([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Link("Inheritance", [Variable("V1"), Node("Concept", "ghost")], True),
    ])
    ag = PatternMatchingAnswer()
    mg = db.query_sharded(q_ghost, ag)
    hg = PatternMatchingAnswer()
    hmg = q_ghost.matched(db, hg)
    assert mg is not None and bool(mg) == bool(hmg)
    assert ag.assignments == hg.assignments
    assert not hasattr(db, "_tree_tensor_db"), "ghost branch must not force the replica"
    # a Not branch disqualifies branch-by-branch execution (de-Morgan
    # joint-negative handling): the MESH tree answers, still host-exact
    q2 = Or([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Not(Link("Inheritance", [Variable("V1"), Variable("V2")], True)),
    ])
    a2 = PatternMatchingAnswer()
    m2 = db.query_sharded(q2, a2)
    h2 = PatternMatchingAnswer()
    hm2 = q2.matched(db, h2)
    assert m2 is not None and bool(m2) == bool(hm2)
    assert a2.assignments == h2.assignments
    assert not hasattr(db, "_tree_tensor_db"), "negated Or must run on the mesh"


MESH_TREE_QUERIES = [
    # all-variable unordered probe
    Link("Similarity", [Variable("V1"), Variable("V2")], False),
    # unordered with grounded member
    Link("Set", [Node("Concept", "human"), Variable("V1"), Variable("V2"),
                 Variable("V3")], False),
    # composite join: ordered x unordered
    And([
        Link("Inheritance", [Variable("V1"), Variable("V3")], True),
        Link("Inheritance", [Variable("V2"), Variable("V3")], True),
        Link("Similarity", [Variable("V1"), Variable("V2")], False),
    ]),
    # negation against an unordered accumulator
    And([
        Link("Set", [Variable("V1"), Variable("V2"), Variable("V3"),
                     Variable("V4")], False),
        Not(Link("Similarity", [Variable("V1"), Variable("V2")], False)),
    ]),
    # negated Or (de-Morgan difference)
    Or([
        Link("Inheritance", [Variable("V1"), Node("Concept", "mammal")], True),
        Not(Link("Inheritance", [Variable("V1"), Variable("V2")], True)),
    ]),
    # nested And inside Or mixing orders
    Or([
        Link("Similarity", [Variable("V1"), Node("Concept", "snake")], False),
        And([
            Link("Inheritance", [Variable("V1"), Variable("V2")], True),
            Not(Link("Similarity", [Variable("V1"), Variable("V2")], False)),
        ]),
    ]),
]


@pytest.mark.parametrize("qi", range(len(MESH_TREE_QUERIES)))
@pytest.mark.full
def test_unordered_and_negated_classes_on_mesh(animals_data, qi):
    """VERDICT r02 item 5 'done when': unordered + Not shapes execute under
    shard_map with host-identical answers, and the single-chip tree replica
    is never built."""
    db = ShardedDB(animals_data, DasConfig())
    q = MESH_TREE_QUERIES[qi]
    host_matched, host = _host_answer(db, q)
    answer = PatternMatchingAnswer()
    got = db.query_sharded(q, answer)
    assert got is not None, f"fell back to host for {q}"
    assert bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments
    assert answer.negation == host.negation
    assert not hasattr(db, "_tree_tensor_db"), "must not build the replica"


def test_mesh_tree_collective_counts(sharded_animals):
    """The mesh tree's data movement contract, counted in traced jaxprs:
    a broadcast join moves the right table ONCE (validity packed into the
    gathered block); replicating a tabu table for negation/difference is
    ONE all_gather; the anti-join itself is then purely shard-local."""
    import jax
    import jax.numpy as jnp

    ops = sharded_animals.tree_ops
    S = ops.S
    cap = 64
    av = jnp.zeros((S * cap, 2), dtype=jnp.int32)
    am = jnp.zeros((S * cap,), dtype=bool)

    join = ops._join_fn(pairs=((0, 0),), extra=(1,), cap=cap)
    counts = count_prims(
        jax.make_jaxpr(join)(av, am, av, am).jaxpr,
        ("all_gather", "all_to_all", "ppermute"),
    )
    assert counts == {"all_gather": 1, "all_to_all": 0, "ppermute": 0}

    rep = ops._replicate_fn()
    counts = count_prims(
        jax.make_jaxpr(rep)(av, am).jaxpr,
        ("all_gather", "all_to_all", "ppermute"),
    )
    assert counts == {"all_gather": 1, "all_to_all": 0, "ppermute": 0}

    anti = ops._anti_fn(pairs=((0, 0), (1, 1)))
    full_v = jnp.zeros((S * cap, 2), dtype=jnp.int32)
    full_m = jnp.zeros((S * cap,), dtype=bool)
    counts = count_prims(
        jax.make_jaxpr(anti)(av, am, full_v, full_m).jaxpr,
        ("all_gather", "all_to_all", "ppermute"),
    )
    assert counts == {"all_gather": 0, "all_to_all": 0, "ppermute": 0}


@pytest.mark.full
def test_mesh_uterm_after_commit(animals_data):
    """Unordered probes on the mesh read the delta-merged targets_sorted
    column: a committed Similarity link answers through the mesh tree."""
    from das_tpu.api.atomspace import DistributedAtomSpace

    das = DistributedAtomSpace(backend="sharded")
    das.load_metta_text(animals_metta())
    tx = das.open_transaction()
    tx.add('(: "lion" Concept)')
    tx.add('(Similarity "lion" "human")')
    das.commit_transaction(tx)
    q = Link("Similarity", [Variable("V1"), Variable("V2")], False)
    host_matched, host = _host_answer(das.db, q)
    answer = PatternMatchingAnswer()
    got = das.db.query_sharded(q, answer)
    assert got is not None and bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments
    lion = das.get_node("Concept", "lion")
    assert any(
        lion in a.values for a in answer.assignments
    )
    assert not hasattr(das.db, "_tree_tensor_db")


def test_legacy_replica_mode_still_answers(animals_data):
    """config.sharded_tree_fallback='tensor' keeps the round-2 behavior
    (single-device tree over a replicated copy) for operators who want it."""
    cfg = DasConfig(sharded_tree_fallback="tensor")
    db = ShardedDB(animals_data, cfg)
    q = Link("Similarity", [Variable("V1"), Variable("V2")], False)
    host_matched, host = _host_answer(db, q)
    answer = PatternMatchingAnswer()
    got = db.query_sharded(q, answer)
    assert got is not None and bool(got) == bool(host_matched)
    assert answer.assignments == host.assignments
    assert hasattr(db, "_tree_tensor_db"), "legacy mode uses the replica"


@pytest.mark.full
def test_mesh_join_side_selection_parity(sharded_animals):
    """Both broadcast orientations of the mesh join (gather-right vs
    gather-left-when-accumulator-smaller) produce the same valid row set
    as the single-device join."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from das_tpu.ops.join import join_tables as ref_join

    ops = sharded_animals.tree_ops
    S = ops.S
    rng = np.random.default_rng(5)
    capA, capB, k = 16, 32, 2
    shard = NamedSharding(ops.mesh, P("shards"))

    def sharded_table(cap, n_valid, hi):
        vals = rng.integers(0, hi, size=(S * cap, k), dtype=np.int32)
        valid = np.zeros(S * cap, dtype=bool)
        valid[rng.choice(S * cap, size=n_valid, replace=False)] = True
        return (
            jax.device_put(jnp.asarray(vals), shard),
            jax.device_put(jnp.asarray(valid), shard),
            vals, valid,
        )

    av, am, av_h, am_h = sharded_table(capA, 20, 6)
    bv, bm, bv_h, bm_h = sharded_table(capB, 90, 6)
    pairs, extra, cap = ((0, 0),), (1,), 512

    ref_vals, ref_valid, _ = ref_join(
        jnp.asarray(av_h), jnp.asarray(am_h), jnp.asarray(bv_h),
        jnp.asarray(bm_h), pairs, extra, 4096,
    )
    want = {
        tuple(int(x) for x in row)
        for row in np.asarray(ref_vals)[np.asarray(ref_valid)]
    }
    for counts in ((90, 20), (20, 90)):  # normal / swapped orientation
        vals, valid, total = ops.join_tables(
            av, am, bv, bm, pairs, extra, cap, counts=counts
        )
        got = {
            tuple(int(x) for x in row)
            for row in np.asarray(vals)[np.asarray(valid)]
        }
        assert got == want, f"orientation counts={counts} diverged"
