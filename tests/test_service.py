"""Black-box service tests: in-process gRPC server + client.

Role of /root/reference/scripts/service_regression_test.sh — drives the
RPC surface end-to-end and checks exact md5 handles and counts — plus the
failure paths the reference never exercises (bad key, bad query, load
error status)."""

import time

import pytest

grpc = pytest.importorskip("grpc")

from das_tpu.models.animals import write_animals_metta
from das_tpu.service.client import DasClient
from das_tpu.service.server import serve

HUMAN = "af12f10f9ae2002a1607ba0b47ba8407"  # Concept:human (reference handle)


@pytest.fixture(scope="module")
def client():
    import socket

    sock = socket.socket()
    sock.bind(("", 0))
    port = sock.getsockname()[1]
    sock.close()
    server, _svc = serve(port=port, block=False, backend="memory")
    c = DasClient("localhost", port)
    yield c
    c.close()
    server.stop(0)


@pytest.fixture(scope="module")
def loaded_key(client, tmp_path_factory):
    path = tmp_path_factory.mktemp("kb") / "animals.metta"
    write_animals_metta(str(path))
    result = client.create("animals")
    assert result["success"]
    key = result["msg"]
    result = client.load_knowledge_base(key, f"file://{path}")
    assert result["success"]
    for _ in range(100):
        status = client.check_das_status(key)
        if status["msg"] == "Ready":
            break
        assert not status["msg"].startswith("Load failed"), status
        time.sleep(0.1)
    else:
        pytest.fail("KB load did not finish")
    return key


def test_create_duplicate_name(client):
    assert client.create("dup")["success"]
    result = client.create("dup")
    assert not result["success"]
    assert "already exists" in result["msg"]


def test_invalid_key(client):
    result = client.count("nonsense")
    assert not result["success"]
    assert result["msg"] == "Invalid DAS key"


def test_count(client, loaded_key):
    result = client.count(loaded_key)
    assert result["success"]
    assert result["msg"] == "(14, 26)"


def test_get_atom(client, loaded_key):
    result = client.get_atom(loaded_key, HUMAN, "DICT")
    assert result["success"]
    assert "human" in result["msg"]


def test_search_nodes(client, loaded_key):
    result = client.search_nodes(loaded_key, "Concept", "human")
    assert result["success"]
    assert HUMAN in result["msg"]


def test_search_links(client, loaded_key):
    result = client.search_links(
        loaded_key, link_type="Inheritance", targets=[HUMAN, "*"]
    )
    assert result["success"]
    assert "mammal" in result["msg"] or len(result["msg"]) > 2


def test_query_dsl(client, loaded_key):
    result = client.query(
        loaded_key,
        "Node n1 Concept human, Link Inheritance n1 $1",
    )
    assert result["success"]
    assert "$1" in result["msg"]


def test_query_and(client, loaded_key):
    result = client.query(
        loaded_key,
        "Link Inheritance $1 $2, Link Similarity $1 $3, AND",
    )
    assert result["success"]


def test_invalid_query(client, loaded_key):
    result = client.query(loaded_key, "Bogus stuff here")
    assert not result["success"]
    assert result["msg"] == "Invalid query"


def test_load_failure_status(client):
    result = client.create("failing")
    key = result["msg"]
    result = client.load_knowledge_base(key, "file:///does/not/exist.metta")
    assert result["success"]
    for _ in range(100):
        status = client.check_das_status(key)
        if status["msg"].startswith("Load failed"):
            return
        time.sleep(0.1)
    pytest.fail("expected FAILED status")


def test_clear(client):
    key = client.create("clearable")["msg"]
    assert client.clear(key)["success"]
    assert client.count(key)["msg"] == "(0, 0)"
