"""The open BASELINE.json north-star requirement: the reference's OWN
artifacts run UNCHANGED against the TPU backends through the compat/das
shim (VERDICT r02 item 1).

* /root/reference/scripts/regression.py executes verbatim (subprocess,
  PYTHONPATH at the shim) on BOTH the memory and tensor backends, and the
  two printed outputs are identical after canonical normalization (set
  iteration order and the uncommitted symbol↔value zip inside
  UnorderedAssignment reprs are nondeterministic in the reference too, so
  blocks are compared as canonical multisets).  The host algebra itself is
  proven identical to the actual reference engine by test_differential.py,
  which closes the chain: reference engine == shim/memory == shim/tensor.

* /root/reference/scripts/benchmark.py executes verbatim against a
  persisted bio-ontology checkpoint (DAS_TPU_CHECKPOINT standing in for
  the reference's Mongo/Redis env endpoints), completing all three query
  layouts with matches.
"""

import ast as pyast
import os
import re
import subprocess
import sys

import pytest

pytestmark = pytest.mark.full  # heavy block: excluded from `pytest -m quick`

REFERENCE_SCRIPTS = "/root/reference/scripts"
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _shim_env(**extra):
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["PYTHONPATH"] = f"{REPO}/compat:{REPO}"
    env["JAX_PLATFORMS"] = "cpu"
    env.update(extra)
    return env


def _run_reference_script(script, env, timeout=900):
    proc = subprocess.run(
        [sys.executable, os.path.join(REFERENCE_SCRIPTS, script)],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    return proc.stdout


# -- output normalization ----------------------------------------------------

def _canon_unord(d):
    # UnorderedAssignment has no committed pairing: equal symbol and value
    # multisets mean the SAME assignment, so canonical form drops the zip
    return tuple(sorted(d.keys())), tuple(sorted(d.values()))


def _canon_line(line):
    line = line.strip()
    if not line:
        return None
    m = re.match(r"Ordered = (.*) \| Unordered = \[(.*)\]$", line)
    if m:
        o = m.group(1)
        od = None if o == "None" else tuple(sorted(pyast.literal_eval(o).items()))
        parts = re.findall(r"\*(\{[^}]*\})", m.group(2))
        uns = tuple(sorted(_canon_unord(pyast.literal_eval(p)) for p in parts))
        return ("comp", od, uns)
    if line.startswith("*{"):
        return ("unord", _canon_unord(pyast.literal_eval(line[1:])))
    if line.startswith("{"):
        return ("ord", tuple(sorted(pyast.literal_eval(line).items())))
    if line.startswith("["):  # get_all_nodes handle list — order-free
        return ("list", tuple(sorted(pyast.literal_eval(line))))
    return ("raw", line)


def normalize_regression_output(text):
    blocks, cur = [], []
    for line in text.splitlines():
        if line.startswith("-----") or line.startswith("====="):
            if cur:
                blocks.append(cur)
                cur = []
            continue
        if line.startswith("Matching"):
            if cur:
                blocks.append(cur)
            cur = [("hdr", line.strip())]
            continue
        c = _canon_line(line)
        if c:
            cur.append(c)
    if cur:
        blocks.append(cur)
    return [
        (
            tuple(x for x in b if x[0] == "hdr"),
            tuple(sorted(repr(x) for x in b if x[0] != "hdr")),
        )
        for b in blocks
    ]


# -- tests -------------------------------------------------------------------

@pytest.fixture(scope="module")
def regression_outputs():
    mem = _run_reference_script(
        "regression.py", _shim_env(DAS_TPU_BACKEND="memory")
    )
    tensor = _run_reference_script(
        "regression.py", _shim_env(DAS_TPU_BACKEND="tensor")
    )
    return mem, tensor


def test_reference_regression_runs_unchanged(regression_outputs):
    mem, tensor = regression_outputs
    for out in (mem, tensor):
        assert "Integration tests" in out
        # Concept:human exists and matches (known md5 from the reference)
        assert "af12f10f9ae2002a1607ba0b47ba8407" in out
    n_mem = normalize_regression_output(mem)
    n_tensor = normalize_regression_output(tensor)
    assert len(n_mem) == len(n_tensor) == 56
    for i, (a, b) in enumerate(zip(n_mem, n_tensor)):
        assert a == b, f"block {i} ({a[0]}) differs between memory and tensor"


def test_reference_regression_known_answers(regression_outputs):
    mem, _ = regression_outputs
    blocks = normalize_regression_output(mem)
    by_hdr = {b[0][0][1] if b[0] else "": b[1] for b in blocks}
    # grounded probes
    assert "('raw', 'True')" in by_hdr["Matching <Concept: human>"]
    assert (
        "('raw', 'False')"
        in by_hdr["Matching <Similarity: [<Concept: human>, <Concept: mammal>]>"]
    )
    # all-variable Inheritance scan yields the full 12-row answer set
    inh = by_hdr["Matching <Inheritance: [V1, V2]>"]
    assert sum(1 for x in inh if x.startswith("('ord'")) == 12


@pytest.fixture(scope="module")
def bio_checkpoint(tmp_path_factory):
    from das_tpu.models.bio import build_bio_ontology_atomspace
    from das_tpu.storage import checkpoint

    data, _, _ = build_bio_ontology_atomspace(
        n_genes=60, n_processes=20, members_per_gene=3, n_interactions=50,
        n_reactomes=20, n_uniprots=40,
    )
    path = str(tmp_path_factory.mktemp("bio_ckpt"))
    checkpoint.save(data, path, with_indexes=True)
    return path


def test_reference_benchmark_runs_unchanged(bio_checkpoint):
    out = _run_reference_script(
        "benchmark.py",
        _shim_env(DAS_TPU_BACKEND="tensor", DAS_TPU_CHECKPOINT=bio_checkpoint),
        timeout=1800,
    )
    # three layouts, each printing a BenchmarkResults block
    assert out.count("Average time per query") == 3
    assert out.count("DB backend architecture: COUCHBASE_AND_MONGODB") == 3
    for layout in ("QUERY_1", "QUERY_2", "QUERY_3"):
        assert f"Test layout: {layout}" in out
    # the conjunctive layouts find matches on this KB
    m1 = re.search(r"100 runs \((\d+) matched\)", out)
    assert m1 and int(m1.group(1)) > 0


def test_reference_pattern_matcher_unit_tests_pass(tmp_path):
    """The reference's OWN engine unit-test file (625 LoC of assignment
    and matching assertions, readable-handle fixture) passes byte-for-byte
    against this framework's engine + storage through the shim's
    translation StubDB (compat/das/database/stub_db.py).

    The file is COPIED into tmp_path before running: pytest's prepend
    import mode puts the test file's ancestor (/root/reference) at
    sys.path[0], AHEAD of PYTHONPATH — running it in place would import
    the reference's own das package and verify nothing about this repo.
    The copy's directory contains no das package, so every `das.*` import
    resolves to the shim.  A probe asserts that resolution explicitly."""
    import shutil

    src = "/root/reference/das/pattern_matcher/pattern_matcher_test.py"
    copied = tmp_path / "pattern_matcher_test.py"
    shutil.copyfile(src, copied)
    # probe: the das package under test must be the SHIM, not the reference
    (tmp_path / "conftest.py").write_text(
        "import das, sys\n"
        "assert '/compat/' in das.__file__, f'wrong das: {das.__file__}'\n"
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
            str(copied),
        ],
        capture_output=True, text=True, timeout=600,
        cwd=str(tmp_path),
        env=_shim_env(),
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    assert "7 passed" in proc.stdout


# -- the reference's DB-integration test files (VERDICT r04 item 3) ----------

@pytest.fixture(scope="module")
def animals_checkpoint(tmp_path_factory):
    """The animals KB persisted as a checkpoint: DAS_TPU_CHECKPOINT stands
    in for the pre-populated Mongo/Redis servers the reference's bare
    `DistributedAtomSpace()` construction expects."""
    from das_tpu.ingest.pipeline import load_knowledge_base
    from das_tpu.storage import checkpoint
    from das_tpu.storage.atom_table import AtomSpaceData

    data = AtomSpaceData()
    load_knowledge_base(data, f"{REPO}/data/samples/animals.metta")
    path = str(tmp_path_factory.mktemp("animals_ckpt"))
    checkpoint.save(data, path, with_indexes=True)
    return path


_REFERENCE_DAS_TESTS = {
    # file -> number of test functions upstream (asserted exactly)
    "distributed_atom_space_test.py": 11,   # das/distributed_atom_space_test.py:8-66
    "das_update_test.py": 4,                # das/das_update_test.py:8-192
}


@pytest.mark.parametrize("backend", ["memory", "tensor"])
@pytest.mark.parametrize("fname", sorted(_REFERENCE_DAS_TESTS))
def test_reference_das_integration_tests_pass(
    tmp_path, animals_checkpoint, fname, backend
):
    """The reference's own public-API integration test files run VERBATIM
    (subprocess copy, same sys.path rationale as the pattern_matcher proof
    above) against the animals checkpoint on both in-process backends.
    das_update_test.py additionally commits 10 expressions through an open
    transaction before its checks — the incremental-commit path on the
    tensor backend."""
    import shutil

    src = f"/root/reference/das/{fname}"
    copied = tmp_path / fname
    shutil.copyfile(src, copied)
    (tmp_path / "conftest.py").write_text(
        "import das, sys\n"
        "assert '/compat/' in das.__file__, f'wrong das: {das.__file__}'\n"
    )
    proc = subprocess.run(
        [
            sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
            str(copied),
        ],
        capture_output=True, text=True, timeout=900,
        cwd=str(tmp_path),
        env=_shim_env(
            DAS_TPU_BACKEND=backend, DAS_TPU_CHECKPOINT=animals_checkpoint
        ),
    )
    assert proc.returncode == 0, (proc.stdout + proc.stderr)[-3000:]
    assert f"{_REFERENCE_DAS_TESTS[fname]} passed" in proc.stdout
