"""Cost-based whole-plan query planner (ISSUE 8; marker `planner`,
standalone via `ops/pytests.sh planner`).

Pins, in order of load-bearing-ness:

  * BIT-IDENTICAL answers planner-vs-greedy on the bio query suite —
    analytic 3-var, grounded conjunctions, Or/negation trees, and a
    sharded mesh tenant (the planner chooses among orders the executors
    already accept; a planner bug may cost time, never answers);
  * the acceptance case: the costed initial capacity settles a query in
    retry round 0 where greedy pays a capacity retry — STRICTLY fewer
    compiled programs than greedy on the same query (every avoided
    retry tier is an XLA compile saved);
  * the `_join_cap_seed` clamp fix: an operator-shrunk
    initial_result_capacity can no longer clamp the join seed below the
    exact grounded row counts (the guaranteed-retry bug), planner OFF;
  * estimator invalidation on commit: statistics rebuild under
    delta_version exactly like the result caches;
  * DL002 sig-completeness for the new `planned` signature field, and
    the explain/telemetry surface.

Compile-budget note: KBs are small, each arm compiles a handful of
fused shapes at serving-scale capacities.
"""

import dataclasses

import pytest

from das_tpu import kernels, planner
from das_tpu.api.atomspace import DistributedAtomSpace
from das_tpu.core.config import DasConfig
from das_tpu.models.bio import build_bio_atomspace
from das_tpu.planner.stats import estimator_for
from das_tpu.query import compiler, fused
from das_tpu.query.ast import And, Link, Node, Not, Or, Variable
from das_tpu.storage.tensor_db import TensorDB

pytestmark = pytest.mark.planner


def _bio_data(**kw):
    data, genes, procs = build_bio_atomspace(**kw)
    return data, genes, procs


def _tensor_das(data, config, monkeypatch):
    # CapStore off: learned capacities persisted by an earlier run (or
    # the other arm) would pre-seed the retry ladder and blind the pins
    monkeypatch.setenv("DAS_TPU_XLA_CACHE", "0")
    db = TensorDB(data, config)
    return DistributedAtomSpace(database_name="zplan", db=db), db


def _sharded_das(data, config, monkeypatch):
    from das_tpu.parallel.sharded_db import ShardedDB

    monkeypatch.setenv("DAS_TPU_XLA_CACHE", "0")
    db = ShardedDB(data, config)
    return DistributedAtomSpace(database_name="zplans", db=db), db


def _three_var():
    return And([
        Link("Member", [Variable("V1"), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Variable("V1"), Variable("V2")], True),
    ])


def _grounded(gene):
    return And([
        Link("Member", [Node("Gene", gene), Variable("V3")], True),
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Interacts", [Node("Gene", gene), Variable("V2")], True),
    ])


def _negated(gene):
    return And([
        Link("Member", [Variable("V2"), Variable("V3")], True),
        Link("Member", [Node("Gene", gene), Variable("V3")], True),
        Not(Link("Interacts", [Node("Gene", gene), Variable("V2")], True)),
    ])


def _or_tree(g1, g2):
    return Or([
        And([
            Link("Member", [Node("Gene", g1), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
        ]),
        And([
            Link("Member", [Node("Gene", g2), Variable("V3")], True),
            Link("Member", [Variable("V2"), Variable("V3")], True),
        ]),
    ])


# -- bit-identical answers planner-vs-greedy -----------------------------


def _suite(names):
    return [
        _three_var(),
        _grounded(names[0]),
        _negated(names[1]),
        _or_tree(names[0], names[2]),
    ]


def _gene_names(db, n):
    return db.get_all_nodes("Gene", names=True)[:n]


def test_planner_vs_greedy_bit_identical_tensor(monkeypatch):
    data, _, _ = _bio_data(
        n_genes=60, n_processes=15, members_per_gene=4, n_interactions=80,
        seed=7,
    )
    das_on, db_on = _tensor_das(
        data, DasConfig(use_planner="on"), monkeypatch
    )
    das_off, db_off = _tensor_das(
        data, DasConfig(use_planner="off"), monkeypatch
    )
    names = _gene_names(db_on, 3)
    for q in _suite(names):
        m_on, a_on = das_on.query_answer(q)
        m_off, a_off = das_off.query_answer(q)
        assert m_on == m_off
        assert a_on.assignments == a_off.assignments, q
        assert a_on.negation == a_off.negation
    # the conjunctions actually took the planner (trees plan per site)
    assert planner.PLANNER_COUNTS["planned"] >= 1


def test_planner_vs_greedy_bit_identical_sharded(monkeypatch):
    data, _, _ = _bio_data(
        n_genes=60, n_processes=15, members_per_gene=4, n_interactions=80,
        seed=7,
    )
    das_on, db_on = _sharded_das(
        data, DasConfig(use_planner="on"), monkeypatch
    )
    das_off, _db_off = _sharded_das(
        data, DasConfig(use_planner="off"), monkeypatch
    )
    names = _gene_names(db_on, 3)
    for q in _suite(names):
        m_on, a_on = das_on.query_answer(q)
        m_off, a_off = das_off.query_answer(q)
        assert m_on == m_off
        assert a_on.assignments == a_off.assignments, q
        assert a_on.negation == a_off.negation


def test_planner_count_parity(monkeypatch):
    """count_matches rides the same executors; counts must agree."""
    data, _, _ = _bio_data(
        n_genes=60, n_processes=15, members_per_gene=4, n_interactions=80,
        seed=7,
    )
    _das_on, db_on = _tensor_das(
        data, DasConfig(use_planner="on"), monkeypatch
    )
    _das_off, db_off = _tensor_das(
        data, DasConfig(use_planner="off"), monkeypatch
    )
    q = _three_var()
    assert compiler.count_matches(db_on, q) == compiler.count_matches(
        db_off, q
    )


# -- the acceptance pin: costed capacity kills a retry round -------------


def _fanout_kb():
    """32 genes x 50 memberships over 100 processes: a grounded probe of
    one process holds ~16 rows, but joining back through Member fans out
    to ~16*50 = ~800 rows — an order of magnitude past greedy's
    max(64, min(init, 4*mg), mg) seed, and almost exactly the
    independence estimate rows_L * |Member| / max(dv) = 16 * 1600 / 32."""
    return _bio_data(
        n_genes=32, n_processes=100, members_per_gene=50,
        n_interactions=0, seed=3,
    )


def _fanout_query(db):
    proc = db.get_all_nodes("BiologicalProcess", names=True)[0]
    return And([
        Link("Member", [Variable("G"), Node("BiologicalProcess", proc)], True),
        Link("Member", [Variable("G"), Variable("P2")], True),
    ])


def test_costed_capacity_settles_round0_greedy_retries(monkeypatch):
    data, _, _ = _fanout_kb()
    das_off, db_off = _tensor_das(
        data, DasConfig(use_planner="off"), monkeypatch
    )
    q = _fanout_query(db_off)
    kernels.reset_dispatch_counts()
    off_answer = das_off.query(q)
    greedy_programs = kernels.DISPATCH_COUNTS["fused"]
    assert greedy_programs >= 2, (
        "greedy was expected to pay a capacity retry on this shape; "
        f"dispatches={kernels.DISPATCH_COUNTS}"
    )

    das_on, db_on = _tensor_das(
        data, DasConfig(use_planner="on"), monkeypatch
    )
    planner.reset_planner_counts()
    kernels.reset_dispatch_counts()
    on_answer = das_on.query(q)
    planner_programs = kernels.DISPATCH_COUNTS["fused"]
    assert planner_programs == 1, kernels.DISPATCH_COUNTS
    assert planner_programs < greedy_programs  # the acceptance criterion
    assert planner.PLANNER_COUNTS["round0"] >= 1
    assert planner.PLANNER_COUNTS["retries"] == 0
    assert on_answer == off_answer  # same bindings, fewer programs


# -- the _join_cap_seed clamp fix (planner OFF) --------------------------


def test_shrunk_capacity_config_no_guaranteed_retry(monkeypatch):
    """ISSUE 8 satellite: `max(64, min(initial_result_capacity, 4*mg))`
    clamped the join seed to 64 when an operator shrank the configured
    capacity — below the EXACT grounded row count mg, a guaranteed
    retry round.  The seed now folds the per-term estimate's bound in:
    seed >= mg, so this query settles in ONE program."""
    data, _, _ = _bio_data(
        n_genes=100, n_processes=1, members_per_gene=1,
        n_interactions=40, seed=5,
    )
    cfg = DasConfig(use_planner="off", initial_result_capacity=64)
    das, db = _tensor_das(data, cfg, monkeypatch)
    proc = db.get_all_nodes("BiologicalProcess", names=True)[0]
    q = And([
        Link("Member", [Variable("G"), Node("BiologicalProcess", proc)], True),
        Link("Interacts", [Variable("G"), Variable("H")], True),
    ])
    plans = compiler.plan_query(db, q)
    ex = fused.get_executor(db)
    grounded_rows = ex._estimate(plans[0])
    assert grounded_rows > cfg.initial_result_capacity  # the bug setup
    term_caps = tuple(fused._pow2_at_least(ex._estimate(p)) for p in plans)
    seed = ex._join_cap_seed(plans, term_caps)
    assert seed >= grounded_rows, (
        "the configured clamp must not force a seed below the exact "
        f"grounded rows: seed={seed} rows={grounded_rows}"
    )
    kernels.reset_dispatch_counts()
    das.query(q)
    assert kernels.DISPATCH_COUNTS["fused"] == 1, kernels.DISPATCH_COUNTS


# -- estimator invalidation on commit ------------------------------------


def test_estimator_invalidates_on_commit(monkeypatch):
    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=10,
        seed=11,
    )
    das, db = _tensor_das(data, DasConfig(), monkeypatch)
    q = _three_var()
    plans = compiler.plan_query(db, q)
    est = estimator_for(db)
    member_rows = est.rows(plans[0])
    assert member_rows == 40  # 20 genes x 2 memberships
    dv = est.distinct_at(plans[0].arity, plans[0].type_id,
                         plans[0].var_cols[0])
    assert 0 < dv <= 20

    # commit two new memberships for a brand-new gene: delta_version
    # bumps, the estimator rebuilds, and both statistics move
    procs = db.get_all_nodes("BiologicalProcess", names=True)[:2]
    das.load_metta_text(
        '(: "GENE:NEW" Gene)\n'
        # re-declaring existing terminals is idempotent (content-
        # addressed); the parser needs them in scope for the new links
        + "".join(f'(: "{p}" BiologicalProcess)\n' for p in procs)
        + "".join(f'(Member "GENE:NEW" "{p}")\n' for p in procs)
    )
    est2 = estimator_for(db)
    assert est2 is not est, "estimator must rebuild on commit"
    assert est2.rows(compiler.plan_query(db, q)[0]) == member_rows + 2
    assert est2.distinct_at(
        plans[0].arity, plans[0].type_id, plans[0].var_cols[0]
    ) == dv + 1
    # same version -> same estimator object (statistics are memoized)
    assert estimator_for(db) is est2


# -- DL002 sig-completeness for the planner fields -----------------------


def test_planned_field_in_plan_signatures():
    from das_tpu.parallel.fused_sharded import ShardedPlanSig

    f_names = [f.name for f in dataclasses.fields(fused.FusedPlanSig)]
    s_names = [f.name for f in dataclasses.fields(ShardedPlanSig)]
    assert "planned" in f_names
    assert "planned" in s_names
    # a costed choice is part of the cache key: planner and greedy
    # executables for the same order/caps must cache side by side
    a = fused.FusedPlanSig((), (), (), planned=True)
    b = fused.FusedPlanSig((), (), (), planned=False)
    assert a != b and hash(a) != hash(b)


def test_planner_sig_fields_pass_dl002_and_dl008():
    from pathlib import Path

    from das_tpu.analysis import run_analysis

    repo = Path(__file__).resolve().parent.parent
    findings = run_analysis(
        [repo / "das_tpu"], rules=["DL002", "DL008"],
        tests_dir=repo / "tests",
    )
    assert not findings, "\n".join(f.render() for f in findings)


# -- explain + telemetry surface -----------------------------------------


def test_explain_estimates_vs_actuals(monkeypatch):
    data, _, _ = _fanout_kb()
    das, db = _tensor_das(data, DasConfig(), monkeypatch)
    q = _fanout_query(db)
    out = das.explain(q, execute=True)
    assert out["planned"] is True
    assert out["route"] in ("fused", "fused_kernel")
    assert out["method"] in ("ref_order", "dp", "greedy_tail")
    assert len(out["order"]) == 2
    assert len(out["est_join_rows"]) == 1
    assert out["join_cap_seeds"][0] >= out["est_join_rows"][0]
    actual = out["actual"]
    assert actual["retry_rounds"] == 0
    assert actual["count"] == actual["join_rows"][0] > 0
    # the independence estimate is exact on this uniform KB shape
    est, act = out["est_join_rows"][0], actual["join_rows"][0]
    assert act / 2 <= est <= act * 2, (est, act)


def test_explain_tree_reports_sites(monkeypatch):
    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=10,
        seed=11,
    )
    das, db = _tensor_das(data, DasConfig(), monkeypatch)
    names = _gene_names(db, 3)
    # the homogeneous Or now renders the WHOLE-TREE fused plan (ISSUE
    # 10): site order, union/anti placement, per-branch est rows
    out = das.explain(_or_tree(names[0], names[2]))
    assert out["route"] == "fused_tree"
    assert out["tree_fused"] is True
    assert len(out["sites"]) == 2
    assert out["union_after"] == 2
    assert out["anti_after_union"] is False
    assert len(out["est_site_rows"]) == 2
    for s in out["sites"]:
        assert s["route"] in ("fused", "fused_kernel")
        if s["planned"]:
            assert "est_term_rows" in s
    # with fusion off the per-site tree rendering survives unchanged
    das_off, db_off = _tensor_das(
        data, DasConfig(use_tree_fusion="off"), monkeypatch
    )
    out_off = das_off.explain(_or_tree(names[0], names[2]))
    assert out_off["route"] == "tree"
    assert len(out_off["sites"]) == 2


def test_planner_snapshot_in_service_stats(monkeypatch):
    from das_tpu.service.server import DasService

    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=10,
        seed=11,
    )
    das, _db = _tensor_das(data, DasConfig(), monkeypatch)
    planner.reset_planner_counts()
    das.query(_three_var())
    service = DasService()
    service.attach_tenant("zplan", das)
    stats = service.coalescer_stats()
    assert "planner" in stats
    assert stats["planner"]["planned"] >= 1
    assert "actual_vs_est_ratio" in stats["planner"]


def test_exact_dot_keys_on_probed_position(monkeypatch):
    """Review regression: two same-shaped leaves sharing a variable at
    DIFFERENT positions have different supports — the degree-dot memo
    must not serve one term's product for the other (a falsely-'exact'
    figure would seed a margin-free capacity, i.e. a guaranteed retry,
    or corrupt the est-vs-actual telemetry)."""
    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=30,
        seed=11,
    )
    _das, db = _tensor_das(data, DasConfig(), monkeypatch)
    q = And([
        # B at position 0 of one Member leaf, position 1 of the other
        Link("Member", [Variable("B"), Variable("P")], True),
        Link("Member", [Variable("G"), Variable("B")], True),
        Link("Interacts", [Variable("B"), Variable("X")], True),
    ])
    plans = compiler.plan_query(db, q)
    est = estimator_for(db)
    first = est.exact_join_rows(plans[0], plans[2], "B")
    second = est.exact_join_rows(plans[1], plans[2], "B")
    fresh = estimator_for(db.__class__(data, DasConfig()))
    assert first == fresh.exact_join_rows(plans[0], plans[2], "B")
    assert second == fresh.exact_join_rows(plans[1], plans[2], "B")
    # Member targets genes at pos 0 and processes at pos 1; Interacts
    # targets genes — the two dots MUST differ (pos-1 support is
    # process rows, disjoint from gene rows)
    assert first != second
    assert second == 0


def test_method_counters_decompose_planned_traffic(monkeypatch):
    """Review regression: explain() plans too, but the planned/method
    decomposition must cover EXECUTOR traffic only — after any mix of
    queries and explains, dp + greedy_tail + ref_order == planned."""
    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=10,
        seed=11,
    )
    das, _db = _tensor_das(data, DasConfig(), monkeypatch)
    planner.reset_planner_counts()
    das.explain(_three_var())
    c = planner.PLANNER_COUNTS
    assert c["planned"] == 0
    assert c["dp"] + c["greedy_tail"] + c["ref_order"] == 0
    assert c["explain"] == 1
    das.query(_three_var())
    das.query(_grounded(_gene_names(_db, 1)[0]))
    c = planner.PLANNER_COUNTS
    assert c["planned"] == 2
    assert c["dp"] + c["greedy_tail"] + c["ref_order"] == c["planned"]


def test_declined_jobs_not_counted_as_planned(monkeypatch):
    """Review regression: _exec_job can still decline AFTER planning
    (capacity ceiling, missing bucket) — the legacy fallback answers,
    and the planned/greedy counters must not credit a job that never
    existed (observe_settle would never complete the decomposition)."""
    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=10,
        seed=11,
    )
    # ceiling below every term capacity: the fused executor must decline
    _das, db = _tensor_das(
        data, DasConfig(max_result_capacity=32), monkeypatch
    )
    plans = compiler.plan_query(db, _three_var())
    planner.reset_planner_counts()
    ex = fused.get_executor(db)
    assert ex._exec_job(list(plans), False) is None
    c = planner.PLANNER_COUNTS
    assert c["planned"] == 0 and c["greedy"] == 0
    assert c["dp"] + c["greedy_tail"] + c["ref_order"] == 0


def test_planner_dp_orders_disconnected_declines(monkeypatch):
    """Disconnected conjunctions (cross products) stay with the legacy
    ordering — the planner declines rather than price cross products."""
    data, _, _ = _bio_data(
        n_genes=20, n_processes=5, members_per_gene=2, n_interactions=10,
        seed=11,
    )
    _das, db = _tensor_das(data, DasConfig(), monkeypatch)
    q = And([
        Link("Member", [Variable("A"), Variable("B")], True),
        Link("Interacts", [Variable("C"), Variable("D")], True),
    ])
    plans = compiler.plan_query(db, q)
    assert planner.plan_conjunction(db, plans) is None


def test_dp_max_env_clamps_search(monkeypatch):
    from das_tpu.planner import search

    monkeypatch.setenv("DAS_TPU_PLANNER_DP_MAX", "2")
    assert search.dp_max() == 2
    monkeypatch.setenv("DAS_TPU_PLANNER_DP_MAX", "bogus")
    assert search.dp_max() == search.DEFAULT_DP_MAX
    monkeypatch.delenv("DAS_TPU_PLANNER_DP_MAX")
    assert search.dp_max() == search.DEFAULT_DP_MAX
