"""Test harness: force an 8-virtual-device CPU JAX platform so sharded paths
are exercised without TPU hardware (SURVEY.md §4 implication (b)/(c))."""

import os
import sys

# hard override: the ambient environment may pin jax to a TPU-tunnel
# platform plugin (and its sitecustomize overrides the jax_platforms config
# AFTER env vars are read), so tests force the virtual 8-device CPU platform
# through jax.config itself, before any backend is initialized.
os.environ["JAX_PLATFORMS"] = os.environ.get("DAS_TPU_TEST_PLATFORM", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def animals_data():
    from das_tpu.models.animals import animals_metta
    from das_tpu.storage.atom_table import load_metta_text

    return load_metta_text(animals_metta())


@pytest.fixture(scope="session")
def animals_db(animals_data):
    from das_tpu.storage.memory_db import MemoryDB

    return MemoryDB(animals_data)


REFERENCE_PATH = "/root/reference"


def reference_available() -> bool:
    return os.path.isdir(os.path.join(REFERENCE_PATH, "das"))


@pytest.fixture(scope="session")
def reference_modules():
    """Import the reference pattern matcher + StubDB for differential tests.
    Skips when the reference checkout is absent (CI portability)."""
    if not reference_available():
        pytest.skip("reference checkout not available")
    sys.path.insert(0, REFERENCE_PATH)
    try:
        from das.pattern_matcher import pattern_matcher as ref_pm  # noqa
        from das.database import stub_db as ref_stub  # noqa
    except Exception as exc:  # pragma: no cover
        pytest.skip(f"reference import failed: {exc}")
    return ref_pm, ref_stub


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running scale tests (million-link KBs)"
    )
    config.addinivalue_line(
        "markers",
        "full: heavy blocks (reference-shim subprocesses, fuzz, scale, "
        "multihost) excluded from the quick inner loop",
    )
    config.addinivalue_line(
        "markers",
        "quick: the <5-min inner loop (auto-applied to everything not "
        "marked slow/full); run with `pytest -m quick`",
    )


def pytest_collection_modifyitems(config, items):
    """`pytest -m quick` = everything not slow/full (VERDICT r04 item 9).
    Plain `pytest tests/` still runs the whole suite."""
    for item in items:
        if "slow" not in item.keywords and "full" not in item.keywords:
            item.add_marker(pytest.mark.quick)
