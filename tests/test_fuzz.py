"""Randomized differential battery: random KBs x random query trees, every
engine must agree.

For each seeded random knowledge base and query AST the same spec tree runs
through:

  * the REFERENCE pattern matcher (imported from /root/reference, over our
    MemoryDB via the RefDBAdapter) — ground truth semantics;
  * our host algebra (query/ast.py + query/assignment.py);
  * the single-device compiled paths (query/compiler.py query_on_device:
    fused / staged / tree);
  * the mesh-sharded path (parallel/sharded_db.py query_sharded) on a
    subset (one shard_map compile per query shape is the cost driver).

The hand-written batteries (tests/test_differential.py, test_tree.py)
cover the regression suite's fixed shapes; this fuzzer covers the
combinatorial space around them — nested And/Or, negation placement,
unordered links, repeated variables, grounded/unknown atoms, templates.
Failures print the (kb_seed, query_seed, spec) triple for replay."""

import random

import pytest

pytestmark = pytest.mark.full  # heavy block: excluded from `pytest -m quick`

import das_tpu.query.ast as my
from das_tpu.query.ast import PatternMatchingAnswer
from das_tpu.storage.atom_table import load_metta_text
from das_tpu.storage.memory_db import MemoryDB

from tests.test_differential import RefDBAdapter, build_query, canon

N_KBS = 4
QUERIES_PER_KB = 24
SHARDED_QUERIES_PER_KB = 6


def random_kb_text(rng: random.Random) -> str:
    """A small random animals-like KB: Concept nodes, ordered Inheritance,
    unordered Similarity (sometimes symmetric, sometimes not), ordered
    arity-3 List links."""
    n_concepts = rng.randint(6, 14)
    names = [f"c{i}" for i in range(n_concepts)]
    lines = [
        "(: Concept Type)",
        "(: Inheritance Type)",
        "(: Similarity Type)",
        "(: List Type)",
    ]
    lines += [f'(: "{n}" Concept)' for n in names]
    for _ in range(rng.randint(4, 14)):
        a, b = rng.sample(names, 2)
        lines.append(f'(Inheritance "{a}" "{b}")')
    for _ in range(rng.randint(3, 10)):
        a, b = rng.sample(names, 2)
        lines.append(f'(Similarity "{a}" "{b}")')
        if rng.random() < 0.6:  # symmetric closure, most of the time
            lines.append(f'(Similarity "{b}" "{a}")')
    for _ in range(rng.randint(0, 4)):
        a, b, c = rng.sample(names, 3)
        lines.append(f'(List "{a}" "{b}" "{c}")')
    return "\n".join(lines)


def _random_target(rng, names, variables):
    r = rng.random()
    if r < 0.45:
        return ("var", rng.choice(variables))
    if r < 0.9:
        return ("node", "Concept", rng.choice(names))
    return ("node", "Concept", "ghost")  # unknown atom: must answer no-match


def _random_leaf(rng, names, variables):
    kind = rng.random()
    if kind < 0.35:
        targets = [_random_target(rng, names, variables) for _ in range(2)]
        return ("link", "Inheritance", True, targets)
    if kind < 0.6:
        targets = [_random_target(rng, names, variables) for _ in range(2)]
        return ("link", "Similarity", False, targets)
    if kind < 0.75:
        targets = [_random_target(rng, names, variables) for _ in range(3)]
        return ("link", "List", True, targets)
    if kind < 0.9:
        link_type = rng.choice(["Inheritance", "Similarity"])
        ordered = link_type != "Similarity"
        tvars = [("tvar", rng.choice(variables), "Concept") for _ in range(2)]
        return ("template", link_type, ordered, tvars)
    # fully grounded existence check
    a, b = rng.sample(names, 2)
    return ("link", "Inheritance", True,
            [("node", "Concept", a), ("node", "Concept", b)])


def random_query_spec(rng: random.Random, names) -> tuple:
    variables = [f"V{i}" for i in range(1, rng.randint(2, 5))]

    def term(depth):
        r = rng.random()
        if depth >= 2 or r < 0.45:
            leaf = _random_leaf(rng, names, variables)
            if rng.random() < 0.2:
                return ("not", leaf)
            return leaf
        op = "and" if r < 0.75 else "or"
        k = rng.randint(2, 3)
        terms = [term(depth + 1) for _ in range(k)]
        if op == "and" and all(t[0] == "not" for t in terms):
            # all-negated And differs from anything useful; keep one positive
            terms[0] = _random_leaf(rng, names, variables)
        return (op, terms)

    return term(0)


def _answers(engine_query, db) -> tuple:
    answer = PatternMatchingAnswer()
    matched = engine_query.matched(db, answer)
    return bool(matched), _identity(answer.assignments)


def _identity(assignments) -> dict:
    """Answer-set identity AS THE ENGINES DEFINE IT: assignment equality is
    hash-only (reference pattern_matcher.py:73-156 and our algebra alike),
    and CompositeAssignment hashes XOR their unordered-mapping hashes — so
    e.g. every composite of two IDENTICAL unordered mappings collides and
    the answer set keeps ONE arbitrary representative (insertion-order
    dependent; the reference itself varies across runs here).  Engines are
    therefore compared on their hash sets; canon forms ride along for
    readable failure output."""
    return {a.hash: canon(a) for a in assignments}


def _assert_same_answers(got, want, label):
    got_matched, got_ids = got
    want_matched, want_ids = want
    assert got_matched == want_matched, label
    assert set(got_ids) == set(want_ids), (
        f"{label}\nonly-got={ [got_ids[h] for h in set(got_ids)-set(want_ids)] }"
        f"\nonly-want={ [want_ids[h] for h in set(want_ids)-set(got_ids)] }"
    )


@pytest.fixture(scope="module", params=range(N_KBS), ids=lambda i: f"kb{i}")
def fuzz_kb(request):
    rng = random.Random(1000 + request.param)
    text = random_kb_text(rng)
    data = load_metta_text(text)
    names = sorted({rec.name for rec in data.nodes.values()})
    return request.param, data, names


@pytest.fixture(scope="module")
def fuzz_dbs(fuzz_kb):
    from das_tpu.storage.tensor_db import TensorDB

    _, data, _ = fuzz_kb
    return MemoryDB(data), TensorDB(data)


def _specs_for(kb_seed, names, count):
    out = []
    for qi in range(count):
        rng = random.Random(5000 + 97 * kb_seed + qi)
        out.append((qi, random_query_spec(rng, names)))
    return out


def test_fuzz_reference_vs_host_vs_device(fuzz_kb, fuzz_dbs, reference_modules):
    """Reference engine == host algebra == device execution, per query."""
    ref_pm, _ = reference_modules
    kb_seed, data, names = fuzz_kb
    host_db, dev_db = fuzz_dbs
    ref_db = RefDBAdapter(host_db)
    from das_tpu.query import compiler

    for qi, spec in _specs_for(kb_seed, names, QUERIES_PER_KB):
        label = f"kb_seed={kb_seed} query={qi} spec={spec}"
        ref = _answers(build_query(ref_pm, spec), ref_db)
        host = _answers(build_query(my, spec), host_db)
        _assert_same_answers(host, ref, label)

        dev_answer = PatternMatchingAnswer()
        dev_matched = compiler.query_on_device(
            dev_db, build_query(my, spec), dev_answer
        )
        assert dev_matched is not None, f"device declined: {label}"
        _assert_same_answers((bool(dev_matched), _identity(dev_answer.assignments)), host, label)


def test_fuzz_sharded_vs_host(fuzz_kb):
    """The mesh-sharded path agrees with the host algebra on a random
    query subset (conjunctive shapes run fused/staged on the mesh, the
    rest route through the device tree executor)."""
    from das_tpu.parallel.sharded_db import ShardedDB

    kb_seed, data, names = fuzz_kb
    db = ShardedDB(data)
    for qi, spec in _specs_for(kb_seed, names, SHARDED_QUERIES_PER_KB):
        label = f"kb_seed={kb_seed} query={qi} spec={spec}"
        host = _answers(build_query(my, spec), db)
        answer = PatternMatchingAnswer()
        matched = db.query_sharded(build_query(my, spec), answer)
        assert matched is not None, f"sharded declined: {label}"
        _assert_same_answers((bool(matched), _identity(answer.assignments)), host, label)


def test_fuzz_incremental_commit_parity(fuzz_kb):
    """Load half the KB, commit the rest through the transaction path, and
    require the delta-merged store to answer like a fresh full build."""
    from das_tpu.api.atomspace import DistributedAtomSpace
    from das_tpu.query import compiler
    from das_tpu.storage.tensor_db import TensorDB

    kb_seed, data, names = fuzz_kb
    rng = random.Random(9000 + kb_seed)
    text = random_kb_text(random.Random(1000 + kb_seed))
    lines = text.splitlines()
    # head must contain at least one LINK: terminals only materialize on
    # first use, and a commit onto an empty store is (correctly) a bulk
    # load, not a delta
    n_decl = sum(1 for l in lines if l.startswith("(:"))
    n_links = len(lines) - n_decl
    cut = n_decl + rng.randint(1, max(1, n_links // 2))
    head, tail = lines[:cut], lines[cut:]

    das = DistributedAtomSpace(backend="tensor")
    das.load_metta_text("\n".join(head))
    tx = das.open_transaction()
    for line in tail:
        tx.add(line)
    das.commit_transaction(tx)
    assert das.db._delta_total > 0 or not tail  # delta path taken

    fresh = TensorDB(das.data)
    for qi, spec in _specs_for(kb_seed, names, 4):
        label = f"kb_seed={kb_seed} query={qi} spec={spec}"
        want = PatternMatchingAnswer()
        want_matched = compiler.query_on_device(fresh, build_query(my, spec), want)
        got = PatternMatchingAnswer()
        got_matched = compiler.query_on_device(das.db, build_query(my, spec), got)
        assert got_matched is not None and want_matched is not None, label
        _assert_same_answers(
            (bool(got_matched), _identity(got.assignments)),
            (bool(want_matched), _identity(want.assignments)),
            label,
        )


def test_fuzz_count_matches_consistency(fuzz_kb, fuzz_dbs):
    """count_matches (the count-only compiled program — a distinct
    executable from the materializing one) must equal the materialized
    answer-set size for random queries."""
    from das_tpu.query import compiler

    kb_seed, data, names = fuzz_kb
    host_db, dev_db = fuzz_dbs
    for qi, spec in _specs_for(kb_seed, names, 8):
        label = f"kb_seed={kb_seed} query={qi} spec={spec}"
        matched, ids = _answers(build_query(my, spec), host_db)
        want = len(ids) if matched else 0
        got = compiler.count_matches(dev_db, build_query(my, spec))
        assert got is not None, f"count declined: {label}"
        assert got == want, f"{label}: count {got} != {want}"


def test_fuzz_checkpoint_roundtrip(fuzz_kb, tmp_path):
    """save -> load must preserve every handle, index, and query answer
    (indexes are restored from the npz, not re-finalized — staleness
    checking is part of what's under test)."""
    from das_tpu.storage import checkpoint
    from das_tpu.storage.tensor_db import TensorDB
    from das_tpu.query import compiler

    kb_seed, data, names = fuzz_kb
    path = str(tmp_path / f"ckpt{kb_seed}")
    checkpoint.save(data, path)
    restored = checkpoint.load(path)
    assert restored._fin is not None  # indexes adopted, no re-finalize
    assert restored.count_atoms() == data.count_atoms()

    db_a = TensorDB(data)
    db_b = TensorDB(restored)
    for qi, spec in _specs_for(kb_seed, names, 5):
        label = f"kb_seed={kb_seed} query={qi} spec={spec}"
        a = PatternMatchingAnswer()
        b = PatternMatchingAnswer()
        ma = compiler.query_on_device(db_a, build_query(my, spec), a)
        mb = compiler.query_on_device(db_b, build_query(my, spec), b)
        assert ma is not None and mb is not None, label
        _assert_same_answers(
            (bool(ma), _identity(a.assignments)),
            (bool(mb), _identity(b.assignments)),
            label,
        )


def test_fuzz_pattern_blacklist_parity(fuzz_kb):
    """With a link type blacklisted, wildcard probes must not see it on
    ANY backend — host, tensor, reference semantics alike (the reference
    never emits patterns: keys for blacklisted types,
    parser_threads.py:41,185)."""
    from das_tpu.core.config import DasConfig
    from das_tpu.storage.tensor_db import TensorDB
    from das_tpu.query import compiler

    kb_seed, data, names = fuzz_kb
    data.pattern_black_list = ["Inheritance"]
    try:
        host_db = MemoryDB(data)
        dev_db = TensorDB(data, DasConfig())
        for qi, spec in _specs_for(kb_seed, names, 5):
            label = f"kb_seed={kb_seed} query={qi} spec={spec} (blacklist)"
            host = _answers(build_query(my, spec), host_db)
            dev_answer = PatternMatchingAnswer()
            dev_matched = compiler.query_on_device(
                dev_db, build_query(my, spec), dev_answer
            )
            if dev_matched is None:
                # blacklisted wildcard terms are legitimately not
                # compilable: the host algebra answers (and is the oracle)
                continue
            _assert_same_answers(
                (bool(dev_matched), _identity(dev_answer.assignments)),
                host, label,
            )
    finally:
        data.pattern_black_list = []
